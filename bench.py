"""Benchmark driver: the full BASELINE.md config matrix on one TPU chip.

Prints one JSON line per config ({"metric", "value", "unit", "vs_baseline",
"mfu", "model_tflops"}), finishing with the headline flagship line (GPT-2
124M training throughput, ``vs_baseline`` = fused/Pallas vs the repo's own
unfused-XLA path, each at its best feasible config: the fused path skips
activation recompute because flash attention's O(seq) memory permits it,
the unfused path cannot — so the ratio measures the kernels AND the memory
headroom they buy. The reference publishes no absolute numbers,
BASELINE.md).

Configs (BASELINE.md / BASELINE.json):
  1. ResNet-50 224px, amp-O2-equivalent bf16 + FusedSGD (north-star config)
  2. DCGAN bf16 G+D step
  3. BERT-base + FusedLAMB
  4. GPT-2 Megatron TP path (tp=1 on a single chip)
  5. GPT-2 355M (large-GEMM MFU row: bs8, no recompute, unrolled scan)
  6. ViT-L/16 + FusedAdam
  7. long-context: GPT at 32k tokens full-causal + 32k/64k sliding-window
     — the reference caps at 16k
  8. generation: prefill + decode-ONLY tokens/sec (bs 1 / 8 / 32, each
     with its share of the weight+KV read-bandwidth bound)
  9. fp8: native-fp8 dense fwd+bwd vs the same GEMM in bf16 (platform
     verdict row — v5e runs fp8 operands without fp8 MXU units)
 10. headline: GPT-2 124M fused-vs-unfused (printed LAST; the driver
     records the tail line)

MFU is model-FLOPs utilization against the chip's bf16 peak
(benchmarks/_harness.py).
"""

from __future__ import annotations

import json
import time
import traceback

import jax
import jax.numpy as jnp

STEPS = 30   # longer window: amortizes queue ramp-up through the tunnel


def _build(recompute: bool):
    from apex_tpu.models import GPTModel, TransformerConfig
    from apex_tpu.optimizers import FusedAdam

    cfg = TransformerConfig(
        num_layers=12, hidden_size=768, num_attention_heads=12,
        vocab_size=50304, max_position_embeddings=1024,
        hidden_dropout=0.0, attention_dropout=0.0,
        recompute=recompute, scan_unroll=12, compute_dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-4)
    opt_state = opt.init(params)

    bs, seq = 8, 1024
    tokens = jax.random.randint(jax.random.PRNGKey(1), (bs, seq), 0, 50304)
    labels = jax.random.randint(jax.random.PRNGKey(2), (bs, seq), 0, 50304)

    def loss_fn(p):
        return model.apply(p, tokens, labels)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, opt_state = opt.step(grads, params, opt_state)
        return params, opt_state, loss

    n_params = sum(x.size for x in jax.tree.leaves(params))
    return step, params, opt_state, bs * seq, n_params, seq


def _run(flash: bool):
    import apex_tpu.ops._support as support
    import os

    # kernel dispatch is keyed on APEX_TPU_FORCE_PALLAS (ops/_support.py);
    # 'off' turns every fused op into its plain-XLA fallback = the baseline
    prev = os.environ.get("APEX_TPU_FORCE_PALLAS")
    fused = flash and jax.default_backend() == "tpu"
    os.environ["APEX_TPU_FORCE_PALLAS"] = "tpu" if fused else "off"
    support.pallas_mode.cache_clear()
    # each path runs its best feasible config: the flash kernel's O(seq)
    # memory lets the fused path skip activation recompute (~+4%); the
    # unfused path materializes per-layer score tensors and OOMs without it.
    # Keyed on whether the Pallas kernels actually engage, not the flag.
    step, params, opt_state, tokens_per_step, n_params, seq = _build(
        recompute=not fused)
    params, opt_state, loss = step(params, opt_state)          # compile
    _ = float(loss)
    # best-of-3 windows: the tunneled backend has multi-second transient
    # stalls (remote compile cache, connection ramp) that a single window
    # folds into the mean; min-of-windows reports steady-state throughput
    best = float("inf")
    for _w in range(3):
        t0 = time.perf_counter()
        for _i in range(STEPS):
            params, opt_state, loss = step(params, opt_state)
        _ = float(loss)                                        # host sync
        best = min(best, (time.perf_counter() - t0) / STEPS)
    dt = best
    if prev is None:
        os.environ.pop("APEX_TPU_FORCE_PALLAS", None)
    else:
        os.environ["APEX_TPU_FORCE_PALLAS"] = prev
    support.pallas_mode.cache_clear()
    return (tokens_per_step / dt, float(loss), n_params, seq, dt,
            tokens_per_step)


def _config_matrix():
    """Run every BASELINE config, each printing its own JSON line; a
    failing config prints an error line instead of killing the run."""
    import benchmarks.bert_lamb as bert
    import benchmarks.dcgan_bf16 as dcgan
    import benchmarks.fp8_bench as fp8_bench
    import benchmarks.generation_bench as generation
    import benchmarks.gpt_large as gpt_large
    import benchmarks.gpt_tp as gpt_tp
    import benchmarks.long_context as long_context
    import benchmarks.rn50_dp as rn50
    import benchmarks.vit_adam as vit

    configs = [
        ("rn50", lambda: rn50.main(batch=256, image=224)),
        ("dcgan", lambda: dcgan.main()),
        ("bert", lambda: bert.main()),
        ("gpt_tp", lambda: gpt_tp.main()),
        ("gpt2_355m", lambda: gpt_large.main()),
        ("vit", lambda: vit.main()),
        ("long_context_32k", lambda: long_context.main()),
        ("long_context_32k_window", lambda: long_context.main(window=1024)),
        ("long_context_64k_window",
         lambda: long_context.main(seq=65536, window=1024)),
        ("generation", lambda: generation.main()),
        ("fp8_dense", lambda: fp8_bench.main()),
    ]
    for name, fn in configs:
        try:
            fn()
        except Exception as e:                        # pragma: no cover
            print(json.dumps({
                "metric": f"{name}_FAILED", "value": 0.0, "unit": "error",
                "vs_baseline": 0.0,
                "error": f"{type(e).__name__}: {str(e)[:200]}"}))
            traceback.print_exc()


def _throwaway_warmup():
    """The FIRST jitted executable benchmarked in a process shows ~10x
    inflated steady-state times through the tunnel (remote-compile and
    connection ramp) — burn that on a dummy matmul, not a published row."""
    import numpy as np

    a = jnp.ones((2048, 2048), jnp.bfloat16)
    f = jax.jit(lambda a: a @ a)
    for _ in range(10):
        a = f(a)
    np.asarray(a[0, 0])


def main():
    _throwaway_warmup()
    _config_matrix()
    fused_tps, loss, n_params, seq, dt, tokens_per_step = _run(flash=True)
    baseline_tps, _, _, _, _, _ = _run(flash=False)
    from benchmarks._harness import peak_flops_per_chip, transformer_train_flops
    line = {
        "metric": "gpt2_124m_train_tokens_per_sec_per_chip",
        "value": round(fused_tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(fused_tps / baseline_tps, 3),
        # each side's best feasible config (ADVICE r2): the ratio measures
        # kernels AND the recompute headroom flash attention buys — it is
        # NOT a matched-config pure-kernel ratio
        "config": {"fused": "pallas kernels, no recompute",
                   "baseline": "plain XLA, full recompute (OOMs without)"},
    }
    peak = peak_flops_per_chip()
    if peak:
        mf = transformer_train_flops(n_params, tokens_per_step, 12, 768, seq,
                                     causal=True)
        line["mfu"] = round(mf / dt / peak, 4)
        line["model_tflops"] = round(mf / dt / 1e12, 1)
    print(json.dumps(line))


if __name__ == "__main__":
    main()
