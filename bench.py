"""Benchmark: flagship GPT training-step throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md) — the baseline here is the
*unfused* XLA implementation of the same model measured in-process (attention
via materialized scores + softmax instead of the Pallas flash kernel), so
``vs_baseline`` reports the speedup the fused/Pallas path delivers, the exact
claim the reference makes for its CUDA kernels.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

STEPS = 30   # longer window: amortizes queue ramp-up through the tunnel


def _build():
    from apex_tpu.models import GPTModel, TransformerConfig
    from apex_tpu.optimizers import FusedAdam

    cfg = TransformerConfig(
        num_layers=12, hidden_size=768, num_attention_heads=12,
        vocab_size=50304, max_position_embeddings=1024,
        hidden_dropout=0.0, attention_dropout=0.0,
        recompute=True, compute_dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-4)
    opt_state = opt.init(params)

    bs, seq = 8, 1024
    tokens = jax.random.randint(jax.random.PRNGKey(1), (bs, seq), 0, 50304)
    labels = jax.random.randint(jax.random.PRNGKey(2), (bs, seq), 0, 50304)

    def loss_fn(p):
        return model.apply(p, tokens, labels)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, opt_state = opt.step(grads, params, opt_state)
        return params, opt_state, loss

    return step, params, opt_state, bs * seq


def _run(flash: bool):
    import apex_tpu.ops._support as support
    import os

    # kernel dispatch is keyed on APEX_TPU_FORCE_PALLAS (ops/_support.py);
    # 'off' turns every fused op into its plain-XLA fallback = the baseline
    prev = os.environ.get("APEX_TPU_FORCE_PALLAS")
    os.environ["APEX_TPU_FORCE_PALLAS"] = (
        "tpu" if flash and jax.default_backend() == "tpu" else "off")
    support.pallas_mode.cache_clear()
    step, params, opt_state, tokens_per_step = _build()
    params, opt_state, loss = step(params, opt_state)          # compile
    _ = float(loss)
    # best-of-3 windows: the tunneled backend has multi-second transient
    # stalls (remote compile cache, connection ramp) that a single window
    # folds into the mean; min-of-windows reports steady-state throughput
    best = float("inf")
    for _w in range(3):
        t0 = time.perf_counter()
        for _i in range(STEPS):
            params, opt_state, loss = step(params, opt_state)
        _ = float(loss)                                        # host sync
        best = min(best, (time.perf_counter() - t0) / STEPS)
    dt = best
    if prev is None:
        os.environ.pop("APEX_TPU_FORCE_PALLAS", None)
    else:
        os.environ["APEX_TPU_FORCE_PALLAS"] = prev
    support.pallas_mode.cache_clear()
    return tokens_per_step / dt, float(loss)


def main():
    fused_tps, loss = _run(flash=True)
    baseline_tps, _ = _run(flash=False)
    print(json.dumps({
        "metric": "gpt2_124m_train_tokens_per_sec_per_chip",
        "value": round(fused_tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(fused_tps / baseline_tps, 3),
    }))


if __name__ == "__main__":
    main()
