"""Fused-optimizer parity vs independent references (tier-L0 analog of
``tests/L0/run_optimizers/test_fused_optimizer.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.optimizers import (
    FusedAdam,
    FusedAdagrad,
    FusedLAMB,
    FusedMixedPrecisionLamb,
    FusedNovoGrad,
    FusedSGD,
)
from apex_tpu.parallel import LARC
from apex_tpu.contrib.clip_grad import clip_grad_norm


def make_params(key=0):
    k = jax.random.PRNGKey(key)
    ks = jax.random.split(k, 4)
    return {
        "w1": jax.random.normal(ks[0], (8, 16)),
        "b1": jax.random.normal(ks[1], (16,)),
        "nested": {"w2": jax.random.normal(ks[2], (16, 4)),
                   "w3": jax.random.normal(ks[3], (3, 5, 7))},
    }


def make_grads(params, key=100):
    flat, treedef = jax.tree_util.tree_flatten(params)
    keys = jax.random.split(jax.random.PRNGKey(key), len(flat))
    return jax.tree_util.tree_unflatten(
        treedef, [jax.random.normal(k, x.shape) for k, x in zip(keys, flat)])


def run_steps(opt, params, n=5, **kw):
    state = opt.init(params)
    for i in range(n):
        grads = make_grads(params, key=100 + i)
        params, state = opt.step(grads, params, state, **kw)
    return params


def test_adam_matches_optax_adamw():
    params = make_params()
    mine = FusedAdam(lr=1e-2, weight_decay=0.01, adam_w_mode=True)
    ref = optax.adamw(1e-2, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.01)
    p1 = run_steps(mine, params)
    state = ref.init(params)
    p2 = params
    for i in range(5):
        grads = make_grads(p2, key=100 + i)
        updates, state = ref.update(grads, state, p2)
        p2 = optax.apply_updates(p2, updates)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


def test_adam_l2_mode_matches_optax_adam_with_l2():
    params = make_params()
    wd = 0.05
    mine = FusedAdam(lr=1e-2, weight_decay=wd, adam_w_mode=False)
    ref = optax.adam(1e-2)
    p1 = run_steps(mine, params)
    state = ref.init(params)
    p2 = params
    for i in range(5):
        grads = make_grads(p2, key=100 + i)
        grads = jax.tree_util.tree_map(lambda g, p: g + wd * p, grads, p2)
        updates, state = ref.update(grads, state, p2)
        p2 = optax.apply_updates(p2, updates)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


def test_sgd_momentum_matches_torch_semantics():
    """First-step momentum buffer = d_p (torch/apex), then EMA."""
    p0 = np.random.RandomState(0).randn(6).astype(np.float32)
    g1 = np.random.RandomState(1).randn(6).astype(np.float32)
    g2 = np.random.RandomState(2).randn(6).astype(np.float32)
    lr, mom, wd = 0.1, 0.9, 0.01

    # manual torch-style reference
    d1 = g1 + wd * p0
    buf = d1.copy()
    p_ref = p0 - lr * buf
    d2 = g2 + wd * p_ref
    buf = mom * buf + d2
    p_ref2 = p_ref - lr * buf

    opt = FusedSGD(lr=lr, momentum=mom, weight_decay=wd)
    params = {"p": jnp.asarray(p0)}
    state = opt.init(params)
    params, state = opt.step({"p": jnp.asarray(g1)}, params, state)
    np.testing.assert_allclose(params["p"], p_ref, atol=1e-6)
    params, state = opt.step({"p": jnp.asarray(g2)}, params, state)
    np.testing.assert_allclose(params["p"], p_ref2, atol=1e-6)


def test_sgd_nesterov_and_plain():
    params = make_params()
    # plain SGD == optax.sgd
    p1 = run_steps(FusedSGD(lr=0.05), params)
    ref = optax.sgd(0.05)
    state = ref.init(params)
    p2 = params
    for i in range(5):
        grads = make_grads(p2, key=100 + i)
        updates, state = ref.update(grads, state, p2)
        p2 = optax.apply_updates(p2, updates)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=1e-6)
    with pytest.raises(ValueError):
        FusedSGD(lr=0.1, nesterov=True)  # needs momentum


def test_adagrad_matches_manual():
    p0 = np.random.RandomState(0).randn(5).astype(np.float32)
    lr, eps = 0.1, 1e-10
    h = np.zeros_like(p0)
    p_ref = p0.copy()
    opt = FusedAdagrad(lr=lr, eps=eps)
    params = {"p": jnp.asarray(p0)}
    state = opt.init(params)
    for i in range(4):
        g = np.random.RandomState(10 + i).randn(5).astype(np.float32)
        h += g * g
        p_ref -= lr * g / (np.sqrt(h) + eps)
        params, state = opt.step({"p": jnp.asarray(g)}, params, state)
        np.testing.assert_allclose(params["p"], p_ref, atol=1e-6)


def test_lamb_trust_ratio_and_clip():
    params = make_params()
    opt = FusedLAMB(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
    p1 = run_steps(opt, params)
    # sanity: params moved, finite
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(params)):
        assert np.isfinite(np.asarray(a)).all()
        assert not np.allclose(a, b)
    # with tiny max_grad_norm, effective grads shrink -> smaller step
    opt_clip = FusedLAMB(lr=1e-2, weight_decay=0.0, max_grad_norm=1e-6)
    opt_free = FusedLAMB(lr=1e-2, weight_decay=0.0, max_grad_norm=0.0)
    pc = run_steps(opt_clip, params, n=1)
    pf = run_steps(opt_free, params, n=1)
    d_clip = sum(float(jnp.sum(jnp.abs(a - b))) for a, b in
                 zip(jax.tree_util.tree_leaves(pc), jax.tree_util.tree_leaves(params)))
    d_free = sum(float(jnp.sum(jnp.abs(a - b))) for a, b in
                 zip(jax.tree_util.tree_leaves(pf), jax.tree_util.tree_leaves(params)))
    assert d_clip < d_free


def test_lamb_without_wd_no_adaptation_matches_adamw_shape():
    """weight_decay=0, always_adapt=False → trust ratio 1 → plain AdamW-like step."""
    params = make_params()
    lamb = FusedLAMB(lr=1e-3, weight_decay=0.0, max_grad_norm=0.0)
    adam = FusedAdam(lr=1e-3, weight_decay=0.0, eps=1e-6)
    p1 = run_steps(lamb, params, n=3)
    p2 = run_steps(adam, params, n=3)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_novograd_runs_and_is_finite():
    params = make_params()
    opt = FusedNovoGrad(lr=1e-2, weight_decay=0.01, grad_averaging=True)
    p1 = run_steps(opt, params)
    for a in jax.tree_util.tree_leaves(p1):
        assert np.isfinite(np.asarray(a)).all()
    # per-tensor v is scalar
    state = opt.init(params)
    for v in jax.tree_util.tree_leaves(state["slots"]["exp_avg_sq"]):
        assert v.shape == ()


def test_master_weights_bf16():
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16), make_params())
    opt = FusedAdam(lr=1e-3, master_weights=True)
    state = opt.init(params)
    assert state["master"]["w1"].dtype == jnp.float32
    grads = make_grads(params)
    new_params, state = opt.step(grads, params, state)
    assert new_params["w1"].dtype == jnp.bfloat16
    # master retains precision across steps
    assert state["master"]["w1"].dtype == jnp.float32


def test_found_inf_skips_step():
    params = make_params()
    opt = FusedAdam(lr=1e-2)
    state = opt.init(params)
    grads = make_grads(params)
    p_skip, st_skip = opt.step(grads, params, state, found_inf=jnp.asarray(True))
    for a, b in zip(jax.tree_util.tree_leaves(p_skip), jax.tree_util.tree_leaves(params)):
        np.testing.assert_allclose(a, b)
    assert int(st_skip["step"]) == 0
    p_go, st_go = opt.step(grads, params, state, found_inf=jnp.asarray(False))
    assert int(st_go["step"]) == 1
    assert not np.allclose(p_go["b1"], params["b1"])


def test_grad_scale_unscales():
    params = make_params()
    opt = FusedAdam(lr=1e-2)
    grads = make_grads(params)
    scaled = jax.tree_util.tree_map(lambda g: g * 128.0, grads)
    p1, _ = opt.step(grads, params, opt.init(params))
    p2, _ = opt.step(scaled, params, opt.init(params),
                     grad_scale=jnp.asarray(128.0))
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_larc_clips_effective_lr():
    params = {"p": jnp.ones((4,)) * 1e-3}
    huge_grads = {"p": jnp.ones((4,)) * 1e3}
    base = FusedSGD(lr=0.1)
    larc = LARC(base, trust_coefficient=0.02)
    state = larc.init(params)
    p1, _ = larc.step(huge_grads, params, state)
    p_plain, _ = base.step(huge_grads, params, base.init(params))
    # LARC shrinks the step for tiny-norm params with huge grads
    assert float(jnp.max(jnp.abs(p1["p"] - params["p"]))) < \
        float(jnp.max(jnp.abs(p_plain["p"] - params["p"])))


def test_clip_grad_norm():
    grads = {"a": jnp.ones((10,)) * 3.0, "b": jnp.ones((5,)) * 4.0}
    clipped, norm = clip_grad_norm(grads, max_norm=1.0)
    expected = np.sqrt(10 * 9.0 + 5 * 16.0)
    np.testing.assert_allclose(float(norm), expected, rtol=1e-6)
    from apex_tpu.utils.tree import global_norm
    np.testing.assert_allclose(float(global_norm(clipped)), 1.0, rtol=1e-4)
    # under the limit -> unchanged
    small = {"a": jnp.full((4,), 0.01)}
    c2, _ = clip_grad_norm(small, 1.0)
    np.testing.assert_allclose(c2["a"], small["a"], rtol=1e-5)


def test_mixed_precision_lamb():
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16), make_params())
    opt = FusedMixedPrecisionLamb(lr=1e-3)
    state = opt.init(params)
    assert "master" in state
    grads = make_grads(params)
    new_params, state = opt.step(
        jax.tree_util.tree_map(lambda g: g.astype(jnp.bfloat16), grads),
        params, state)
    assert new_params["w1"].dtype == jnp.bfloat16


def test_jitted_step():
    params = make_params()
    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)
    grads = make_grads(params)

    @jax.jit
    def step(g, p, s):
        return opt.step(g, p, s)

    p1, s1 = step(grads, params, state)
    p2, s2 = opt.step(grads, params, state)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, atol=1e-6)


class TestWeightDecayMask:
    """Param-groups parity: torch users put norm/bias params in a wd=0
    group (the reference's examples do exactly this); here a per-leaf mask
    on the optimizer."""

    def _params(self):
        return {"w": jnp.full((4,), 2.0), "bias": jnp.full((4,), 2.0)}

    def _mask(self, params):
        return {"w": True, "bias": False}

    @pytest.mark.parametrize("cls,kw", [
        (FusedAdam, {}),
        (FusedSGD, {"momentum": 0.9}),
        (FusedLAMB, {}),
        (FusedNovoGrad, {}),
        (FusedAdagrad, {}),
    ])
    def test_masked_leaf_not_decayed(self, cls, kw):
        p = self._params()
        g = {"w": jnp.zeros((4,)), "bias": jnp.zeros((4,))}
        opt = cls(lr=0.1, weight_decay=0.1, weight_decay_mask=self._mask,
                  **kw)
        ref = cls(lr=0.1, weight_decay=0.0, **kw)   # wd fully off
        st, rst = opt.init(p), ref.init(p)
        p1, _ = opt.step(g, p, st)
        p_ref, _ = ref.step(g, p, rst)
        # bias leaf behaves exactly as wd=0
        np.testing.assert_allclose(np.asarray(p1["bias"]),
                                   np.asarray(p_ref["bias"]), rtol=1e-6)
        # w leaf is decayed (zero grads -> only wd moves it)
        assert float(jnp.max(jnp.abs(p1["w"] - p["w"]))) > 0

    def test_mask_as_pytree(self):
        p = self._params()
        g = jax.tree.map(jnp.zeros_like, p)
        opt = FusedAdam(lr=0.1, weight_decay=0.1,
                        weight_decay_mask={"w": True, "bias": False})
        p1, _ = opt.step(g, p, opt.init(p))
        np.testing.assert_allclose(np.asarray(p1["bias"]), 2.0)

    def test_distributed_accepts_mask(self):
        # masks flatten into per-element buffer segments now; full parity
        # coverage lives in tests/test_zero_checkpoint.py
        from apex_tpu.optimizers import DistributedFusedAdam

        p = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
        opt = DistributedFusedAdam(lr=0.1, num_shards=1, weight_decay=0.1,
                                   weight_decay_mask={"w": True, "b": False})
        g = jax.tree.map(jnp.ones_like, p)
        p1, s1 = opt.step(g, p, opt.init(p))
        assert int(s1["step"]) == 1
