"""fp8 delayed-scaling scaffolding tests.

The reference's fp8 story is the AMAX reduction process group
(``apex/transformer/parallel_state.py:280-292``, TP x DP per pipeline
stage); here that group is a set of mesh axes and the reduction is a
``lax.pmax``. These tests pin (a) the mesh-axis translation — every rank in
the amax group computes the identical scale, pipeline stages stay
independent — and (b) the delayed-scaling recipe math.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.amp import fp8
from apex_tpu.transformer import parallel_state
from apex_tpu.utils.sharding import shard_map


class TestRecipe:
    def test_scale_from_history_max(self):
        state = fp8.init_fp8_state(["w"], fp8.Fp8Recipe(amax_history_len=4))
        r = fp8.Fp8Recipe(amax_history_len=4)
        for amax in (2.0, 8.0, 4.0):
            state = fp8.update_fp8_state(
                state, {"w": jnp.asarray(amax)}, r, axis_names=())
        # window max = 8 -> scale = 448 / 8
        np.testing.assert_allclose(float(state["w"]["scale"]), 448.0 / 8.0)
        np.testing.assert_allclose(
            np.asarray(state["w"]["amax_history"])[:3], [4.0, 8.0, 2.0])

    def test_most_recent_and_margin(self):
        r = fp8.Fp8Recipe(amax_history_len=4, amax_compute_algo="most_recent",
                          margin=1)
        state = fp8.init_fp8_state(["w"], r)
        for amax in (8.0, 2.0):
            state = fp8.update_fp8_state(state, {"w": jnp.asarray(amax)}, r,
                                         axis_names=())
        np.testing.assert_allclose(float(state["w"]["scale"]),
                                   448.0 / (2.0 * 2.0))

    def test_zero_amax_keeps_scale(self):
        r = fp8.Fp8Recipe(amax_history_len=2)
        state = fp8.init_fp8_state(["w"], r)
        state = fp8.update_fp8_state(state, {"w": jnp.asarray(0.0)}, r,
                                     axis_names=())
        np.testing.assert_allclose(float(state["w"]["scale"]), 1.0)

    def test_inf_amax_keeps_scale_and_recovers(self):
        # an overflow step (amax = inf) must neither zero the scale (NaN
        # dequantize) nor pin the window at inf
        r = fp8.Fp8Recipe(amax_history_len=2)
        state = fp8.init_fp8_state(["w"], r)
        state = fp8.update_fp8_state(state, {"w": jnp.asarray(4.0)}, r,
                                     axis_names=())
        s_before = float(state["w"]["scale"])
        state = fp8.update_fp8_state(state, {"w": jnp.asarray(jnp.inf)}, r,
                                     axis_names=())
        assert float(state["w"]["scale"]) == s_before
        y = fp8.dequantize(fp8.quantize(jnp.ones(4), state["w"]["scale"]),
                           state["w"]["scale"])
        assert np.isfinite(np.asarray(y)).all()
        # window rolls the sanitized 0 out; next finite amax takes over
        state = fp8.update_fp8_state(state, {"w": jnp.asarray(2.0)}, r,
                                     axis_names=())
        state = fp8.update_fp8_state(state, {"w": jnp.asarray(2.0)}, r,
                                     axis_names=())
        np.testing.assert_allclose(float(state["w"]["scale"]), 448.0 / 2.0)

    def test_bwd_dtype_range(self):
        r = fp8.Fp8Recipe(amax_history_len=1)
        state = fp8.init_fp8_state(["g"], r)
        state = fp8.update_fp8_state(state, {"g": jnp.asarray(2.0)}, r,
                                     axis_names=(),
                                     dtypes={"g": r.bwd_dtype})
        np.testing.assert_allclose(float(state["g"]["scale"]), 57344.0 / 2.0)

    def test_qdq_roundtrip_error_bounded(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (64, 64)) * 3.0
        scale = jnp.asarray(448.0 / float(jnp.max(jnp.abs(x))))
        y = fp8.qdq(x, scale, fp8.E4M3)
        assert y.dtype == x.dtype
        # e4m3 has 3 mantissa bits -> relative step 2^-3; scaled to amax
        err = np.max(np.abs(np.asarray(y - x)))
        assert err <= float(jnp.max(jnp.abs(x))) / 8.0
        # and fp8 rounding genuinely happened
        assert not np.allclose(np.asarray(y), np.asarray(x), atol=1e-6)


class TestAmaxReductionMesh:
    def test_axes_exclude_pipeline(self):
        axes = parallel_state.amax_reduction_axes()
        assert "pipeline" not in axes
        assert set(axes) == {"data", "context", "tensor"}
        assert "pipeline" in parallel_state.amax_reduction_axes(
            include_pipeline=True)

    def test_scales_agree_within_group_and_differ_across_stages(self):
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=2, pipeline_model_parallel_size=2)
        r = fp8.Fp8Recipe(amax_history_len=2)

        def per_rank(x):
            # per-rank distinct activations; stages see different tensors
            state = fp8.init_fp8_state(["h"], r)
            state = fp8.update_fp8_state(state, {"h": fp8.compute_amax(x)}, r)
            return state["h"]["scale"].reshape(1, 1, 1)

        # amax on (dp, pp, tp) rank = crafted so the group max differs per
        # pipeline stage: stage 0 sees max 4, stage 1 sees max 16
        x = jnp.asarray([[[1.0, 4.0], [2.0, 16.0]],
                         [[3.0, 2.0], [8.0, 1.0]]])   # [dp, pp, tp]
        scales = jax.jit(shard_map(
            per_rank, mesh=mesh,
            in_specs=P("data", "pipeline", "tensor"),
            out_specs=P("data", "pipeline", "tensor"),
            check_vma=False))(x[..., None])
        scales = np.asarray(scales).reshape(2, 2, 2)
        # within each pipeline stage: all dp x tp ranks agree
        np.testing.assert_allclose(scales[:, 0, :], 448.0 / 4.0)
        np.testing.assert_allclose(scales[:, 1, :], 448.0 / 16.0)
        parallel_state.destroy_model_parallel()

    def test_unsharded_is_identity(self):
        a = {"w": jnp.asarray(3.0)}
        out = fp8.reduce_amaxes(a, ("data", "tensor"))
        np.testing.assert_allclose(float(out["w"]), 3.0)


class TestMultiSliceMesh:
    def test_dcn_major_data_axis(self):
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=2, num_slices=2)
        assert parallel_state.get_num_slices() == 2
        assert parallel_state.get_data_parallel_world_size() == 4
        assert parallel_state.get_data_parallel_dcn_size() == 2
        assert parallel_state.get_data_parallel_ici_size() == 2
        # model-axis groups never cross the slice boundary: with 8 devices
        # in enumeration order, slice = id // 4
        devs = mesh.devices          # [dp, pp, cp, tp]
        per_slice = 4
        for d in range(devs.shape[0]):
            block = devs[d].reshape(-1)
            slices = {dev.id // per_slice for dev in block}
            assert len(slices) == 1, (
                f"data coord {d} spans slices {slices}")
        # DCN-major: data coords 0,1 on slice 0; 2,3 on slice 1
        slice_of = [devs[d, 0, 0, 0].id // per_slice
                    for d in range(devs.shape[0])]
        assert slice_of == [0, 0, 1, 1]
        parallel_state.destroy_model_parallel()

    def test_model_axes_cannot_cross_dcn(self):
        import pytest

        if len(jax.devices()) < 8:
            pytest.skip("the divisibility check fires before the DCN guard "
                        "on small device counts")
        parallel_state.destroy_model_parallel()
        with pytest.raises(RuntimeError, match="DCN"):
            parallel_state.initialize_model_parallel(
                tensor_model_parallel_size=8, num_slices=2)
        parallel_state.destroy_model_parallel()

    def test_indivisible_slices_rejected(self):
        import pytest

        parallel_state.destroy_model_parallel()
        with pytest.raises(RuntimeError, match="num_slices"):
            parallel_state.initialize_model_parallel(num_slices=3)
        parallel_state.destroy_model_parallel()


class TestFp8Dense:
    """The delayed-scaling matmul hook: scales trail the data one step,
    gradients pass straight-through the quantizer."""

    def test_trains_and_scales_adapt(self):
        r = fp8.Fp8Recipe(amax_history_len=4)
        w = jax.random.normal(jax.random.PRNGKey(0), (32, 16)) * 0.3
        x = jax.random.normal(jax.random.PRNGKey(1), (64, 32)) * 2.0
        y_t = jax.random.normal(jax.random.PRNGKey(2), (64, 16))
        state = fp8.init_fp8_state(["x", "w"], r)

        @jax.jit
        def step(w, state):
            def loss_fn(w):
                y, new_state = fp8.fp8_dense(x, w, state, recipe=r,
                                             axis_names=())
                return jnp.mean((y - y_t) ** 2), new_state
            (loss, new_state), g = jax.value_and_grad(
                loss_fn, has_aux=True)(w)
            return w - 0.05 * g, new_state, loss

        losses = []
        for _ in range(25):
            w, state, loss = step(w, state)
            losses.append(float(loss))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.7
        # scales adapted to the observed amaxes (no longer the init 1.0)
        assert float(state["x"]["scale"]) != 1.0
        assert float(state["w"]["scale"]) != 1.0

    def test_matches_unquantized_within_fp8_tolerance(self):
        r = fp8.Fp8Recipe(amax_history_len=1)
        x = jax.random.normal(jax.random.PRNGKey(3), (32, 64))
        w = jax.random.normal(jax.random.PRNGKey(4), (64, 32)) * 0.1
        state = fp8.init_fp8_state(["x", "w"], r)
        # one warmup call installs data-driven scales
        _, state = fp8.fp8_dense(x, w, state, recipe=r, axis_names=())
        y, _ = fp8.fp8_dense(x, w, state, recipe=r, axis_names=())
        ref = x @ w
        rel = float(jnp.max(jnp.abs(y - ref)) / jnp.max(jnp.abs(ref)))
        assert rel < 0.1          # e4m3 rounding, not garbage

    def test_straight_through_gradient(self):
        r = fp8.Fp8Recipe(amax_history_len=1)
        x = jnp.ones((4, 8))
        w = jnp.full((8, 2), 0.5)
        state = fp8.init_fp8_state(["x", "w"], r)
        _, state = fp8.fp8_dense(x, w, state, recipe=r, axis_names=())

        def loss_fn(w):
            y, _ = fp8.fp8_dense(x, w, state, recipe=r, axis_names=())
            return jnp.sum(y)

        g = jax.grad(loss_fn)(w)
        # d(sum(xq @ wq))/dw ~= x^T @ ones through the straight-through path
        ref = jnp.ones((4, 8)).T @ jnp.ones((4, 2))
        np.testing.assert_allclose(np.asarray(g), np.asarray(ref),
                                   rtol=0.1)

    def test_backward_e5m2_rounding_applied(self):
        # the cotangent path must show e5m2 quantization effects (current
        # scaling): grads through fp8_dense differ from exact bf16 grads
        # by bounded rounding, and disabling bwd_dtype recovers exactness
        x = jax.random.normal(jax.random.PRNGKey(5), (16, 32))
        w = jax.random.normal(jax.random.PRNGKey(6), (32, 8)) * 0.1
        r = fp8.Fp8Recipe(amax_history_len=1)
        state = fp8.init_fp8_state(["x", "w"], r)
        _, state = fp8.fp8_dense(x, w, state, recipe=r, axis_names=())
        ct = jax.random.normal(jax.random.PRNGKey(7), (16, 8))

        def loss_fn(w):
            y, _ = fp8.fp8_dense(x, w, state, recipe=r, axis_names=())
            return jnp.sum(y * ct)

        g = jax.grad(loss_fn)(w)
        # reference: same fwd qdq operands, exact backward
        xq = fp8.qdq(x, state["x"]["scale"])
        ref = jax.grad(lambda w: jnp.sum(
            (xq @ fp8.qdq(w, state["w"]["scale"])) * ct))(w)
        rel = float(jnp.max(jnp.abs(g - ref)) / jnp.max(jnp.abs(ref)))
        assert rel < 0.4             # e5m2 (2 mantissa bits), not garbage
        assert rel > 0.0             # and genuinely quantized


class TestNativeFp8Dispatch:
    """Native fp8 dot_general path (round 3): same delayed-scaling state,
    the dot runs ON fp8 storage dtypes instead of the qdq simulation.
    Parity bounds reflect only accumulation-dtype differences (the native
    path accumulates in fp32; the qdq path matmuls dequantized values in
    the input dtype)."""

    def test_probe_and_forward_parity(self):
        assert fp8.native_fp8_dot_supported() in (True, False)
        if not fp8.native_fp8_dot_supported():
            pytest.skip("backend cannot run fp8 dot_general")
        r = fp8.Fp8Recipe(amax_history_len=1)
        x = jax.random.normal(jax.random.PRNGKey(0), (32, 64))
        w = jax.random.normal(jax.random.PRNGKey(1), (64, 32)) * 0.1
        state = fp8.init_fp8_state(["x", "w"], r)
        _, state = fp8.fp8_dense(x, w, state, recipe=r, axis_names=())
        y_n, st_n = fp8.fp8_dense(x, w, state, recipe=r, axis_names=(),
                                  native=True)
        y_q, st_q = fp8.fp8_dense(x, w, state, recipe=r, axis_names=(),
                                  native=False)
        np.testing.assert_allclose(np.asarray(y_n), np.asarray(y_q),
                                   rtol=2e-3, atol=2e-3)
        # the state machinery is shared: identical updates
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b)), st_n, st_q)

    def test_gradient_parity_vs_unquantized(self):
        """The two backwards round in different places (native quantizes
        the cotangent BEFORE its GEMMs — the TE order; qdq rounds the
        already-computed grads), so they are not bitwise-comparable: both
        must instead sit within e5m2-level error of the unquantized
        reference gradients."""
        if not fp8.native_fp8_dot_supported():
            pytest.skip("backend cannot run fp8 dot_general")
        r = fp8.Fp8Recipe(amax_history_len=1)
        x = jax.random.normal(jax.random.PRNGKey(2), (16, 32))
        w = jax.random.normal(jax.random.PRNGKey(3), (32, 16)) * 0.2
        state = fp8.init_fp8_state(["x", "w"], r)
        _, state = fp8.fp8_dense(x, w, state, recipe=r, axis_names=())

        def loss(native):
            def f(x, w):
                y, _ = fp8.fp8_dense(x, w, state, recipe=r, axis_names=(),
                                     native=native)
                return jnp.sum(y ** 2)
            return f

        g_ref = jax.grad(lambda x, w: jnp.sum((x @ w) ** 2),
                         argnums=(0, 1))(x, w)
        for native in (True, False):
            for g, ref in zip(jax.grad(loss(native), argnums=(0, 1))(x, w),
                              g_ref):
                rel = float(jnp.max(jnp.abs(g - ref))
                            / jnp.max(jnp.abs(ref)))
                assert rel < 0.4, (native, rel)   # e5m2, not garbage

    def test_native_trains(self):
        if not fp8.native_fp8_dot_supported():
            pytest.skip("backend cannot run fp8 dot_general")
        r = fp8.Fp8Recipe(amax_history_len=4)
        x = jax.random.normal(jax.random.PRNGKey(5), (64, 32))
        w0 = jax.random.normal(jax.random.PRNGKey(6), (32, 8)) * 0.3
        y_t = jnp.tanh(x @ w0)
        w = jax.random.normal(jax.random.PRNGKey(7), (32, 8)) * 0.3
        state = fp8.init_fp8_state(["x", "w"], r)

        @jax.jit
        def step(w, state):
            def loss_fn(w):
                y, new_state = fp8.fp8_dense(x, w, state, recipe=r,
                                             axis_names=(), native=True)
                return jnp.mean((y - y_t) ** 2), new_state
            (loss, new_state), g = jax.value_and_grad(
                loss_fn, has_aux=True)(w)
            return w - 0.05 * g, new_state, loss

        losses = []
        for _ in range(25):
            w, state, loss = step(w, state)
            losses.append(float(loss))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.7
