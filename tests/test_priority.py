"""Priority-aware overload survival (ISSUE 20): preemptive scheduling,
per-tenant quotas, and the brownout degradation ladder.

The contract under test, end to end:

- `SamplingParams.priority` selects a class; the scheduler dispatches
  strict-priority across classes, FCFS inside one, with an aging floor
  that keeps batch deferred-but-never-starved.
- Preemption parks a running lower-class slot (slot + private pages
  released, host-side token cursor kept) and resumes it later
  TOKEN-EXACT — greedy and sampled — under the request's ORIGINAL
  ids and deadline clock, with exactly one terminal record.
- `QuotaLedger` token-bucket / inflight / page math is deterministic
  in the caller's clock; hard limits shed, soft limits defer.
- The brownout ladder escalates batch-first with hysteresis and emits
  a typed record + counter/event pair per transition, reconciling
  key-for-key in the monitor report.
- A committed pre-PR20 run log (no priority fields, no brownout rows)
  still builds, renders without the new sections, and stays
  span-conservation clean.
"""

import random

import pytest

from apex_tpu.analysis.mc.sim import SimEngine, SimModel, sim_stream
from apex_tpu.observability import (
    InMemorySink,
    MetricsRegistry,
    build_report,
    render_report,
)
from apex_tpu.serving import clock
from apex_tpu.serving.clock import VirtualClock, use_clock
from apex_tpu.serving.engine import EngineConfig
from apex_tpu.serving.fleet.brownout import (
    BROWNOUT_RUNGS,
    BrownoutConfig,
    BrownoutController,
)
from apex_tpu.serving.fleet.quota import (
    BASE_TENANT,
    QUOTA_ADMIT,
    QUOTA_DEFER,
    QUOTA_SHED,
    QuotaConfig,
    QuotaLedger,
    TenantQuota,
)
from apex_tpu.serving.request import (
    PRIORITIES,
    PRIORITY_BATCH,
    PRIORITY_INTERACTIVE,
    PRIORITY_STANDARD,
    Request,
    SamplingParams,
)
from apex_tpu.serving.scheduler import FCFSScheduler, SchedulerConfig


def _req(prompt, max_new=4, priority=PRIORITY_STANDARD, rid=None,
         adapter=None, deadline=None, **sampling):
    kwargs = {} if rid is None else {"request_id": rid}
    return Request(prompt=list(prompt), max_new_tokens=max_new,
                   sampling=SamplingParams(priority=priority,
                                           adapter_id=adapter, **sampling),
                   deadline_s=deadline, **kwargs)


# ---------------------------------------------------------------------------
# priority classes + class-aware scheduler (pure host-side)
# ---------------------------------------------------------------------------

class TestPriorityClasses:
    def test_default_is_standard(self):
        assert SamplingParams().priority == PRIORITY_STANDARD

    def test_invalid_priority_rejected(self):
        with pytest.raises(ValueError, match="priority"):
            SamplingParams(priority="urgent")

    def test_strict_priority_across_classes_fcfs_within(self):
        sched = FCFSScheduler(SchedulerConfig(max_queue=16))
        order = [("b1", PRIORITY_BATCH), ("s1", PRIORITY_STANDARD),
                 ("b2", PRIORITY_BATCH), ("i1", PRIORITY_INTERACTIVE),
                 ("s2", PRIORITY_STANDARD)]
        ids = {}
        for i, (name, prio) in enumerate(order):
            r = _req([1, 2], priority=prio)
            ids[r.request_id] = name
            sched.submit(r, now=float(i))
        popped = []
        while sched.depth:
            (got,) = sched.pop_admissible(1, False, now=10.0)
            popped.append(ids[got[0].request_id])
        assert popped == ["i1", "s1", "s2", "b1", "b2"]

    def test_batch_aging_promotes_to_standard_rank(self):
        sched = FCFSScheduler(SchedulerConfig(max_queue=8,
                                              batch_aging_s=5.0))
        aged = _req([1], priority=PRIORITY_BATCH)
        fresh = _req([1], priority=PRIORITY_STANDARD)
        sched.submit(aged, now=0.0)
        sched.submit(fresh, now=8.0)
        # past the aging floor the batch head competes at standard rank;
        # FCFS inside that rank makes the older batch head win
        head, _ = sched.head(now=9.0)
        assert head.request_id == aged.request_id
        # without aging (young head) standard dispatches first
        assert sched.head(now=1.0)[0].request_id == fresh.request_id

    def test_admission_floor_pauses_lower_classes(self):
        sched = FCFSScheduler(SchedulerConfig(max_queue=8))
        b = _req([1], priority=PRIORITY_BATCH)
        s = _req([1], priority=PRIORITY_STANDARD)
        sched.submit(b, now=0.0)
        sched.submit(s, now=0.0)
        sched.set_admission_floor(PRIORITY_STANDARD)
        got = sched.pop_admissible(4, False, now=1.0)
        assert [g[0].request_id for g in got] == [s.request_id]
        assert sched.depth_by_class()[PRIORITY_BATCH] == 1
        sched.set_admission_floor(None)
        (got,) = sched.pop_admissible(4, False, now=1.0)
        assert got[0].request_id == b.request_id

    def test_queued_tokens_split_per_class(self):
        sched = FCFSScheduler(SchedulerConfig(max_queue=8))
        sched.submit(_req([1] * 5, priority=PRIORITY_BATCH), now=0.0)
        sched.submit(_req([1] * 3, priority=PRIORITY_INTERACTIVE), now=0.0)
        by = sched.queued_tokens_by_class()
        assert by[PRIORITY_BATCH] == 5
        assert by[PRIORITY_INTERACTIVE] == 3
        assert by[PRIORITY_STANDARD] == 0
        assert sched.queued_tokens == 8


# ---------------------------------------------------------------------------
# quota bucket math (pure host-side)
# ---------------------------------------------------------------------------

class TestQuotaMath:
    def _ledger(self, **quota):
        cfg = QuotaConfig(tenants={"t": TenantQuota(**quota)})
        return QuotaLedger(cfg)

    def test_unlisted_tenant_unlimited(self):
        led = QuotaLedger(QuotaConfig(tenants={"t": TenantQuota(
            max_inflight=1)}))
        for _ in range(50):
            assert led.verdict("other", 0.0) == (QUOTA_ADMIT, None)
            led.commit("other", 0.0)

    def test_default_applies_to_unlisted(self):
        led = QuotaLedger(QuotaConfig(
            default=TenantQuota(max_inflight=1)))
        assert led.verdict("x", 0.0) == (QUOTA_ADMIT, None)
        led.commit("x", 0.0)
        assert led.verdict("x", 0.0) == (QUOTA_SHED, "inflight")

    def test_bucket_burst_then_refill(self):
        led = self._ledger(rate_rps=2.0, burst=3.0)
        # a quiet tenant lands its full burst at one instant
        for _ in range(3):
            assert led.verdict("t", 10.0)[0] == QUOTA_ADMIT
            led.commit("t", 10.0)
        assert led.verdict("t", 10.0) == (QUOTA_SHED, "rate")
        # refill is linear in elapsed time, capped at burst
        assert led.bucket_tokens("t", 10.25) == pytest.approx(0.5)
        assert led.verdict("t", 10.25)[0] == QUOTA_SHED
        assert led.verdict("t", 10.5)[0] == QUOTA_ADMIT   # 1 token back
        led.commit("t", 10.5)
        assert led.bucket_tokens("t", 100.0) == pytest.approx(3.0)

    def test_inflight_cap_and_release(self):
        led = self._ledger(max_inflight=2)
        led.commit("t", 0.0)
        led.commit("t", 0.0)
        assert led.verdict("t", 0.0) == (QUOTA_SHED, "inflight")
        led.release("t")
        assert led.verdict("t", 0.0)[0] == QUOTA_ADMIT
        # release is floored at zero, never negative
        for _ in range(5):
            led.release("t")
        assert led.inflight("t") == 0

    def test_page_cap_worst_case(self):
        led = self._ledger(max_pages=4)
        assert led.verdict("t", 0.0, pages=3)[0] == QUOTA_ADMIT
        led.commit("t", 0.0, pages=3)
        assert led.verdict("t", 0.0, pages=2) == (QUOTA_SHED, "pages")
        assert led.verdict("t", 0.0, pages=1)[0] == QUOTA_ADMIT
        led.release("t", pages=3)
        assert led.pages_held("t") == 0

    def test_soft_quota_defers_instead_of_shedding(self):
        led = self._ledger(rate_rps=1.0, burst=1.0, soft=True)
        led.commit("t", 0.0)
        assert led.verdict("t", 0.0) == (QUOTA_DEFER, "rate")
        assert led.verdict("t", 1.5)[0] == QUOTA_ADMIT

    def test_verdict_is_pure_commit_consumes(self):
        led = self._ledger(rate_rps=1.0, burst=1.0)
        for _ in range(10):    # verdicts never burn bucket tokens
            assert led.verdict("t", 0.0)[0] == QUOTA_ADMIT
        led.commit("t", 0.0)
        assert led.verdict("t", 0.0)[0] == QUOTA_SHED

    def test_tenant_key_is_adapter_or_base(self):
        assert QuotaLedger.tenant(_req([1], adapter="a0")) == "a0"
        assert QuotaLedger.tenant(_req([1])) == BASE_TENANT

    def test_knob_validation(self):
        with pytest.raises(ValueError, match="burst"):
            TenantQuota(burst=0.5)
        with pytest.raises(ValueError, match="rate_rps"):
            TenantQuota(rate_rps=-1.0)


# ---------------------------------------------------------------------------
# park / resume mechanics + page conservation (SimEngine, no jax)
# ---------------------------------------------------------------------------

def _sim_engine(metrics=None, max_slots=2, max_queue=8, page_size=4):
    cfg = EngineConfig(max_slots=max_slots, max_len=64,
                       page_size=page_size,
                       scheduler=SchedulerConfig(
                           max_queue=max_queue, max_prefills_per_tick=1))
    if metrics is None:
        metrics = MetricsRegistry(sinks=[InMemorySink()])
    return SimEngine(SimModel(), {}, cfg, metrics=metrics, replica_id=0)


class TestParkReleaseConservation:
    def test_park_releases_slot_and_pages(self):
        with use_clock(VirtualClock()):
            eng = _sim_engine()
            r = _req([1, 2, 3], max_new=8, priority=PRIORITY_BATCH)
            eng.submit(r)
            eng.tick()
            assert eng.active_count == 1 and eng.pool.used > 0
            assert eng.park_class(PRIORITY_BATCH, cause="test") == 1
            assert eng.active_count == 0 and eng.pool.used == 0
            assert eng.parked_count == 1
            ((req, toks, _ts),) = eng.take_parked()
            assert req.request_id == r.request_id
            assert toks == sim_stream(r.prompt, 8)[:len(toks)]
            eng.close()

    def test_parked_request_cancel_and_deadline(self):
        with use_clock(VirtualClock()) as vc:
            eng = _sim_engine()
            dead = _req([1, 2], max_new=30, priority=PRIORITY_BATCH,
                        deadline=0.5)
            keep = _req([3, 4], max_new=30, priority=PRIORITY_BATCH)
            eng.submit(dead)
            eng.submit(keep)
            eng.tick()    # max_prefills_per_tick=1: one admit per tick
            eng.tick()
            assert eng.park_class(PRIORITY_BATCH, cause="test") == 2
            # cancel while parked: terminal immediately
            assert eng.cancel(keep.request_id)
            assert eng.completed[keep.request_id].finish_reason == "cancelled"
            # the deadline clock never stopped while parked
            vc.advance(1.0)
            finished = eng.tick()
            (res,) = [r for r in finished
                      if r.request_id == dead.request_id]
            assert res.finish_reason == "timeout"
            assert eng.parked_count == 0 and eng.pool.used == 0
            eng.close()

    def test_randomized_park_churn_conserves_pages(self):
        """Under seeded random submit/park/cancel/tick churn the page
        pool balances every step (used == live requests' footprint,
        allocs - frees == used) and drains to zero."""
        rng = random.Random(20)
        with use_clock(VirtualClock()) as vc:
            metrics = MetricsRegistry(sinks=[InMemorySink()])
            eng = _sim_engine(metrics=metrics, max_slots=2, max_queue=16)
            eng.resume_consumer = True     # let _maybe_preempt fire too
            live = []
            for step in range(120):
                op = rng.randrange(6)
                if op <= 1 and eng.queued_count < 15:
                    r = _req([1 + rng.randrange(6)] * (1 + rng.randrange(5)),
                             max_new=1 + rng.randrange(6),
                             priority=PRIORITIES[rng.randrange(3)])
                    eng.submit(r)
                    live.append(r.request_id)
                elif op == 2:
                    eng.park_class(PRIORITIES[1 + rng.randrange(2)],
                                   cause="churn")
                elif op == 3 and live:
                    eng.cancel(live[rng.randrange(len(live))])
                else:
                    vc.advance(0.01)
                    eng.tick()
                # parked requests hold no pages; actives account for all
                want = sum(eng.pool.pages_for(rec.request)
                           for rec in eng._active.values())
                assert eng.pool.used == want
                assert (eng.pool.total_allocs - eng.pool.total_frees
                        == eng.pool.used)
            # drain everything: parked cursors must resume via a
            # consumer in real life — here the churn drains by restart
            for _, toks, _ in eng.take_parked():
                pass
            for _ in range(200):
                vc.advance(0.01)
                if not eng.tick() and eng.inflight() == 0:
                    break
            assert eng.pool.used == 0
            eng.close()


class TestSimPreemptResume:
    """The preemption rule + token-exact resume on the sim engine (the
    same code path the mc checker explores; the jax engine's exactness
    is covered by the slow-tier cross below and the priority_storm
    scenario gate)."""

    def test_interactive_head_parks_lowest_class(self):
        with use_clock(VirtualClock()):
            metrics = MetricsRegistry(sinks=[sink := InMemorySink()])
            eng = _sim_engine(metrics=metrics, max_slots=2)
            eng.resume_consumer = True
            b = _req([1, 2], max_new=20, priority=PRIORITY_BATCH)
            s = _req([3, 4], max_new=20, priority=PRIORITY_STANDARD)
            eng.submit(b)
            eng.submit(s)
            eng.tick()        # one admit per tick -> two ticks
            eng.tick()        # both admitted, slots full
            hi = _req([5, 6], max_new=2, priority=PRIORITY_INTERACTIVE)
            eng.submit(hi)
            eng.tick()        # preempts ONE slot: the batch one
            parked = eng.take_parked()
            assert [p[0].request_id for p in parked] == [b.request_id]
            assert metrics.counters()["requests_preempted"] == 1
            events = [r for r in sink.records
                      if r.get("kind") == "event"
                      and r.get("event") == "request_preempted"]
            assert len(events) == 1
            assert events[0]["priority"] == PRIORITY_BATCH
            eng.close()

    def test_no_preemption_without_consumer_or_free_slots(self):
        with use_clock(VirtualClock()):
            eng = _sim_engine(max_slots=2)
            assert eng.resume_consumer is False
            b = _req([1, 2], max_new=20, priority=PRIORITY_BATCH)
            eng.submit(b)
            eng.tick()
            eng.submit(_req([5], max_new=2,
                            priority=PRIORITY_INTERACTIVE))
            eng.tick()
            # a free slot admitted the head — nothing was parked; and
            # without a resume consumer the engine never parks on its own
            assert eng.parked_count == 0
            eng.close()

    def test_resume_token_exact_through_fleet(self):
        """Fleet + supervisor end to end on sim engines: a parked batch
        request resumes TOKEN-EXACT (canonical sim stream), keeps its
        original trace_id, and is terminal exactly once."""
        from apex_tpu.analysis.mc.harness import MCConfig, FleetHarness

        with use_clock(VirtualClock()):
            h = FleetHarness(MCConfig(replicas=1, preempt=True))
            try:
                b = _req([2, 3], max_new=6, priority=PRIORITY_BATCH,
                         rid=900001)
                h.fleet.submit(b)
                h._tick_once()      # admit + first token
                (replica,) = h.fleet.replicas
                assert replica.supervisor.preempt_class(
                    PRIORITY_BATCH, cause="test") == 1
                for _ in range(100):
                    h._tick_once()
                    if b.request_id in h.fleet.completed:
                        break
                res = h.fleet.completed[b.request_id]
                assert res.finish_reason == "length"
                assert list(res.tokens) == sim_stream(b.prompt, 6)
                assert res.trace_id == b.trace_id
                counters = h.registry.counters()
                assert counters["requests_preempted"] == 1
                assert counters["requests_resumed"] == 1
                terminal = [r for r in h.sink.records
                            if r.get("kind") == "request"
                            and r.get("request_id") == b.request_id]
                assert len(terminal) == 1
                marks = [r for r in h.sink.records
                         if r.get("kind") == "span"
                         and r.get("span") in ("preempt", "resume")]
                assert [m["span"] for m in marks] == ["preempt", "resume"]
                assert all(m["trace_id"] == b.trace_id for m in marks)
            finally:
                h.cleanup()


# ---------------------------------------------------------------------------
# brownout ladder (pure controller + telemetry reconciliation)
# ---------------------------------------------------------------------------

class _StubFleetMetrics:
    """Scripted signals stream: full control of the pressure the
    controller sees, poll by poll."""

    def __init__(self, fleet, depths):
        self.fleet = fleet
        self.depths = list(depths)

    def signals(self):
        return {"queue_depth": self.depths.pop(0),
                "replicas_dispatchable": 1}


class _StubFleet:
    def __init__(self, registry):
        self.replicas = []
        self.metrics = registry


class TestBrownoutLadder:
    CFG = BrownoutConfig(poll_interval_s=1.0, queue_depth_high=8.0,
                         queue_depth_low=2.0, hot_polls=2, cool_polls=2,
                         clamp_max_new_tokens=4)

    def _drive(self, depths):
        registry = MetricsRegistry(sinks=[sink := InMemorySink()])
        fleet = _StubFleet(registry)
        ctrl = BrownoutController(self.CFG)
        ctrl._fm = _StubFleetMetrics(fleet, depths)
        rungs = []
        for i in range(len(depths)):
            ctrl.maybe_step(fleet, now=float(i))
            rungs.append(ctrl.rung)
        return ctrl, rungs, registry, sink

    def test_escalation_needs_hot_streak(self):
        ctrl, rungs, _, _ = self._drive([9, 9, 9, 9])
        # hot_polls=2: first hot poll arms, second moves — one rung per
        # streak completion
        assert rungs == [0, 1, 1, 2]
        assert ctrl.rung_name == BROWNOUT_RUNGS[2]

    def test_neutral_zone_resets_streaks(self):
        # hot, neutral, hot, hot: the neutral poll resets the streak so
        # escalation needs two MORE consecutive hot polls
        _, rungs, _, _ = self._drive([9, 5, 9, 9])
        assert rungs == [0, 0, 0, 1]

    def test_recovery_one_rung_with_hysteresis(self):
        ctrl, rungs, _, _ = self._drive(
            [9, 9, 9, 9, 1, 1, 1, 1, 5, 1, 1])
        assert rungs[:4] == [0, 1, 1, 2]
        # cool streaks step down one rung at a time; the neutral poll
        # (depth 5) resets the cool streak too
        assert rungs[4:8] == [2, 1, 1, 0]
        assert rungs[8:] == [0, 0, 0]
        assert ctrl.rung == 0

    def test_poll_interval_enforced(self):
        registry = MetricsRegistry(sinks=[InMemorySink()])
        fleet = _StubFleet(registry)
        ctrl = BrownoutController(self.CFG)
        ctrl._fm = _StubFleetMetrics(fleet, [9, 9, 9])
        ctrl.maybe_step(fleet, now=0.0)
        ctrl.maybe_step(fleet, now=0.5)   # under poll_interval_s: no poll
        assert len(ctrl._fm.depths) == 2
        ctrl.maybe_step(fleet, now=1.0)
        assert ctrl.rung == 1

    def test_admission_floor_per_rung(self):
        ctrl = BrownoutController(self.CFG)
        floors = []
        for rung in range(len(BROWNOUT_RUNGS)):
            ctrl.rung = rung
            floors.append(ctrl.admission_floor())
        assert floors == [None, PRIORITY_STANDARD, PRIORITY_STANDARD,
                          PRIORITY_STANDARD, PRIORITY_INTERACTIVE]

    def test_clamp_batch_only_at_rung3(self):
        ctrl = BrownoutController(self.CFG)
        batch = _req([1, 2], max_new=50, priority=PRIORITY_BATCH,
                     rid=777)
        std = _req([1, 2], max_new=50, priority=PRIORITY_STANDARD)
        ctrl.rung = 2
        assert ctrl.clamp(batch) is batch      # below clamp rung
        ctrl.rung = 3
        clamped = ctrl.clamp(batch)
        assert clamped.max_new_tokens == 4
        # same identity: ids, trace, deadline clock are untouched
        assert clamped.request_id == 777
        assert clamped.trace_id == batch.trace_id
        assert ctrl.clamp(std) is std          # never non-batch
        short = _req([1], max_new=2, priority=PRIORITY_BATCH)
        assert ctrl.clamp(short) is short      # already under the cap

    def test_transitions_emit_record_counter_event_triples(self):
        _, _, registry, sink = self._drive(
            [9, 9, 9, 9, 1, 1, 1, 1, 1, 1])
        counters = registry.counters()
        assert counters["brownouts_escalated"] == 2
        assert counters["brownouts_recovered"] == 2
        recs = [r for r in sink.records if r.get("kind") == "brownout"]
        assert [r["action"] for r in recs] == ["escalate", "escalate",
                                               "recover", "recover"]
        assert [r["rung"] for r in recs] == [1, 2, 1, 0]
        for name, want in (("brownout_escalate", 2),
                           ("brownout_recover", 2)):
            events = [r for r in sink.records
                      if r.get("kind") == "event"
                      and r.get("event") == name]
            assert len(events) == want

    def test_config_validation(self):
        with pytest.raises(ValueError, match="queue_depth_low"):
            BrownoutConfig(queue_depth_high=2.0, queue_depth_low=3.0)
        with pytest.raises(ValueError, match="hot_polls"):
            BrownoutConfig(hot_polls=0)
        with pytest.raises(ValueError, match="max_rung"):
            BrownoutConfig(max_rung=99)

    def test_pure_batch_storm_breathes_instead_of_wedging(self):
        """Pressure counts only ADMISSIBLE queued work: once rung 1
        pauses batch, a pure-batch backlog stops counting, so the
        ladder recovers instead of escalating on its own backpressure
        and starving batch at the top rung forever."""
        from apex_tpu.analysis.mc.harness import MCConfig, FleetHarness

        with use_clock(VirtualClock()):
            h = FleetHarness(MCConfig(replicas=1, preempt=True,
                                      max_queue=32))
            try:
                ctrl = BrownoutController(BrownoutConfig(
                    poll_interval_s=0.01, queue_depth_high=4.0,
                    queue_depth_low=1.0, hot_polls=2, cool_polls=2))
                h.fleet.brownout = ctrl
                for i in range(10):
                    h.fleet.submit(_req([2, 3], max_new=8,
                                        priority=PRIORITY_BATCH,
                                        rid=910000 + i))
                for _ in range(600):
                    h._tick_once()
                    assert ctrl.rung <= 1   # never past pause_batch
                    if ctrl.rung == 0 and len(h.fleet.completed) == 10:
                        break
                # everything completed and the ladder came back down
                assert ctrl.rung == 0
                assert len(h.fleet.completed) == 10
                actions = [t[1] for t in ctrl.transitions]
                assert "escalate" in actions and "recover" in actions
            finally:
                h.cleanup()

    def test_fleet_integration_pauses_and_preempts_batch(self):
        """A real (sim-engine) fleet: batch slots running, then a
        standard-class storm. The ladder pauses batch admissions
        (rung 1), then parks the RUNNING batch slots (rung 2) to hand
        their slots to the admissible storm — and the floor is
        re-asserted on every poll (autoscaled replicas inherit it)."""
        from apex_tpu.analysis.mc.harness import MCConfig, FleetHarness

        with use_clock(VirtualClock()):
            h = FleetHarness(MCConfig(replicas=1, preempt=True,
                                      max_queue=64))
            try:
                ctrl = BrownoutController(BrownoutConfig(
                    poll_interval_s=0.01, queue_depth_high=4.0,
                    queue_depth_low=1.0, hot_polls=2, cool_polls=2))
                h.fleet.brownout = ctrl
                for i in range(2):      # long batch work holds the slots
                    h.fleet.submit(_req([2, 3], max_new=40,
                                        priority=PRIORITY_BATCH,
                                        rid=910000 + i))
                h._tick_once()
                h._tick_once()
                for i in range(16):     # admissible standard storm
                    h.fleet.submit(_req([4, 5], max_new=6,
                                        priority=PRIORITY_STANDARD,
                                        rid=920000 + i))
                for _ in range(30):
                    h._tick_once()
                    if ctrl.rung >= 2:
                        break
                assert ctrl.rung >= 2
                # the floor is asserted on every replica each poll
                # (the fleet may have autoscaled mid-storm)
                floors = [eng.admission_floor for eng in h.engines]
                assert PRIORITY_STANDARD in floors
                assert h.registry.counters().get(
                    "requests_preempted", 0) >= 1
                # drain: pressure falls, the ladder recovers to normal
                # and every request — parked batch included — completes
                for _ in range(800):
                    h._tick_once()
                    if ctrl.rung == 0 and len(h.fleet.completed) == 18:
                        break
                assert ctrl.rung == 0
                assert len(h.fleet.completed) == 18
            finally:
                h.cleanup()


# ---------------------------------------------------------------------------
# monitor reconciliation + pre-PR20 back-compat
# ---------------------------------------------------------------------------

def _report_from(records, tmp_path):
    import json

    path = tmp_path / "run.jsonl"
    with open(path, "w", encoding="utf-8") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return build_report(str(path))


class TestMonitorReconciliation:
    def test_preempt_resume_quota_reconcile_key_for_key(self, tmp_path):
        from apex_tpu.analysis.mc.harness import MCConfig, FleetHarness
        from apex_tpu.analysis.mc.events import Event

        with use_clock(VirtualClock()):
            h = FleetHarness(MCConfig(replicas=1, preempt=True))
            try:
                h.apply(Event("arrive", a=2, b=2))       # standard
                h.apply(Event("preempt", a=0, b=1))
                h.apply(Event("quota_exceeded", a=1, b=0))
                h.settle()
                counters = h.registry.counters()
                assert counters["requests_preempted"] >= 1
                assert counters["requests_shed_quota"] >= 1

                def events(name):
                    return [r for r in h.sink.records
                            if r.get("kind") == "event"
                            and r.get("event") == name]

                for counter, event in (
                        ("requests_preempted", "request_preempted"),
                        ("requests_resumed", "request_resumed")):
                    assert counters.get(counter, 0) == len(events(event))
                quota_sheds = [e for e in events("request_shed")
                               if e.get("reason") == "quota"]
                assert counters["requests_shed_quota"] == len(quota_sheds)

                report = _report_from(list(h.sink.records), tmp_path)
                by_prio = report["requests"]["by_priority"]
                assert sum(by_prio.values()) == report["requests"]["count"]
                text = render_report(report)
                assert "priority:" in text
            finally:
                h.cleanup()

    def test_brownout_section_in_report(self, tmp_path):
        registry = MetricsRegistry(sinks=[sink := InMemorySink()])
        fleet = _StubFleet(registry)
        ctrl = BrownoutController(TestBrownoutLadder.CFG)
        ctrl._fm = _StubFleetMetrics(fleet, [9, 9, 9, 1, 1, 1])
        for i in range(6):
            ctrl.maybe_step(fleet, now=float(i))
        registry.flush()    # the kind="counters" snapshot row
        report = _report_from(list(sink.records), tmp_path)
        section = report["brownout"]
        assert section is not None
        assert section["by_action"] == {"escalate": 1, "recover": 1}
        assert section["counters"]["brownouts_escalated"] == 1
        assert section["final_rung"] == ctrl.rung
        text = render_report(report)
        assert "brownout ladder" in text


class TestPrePr20BackCompat:
    import os
    PRE_PR20 = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "data", "pre_pr20_run.jsonl")

    def test_renders_without_priority_or_brownout_sections(self):
        """A committed pre-priority log (PR-19 vintage: anomaly rows
        present, NO priority fields on request rows, no brownout /
        preempt / quota rows or counters, torn last line) builds and
        renders with no priority split and no brownout ladder — the
        new sections only appear when their rows exist."""
        report = build_report(self.PRE_PR20)
        assert report["requests"]["count"] == 3
        assert report["requests"]["by_priority"] == {}
        assert report["brownout"] is None
        text = render_report(report)
        assert "priority:" not in text
        assert "brownout ladder" not in text
        # the era's own sections are untouched by the new readers
        assert "drift anomalies" in text

    def test_span_conservation_vacuous_clean(self):
        from apex_tpu.observability.report import read_records
        from apex_tpu.observability.trace import check_span_conservation

        records = read_records(self.PRE_PR20)
        assert check_span_conservation(records) == []


# ---------------------------------------------------------------------------
# jax engine: preempt/resume token-exactness (greedy + sampled), and the
# paged+int8+LoRA cross on the slow tier
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def small():
    import jax
    from apex_tpu.models import GPTModel, TransformerConfig

    model = GPTModel(TransformerConfig(
        num_layers=1, hidden_size=32, num_attention_heads=4, vocab_size=64,
        max_position_embeddings=64, hidden_dropout=0.0,
        attention_dropout=0.0))
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _serve_with_preempt(model, params, victim, hi, cfg, adapters=None):
    """Run victim (low class, long budget) until it is mid-decode, then
    submit hi (interactive); tick to completion. Returns
    (results_by_id, registry, sink)."""
    from apex_tpu.serving import EngineSupervisor

    registry = MetricsRegistry(sinks=[sink := InMemorySink()])
    results = {}
    with EngineSupervisor(model, params, cfg, metrics=registry,
                          adapters=adapters) as sup:
        sup.submit(victim)
        for _ in range(200):
            for res in sup.tick():
                results[res.request_id] = res
            if sup.engine.active_count and len(
                    sup.engine._active[
                        next(iter(sup.engine._active))].tokens) >= 2:
                break
        sup.submit(hi)
        for _ in range(400):
            for res in sup.tick():
                results[res.request_id] = res
            if len(results) == 2:
                break
    return results, registry, sink


def _serve_alone(model, params, request, cfg, adapters=None):
    from apex_tpu.serving import EngineSupervisor

    with EngineSupervisor(model, params, cfg,
                          adapters=adapters) as sup:
        (res,) = sup.serve([request])
    return res


class TestEnginePreemptResume:
    def _cfg(self, **kw):
        kw.setdefault("max_slots", 1)
        kw.setdefault("max_len", 48)
        kw.setdefault("page_size", 4)
        kw.setdefault("scheduler",
                      SchedulerConfig(max_queue=8,
                                      max_prefills_per_tick=1))
        return EngineConfig(**kw)

    def test_greedy_resume_token_exact(self, small):
        model, params = small
        cfg = self._cfg()
        victim = _req([5, 9, 3], max_new=10, priority=PRIORITY_BATCH,
                      rid=400001)
        hi = _req([7, 2], max_new=2, priority=PRIORITY_INTERACTIVE)
        results, registry, sink = _serve_with_preempt(
            model, params, victim, hi, cfg)
        counters = registry.counters()
        assert counters["requests_preempted"] == 1
        assert counters["requests_resumed"] == 1
        res = results[victim.request_id]
        assert res.finish_reason == "length"
        assert res.trace_id == victim.trace_id
        assert res.priority == PRIORITY_BATCH
        # token-exact vs the same request served alone, un-preempted
        alone = _serve_alone(model, params,
                             _req([5, 9, 3], max_new=10,
                                  priority=PRIORITY_BATCH), cfg)
        assert list(res.tokens) == list(alone.tokens)
        # exactly one terminal record, preempt/resume marks on the
        # ORIGINAL trace
        terminal = [r for r in sink.records
                    if r.get("kind") == "request"
                    and r.get("request_id") == victim.request_id]
        assert len(terminal) == 1
        marks = [r for r in sink.records if r.get("kind") == "span"
                 and r.get("span") in ("preempt", "resume")]
        assert {m["trace_id"] for m in marks} == {victim.trace_id}

    def test_sampled_resume_token_exact(self, small):
        model, params = small
        cfg = self._cfg()
        mk = lambda: _req([4, 8, 1], max_new=10, priority=PRIORITY_BATCH,
                          temperature=0.9, top_k=8, seed=1234)
        victim = mk()
        hi = _req([7, 2], max_new=2, priority=PRIORITY_INTERACTIVE)
        results, registry, _ = _serve_with_preempt(
            model, params, victim, hi, cfg)
        assert registry.counters()["requests_preempted"] == 1
        res = results[victim.request_id]
        alone = _serve_alone(model, params, mk(), cfg)
        # sampling keys on absolute position: the resumed stream is
        # bitwise the un-preempted one
        assert list(res.tokens) == list(alone.tokens)

    @pytest.mark.slow
    def test_paged_int8_lora_cross_resume_exact(self, small):
        import jax
        from apex_tpu.lora import AdapterStore, random_adapter

        model, params = small
        store = AdapterStore(model.config, 4, max_adapters=2)
        store.load("a", random_adapter(model.config, 4,
                                       jax.random.PRNGKey(3)))
        cfg = self._cfg(kv_layout="paged", kv_dtype="int8")
        mk = lambda: _req([6, 2, 9], max_new=10, priority=PRIORITY_BATCH,
                          adapter="a", temperature=0.8, top_k=8,
                          seed=77)
        victim = mk()
        hi = _req([7, 2], max_new=2, priority=PRIORITY_INTERACTIVE)
        results, registry, _ = _serve_with_preempt(
            model, params, victim, hi, cfg, adapters=store)
        assert registry.counters()["requests_preempted"] == 1
        res = results[victim.request_id]
        assert res.finish_reason == "length"
        alone = _serve_alone(model, params, mk(), cfg, adapters=store)
        assert list(res.tokens) == list(alone.tokens)
