"""SLO-driven autoscaling tests: policy, damping, and churn hygiene.

Three layers:

- **Pure policy** — :meth:`Autoscaler.desired_direction` maps one
  signals dict to up/down/hold with no fleet, engine, or jax in sight;
  the idle-window contract (``goodput_window == 0.0`` with
  ``window_terminal == 0`` never scales up) is pinned here.
- **Damping** — hysteresis streaks, the cooldown window, band clamps,
  and the hold-while-topology-busy rule, driven through scripted
  signal sequences against a stub fleet (at most one decision per
  cooldown window, by construction).
- **Churn hygiene** — a real fleet swept through scale-up/scale-down
  cycles leaks NOTHING: retired replica ids vanish from the router's
  residency table, the per-replica counter/gauge views, and the
  dispatch set, while merged fleet totals still reconcile with the
  parent registry (the retired ledger keeps the work counted).
"""

import jax
import pytest

from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.observability import MetricsRegistry
from apex_tpu.observability.fleet_metrics import FleetMetrics
from apex_tpu.serving import EngineConfig, Request, SchedulerConfig
from apex_tpu.serving.fleet import (
    REPLICA_ACTIVE,
    AutoscaleConfig,
    Autoscaler,
    FleetConfig,
    ReplicaFleet,
)
from apex_tpu.serving.fleet.router import _Replica


@pytest.fixture(scope="module")
def small():
    # 1 layer, same rationale as the fleet suite: scale-ups build fresh
    # engines and the policy/bookkeeping under test is depth-agnostic
    model = GPTModel(TransformerConfig(
        num_layers=1, hidden_size=32, num_attention_heads=4, vocab_size=64,
        max_position_embeddings=64, hidden_dropout=0.0,
        attention_dropout=0.0))
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _signals(**overrides):
    base = {
        "replicas_total": 1, "replicas_dispatchable": 1,
        "inflight": 0, "queue_depth": 0, "queued_tokens": 0,
        "goodput_window": 0.0, "window_ok": 0, "window_terminal": 0,
        "window_s": 0.25, "ttft_p99_s": None, "tpot_p99_s": None,
        "slot_occupancy": 0.0,
    }
    base.update(overrides)
    return base


# ---------------------------------------------------------------------------
# config validation


class TestAutoscaleConfig:
    def test_band_must_be_ordered(self):
        with pytest.raises(ValueError, match="max_replicas"):
            AutoscaleConfig(min_replicas=3, max_replicas=2)

    def test_min_replicas_positive(self):
        with pytest.raises(ValueError, match="min_replicas"):
            AutoscaleConfig(min_replicas=0)

    def test_queue_bands_must_not_overlap(self):
        # scale-down-at >= scale-up-at would flap forever
        with pytest.raises(ValueError, match="flap"):
            AutoscaleConfig(scale_up_queue_per_replica=2.0,
                            scale_down_queue_per_replica=2.0)

    def test_goodput_threshold_is_a_fraction(self):
        with pytest.raises(ValueError, match="scale_up_goodput"):
            AutoscaleConfig(scale_up_goodput=1.5)


# ---------------------------------------------------------------------------
# the pure policy


class TestDesiredDirection:
    def test_queue_pressure_scales_up(self):
        scaler = Autoscaler(AutoscaleConfig(scale_up_queue_per_replica=4.0))
        direction, reason = scaler.desired_direction(
            _signals(queue_depth=9, replicas_dispatchable=2,
                     slot_occupancy=1.0))
        assert (direction, reason) == ("up", "queue_depth")

    def test_queue_is_normalized_per_dispatchable_replica(self):
        scaler = Autoscaler(AutoscaleConfig(scale_up_queue_per_replica=4.0))
        # 6 queued over 2 dispatchable = 3 per replica: under the bar
        direction, _ = scaler.desired_direction(
            _signals(queue_depth=6, replicas_dispatchable=2,
                     slot_occupancy=1.0))
        assert direction is None

    def test_token_weighted_backlog_scales_up(self):
        # long-prompt backlog trips the token trigger before raw depth
        scaler = Autoscaler(AutoscaleConfig(
            scale_up_queue_per_replica=100.0,
            scale_up_queued_tokens_per_replica=64.0))
        direction, reason = scaler.desired_direction(
            _signals(queue_depth=3, queued_tokens=200, slot_occupancy=1.0))
        assert (direction, reason) == ("up", "queued_tokens")

    def test_degraded_goodput_scales_up_only_with_traffic(self):
        scaler = Autoscaler(AutoscaleConfig(scale_up_goodput=0.9))
        bad = _signals(goodput_window=0.5, window_terminal=4,
                       slot_occupancy=1.0)
        assert scaler.desired_direction(bad) == ("up", "goodput")

    def test_idle_window_zero_goodput_never_scales_up(self):
        # the FleetMetrics contract: an idle window reports 0.0 (never
        # None/NaN) with window_terminal == 0 — that is "no evidence",
        # not "every request failed"
        scaler = Autoscaler(AutoscaleConfig(scale_up_goodput=0.9,
                                            scale_down_slot_occupancy=0.0))
        idle = _signals(goodput_window=0.0, window_terminal=0,
                        queue_depth=1, slot_occupancy=0.5)
        direction, _ = scaler.desired_direction(idle)
        assert direction is None

    def test_ttft_breach_scales_up(self):
        scaler = Autoscaler(AutoscaleConfig(scale_up_ttft_p99_s=1.0))
        direction, reason = scaler.desired_direction(
            _signals(ttft_p99_s=2.5, slot_occupancy=1.0))
        assert (direction, reason) == ("up", "ttft_p99")

    def test_scale_down_needs_quiet_on_every_axis(self):
        scaler = Autoscaler(AutoscaleConfig(
            scale_down_queue_per_replica=0.5,
            scale_down_slot_occupancy=0.25))
        assert scaler.desired_direction(_signals()) == ("down", "idle")
        # quiet queue but busy slots: hold
        busy_slots = _signals(slot_occupancy=0.8)
        assert scaler.desired_direction(busy_slots)[0] is None
        # unmeasurable occupancy counts as quiet
        no_slots = _signals(slot_occupancy=None)
        assert scaler.desired_direction(no_slots) == ("down", "idle")

    def test_mid_band_load_holds(self):
        scaler = Autoscaler(AutoscaleConfig(
            scale_up_queue_per_replica=4.0,
            scale_down_queue_per_replica=0.5))
        direction, _ = scaler.desired_direction(
            _signals(queue_depth=2, slot_occupancy=0.6))
        assert direction is None


# ---------------------------------------------------------------------------
# damping: hysteresis, cooldown, bounds, topology holds


class _ScriptedMetrics:
    """Stands in for the autoscaler's private FleetMetrics view: each
    poll pops the next scripted signals dict (the last one repeats)."""

    def __init__(self, fleet, script):
        self.fleet = fleet
        self._script = list(script)

    def signals(self):
        if len(self._script) > 1:
            return self._script.pop(0)
        return self._script[0]


class _PolicyFleet:
    """The minimal fleet surface maybe_scale touches."""

    def __init__(self, n=1):
        self.metrics = MetricsRegistry()
        self.replicas = [self._active(i, 0) for i in range(n)]
        self.topology_busy = None
        self.deployment = None
        self.added = 0
        self.retired = []

    @staticmethod
    def _active(rid, depth):
        r = _Replica.__new__(_Replica)
        r.replica_id, r.state = rid, REPLICA_ACTIVE
        r.supervisor = type("S", (), {
            "queued_count": depth, "active_count": 0,
            "service_estimate_s": 0.01})()
        return r

    @property
    def n_replicas(self):
        return len(self.replicas)

    def add_replica(self):
        rid = max((r.replica_id for r in self.replicas), default=-1) + 1
        self.replicas.append(self._active(rid, 0))
        self.added += 1
        return rid

    def retire_replica(self, rid):
        self.replicas = [r for r in self.replicas if r.replica_id != rid]
        self.retired.append(rid)


def _scripted(fleet, config, script):
    scaler = Autoscaler(config)
    scaler._fm = _ScriptedMetrics(fleet, script)
    return scaler


class TestMaybeScale:
    CFG = AutoscaleConfig(min_replicas=1, max_replicas=3,
                          poll_interval_s=0.1, cooldown_s=1.0,
                          hysteresis_polls=2,
                          scale_up_queue_per_replica=2.0)

    def test_hysteresis_requires_consecutive_polls(self):
        fleet = _PolicyFleet(n=1)
        hot = _signals(queue_depth=9, slot_occupancy=1.0)
        scaler = _scripted(fleet, self.CFG, [hot])
        assert scaler.maybe_scale(fleet, now=0.0) is None     # streak 1
        assert scaler.maybe_scale(fleet, now=0.2) == "up"     # streak 2
        assert fleet.added == 1

    def test_direction_flip_resets_the_streak(self):
        fleet = _PolicyFleet(n=2)
        hot = _signals(queue_depth=9, slot_occupancy=1.0)
        idle = _signals()
        scaler = _scripted(fleet, self.CFG, [hot, idle, hot, hot])
        assert scaler.maybe_scale(fleet, now=0.0) is None     # up x1
        assert scaler.maybe_scale(fleet, now=0.2) is None     # down x1
        assert scaler.maybe_scale(fleet, now=0.4) is None     # up x1 again
        assert scaler.maybe_scale(fleet, now=0.6) == "up"

    def test_poll_interval_gates_reads(self):
        fleet = _PolicyFleet(n=1)
        hot = _signals(queue_depth=9, slot_occupancy=1.0)
        scaler = _scripted(fleet, self.CFG, [hot])
        assert scaler.maybe_scale(fleet, now=0.0) is None
        # inside the poll interval: not even a signals read, no streak
        assert scaler.maybe_scale(fleet, now=0.05) is None
        assert scaler._streak == 1

    def test_cooldown_allows_one_decision_per_window(self):
        fleet = _PolicyFleet(n=1)
        hot = _signals(queue_depth=50, slot_occupancy=1.0)
        scaler = _scripted(fleet, self.CFG, [hot])
        times = [0.0, 0.15, 0.3, 0.45, 0.6, 0.75, 0.9, 1.05, 1.2, 1.35]
        applied = [t for t in times if scaler.maybe_scale(fleet, now=t)]
        # decisions at least cooldown_s (1.0) apart: 2 in 1.35s, max
        assert len(applied) == 2
        assert applied[1] - applied[0] >= self.CFG.cooldown_s

    def test_bounds_clamp_before_streak_accounting(self):
        fleet = _PolicyFleet(n=3)           # already at max_replicas
        hot = _signals(queue_depth=50, slot_occupancy=1.0)
        scaler = _scripted(fleet, self.CFG, [hot])
        for k in range(5):
            assert scaler.maybe_scale(fleet, now=0.2 * k) is None
        assert fleet.added == 0
        assert scaler._streak == 0          # forbidden direction != held

    def test_min_replicas_blocks_scale_down(self):
        fleet = _PolicyFleet(n=1)
        scaler = _scripted(fleet, self.CFG, [_signals()])
        for k in range(5):
            assert scaler.maybe_scale(fleet, now=0.2 * k) is None
        assert fleet.retired == []

    def test_holds_while_topology_busy_without_resetting_streak(self):
        fleet = _PolicyFleet(n=1)
        hot = _signals(queue_depth=9, slot_occupancy=1.0)
        scaler = _scripted(fleet, self.CFG, [hot])
        scaler.maybe_scale(fleet, now=0.0)
        fleet.topology_busy = 0             # a drain/probe in flight
        assert scaler.maybe_scale(fleet, now=0.2) is None
        assert scaler._streak >= 2          # evidence kept, not reset
        fleet.topology_busy = None
        assert scaler.maybe_scale(fleet, now=0.4) == "up"

    def test_holds_while_deployment_rolls(self):
        fleet = _PolicyFleet(n=1)
        fleet.deployment = type("D", (), {"done": False})()
        hot = _signals(queue_depth=9, slot_occupancy=1.0)
        scaler = _scripted(fleet, self.CFG, [hot])
        scaler.maybe_scale(fleet, now=0.0)
        assert scaler.maybe_scale(fleet, now=0.2) is None
        fleet.deployment.done = True
        assert scaler.maybe_scale(fleet, now=0.4) == "up"

    def test_retire_target_is_least_loaded_then_youngest(self):
        fleet = _PolicyFleet(n=3)
        fleet.replicas[0].supervisor.queued_count = 3
        fleet.replicas[1].supervisor.queued_count = 0
        fleet.replicas[2].supervisor.queued_count = 0
        # replicas 1 and 2 tie on depth: the YOUNGEST id unwinds first
        assert Autoscaler._retire_target(fleet) == 2

    def test_retire_target_never_empties_the_fleet(self):
        fleet = _PolicyFleet(n=1)
        assert Autoscaler._retire_target(fleet) is None

    def test_applied_decisions_are_recorded_in_order(self):
        fleet = _PolicyFleet(n=1)
        hot = _signals(queue_depth=9, slot_occupancy=1.0)
        scaler = _scripted(fleet, self.CFG, [hot])
        scaler.maybe_scale(fleet, now=0.0)
        scaler.maybe_scale(fleet, now=0.2)
        assert scaler.decisions == [(0.2, "up", 1, "queue_depth")]


# ---------------------------------------------------------------------------
# churn hygiene against a real fleet


class TestChurnHygiene:
    def _fleet(self, model, params, n=2):
        return ReplicaFleet(
            model, params,
            EngineConfig(max_slots=2, max_len=32,
                         scheduler=SchedulerConfig(max_queue=16)),
            fleet=FleetConfig(n_replicas=n, probe_on_rebuild=False))

    def test_scale_up_down_sweep_leaks_nothing(self, small):
        model, params = small
        fleet = self._fleet(model, params, n=2)
        fm = FleetMetrics(fleet)
        try:
            retired_ids = []
            for _ in range(3):
                rid = fleet.add_replica()
                # seed residency so invalidate() has something to clear
                fleet.router.note_dispatch(rid, (1, 2, 3))
                assert rid in fleet.router._resident
                fleet.retire_replica(rid)
                retired_ids.append(rid)
            live = {r.replica_id for r in fleet.replicas}
            assert live == {0, 1}
            for rid in retired_ids:
                # ids are never reused and never linger anywhere live
                assert rid not in live
                assert rid not in fleet.router._resident
                assert rid not in fleet.replica_metrics
                assert rid in fleet.retired_replica_metrics
                assert rid not in fm.replica_counters()
                assert not any(f'replica="{rid}"' in k
                               for k in fm.labeled_gauges())
            assert fleet._next_replica_id == 2 + len(retired_ids)
            signals = fm.signals()
            assert signals["replicas_total"] == 2
            assert signals["replicas_dispatchable"] == 2
        finally:
            fleet.close()

    def test_retired_work_stays_counted(self, small):
        """Scale a replica up, serve THROUGH it, scale it down: merged
        counters still reconcile with the parent for every
        replica-incremented key — the retired ledger keeps the work."""
        model, params = small
        fleet = self._fleet(model, params, n=1)
        fm = FleetMetrics(fleet)
        try:
            rid = fleet.add_replica()
            for req_id, prompt in enumerate([[1, 2, 3], [4, 5, 6]]):
                fleet.submit(Request(request_id=req_id, prompt=prompt,
                                     max_new_tokens=2))
            while fleet.inflight_count:
                fleet.tick()
            served_by_new = fleet.metrics.counters().get(
                f"replica{rid}_dispatches", 0)
            fleet.retire_replica(rid)
            while any(r.replica_id == rid for r in fleet.replicas):
                fleet.tick()
            merged = fm.merged_counters()
            parent = fleet.metrics.counters()
            for key in ("requests_submitted", "prefills", "decode_steps"):
                if key in merged:
                    assert merged[key] == parent.get(key, 0), key
            assert rid in fleet.retired_replica_metrics
            if served_by_new:
                assert fleet.retired_replica_metrics[rid].counters().get(
                    "requests_submitted", 0) > 0
        finally:
            fleet.close()

    def test_one_topology_change_at_a_time(self, small):
        model, params = small
        fleet = ReplicaFleet(
            model, params,
            EngineConfig(max_slots=2, max_len=32,
                         scheduler=SchedulerConfig(max_queue=16)),
            fleet=FleetConfig(n_replicas=2, probe_on_rebuild=True))
        try:
            rid = fleet.add_replica()       # probing: topology busy
            assert fleet.topology_busy == rid
            with pytest.raises(RuntimeError, match="one topology"):
                fleet.add_replica()
            with pytest.raises(RuntimeError, match="one topology"):
                fleet.retire_replica(0)
            while fleet.topology_busy is not None:
                fleet.tick()
            fleet.retire_replica(rid)
        finally:
            fleet.close()

    def test_last_active_replica_cannot_retire(self, small):
        model, params = small
        fleet = self._fleet(model, params, n=1)
        try:
            with pytest.raises(RuntimeError, match="last active"):
                fleet.retire_replica(0)
        finally:
            fleet.close()


# ---------------------------------------------------------------------------
# end-to-end (compile-heavy: slow lane; the committed traffic_ramp
# scenario gates the same loop in CI via the loadtest harness)


@pytest.mark.slow
class TestAutoscaleEndToEnd:
    def test_burst_scales_up_then_idle_scales_down(self, small):
        model, params = small
        fleet = ReplicaFleet(
            model, params,
            EngineConfig(max_slots=2, max_len=32,
                         scheduler=SchedulerConfig(max_queue=32)),
            fleet=FleetConfig(n_replicas=1),
            autoscale=AutoscaleConfig(
                min_replicas=1, max_replicas=2, poll_interval_s=0.01,
                cooldown_s=0.05, hysteresis_polls=2,
                scale_up_queue_per_replica=2.0))
        try:
            for i in range(12):
                fleet.submit(Request(request_id=i, prompt=[1 + i % 8, 2],
                                     max_new_tokens=3))
            while fleet.inflight_count:
                fleet.tick()
            assert len(fleet.completed) == 12
            actions = [a for _, a, _, _ in fleet.autoscaler.decisions]
            assert "up" in actions
            # idle polls after the burst retire the extra replica
            import time as _time
            deadline = _time.monotonic() + 30.0
            while (len(fleet.replicas) > 1
                   and _time.monotonic() < deadline):
                fleet.tick()
                _time.sleep(0.005)
            assert len(fleet.replicas) == 1
            assert "down" in [a for _, a, _, _
                              in fleet.autoscaler.decisions]
            # every decision reconciles: counters == events == records
            counters = fleet.metrics.counters()
            ups = sum(1 for _, a, _, _ in fleet.autoscaler.decisions
                      if a == "up")
            downs = sum(1 for _, a, _, _ in fleet.autoscaler.decisions
                        if a == "down")
            assert counters.get("replica_scale_ups", 0) == ups
            assert counters.get("replica_scale_downs", 0) == downs
        finally:
            fleet.close()
