"""MLP / FusedDense / RNN parity suite.

Mirrors the reference's ``tests/L0/run_mlp/`` (MLP vs an ``nn.Sequential``
of Linears) and the torch-cell semantics of ``apex/RNN``: weights are copied
into torch modules and outputs/grads must agree.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

os.environ.setdefault("APEX_TPU_FORCE_PALLAS", "interpret")

from apex_tpu.fused_dense import FusedDense, FusedDenseGeluDense  # noqa: E402
from apex_tpu.mlp import MLP  # noqa: E402
from apex_tpu.rnn import GRU, LSTM, ReLU, Tanh, mLSTM  # noqa: E402


def _t(x):
    return torch.tensor(np.asarray(x))


class TestMLP:
    @pytest.mark.parametrize("activation", ["none", "relu", "sigmoid"])
    def test_matches_torch_sequential(self, activation):
        sizes = [13, 27, 11]
        mlp = MLP(sizes, bias=True, activation=activation)
        params = mlp.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (5, 13))

        layers = []
        for i in range(2):
            lin = torch.nn.Linear(sizes[i], sizes[i + 1])
            with torch.no_grad():
                lin.weight.copy_(_t(params[f"weight_{i}"]))
                lin.bias.copy_(_t(params[f"bias_{i}"]))
            layers.append(lin)
            if activation == "relu":
                layers.append(torch.nn.ReLU())
            elif activation == "sigmoid":
                layers.append(torch.nn.Sigmoid())
        ref = torch.nn.Sequential(*layers)

        out = mlp.apply(params, x)
        ref_out = ref(_t(x)).detach().numpy()
        np.testing.assert_allclose(np.asarray(out), ref_out, rtol=1e-5,
                                   atol=1e-6)

    def test_no_bias_and_bad_activation(self):
        mlp = MLP([4, 4], bias=False)
        params = mlp.init(jax.random.PRNGKey(0))
        assert "bias_0" not in params
        with pytest.raises(TypeError):
            MLP([4, 4], activation="gelu")

    def test_grads_flow(self):
        mlp = MLP([8, 16, 4])
        params = mlp.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (3, 8))
        g = jax.grad(lambda p: jnp.sum(mlp.apply(p, x) ** 2))(params)
        assert all(bool(jnp.any(v != 0)) for v in jax.tree.leaves(g))


class TestFusedDense:
    def test_matches_torch_linear(self):
        fd = FusedDense(9, 17)
        params = fd.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 9))
        lin = torch.nn.Linear(9, 17)
        with torch.no_grad():
            lin.weight.copy_(_t(params["weight"]))
            lin.bias.copy_(_t(params["bias"]))
        np.testing.assert_allclose(
            np.asarray(fd.apply(params, x)),
            lin(_t(x)).detach().numpy(), rtol=1e-5, atol=1e-6)

    def test_gelu_dense_matches_torch(self):
        fdg = FusedDenseGeluDense(8, 32, 6)
        params = fdg.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 8))
        l1, l2 = torch.nn.Linear(8, 32), torch.nn.Linear(32, 6)
        with torch.no_grad():
            l1.weight.copy_(_t(params["weight1"]))
            l1.bias.copy_(_t(params["bias1"]))
            l2.weight.copy_(_t(params["weight2"]))
            l2.bias.copy_(_t(params["bias2"]))
        ref = l2(torch.nn.functional.gelu(l1(_t(x)), approximate="tanh"))
        np.testing.assert_allclose(
            np.asarray(fdg.apply(params, x)),
            ref.detach().numpy(), rtol=1e-4, atol=1e-5)

    def test_no_bias_gelu_raises(self):
        with pytest.raises(AssertionError):
            FusedDenseGeluDense(4, 8, 4, bias=False)


def _copy_rnn_weights_to_torch(trnn, params, bidirectional=False):
    with torch.no_grad():
        for layer, p in enumerate(params):
            dirs = p if bidirectional else [p]
            for d, pd in enumerate(dirs):
                sfx = f"_l{layer}" + ("_reverse" if d == 1 else "")
                getattr(trnn, f"weight_ih{sfx}").copy_(_t(pd["w_ih"]))
                getattr(trnn, f"weight_hh{sfx}").copy_(_t(pd["w_hh"]))
                getattr(trnn, f"bias_ih{sfx}").copy_(_t(pd["b_ih"]))
                getattr(trnn, f"bias_hh{sfx}").copy_(_t(pd["b_hh"]))


class TestRNN:
    @pytest.mark.parametrize("bidirectional", [False, True])
    def test_lstm_matches_torch(self, bidirectional):
        T, B, I, H, L = 6, 3, 5, 7, 2
        model = LSTM(I, H, L, bias=True, bidirectional=bidirectional)
        params = model.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (T, B, I))

        trnn = torch.nn.LSTM(I, H, L, bidirectional=bidirectional)
        _copy_rnn_weights_to_torch(trnn, params, bidirectional)
        ref_out, _ = trnn(_t(x))

        out, finals = model.apply(params, x)
        # atol 1e-4: TPU transcendental units (tanh/sigmoid) differ from
        # torch CPU at ~3e-5 over recurrent accumulation
        np.testing.assert_allclose(np.asarray(out),
                                   ref_out.detach().numpy(),
                                   rtol=1e-4, atol=1e-4)
        assert len(finals) == L

    def test_gru_matches_torch(self):
        T, B, I, H = 5, 2, 4, 6
        model = GRU(I, H, 1, bias=True)
        params = model.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (T, B, I))
        trnn = torch.nn.GRU(I, H, 1)
        _copy_rnn_weights_to_torch(trnn, params)
        ref_out, _ = trnn(_t(x))
        out, _ = model.apply(params, x)
        # atol 1e-4: TPU transcendental units (tanh/sigmoid) differ from
        # torch CPU at ~3e-5 over recurrent accumulation
        np.testing.assert_allclose(np.asarray(out),
                                   ref_out.detach().numpy(),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("factory,mode", [(ReLU, "relu"), (Tanh, "tanh")])
    def test_elman_matches_torch(self, factory, mode):
        T, B, I, H = 4, 2, 3, 5
        model = factory(I, H, 1, bias=True)
        params = model.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (T, B, I))
        trnn = torch.nn.RNN(I, H, 1, nonlinearity=mode)
        _copy_rnn_weights_to_torch(trnn, params)
        ref_out, _ = trnn(_t(x))
        out, _ = model.apply(params, x)
        # atol 1e-4: TPU transcendental units (tanh/sigmoid) differ from
        # torch CPU at ~3e-5 over recurrent accumulation
        np.testing.assert_allclose(np.asarray(out),
                                   ref_out.detach().numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_mlstm_shapes_and_grads(self):
        T, B, I, H = 4, 2, 3, 5
        model = mLSTM(I, H, 1, bias=True)
        params = model.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (T, B, I))
        out, finals = model.apply(params, x)
        assert out.shape == (T, B, H)
        g = jax.grad(lambda p: jnp.sum(model.apply(p, x)[0] ** 2))(params)
        assert all(np.isfinite(np.asarray(v)).all()
                   for v in jax.tree.leaves(g))
        assert any(bool(jnp.any(v != 0)) for v in jax.tree.leaves(g))

    def test_output_projection(self):
        T, B, I, H, O = 4, 2, 3, 8, 5
        model = LSTM(I, H, 1, output_size=O)
        params = model.init(jax.random.PRNGKey(0))
        assert params[0]["w_ho"].shape == (O, H)
        x = jax.random.normal(jax.random.PRNGKey(1), (T, B, I))
        out, finals = model.apply(params, x)
        assert out.shape == (T, B, O)
        # recurrent state: h is output-sized, c is hidden-sized
        h, c = finals[0]
        assert h.shape == (B, O) and c.shape == (B, H)

    def test_batch_first(self):
        model = Tanh(3, 4, 1, batch_first=True)
        params = model.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 3))  # [B, T, I]
        out, _ = model.apply(params, x)
        assert out.shape == (2, 6, 4)
