"""Serving-engine tests: scheduler invariants + continuous-batching
correctness.

Correctness anchor: for any request set, greedy engine output must be
TOKEN-EXACT against per-request ``generate()`` calls — continuous
batching is a scheduling optimization, never an approximation. The
structural invariants ride along: no slot leaks, FCFS admission order,
prefill compile count bounded by the bucket set, and a decode step that
NEVER retraces as requests come and go (asserted through the engine's
``RetraceWatchdog``).
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.models.generation import generate
from apex_tpu.observability import (
    InMemorySink,
    JsonlSink,
    MetricsRegistry,
    build_report,
    render_report,
)
from apex_tpu.serving import (
    EngineConfig,
    FCFSScheduler,
    InferenceEngine,
    QueueFullError,
    Request,
    SamplingParams,
    SchedulerConfig,
    SlotError,
    SlotPool,
    bucket_for,
    prefill_buckets,
)


@pytest.fixture(scope="module")
def small():
    model = GPTModel(TransformerConfig(
        num_layers=2, hidden_size=32, num_attention_heads=4, vocab_size=64,
        max_position_embeddings=64, hidden_dropout=0.0,
        attention_dropout=0.0))
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _prompts(lens, seed=7):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 64, size=n).tolist() for n in lens]


def _expected_greedy(model, params, request, max_len):
    """Per-request generate() reference, truncated at the first EOS —
    exactly what the engine's result.tokens promises."""
    out = generate(model, params, jnp.asarray([request.prompt], jnp.int32),
                   request.max_new_tokens, max_len=max_len,
                   eos_token=request.eos_token)
    toks = np.asarray(out[0, request.prompt_len:]).tolist()
    if request.eos_token is not None and request.eos_token in toks:
        toks = toks[:toks.index(request.eos_token) + 1]
    return toks


class TestRequestValidation:
    def test_empty_prompt_rejected(self):
        with pytest.raises(ValueError, match="non-empty"):
            Request(prompt=[], max_new_tokens=1)

    def test_max_new_tokens_zero_rejected(self):
        with pytest.raises(ValueError, match="max_new_tokens"):
            Request(prompt=[1], max_new_tokens=0)

    def test_top_k_zero_rejected(self):
        with pytest.raises(ValueError, match="top_k"):
            SamplingParams(top_k=0)

    def test_negative_temperature_rejected(self):
        with pytest.raises(ValueError, match="temperature"):
            SamplingParams(temperature=-0.1)

    def test_nonpositive_deadline_rejected(self):
        with pytest.raises(ValueError, match="deadline"):
            Request(prompt=[1], max_new_tokens=1, deadline_s=0.0)


class TestBuckets:
    def test_powers_of_two_plus_max(self):
        assert prefill_buckets(16) == (1, 2, 4, 8, 16)
        assert prefill_buckets(12) == (1, 2, 4, 8, 12)

    def test_bucket_for_picks_smallest_fit(self):
        assert bucket_for(1, 16) == 1
        assert bucket_for(3, 16) == 4
        assert bucket_for(9, 12) == 12
        with pytest.raises(ValueError):
            bucket_for(17, 16)


class TestSlotPool:
    def test_lowest_first_and_no_leak(self):
        pool = SlotPool(3)
        assert [pool.allocate() for _ in range(3)] == [0, 1, 2]
        assert pool.allocate() is None
        pool.release(1)
        assert pool.allocate() == 1
        pool.check()

    def test_double_release_raises(self):
        pool = SlotPool(2)
        s = pool.allocate()
        pool.release(s)
        with pytest.raises(SlotError):
            pool.release(s)


class TestScheduler:
    def test_fcfs_order_and_bounded_queue(self):
        sched = FCFSScheduler(SchedulerConfig(max_queue=3))
        reqs = [Request(prompt=[1], max_new_tokens=1) for _ in range(3)]
        for r in reqs:
            sched.submit(r, now=0.0)
        with pytest.raises(QueueFullError):
            sched.submit(Request(prompt=[1], max_new_tokens=1), now=0.0)
        got = sched.pop_admissible(free_slots=8, decoding=False)
        assert [r.request_id for r, _ in got] == \
            [r.request_id for r in reqs]

    def test_decode_starvation_cap(self):
        sched = FCFSScheduler(SchedulerConfig(max_prefills_per_tick=2))
        for _ in range(5):
            sched.submit(Request(prompt=[1], max_new_tokens=1), now=0.0)
        assert len(sched.pop_admissible(5, decoding=True)) == 2
        assert len(sched.pop_admissible(5, decoding=False)) == 3

    def test_admission_hook_defers_head_blocks_line(self):
        allow = {"ok": False}
        sched = FCFSScheduler(SchedulerConfig(
            admission_hook=lambda r: allow["ok"]))
        sched.submit(Request(prompt=[1], max_new_tokens=1), now=0.0)
        sched.submit(Request(prompt=[1], max_new_tokens=1), now=0.0)
        assert sched.pop_admissible(4, decoding=False) == []
        allow["ok"] = True
        assert len(sched.pop_admissible(4, decoding=False)) == 2

    def test_expire_pops_overdue_only(self):
        sched = FCFSScheduler()
        keep = Request(prompt=[1], max_new_tokens=1)
        drop = Request(prompt=[1], max_new_tokens=1, deadline_s=0.5)
        sched.submit(keep, now=0.0)
        sched.submit(drop, now=0.0)
        expired = sched.expire(now=1.0)
        assert [r.request_id for r, _ in expired] == [drop.request_id]
        assert sched.depth == 1

    def test_cancel_removes_queued(self):
        sched = FCFSScheduler()
        r = Request(prompt=[1], max_new_tokens=1)
        sched.submit(r, now=0.0)
        assert sched.cancel(r.request_id) is not None
        assert sched.cancel(r.request_id) is None
        assert sched.depth == 0


class TestEngine:
    @pytest.mark.slow
    def test_matches_per_request_generate(self, small):
        """More requests than slots: arrivals and retirements happen
        mid-flight, output must still be token-exact vs generate()."""
        model, params = small
        reqs = [Request(prompt=p, max_new_tokens=n)
                for p, n in zip(_prompts([3, 5, 8, 4, 6, 2]),
                                [6, 4, 5, 7, 3, 8])]
        eng = InferenceEngine(model, params,
                              EngineConfig(max_slots=2, max_len=16))
        results = eng.serve(reqs)
        assert [r.request_id for r in results] == \
            [r.request_id for r in reqs]
        for req, res in zip(reqs, results):
            assert res.finish_reason == "length"
            assert res.tokens == _expected_greedy(model, params, req, 16)
        # FCFS admission, no slot leaks, bounded compile count, and the
        # one-compile decode invariant straight from the watchdog
        assert eng.admission_log == [r.request_id for r in reqs]
        eng.slots.check()
        assert eng.slots.free_count == eng.config.max_slots
        assert eng.decode_retraces == 0
        used = {bucket_for(r.prompt_len, 16) for r in reqs}
        assert eng.prefill_compiles <= len(used)

    def test_eos_retires_slot_and_matches(self, small):
        model, params = small
        (prompt,) = _prompts([4], seed=3)
        probe = generate(model, params, jnp.asarray([prompt], jnp.int32),
                         8, max_len=16)
        eos = int(probe[0, 5])   # second generated token (greedy repeats,
        #                          so it may equal the first — both fine)
        req = Request(prompt=prompt, max_new_tokens=8, eos_token=eos)
        eng = InferenceEngine(model, params,
                              EngineConfig(max_slots=2, max_len=16))
        (res,) = eng.serve([req])
        assert res.finish_reason == "eos"
        assert res.tokens == _expected_greedy(model, params, req, 16)
        assert res.tokens[-1] == eos
        assert eng.slots.free_count == eng.config.max_slots

    @pytest.mark.slow  # sampling-independence property sweep: slow tier (ROADMAP)

    def test_sampled_stream_independent_of_cotenants(self, small):
        """A sampled request's tokens depend only on (seed, prompt,
        positions) — never on what shares the batch: alone vs co-batched
        with other traffic must draw the identical stream."""
        model, params = small
        (p0, p1, p2) = _prompts([4, 3, 5], seed=11)
        sampled = dict(prompt=p0, max_new_tokens=6,
                       sampling=SamplingParams(temperature=1.0, top_k=5,
                                               seed=123))
        eng1 = InferenceEngine(model, params,
                               EngineConfig(max_slots=3, max_len=16))
        (alone,) = eng1.serve([Request(**sampled)])
        eng2 = InferenceEngine(model, params,
                               EngineConfig(max_slots=3, max_len=16))
        mixed = eng2.serve([Request(prompt=p1, max_new_tokens=7),
                            Request(**sampled),
                            Request(prompt=p2, max_new_tokens=5)])
        assert mixed[1].tokens == alone.tokens
        assert eng2.decode_retraces == 0

    def test_queue_full_rejection(self, small):
        model, params = small
        sink = InMemorySink()
        reg = MetricsRegistry([sink])
        eng = InferenceEngine(
            model, params,
            EngineConfig(max_slots=1, max_len=16,
                         scheduler=SchedulerConfig(max_queue=2)),
            metrics=reg)
        p = _prompts([2, 2, 2], seed=5)
        eng.submit(Request(prompt=p[0], max_new_tokens=2))
        eng.submit(Request(prompt=p[1], max_new_tokens=2))
        rejected = Request(prompt=p[2], max_new_tokens=2)
        with pytest.raises(QueueFullError):
            eng.submit(rejected)
        assert reg.counters()["requests_rejected"] == 1
        res = eng.completed[rejected.request_id]
        assert res.finish_reason == "rejected" and res.tokens == []
        assert any(r.get("event") == "request_rejected"
                   for r in sink.of_kind("event"))
        # the engine still drains the admitted work
        while eng.active_count or eng.queued_count:
            eng.tick()
        eng.slots.check()

    def test_cancel_mid_flight_keeps_partial_tokens(self, small):
        model, params = small
        reqs = [Request(prompt=p, max_new_tokens=12)
                for p in _prompts([3, 4], seed=9)]
        eng = InferenceEngine(model, params,
                              EngineConfig(max_slots=2, max_len=16))

        def chaos(engine, tick):
            if tick == 2:
                assert engine.cancel(reqs[0].request_id)

        results = eng.serve(reqs, on_tick=chaos)
        cancelled, survivor = results
        assert cancelled.finish_reason == "cancelled"
        assert 0 < cancelled.new_tokens < 12
        expected = _expected_greedy(model, params, reqs[0], 16)
        assert cancelled.tokens == expected[:cancelled.new_tokens]
        assert survivor.finish_reason == "length"
        assert survivor.tokens == _expected_greedy(model, params,
                                                   reqs[1], 16)
        eng.slots.check()
        assert eng.slots.free_count == 2

    def test_deadline_timeouts_queued_and_active(self, small):
        model, params = small
        p = _prompts([3, 3], seed=13)
        eng = InferenceEngine(model, params,
                              EngineConfig(max_slots=1, max_len=16))
        # slow holds the only slot; starved times out while QUEUED
        slow = Request(prompt=p[0], max_new_tokens=12)
        starved = Request(prompt=p[1], max_new_tokens=2, deadline_s=1e-4)
        eng.submit(slow)
        eng.submit(starved)
        eng.tick()                       # admits slow (prefill compiles)
        eng.tick()                       # starved is now overdue
        res = eng.completed[starved.request_id]
        assert res.finish_reason == "timeout" and res.tokens == []
        # ACTIVE timeout: retired mid-decode with its partial tokens
        eng2 = InferenceEngine(model, params,
                               EngineConfig(max_slots=1, max_len=16))
        active = Request(prompt=p[0], max_new_tokens=12, deadline_s=0.05)

        def stall(engine, tick):
            time.sleep(0.06)

        (res2,) = eng2.serve([active], on_tick=stall)
        assert res2.finish_reason == "timeout"
        assert res2.new_tokens >= 1
        assert eng2.slots.free_count == 1

    def test_mid_serve_submission_never_retraces(self, small):
        model, params = small
        first = [Request(prompt=p, max_new_tokens=6)
                 for p in _prompts([3, 5], seed=21)]
        late = Request(prompt=_prompts([4], seed=22)[0], max_new_tokens=4)
        eng = InferenceEngine(model, params,
                              EngineConfig(max_slots=2, max_len=16))

        def arrive(engine, tick):
            if tick == 2:
                engine.submit(late)

        eng.serve(first, on_tick=arrive)
        while eng.active_count or eng.queued_count:
            eng.tick()
        assert eng.decode_retraces == 0
        res = eng.completed[late.request_id]
        assert res.tokens == _expected_greedy(model, params, late, 16)

    def test_overflowing_request_rejected_at_submit(self, small):
        model, params = small
        eng = InferenceEngine(model, params,
                              EngineConfig(max_slots=1, max_len=8))
        with pytest.raises(ValueError, match="max_len"):
            eng.submit(Request(prompt=[1, 2, 3, 4], max_new_tokens=5))

    @pytest.mark.slow  # report-level reconciliation integration: slow tier (ROADMAP)

    def test_request_records_reconcile_with_monitor_report(
            self, small, tmp_path):
        """Acceptance: per-request JSONL rows reconcile with the engine's
        completion counters in the monitor report — through the real
        ``python -m apex_tpu.monitor`` CLI."""
        model, params = small
        log = tmp_path / "serving.jsonl"
        reg = MetricsRegistry([JsonlSink(str(log))])
        eng = InferenceEngine(
            model, params,
            EngineConfig(max_slots=2, max_len=16,
                         scheduler=SchedulerConfig(max_queue=2)),
            metrics=reg)
        reqs = [Request(prompt=p, max_new_tokens=n)
                for p, n in zip(_prompts([3, 6, 4], seed=17), [4, 3, 12])]
        eng.submit(reqs[0])
        eng.submit(reqs[1])
        with pytest.raises(QueueFullError):   # bounded-queue backpressure
            eng.submit(reqs[2])
        cancel_me = Request(prompt=_prompts([5], seed=18)[0],
                            max_new_tokens=11)
        cancel_submitted = False
        ticks = 0
        while (eng.active_count or eng.queued_count
               or not cancel_submitted):
            eng.tick()
            ticks += 1
            if not cancel_submitted and eng.queued_count < 2:
                eng.submit(cancel_me)
                cancel_submitted = True
            elif cancel_submitted and ticks > 4 and \
                    cancel_me.request_id not in eng.completed:
                eng.cancel(cancel_me.request_id)
        eng.close()
        report = build_report(str(log))
        counters = report["counters"]
        req_sec = report["requests"]
        assert req_sec is not None
        by_reason = req_sec["by_finish_reason"]
        # key-for-key reconciliation: every terminal record is counted by
        # exactly one requests_<reason> counter, and vice versa
        for reason in ("eos", "length", "cancelled", "timeout", "rejected"):
            assert counters[f"requests_{reason}"] == \
                by_reason.get(reason, 0), reason
        assert req_sec["count"] == sum(by_reason.values())
        assert counters["requests_submitted"] == req_sec["count"]
        assert req_sec["total_s"]["count"] == req_sec["count"]
        text = render_report(report)
        assert "serving requests" in text and "finish:" in text
        # the real CLI parses the same log (pure stdlib, no jax import)
        proc = subprocess.run(
            [sys.executable, "-m", "apex_tpu.monitor", str(log), "--json"],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stderr
        cli = json.loads(proc.stdout)
        assert cli["requests"]["by_finish_reason"] == by_reason

    def test_histograms_populated(self, small):
        model, params = small
        reg = MetricsRegistry()
        eng = InferenceEngine(model, params,
                              EngineConfig(max_slots=2, max_len=16),
                              metrics=reg)
        eng.serve([Request(prompt=p, max_new_tokens=3)
                   for p in _prompts([3, 4], seed=19)])
        hists = reg.histograms()
        for name in ("request_queue_s", "request_prefill_s",
                     "request_decode_s", "request_total_s",
                     "request_ttft_s", "request_tpot_s",
                     "slot_occupancy", "decode_batch_size"):
            assert name in hists and hists[name].count > 0, name

    def test_ttft_tpot_first_class(self, small):
        """Satellite contract: TTFT/TPOT are stamped from the engine's
        own token timestamps — not reconstructed by adding the coarse
        queue/prefill/decode buckets — and land in the JSONL record."""
        model, params = small
        reg = MetricsRegistry([InMemorySink()])
        eng = InferenceEngine(model, params,
                              EngineConfig(max_slots=2, max_len=16),
                              metrics=reg)
        multi, single = [
            Request(prompt=p, max_new_tokens=n) for p, n in
            zip(_prompts([4, 3], seed=23), (5, 1))]
        results = {r.request_id: r for r in eng.serve([multi, single])}
        res = results[multi.request_id]
        # first token arrives with the prefill result: TTFT brackets the
        # queue+prefill span and precedes the total latency
        assert res.ttft_s is not None and 0 < res.ttft_s <= res.total_s
        assert res.ttft_s == pytest.approx(
            res.queue_s + res.prefill_s, abs=0.05)
        # 5 tokens -> 4 inter-token gaps spanning the decode phase
        assert res.tpot_s is not None and res.tpot_s >= 0
        assert res.tpot_s * (res.new_tokens - 1) <= res.decode_s + 0.05
        # a single-token request has a TTFT but no inter-token interval
        one = results[single.request_id]
        assert one.ttft_s is not None and one.tpot_s is None
        sink = reg._sinks[0]
        recs = {r["request_id"]: r for r in sink.of_kind("request")}
        assert recs[multi.request_id]["ttft_s"] == res.ttft_s
        assert recs[multi.request_id]["tpot_s"] == res.tpot_s
        assert "tpot_s" not in recs[single.request_id]

    def test_rejected_request_has_no_ttft(self, small):
        model, params = small
        eng = InferenceEngine(model, params, EngineConfig(
            max_slots=1, max_len=16,
            scheduler=SchedulerConfig(max_queue=1)))
        p = _prompts([3, 3], seed=29)
        eng.submit(Request(prompt=p[0], max_new_tokens=2))
        rejected = Request(prompt=p[1], max_new_tokens=2)
        with pytest.raises(QueueFullError):   # queue of 1 already full
            eng.submit(rejected)
        res = eng.completed[rejected.request_id]
        assert res.ttft_s is None and res.tpot_s is None


@pytest.mark.slow
class TestServingSweep:
    def test_randomized_continuous_batching_parity(self, small):
        """Property-style sweep: randomized arrivals, lengths, and
        cancellations — no slot leaks, FCFS admission, compile count
        bounded by the bucket set, zero decode retraces, and token-exact
        greedy parity for every request that ran to completion."""
        model, params = small
        rng = np.random.RandomState(0)
        max_len = 24
        eng = InferenceEngine(model, params,
                              EngineConfig(max_slots=3, max_len=max_len))
        reqs = []
        for _ in range(12):
            pl = int(rng.randint(1, 13))
            mn = int(rng.randint(1, 1 + min(8, max_len - pl)))
            reqs.append(Request(
                prompt=rng.randint(0, 64, size=pl).tolist(),
                max_new_tokens=mn,
                eos_token=(int(rng.randint(0, 64))
                           if rng.rand() < 0.3 else None)))
        cancel_at = {reqs[4].request_id: 3, reqs[9].request_id: 5}

        def chaos(engine, tick):
            for rid, t in cancel_at.items():
                if tick == t:
                    engine.cancel(rid)

        results = eng.serve(reqs, on_tick=chaos)
        eng.slots.check()
        assert eng.slots.free_count == eng.config.max_slots
        assert eng.decode_retraces == 0
        assert eng.prefill_compiles <= len(eng.buckets)
        queue_cancelled = {r.request_id for r in results
                           if r.finish_reason == "cancelled"
                           and r.prefill_s == 0.0}
        assert eng.admission_log == [
            r.request_id for r in reqs
            if r.request_id not in queue_cancelled]
        assert len(results) == len(reqs)
        for req, res in zip(reqs, results):
            expected = _expected_greedy(model, params, req, max_len)
            if res.finish_reason in ("eos", "length"):
                assert res.tokens == expected, req.request_id
            elif res.finish_reason == "cancelled":
                assert res.tokens == expected[:res.new_tokens]
