"""Import sweep: every public module must import cleanly.

Role of the reference's ``tests/docker_extension_builds/run.sh`` (verifies
each optional extension builds): here each subpackage — including every
contrib extension and the C++-backed native module — must import and expose
its ``__all__`` names.
"""

import importlib
import pkgutil

import pytest

import apex_tpu

MODULES = [
    "apex_tpu",
    "apex_tpu.amp",
    "apex_tpu.analysis",
    "apex_tpu.analysis.rules",
    "apex_tpu.checkpoint",
    "apex_tpu.checkpoint.manifest",
    "apex_tpu.checkpoint.retry",
    "apex_tpu.checkpoint.sharded",
    "apex_tpu.checkpoint.verify",
    "apex_tpu.data",
    "apex_tpu.fp16_utils",
    "apex_tpu.fused_dense",
    "apex_tpu.loadtest",
    "apex_tpu.lora",
    "apex_tpu.mlp",
    "apex_tpu.monitor",
    "apex_tpu.multi_tensor_apply",
    "apex_tpu.native",
    "apex_tpu.normalization",
    "apex_tpu.observability",
    "apex_tpu.observability.fleet_metrics",
    "apex_tpu.observability.slo",
    "apex_tpu.observability.trace",
    "apex_tpu.ops",
    "apex_tpu.ops.decode_attention",
    "apex_tpu.optimizers",
    "apex_tpu.parallel",
    "apex_tpu.parallel.multiproc",
    "apex_tpu.resilience",
    "apex_tpu.rnn",
    "apex_tpu.serving",
    "apex_tpu.serving.fleet",
    "apex_tpu.serving.prefix",
    "apex_tpu.serving.speculation",
    "apex_tpu.testing_faults",
    "apex_tpu.training",
    "apex_tpu.transformer",
    "apex_tpu.transformer.amp",
    "apex_tpu.transformer.moe",
    "apex_tpu.transformer.parallel_state",
    "apex_tpu.transformer.pipeline_parallel",
    "apex_tpu.transformer.tensor_parallel",
    "apex_tpu.transformer.tensor_parallel.memory",
    "apex_tpu.transformer.testing",
    "apex_tpu.transformer._data",
    "apex_tpu.utils",
    "apex_tpu.models",
    "apex_tpu.contrib",
    "apex_tpu.contrib.bottleneck",
    "apex_tpu.contrib.clip_grad",
    "apex_tpu.contrib.conv_bias_relu",
    "apex_tpu.contrib.cudnn_gbn",
    "apex_tpu.contrib.fmha",
    "apex_tpu.contrib.focal_loss",
    "apex_tpu.contrib.gpu_direct_storage",
    "apex_tpu.contrib.group_norm",
    "apex_tpu.contrib.groupbn",
    "apex_tpu.contrib.index_mul_2d",
    "apex_tpu.contrib.layer_norm",
    "apex_tpu.contrib.multihead_attn",
    "apex_tpu.contrib.openfold",
    "apex_tpu.contrib.peer_memory",
    "apex_tpu.contrib.sparsity",
    "apex_tpu.contrib.transducer",
    "apex_tpu.contrib.xentropy",
]


@pytest.mark.parametrize("name", MODULES)
def test_imports(name):
    mod = importlib.import_module(name)
    for sym in getattr(mod, "__all__", []):
        assert hasattr(mod, sym), f"{name}.__all__ lists missing {sym!r}"


def test_no_unlisted_packages():
    """Every subpackage on disk is in the sweep (catches future additions)."""
    found = {
        name
        for _, name, _ in pkgutil.walk_packages(
            apex_tpu.__path__, prefix="apex_tpu.")
    }
    packages = {n for n in found if not n.rsplit(".", 1)[-1].startswith("_")}
    swept = set(MODULES)
    # sweep granularity: top-level subpackages + immediate contrib children
    # (their internal modules are covered transitively by the package import)
    top_and_contrib = {
        n for n in packages
        if n.count(".") == 1
        or (n.startswith("apex_tpu.contrib.") and n.count(".") == 2)
    }
    missing = {n for n in top_and_contrib if n not in swept
               and not any(s.startswith(n + ".") or s == n for s in swept)}
    assert not missing, f"unswept subpackages: {sorted(missing)}"
