"""Contrib extension suite.

Mirrors the per-extension tests under ``apex/contrib/test/`` (focal_loss vs
torchvision's sigmoid_focal_loss, index_mul_2d vs composed ops, group_norm
vs torch GroupNorm, transducer vs torchaudio-style reference DP, multihead
attn vs torch.nn.MultiheadAttention, groupbn vs torch BatchNorm, spatial
bottleneck vs its unsharded self, ASP mask invariants).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
from jax.sharding import PartitionSpec as P

os.environ.setdefault("APEX_TPU_FORCE_PALLAS", "interpret")

from apex_tpu.transformer import parallel_state  # noqa: E402
from apex_tpu.utils.sharding import shard_map  # noqa: E402


def _t(x):
    return torch.tensor(np.asarray(x))


class TestFocalLoss:
    def test_matches_torchvision_formula(self):
        from apex_tpu.contrib.focal_loss import focal_loss

        N, K, alpha, gamma = 12, 8, 0.24, 2.0
        x = jax.random.normal(jax.random.PRNGKey(0), (N, K))
        classes = jax.random.randint(jax.random.PRNGKey(1), (N,), 0, K)

        # torchvision sigmoid_focal_loss reimplemented as ground truth
        xt = _t(x).requires_grad_()
        y = torch.nn.functional.one_hot(_t(classes).long(), K).float()
        p = torch.sigmoid(xt)
        ce = torch.nn.functional.binary_cross_entropy_with_logits(
            xt, y, reduction="none")
        p_t = p * y + (1 - p) * (1 - y)
        ref = (ce * ((1 - p_t) ** gamma) * (alpha * y + (1 - alpha) * (1 - y))
               ).sum()
        ref.backward()

        loss, grads = jax.value_and_grad(
            lambda x: focal_loss(x, classes, jnp.ones(()), K, alpha, gamma))(x)
        np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
        # atol 1e-5: TPU sigmoid/pow transcendentals drift ~4e-6 vs torch
        np.testing.assert_allclose(np.asarray(grads), xt.grad.numpy(),
                                   rtol=2e-4, atol=1e-5)

    def test_label_smoothing_and_background(self):
        from apex_tpu.contrib.focal_loss import focal_loss

        x = jax.random.normal(jax.random.PRNGKey(0), (6, 4))
        classes = jnp.array([0, 1, -1, 3, -1, 2])  # -1 = background
        loss = focal_loss(x, classes, jnp.asarray(2.0), 4, 0.25, 2.0,
                          label_smoothing=0.1)
        assert np.isfinite(float(loss))


class TestIndexMul2d:
    def test_matches_composition_and_grads(self):
        from apex_tpu.contrib.index_mul_2d import index_mul_2d

        in1 = jax.random.normal(jax.random.PRNGKey(0), (10, 7))
        in2 = jax.random.normal(jax.random.PRNGKey(1), (16, 7))
        idx = jax.random.randint(jax.random.PRNGKey(2), (16,), 0, 10)
        out = index_mul_2d(in1, in2, idx)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(in1)[np.asarray(idx)]
                                   * np.asarray(in2), rtol=1e-6)
        # grad of in1 is a scatter-add over duplicate indices
        g1 = jax.grad(lambda a: jnp.sum(index_mul_2d(a, in2, idx)))(in1)
        ref = np.zeros_like(np.asarray(in1))
        np.add.at(ref, np.asarray(idx), np.asarray(in2))
        np.testing.assert_allclose(np.asarray(g1), ref, rtol=1e-5, atol=1e-6)


class TestGroupNorm:
    @pytest.mark.parametrize("act", ["", "swish"])
    def test_matches_torch_group_norm(self, act):
        from apex_tpu.contrib.group_norm import GroupNorm

        N, H, W, C, G = 2, 5, 6, 16, 4
        gn = GroupNorm(G, C, act=act)
        params = gn.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (N, H, W, C))
        out = gn.apply(params, x)

        tgn = torch.nn.GroupNorm(G, C)
        ref = tgn(_t(x).permute(0, 3, 1, 2)).permute(0, 2, 3, 1)
        if act:
            ref = ref * torch.sigmoid(ref)
        np.testing.assert_allclose(np.asarray(out), ref.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_bad_activation(self):
        from apex_tpu.contrib.group_norm import group_norm_nhwc

        with pytest.raises(ValueError):
            group_norm_nhwc(jnp.zeros((1, 2, 2, 4)), 2, None, None,
                            act="relu")


class TestTransducer:
    def test_joint_shapes_and_relu(self):
        from apex_tpu.contrib.transducer import TransducerJoint

        B, T, U, H = 2, 5, 4, 8
        f = jax.random.normal(jax.random.PRNGKey(0), (B, T, H))
        g = jax.random.normal(jax.random.PRNGKey(1), (B, U, H))
        out = TransducerJoint()(f, g)
        np.testing.assert_allclose(
            np.asarray(out[0, 1, 2]), np.asarray(f[0, 1] + g[0, 2]),
            rtol=1e-6)
        out_relu = TransducerJoint(relu=True)(f, g)
        assert float(jnp.min(out_relu)) >= 0.0

    def test_loss_matches_brute_force(self):
        from apex_tpu.contrib.transducer import transducer_loss

        # brute-force DP in numpy over log-probs
        B, T, U, K, blank = 2, 4, 3, 5, 0
        rng = np.random.default_rng(0)
        x = rng.normal(size=(B, T, U, K)).astype(np.float32)
        label = rng.integers(1, K, size=(B, U - 1))
        f_len = np.array([4, 3])
        y_len = np.array([2, 1])

        logp = np.asarray(jax.nn.log_softmax(jnp.asarray(x), axis=-1))

        def brute(b):
            T_, U_ = f_len[b], y_len[b] + 1
            alpha = np.full((T_, U_), -np.inf)
            alpha[0, 0] = 0.0
            for t in range(T_):
                for u in range(U_):
                    terms = []
                    if t > 0:
                        terms.append(alpha[t - 1, u]
                                     + logp[b, t - 1, u, blank])
                    if u > 0:
                        terms.append(alpha[t, u - 1]
                                     + logp[b, t, u - 1, label[b, u - 1]])
                    if terms:
                        alpha[t, u] = np.logaddexp.reduce(terms)
            return -(alpha[T_ - 1, U_ - 1] + logp[b, T_ - 1, U_ - 1, blank])

        ref = np.array([brute(b) for b in range(B)])
        loss = transducer_loss(jnp.asarray(x), jnp.asarray(label),
                               jnp.asarray(f_len), jnp.asarray(y_len), blank)
        np.testing.assert_allclose(np.asarray(loss), ref, rtol=1e-5,
                                   atol=1e-5)

    def test_loss_grads_finite(self):
        from apex_tpu.contrib.transducer import transducer_loss

        x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 3, 5))
        g = jax.grad(lambda x: jnp.sum(transducer_loss(
            x, jnp.array([[1, 2], [3, 4]]), jnp.array([4, 3]),
            jnp.array([2, 1]), 0)))(x)
        assert np.isfinite(np.asarray(g)).all()


class TestMultiheadAttn:
    def test_self_attn_matches_torch(self):
        from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

        T, B, E, H = 6, 2, 16, 4
        attn = SelfMultiheadAttn(E, H, bias=True)
        params = attn.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (T, B, E))

        ref = torch.nn.MultiheadAttention(E, H, bias=True)
        with torch.no_grad():
            ref.in_proj_weight.copy_(_t(params["in_proj_weight"]))
            ref.in_proj_bias.copy_(_t(params["in_proj_bias"]))
            ref.out_proj.weight.copy_(_t(params["out_proj_weight"]))
            ref.out_proj.bias.copy_(_t(params["out_proj_bias"]))
        ref_out, _ = ref(_t(x), _t(x), _t(x), need_weights=False)

        out = attn.apply(params, x, is_training=False)
        np.testing.assert_allclose(np.asarray(out),
                                   ref_out.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_self_attn_key_padding_mask(self):
        from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

        T, B, E, H = 5, 2, 8, 2
        attn = SelfMultiheadAttn(E, H, bias=True)
        params = attn.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (T, B, E))
        mask = jnp.zeros((B, T), bool).at[:, -2:].set(True)
        out_m = attn.apply(params, x, key_padding_mask=mask,
                           is_training=False)
        # masking the padded keys must equal attention over the prefix only
        out_prefix = attn.apply(params, x[:3], is_training=False)
        np.testing.assert_allclose(np.asarray(out_m[:3]),
                                   np.asarray(out_prefix),
                                   rtol=1e-4, atol=1e-5)

    def test_encdec_and_norm_add(self):
        from apex_tpu.contrib.multihead_attn import EncdecMultiheadAttn

        Tq, Tk, B, E, H = 4, 6, 2, 8, 2
        attn = EncdecMultiheadAttn(E, H, bias=True, include_norm_add=True)
        params = attn.init(jax.random.PRNGKey(0))
        q = jax.random.normal(jax.random.PRNGKey(1), (Tq, B, E))
        k = jax.random.normal(jax.random.PRNGKey(2), (Tk, B, E))
        out = attn.apply(params, q, k, is_training=False)
        assert out.shape == (Tq, B, E)
        # residual add: zero attention output would return query unchanged;
        # with real params the difference from query must be bounded but
        # nonzero
        assert float(jnp.max(jnp.abs(out - q))) > 0


class TestGroupBN:
    def test_matches_torch_bn_training_and_eval(self):
        from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

        N, H, W, C = 4, 5, 6, 8
        bn = BatchNorm2d_NHWC(C)
        params, state = bn.init(), bn.init_state()
        x = jax.random.normal(jax.random.PRNGKey(0), (N, H, W, C))

        tbn = torch.nn.BatchNorm2d(C)
        xt = _t(x).permute(0, 3, 1, 2)
        ref = tbn(xt).permute(0, 2, 3, 1)
        y, state = bn.apply(params, state, x, training=True)
        np.testing.assert_allclose(np.asarray(y), ref.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(state["running_mean"]),
                                   tbn.running_mean.numpy(), rtol=1e-4,
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(state["running_var"]),
                                   tbn.running_var.numpy(), rtol=1e-4,
                                   atol=1e-5)
        # eval mode uses running stats
        tbn.eval()
        ref_e = tbn(xt).permute(0, 2, 3, 1)
        y_e, _ = bn.apply(params, state, x, training=False)
        np.testing.assert_allclose(np.asarray(y_e), ref_e.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_fused_add_relu(self):
        from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

        bn = BatchNorm2d_NHWC(4, fuse_relu=True)
        params, state = bn.init(), bn.init_state()
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 3, 4))
        z = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 3, 4))
        y, _ = bn.apply(params, state, x, z)
        assert float(jnp.min(y)) >= 0.0

    def test_group_stats_sync(self):
        from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel()
        C = 4
        bn = BatchNorm2d_NHWC(C, bn_group=8, bn_group_axis="data")
        params, state = bn.init(), bn.init_state()
        x = jax.random.normal(jax.random.PRNGKey(0), (16, 3, 3, C))

        def per_rank(x):
            y, _ = bn.apply(params, state, x, training=True)
            return y

        y = jax.jit(shard_map(per_rank, mesh=mesh, in_specs=P("data"),
                                  out_specs=P("data"),
                                  check_vma=False))(x)
        # group-synced stats == full-batch BN
        y_ref, _ = bn.apply(params, state, x, training=True)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   rtol=1e-4, atol=1e-5)
        parallel_state.destroy_model_parallel()


class TestBottleneck:
    def test_spatial_matches_unsharded(self):
        from apex_tpu.contrib.bottleneck import Bottleneck, SpatialBottleneck

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=4)
        N, H, W, C = 2, 16, 8, 8
        ref_block = Bottleneck(C, 4, C)
        params = ref_block.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (N, H, W, C))
        ref = ref_block.apply(params, x)

        sp = SpatialBottleneck(C, 4, C, spatial_axis="context")
        out = jax.jit(shard_map(
            lambda p, x: sp.apply(p, x), mesh=mesh,
            in_specs=(ref_block.spec(), P(None, "context")),
            out_specs=P(None, "context"),
            check_vma=False))(params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)
        parallel_state.destroy_model_parallel()

    def test_downsample_path(self):
        from apex_tpu.contrib.bottleneck import Bottleneck

        block = Bottleneck(8, 4, 16, stride=2)
        params = block.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8, 8))
        out = block.apply(params, x)
        assert out.shape == (2, 4, 4, 16)


class TestASP:
    def test_mask_2to4_invariants(self):
        from apex_tpu.contrib.sparsity import compute_sparse_mask_2to4

        w = jax.random.normal(jax.random.PRNGKey(0), (32, 64))
        mask = compute_sparse_mask_2to4(w)
        groups = np.asarray(mask).reshape(32, 16, 4)
        assert (groups.sum(-1) == 2).all()
        # kept entries are the 2 largest magnitudes per group
        wg = np.abs(np.asarray(w)).reshape(32, 16, 4)
        kept = np.where(groups, wg, -1.0)
        dropped = np.where(~groups, wg, np.inf)
        assert (kept.max(-1) >= dropped.min(-1) - 1e-12).all()

    def test_asp_workflow(self):
        from apex_tpu.contrib.sparsity import ASP

        params = {
            "dense": {"weight": jax.random.normal(jax.random.PRNGKey(0),
                                                  (64, 64)),
                      "bias": jnp.ones((64,))},
        }
        asp = ASP()
        asp.init_model_for_pruning(params)
        masks = asp.compute_sparse_masks(params)
        pruned = asp.apply_masks(params, masks)
        # weight pruned to 50%, bias untouched
        assert float(jnp.mean((pruned["dense"]["weight"] != 0))) == 0.5
        np.testing.assert_array_equal(np.asarray(pruned["dense"]["bias"]),
                                      np.ones(64))


class TestFMHA:
    def test_varlen_matches_per_sample(self):
        from apex_tpu.contrib.fmha import FMHA

        B, S, H, E = 3, 8, 2, 8
        fmha = FMHA(num_attention_heads=H, hidden_size=E)
        qkv = jax.random.normal(jax.random.PRNGKey(0), (B, S, 3 * E))
        seqlens = jnp.array([8, 5, 3])
        out = fmha(qkv, seqlens)
        # each sample equals dense attention over its true length
        for b, L in enumerate([8, 5, 3]):
            sub = fmha(qkv[b:b + 1, :L], jnp.array([L]))
            np.testing.assert_allclose(np.asarray(out[b, :L]),
                                       np.asarray(sub[0]),
                                       rtol=1e-4, atol=1e-5)
            np.testing.assert_allclose(np.asarray(out[b, L:]), 0.0)


class TestConvBiasReLU:
    """apex/contrib/conv_bias_relu parity: epilogue math vs unfused ops."""

    def _data(self):
        k = jax.random.PRNGKey(0)
        k1, k2, k3 = jax.random.split(k, 3)
        x = jax.random.normal(k1, (2, 8, 8, 4))
        w = jax.random.normal(k2, (3, 3, 4, 6)) * 0.1
        b = jax.random.normal(k3, (6,))
        return x, w, b

    def test_conv_bias_relu(self):
        from apex_tpu.contrib.conv_bias_relu import ConvBias, ConvBiasReLU
        from apex_tpu.utils.conv import conv_nhwc

        x, w, b = self._data()
        ref = conv_nhwc(x, w) + b
        np.testing.assert_allclose(np.asarray(ConvBias(x, w, b)),
                                   np.asarray(ref), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(ConvBiasReLU(x, w, b)),
                                   np.maximum(np.asarray(ref), 0), rtol=1e-6)

    def test_conv_bias_mask_relu(self):
        from apex_tpu.contrib.conv_bias_relu import ConvBiasMaskReLU
        from apex_tpu.utils.conv import conv_nhwc

        x, w, b = self._data()
        mask = (jax.random.uniform(jax.random.PRNGKey(7),
                                   (2, 8, 8, 6)) > 0.5).astype(x.dtype)
        ref = np.maximum(np.asarray((conv_nhwc(x, w) + b) * mask), 0)
        np.testing.assert_allclose(
            np.asarray(ConvBiasMaskReLU(x, w, b, mask)), ref, rtol=1e-6)

    def test_frozen_scale_bias(self):
        from apex_tpu.contrib.conv_bias_relu import ConvFrozenScaleBiasReLU
        from apex_tpu.utils.conv import conv_nhwc

        x, w, _ = self._data()
        scale = jnp.full((6,), 1.5)
        bias = jnp.full((6,), -0.25)
        ref = np.maximum(np.asarray(conv_nhwc(x, w) * scale + bias), 0)
        np.testing.assert_allclose(
            np.asarray(ConvFrozenScaleBiasReLU(x, w, scale, bias)), ref,
            rtol=1e-6)

    def test_grad_flows(self):
        from apex_tpu.contrib.conv_bias_relu import ConvBiasReLU

        x, w, b = self._data()
        g = jax.grad(lambda w: jnp.sum(ConvBiasReLU(x, w, b)))(w)
        assert np.isfinite(np.asarray(g)).all()


class TestFusedAdamSWA:
    """apex/contrib/openfold_triton FusedAdamSWA semantics
    (fused_adam_swa.py:102-112)."""

    def _setup(self):
        params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
        grads = {"w": jnp.full((4, 4), 0.1), "b": jnp.full((4,), 0.2)}
        return params, grads

    def test_first_step_copies_params(self):
        from apex_tpu.contrib.openfold import FusedAdamSWA

        params, grads = self._setup()
        opt = FusedAdamSWA(lr=1e-2, swa_decay_rate=0.9)
        state = opt.init(params)
        new_p, new_s = opt.step(grads, params, state)
        assert int(new_s["n_averaged"]) == 1
        # n_averaged was 0 -> SWA buffer = stepped params exactly
        jax.tree.map(lambda s, p: np.testing.assert_allclose(s, p),
                     new_s["swa_params"], new_p)

    def test_ema_after_first(self):
        from apex_tpu.contrib.openfold import FusedAdamSWA

        params, grads = self._setup()
        decay = 0.8
        opt = FusedAdamSWA(lr=1e-2, swa_decay_rate=decay)
        state = opt.init(params)
        p1, s1 = opt.step(grads, params, state)
        p2, s2 = opt.step(grads, p1, s1)
        expect = jax.tree.map(
            lambda swa, p: swa + (1 - decay) * (p - swa),
            s1["swa_params"], p2)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6),
            s2["swa_params"], expect)
        assert int(s2["n_averaged"]) == 2

    def test_adam_math_matches_fused_adam(self):
        from apex_tpu.contrib.openfold import FusedAdamSWA
        from apex_tpu.optimizers import FusedAdam

        params, grads = self._setup()
        swa = FusedAdamSWA(lr=1e-2, swa_decay_rate=0.9, weight_decay=0.01)
        ref = FusedAdam(lr=1e-2, weight_decay=0.01)
        ps, ss = params, swa.init(params)
        pr, sr = params, ref.init(params)
        for _ in range(3):
            ps, ss = swa.step(grads, ps, ss)
            pr, sr = ref.step(grads, pr, sr)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6),
                     ps, pr)

    def test_found_inf_freezes_swa(self):
        from apex_tpu.contrib.openfold import FusedAdamSWA

        params, grads = self._setup()
        opt = FusedAdamSWA(lr=1e-2)
        state = opt.init(params)
        new_p, new_s = opt.step(grads, params, state,
                                found_inf=jnp.asarray(True))
        jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b),
                     new_p, params)
        assert int(new_s["n_averaged"]) == 0
        jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b),
                     new_s["swa_params"], state["swa_params"])
