"""amp policy + loss scaler tests (tier-L0 analog of ``tests/L0/run_amp``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.amp.scaler import LossScaler


def test_opt_levels():
    for lvl in ("O0", "O1", "O2", "O3"):
        st = amp.initialize(lvl)
        assert st.properties.opt_level == lvl
    with pytest.raises(ValueError):
        amp.initialize("O4")
    o2 = amp.initialize("O2")
    assert o2.properties.master_weights
    assert o2.policy.param_dtype == jnp.bfloat16
    o0 = amp.initialize("O0")
    assert float(o0.loss_scale) == 1.0


def test_policy_wrap():
    policy = amp.Policy(jnp.float32, jnp.bfloat16, jnp.float32)
    fn = policy.wrap(lambda x: x * 2)
    out = fn(jnp.ones((4,), jnp.float32))
    assert out.dtype == jnp.float32
    seen = {}

    def probe(x):
        seen["dtype"] = x.dtype
        return x

    policy.wrap(probe)(jnp.ones((4,), jnp.float32))
    assert seen["dtype"] == jnp.bfloat16


def test_half_float_promote():
    h = amp.half_function(lambda x: x)
    assert h(jnp.ones(3, jnp.float32)).dtype == jnp.bfloat16
    f = amp.float_function(lambda x: x)
    assert f(jnp.ones(3, jnp.bfloat16)).dtype == jnp.float32
    p = amp.promote_function(lambda x, y: x + y)
    out = p(jnp.ones(3, jnp.bfloat16), jnp.ones(3, jnp.float32))
    assert out.dtype == jnp.float32


def test_scaler_static():
    sc = LossScaler(128.0)
    st = sc.init()
    assert float(sc.scale(jnp.asarray(2.0), st)) == 256.0
    grads = {"w": jnp.full((4,), 256.0)}
    unscaled, found_inf = sc.unscale(grads, st)
    np.testing.assert_allclose(unscaled["w"], 2.0)
    assert not bool(found_inf)
    st2 = sc.update(st, found_inf)
    assert float(st2.loss_scale) == 128.0


def test_scaler_dynamic_backoff_growth():
    sc = LossScaler("dynamic", init_scale=2.0 ** 8, scale_window=3)
    st = sc.init()
    bad = {"w": jnp.array([jnp.inf, 1.0])}
    _, found_inf = sc.unscale(bad, st)
    assert bool(found_inf)
    st = sc.update(st, found_inf)
    assert float(st.loss_scale) == 2.0 ** 7  # halved
    good = {"w": jnp.ones(2)}
    for _ in range(3):
        _, fi = sc.unscale(good, st)
        st = sc.update(st, fi)
    assert float(st.loss_scale) == 2.0 ** 8  # grew after window


def test_scaler_hysteresis():
    sc = LossScaler("dynamic", init_scale=2.0 ** 8, hysteresis=2)
    st = sc.init()
    fi = jnp.asarray(True)
    st = sc.update(st, fi)
    assert float(st.loss_scale) == 2.0 ** 8  # first overflow absorbed
    st = sc.update(st, fi)
    assert float(st.loss_scale) == 2.0 ** 7  # credits exhausted -> backoff


def test_scaler_unscale_zeroes_nonfinite():
    sc = LossScaler(1.0)
    g = {"w": jnp.array([1.0, jnp.nan, jnp.inf])}
    u, fi = sc.unscale(g, sc.init())
    assert bool(fi)
    assert np.isfinite(np.asarray(u["w"])).all()


def test_state_dict_roundtrip():
    st = amp.initialize("O2", num_losses=2)
    d = amp.state_dict(st)
    assert set(d) == {"loss_scaler0", "loss_scaler1"}
    st2 = amp.load_state_dict(st, {"loss_scaler0": {"loss_scale": 42.0}})
    assert float(st2.scaler_states[0].loss_scale) == 42.0


def test_apply_if_finite():
    params = jnp.ones((3,))
    stepped = amp.apply_if_finite(jnp.asarray(False), lambda p: p + 1, params)
    np.testing.assert_allclose(stepped, 2.0)
    skipped = amp.apply_if_finite(jnp.asarray(True), lambda p: p + 1, params)
    np.testing.assert_allclose(skipped, 1.0)


def test_scale_skip_flow_jitted():
    """End-to-end jitted train-step flow with an injected overflow."""
    sc = LossScaler("dynamic", init_scale=2.0 ** 4)
    opt_lr = 0.1

    @jax.jit
    def step(params, scaler_state, x):
        def loss_fn(p):
            loss = jnp.sum((p * x) ** 2)
            return sc.scale(loss, scaler_state)

        grads = jax.grad(loss_fn)(params)
        grads, found_inf = sc.unscale(grads, scaler_state)
        new_params = amp.apply_if_finite(
            found_inf, lambda p: p - opt_lr * grads, params)
        return new_params, sc.update(scaler_state, found_inf)

    params = jnp.ones((4,))
    st = sc.init()
    params2, st2 = step(params, st, jnp.ones((4,)))
    assert not np.allclose(params2, params)  # stepped
    params3, st3 = step(params2, st2, jnp.full((4,), jnp.inf))
    np.testing.assert_allclose(params3, params2)  # skipped
    assert float(st3.loss_scale) == 2.0 ** 3


class TestFp16Path:
    """True float16 (not bf16) flow — fp16 is the dtype dynamic loss scaling
    exists for (the reference's amp O2 default). fp16's 65504 max makes
    scaled gradients genuinely overflow, exercising backoff + recovery end
    to end."""

    def test_fp16_policy(self):
        amp_state = amp.initialize("O2", half_dtype=jnp.float16)
        assert amp_state.policy.param_dtype == jnp.float16
        assert amp_state.policy.compute_dtype == jnp.float16

    def test_fp16_overflow_backoff_and_recovery(self):
        scaler = amp.LossScaler("dynamic", init_scale=2.0 ** 16,
                                scale_window=2, hysteresis=1)
        st = scaler.init()
        # fp16 grads that overflow once scaled by 2^16
        big = jnp.full((4,), 4.0, jnp.float16)       # 4 * 65536 > fp16 max
        scaled = (big.astype(jnp.float32) * st.loss_scale).astype(jnp.float16)
        grads, found_inf = scaler.unscale({"g": scaled}, st)
        assert bool(found_inf)
        st = scaler.update(st, found_inf)
        assert float(st.loss_scale) == 2.0 ** 15     # backed off
        # finite steps at the reduced scale grow it back after scale_window
        ok = jnp.ones((4,), jnp.float16)
        for _ in range(2):
            g, fi = scaler.unscale(
                {"g": (ok.astype(jnp.float32) * st.loss_scale / 2.0 ** 14
                       ).astype(jnp.float16)}, st)
            assert not bool(fi)
            st = scaler.update(st, fi)
        assert float(st.loss_scale) == 2.0 ** 16     # regrown

    def test_fp16_train_step_converges(self):
        from apex_tpu.optimizers import FusedSGD

        amp_state = amp.initialize("O2", half_dtype=jnp.float16)
        scaler, st = amp_state.scaler, amp_state.scaler_states[0]
        w = {"w": jnp.ones((8,), jnp.float16) * 0.5}
        opt = FusedSGD(lr=0.1, master_weights=True)
        os_ = opt.init(w)
        x = jnp.linspace(-1, 1, 8).astype(jnp.float16)

        @jax.jit
        def step(w, os_, st):
            def loss_fn(p):
                return jnp.mean((p["w"].astype(jnp.float32) * x.astype(
                    jnp.float32) - x.astype(jnp.float32)) ** 2)

            sloss, grads = jax.value_and_grad(
                lambda p: scaler.scale(loss_fn(p), st))(w)
            grads, found_inf = scaler.unscale(grads, st)
            w2, os2 = opt.step(grads, w, os_, found_inf=found_inf)
            return w2, os2, scaler.update(st, found_inf), sloss / st.loss_scale

        losses = []
        for _ in range(20):
            w, os_, st, loss = step(w, os_, st)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        assert w["w"].dtype == jnp.float16


class TestRegistrationAndDisable:
    """The remaining apex.amp public surface: register_* module patching
    (amp/amp.py:52-72), disable_casts (handle.py:164), master_params
    (_amp_state.py:50)."""

    def test_register_half_function(self):
        import types

        mod = types.SimpleNamespace(op=lambda x: x.dtype)
        amp.register_half_function(mod, "op")
        assert mod.op(jnp.ones((2,), jnp.float32)) == jnp.bfloat16

    def test_register_float_function(self):
        import types

        mod = types.SimpleNamespace(op=lambda x: x.dtype)
        amp.register_float_function(mod, "op")
        assert mod.op(jnp.ones((2,), jnp.bfloat16)) == jnp.float32

    def test_register_promote_function(self):
        import types

        mod = types.SimpleNamespace(op=lambda x, y: (x.dtype, y.dtype))
        amp.register_promote_function(mod, "op")
        a = jnp.ones((2,), jnp.bfloat16)
        b = jnp.ones((2,), jnp.float32)
        assert mod.op(a, b) == (jnp.float32, jnp.float32)

    def test_disable_casts_suspends_wrappers(self):
        fn = amp.half_function(lambda x: x.dtype)
        x32 = jnp.ones((2,), jnp.float32)
        assert fn(x32) == jnp.bfloat16
        with amp.disable_casts():
            assert fn(x32) == jnp.float32
        assert fn(x32) == jnp.bfloat16          # restored

    def test_disable_casts_suspends_policy_wrap(self):
        pol = amp.Policy(jnp.float32, jnp.bfloat16, jnp.float32)
        seen = {}

        def probe(x):
            seen["dt"] = x.dtype
            return x

        wrapped = pol.wrap(probe)
        wrapped(jnp.ones((2,), jnp.float32))
        assert seen["dt"] == jnp.bfloat16
        seen.clear()
        with amp.disable_casts():
            wrapped(jnp.ones((2,), jnp.float32))
        assert seen["dt"] == jnp.float32

    def test_master_params_from_optimizer_state(self):
        from apex_tpu.optimizers import FusedAdam

        p = {"w": jnp.ones((3,), jnp.bfloat16)}
        opt = FusedAdam(lr=1e-3, master_weights=True)
        st = opt.init(p)
        masters = amp.master_params(st)
        assert len(masters) == 1 and masters[0].dtype == jnp.float32
        assert amp.master_params(FusedAdam(lr=1e-3).init(p)) == []
