"""amp policy + loss scaler tests (tier-L0 analog of ``tests/L0/run_amp``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.amp.scaler import LossScaler


def test_opt_levels():
    for lvl in ("O0", "O1", "O2", "O3"):
        st = amp.initialize(lvl)
        assert st.properties.opt_level == lvl
    with pytest.raises(ValueError):
        amp.initialize("O4")
    o2 = amp.initialize("O2")
    assert o2.properties.master_weights
    assert o2.policy.param_dtype == jnp.bfloat16
    o0 = amp.initialize("O0")
    assert float(o0.loss_scale) == 1.0


def test_policy_wrap():
    policy = amp.Policy(jnp.float32, jnp.bfloat16, jnp.float32)
    fn = policy.wrap(lambda x: x * 2)
    out = fn(jnp.ones((4,), jnp.float32))
    assert out.dtype == jnp.float32
    seen = {}

    def probe(x):
        seen["dtype"] = x.dtype
        return x

    policy.wrap(probe)(jnp.ones((4,), jnp.float32))
    assert seen["dtype"] == jnp.bfloat16


def test_half_float_promote():
    h = amp.half_function(lambda x: x)
    assert h(jnp.ones(3, jnp.float32)).dtype == jnp.bfloat16
    f = amp.float_function(lambda x: x)
    assert f(jnp.ones(3, jnp.bfloat16)).dtype == jnp.float32
    p = amp.promote_function(lambda x, y: x + y)
    out = p(jnp.ones(3, jnp.bfloat16), jnp.ones(3, jnp.float32))
    assert out.dtype == jnp.float32


def test_scaler_static():
    sc = LossScaler(128.0)
    st = sc.init()
    assert float(sc.scale(jnp.asarray(2.0), st)) == 256.0
    grads = {"w": jnp.full((4,), 256.0)}
    unscaled, found_inf = sc.unscale(grads, st)
    np.testing.assert_allclose(unscaled["w"], 2.0)
    assert not bool(found_inf)
    st2 = sc.update(st, found_inf)
    assert float(st2.loss_scale) == 128.0


def test_scaler_dynamic_backoff_growth():
    sc = LossScaler("dynamic", init_scale=2.0 ** 8, scale_window=3)
    st = sc.init()
    bad = {"w": jnp.array([jnp.inf, 1.0])}
    _, found_inf = sc.unscale(bad, st)
    assert bool(found_inf)
    st = sc.update(st, found_inf)
    assert float(st.loss_scale) == 2.0 ** 7  # halved
    good = {"w": jnp.ones(2)}
    for _ in range(3):
        _, fi = sc.unscale(good, st)
        st = sc.update(st, fi)
    assert float(st.loss_scale) == 2.0 ** 8  # grew after window


def test_scaler_hysteresis():
    sc = LossScaler("dynamic", init_scale=2.0 ** 8, hysteresis=2)
    st = sc.init()
    fi = jnp.asarray(True)
    st = sc.update(st, fi)
    assert float(st.loss_scale) == 2.0 ** 8  # first overflow absorbed
    st = sc.update(st, fi)
    assert float(st.loss_scale) == 2.0 ** 7  # credits exhausted -> backoff


def test_scaler_unscale_zeroes_nonfinite():
    sc = LossScaler(1.0)
    g = {"w": jnp.array([1.0, jnp.nan, jnp.inf])}
    u, fi = sc.unscale(g, sc.init())
    assert bool(fi)
    assert np.isfinite(np.asarray(u["w"])).all()


def test_state_dict_roundtrip():
    st = amp.initialize("O2", num_losses=2)
    d = amp.state_dict(st)
    assert set(d) == {"loss_scaler0", "loss_scaler1"}
    st2 = amp.load_state_dict(st, {"loss_scaler0": {"loss_scale": 42.0}})
    assert float(st2.scaler_states[0].loss_scale) == 42.0


def test_apply_if_finite():
    params = jnp.ones((3,))
    stepped = amp.apply_if_finite(jnp.asarray(False), lambda p: p + 1, params)
    np.testing.assert_allclose(stepped, 2.0)
    skipped = amp.apply_if_finite(jnp.asarray(True), lambda p: p + 1, params)
    np.testing.assert_allclose(skipped, 1.0)


def test_scale_skip_flow_jitted():
    """End-to-end jitted train-step flow with an injected overflow."""
    sc = LossScaler("dynamic", init_scale=2.0 ** 4)
    opt_lr = 0.1

    @jax.jit
    def step(params, scaler_state, x):
        def loss_fn(p):
            loss = jnp.sum((p * x) ** 2)
            return sc.scale(loss, scaler_state)

        grads = jax.grad(loss_fn)(params)
        grads, found_inf = sc.unscale(grads, scaler_state)
        new_params = amp.apply_if_finite(
            found_inf, lambda p: p - opt_lr * grads, params)
        return new_params, sc.update(scaler_state, found_inf)

    params = jnp.ones((4,))
    st = sc.init()
    params2, st2 = step(params, st, jnp.ones((4,)))
    assert not np.allclose(params2, params)  # stepped
    params3, st3 = step(params2, st2, jnp.full((4,), jnp.inf))
    np.testing.assert_allclose(params3, params2)  # skipped
    assert float(st3.loss_scale) == 2.0 ** 3
