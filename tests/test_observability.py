"""Observability-subsystem suite (ISSUE 3): registry semantics and
thread-safety, bounded histograms, sink round-trips, MFU math, span
tracing, profiler-capture scheduling, retrace-watchdog metric emission —
and the acceptance path: a fault-injected CPU ``run_training`` with a
JSONL sink whose ``python -m apex_tpu.monitor`` report reconciles
exactly with ``TrainingResult.telemetry``.
"""

import json
import os
import subprocess
import sys
import threading

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.amp.scaler import LossScaler
from apex_tpu.analysis.retrace import RetraceWatchdog
from apex_tpu.observability import (
    TRIGGER_EVENTS,
    DriftSentinel,
    FlightRecorder,
    InMemorySink,
    JsonlSink,
    MetricsRegistry,
    PrometheusTextfileSink,
    ProfilerCapture,
    SentinelConfig,
    StepMetrics,
    StepTimer,
    build_report,
    percentile,
    render_report,
    span,
)
from apex_tpu.optimizers import FusedSGD
from apex_tpu.resilience import (
    ResilienceConfig,
    make_resilient_train_step,
    make_train_state,
    run_training,
)
from apex_tpu.testing_faults import FaultInjector
from apex_tpu.utils.flops import (
    peak_flops_per_chip,
    resnet50_train_flops,
    transformer_train_flops,
)


class TestRegistry:
    def test_counters_gauges_histograms(self):
        reg = MetricsRegistry()
        assert reg.inc("steps") == 1
        assert reg.inc("steps", 4) == 5
        reg.declare_counters("skips", "steps")
        assert reg.counters() == {"steps": 5, "skips": 0}
        reg.set_gauge("loss", 0.25)
        assert reg.gauges()["loss"] == 0.25
        for v in (1.0, 2.0, 3.0, 4.0):
            reg.observe("h", v)
        snap = reg.histogram("h")
        assert snap.count == 4 and snap.sum == 10.0
        assert snap.min == 1.0 and snap.max == 4.0 and snap.mean == 2.5
        assert reg.histogram("missing") is None

    def test_percentile_nearest_rank(self):
        values = list(range(1, 101))  # 1..100
        assert percentile(values, 50) == 50
        assert percentile(values, 95) == 95
        assert percentile(values, 0) == 1
        assert percentile(values, 100) == 100
        assert percentile([7.0], 50) == 7.0
        with pytest.raises(ValueError):
            percentile([], 50)

    def test_events_are_seq_ordered_and_stamped(self):
        mem = InMemorySink()
        reg = MetricsRegistry([mem])
        reg.event("skip", step=3)
        reg.event("rollback", to_step=1)
        events = mem.of_kind("event")
        assert [e["event"] for e in events] == ["skip", "rollback"]
        assert events[0]["seq"] < events[1]["seq"]
        assert events[0]["ts"] <= events[1]["ts"]
        assert all("wall" in e for e in events)
        assert events[0]["step"] == 3

    def test_thread_safety_under_concurrent_emitters(self):
        # the real topology: watchdog thread + step loop both emit
        mem = InMemorySink()
        reg = MetricsRegistry([mem])
        workers, per = 8, 500

        def emit(worker):
            for i in range(per):
                reg.inc("c")
                reg.observe("h", float(i))
                reg.event("tick", worker=worker)

        threads = [threading.Thread(target=emit, args=(w,))
                   for w in range(workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert reg.counters()["c"] == workers * per
        assert reg.histogram("h").count == workers * per
        events = mem.of_kind("event")
        assert len(events) == workers * per
        # seq never duplicated or skipped despite contention
        seqs = sorted(e["seq"] for e in events)
        assert seqs == list(range(1, workers * per + 1))

    def test_histogram_memory_bounded_over_1000_steps(self):
        # acceptance: ring memory does not grow with step count
        reg = MetricsRegistry(histogram_bound=64)
        for i in range(1200):
            reg.observe("step_time_s", float(i))
        snap = reg.histogram("step_time_s")
        assert snap.count == 1200          # exact aggregates kept
        assert snap.max == 1199.0 and snap.min == 0.0
        assert len(snap._recent) == 64     # percentile window stays bounded
        # percentiles reflect the recent window (values 1136..1199)
        assert snap.percentile(50) >= 1136


class TestSinks:
    def test_jsonl_round_trip(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        sink = JsonlSink(path)
        reg = MetricsRegistry([sink])
        reg.inc("steps", 3)
        reg.event("skip", step=1)
        reg.emit_step({"kind": "step", "step": 1, "step_time_s": 0.5})
        reg.flush()
        sink.close()
        kinds = [json.loads(line)["kind"]
                 for line in open(path, encoding="utf-8")]
        assert kinds == ["event", "step", "counters", "gauges",
                         "histograms"]
        counters = [json.loads(line) for line in open(path, encoding="utf-8")
                    if json.loads(line)["kind"] == "counters"]
        assert counters[-1]["values"] == {"steps": 3}

    def test_jsonl_degrades_unserializable_fields(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        sink = JsonlSink(path)
        sink.write({"kind": "event", "event": "odd", "obj": object()})
        sink.close()
        rec = json.loads(open(path, encoding="utf-8").read())
        assert rec["event"] == "odd" and "object" in rec["obj"]

    def test_prometheus_textfile_round_trip(self, tmp_path):
        path = str(tmp_path / "metrics.prom")
        reg = MetricsRegistry([PrometheusTextfileSink(path)])
        reg.inc("steps", 7)
        reg.set_gauge("mfu", 0.4)
        for v in (1.0, 2.0, 3.0):
            reg.observe("step_time_s", v)
        reg.flush()
        text = open(path, encoding="utf-8").read()
        assert "apex_tpu_steps_total 7" in text
        assert "apex_tpu_mfu 0.4" in text
        assert "apex_tpu_step_time_s_count 3" in text
        assert "apex_tpu_step_time_s_sum 6.0" in text
        assert 'quantile="0.50"' in text
        # no torn files: the render is atomic (temp + rename)
        assert not os.path.exists(path + ".tmp")

    def test_prometheus_gauges_round_trip(self, tmp_path):
        """Plain gauges survive the sink round-trip with exact values
        and one TYPE declaration each."""
        path = str(tmp_path / "metrics.prom")
        reg = MetricsRegistry([PrometheusTextfileSink(path)])
        reg.set_gauge("mfu", 0.4)
        reg.set_gauge("kv_pages_free", 12)
        reg.set_gauge("loss_scale", 256.0)
        reg.flush()
        lines = open(path, encoding="utf-8").read().splitlines()
        assert "apex_tpu_mfu 0.4" in lines
        assert "apex_tpu_kv_pages_free 12.0" in lines
        assert "apex_tpu_loss_scale 256.0" in lines
        assert lines.count("# TYPE apex_tpu_mfu gauge") == 1

    def test_prometheus_labeled_gauges_round_trip(self, tmp_path):
        """Fleet-labeled gauges (``name{replica="i"}`` flat keys, what
        FleetMetrics.write_prometheus emits) render as one metric family
        per base name — a single TYPE line followed by every label set —
        and the label block survives name sanitization untouched."""
        sink = PrometheusTextfileSink(str(tmp_path / "metrics.prom"))
        sink.write({"kind": "gauges", "wall": 0.0, "values": {
            "kv_pages_free": 5.0,
            'kv_pages_free{replica="0"}': 2.0,
            'kv_pages_free{replica="1"}': 3.0,
        }})
        sink.flush()
        lines = open(sink.path, encoding="utf-8").read().splitlines()
        assert "apex_tpu_kv_pages_free 5.0" in lines
        assert 'apex_tpu_kv_pages_free{replica="0"} 2.0' in lines
        assert 'apex_tpu_kv_pages_free{replica="1"} 3.0' in lines
        # one TYPE line per family, not per label set
        assert lines.count("# TYPE apex_tpu_kv_pages_free gauge") == 1
        # labeled series sit under their family's TYPE line
        t = lines.index("# TYPE apex_tpu_kv_pages_free gauge")
        assert lines[t + 1].startswith("apex_tpu_kv_pages_free")


class TestFlops:
    def test_transformer_train_flops_hand_computed(self):
        # 6N term: 6 * 1e6 params; attention: 12 * L2 * s8 * d16 = 1536/tok
        got = transformer_train_flops(n_params=1_000_000, tokens=100,
                                      num_layers=2, hidden=16, seq=8,
                                      causal=False)
        assert got == 100 * (6.0 * 1_000_000 + 12 * 2 * 8 * 16)
        causal = transformer_train_flops(n_params=1_000_000, tokens=100,
                                         num_layers=2, hidden=16, seq=8,
                                         causal=True)
        assert causal == 100 * (6.0 * 1_000_000 + 6 * 2 * 8 * 16)

    def test_resnet50_train_flops_hand_computed(self):
        assert resnet50_train_flops(10, 224) == 10 * 3.0 * 4.09e9
        # area scaling: 112px is a quarter of the pixels
        assert resnet50_train_flops(1, 112) == pytest.approx(
            3.0 * 4.09e9 * 0.25)

    def test_peak_flops_unknown_on_cpu(self):
        assert peak_flops_per_chip() is None  # tier-1 runs on CPU

    def test_harness_shares_the_library_estimators(self):
        # satellite: benchmarks/_harness re-exports, not redefines
        from benchmarks import _harness

        assert _harness.transformer_train_flops is transformer_train_flops
        assert _harness.resnet50_train_flops is resnet50_train_flops
        assert _harness.peak_flops_per_chip is peak_flops_per_chip


class TestStepMetrics:
    def _clock(self, dt):
        """Deterministic clock advancing dt per reading."""
        state = {"t": 0.0}

        def clock():
            state["t"] += dt / 2  # begin+end = one dt per step
            return state["t"]

        return clock

    def test_mfu_and_throughput_hand_computed(self):
        mem = InMemorySink()
        reg = MetricsRegistry([mem])
        sm = StepMetrics(reg, tokens_per_step=1000,
                         model_flops_per_step=2e12, peak_flops=8e12,
                         memory_interval_steps=0, clock=self._clock(0.5))
        sm.begin_step()
        sm.end_step(1)
        rec = sm.record_polled(1, loss=0.5, grad_norm=2.0, skipped=False)
        assert rec["step_time_s"] == pytest.approx(0.25)
        assert rec["tokens_per_s"] == pytest.approx(1000 / 0.25)
        # mfu = model_flops / dt / peak = 2e12 / 0.25 / 8e12 = 1.0
        assert rec["mfu"] == pytest.approx(1.0)
        assert rec["model_tflops"] == pytest.approx(8.0)
        assert reg.gauges()["mfu"] == pytest.approx(1.0)
        assert reg.histogram("loss").count == 1
        steps = mem.of_kind("step")
        assert len(steps) == 1 and steps[0]["loss"] == 0.5

    def test_peak_defaults_to_chip_table(self):
        reg = MetricsRegistry()
        sm = StepMetrics(reg, model_flops_per_step=1e12)
        assert sm.peak_flops is None  # CPU: unknown chip, MFU stays unset
        sm.begin_step()
        sm.end_step(1)
        rec = sm.record_polled(1, loss=1.0)
        assert "mfu" not in rec and "model_tflops" in rec

    def test_skipped_steps_stay_out_of_loss_histogram(self):
        reg = MetricsRegistry()
        sm = StepMetrics(reg, memory_interval_steps=0)
        sm.begin_step()
        sm.end_step(1)
        rec = sm.record_polled(1, loss=float("nan"), skipped=True)
        assert rec["skipped"] is True
        assert reg.histogram("loss") is None  # never polluted by NaN

    def test_pending_map_stays_bounded(self):
        # 1200 steps, polled each step: buffered timings never accumulate
        reg = MetricsRegistry(histogram_bound=32)
        sm = StepMetrics(reg, tokens_per_step=10, memory_interval_steps=0,
                         clock=self._clock(0.1))
        for step in range(1, 1201):
            sm.begin_step()
            sm.end_step(step)
            sm.record_polled(step, loss=1.0)
        assert sm._pending == {}
        snap = reg.histogram("step_time_s")
        assert snap.count == 1200 and len(snap._recent) == 32

    def test_step_timer_context(self):
        reg = MetricsRegistry()
        with StepTimer(reg, "data_wait_s") as t:
            pass
        assert t.elapsed >= 0
        assert reg.histogram("data_wait_s").count == 1


class TestTracing:
    def test_span_records_host_duration(self):
        reg = MetricsRegistry()
        with span("fwd", reg):
            jnp.ones((2, 2)) + 1
        snap = reg.histogram("span/fwd_s")
        assert snap is not None and snap.count == 1 and snap.min >= 0

    def test_nvtx_range_without_registry_is_bare_scope(self):
        from apex_tpu.utils.profiling import nvtx_range

        with nvtx_range("legacy"):  # original call shape still works
            pass

    def test_annotate_fn_with_registry(self):
        from apex_tpu.utils.profiling import annotate_fn

        reg = MetricsRegistry()

        @annotate_fn("bwd", registry=reg)
        def f(x):
            return x + 1

        assert f(1) == 2 and f(2) == 3
        assert reg.histogram("span/bwd_s").count == 2

    def test_profiler_capture_schedule(self, tmp_path):
        calls = []
        prof = ProfilerCapture(
            str(tmp_path), every_n_steps=5, capture_steps=2,
            max_captures=2, registry=None,
            start_fn=lambda d: calls.append(("start", d)),
            stop_fn=lambda: calls.append(("stop",)))
        for step in range(1, 21):
            prof.on_step(step)
        # windows [5,7) and [10,12); then the capture budget is spent
        assert [c[0] for c in calls] == ["start", "stop", "start", "stop"]
        assert calls[0][1].endswith("step5_interval")
        assert calls[2][1].endswith("step10_interval")
        assert prof.captures == 2 and not prof.active

    def test_profiler_capture_on_incident(self, tmp_path):
        calls = []
        reg = MetricsRegistry()
        prof = ProfilerCapture(
            str(tmp_path), capture_steps=1, registry=reg,
            start_fn=lambda d: calls.append(d),
            stop_fn=lambda: None)
        prof.on_incident("loss_spike", step=42)
        assert prof.active and calls[0].endswith("step42_loss_spike")
        prof.on_incident("grad_spike", step=43)  # already active: no-op
        assert len(calls) == 1
        prof.on_step(43)  # past the window: auto-stop
        assert not prof.active
        assert reg.counters()["profiler_captures"] == 1


class TestRetraceWatchdogMetrics:
    def test_retraces_emit_counter_and_events(self):
        mem = InMemorySink()
        reg = MetricsRegistry([mem])
        f = jax.jit(lambda x: x * 2)
        wd = RetraceWatchdog(f, budget=None, metrics=reg)
        for n in range(2, 8):  # every call a new shape
            wd(jnp.ones((n,)))
        assert wd.retraces == 5
        assert reg.counters()["retraces"] == 5
        events = [e for e in mem.of_kind("event")
                  if e["event"] == "retrace"]
        assert len(events) == 5
        assert events[-1]["retraces"] == 5

    def test_no_registry_no_emission(self):
        f = jax.jit(lambda x: x + 1)
        wd = RetraceWatchdog(f, budget=None)
        for n in range(2, 5):
            wd(jnp.ones((n,)))
        assert wd.metrics is None and wd.retraces == 2


# ---------------------------------------------------------------------------
# acceptance: fault-injected run -> JSONL -> monitor report reconciliation
# ---------------------------------------------------------------------------

TARGET = jnp.full((4, 4), 0.3)


def _loss_fn(p, batch, rng):
    pred = batch["x"] @ p["w"] + p["b"]
    return jnp.mean((pred - batch["y"]) ** 2)


def _batch_fn(step):
    x = jax.random.normal(jax.random.PRNGKey(step), (8, 4))
    return {"x": x, "y": x @ TARGET}


@pytest.fixture(scope="module")
def fault_run(tmp_path_factory):
    """One fault-injected CPU run with the full sink stack attached;
    shared by the reconciliation/report/CLI assertions below."""
    tmp = tmp_path_factory.mktemp("obsrun")
    jsonl = str(tmp / "run.jsonl")
    prom = str(tmp / "metrics.prom")
    reg = MetricsRegistry([JsonlSink(jsonl), PrometheusTextfileSink(prom)])
    scaler = LossScaler("dynamic", init_scale=2.0 ** 8, scale_window=100)
    opt = FusedSGD(lr=0.05)
    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    step_fn = make_resilient_train_step(_loss_fn, opt, scaler)
    state = make_train_state(params, opt.init(params), scaler.init())
    cfg = ResilienceConfig(
        poll_interval_steps=2, save_interval_steps=4,
        max_consecutive_skips=3, min_history=4, save_backoff_base=0.0,
        handle_sigterm=False, metrics=reg,
        tokens_per_step=32, model_flops_per_step=1e9,
        peak_flops=1e12,  # CPU has no table entry: override for MFU
        memory_stats_interval_steps=5)
    inj = FaultInjector(nan_grad_calls=range(6, 10))
    result = run_training(step_fn, state, _batch_fn, 20,
                          checkpoint_dir=str(tmp / "ckpts"),
                          config=cfg, fault_injector=inj)
    reg.close()
    return {"result": result, "jsonl": jsonl, "prom": prom}


class TestMonitorReconciliation:
    def test_counters_reconcile_exactly_with_telemetry(self, fault_run):
        report = build_report(fault_run["jsonl"])
        assert report["counters"] == fault_run["result"].telemetry
        # the run actually exercised the incident paths
        assert report["counters"]["rollbacks"] == 1
        assert report["counters"]["skips"] >= 3

    def test_step_stats_nonzero(self, fault_run):
        report = build_report(fault_run["jsonl"])
        for key in ("step_time_s", "tokens_per_s", "mfu"):
            stats = report[key]
            assert stats is not None, key
            assert stats["p50"] > 0 and stats["p95"] > 0, key
            assert stats["count"] == fault_run["result"].telemetry["steps"]
        assert report["loss"]["last"] < report["loss"]["first"]

    def test_incident_timeline_orders_skips_and_rollback(self, fault_run):
        report = build_report(fault_run["jsonl"])
        names = [e["event"] for e in report["timeline"]]
        assert "skip" in names and "rollback" in names
        assert "watchdog_verdict" in names
        # verdict precedes its rollback in seq order
        assert names.index("watchdog_verdict") < names.index("rollback")

    def test_rendered_report_mentions_everything(self, fault_run):
        report = build_report(fault_run["jsonl"])
        text = render_report(report)
        for token in ("counters:", "step time", "tokens/s", "mfu",
                      "incident timeline", "rollback"):
            assert token in text, token

    def test_monitor_cli_reconciles(self, fault_run):
        """The acceptance criterion through the real CLI:
        ``python -m apex_tpu.monitor run.jsonl --json``."""
        proc = subprocess.run(
            [sys.executable, "-m", "apex_tpu.monitor",
             fault_run["jsonl"], "--json"],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stderr
        report = json.loads(proc.stdout)
        telemetry = fault_run["result"].telemetry
        assert report["counters"] == {k: int(v) for k, v in
                                      telemetry.items()}
        assert report["mfu"]["p50"] > 0

    def test_monitor_cli_text_mode_and_missing_file(self, fault_run):
        from apex_tpu.monitor import main

        assert main([fault_run["jsonl"]]) == 0
        assert main([fault_run["jsonl"] + ".nope"]) == 2

    def test_prometheus_file_written(self, fault_run):
        text = open(fault_run["prom"], encoding="utf-8").read()
        assert "apex_tpu_steps_total" in text
        assert "apex_tpu_rollbacks_total 1" in text

    def test_report_survives_torn_last_line(self, fault_run, tmp_path):
        torn = tmp_path / "torn.jsonl"
        data = open(fault_run["jsonl"], encoding="utf-8").read()
        torn.write_text(data + '{"kind": "step", "ste')  # killed mid-write
        report = build_report(str(torn))
        assert report["counters"] == fault_run["result"].telemetry


class TestReportBackCompat:
    """Run logs outlive the writers that produced them: the reader must
    fold records missing newer fields into "no data", never raise."""

    FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "data", "pre_pr6_run.jsonl")

    def test_pre_pr6_log_still_renders(self):
        """A committed pre-TTFT-era log (request rows without
        ``ttft_s``/``tpot_s``, a step row without ``step``, a torn last
        line) builds and renders without KeyError."""
        report = build_report(self.FIXTURE)
        req = report["requests"]
        assert req["count"] == 3
        assert req["by_finish_reason"] == {
            "length": 1, "eos": 1, "rejected": 1}
        # the newer stats degrade to no-data instead of raising
        assert req["ttft_s"] is None and req["tpot_s"] is None
        assert req["total_s"]["count"] == 3
        assert report["slo"] is None          # nothing declared, no verdict
        text = render_report(report)
        assert "serving requests" in text
        assert "ttft" in text and "(no data)" in text

    def test_pre_pr6_log_scores_against_external_spec(self, tmp_path,
                                                      capsys):
        """``--slo`` can score an old log — and a TTFT objective FAILS
        on it (no data is never a pass), while reason-based objectives
        still evaluate."""
        report = build_report(self.FIXTURE, slo_spec={
            "ttft_p99_s": 1.0, "goodput": 0.5})
        slo = report["slo"]
        assert slo is not None and not slo["ok"]
        by = {o["name"]: o for o in slo["objectives"]}
        assert by["ttft_p99_s"]["measured"] is None
        assert not by["ttft_p99_s"]["ok"]
        assert by["goodput"]["ok"]            # 2/3 >= 0.5
        # the monitor CLI takes the same spec via --slo (in-process —
        # the monitor's subprocess plumbing is covered elsewhere)
        from apex_tpu.observability.report import main as monitor_main

        spec = tmp_path / "spec.json"
        spec.write_text(json.dumps({"goodput": 0.5}))
        assert monitor_main(
            [self.FIXTURE, "--json", "--slo", str(spec)]) == 0
        cli = json.loads(capsys.readouterr().out)
        assert cli["slo"]["ok"] is True

    PRE_PR7 = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "data", "pre_pr7_run.jsonl")

    def test_pre_pr7_log_without_replica_id_still_renders(self):
        """A committed pre-fleet-era log (PR-6 vintage: ttft/tpot
        present, ``replica_id`` absent, no fleet counters) builds,
        renders with NO fleet section, and scores its embedded SLO —
        the readers tolerate the field's absence end-to-end."""
        report = build_report(self.PRE_PR7)
        req = report["requests"]
        assert req["count"] == 3
        assert req["ttft_s"]["count"] == 2     # newer fields still fold
        # no replica_id on any row, no fleet counters: no fleet section
        assert report["fleet"] is None
        # the embedded scenario SLO scores the old log (goodput 2/3)
        assert report["slo"]["ok"]
        text = render_report(report)
        assert "serving requests" in text
        assert "fleet:" not in text

    def test_mixed_replica_id_rows_fold_by_replica(self, tmp_path):
        """Rows with and without ``replica_id`` coexist (a fleet log
        whose fleet-level sheds carry no replica): the fleet section
        groups the tagged ones and never raises on the untagged."""
        log = tmp_path / "mixed.jsonl"
        rows = [
            {"kind": "request", "request_id": 0, "finish_reason": "length",
             "prompt_len": 4, "new_tokens": 2, "total_s": 0.1, "wall": 1.0,
             "replica_id": 0},
            {"kind": "request", "request_id": 1, "finish_reason": "length",
             "prompt_len": 4, "new_tokens": 2, "total_s": 0.1, "wall": 2.0,
             "replica_id": 1},
            {"kind": "request", "request_id": 2, "finish_reason":
             "rejected", "prompt_len": 4, "new_tokens": 0, "wall": 3.0},
            {"kind": "counters", "wall": 4.0, "values":
             {"fleet_dispatches": 2, "replica0_dispatches": 1,
              "replica1_dispatches": 1}},
        ]
        log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        report = build_report(str(log))
        fleet = report["fleet"]
        assert fleet["requests_by_replica"] == {"0": 1, "1": 1}
        assert fleet["dispatches"]["fleet_dispatches"] == 2
        text = render_report(report)
        assert "dispatches: 2" in text
        assert "replica0=1 replica1=1" in text

    PRE_PR14 = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "data", "pre_pr14_run.jsonl")

    def test_pre_pr14_log_without_spans_still_renders(self):
        """A committed pre-tracing-era log (PR-13 vintage: adapter
        ledger present, NO ``trace_id`` on requests, NO span rows, no
        ``spans_*`` counters, torn last line) builds, renders without a
        tracing section, and still yields per-tenant attribution from
        the ``adapter_id`` request fields alone."""
        report = build_report(self.PRE_PR14)
        assert report["requests"]["count"] == 4
        # no span rows anywhere: the tracing section degrades to absent
        assert report["spans"] is None
        assert report["signals"] is None
        # per-tenant attribution needs only adapter_id on request rows
        by_adapter = report["slo_by_adapter"]
        assert set(by_adapter) == {"0", "1", "base"}
        assert by_adapter["0"]["requests"] == 1
        assert by_adapter["1"]["requests"] == 1
        assert by_adapter["base"]["requests"] == 2
        text = render_report(report)
        assert "per-tenant slo" in text
        assert "request tracing" not in text
        assert "fleet signals" not in text

    def test_pre_pr14_log_span_check_is_vacuous(self):
        """``check_span_conservation`` only examines requests that carry
        a ``trace_id`` — a trace-less log passes vacuously, so the
        loadtest ``--check`` gate cannot fail old logs."""
        from apex_tpu.observability.report import read_records
        from apex_tpu.observability.trace import check_span_conservation

        records = read_records(self.PRE_PR14)
        assert check_span_conservation(records) == []

    def test_pre_pr14_trace_lookup_reports_not_found(self, capsys):
        """``--trace`` on a trace-less log exits 2 with a clear message
        instead of raising."""
        from apex_tpu.observability.report import main as monitor_main

        assert monitor_main([self.PRE_PR14, "--trace", "0"]) == 2
        out = capsys.readouterr()
        assert "no spans" in (out.out + out.err).lower() or \
            "not found" in (out.out + out.err).lower()

    PRE_PR15 = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "data", "pre_pr15_run.jsonl")

    def test_pre_pr15_log_without_chunk_fields_still_renders(self):
        """A committed pre-chunked-prefill log (PR-14 vintage: tracing
        present, single-segment prefill spans, NO ``prefill_chunks``
        request fields, no ``prefill_tokens_per_tick`` histogram, torn
        last line) builds and renders with no chunked-prefill section —
        the new audit line only appears when the counter is non-zero."""
        report = build_report(self.PRE_PR15)
        assert report["requests"]["count"] == 4
        # rows without the field fold to a zero sum, not a KeyError
        assert report["requests"]["prefill_chunks"] == 0
        text = render_report(report)
        assert "chunked prefill" not in text

    def test_pre_pr15_log_span_check_still_conserves(self):
        """Single-segment prefill spans from a pre-chunking engine pass
        the SAME conservation checker the multi-segment timelines do —
        the gate cannot fail old logs."""
        from apex_tpu.observability.report import read_records
        from apex_tpu.observability.trace import check_span_conservation

        records = read_records(self.PRE_PR15)
        assert check_span_conservation(records) == []

    def test_pre_pr15_trace_renders_single_segment(self, capsys):
        """``--trace`` on a pre-chunking timeline renders the familiar
        queued/prefill/decode trio with no chunk annotations."""
        from apex_tpu.observability.report import main as monitor_main

        assert monitor_main([self.PRE_PR15, "--trace", "0"]) == 0
        out = capsys.readouterr().out
        assert "prefill" in out and "chunk=" not in out

    PRE_PR16 = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "data", "pre_pr16_run.jsonl")

    def test_pre_pr16_log_without_autoscale_deploy_still_renders(self):
        """A committed pre-autoscaling log (PR-15 vintage: fleet +
        signals present, NO ``queued_tokens``/``window_s`` signal keys,
        ``goodput_window`` still null-on-idle, no ``kind="autoscale"``
        / ``kind="deploy"`` rows, no ``replica_scale_*``/``deploys_*``
        counters, torn last line) builds and renders with no autoscale
        or deployment section."""
        report = build_report(self.PRE_PR16)
        assert report["requests"]["count"] == 4
        assert report["autoscale"] is None
        assert report["deploys"] is None
        # the old signals snapshot still renders: the new keys are
        # guarded, not assumed
        signals = report["signals"]
        assert signals is not None
        assert "queued_tokens" not in signals
        assert signals["goodput_window"] is None
        text = render_report(report)
        assert "fleet signals" in text
        assert "autoscale decisions" not in text
        assert "deployments (" not in text
        assert "queued_tokens=" not in text

    def test_pre_pr16_fleet_section_still_reconciles(self):
        """The fleet incident reconciliation (drain/rebuild events vs
        their counters) is unchanged by the PR 16 counter additions —
        absent deploy/scale counters read as zero, not as a mismatch."""
        report = build_report(self.PRE_PR16)
        fleet = report["fleet"]
        assert fleet["counts"]["replica_drain"] == 1
        assert fleet["counts"]["replica_rebuild"] == 1
        assert fleet["requests_by_replica"] == {"0": 2, "1": 1}

    def test_pre_pr16_log_span_check_still_conserves(self):
        from apex_tpu.observability.report import read_records
        from apex_tpu.observability.trace import check_span_conservation

        records = read_records(self.PRE_PR16)
        assert check_span_conservation(records) == []

    PRE_PR18 = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "data", "pre_pr18_run.jsonl")

    def test_pre_pr18_log_without_anomaly_bundle_still_renders(self):
        """A committed pre-flight-recorder log (PR-17 vintage: fleet +
        autoscale rows present, NO ``kind="anomaly"`` /
        ``kind="bundle"`` / ``kind="gauge_snapshot"`` rows, no
        ``anomalies_*`` / ``bundles_dumped`` / ``gauge_snapshots``
        counters, torn last line) builds and renders with no drift or
        bundle section — the new sections only appear when their rows
        or counters exist."""
        report = build_report(self.PRE_PR18)
        assert report["requests"]["count"] == 3
        assert report["anomalies"] is None
        assert report["bundles"] is None
        assert report["gauge_trajectory"] == []
        text = render_report(report)
        assert "drift anomalies" not in text
        assert "postmortem bundles" not in text
        assert "signal trajectory" not in text
        # the era's own sections are untouched by the new readers
        assert "autoscale decisions" in text

    def test_pre_pr18_log_span_check_still_conserves(self):
        from apex_tpu.observability.report import read_records
        from apex_tpu.observability.trace import check_span_conservation

        records = read_records(self.PRE_PR18)
        assert check_span_conservation(records) == []


class TestFlightRecorder:
    """The bounded-ring recorder + incident bundle dumper."""

    def _registry_with_recorder(self, **kwargs):
        rec = FlightRecorder(**kwargs)
        reg = MetricsRegistry([rec])
        rec.attach(None, reg)
        return reg, rec

    def test_rings_bounded_o_capacity(self):
        """Memory stays O(capacity) no matter how long the run is —
        the ring length never exceeds maxlen and keeps the NEWEST
        records."""
        reg, rec = self._registry_with_recorder(
            events_capacity=4, records_capacity=3, gauges_capacity=2,
            triggers=frozenset())
        for i in range(50):
            reg.event("tick", i=i)
            reg.emit_record({"kind": "request", "request_id": i})
            reg.emit_record({"kind": "gauge_snapshot", "signals": {},
                             "i": i})
        assert len(rec.events) == 4 and rec.events.maxlen == 4
        assert [e["i"] for e in rec.events] == [46, 47, 48, 49]
        assert len(rec.records) == 3
        assert [r["request_id"] for r in rec.records] == [47, 48, 49]
        assert len(rec.gauge_snapshots) == 2

    def test_incident_event_triggers_exactly_one_dump(self):
        """Any TRIGGER_EVENTS member flowing through the sink dumps a
        bundle; the max_bundles=1 latch makes later incidents no-ops."""
        reg, rec = self._registry_with_recorder(max_bundles=1)
        reg.event("heartbeat")            # not incident-class
        assert rec.bundles == []
        reg.event("engine_restart", replica_id=0)
        assert len(rec.bundles) == 1
        reg.event("engine_restart", replica_id=1)
        reg.event("replica_quarantine", replica_id=1)
        assert len(rec.bundles) == 1      # latched
        assert reg.counters()["bundles_dumped"] == 1
        bundle = rec.bundles[0]
        assert bundle["schema"] == 1
        assert bundle["trigger"]["event"] == "engine_restart"
        # the trigger itself sits inside the ring window it froze
        assert any(e.get("event") == "engine_restart"
                   for e in bundle["events"])

    def test_bundle_dumped_is_not_a_trigger(self):
        """The dump's own co-sited event must never re-trigger a dump
        (and is statically excluded from the trigger table)."""
        assert "bundle_dumped" not in TRIGGER_EVENTS
        reg, rec = self._registry_with_recorder(max_bundles=5)
        reg.event("engine_restart")
        assert len(rec.bundles) == 1      # one incident, one bundle

    def test_bundle_counters_snapshot_precedes_own_increment(self):
        """The bundle freezes the counters as they were AT the incident
        — its own ``bundles_dumped`` increment lands after the
        snapshot."""
        reg, rec = self._registry_with_recorder()
        reg.inc("engine_restarts")
        reg.event("engine_restart")
        bundle = rec.bundles[0]
        assert bundle["counters"]["engine_restarts"] == 1
        assert bundle["counters"]["bundles_dumped"] == 0
        assert reg.counters()["bundles_dumped"] == 1

    def test_bundle_reconciles_key_for_key(self):
        """Dumping follows the reconcile contract: one counter inc
        co-sited with one ``bundle_dumped`` event and one
        ``kind="bundle"`` record."""
        mem = InMemorySink()
        rec = FlightRecorder()
        reg = MetricsRegistry([mem, rec])
        rec.attach(None, reg)
        reg.event("tick_failure")
        events = [e for e in mem.of_kind("event")
                  if e["event"] == "bundle_dumped"]
        records = mem.of_kind("bundle")
        assert len(events) == 1 == len(records)
        assert reg.counters()["bundles_dumped"] == 1
        assert records[0]["trigger"] == "tick_failure"

    def test_bundle_file_is_self_contained_json(self, tmp_path):
        """With a bundle_dir the dump lands as one deterministic-named
        JSON file, loadable with nothing but the stdlib."""
        rec = FlightRecorder(bundle_dir=str(tmp_path),
                             bundle_prefix="myrun")
        reg = MetricsRegistry([rec])
        rec.attach(None, reg)
        reg.emit_record({"kind": "signals",
                         "values": {"queue_depth": 7}})
        reg.event("deploy_rollback")
        path = tmp_path / "myrun-bundle-1.json"
        assert rec.bundle_paths == [str(path)]
        bundle = json.loads(path.read_text())
        assert bundle["kind"] == "flight_bundle"
        assert bundle["trigger"]["event"] == "deploy_rollback"
        assert bundle["signals"] == {"queue_depth": 7}

    def test_dump_never_raises_on_torn_target(self):
        """Postmortem evidence is best-effort: a digest target that
        explodes mid-incident degrades the digest, not the serving
        path."""
        class Torn:
            @property
            def replicas(self):
                raise RuntimeError("mid-rebuild")

        rec = FlightRecorder()
        reg = MetricsRegistry([rec])
        rec.attach(Torn(), reg)
        reg.event("engine_restart")       # must not raise
        assert len(rec.bundles) == 1
        assert rec.bundles[0]["replicas"] == []

    def test_capacity_validation(self):
        with pytest.raises(ValueError):
            FlightRecorder(events_capacity=0)
        with pytest.raises(ValueError):
            FlightRecorder(max_bundles=-1)

    def test_retrace_watchdog_event_triggers_dump(self):
        """Satellite: a real RetraceWatchdog recompile is an
        incident-class trigger — the shape-drift postmortem survives
        even though retrace counters batch."""
        rec = FlightRecorder()
        reg = MetricsRegistry([rec])
        rec.attach(None, reg)
        f = jax.jit(lambda x: x * 2)
        wd = RetraceWatchdog(f, budget=None, metrics=reg)
        wd(jnp.ones((2,)))
        wd(jnp.ones((3,)))       # retrace -> trigger
        assert len(rec.bundles) == 1
        assert rec.bundles[0]["trigger"]["event"] == "retrace"

    def test_trigger_table_covers_every_incident_map(self):
        """LOCK: TRIGGER_EVENTS must be a superset of every key of
        every ``*_INCIDENT_COUNTERS`` map the monitor reconciles —
        the inclusion APX013 re-checks tree-wide."""
        from apex_tpu.observability import report as report_mod

        for name in dir(report_mod):
            if not name.endswith("_INCIDENT_COUNTERS"):
                continue
            for event in getattr(report_mod, name):
                assert event in TRIGGER_EVENTS, (
                    f"{name} key {event!r} missing from TRIGGER_EVENTS")
        assert "retrace" in TRIGGER_EVENTS   # recorder-only extra


class TestDriftSentinel:
    """The pure EWMA/robust-z detector core, then the fleet seam."""

    def _drive(self, sentinel, values, start=0.0, dt=1.0):
        fired = []
        for i, v in enumerate(values):
            fired.extend(sentinel.observe({"queue_depth": v},
                                          start + i * dt))
        return fired

    def test_warmup_gate_holds_fire(self):
        s = DriftSentinel(SentinelConfig(
            warmup_polls=5, hysteresis_polls=1, min_abs_dev=0.5,
            signals=("queue_depth",)))
        # a huge excursion during warmup is baseline-learning, not news
        assert self._drive(s, [0, 0, 100, 0]) == []

    def test_spike_fires_after_hysteresis(self):
        s = DriftSentinel(SentinelConfig(
            warmup_polls=3, hysteresis_polls=2, z_threshold=4.0,
            min_abs_dev=0.5, cooldown_s=100.0,
            signals=("queue_depth",)))
        fired = self._drive(s, [1, 1, 1, 1, 30, 30, 30])
        assert len(fired) == 1            # breach #2 arms it, once
        a = fired[0]
        assert a["signal"] == "queue_depth" and a["value"] == 30.0
        assert a["z"] >= 4.0 and a["baseline"] < 2.0

    def test_single_breach_is_not_an_anomaly(self):
        """hysteresis_polls=2: one outlier poll (a scheduling blip)
        stays quiet."""
        s = DriftSentinel(SentinelConfig(
            warmup_polls=3, hysteresis_polls=2, min_abs_dev=0.5,
            signals=("queue_depth",)))
        assert self._drive(s, [1, 1, 1, 1, 30, 1, 1, 30, 1]) == []

    def test_breaches_do_not_corrupt_baseline(self):
        """Breach values are evidence about the incident, not the
        baseline: after the excursion the baseline still reflects the
        healthy level."""
        s = DriftSentinel(SentinelConfig(
            warmup_polls=3, hysteresis_polls=2, min_abs_dev=0.5,
            cooldown_s=0.0, signals=("queue_depth",)))
        self._drive(s, [1, 1, 1, 1, 30, 30])
        assert s._trackers["queue_depth"].mean < 2.0

    def test_direction_a_good_day_never_fires(self):
        """goodput_window degrades DOWN: a jump above baseline is an
        improvement, not an anomaly."""
        s = DriftSentinel(SentinelConfig(
            warmup_polls=3, hysteresis_polls=1, min_abs_dev=0.01,
            signals=("goodput_window",)))
        fired = []
        for i, v in enumerate([0.5, 0.5, 0.5, 0.5, 1.0, 1.0]):
            fired.extend(s.observe({"goodput_window": v}, float(i)))
        assert fired == []
        # ...while the same magnitude downward fires
        s2 = DriftSentinel(SentinelConfig(
            warmup_polls=3, hysteresis_polls=1, min_abs_dev=0.01,
            signals=("goodput_window",)))
        fired2 = []
        for i, v in enumerate([0.5, 0.5, 0.5, 0.5, 0.0]):
            fired2.extend(s2.observe({"goodput_window": v}, float(i)))
        assert len(fired2) == 1

    def test_cooldown_suppresses_refire(self):
        s = DriftSentinel(SentinelConfig(
            warmup_polls=3, hysteresis_polls=1, z_threshold=4.0,
            min_abs_dev=0.5, cooldown_s=100.0,
            signals=("queue_depth",)))
        fired = self._drive(s, [1, 1, 1, 1, 30, 35, 40, 45])
        assert len(fired) == 1            # one excursion, one anomaly

    def test_none_and_missing_signals_are_skipped(self):
        s = DriftSentinel(SentinelConfig(
            warmup_polls=2, hysteresis_polls=1, min_abs_dev=0.5,
            signals=("queue_depth", "ttft_p99_s")))
        # None (idle window) and absent keys never touch the tracker
        for i in range(6):
            s.observe({"queue_depth": 1.0, "ttft_p99_s": None},
                      float(i))
        assert s._trackers["ttft_p99_s"].samples == 0
        assert s._trackers["queue_depth"].samples == 6

    def test_min_abs_dev_floors_flat_baselines(self):
        """A perfectly flat baseline has dev=0 — without the floor the
        first real wiggle would divide by ~zero and fire on noise."""
        s = DriftSentinel(SentinelConfig(
            warmup_polls=3, hysteresis_polls=1, z_threshold=4.0,
            min_abs_dev=2.0, signals=("queue_depth",)))
        # wiggles of |x - 0| < 2*4 stay under threshold
        assert self._drive(s, [0, 0, 0, 0, 3, 4, 3, 5, 0]) == []

    def test_config_validation(self):
        with pytest.raises(ValueError):
            SentinelConfig(ewma_alpha=0.0)
        with pytest.raises(ValueError):
            SentinelConfig(warmup_polls=0)
        with pytest.raises(ValueError):
            SentinelConfig(signals=())

    class _FakeSupervisor:
        queued_count = 0
        active_count = 0
        queued_prompt_tokens = 0

    class _FakeReplica:
        def __init__(self):
            self.supervisor = TestDriftSentinel._FakeSupervisor()

    class _FakeConfig:
        max_slots = 4

    class _FakeFleet:
        """Duck-typed just far enough for FleetMetrics: the sentinel's
        fleet seam is the interface, not the ReplicaFleet class."""

        def __init__(self, registry):
            self.metrics = registry
            self.replica_metrics = {0: MetricsRegistry()}
            self.replicas = [TestDriftSentinel._FakeReplica()]
            self.config = TestDriftSentinel._FakeConfig()
            self.inflight_count = 0

        def dispatch_set(self):
            return list(self.replicas)

    def test_maybe_poll_declares_and_reconciles_counters(self):
        """The fleet seam: counters declared up front (snapshots carry
        the keys at zero), poll gating by interval, anomaly emission
        co-sited counter+event+record, periodic gauge_snapshot."""
        mem = InMemorySink()
        reg = MetricsRegistry([mem])
        fleet = self._FakeFleet(reg)
        s = DriftSentinel(SentinelConfig(
            poll_interval_s=1.0, warmup_polls=2, hysteresis_polls=1,
            z_threshold=4.0, min_abs_dev=0.5, snapshot_every_polls=2,
            signals=("queue_depth",)))
        assert s.maybe_poll(fleet, 0.0) == []
        counters = reg.counters()
        assert counters["anomalies_total"] == 0
        assert counters["anomalies_queue_depth"] == 0
        assert counters["gauge_snapshots"] == 0
        # inside the interval: gated, no poll consumed
        assert s.maybe_poll(fleet, 0.5) == [] and s.polls == 1
        s.maybe_poll(fleet, 1.0)          # poll 2 -> gauge_snapshot
        assert reg.counters()["gauge_snapshots"] == 1
        snaps = mem.of_kind("gauge_snapshot")
        assert len(snaps) == 1
        assert "queue_depth" in snaps[0]["signals"]
        # now degrade: queue_depth jumps fleet-wide
        self._FakeSupervisor.queued_count = 40
        try:
            fired = s.maybe_poll(fleet, 2.0)
        finally:
            self._FakeSupervisor.queued_count = 0
        assert len(fired) == 1
        counters = reg.counters()
        assert counters["anomalies_total"] == 1
        assert counters["anomalies_queue_depth"] == 1
        events = [e for e in mem.of_kind("event")
                  if e["event"] == "anomaly"]
        records = mem.of_kind("anomaly")
        assert len(events) == 1 == len(records)
        assert records[0]["signal"] == "queue_depth"


class TestBundleRendering:
    """``python -m apex_tpu.monitor bundle <path>`` — the postmortem
    reader."""

    def _dump_bundle(self, tmp_path):
        rec = FlightRecorder(bundle_dir=str(tmp_path),
                             bundle_prefix="t")
        reg = MetricsRegistry([rec])
        rec.attach(None, reg)
        reg.emit_record({"kind": "gauge_snapshot", "wall": 1.0,
                         "signals": {"queue_depth": 0,
                                     "ttft_p99_s": 0.1}})
        reg.emit_record({"kind": "gauge_snapshot", "wall": 2.0,
                         "signals": {"queue_depth": 9,
                                     "ttft_p99_s": 0.4}})
        reg.emit_record({"kind": "request", "request_id": 0,
                         "wall": 2.5})
        reg.event("anomaly", signal="queue_depth", value=9.0, z=5.0)
        return rec.bundle_paths[0]

    def test_render_marks_trigger_inside_timeline(self, tmp_path):
        from apex_tpu.observability.report import render_bundle

        path = self._dump_bundle(tmp_path)
        text = render_bundle(json.loads(open(path).read()))
        assert "postmortem bundle" in text
        assert "trigger: anomaly" in text
        # the trigger row is matched in the merged ring timeline
        assert ">>" in text
        assert "queue_depth" in text and "0 -> 9" in text

    def test_monitor_bundle_cli_human_and_json(self, tmp_path, capsys):
        from apex_tpu.observability.report import main as monitor_main

        path = self._dump_bundle(tmp_path)
        assert monitor_main(["bundle", path]) == 0
        human = capsys.readouterr().out
        assert "trigger: anomaly" in human
        assert monitor_main(["bundle", path, "--json"]) == 0
        bundle = json.loads(capsys.readouterr().out)
        assert bundle["kind"] == "flight_bundle"
        assert bundle["trigger"]["event"] == "anomaly"

    def test_monitor_bundle_cli_bad_path_exits_2(self, tmp_path):
        from apex_tpu.observability.report import main as monitor_main

        assert monitor_main(["bundle",
                             str(tmp_path / "missing.json")]) == 2
        torn = tmp_path / "torn.json"
        torn.write_text("{not json")
        assert monitor_main(["bundle", str(torn)]) == 2


class TestLabeledHistogramExport:
    """FleetMetrics' labeled histograms through the Prometheus sink:
    one TYPE line per family, per-replica label splits, quantiles
    folded into the label block."""

    def test_one_type_line_per_family_with_label_splits(self, tmp_path):
        path = tmp_path / "prom.txt"
        sink = PrometheusTextfileSink(str(path))
        summ = {"count": 3, "sum": 0.6, "p50": 0.2, "p95": 0.3}
        sink.write({"kind": "histograms", "values": {
            "request_ttft_s": dict(summ),
            'request_ttft_s{replica="0"}': dict(summ),
            'request_ttft_s{replica="1"}': dict(summ)}})
        sink.flush()
        text = path.read_text()
        assert text.count("# TYPE apex_tpu_request_ttft_s summary") == 1
        assert "apex_tpu_request_ttft_s_count 3" in text
        assert 'apex_tpu_request_ttft_s_count{replica="0"} 3' in text
        assert 'apex_tpu_request_ttft_s_sum{replica="1"} 0.6' in text
        # quantile merged into the replica label block, not appended
        assert ('apex_tpu_request_ttft_s{replica="0",quantile="0.50"} '
                "0.2") in text
        assert 'apex_tpu_request_ttft_s{quantile="0.95"} 0.3' in text
