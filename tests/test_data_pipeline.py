"""End-to-end input-pipeline suite: on-disk shards through the C++-queue
prefetch loader into a jitted train step — the role the reference's imagenet
example gives DALI / torch DataLoader (``examples/imagenet/main_amp.py``).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.data import (
    PrefetchLoader,
    disk_image_batches,
    make_input_pipeline,
    write_synthetic_imagenet,
)


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    root = tmp_path_factory.mktemp("imagenet")
    return write_synthetic_imagenet(
        str(root), num_shards=3, per_shard=32, image_size=20,
        num_classes=10, seed=0)


class TestDiskBatches:
    def test_shapes_normalization_epochs(self, dataset):
        batches = list(disk_image_batches(dataset, 16, epochs=1))
        assert len(batches) == 96 // 16
        imgs, labs = batches[0]
        assert imgs.shape == (16, 20, 20, 3) and imgs.dtype == np.float32
        assert labs.shape == (16,) and labs.dtype == np.int32
        # normalized: roughly zero-mean, not uint8 range
        assert abs(float(imgs.mean())) < 1.0
        assert float(np.abs(imgs).max()) < 10.0

    def test_crop(self, dataset):
        imgs, _ = next(iter(disk_image_batches(dataset, 8, crop=16,
                                               epochs=1)))
        assert imgs.shape == (8, 16, 16, 3)

    def test_shuffle_differs_across_epochs(self, dataset):
        two = disk_image_batches(dataset, 96, epochs=2, train=True)
        e1 = next(two)[1]
        e2 = next(two)[1]
        assert not np.array_equal(e1, e2)           # order reshuffled
        assert np.array_equal(np.sort(e1), np.sort(e2))  # same multiset

    def test_eval_mode_deterministic(self, dataset):
        a = next(iter(disk_image_batches(dataset, 32, train=False,
                                         epochs=1)))
        b = next(iter(disk_image_batches(dataset, 32, train=False,
                                         epochs=1)))
        np.testing.assert_array_equal(a[0], b[0])


class TestPipelineEndToEnd:
    @pytest.mark.slow
    def test_loader_feeds_jitted_train_step(self, dataset):
        """The full path: disk -> workers -> C++ queue -> device_put ->
        jitted step; loss finite and descending over one pass."""
        from apex_tpu.models import ResNet, ResNetConfig
        from apex_tpu.optimizers import FusedSGD

        model = ResNet(ResNetConfig(depth=18, num_classes=10, width=8))
        params, bn = model.init(jax.random.PRNGKey(0))
        opt = FusedSGD(lr=0.05, momentum=0.9)
        ostate = opt.init(params)

        @jax.jit
        def train_step(params, bn, ostate, images, labels):
            def loss_fn(p):
                logits, new_bn = model.apply(p, bn, images, train=True)
                logp = jax.nn.log_softmax(logits)
                n = labels.shape[0]
                return -jnp.mean(logp[jnp.arange(n), labels]), new_bn

            (loss, new_bn), g = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            params, ostate = opt.step(g, params, ostate)
            return params, new_bn, ostate, loss

        loader = make_input_pipeline(dataset, 16, crop=16, epochs=2,
                                     prefetch=2, num_workers=2)
        losses = []
        n_batches = 0
        for images, labels in loader:
            assert isinstance(images, jax.Array)   # device_put happened
            params, bn, ostate, loss = train_step(
                params, bn, ostate, images, labels)
            losses.append(float(loss))
            n_batches += 1
        assert n_batches == 2 * (96 // 16)
        # the pipeline contract is data flow, not optimization: every batch
        # reached the device and produced a finite loss
        assert np.isfinite(losses).all()

    def test_worker_exception_surfaces(self):
        def bad():
            yield np.zeros((2, 2))
            raise RuntimeError("shard corrupted")

        loader = PrefetchLoader(bad, prefetch=2, num_workers=1)
        with pytest.raises(RuntimeError, match="shard corrupted"):
            list(loader)


class TestReviewRegressions:
    def test_eval_mode_center_crops(self, dataset):
        imgs, _ = next(iter(disk_image_batches(dataset, 8, crop=16,
                                               train=False, epochs=1)))
        assert imgs.shape == (8, 16, 16, 3)

    def test_meta_mismatch_rejected(self, dataset):
        with pytest.raises(ValueError, match="was written with"):
            write_synthetic_imagenet(dataset, num_shards=3, per_shard=32,
                                     image_size=28, num_classes=10)

    def test_parallel_workers_deterministic_multiset(self, dataset):
        """Augmentation rng is keyed by the batch counter, so worker
        scheduling cannot change the realized batches (only their order)."""
        def collect(workers):
            loader = make_input_pipeline(dataset, 16, crop=16, epochs=1,
                                         num_workers=workers, seed=3)
            out = {}
            for imgs, labs in loader:
                out[float(np.asarray(imgs).sum())] = np.asarray(labs).sum()
            return out

        assert collect(1) == collect(3)

    def test_worker_error_surfaces_with_infinite_source(self):
        """map_fn failure must raise promptly even when the source never
        ends (another worker keeps the queue alive forever otherwise)."""
        def infinite():
            i = 0
            while True:
                yield i
                i += 1

        def boom(i):
            if i == 3:
                raise RuntimeError("corrupted shard 3")
            return np.zeros((2,))

        loader = PrefetchLoader(infinite, prefetch=2, num_workers=2,
                                map_fn=boom)
        with pytest.raises(RuntimeError, match="corrupted shard 3"):
            for n, _ in enumerate(loader):
                assert n < 100   # must fail long before this
