"""ZeRO-sharded optimizer + distributed checkpoint suite.

Mirrors the reference's ``apex/contrib/test/optimizers/test_dist_adam.py``
(DistributedFusedAdam vs plain Adam parity) and the checkpoint round-trip
flows of ``apex/amp`` state_dict + ``DistributedFusedAdam`` sharded
state_dict (SURVEY.md §5 checkpoint/resume).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

os.environ.setdefault("APEX_TPU_FORCE_PALLAS", "interpret")

from apex_tpu.optimizers import DistributedFusedAdam, FusedAdam  # noqa: E402
from apex_tpu.training import make_train_step  # noqa: E402
from apex_tpu.transformer import parallel_state  # noqa: E402


def _params(key=0):
    k = jax.random.PRNGKey(key)
    k1, k2, k3 = jax.random.split(k, 3)
    return {
        "w1": jax.random.normal(k1, (16, 33)),   # odd sizes force padding
        "b1": jax.random.normal(k2, (33,)),
        "w2": jax.random.normal(k3, (33, 4)),
    }


def _grads(key=9):
    return jax.tree.map(
        lambda x: jax.random.normal(jax.random.fold_in(
            jax.random.PRNGKey(key), x.size), x.shape), _params())


class TestDistributedFusedAdamSingle:
    def test_matches_fused_adam_unsharded(self):
        parallel_state.destroy_model_parallel()
        params = _params()
        grads = _grads()
        ref = FusedAdam(lr=1e-2, weight_decay=0.01)
        dist = DistributedFusedAdam(lr=1e-2, weight_decay=0.01, num_shards=1)
        rstate, dstate = ref.init(params), dist.init(params)
        p_ref, p_dist = params, params
        for _ in range(3):
            p_ref, rstate = ref.step(grads, p_ref, rstate)
            p_dist, dstate = dist.step(grads, p_dist, dstate)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5,
                                                    atol=1e-6),
            p_ref, p_dist)

    def test_found_inf_skips_update(self):
        params = _params()
        grads = _grads()
        dist = DistributedFusedAdam(lr=1e-2, num_shards=1)
        state = dist.init(params)
        new_p, new_state = dist.step(grads, params, state,
                                     found_inf=jnp.asarray(True))
        jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b),
                     new_p, params)
        assert int(new_state["step"]) == 0

    def test_grad_scale_unscales(self):
        params = _params()
        grads = _grads()
        dist = DistributedFusedAdam(lr=1e-2, num_shards=1)
        s1 = dist.init(params)
        p1, _ = dist.step(grads, params, s1)
        scaled = jax.tree.map(lambda g: g * 512.0, grads)
        s2 = dist.init(params)
        p2, _ = dist.step(scaled, params, s2,
                          grad_scale=jnp.asarray(512.0))
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5,
                                                    atol=1e-6), p1, p2)


class TestDistributedFusedAdamSharded:
    """ZeRO path on an 8-device mesh must match replicated FusedAdam."""

    def _train(self, optimizer, tp=1, steps=4):
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=tp)
        params = _params()
        # simple per-rank model: tp shards w2 columns
        param_spec = {"w1": P(), "b1": P(),
                      "w2": P(None, "tensor") if tp > 1 else P()}

        def loss_fn(p, batch, rng):
            h = jnp.tanh(batch["x"] @ p["w1"] + p["b1"])
            out = h @ p["w2"]
            if tp > 1:
                out = jax.lax.all_gather(out, "tensor", axis=1, tiled=True)
            return jnp.mean((out - batch["y"]) ** 2)

        if isinstance(optimizer, DistributedFusedAdam):
            opt_state = optimizer.init(params, param_spec)
        else:
            opt_state = optimizer.init(params)
        step = make_train_step(
            loss_fn, optimizer, mesh, param_spec,
            {"x": P("data"), "y": P("data")},
            opt_state_spec=optimizer.state_spec(params, param_spec))
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 16))
        y = jax.random.normal(jax.random.PRNGKey(2), (16, 4))
        p, s = params, opt_state
        losses = []
        for _ in range(steps):
            p, s, loss = step(p, s, {"x": x, "y": y}, None)
            losses.append(float(loss))
        parallel_state.destroy_model_parallel()
        return losses, jax.device_get(p), s

    def test_zero_matches_replicated_adam(self):
        ref_losses, ref_p, _ = self._train(FusedAdam(lr=1e-2))
        z_losses, z_p, z_s = self._train(
            DistributedFusedAdam(lr=1e-2, num_shards=8))
        np.testing.assert_allclose(z_losses, ref_losses, rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4,
                                                    atol=1e-6),
            z_p, ref_p)
        # state is genuinely sharded: leading dim = dp shards
        assert z_s["master"].shape[0] == 8

    def test_zero_with_tensor_parallel(self):
        ref_losses, _, _ = self._train(FusedAdam(lr=1e-2), tp=2)
        z_losses, _, z_s = self._train(
            DistributedFusedAdam(lr=1e-2, num_shards=4), tp=2)
        np.testing.assert_allclose(z_losses, ref_losses, rtol=1e-5)
        assert z_s["master"].shape[0] == 4  # dp shards

    def test_weight_decay_mask_matches_per_leaf(self):
        # biases excluded from decay, exactly as torch param-groups would
        mask = {"w1": True, "b1": False, "w2": True}
        ref_losses, ref_p, _ = self._train(
            FusedAdam(lr=1e-2, weight_decay=0.1, weight_decay_mask=mask))
        z_losses, z_p, _ = self._train(
            DistributedFusedAdam(lr=1e-2, weight_decay=0.1,
                                 weight_decay_mask=mask, num_shards=8))
        np.testing.assert_allclose(z_losses, ref_losses, rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4,
                                                    atol=1e-6),
            z_p, ref_p)


class TestGatherDtypeAndRemainders:
    """Reduced-precision param all-gather + bf16-remainder master storage
    (reference ``distributed_fused_lamb.py:105,340`` fp16/e5m2 gather,
    ``distributed_fused_adam.py:251-267`` store_param_remainders)."""

    def _train_bf16(self, optimizer, steps=100):
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel()
        params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), _params())
        spec = {"w1": P(), "b1": P(), "w2": P()}

        def loss_fn(p, batch, rng):
            p32 = jax.tree.map(lambda x: x.astype(jnp.float32), p)
            h = jnp.tanh(batch["x"] @ p32["w1"] + p32["b1"])
            return jnp.mean((h @ p32["w2"] - batch["y"]) ** 2)

        opt_state = optimizer.init(params, spec)
        step = make_train_step(
            loss_fn, optimizer, mesh, spec,
            {"x": P("data"), "y": P("data")},
            opt_state_spec=optimizer.state_spec(params, spec))
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 16))
        y = jax.random.normal(jax.random.PRNGKey(2), (16, 4))
        p, s = params, opt_state
        losses = []
        for _ in range(steps):
            p, s, loss = step(p, s, {"x": x, "y": y}, None)
            losses.append(float(loss))
        parallel_state.destroy_model_parallel()
        return losses, jax.device_get(p), s

    def test_bf16_gather_matches_fp32_gather(self):
        """Auto gather dtype (bf16 for all-bf16 params) is LOSSLESS vs an
        explicit fp32 gather: the gathered values are cast to the leaf
        dtype anyway, and the cast commutes with all_gather."""
        a_losses, a_p, _ = self._train_bf16(
            DistributedFusedAdam(lr=1e-2, num_shards=8))
        b_losses, b_p, _ = self._train_bf16(
            DistributedFusedAdam(lr=1e-2, num_shards=8,
                                 gather_dtype=jnp.float32))
        np.testing.assert_allclose(a_losses, b_losses, rtol=1e-6)
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(
            np.asarray(a, np.float32), np.asarray(b, np.float32)), a_p, b_p)

    def test_fp32_params_default_to_fp32_gather(self):
        opt = DistributedFusedAdam(lr=1e-2, num_shards=8)
        assert opt._resolve_gather_dtype(_params()) == jnp.float32
        bf16 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), _params())
        assert opt._resolve_gather_dtype(bf16) == jnp.bfloat16
        mixed = dict(bf16, w1=_params()["w1"])
        assert opt._resolve_gather_dtype(mixed) == jnp.float32

    def test_store_param_remainders_matches_master_mode(self):
        """(bf16 image + int16 remainder) storage follows the fp32-master
        trajectory; differences are bounded by round-half-up vs
        round-nearest-even 1-ulp ties in the gathered image."""
        a_losses, _, a_s = self._train_bf16(
            DistributedFusedAdam(lr=1e-2, num_shards=8))
        b_losses, _, b_s = self._train_bf16(
            DistributedFusedAdam(lr=1e-2, num_shards=8,
                                 store_param_remainders=True))
        np.testing.assert_allclose(a_losses, b_losses, rtol=2e-2, atol=1e-4)
        assert "master" not in b_s
        assert b_s["master_rem"].dtype == jnp.int16
        # reconstruction is exact: master == image<<16 + remainder
        opt = DistributedFusedAdam(lr=1e-2, num_shards=1)
        m = jnp.asarray([1.0000123, -3.5e-4, 2.75, 0.0, 1e30], jnp.float32)
        img, rem = opt._remainder_split(m)
        np.testing.assert_array_equal(
            np.asarray(opt._master_from_remainder(
                img.astype(jnp.float32), rem)), np.asarray(m))

    def test_remainders_reject_non_bf16(self):
        opt = DistributedFusedAdam(lr=1e-2, num_shards=1,
                                   store_param_remainders=True)
        with pytest.raises(ValueError, match="bfloat16"):
            opt.init(_params())

    def test_e5m2_gather_converges(self):
        """The reference's e5m2_allgather analog: lossy, but training still
        converges on the toy problem."""
        losses, _, _ = self._train_bf16(
            DistributedFusedAdam(lr=1e-2, num_shards=8,
                                 gather_dtype=jnp.float8_e5m2), steps=60)
        assert losses[-1] < losses[0] * 0.5


class TestCheckpoint:
    def test_roundtrip(self, tmp_path):
        from apex_tpu.checkpoint import load_checkpoint, save_checkpoint

        state = {
            "params": _params(),
            "opt": {"step": jnp.asarray(7, jnp.int32),
                    "m": jax.tree.map(jnp.zeros_like, _params())},
            "scaler": {"loss_scale": jnp.asarray(2.0 ** 16)},
        }
        path = tmp_path / "ckpt1"
        save_checkpoint(str(path), state)
        restored = load_checkpoint(str(path), template=state)
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                     state, restored)

    def test_roundtrip_sharded(self, tmp_path):
        from apex_tpu.checkpoint import load_checkpoint, save_checkpoint

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel()
        from jax.sharding import NamedSharding

        sharding = NamedSharding(mesh, P("data"))
        arr = jax.device_put(jnp.arange(64, dtype=jnp.float32), sharding)
        state = {"master": arr, "step": jnp.asarray(3)}
        path = tmp_path / "ckpt2"
        save_checkpoint(str(path), state)
        restored = load_checkpoint(str(path), template=state)
        np.testing.assert_array_equal(
            np.asarray(restored["master"]), np.arange(64, dtype=np.float32))
        assert restored["master"].sharding == sharding
        parallel_state.destroy_model_parallel()

    def test_manager_rotation_and_resume(self, tmp_path):
        from apex_tpu.checkpoint import CheckpointManager

        state = {"w": jnp.zeros((4,))}
        mgr = CheckpointManager(str(tmp_path / "run"), max_to_keep=2)
        for step in range(3):
            mgr.save(step, {"w": jnp.full((4,), float(step))})
        mgr.wait_until_finished()
        assert mgr.latest_step() == 2
        step, restored = mgr.restore(state)
        assert step == 2
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.full((4,), 2.0))
        mgr.close()


class TestBatchSamplers:
    def test_pretraining_sampler_shards_and_resumes(self):
        from apex_tpu.transformer._data import MegatronPretrainingSampler

        s0 = list(MegatronPretrainingSampler(
            total_samples=32, consumed_samples=0, micro_batch_size=2,
            data_parallel_rank=0, data_parallel_size=2))
        s1 = list(MegatronPretrainingSampler(
            total_samples=32, consumed_samples=0, micro_batch_size=2,
            data_parallel_rank=1, data_parallel_size=2))
        assert s0[0] == [0, 1] and s1[0] == [2, 3]
        # disjoint coverage
        flat = sorted(i for b in s0 + s1 for i in b)
        assert flat == list(range(32))
        # resume at consumed_samples=8 continues exactly
        resumed = list(MegatronPretrainingSampler(
            total_samples=32, consumed_samples=8, micro_batch_size=2,
            data_parallel_rank=0, data_parallel_size=2))
        assert resumed == s0[2:]

    def test_random_sampler_resumable(self):
        from apex_tpu.transformer._data import (
            MegatronPretrainingRandomSampler,
        )

        full = list(MegatronPretrainingRandomSampler(
            total_samples=32, consumed_samples=0, micro_batch_size=2,
            data_parallel_rank=0, data_parallel_size=2))
        resumed = list(MegatronPretrainingRandomSampler(
            total_samples=32, consumed_samples=8, micro_batch_size=2,
            data_parallel_rank=0, data_parallel_size=2))
        # resuming skips exactly consumed/dp per-rank samples
        assert resumed == full[2:]
        # ranks see disjoint index ranges
        r1 = list(MegatronPretrainingRandomSampler(
            total_samples=32, consumed_samples=0, micro_batch_size=2,
            data_parallel_rank=1, data_parallel_size=2))
        flat0 = {i for b in full for i in b}
        flat1 = {i for b in r1 for i in b}
        assert not (flat0 & flat1)

    def test_random_sampler_multi_epoch_and_dropped_tail(self):
        from apex_tpu.transformer._data import (
            MegatronPretrainingRandomSampler,
        )

        # total=34, global batch 4: tail of 2 dropped, active epoch = 32.
        # Resume exactly at the epoch boundary must start epoch 1, not an
        # empty iterator; resume past one epoch must also work.
        for consumed in (32, 40):
            resumed = list(MegatronPretrainingRandomSampler(
                total_samples=34, consumed_samples=consumed,
                micro_batch_size=2, data_parallel_rank=0,
                data_parallel_size=2))
            assert len(resumed) == (32 - consumed % 32) // 4
            assert all(len(b) == 2 for b in resumed)


class TestDistributedFusedLAMB:
    """ZeRO LAMB: trust ratios computed from cross-shard segment norms must
    reproduce the unsharded FusedLAMB exactly (reference
    ``apex/contrib/test/optimizers/test_dist_lamb.py`` strategy)."""

    def test_matches_fused_lamb_unsharded(self):
        from apex_tpu.optimizers import DistributedFusedLAMB, FusedLAMB

        parallel_state.destroy_model_parallel()
        params = _params()
        grads = _grads()
        ref = FusedLAMB(lr=1e-2, weight_decay=0.01)
        dist = DistributedFusedLAMB(lr=1e-2, weight_decay=0.01, num_shards=1)
        rstate, dstate = ref.init(params), dist.init(params)
        p_ref, p_dist = params, params
        for _ in range(3):
            p_ref, rstate = ref.step(grads, p_ref, rstate)
            p_dist, dstate = dist.step(grads, p_dist, dstate)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5,
                                                    atol=1e-6),
            p_ref, p_dist)

    def test_zero_lamb_matches_replicated(self):
        from apex_tpu.optimizers import DistributedFusedLAMB, FusedLAMB

        harness = TestDistributedFusedAdamSharded()
        ref_losses, ref_p, _ = harness._train(FusedLAMB(lr=1e-2))
        z_losses, z_p, z_s = harness._train(
            DistributedFusedLAMB(lr=1e-2, num_shards=8))
        np.testing.assert_allclose(z_losses, ref_losses, rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4,
                                                    atol=1e-6),
            z_p, ref_p)
        assert z_s["master"].shape[0] == 8

    def test_weight_decay_mask_matches_per_leaf(self):
        from apex_tpu.optimizers import DistributedFusedLAMB, FusedLAMB

        mask = {"w1": True, "b1": False, "w2": True}
        harness = TestDistributedFusedAdamSharded()
        ref_losses, ref_p, _ = harness._train(
            FusedLAMB(lr=1e-2, weight_decay=0.1, weight_decay_mask=mask))
        z_losses, z_p, _ = harness._train(
            DistributedFusedLAMB(lr=1e-2, weight_decay=0.1,
                                 weight_decay_mask=mask, num_shards=8))
        np.testing.assert_allclose(z_losses, ref_losses, rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4,
                                                    atol=1e-6),
            z_p, ref_p)

    def test_no_decay_no_adapt_matches_adam_shape(self):
        from apex_tpu.optimizers import DistributedFusedLAMB

        parallel_state.destroy_model_parallel()
        params = _params()
        grads = _grads()
        opt = DistributedFusedLAMB(lr=1e-2, weight_decay=0.0, num_shards=1)
        state = opt.init(params)
        new_p, new_state = opt.step(grads, params, state)
        assert int(new_state["step"]) == 1
        changed = jax.tree.map(
            lambda a, b: bool(np.any(np.asarray(a) != np.asarray(b))),
            new_p, params)
        assert all(jax.tree.leaves(changed))

    def test_found_inf_skips(self):
        from apex_tpu.optimizers import DistributedFusedLAMB

        parallel_state.destroy_model_parallel()
        params = _params()
        grads = _grads()
        opt = DistributedFusedLAMB(lr=1e-2, num_shards=1)
        state = opt.init(params)
        new_p, new_state = opt.step(grads, params, state,
                                    found_inf=jnp.asarray(True))
        jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b),
                     new_p, params)
        assert int(new_state["step"]) == 0
