"""Cross-attention / encoder-decoder layer tests.

Reference: ``standalone_transformer_lm.py`` ``ParallelAttention`` cross_attn
branch and decoder ``ParallelTransformerLayer`` (inter_attention ~:1090-1115);
the reference exercises them through ``ModelType.encoder_and_decoder``
pipeline tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.transformer import (
    ParallelAttention,
    ParallelTransformer,
    ParallelTransformerLayer,
    TransformerConfig,
)
from apex_tpu.transformer.enums import AttnMaskType, AttnType, LayerType
from apex_tpu.utils.sharding import shard_map


def _cfg(**kw):
    d = dict(num_layers=2, hidden_size=32, num_attention_heads=4,
             hidden_dropout=0.0, attention_dropout=0.0,
             attn_mask_type=AttnMaskType.causal)
    d.update(kw)
    return TransformerConfig(**d)


class TestCrossAttention:
    def test_shapes(self):
        attn = ParallelAttention(_cfg(), attn_type=AttnType.cross_attn)
        params = attn.init(jax.random.PRNGKey(0))
        assert set(params) == {"query", "key_value", "dense"}
        dec = jax.random.normal(jax.random.PRNGKey(1), (6, 2, 32))
        enc = jax.random.normal(jax.random.PRNGKey(2), (9, 2, 32))
        out = attn.apply(params, dec, encoder_output=enc)
        assert out.shape == (6, 2, 32)

    def test_requires_encoder_output(self):
        attn = ParallelAttention(_cfg(), attn_type=AttnType.cross_attn)
        params = attn.init(jax.random.PRNGKey(0))
        dec = jax.random.normal(jax.random.PRNGKey(1), (6, 2, 32))
        with pytest.raises(ValueError):
            attn.apply(params, dec)

    def test_not_causal_across_encoder(self):
        """Cross-attention must see the WHOLE encoder sequence: changing a
        late encoder position must affect an early decoder position (a
        causal mask would forbid that)."""
        attn = ParallelAttention(_cfg(), attn_type=AttnType.cross_attn)
        params = attn.init(jax.random.PRNGKey(0))
        dec = jax.random.normal(jax.random.PRNGKey(1), (4, 1, 32))
        enc = jax.random.normal(jax.random.PRNGKey(2), (8, 1, 32))
        out1 = attn.apply(params, dec, encoder_output=enc)
        enc2 = enc.at[-1].add(1.0)
        out2 = attn.apply(params, dec, encoder_output=enc2)
        delta = np.abs(np.asarray(out1 - out2))[0]   # first decoder pos
        assert delta.max() > 1e-6

    def test_encoder_padding_mask(self):
        """Masked encoder positions must not influence the output."""
        attn = ParallelAttention(_cfg(), attn_type=AttnType.cross_attn)
        params = attn.init(jax.random.PRNGKey(0))
        dec = jax.random.normal(jax.random.PRNGKey(1), (4, 1, 32))
        enc = jax.random.normal(jax.random.PRNGKey(2), (8, 1, 32))
        # True = masked out; mask the last 3 encoder positions
        mask = jnp.zeros((1, 1, 4, 8), bool).at[..., 5:].set(True)
        out1 = attn.apply(params, dec, encoder_output=enc,
                          attention_mask=mask)
        enc2 = enc.at[6].add(10.0)
        out2 = attn.apply(params, dec, encoder_output=enc2,
                          attention_mask=mask)
        np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                                   atol=1e-6)


class TestDecoderLayer:
    def test_decoder_layer_params_and_apply(self):
        layer = ParallelTransformerLayer(_cfg(), LayerType.decoder)
        params = layer.init(jax.random.PRNGKey(0))
        assert "inter_attention" in params
        assert "post_inter_attention_layernorm" in params
        dec = jax.random.normal(jax.random.PRNGKey(1), (6, 2, 32))
        enc = jax.random.normal(jax.random.PRNGKey(2), (9, 2, 32))
        out = layer.apply(params, dec, encoder_output=enc)
        assert out.shape == (6, 2, 32)
        assert np.isfinite(np.asarray(out)).all()

    def test_encoder_layer_unchanged(self):
        layer = ParallelTransformerLayer(_cfg())
        params = layer.init(jax.random.PRNGKey(0))
        assert "inter_attention" not in params

    @pytest.mark.slow
    def test_decoder_stack_grads(self):
        model = ParallelTransformer(_cfg(), LayerType.decoder)
        params = model.init(jax.random.PRNGKey(0))
        dec = jax.random.normal(jax.random.PRNGKey(1), (6, 2, 32))
        enc = jax.random.normal(jax.random.PRNGKey(2), (9, 2, 32))

        def loss(p, enc):
            out = model.apply(p, dec, encoder_output=enc)
            return jnp.mean(out ** 2)

        g_params = jax.grad(loss)(params, enc)
        g_enc = jax.grad(loss, argnums=1)(params, enc)
        total = sum(float(jnp.sum(jnp.abs(l)))
                    for l in jax.tree.leaves(g_params))
        assert np.isfinite(total) and total > 0
        # encoder gradient flows through cross-attention
        assert float(jnp.sum(jnp.abs(g_enc))) > 0

    def test_decoder_with_recompute(self):
        model = ParallelTransformer(_cfg(recompute=True), LayerType.decoder)
        params = model.init(jax.random.PRNGKey(0))
        dec = jax.random.normal(jax.random.PRNGKey(1), (6, 2, 32))
        enc = jax.random.normal(jax.random.PRNGKey(2), (9, 2, 32))
        out = jax.jit(lambda p, d, e: model.apply(p, d, encoder_output=e))(
            params, dec, enc)
        assert np.isfinite(np.asarray(out)).all()


class TestEncoderDecoderModel:
    def _model(self, **kw):
        from apex_tpu.models import EncoderDecoderModel

        cfg = _cfg(vocab_size=64, max_position_embeddings=32, **kw)
        return EncoderDecoderModel(cfg)

    @pytest.mark.slow  # compile-bound mode sweep: slow tier (ROADMAP)

    def test_loss_and_logits_modes(self):
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        enc = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 64)
        dec = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, 64)
        labels = jax.random.randint(jax.random.PRNGKey(3), (2, 8), 0, 64)
        loss = model.apply(params, enc, dec, labels)
        assert loss.shape == () and np.isfinite(float(loss))
        logits = model.apply(params, enc, dec)
        assert logits.shape == (8, 2, 64)

    @pytest.mark.slow
    def test_trains(self):
        from apex_tpu.optimizers import FusedAdam

        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        opt = FusedAdam(lr=1e-2)
        opt_state = opt.init(params)
        enc = jax.random.randint(jax.random.PRNGKey(1), (4, 12), 0, 64)
        dec = jax.random.randint(jax.random.PRNGKey(2), (4, 8), 0, 64)
        labels = jax.random.randint(jax.random.PRNGKey(3), (4, 8), 0, 64)

        @jax.jit
        def step(params, opt_state):
            loss, grads = jax.value_and_grad(
                lambda p: model.apply(p, enc, dec, labels))(params)
            params, opt_state = opt.step(grads, params, opt_state)
            return params, opt_state, loss

        losses = []
        for _ in range(6):
            params, opt_state, loss = step(params, opt_state)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_encoder_padding_mask_blocks_pads(self):
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        enc = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0, 64)
        dec = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, 64)
        pad = jnp.zeros((1, 12), bool).at[:, 8:].set(True)
        out1 = model.apply(params, enc, dec, enc_padding_mask=pad)
        enc2 = enc.at[0, 10].set(int(enc[0, 10]) ^ 1)
        out2 = model.apply(params, enc2, dec, enc_padding_mask=pad)
        np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                                   atol=1e-5)

    def test_asymmetric_depths(self):
        from apex_tpu.models import EncoderDecoderModel

        model = EncoderDecoderModel(
            _cfg(vocab_size=64, max_position_embeddings=32),
            num_encoder_layers=1)
        params = model.init(jax.random.PRNGKey(0))
        n_enc = params["encoder"]["layers"]["input_layernorm"]["weight"].shape[0]
        assert n_enc == 1     # stacked leading dim = encoder depth
        enc = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, 64)
        dec = jax.random.randint(jax.random.PRNGKey(2), (2, 6), 0, 64)
        logits = model.apply(params, enc, dec)
        assert logits.shape == (6, 2, 64)

    @pytest.mark.parametrize("sp", [False, True])
    @pytest.mark.slow
    def test_tensor_parallel_matches_single_rank(self, sp):
        """TP(+SP) sharded run == unsharded reference — exercises the
        encoder-output gather before cross-attention under a bound axis."""
        from jax.sharding import PartitionSpec as P

        from apex_tpu.models import EncoderDecoderModel
        from apex_tpu.transformer import parallel_state

        enc_t = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)
        dec_t = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, 64)
        labels = jax.random.randint(jax.random.PRNGKey(3), (2, 8), 0, 64)

        def run(tp, sp):
            parallel_state.destroy_model_parallel()
            mesh = parallel_state.initialize_model_parallel(
                tensor_model_parallel_size=tp)
            model = EncoderDecoderModel(_cfg(
                vocab_size=64, max_position_embeddings=32,
                sequence_parallel=sp))
            params = model.init(jax.random.PRNGKey(0))

            def loss_fn(p):
                return model.apply(p, enc_t, dec_t, labels)

            out = shard_map(
                jax.value_and_grad(loss_fn), mesh=mesh,
                in_specs=(model.spec(),),
                out_specs=(P(), model.spec()), check_vma=False)(params)
            parallel_state.destroy_model_parallel()
            return out

        ref_loss, ref_grads = run(1, False)
        tp_loss, tp_grads = run(2, sp)
        np.testing.assert_allclose(float(ref_loss), float(tp_loss),
                                   atol=2e-5, rtol=2e-5)
        for a, b_ in zip(jax.tree.leaves(ref_grads),
                         jax.tree.leaves(tp_grads)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=5e-5, rtol=5e-5)

    def test_enc_lengths_matches_padding_mask(self):
        """Varlen flash path (enc_lengths) == boolean-mask fallback."""
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        enc = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 64)
        dec = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, 64)
        labels = jax.random.randint(jax.random.PRNGKey(3), (2, 8), 0, 64)
        lengths = jnp.array([9, 12])
        pad = jnp.arange(12)[None, :] >= lengths[:, None]
        l_len = model.apply(params, enc, dec, labels, enc_lengths=lengths)
        l_mask = model.apply(params, enc, dec, labels, enc_padding_mask=pad)
        np.testing.assert_allclose(float(l_len), float(l_mask), rtol=1e-5)

    def test_both_mask_kinds_rejected(self):
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        enc = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 64)
        dec = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, 64)
        with pytest.raises(ValueError):
            model.apply(params, enc, dec,
                        enc_padding_mask=jnp.zeros((2, 12), bool),
                        enc_lengths=jnp.array([12, 12]))
