"""Cross-attention / encoder-decoder layer tests.

Reference: ``standalone_transformer_lm.py`` ``ParallelAttention`` cross_attn
branch and decoder ``ParallelTransformerLayer`` (inter_attention ~:1090-1115);
the reference exercises them through ``ModelType.encoder_and_decoder``
pipeline tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.transformer import (
    ParallelAttention,
    ParallelTransformer,
    ParallelTransformerLayer,
    TransformerConfig,
)
from apex_tpu.transformer.enums import AttnMaskType, AttnType, LayerType


def _cfg(**kw):
    d = dict(num_layers=2, hidden_size=32, num_attention_heads=4,
             hidden_dropout=0.0, attention_dropout=0.0,
             attn_mask_type=AttnMaskType.causal)
    d.update(kw)
    return TransformerConfig(**d)


class TestCrossAttention:
    def test_shapes(self):
        attn = ParallelAttention(_cfg(), attn_type=AttnType.cross_attn)
        params = attn.init(jax.random.PRNGKey(0))
        assert set(params) == {"query", "key_value", "dense"}
        dec = jax.random.normal(jax.random.PRNGKey(1), (6, 2, 32))
        enc = jax.random.normal(jax.random.PRNGKey(2), (9, 2, 32))
        out = attn.apply(params, dec, encoder_output=enc)
        assert out.shape == (6, 2, 32)

    def test_requires_encoder_output(self):
        attn = ParallelAttention(_cfg(), attn_type=AttnType.cross_attn)
        params = attn.init(jax.random.PRNGKey(0))
        dec = jax.random.normal(jax.random.PRNGKey(1), (6, 2, 32))
        with pytest.raises(ValueError):
            attn.apply(params, dec)

    def test_not_causal_across_encoder(self):
        """Cross-attention must see the WHOLE encoder sequence: changing a
        late encoder position must affect an early decoder position (a
        causal mask would forbid that)."""
        attn = ParallelAttention(_cfg(), attn_type=AttnType.cross_attn)
        params = attn.init(jax.random.PRNGKey(0))
        dec = jax.random.normal(jax.random.PRNGKey(1), (4, 1, 32))
        enc = jax.random.normal(jax.random.PRNGKey(2), (8, 1, 32))
        out1 = attn.apply(params, dec, encoder_output=enc)
        enc2 = enc.at[-1].add(1.0)
        out2 = attn.apply(params, dec, encoder_output=enc2)
        delta = np.abs(np.asarray(out1 - out2))[0]   # first decoder pos
        assert delta.max() > 1e-6

    def test_encoder_padding_mask(self):
        """Masked encoder positions must not influence the output."""
        attn = ParallelAttention(_cfg(), attn_type=AttnType.cross_attn)
        params = attn.init(jax.random.PRNGKey(0))
        dec = jax.random.normal(jax.random.PRNGKey(1), (4, 1, 32))
        enc = jax.random.normal(jax.random.PRNGKey(2), (8, 1, 32))
        # True = masked out; mask the last 3 encoder positions
        mask = jnp.zeros((1, 1, 4, 8), bool).at[..., 5:].set(True)
        out1 = attn.apply(params, dec, encoder_output=enc,
                          attention_mask=mask)
        enc2 = enc.at[6].add(10.0)
        out2 = attn.apply(params, dec, encoder_output=enc2,
                          attention_mask=mask)
        np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                                   atol=1e-6)


class TestDecoderLayer:
    def test_decoder_layer_params_and_apply(self):
        layer = ParallelTransformerLayer(_cfg(), LayerType.decoder)
        params = layer.init(jax.random.PRNGKey(0))
        assert "inter_attention" in params
        assert "post_inter_attention_layernorm" in params
        dec = jax.random.normal(jax.random.PRNGKey(1), (6, 2, 32))
        enc = jax.random.normal(jax.random.PRNGKey(2), (9, 2, 32))
        out = layer.apply(params, dec, encoder_output=enc)
        assert out.shape == (6, 2, 32)
        assert np.isfinite(np.asarray(out)).all()

    def test_encoder_layer_unchanged(self):
        layer = ParallelTransformerLayer(_cfg())
        params = layer.init(jax.random.PRNGKey(0))
        assert "inter_attention" not in params

    def test_decoder_stack_grads(self):
        model = ParallelTransformer(_cfg(), LayerType.decoder)
        params = model.init(jax.random.PRNGKey(0))
        dec = jax.random.normal(jax.random.PRNGKey(1), (6, 2, 32))
        enc = jax.random.normal(jax.random.PRNGKey(2), (9, 2, 32))

        def loss(p, enc):
            out = model.apply(p, dec, encoder_output=enc)
            return jnp.mean(out ** 2)

        g_params = jax.grad(loss)(params, enc)
        g_enc = jax.grad(loss, argnums=1)(params, enc)
        total = sum(float(jnp.sum(jnp.abs(l)))
                    for l in jax.tree.leaves(g_params))
        assert np.isfinite(total) and total > 0
        # encoder gradient flows through cross-attention
        assert float(jnp.sum(jnp.abs(g_enc))) > 0

    def test_decoder_with_recompute(self):
        model = ParallelTransformer(_cfg(recompute=True), LayerType.decoder)
        params = model.init(jax.random.PRNGKey(0))
        dec = jax.random.normal(jax.random.PRNGKey(1), (6, 2, 32))
        enc = jax.random.normal(jax.random.PRNGKey(2), (9, 2, 32))
        out = jax.jit(lambda p, d, e: model.apply(p, d, encoder_output=e))(
            params, dec, enc)
        assert np.isfinite(np.asarray(out)).all()
