"""Pipeline-parallel suite.

Mirrors the reference's ``tests/L0/run_transformer/``:
``test_microbatches.py`` (calculator semantics), ``test_p2p_comm.py``
(ring exchange), and ``test_pipeline_parallel_fwd_bwd.py`` (725 LoC: every
schedule's loss/grads must match the non-pipelined reference run).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

os.environ.setdefault("APEX_TPU_FORCE_PALLAS", "interpret")

from apex_tpu.models import GPTModel, PipelinedGPT, TransformerConfig  # noqa: E402
from apex_tpu.transformer import parallel_state  # noqa: E402
from apex_tpu.transformer.pipeline_parallel import (  # noqa: E402
    ConstantNumMicroBatches,
    RampupBatchsizeNumMicroBatches,
    build_num_microbatches_calculator,
    get_forward_backward_func,
)
from apex_tpu.transformer.pipeline_parallel.p2p_communication import (  # noqa: E402
    ring_shift,
)
from apex_tpu.transformer.pipeline_parallel.schedules import (  # noqa: E402
    forward_backward_no_pipelining,
    make_interleaved_pipelined_loss_fn,
    make_pipelined_loss_fn,
)
from apex_tpu.transformer.pipeline_parallel.schedules.common import (  # noqa: E402
    arrange_layers_for_pipeline,
    mark_pipeline_replicated,
    pipeline_stage_spec,
)
from apex_tpu.transformer.pipeline_parallel.utils import (  # noqa: E402
    get_ltor_masks_and_position_ids,
    split_batch_into_microbatches,
)
from apex_tpu.utils.sharding import shard_map  # noqa: E402


class TestMicrobatchCalculators:
    def test_constant(self):
        calc = ConstantNumMicroBatches(
            global_batch_size=32, micro_batch_size=2, data_parallel_size=4)
        assert calc.get() == 4
        assert calc.get_current_global_batch_size() == 32
        calc.update(1000, True)
        assert calc.get() == 4

    def test_constant_indivisible_raises(self):
        with pytest.raises(ValueError):
            ConstantNumMicroBatches(30, 2, 4)

    def test_rampup(self):
        # start 8, +8 per increment, over 64 samples, to 32: 3 increments
        calc = RampupBatchsizeNumMicroBatches(
            start_batch_size=8, batch_size_increment=8, ramup_samples=64,
            global_batch_size=32, micro_batch_size=2, data_parallel_size=2)
        assert calc.get_current_global_batch_size() == 8
        assert calc.get() == 2
        calc.update(70, True)
        assert calc.get_current_global_batch_size() == 32
        assert calc.get() == 8

    def test_rampup_no_increments(self):
        # start == global: zero increments must not divide by zero
        calc = RampupBatchsizeNumMicroBatches(
            start_batch_size=32, batch_size_increment=8, ramup_samples=64,
            global_batch_size=32, micro_batch_size=2, data_parallel_size=2)
        assert calc.get_current_global_batch_size() == 32
        assert calc.get() == 8

    def test_build_selector(self):
        c = build_num_microbatches_calculator(0, None, 16, 2, 2)
        assert isinstance(c, ConstantNumMicroBatches)
        c = build_num_microbatches_calculator(0, [8, 8, 32], 16, 2, 2)
        assert isinstance(c, RampupBatchsizeNumMicroBatches)


class TestP2P:
    def test_ring_shift_forward_and_reverse(self):
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=4)

        def f(x):
            fwd = ring_shift(x)
            bwd = ring_shift(x, reverse=True)
            return fwd, bwd

        x = jnp.arange(8, dtype=jnp.float32).reshape(4, 2)
        fwd, bwd = jax.jit(shard_map(
            f, mesh=mesh,
            in_specs=P("pipeline"),
            out_specs=(P("pipeline"), P("pipeline")),
            check_vma=False))(x)
        np.testing.assert_array_equal(np.asarray(fwd), np.roll(x, 1, axis=0))
        np.testing.assert_array_equal(np.asarray(bwd), np.roll(x, -1, axis=0))
        parallel_state.destroy_model_parallel()


def test_arrange_layers_round_robin():
    x = jnp.arange(8)
    plain = arrange_layers_for_pipeline({"w": x}, 2)["w"]
    np.testing.assert_array_equal(np.asarray(plain),
                                  [[0, 1, 2, 3], [4, 5, 6, 7]])
    inter = arrange_layers_for_pipeline({"w": x}, 2, 2)["w"]
    # rank i chunk c holds virtual stage v = c*S + i: rank0 -> v0,v2 =
    # layers (0,1),(4,5); rank1 -> v1,v3 = layers (2,3),(6,7)
    np.testing.assert_array_equal(np.asarray(inter),
                                  [[[0, 1], [4, 5]], [[2, 3], [6, 7]]])


# ---------------------------------------------------------------------------
# schedule numerics on a toy deep MLP
# ---------------------------------------------------------------------------

L, D = 4, 8


def _toy_params(key):
    ws = jax.random.normal(key, (L, D, D)) / np.sqrt(D)
    return {"layers": ws, "head": jnp.ones((D,)) / D}


def _toy_batch(m, b=2):
    x = jax.random.normal(jax.random.PRNGKey(7), (m, b, D))
    y = jax.random.normal(jax.random.PRNGKey(8), (m, b))
    return {"x": x, "y": y}


def _reference_loss(params, batch):
    """Sequential ground truth: run every microbatch through all layers."""
    def one(mb):
        h = mb["x"]
        for l in range(L):
            h = jnp.tanh(h @ params["layers"][l])
        pred = h @ params["head"]
        return jnp.mean((pred - mb["y"]) ** 2)

    losses = jax.vmap(one)(batch)
    return jnp.mean(losses)


def _stage_fns(layers_key="stages", vpp=None):
    def preprocess(params, mb):
        return mb["x"]

    def run(chunk, h):
        def body(h, w):
            return jnp.tanh(h @ w), None
        h, _ = jax.lax.scan(body, h, chunk)
        return h

    if vpp is None:
        def stage(params, h, tick):
            return run(jax.tree.map(lambda x: x[0], params[layers_key]), h)
    else:
        def stage(params, h, chunk, tick):
            local = jax.lax.dynamic_index_in_dim(
                params[layers_key][0], chunk, 0, keepdims=False)
            return run(local, h)

    def postprocess(params, h, mb):
        head = mark_pipeline_replicated(params["head"])
        pred = h @ head
        return jnp.mean((pred - mb["y"]) ** 2)

    return preprocess, stage, postprocess


class TestSchedules:
    M = 4

    def test_no_pipelining_matches_full_batch(self):
        params = _toy_params(jax.random.PRNGKey(0))
        batch = _toy_batch(self.M)

        def fwd(p, mb):
            h = mb["x"]
            def body(h, w):
                return jnp.tanh(h @ w), None
            h, _ = jax.lax.scan(body, h, p["layers"])
            return jnp.mean((h @ p["head"] - mb["y"]) ** 2)

        loss, grads = forward_backward_no_pipelining(
            fwd, batch, params, num_microbatches=self.M)
        ref_loss, ref_grads = jax.value_and_grad(_reference_loss)(
            params, batch)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4,
                                                    atol=1e-6),
            grads, ref_grads)

    def _pipelined_run(self, vpp=None, forward_only=False):
        parallel_state.destroy_model_parallel()
        S = 2
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=S)
        full = _toy_params(jax.random.PRNGKey(0))
        batch = _toy_batch(self.M)
        staged = {
            "stages": arrange_layers_for_pipeline(full["layers"], S, vpp),
            "head": full["head"],
        }
        spec = {
            "stages": P("pipeline"),
            "head": P(),
        }
        pre, stage, post = _stage_fns(vpp=vpp)
        if vpp is None:
            loss_fn = make_pipelined_loss_fn(pre, stage, post, self.M)
        else:
            loss_fn = make_interleaved_pipelined_loss_fn(
                pre, stage, post, self.M, vpp)

        def per_rank(p, b):
            if forward_only:
                return loss_fn(p, b), jax.tree.map(jnp.zeros_like, p)
            return jax.value_and_grad(loss_fn)(p, b)

        run = jax.jit(shard_map(
            per_rank, mesh=mesh,
            in_specs=(spec, P()),
            out_specs=(P(), spec),
            check_vma=False))
        loss, grads = run(staged, batch)
        parallel_state.destroy_model_parallel()

        # map staged grads back to the flat-layer layout for comparison
        g_stages = grads["stages"]
        if vpp is None:
            g_layers = g_stages.reshape(L, D, D)
        else:
            g_layers = (np.asarray(g_stages)
                        .transpose(1, 0, 2, 3, 4)
                        .reshape(L, D, D))
        return (float(loss),
                {"layers": np.asarray(g_layers),
                 "head": np.asarray(grads["head"])},
                full, batch)

    def test_pipelined_matches_reference(self):
        loss, grads, full, batch = self._pipelined_run()
        ref_loss, ref_grads = jax.value_and_grad(_reference_loss)(
            full, batch)
        np.testing.assert_allclose(loss, float(ref_loss), rtol=1e-5)
        np.testing.assert_allclose(grads["layers"],
                                   np.asarray(ref_grads["layers"]),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(grads["head"],
                                   np.asarray(ref_grads["head"]),
                                   rtol=1e-4, atol=1e-6)

    def test_interleaved_matches_reference(self):
        loss, grads, full, batch = self._pipelined_run(vpp=2)
        ref_loss, ref_grads = jax.value_and_grad(_reference_loss)(
            full, batch)
        np.testing.assert_allclose(loss, float(ref_loss), rtol=1e-5)
        np.testing.assert_allclose(grads["layers"],
                                   np.asarray(ref_grads["layers"]),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(grads["head"],
                                   np.asarray(ref_grads["head"]),
                                   rtol=1e-4, atol=1e-6)

    def test_forward_only(self):
        loss, _, full, batch = self._pipelined_run(forward_only=True)
        ref_loss = _reference_loss(full, batch)
        np.testing.assert_allclose(loss, float(ref_loss), rtol=1e-5)

    def test_selector(self):
        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=2)
        from apex_tpu.transformer.pipeline_parallel.schedules import (
            forward_backward_pipelining_with_interleaving,
            forward_backward_pipelining_without_interleaving,
        )
        assert (get_forward_backward_func()
                is forward_backward_pipelining_without_interleaving)
        assert (get_forward_backward_func(2)
                is forward_backward_pipelining_with_interleaving)
        parallel_state.destroy_model_parallel()
        assert (get_forward_backward_func(None, 1)
                is forward_backward_no_pipelining)


# ---------------------------------------------------------------------------
# pipelined GPT end-to-end vs the single-stack model
# ---------------------------------------------------------------------------

def _gpt_config(**kw):
    defaults = dict(num_layers=4, hidden_size=64, num_attention_heads=4,
                    vocab_size=128, max_position_embeddings=32,
                    hidden_dropout=0.0, attention_dropout=0.0)
    defaults.update(kw)
    return TransformerConfig(**defaults)


@pytest.mark.slow  # compile-bound pipelined-model parity (10-16s each)
class TestPipelinedGPT:
    M = 2

    def _run(self, vpp=None, tp=1):
        parallel_state.destroy_model_parallel()
        S = 2
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=tp, pipeline_model_parallel_size=S)
        cfg = _gpt_config()
        ref_model = GPTModel(cfg)
        ref_params = ref_model.init(jax.random.PRNGKey(0))

        pmodel = PipelinedGPT(cfg, pipeline_size=S, num_microbatches=self.M,
                              virtual_pipeline_size=vpp)
        pparams = {
            "embedding": ref_params["embedding"],
            "stages": arrange_layers_for_pipeline(
                ref_params["transformer"]["layers"], S, vpp),
            "final_layernorm": ref_params["transformer"]["final_layernorm"],
        }
        bs, seq = 4, 16
        tokens = jax.random.randint(jax.random.PRNGKey(1), (bs, seq), 0, 128)
        labels = jax.random.randint(jax.random.PRNGKey(2), (bs, seq), 0, 128)
        mb = split_batch_into_microbatches(
            {"tokens": tokens, "labels": labels}, self.M)

        loss_fn = pmodel.make_loss_fn()
        spec = pmodel.spec()

        run = jax.jit(shard_map(
            jax.value_and_grad(loss_fn), mesh=mesh,
            in_specs=(spec, P()),
            out_specs=(P(), spec),
            check_vma=False))
        loss, grads = run(pparams, mb)

        ref_loss, ref_grads = jax.jit(jax.value_and_grad(
            lambda p: ref_model.apply(p, tokens, labels)))(ref_params)
        parallel_state.destroy_model_parallel()
        return loss, grads, ref_loss, ref_grads, vpp, S

    def test_pp2_matches_single_stack(self):
        loss, grads, ref_loss, ref_grads, vpp, S = self._run()
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=2e-4, atol=2e-5)
        # embedding grads psum-synced across stages must match
        np.testing.assert_allclose(
            np.asarray(grads["embedding"]["word_embeddings"]["weight"]),
            np.asarray(ref_grads["embedding"]["word_embeddings"]["weight"]),
            rtol=2e-3, atol=2e-5)
        # layer grads: un-arrange and compare
        g = np.asarray(grads["stages"]["mlp"]["dense_h_to_4h"]["weight"])
        ref_g = np.asarray(
            ref_grads["transformer"]["layers"]["mlp"]["dense_h_to_4h"]["weight"])
        np.testing.assert_allclose(g.reshape(ref_g.shape), ref_g,
                                   rtol=2e-3, atol=2e-5)

    def test_pp2_vpp2_matches_single_stack(self):
        loss, grads, ref_loss, ref_grads, vpp, S = self._run(vpp=2)
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=2e-4, atol=2e-5)
        g = np.asarray(grads["stages"]["mlp"]["dense_h_to_4h"]["weight"])
        ref_g = np.asarray(
            ref_grads["transformer"]["layers"]["mlp"]["dense_h_to_4h"]["weight"])
        # [S, vpp, Lc, ...] -> [L, ...] with v = c*S + i
        g_flat = g.transpose(1, 0, 2, *range(3, g.ndim)).reshape(ref_g.shape)
        np.testing.assert_allclose(g_flat, ref_g, rtol=2e-3, atol=2e-5)

    def test_pp2_tp2_matches_single_stack(self):
        loss, grads, ref_loss, ref_grads, vpp, S = self._run(tp=2)
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.slow  # compile-bound PP x MoE parity (14-23s each)
class TestPipelinedMoE:
    """PP x MoE/EP composition (VERDICT r2 item 4): the pipeline scan
    carries each stage's pre-scaled aux loss to the total with a direct
    1/M cotangent seed. The reference against GPTModel is per-microbatch
    (the load-balancing loss is nonlinear in the batch, so aux(full batch)
    != mean of aux(microbatch) — Megatron computes it per microbatch too).
    """

    M = 2

    def _run(self, vpp=None, expert_axis=None):
        parallel_state.destroy_model_parallel()
        S = 2
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=S)
        dp = 8 // S
        cfg = _gpt_config(num_moe_experts=dp, moe_capacity_factor=4.0,
                          moe_expert_axis=expert_axis)
        ref_cfg = _gpt_config(num_moe_experts=dp, moe_capacity_factor=4.0,
                              moe_expert_axis=None)
        ref_model = GPTModel(ref_cfg)
        ref_params = ref_model.init(jax.random.PRNGKey(0))

        pmodel = PipelinedGPT(cfg, pipeline_size=S, num_microbatches=self.M,
                              virtual_pipeline_size=vpp)
        pparams = {
            "embedding": ref_params["embedding"],
            "stages": arrange_layers_for_pipeline(
                ref_params["transformer"]["layers"], S, vpp),
            "final_layernorm": ref_params["transformer"]["final_layernorm"],
        }
        bs, seq = 4, 16
        tokens = jax.random.randint(jax.random.PRNGKey(1), (bs, seq), 0, 128)
        labels = jax.random.randint(jax.random.PRNGKey(2), (bs, seq), 0, 128)
        mb = split_batch_into_microbatches(
            {"tokens": tokens, "labels": labels}, self.M)

        loss_fn = pmodel.make_loss_fn()
        spec = pmodel.spec()
        run = jax.jit(shard_map(
            jax.value_and_grad(loss_fn), mesh=mesh,
            in_specs=(spec, P()),
            out_specs=(P(), spec),
            check_vma=False))
        loss, grads = run(pparams, mb)

        def ref_loss_fn(p):
            per_mb = [ref_model.apply(
                p,
                jax.tree.map(lambda x: x[m], mb)["tokens"],
                jax.tree.map(lambda x: x[m], mb)["labels"])
                for m in range(self.M)]
            return sum(per_mb) / self.M

        ref_loss, ref_grads = jax.jit(
            jax.value_and_grad(ref_loss_fn))(ref_params)
        parallel_state.destroy_model_parallel()
        return loss, grads, ref_loss, ref_grads

    def test_pp2_moe_matches_per_microbatch_reference(self):
        loss, grads, ref_loss, ref_grads = self._run()
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=2e-4, atol=2e-5)
        # router + expert grads must flow and match the dense reference
        g = np.asarray(grads["stages"]["mlp"]["router"])
        ref_g = np.asarray(ref_grads["transformer"]["layers"]["mlp"]["router"])
        np.testing.assert_allclose(g.reshape(ref_g.shape), ref_g,
                                   rtol=2e-3, atol=2e-5)
        assert np.abs(g).max() > 0
        g = np.asarray(grads["stages"]["mlp"]["w_in"])
        ref_g = np.asarray(ref_grads["transformer"]["layers"]["mlp"]["w_in"])
        np.testing.assert_allclose(g.reshape(ref_g.shape), ref_g,
                                   rtol=2e-3, atol=2e-5)

    def test_pp2_vpp2_moe_matches_per_microbatch_reference(self):
        loss, grads, ref_loss, ref_grads = self._run(vpp=2)
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=2e-4, atol=2e-5)

    def test_pp2_ep_matches_dense_reference(self):
        """Experts sharded over the data axis (EP rides DP) inside the
        pipeline — the PP x EP layout the reference cannot express."""
        loss, grads, ref_loss, ref_grads = self._run(expert_axis="data")
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=2e-4, atol=2e-5)


class TestPipelinedDropout:
    @pytest.mark.slow  # compile-bound dropout-rng check: slow tier (ROADMAP)
    def test_rng_enables_dropout(self):
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=2)
        cfg = _gpt_config(hidden_dropout=0.3, attention_dropout=0.0)
        model = PipelinedGPT(cfg, pipeline_size=2, num_microbatches=2)
        params = model.init(jax.random.PRNGKey(0))
        bs, seq = 4, 16
        tokens = jax.random.randint(jax.random.PRNGKey(1), (bs, seq), 0, 128)
        mb = split_batch_into_microbatches(
            {"tokens": tokens, "labels": tokens}, 2)
        loss_fn = model.make_loss_fn()
        spec = model.spec()
        run = jax.jit(shard_map(
            loss_fn, mesh=mesh, in_specs=(spec, P(), P()),
            out_specs=P(), check_vma=False))
        det = float(run(params, mb, None))
        d1 = float(run(params, mb, jax.random.PRNGKey(5)))
        d2 = float(run(params, mb, jax.random.PRNGKey(6)))
        # dropout must perturb the loss, differently per key
        assert det != d1 and d1 != d2
        parallel_state.destroy_model_parallel()


class TestScaledLossReporting:
    def test_no_pipelining_reports_unscaled_loss(self):
        params = _toy_params(jax.random.PRNGKey(0))
        batch = _toy_batch(4)

        def fwd(p, mb):
            h = mb["x"]
            def body(h, w):
                return jnp.tanh(h @ w), None
            h, _ = jax.lax.scan(body, h, p["layers"])
            return jnp.mean((h @ p["head"] - mb["y"]) ** 2)

        loss, grads = forward_backward_no_pipelining(
            fwd, batch, params, num_microbatches=4,
            grad_scaler=lambda l: l * 1024.0)
        ref_loss, ref_grads = jax.value_and_grad(_reference_loss)(
            params, batch)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(grads["layers"]),
            np.asarray(ref_grads["layers"]) * 1024.0, rtol=1e-4, atol=1e-4)


def test_ltor_masks_and_position_ids():
    data = jnp.array([[5, 1, 7, 1, 3, 2]])  # eod = 1
    attn, loss_mask, pos = get_ltor_masks_and_position_ids(
        data, 1, reset_position_ids=True, reset_attention_mask=True,
        eod_mask_loss=True)
    np.testing.assert_array_equal(np.asarray(loss_mask),
                                  [[1, 0, 1, 0, 1, 1]])
    np.testing.assert_array_equal(np.asarray(pos), [[0, 1, 0, 1, 0, 1]])
    a = np.asarray(attn)[0, 0]
    # cross-document attention masked: position 2 (doc 2) may not see pos 0
    assert a[2, 0] and a[2, 1]
    assert not a[3, 2]
    # causal within doc
    assert a[0, 1]


class Test1F1BMemory:
    """The defining 1F1B property (reference
    fwd_bwd_pipelining_without_interleaving.py:241-597): in-flight activation
    memory is bounded by the pipeline depth, NOT the microbatch count. The
    compiled train step's temp arena must stay flat as M grows 4 -> 32 at
    equal microbatch size (the pre-1F1B scan design grew it ~O(M))."""

    def _temp_bytes(self, M):
        from apex_tpu.models import PipelinedGPT
        from apex_tpu.transformer.pipeline_parallel.utils import (
            split_batch_into_microbatches,
        )

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=4)
        cfg = TransformerConfig(
            num_layers=4, hidden_size=64, num_attention_heads=4,
            vocab_size=256, max_position_embeddings=64,
            hidden_dropout=0.0, attention_dropout=0.0)
        model = PipelinedGPT(cfg, pipeline_size=4, num_microbatches=M)
        params = model.init(jax.random.PRNGKey(0))
        loss_fn = model.make_loss_fn()
        batch = split_batch_into_microbatches(
            {"tokens": jnp.zeros((4 * M, 32), jnp.int32),
             "labels": jnp.zeros((4 * M, 32), jnp.int32)}, M)

        def per_rank(p, b):
            return jax.value_and_grad(lambda p: loss_fn(p, b))(p)

        f = jax.jit(shard_map(
            per_rank, mesh=mesh,
            in_specs=(model.spec(),
                      {"tokens": P(None, "data"), "labels": P(None, "data")}),
            out_specs=(P(), model.spec()), check_vma=False))
        ma = f.lower(params, batch).compile().memory_analysis()
        parallel_state.destroy_model_parallel()
        if ma is None:
            pytest.skip("backend does not expose memory_analysis")
        return ma.temp_size_in_bytes

    @pytest.mark.slow
    def test_temp_memory_flat_in_microbatch_count(self):
        small = self._temp_bytes(4)
        big = self._temp_bytes(32)
        assert big < small * 1.2, (
            f"temp arena grew {big / small:.2f}x from M=4 ({small}B) to "
            f"M=32 ({big}B); 1F1B requires O(pipeline-depth) memory")


class Test1F1BRecomputeRngAlignment:
    """The 1F1B backward recomputes each stage forward from the stashed
    input; a stage whose compute depends on the tick (dropout streams fold
    the tick into their rng) must be replayed with the ORIGINAL tick value
    (m + i), or grads silently diverge. A tick-dependent multiplicative mask
    stands in for dropout so the check is exact."""

    def test_grads_match_sequential_with_tick_dependent_stage(self):
        parallel_state.destroy_model_parallel()
        S, M = 2, 4
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=S)
        full = _toy_params(jax.random.PRNGKey(0))
        batch = _toy_batch(M)
        key = jax.random.PRNGKey(42)

        def mask_for(tick, shape):
            k = jax.random.fold_in(key, tick)
            return jax.random.bernoulli(k, 0.8, shape).astype(jnp.float32)

        def preprocess(params, mb):
            return mb["x"]

        def stage(params, h, tick):
            chunk = jax.tree.map(lambda x: x[0], params["stages"])

            def body(h, w):
                return jnp.tanh(h @ w) * mask_for(tick, h.shape), None

            h, _ = jax.lax.scan(body, h, chunk)
            return h

        def postprocess(params, h, mb):
            head = mark_pipeline_replicated(params["head"])
            return jnp.mean((h @ head - mb["y"]) ** 2)

        staged = {
            "stages": arrange_layers_for_pipeline(full["layers"], S, None),
            "head": full["head"],
        }
        spec = {"stages": P("pipeline"), "head": P()}
        loss_fn = make_pipelined_loss_fn(preprocess, stage, postprocess, M)
        loss, grads = jax.jit(shard_map(
            lambda p, b: jax.value_and_grad(loss_fn)(p, b),
            mesh=mesh, in_specs=(spec, P()), out_specs=(P(), spec),
            check_vma=False))(staged, batch)

        # sequential reference replaying the schedule's tick values:
        # stage i applies its chunk to microbatch m at tick m + i
        lpc = L // S

        def reference(params, batch):
            def one(mb, m):
                h = mb["x"]
                for i in range(S):
                    for j in range(lpc):
                        w = params["layers"][i * lpc + j]
                        h = jnp.tanh(h @ w) * mask_for(m + i, h.shape)
                return jnp.mean((h @ params["head"] - mb["y"]) ** 2)

            losses = jax.vmap(one)(batch, jnp.arange(M))
            return jnp.mean(losses)

        ref_loss, ref_grads = jax.value_and_grad(reference)(full, batch)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(grads["stages"]).reshape(L, D, D),
            np.asarray(ref_grads["layers"]), rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(grads["head"]),
                                   np.asarray(ref_grads["head"]),
                                   rtol=1e-4, atol=1e-6)
        parallel_state.destroy_model_parallel()


class Test1F1BInputGradients:
    """Input (batch) cotangents through the explicit-backward 1F1B: float
    batch leaves must receive true gradients (stage 0 contributes the
    preprocess path, the last stage the loss path), matching autodiff of
    the sequential reference."""

    def test_batch_float_grads_match_reference(self):
        parallel_state.destroy_model_parallel()
        S, M = 2, 4
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=S)
        full = _toy_params(jax.random.PRNGKey(0))
        batch = _toy_batch(M)
        staged = {
            "stages": arrange_layers_for_pipeline(full["layers"], S, None),
            "head": full["head"],
        }
        spec = {"stages": P("pipeline"), "head": P()}
        pre, stage, post = _stage_fns()
        loss_fn = make_pipelined_loss_fn(pre, stage, post, M)

        def per_rank(p, b):
            _, bg = jax.value_and_grad(loss_fn, argnums=1)(p, b)
            # per-rank cotangents are partial (pre on stage 0, post on the
            # last stage); the global input grad is their pipeline psum
            return jax.tree.map(
                lambda x: jax.lax.psum(x, "pipeline"), bg)

        bg = jax.jit(shard_map(
            per_rank, mesh=mesh, in_specs=(spec, P()),
            out_specs=P(), check_vma=False))(staged, batch)
        ref_bg = jax.grad(_reference_loss, argnums=1)(full, batch)
        np.testing.assert_allclose(np.asarray(bg["x"]),
                                   np.asarray(ref_bg["x"]),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(bg["y"]),
                                   np.asarray(ref_bg["y"]),
                                   rtol=1e-4, atol=1e-6)
        parallel_state.destroy_model_parallel()


class TestInterleavedMemory:
    """The interleaved schedule shares the 1F1B property now: per-chunk
    in-flight stashes bounded by the virtual pipeline depth, flat in M."""

    def _temp_bytes(self, M):
        from apex_tpu.models import PipelinedGPT
        from apex_tpu.transformer.pipeline_parallel.utils import (
            split_batch_into_microbatches,
        )

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=2,
            virtual_pipeline_model_parallel_size=2)
        cfg = TransformerConfig(
            num_layers=4, hidden_size=64, num_attention_heads=4,
            vocab_size=256, max_position_embeddings=64,
            hidden_dropout=0.0, attention_dropout=0.0)
        model = PipelinedGPT(cfg, pipeline_size=2, num_microbatches=M,
                             virtual_pipeline_size=2)
        params = model.init(jax.random.PRNGKey(0))
        loss_fn = model.make_loss_fn()
        batch = split_batch_into_microbatches(
            {"tokens": jnp.zeros((4 * M, 32), jnp.int32),
             "labels": jnp.zeros((4 * M, 32), jnp.int32)}, M)

        def per_rank(p, b):
            return jax.value_and_grad(lambda p: loss_fn(p, b))(p)

        f = jax.jit(shard_map(
            per_rank, mesh=mesh,
            in_specs=(model.spec(),
                      {"tokens": P(None, "data"), "labels": P(None, "data")}),
            out_specs=(P(), model.spec()), check_vma=False))
        ma = f.lower(params, batch).compile().memory_analysis()
        parallel_state.destroy_model_parallel()
        if ma is None:
            pytest.skip("backend does not expose memory_analysis")
        return ma.temp_size_in_bytes

    @pytest.mark.slow
    def test_temp_memory_flat_in_microbatch_count(self):
        small = self._temp_bytes(4)
        big = self._temp_bytes(32)
        assert big < small * 1.2, (
            f"interleaved temp arena grew {big / small:.2f}x from M=4 "
            f"({small}B) to M=32 ({big}B)")


@pytest.mark.slow  # pipelined-model parity: slow-tier family (ROADMAP)
class TestPipelinedEncoderDecoder:
    """Two-section (encoder|decoder) pipeline vs the unpipelined
    EncoderDecoderModel — the ``ModelType.encoder_and_decoder`` parity the
    reference pins in ``test_pipeline_parallel_fwd_bwd.py`` (split-rank
    construction ``apex/transformer/parallel_state.py:155-247``)."""

    M = 2

    def _data(self, bs=4, s_enc=12, s_dec=16, vocab=128):
        enc_tokens = jax.random.randint(
            jax.random.PRNGKey(1), (bs, s_enc), 0, vocab)
        dec_tokens = jax.random.randint(
            jax.random.PRNGKey(2), (bs, s_dec), 0, vocab)
        labels = jax.random.randint(
            jax.random.PRNGKey(3), (bs, s_dec), 0, vocab)
        return enc_tokens, dec_tokens, labels

    def _run(self, S=2, split=1, n_enc=2, n_dec=2, tp=1, sp=False):
        from apex_tpu.models import EncoderDecoderModel, PipelinedEncoderDecoder
        from apex_tpu.models.pipelined import _pad_stage_rows

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=tp, pipeline_model_parallel_size=S,
            pipeline_model_parallel_split_rank=split)
        cfg = _gpt_config(num_layers=n_dec, sequence_parallel=sp)
        ref_model = EncoderDecoderModel(cfg, num_encoder_layers=n_enc)
        ref_params = ref_model.init(jax.random.PRNGKey(0))

        # split_rank comes from parallel_state — the end-to-end consumer of
        # --pipeline-model-parallel-split-rank
        pmodel = PipelinedEncoderDecoder(
            cfg, pipeline_size=S, num_microbatches=self.M,
            num_encoder_layers=n_enc)
        assert pmodel.split_rank == split
        pparams = {
            "embedding": ref_params["embedding"],
            "enc_stages": _pad_stage_rows(
                arrange_layers_for_pipeline(
                    ref_params["encoder"]["layers"], split), S, front=False),
            "dec_stages": _pad_stage_rows(
                arrange_layers_for_pipeline(
                    ref_params["decoder"]["layers"], S - split), S,
                front=True),
            "enc_final_layernorm": ref_params["encoder"]["final_layernorm"],
            "dec_final_layernorm": ref_params["decoder"]["final_layernorm"],
        }
        enc_tokens, dec_tokens, labels = self._data()
        mb = split_batch_into_microbatches(
            {"enc_tokens": enc_tokens, "dec_tokens": dec_tokens,
             "labels": labels}, self.M)

        loss_fn = pmodel.make_loss_fn()
        spec = pmodel.spec()
        run = jax.jit(shard_map(
            jax.value_and_grad(loss_fn), mesh=mesh,
            in_specs=(spec, P()),
            out_specs=(P(), spec),
            check_vma=False))
        loss, grads = run(pparams, mb)

        ref_loss, ref_grads = jax.jit(jax.value_and_grad(
            lambda p: ref_model.apply(p, enc_tokens, dec_tokens, labels)))(
                ref_params)
        parallel_state.destroy_model_parallel()
        return loss, grads, ref_loss, ref_grads, split, S

    def _check(self, loss, grads, ref_loss, ref_grads, split, S):
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=2e-4, atol=2e-5)
        # tied-embedding grads psum-synced across stages
        np.testing.assert_allclose(
            np.asarray(grads["embedding"]["word_embeddings"]["weight"]),
            np.asarray(ref_grads["embedding"]["word_embeddings"]["weight"]),
            rtol=2e-3, atol=2e-5)
        # encoder layer grads live in rows [:split]; padded rows exactly 0
        g = np.asarray(grads["enc_stages"]["mlp"]["dense_h_to_4h"]["weight"])
        ref_g = np.asarray(
            ref_grads["encoder"]["layers"]["mlp"]["dense_h_to_4h"]["weight"])
        np.testing.assert_allclose(g[:split].reshape(ref_g.shape), ref_g,
                                   rtol=2e-3, atol=2e-5)
        assert np.all(g[split:] == 0)
        # decoder cross-attention grads live in rows [split:]
        g = np.asarray(
            grads["dec_stages"]["inter_attention"]["key_value"]["weight"])
        ref_g = np.asarray(
            ref_grads["decoder"]["layers"]["inter_attention"]["key_value"]
            ["weight"])
        np.testing.assert_allclose(g[split:].reshape(ref_g.shape), ref_g,
                                   rtol=2e-3, atol=2e-5)
        assert np.all(g[:split] == 0)
        assert np.abs(g[split:]).max() > 0
        # boundary/final norms
        for k, sect in (("enc_final_layernorm", "encoder"),
                        ("dec_final_layernorm", "decoder")):
            np.testing.assert_allclose(
                np.asarray(grads[k]["weight"]),
                np.asarray(ref_grads[sect]["final_layernorm"]["weight"]),
                rtol=2e-3, atol=2e-5)

    @pytest.mark.slow
    def test_pp2_split1_matches_unpipelined(self):
        self._check(*self._run(S=2, split=1, n_enc=2, n_dec=2))

    @pytest.mark.slow
    def test_pp4_split2_matches_unpipelined(self):
        self._check(*self._run(S=4, split=2, n_enc=2, n_dec=4))

    @pytest.mark.slow
    def test_pp4_split1_uneven_sections(self):
        # 1 encoder stage vs 3 decoder stages: section depths needn't match
        self._check(*self._run(S=4, split=1, n_enc=2, n_dec=3))

    @pytest.mark.slow
    def test_pp2_tp2_sp_matches_unpipelined(self):
        # TP+SP inside each stage; decoder stages re-gather the sequence-
        # sharded encoder stream for cross-attention
        loss, grads, ref_loss, ref_grads, split, S = self._run(
            S=2, split=1, n_enc=2, n_dec=2, tp=2, sp=True)
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=2e-4, atol=2e-5)

    def test_single_rank_degenerate_matches_unpipelined(self):
        """Pipeline axis unbound: sections run back-to-back per microbatch."""
        from apex_tpu.models import EncoderDecoderModel, PipelinedEncoderDecoder

        parallel_state.destroy_model_parallel()
        cfg = _gpt_config(num_layers=2)
        ref_model = EncoderDecoderModel(cfg, num_encoder_layers=2)
        ref_params = ref_model.init(jax.random.PRNGKey(0))
        pmodel = PipelinedEncoderDecoder(
            cfg, pipeline_size=2, num_microbatches=self.M, split_rank=1,
            num_encoder_layers=2)
        pparams = pmodel.init(jax.random.PRNGKey(0))
        # re-use its own init; compare against ref built from those params
        ref_like = {
            "embedding": pparams["embedding"],
            "encoder": {
                "layers": jax.tree.map(
                    lambda x: x[:1].reshape((2,) + x.shape[2:]),
                    pparams["enc_stages"]),
                "final_layernorm": pparams["enc_final_layernorm"],
            },
            "decoder": {
                "layers": jax.tree.map(
                    lambda x: x[1:].reshape((2,) + x.shape[2:]),
                    pparams["dec_stages"]),
                "final_layernorm": pparams["dec_final_layernorm"],
            },
        }
        enc_tokens, dec_tokens, labels = self._data()
        mb = split_batch_into_microbatches(
            {"enc_tokens": enc_tokens, "dec_tokens": dec_tokens,
             "labels": labels}, self.M)
        loss = jax.jit(pmodel.make_loss_fn())(pparams, mb)
        ref_loss = jax.jit(
            lambda p: ref_model.apply(p, enc_tokens, dec_tokens, labels))(
                ref_like)
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=2e-4, atol=2e-5)

    def test_dropout_rng_path_runs(self):
        from apex_tpu.models import PipelinedEncoderDecoder

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=2,
            pipeline_model_parallel_split_rank=1)
        cfg = _gpt_config(num_layers=2, hidden_dropout=0.1,
                          attention_dropout=0.1)
        pmodel = PipelinedEncoderDecoder(
            cfg, pipeline_size=2, num_microbatches=self.M,
            num_encoder_layers=2)
        pparams = pmodel.init(jax.random.PRNGKey(0))
        enc_tokens, dec_tokens, labels = self._data()
        mb = split_batch_into_microbatches(
            {"enc_tokens": enc_tokens, "dec_tokens": dec_tokens,
             "labels": labels}, self.M)
        loss_fn = pmodel.make_loss_fn()
        spec = pmodel.spec()
        run = jax.jit(shard_map(
            lambda p, b, r: loss_fn(p, b, r), mesh=mesh,
            in_specs=(spec, P(), P()),
            out_specs=P(), check_vma=False))
        l1 = float(run(pparams, mb, jax.random.PRNGKey(7)))
        l2 = float(run(pparams, mb, jax.random.PRNGKey(8)))
        det = jax.jit(shard_map(
            lambda p, b: loss_fn(p, b), mesh=mesh,
            in_specs=(spec, P()), out_specs=P(), check_vma=False))
        l0 = float(det(pparams, mb))
        assert np.isfinite([l0, l1, l2]).all()
        assert l1 != l0 and l1 != l2
        parallel_state.destroy_model_parallel()

    def test_validation(self):
        from apex_tpu.models import PipelinedEncoderDecoder

        parallel_state.destroy_model_parallel()
        cfg = _gpt_config(num_layers=2)
        with pytest.raises(ValueError, match="split"):
            PipelinedEncoderDecoder(cfg, pipeline_size=2, num_microbatches=2,
                                    split_rank=0)
        with pytest.raises(ValueError, match="split"):
            PipelinedEncoderDecoder(cfg, pipeline_size=2, num_microbatches=2,
                                    split_rank=2)
        with pytest.raises(ValueError, match="split rank"):
            PipelinedEncoderDecoder(cfg, pipeline_size=2, num_microbatches=2)
        with pytest.raises(ValueError, match="divide evenly"):
            PipelinedEncoderDecoder(cfg, pipeline_size=3, num_microbatches=2,
                                    split_rank=2, num_encoder_layers=3)


class TestSplitRankState:
    def test_predicates_host_side(self):
        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=4,
            pipeline_model_parallel_split_rank=2)
        assert parallel_state.get_pipeline_model_parallel_split_rank() == 2
        # host-side (untraced) rank is 0 -> encoder section
        assert parallel_state.is_pipeline_stage_before_split(0)
        assert parallel_state.is_pipeline_stage_before_split(1)
        assert not parallel_state.is_pipeline_stage_before_split(2)
        assert parallel_state.is_pipeline_stage_after_split(2)
        assert parallel_state.is_pipeline_stage_after_split(3)
        assert not parallel_state.is_pipeline_stage_after_split(1)
        parallel_state.destroy_model_parallel()
        # no split configured: both predicates pass (reference semantics)
        parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=2)
        assert parallel_state.get_pipeline_model_parallel_split_rank() is None
        assert parallel_state.is_pipeline_stage_before_split(1)
        assert parallel_state.is_pipeline_stage_after_split(0)
        parallel_state.destroy_model_parallel()

    def test_init_validation(self):
        parallel_state.destroy_model_parallel()
        with pytest.raises(ValueError, match="split"):
            parallel_state.initialize_model_parallel(
                pipeline_model_parallel_size=2,
                pipeline_model_parallel_split_rank=2)
        with pytest.raises(ValueError, match="interleaved"):
            parallel_state.initialize_model_parallel(
                pipeline_model_parallel_size=4,
                virtual_pipeline_model_parallel_size=2,
                pipeline_model_parallel_split_rank=2)
        parallel_state.destroy_model_parallel()


class TestVPPGenerality:
    """The interleaved schedule beyond the vpp=2 comfort zone (VERDICT r3
    weak #5): vpp=3, microbatch counts indivisible by the schedule's
    natural granularity, vpp x M cross-products, and the uneven
    layers-per-stage guard. The reference's interleaved schedule requires
    M % pp == 0 (``fwd_bwd_pipelining_with_interleaving.py:27-744``
    asserts it); the wavefront scan here has no such constraint — these
    tests pin that the generality is real, not assumed."""

    def _run(self, vpp, M, n_layers, S=2, bs=None):
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=S)
        cfg = _gpt_config(num_layers=n_layers)
        ref_model = GPTModel(cfg)
        ref_params = ref_model.init(jax.random.PRNGKey(0))
        pmodel = PipelinedGPT(cfg, pipeline_size=S, num_microbatches=M,
                              virtual_pipeline_size=vpp)
        pparams = {
            "embedding": ref_params["embedding"],
            "stages": arrange_layers_for_pipeline(
                ref_params["transformer"]["layers"], S, vpp),
            "final_layernorm": ref_params["transformer"]["final_layernorm"],
        }
        bs = bs or 2 * M
        seq = 16
        tokens = jax.random.randint(jax.random.PRNGKey(1), (bs, seq), 0, 128)
        labels = jax.random.randint(jax.random.PRNGKey(2), (bs, seq), 0, 128)
        mb = split_batch_into_microbatches(
            {"tokens": tokens, "labels": labels}, M)
        loss_fn = pmodel.make_loss_fn()
        spec = pmodel.spec()
        run = jax.jit(shard_map(
            jax.value_and_grad(loss_fn), mesh=mesh,
            in_specs=(spec, P()),
            out_specs=(P(), spec),
            check_vma=False))
        loss, grads = run(pparams, mb)
        ref_loss, ref_grads = jax.jit(jax.value_and_grad(
            lambda p: ref_model.apply(p, tokens, labels)))(ref_params)
        parallel_state.destroy_model_parallel()

        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=2e-4, atol=2e-5)
        g = np.asarray(grads["stages"]["mlp"]["dense_h_to_4h"]["weight"])
        ref_g = np.asarray(
            ref_grads["transformer"]["layers"]["mlp"]["dense_h_to_4h"]
            ["weight"])
        # [S, vpp, Lc, ...] -> [L, ...] with v = c*S + i
        g_flat = g.transpose(1, 0, 2, *range(3, g.ndim)).reshape(ref_g.shape)
        np.testing.assert_allclose(g_flat, ref_g, rtol=2e-3, atol=2e-5)
        np.testing.assert_allclose(
            np.asarray(grads["embedding"]["word_embeddings"]["weight"]),
            np.asarray(ref_grads["embedding"]["word_embeddings"]["weight"]),
            rtol=2e-3, atol=2e-5)

    @pytest.mark.slow
    def test_vpp3_pp2_six_layers(self):
        self._run(vpp=3, M=4, n_layers=6)

    @pytest.mark.slow
    def test_vpp2_microbatches_indivisible_by_pp(self):
        # M=5 with pp=2: indivisible by the pipeline size (the reference
        # asserts M % pp == 0; the lock-step scan doesn't need it)
        self._run(vpp=2, M=5, n_layers=4)

    @pytest.mark.slow
    def test_vpp3_microbatches_indivisible(self):
        # M=5 against V = S*vpp = 6 virtual stages: M < V and coprime
        self._run(vpp=3, M=5, n_layers=6)

    @pytest.mark.slow
    def test_vpp2_single_microbatch(self):
        # M=1: pure bubble — every tick is warmup/cooldown
        self._run(vpp=2, M=1, n_layers=4, bs=4)

    def test_uneven_layers_per_stage_raises(self):
        parallel_state.destroy_model_parallel()
        cfg = _gpt_config(num_layers=5)
        with pytest.raises(ValueError, match="divide evenly"):
            PipelinedGPT(cfg, pipeline_size=2, num_microbatches=2)
        with pytest.raises(ValueError, match="divide evenly"):
            PipelinedGPT(cfg, pipeline_size=2, num_microbatches=2,
                         virtual_pipeline_size=2)
        cfg6 = _gpt_config(num_layers=6)
        with pytest.raises(ValueError, match="divide evenly"):
            # 6 layers, S*vpp = 4 virtual stages: indivisible
            PipelinedGPT(cfg6, pipeline_size=2, num_microbatches=2,
                         virtual_pipeline_size=2)
