"""Mesh registry tests (analog of ``tests/L0/run_transformer/test_parallel_state.py``)."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.utils.sharding import shard_map


def teardown_function():
    ps.destroy_model_parallel()


def test_initialize_and_sizes():
    mesh = ps.initialize_model_parallel(tensor_model_parallel_size=2,
                                        pipeline_model_parallel_size=2)
    assert ps.model_parallel_is_initialized()
    assert ps.get_tensor_model_parallel_world_size() == 2
    assert ps.get_pipeline_model_parallel_world_size() == 2
    assert ps.get_data_parallel_world_size() == 2
    assert ps.get_context_parallel_world_size() == 1
    assert ps.get_model_parallel_world_size() == 4
    assert mesh.axis_names == ps.MESH_AXIS_NAMES


def test_invalid_sizes():
    with pytest.raises(RuntimeError):
        ps.initialize_model_parallel(tensor_model_parallel_size=3)


def test_uninitialized_raises():
    with pytest.raises(RuntimeError):
        ps.get_mesh()


def test_rank_inside_shard_map():
    mesh = ps.initialize_model_parallel(tensor_model_parallel_size=2)
    import jax.numpy as jnp

    @shard_map(mesh=mesh, in_specs=P("tensor"), out_specs=P("tensor"))
    def get_rank(x):
        return x + ps.get_tensor_model_parallel_rank()

    out = get_rank(jnp.zeros((2, 1)))
    np.testing.assert_allclose(np.asarray(out).ravel(), [0, 1])


def test_rank_on_controller_is_zero():
    ps.initialize_model_parallel()
    assert ps.get_tensor_model_parallel_rank() == 0
    assert ps.is_pipeline_first_stage()
    assert ps.is_pipeline_last_stage()  # pp=1


def test_virtual_pipeline_state():
    ps.initialize_model_parallel(pipeline_model_parallel_size=2,
                                 virtual_pipeline_model_parallel_size=2)
    assert ps.get_virtual_pipeline_model_parallel_world_size() == 2
    ps.set_virtual_pipeline_model_parallel_rank(1)
    assert ps.get_virtual_pipeline_model_parallel_rank() == 1
    assert not ps.is_pipeline_first_stage()


def test_fake_world_size_override():
    ps.initialize_model_parallel()
    ps.set_tensor_model_parallel_world_size(8)
    assert ps.get_tensor_model_parallel_world_size() == 8
    ps.set_tensor_model_parallel_world_size(None)
    assert ps.get_tensor_model_parallel_world_size() == 1


def test_destroy():
    ps.initialize_model_parallel()
    ps.destroy_model_parallel()
    assert not ps.model_parallel_is_initialized()


def test_rank_info_string():
    ps.initialize_model_parallel(tensor_model_parallel_size=2)
    s = ps.get_rank_info()
    assert "tp=2" in s and "dp=4" in s
