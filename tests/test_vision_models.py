"""Vision model tests: ResNet, DCGAN, ViT.

Mirrors the reference's example-level coverage (``examples/imagenet``,
``examples/dcgan`` drive RN50/DCGAN through amp + DDP; SyncBN numerics in
``tests/distributed/synced_batchnorm/``): shape/dtype contracts, a train
step that actually descends, and SyncBN-inside-ResNet parity between a
sharded run and the equivalent unsharded batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from apex_tpu.models import (
    DCGANConfig,
    Discriminator,
    Generator,
    ResNet,
    ResNetConfig,
    resnet18,
    resnet50,
    vit_b16,
)
from apex_tpu.optimizers import FusedSGD


class TestResNet:
    @pytest.mark.slow  # full RN50 build+forward is compile-bound (ROADMAP tiers)
    def test_resnet50_shapes(self):
        model = resnet50(num_classes=10)
        params, state = model.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 64, 3))
        logits, new_state = jax.jit(
            lambda p, s, x: model.apply(p, s, x, train=True))(params, state, x)
        assert logits.shape == (2, 10)
        assert logits.dtype == jnp.float32
        # running stats updated
        old = state["stem"]["bn"]["mean"]
        new = new_state["stem"]["bn"]["mean"]
        assert not np.allclose(old, new)

    @pytest.mark.slow  # compile-bound eval sweep: slow tier (ROADMAP)

    def test_resnet18_eval_deterministic(self):
        model = resnet18(num_classes=4)
        params, state = model.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        l1, s1 = model.apply(params, state, x, train=False)
        l2, s2 = model.apply(params, state, x, train=False)
        np.testing.assert_allclose(l1, l2)
        # eval does not touch stats
        jax.tree.map(np.testing.assert_allclose, s1, state)

    def test_bf16_compute(self):
        model = ResNet(ResNetConfig(depth=18, num_classes=4,
                                    compute_dtype=jnp.bfloat16))
        params, state = model.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        logits, _ = jax.jit(
            lambda p, s, x: model.apply(p, s, x, train=True))(params, state, x)
        assert logits.dtype == jnp.float32
        assert np.isfinite(np.asarray(logits)).all()

    @pytest.mark.slow
    def test_train_step_descends(self):
        model = resnet18(num_classes=4)
        params, state = model.init(jax.random.PRNGKey(0))
        opt = FusedSGD(lr=0.05, momentum=0.9)
        opt_state = opt.init(params)
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 32, 32, 3))
        y = jax.random.randint(jax.random.PRNGKey(2), (8,), 0, 4)

        @jax.jit
        def step(params, state, opt_state):
            def loss_fn(p):
                logits, new_s = model.apply(p, state, x, train=True)
                logp = jax.nn.log_softmax(logits)
                return -jnp.mean(logp[jnp.arange(8), y]), new_s
            (loss, new_s), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            params, opt_state = opt.step(grads, params, opt_state)
            return params, new_s, opt_state, loss

        losses = []
        for _ in range(8):
            params, state, opt_state, loss = step(params, state, opt_state)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    @pytest.mark.slow
    def test_syncbn_matches_global_batch(self):
        """Sharded ResNet (BN psum over 'data') == unsharded on full batch —
        the property the reference tests in
        tests/distributed/synced_batchnorm/."""
        devices = jax.devices()
        if len(devices) < 4:
            pytest.skip("needs >=4 devices")
        mesh = Mesh(np.array(devices[:4]), ("data",))
        cfg_sync = ResNetConfig(depth=18, num_classes=4, axis_name="data")
        cfg_ref = ResNetConfig(depth=18, num_classes=4, axis_name=None)
        m_sync, m_ref = ResNet(cfg_sync), ResNet(cfg_ref)
        params, state = m_ref.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 32, 32, 3))

        ref_logits, ref_state = m_ref.apply(params, state, x, train=True)

        sharded = shard_map(
            lambda p, s, x: m_sync.apply(p, s, x, train=True),
            mesh=mesh,
            in_specs=(P(), P(), P("data")),
            out_specs=(P("data"), P()))
        logits, new_state = sharded(params, state, x)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(ref_logits),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(
            np.asarray(new_state["stem"]["bn"]["mean"]),
            np.asarray(ref_state["stem"]["bn"]["mean"]), rtol=1e-5,
            atol=1e-6)


class TestDCGAN:
    def test_generator_shapes(self):
        cfg = DCGANConfig(latent_dim=32, gen_features=16, disc_features=16)
        gen = Generator(cfg)
        params, state = gen.init(jax.random.PRNGKey(0))
        z = jax.random.normal(jax.random.PRNGKey(1), (4, 32))
        img, _ = jax.jit(
            lambda p, s, z: gen.apply(p, s, z, train=True))(params, state, z)
        assert img.shape == (4, 64, 64, 3)
        assert float(jnp.max(jnp.abs(img))) <= 1.0

    def test_discriminator_shapes(self):
        cfg = DCGANConfig(latent_dim=32, gen_features=16, disc_features=16)
        disc = Discriminator(cfg)
        params, state = disc.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 64, 64, 3))
        logit, _ = jax.jit(
            lambda p, s, x: disc.apply(p, s, x, train=True))(params, state, x)
        assert logit.shape == (4,)

    def test_adversarial_step(self):
        """One G/D update each with separate optimizers — the multi-model,
        multi-optimizer capability of examples/dcgan/main_amp.py."""
        cfg = DCGANConfig(latent_dim=16, gen_features=8, disc_features=8)
        gen, disc = Generator(cfg), Discriminator(cfg)
        gp, gs = gen.init(jax.random.PRNGKey(0))
        dp, ds = disc.init(jax.random.PRNGKey(1))
        g_opt = FusedSGD(lr=0.01)
        d_opt = FusedSGD(lr=0.01)
        g_os, d_os = g_opt.init(gp), d_opt.init(dp)
        z = jax.random.normal(jax.random.PRNGKey(2), (4, 16))
        real = jax.random.normal(jax.random.PRNGKey(3), (4, 64, 64, 3))

        def bce(logit, target):
            return jnp.mean(jnp.maximum(logit, 0) - logit * target
                            + jnp.log1p(jnp.exp(-jnp.abs(logit))))

        @jax.jit
        def step(gp, gs, dp, ds, g_os, d_os):
            def d_loss(dp):
                fake, _ = gen.apply(gp, gs, z, train=True)
                rl, _ = disc.apply(dp, ds, real, train=True)
                fl, _ = disc.apply(dp, ds, fake, train=True)
                return bce(rl, jnp.ones(4)) + bce(fl, jnp.zeros(4))
            dl, dg = jax.value_and_grad(d_loss)(dp)
            dp, d_os = d_opt.step(dg, dp, d_os)

            def g_loss(gp):
                fake, _ = gen.apply(gp, gs, z, train=True)
                fl, _ = disc.apply(dp, ds, fake, train=True)
                return bce(fl, jnp.ones(4))
            gl, gg = jax.value_and_grad(g_loss)(gp)
            gp, g_os = g_opt.step(gg, gp, g_os)
            return gp, dp, g_os, d_os, dl, gl

        gp, dp, g_os, d_os, dl, gl = step(gp, gs, dp, ds, g_os, d_os)
        assert np.isfinite(float(dl)) and np.isfinite(float(gl))


class TestViT:
    def test_vit_ctor(self):
        model = vit_b16(image_size=224, num_classes=10)
        assert model.config.num_patches == 196
        assert model.config.transformer.hidden_size == 768

    def test_vit_shapes(self):
        from apex_tpu.models.vit import ViTConfig, ViTModel, _encoder_config
        enc = _encoder_config(2, 64, 4, ffn_hidden_size=128)
        model = ViTModel(ViTConfig(image_size=32, patch_size=16,
                                   num_classes=10, transformer=enc))
        params = model.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        logits = jax.jit(model.apply)(params, x)
        assert logits.shape == (2, 10)
        assert np.isfinite(np.asarray(logits)).all()

    @pytest.mark.slow
    def test_vit_grad_flows(self):
        from apex_tpu.models.vit import ViTConfig, ViTModel, _encoder_config
        enc = _encoder_config(2, 64, 4, ffn_hidden_size=128)
        model = ViTModel(ViTConfig(image_size=32, patch_size=16,
                                   num_classes=10, transformer=enc))
        params = model.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        y = jnp.array([1, 3])

        def loss_fn(p):
            logits = model.apply(p, x)
            return -jnp.mean(jax.nn.log_softmax(logits)[jnp.arange(2), y])

        grads = jax.grad(loss_fn)(params)
        gnorm = jax.tree.reduce(
            lambda a, b: a + b,
            jax.tree.map(lambda g: float(jnp.sum(jnp.abs(g))), grads))
        assert np.isfinite(gnorm) and gnorm > 0
