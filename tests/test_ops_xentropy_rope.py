"""Cross-entropy + RoPE parity tests (analogs of ``apex/contrib/test/xentropy``
and the fused_rope functional tests)."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.ops import (
    softmax_cross_entropy_loss,
    fused_rope,
    fused_rope_cached,
    fused_rope_thd,
    fused_rope_2d,
)


def ref_xent(logits, labels, smoothing=0.0):
    lf = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(lf, axis=-1)
    v = logits.shape[-1]
    onehot = jax.nn.one_hot(labels, v)
    target = (1 - smoothing) * onehot + smoothing / v
    return -jnp.sum(target * logp, axis=-1)


def test_xentropy_values_and_grads():
    logits = jax.random.normal(jax.random.PRNGKey(0), (16, 50))
    labels = jax.random.randint(jax.random.PRNGKey(1), (16,), 0, 50)
    for sm in (0.0, 0.1):
        loss = softmax_cross_entropy_loss(logits, labels, sm)
        np.testing.assert_allclose(loss, ref_xent(logits, labels, sm), atol=1e-5)
        g = jax.grad(lambda l: jnp.sum(softmax_cross_entropy_loss(l, labels, sm)))(logits)
        gr = jax.grad(lambda l: jnp.sum(ref_xent(l, labels, sm)))(logits)
        np.testing.assert_allclose(g, gr, atol=1e-5)


def test_xentropy_ignore_index():
    logits = jax.random.normal(jax.random.PRNGKey(0), (8, 10))
    labels = jnp.array([0, 1, -100, 3, -100, 5, 6, 7])
    loss = softmax_cross_entropy_loss(logits, labels, 0.0)
    assert float(loss[2]) == 0.0 and float(loss[4]) == 0.0
    g = jax.grad(lambda l: jnp.sum(softmax_cross_entropy_loss(l, labels, 0.0)))(logits)
    np.testing.assert_allclose(g[2], 0.0, atol=1e-7)


def _freqs(s, d):
    inv = 1.0 / (10000.0 ** (jnp.arange(0, d, 2) / d))
    t = jnp.arange(s)[:, None] * inv[None, :]
    return jnp.concatenate([t, t], axis=-1)[:, None, None, :]


def ref_rope(t, freqs):
    f = freqs.astype(jnp.float32)
    cos, sin = jnp.cos(f), jnp.sin(f)
    rot = t[..., : f.shape[-1]]
    half = rot.shape[-1] // 2
    rot_half = jnp.concatenate([-rot[..., half:], rot[..., :half]], axis=-1)
    out = rot * cos + rot_half * sin
    return jnp.concatenate([out, t[..., f.shape[-1]:]], axis=-1).astype(t.dtype)


def test_rope_matches_reference_math():
    s, b, h, d = 12, 2, 4, 16
    t = jax.random.normal(jax.random.PRNGKey(0), (s, b, h, d))
    freqs = _freqs(s, d)
    np.testing.assert_allclose(fused_rope(t, freqs), ref_rope(t, freqs), atol=1e-5)
    # partial rotation
    freqs_half = _freqs(s, d // 2)
    np.testing.assert_allclose(
        fused_rope(t, freqs_half), ref_rope(t, freqs_half), atol=1e-5)


def test_rope_grad_is_inverse_rotation():
    s, b, h, d = 8, 2, 2, 8
    t = jax.random.normal(jax.random.PRNGKey(0), (s, b, h, d))
    freqs = _freqs(s, d)
    g = jax.grad(lambda t: jnp.sum(fused_rope(t, freqs) * jnp.sin(t)))(t)
    gr = jax.grad(lambda t: jnp.sum(ref_rope(t, freqs) * jnp.sin(t)))(t)
    np.testing.assert_allclose(g, gr, atol=1e-5)


def test_rope_cached():
    s, b, h, d = 8, 2, 2, 8
    t = jax.random.normal(jax.random.PRNGKey(0), (s, b, h, d))
    f = _freqs(s, d).astype(jnp.float32)
    y = fused_rope_cached(t, jnp.cos(f), jnp.sin(f))
    np.testing.assert_allclose(y, fused_rope(t, f), atol=1e-6)


def test_rope_thd():
    d, h = 8, 2
    lens = [3, 5, 2]
    cu = jnp.array([0, 3, 8, 10])
    total = 10
    t = jax.random.normal(jax.random.PRNGKey(0), (total, h, d))
    freqs = _freqs(8, d)
    y = fused_rope_thd(t, cu, freqs)
    # manual: each sequence restarts positions
    off = 0
    for L in lens:
        seg = t[off:off + L][:, None]          # (L, 1, h, d) as (s, b, h, d)
        seg = jnp.transpose(seg, (0, 1, 2, 3))
        expect = ref_rope(seg, freqs[:L])
        np.testing.assert_allclose(y[off:off + L], expect[:, 0], atol=1e-5)
        off += L


def test_rope_2d_shapes():
    b, H, W, h, d = 2, 4, 4, 2, 8
    t = jax.random.normal(jax.random.PRNGKey(0), (b, H * W, h, d))
    fh = _freqs(H, d // 2)
    fw = _freqs(W, d // 2)
    y = fused_rope_2d(t, H, W, fh, fw)
    assert y.shape == t.shape
    assert jnp.isfinite(y).all()
