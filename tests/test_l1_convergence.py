"""L1-tier: amp opt-level convergence parity — the cross-product sweep.

Mirrors the reference's integration matrix (``tests/L1/common/run_test.sh:
29-48`` + ``compare.py``): train the same model under O0 (pure fp32
baseline) and the cross product of opt level x loss scale (default / 1.0 /
128.0 / dynamic) x keep_batchnorm_fp32 (default / True / False), plus the
FusedAdam O2 configuration (``ADAM_ARGS``), recording loss and grad-norm
traces and requiring them to track the baseline within precision-
appropriate tolerances. The reference does this with ResNet-50 on ImageNet
over hours; here a ResNet-18-w16 on synthetic data exercises the same
plumbing (cast policy, scaler flavors, master weights, BN dtype) in the
30-minute suite budget — the sweep samples the matrix the way run_test.sh's
loops do, skipping only redundant points.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.models import ResNet, ResNetConfig
from apex_tpu.optimizers import FusedAdam, FusedSGD
from apex_tpu.utils.tree import global_norm

# L1 by name and by nature: a convergence sweep (~15-25s per matrix point
# on CPU) — the slow tier, not the tier-1 quick gate
pytestmark = pytest.mark.slow

STEPS = 12


def _data(n=16, hw=24, classes=8):
    x = jax.random.normal(jax.random.PRNGKey(5), (n, hw, hw, 3))
    y = jax.random.randint(jax.random.PRNGKey(6), (n,), 0, classes)
    return x, y


def _cast_bn_params(params, dtype):
    """keep_batchnorm_fp32=False: BN scale/bias participate in half —
    the reference's ``--keep-batchnorm-fp32 False`` leg."""
    from jax.tree_util import tree_map_with_path

    def f(path, x):
        keys = "/".join(str(getattr(p, "key", p)) for p in path)
        return x.astype(dtype) if "bn" in keys else x

    return tree_map_with_path(f, params)


def _train_trace(opt_level: str, loss_scale=None, keep_bn=None,
                 use_adam: bool = False):
    """Train a small ResNet under one amp config; return (losses, gnorms)."""
    amp_state = amp.initialize(
        opt_level, loss_scale=loss_scale, keep_batchnorm_fp32=keep_bn,
        half_dtype=jnp.bfloat16)
    props = amp_state.properties
    compute = (jnp.float32 if opt_level == "O0" else jnp.bfloat16)
    model = ResNet(ResNetConfig(depth=18, num_classes=8, width=16,
                                compute_dtype=compute))
    params, state = model.init(jax.random.PRNGKey(0))
    if use_adam:
        # run_test.sh ADAM_ARGS: --opt-level O2 --keep-batchnorm-fp32 False
        # --fused-adam
        opt = FusedAdam(lr=1e-3, master_weights=bool(props.master_weights))
    else:
        opt = FusedSGD(lr=0.05, momentum=0.9,
                       master_weights=bool(props.master_weights))
    opt_state = opt.init(params)
    scaler = amp_state.scaler
    sstate = amp_state.scaler_states[0]
    x, y = _data()
    half_bn = props.keep_batchnorm_fp32 is False and opt_level != "O0"

    @jax.jit
    def step(params, state, opt_state, sstate):
        def loss_fn(p):
            if half_bn:
                p = _cast_bn_params(p, jnp.bfloat16)
            logits, new_s = model.apply(p, state, x, train=True)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(logp[jnp.arange(16), y]), new_s

        def scaled(p):
            loss, new_s = loss_fn(p)
            return scaler.scale(loss, sstate), (loss, new_s)

        (_, (loss, new_s)), grads = jax.value_and_grad(
            scaled, has_aux=True)(params)
        grads, found_inf = scaler.unscale(grads, sstate)
        gnorm = global_norm(grads)
        params, opt_state = opt.step(grads, params, opt_state,
                                     found_inf=found_inf)
        new_sstate = scaler.update(sstate, found_inf)
        return params, new_s, opt_state, new_sstate, loss, gnorm

    losses, gnorms = [], []
    for _ in range(STEPS):
        params, state, opt_state, sstate, loss, gnorm = step(
            params, state, opt_state, sstate)
        losses.append(float(loss))
        gnorms.append(float(gnorm))
    return np.array(losses), np.array(gnorms)


@pytest.fixture(scope="module")
def baseline():
    return _train_trace("O0")


def _check(losses, gnorms, base, loss_tol):
    b_losses, b_gnorms = base
    assert np.isfinite(losses).all() and np.isfinite(gnorms).all()
    # same qualitative descent
    assert losses[-1] < losses[0]
    np.testing.assert_allclose(losses, b_losses, rtol=loss_tol,
                               atol=loss_tol)
    # grad norms must track too (catches broken unscale factors that
    # leave losses within tolerance), loosely: bf16 grads drift more
    np.testing.assert_allclose(gnorms, b_gnorms,
                               rtol=3 * loss_tol, atol=3 * loss_tol)


# the run_test.sh matrix, sampled: every loss-scale leg for O1 and O2,
# both keep_batchnorm legs for O2 (None = the level's default)
_SWEEP = [
    ("O1", None, None),
    ("O1", 1.0, None),
    ("O1", 128.0, None),
    ("O1", "dynamic", None),
    ("O2", None, None),
    ("O2", 1.0, None),
    ("O2", 128.0, None),
    ("O2", "dynamic", None),
    ("O2", None, True),
    ("O2", None, False),
]


class TestOptLevelSweep:
    """Loss/grad-trace parity vs the O0 baseline across the matrix
    (reference ``compare.py`` semantics at bf16-appropriate tolerances)."""

    @pytest.mark.parametrize("opt_level,loss_scale,keep_bn", _SWEEP)
    def test_tracks_baseline(self, baseline, opt_level, loss_scale, keep_bn):
        losses, gnorms = _train_trace(opt_level, loss_scale=loss_scale,
                                      keep_bn=keep_bn)
        _check(losses, gnorms, baseline, loss_tol=0.12)

    @pytest.mark.parametrize("keep_bn", [True, False])
    def test_o3_runs_and_descends(self, keep_bn):
        # O3 (no master weights, pure half) is allowed to drift further;
        # the reference only requires it to run and roughly converge
        losses, _ = _train_trace("O3", keep_bn=keep_bn)
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    def test_o2_fused_adam(self, baseline):
        # ADAM_ARGS leg: O2 + keep_batchnorm_fp32 False + FusedAdam; Adam's
        # trajectory differs from SGD's, so the bar is finite + descending
        # with the amp plumbing (scaler, master weights, half BN) active
        losses, gnorms = _train_trace("O2", keep_bn=False, use_adam=True)
        assert np.isfinite(losses).all() and np.isfinite(gnorms).all()
        assert losses[-1] < losses[0]

    def test_o0_deterministic(self, baseline):
        again = _train_trace("O0")
        np.testing.assert_allclose(again[0], baseline[0], rtol=1e-6)
