"""L1-tier: amp opt-level convergence parity.

Mirrors the reference's integration sweep (``tests/L1/common/run_test.sh:
29-48`` + ``compare.py``): train the same model under O0 (pure fp32 baseline)
and each other opt level / loss-scale configuration, record loss and
grad-norm traces, and require them to track the baseline within
precision-appropriate tolerances. The reference does this with ResNet-50 on
ImageNet; here a conv+norm+linear stack on synthetic data exercises the same
plumbing (cast policy, scaler, master weights, BN fp32) in minutes not hours.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.models import ResNet, ResNetConfig
from apex_tpu.optimizers import FusedSGD
from apex_tpu.utils.tree import global_norm

STEPS = 12


def _data(n=16, hw=24, classes=8):
    x = jax.random.normal(jax.random.PRNGKey(5), (n, hw, hw, 3))
    y = jax.random.randint(jax.random.PRNGKey(6), (n,), 0, classes)
    return x, y


def _train_trace(opt_level: str, loss_scale=None):
    """Train a small ResNet under one amp config; return (losses, gnorms)."""
    amp_state = amp.initialize(
        opt_level, loss_scale=loss_scale,
        half_dtype=jnp.bfloat16)
    compute = (jnp.float32 if opt_level == "O0" else jnp.bfloat16)
    model = ResNet(ResNetConfig(depth=18, num_classes=8, width=16,
                                compute_dtype=compute))
    params, state = model.init(jax.random.PRNGKey(0))
    opt = FusedSGD(lr=0.05, momentum=0.9,
                   master_weights=(opt_level == "O2"))
    opt_state = opt.init(params)
    scaler = amp_state.scaler
    sstate = amp_state.scaler_states[0]
    x, y = _data()

    @jax.jit
    def step(params, state, opt_state, sstate):
        def loss_fn(p):
            logits, new_s = model.apply(p, state, x, train=True)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(logp[jnp.arange(16), y]), new_s

        def scaled(p):
            loss, new_s = loss_fn(p)
            return scaler.scale(loss, sstate), (loss, new_s)

        (_, (loss, new_s)), grads = jax.value_and_grad(
            scaled, has_aux=True)(params)
        grads, found_inf = scaler.unscale(grads, sstate)
        gnorm = global_norm(grads)
        params, opt_state = opt.step(grads, params, opt_state,
                                     found_inf=found_inf)
        new_sstate = scaler.update(sstate, found_inf)
        return params, new_s, opt_state, new_sstate, loss, gnorm

    losses, gnorms = [], []
    for _ in range(STEPS):
        params, state, opt_state, sstate, loss, gnorm = step(
            params, state, opt_state, sstate)
        losses.append(float(loss))
        gnorms.append(float(gnorm))
    return np.array(losses), np.array(gnorms)


@pytest.fixture(scope="module")
def baseline():
    return _train_trace("O0")


class TestOptLevelParity:
    """Each O-level's loss trace must track the O0 baseline (reference
    compare.py semantics, loosened to bf16-appropriate tolerances)."""

    def _check(self, losses, gnorms, base, loss_tol):
        b_losses, b_gnorms = base
        assert np.isfinite(losses).all() and np.isfinite(gnorms).all()
        # same qualitative descent
        assert losses[-1] < losses[0]
        np.testing.assert_allclose(losses, b_losses, rtol=loss_tol,
                                   atol=loss_tol)
        # grad norms must track too (catches broken unscale factors that
        # leave losses within tolerance), loosely: bf16 grads drift more
        np.testing.assert_allclose(gnorms, b_gnorms,
                                   rtol=3 * loss_tol, atol=3 * loss_tol)

    def test_o1(self, baseline):
        losses, gnorms = _train_trace("O1")
        self._check(losses, gnorms, baseline, loss_tol=0.12)

    def test_o2(self, baseline):
        losses, gnorms = _train_trace("O2")
        self._check(losses, gnorms, baseline, loss_tol=0.12)

    def test_o2_static_scale(self, baseline):
        losses, gnorms = _train_trace("O2", loss_scale=128.0)
        self._check(losses, gnorms, baseline, loss_tol=0.12)

    def test_o3(self, baseline):
        # O3 (no master weights, pure half) is allowed to drift further;
        # the reference only requires it to run and roughly converge
        losses, _ = _train_trace("O3")
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    def test_o0_deterministic(self, baseline):
        again = _train_trace("O0")
        np.testing.assert_allclose(again[0], baseline[0], rtol=1e-6)
