"""Fused 1x1-conv + BN + stats kernel tests (ops/conv_fused.py).

Capability counterpart of the reference's fused conv-epilogue tests
(``apex/contrib/test/conv_bias_relu``, ``apex/contrib/test/bottleneck``):
kernel-vs-composition parity for forward, gradients (including the
statistics cotangent — the BN backward-through-stats path), multi-block
grids with tail masking, and full bottleneck-block / ResNet-50 parity
between the fused and unfused training paths.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.ops._support as _support
from apex_tpu.ops.conv_fused import _ref_impl, conv1x1_bn_act


@pytest.fixture
def interpret(monkeypatch):
    monkeypatch.setenv("APEX_TPU_FORCE_PALLAS", "interpret")
    _support.pallas_mode.cache_clear()
    yield
    _support.pallas_mode.cache_clear()


def _ref(x, w, a=None, b=None, *, relu=False, shift=None):
    k, n = w.shape
    x2 = x.reshape(-1, k)
    if shift is None:
        shift = jnp.zeros((n,), jnp.float32)
    if a is None:
        y, s = _ref_impl(x2, None, None, w, shift, affine=False, relu=False)
    else:
        y, s = _ref_impl(x2, a.astype(jnp.float32), b.astype(jnp.float32),
                         w, shift, affine=True, relu=relu)
    return y.reshape(*x.shape[:-1], n), s


class TestOpParity:
    @pytest.mark.parametrize("affine,relu", [(False, False), (True, False),
                                             (True, True)])
    def test_forward(self, interpret, affine, relu):
        k, n, m = 64, 96, 200
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, (m, k), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (k, n), jnp.float32)
        a = jnp.abs(jax.random.normal(jax.random.PRNGKey(2), (k,))) + 0.5 \
            if affine else None
        b = jax.random.normal(jax.random.PRNGKey(3), (k,)) if affine else None
        c = jax.random.normal(jax.random.PRNGKey(4), (n,))
        y, s = conv1x1_bn_act(x, w, a, b, relu=relu, stats_shift=c)
        yr, sr = _ref(x, w, a, b, relu=relu, shift=c)
        np.testing.assert_allclose(y, yr, atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(s, sr, atol=1e-2, rtol=1e-4)

    def test_forward_bf16(self, interpret):
        k, n, m = 64, 64, 128
        x = jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.bfloat16)
        w = jax.random.normal(jax.random.PRNGKey(1), (k, n), jnp.bfloat16)
        y, s = conv1x1_bn_act(x, w)
        yr, sr = _ref(x, w)
        np.testing.assert_allclose(np.asarray(y, np.float32),
                                   np.asarray(yr, np.float32),
                                   atol=0.1, rtol=0.05)
        np.testing.assert_allclose(s, sr, atol=2.0, rtol=0.02)

    def test_gradients_with_stats_cotangent(self, interpret):
        """Statistics cotangent flows through the kernel backward — the
        fused equivalent of BN's backward-through-batch-stats terms."""
        k, n, m = 32, 48, 96
        x = jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (k, n), jnp.float32)
        a = jnp.abs(jax.random.normal(jax.random.PRNGKey(2), (k,))) + 0.5
        b = jax.random.normal(jax.random.PRNGKey(3), (k,))
        c = jax.random.normal(jax.random.PRNGKey(4), (n,))
        r1 = jax.random.normal(jax.random.PRNGKey(5), (m, n))
        r2 = jax.random.normal(jax.random.PRNGKey(6), (2, n))

        def loss(fn):
            def f(x, a, b, w):
                y, s = fn(x, w, a, b, relu=True, shift_kw=c)
                return jnp.sum(y * r1) + jnp.sum(s * r2)
            return f

        fused = loss(lambda x, w, a, b, relu, shift_kw:
                     conv1x1_bn_act(x, w, a, b, relu=relu,
                                    stats_shift=shift_kw))
        ref = loss(lambda x, w, a, b, relu, shift_kw:
                   _ref(x, w, a, b, relu=relu, shift=shift_kw))
        gf = jax.grad(fused, argnums=(0, 1, 2, 3))(x, a, b, w)
        gr = jax.grad(ref, argnums=(0, 1, 2, 3))(x, a, b, w)
        for f_, r_ in zip(gf, gr):
            np.testing.assert_allclose(f_, r_, atol=1e-3, rtol=1e-3)

    def test_multiblock_tail_masking(self, interpret):
        """m not divisible by the block size: tail rows must not leak into
        the statistics or the dW/da/db accumulators."""
        k, n = 16, 16
        m = 40  # bm >= 16 -> last block partial
        import apex_tpu.ops.conv_fused as cf
        orig = cf._pick_bm
        cf._pick_bm = lambda *a, **kw: 16
        try:
            x = jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.float32)
            w = jax.random.normal(jax.random.PRNGKey(1), (k, n), jnp.float32)
            a = jnp.abs(jax.random.normal(jax.random.PRNGKey(2), (k,))) + 0.5
            b = jax.random.normal(jax.random.PRNGKey(3), (k,))

            def f(fn):
                def g(x, a, b, w):
                    y, s = fn(x, w, a, b)
                    return jnp.sum(y ** 2) + jnp.sum(s ** 2)
                return g

            fused = f(lambda x, w, a, b: conv1x1_bn_act(x, w, a, b,
                                                        relu=True))
            ref = f(lambda x, w, a, b: _ref(x, w, a, b, relu=True))
            np.testing.assert_allclose(fused(x, a, b, w), ref(x, a, b, w),
                                       rtol=1e-5)
            gf = jax.grad(fused, argnums=(0, 1, 2, 3))(x, a, b, w)
            gr = jax.grad(ref, argnums=(0, 1, 2, 3))(x, a, b, w)
            for f_, r_ in zip(gf, gr):
                np.testing.assert_allclose(f_, r_, atol=1e-3, rtol=1e-3)
        finally:
            cf._pick_bm = orig


class TestResNetFusedParity:
    """Fused bottleneck path == unfused XLA path, forward + grads + state."""

    def _build(self, fused):
        from apex_tpu.models import ResNet, ResNetConfig
        cfg = ResNetConfig(depth=50, num_classes=8, fused_conv=fused)
        return ResNet(cfg)

    @pytest.mark.slow
    def test_model_parity(self, interpret):
        m_f, m_u = self._build(True), self._build(False)
        params, state = m_u.init(jax.random.PRNGKey(0))
        # batch 4 @ 64px keeps the deepest stage's per-channel sample count
        # non-degenerate (var >> eps), so 1/sqrt(var+eps) does not amplify
        # fp32 reassociation noise between the two compute paths
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 64, 64, 3))
        y = jax.random.randint(jax.random.PRNGKey(2), (4,), 0, 8)

        def loss(model):
            def f(p):
                logits, new_s = model.apply(p, state, x, train=True)
                logp = jax.nn.log_softmax(logits)
                return -jnp.mean(logp[jnp.arange(4), y]), new_s
            return f

        (lf, sf), gf = jax.value_and_grad(loss(m_f), has_aux=True)(params)
        (lu, su), gu = jax.value_and_grad(loss(m_u), has_aux=True)(params)
        np.testing.assert_allclose(lf, lu, rtol=2e-4)
        jax.tree.map(lambda a_, b_: np.testing.assert_allclose(
            a_, b_, atol=5e-3, rtol=5e-3), sf, su)
        jax.tree.map(lambda a_, b_: np.testing.assert_allclose(
            a_, b_, atol=1e-2, rtol=5e-2), gf, gu)

    @pytest.mark.slow
    def test_eval_uses_unfused_path(self, interpret):
        """Eval mode must not require the training-stats kernel."""
        m_f = self._build(True)
        params, state = m_f.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        logits, new_s = m_f.apply(params, state, x, train=False)
        assert logits.shape == (2, 8)
        jax.tree.map(np.testing.assert_allclose, new_s, state)


class TestConv3x3Parity:
    """Fused 3x3 kernel (full-image blocks, 9-tap shifted GEMMs) vs the
    XLA composition oracle — forward, stats, and all gradients including
    the statistics cotangent."""

    def _args(self, nimg=4, H=8, W=8, k=16, n=32, affine=True):
        x = jax.random.normal(jax.random.PRNGKey(0), (nimg, H, W, k),
                              jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (3, 3, k, n),
                              jnp.float32) * 0.2
        a = (jnp.abs(jax.random.normal(jax.random.PRNGKey(2), (k,))) + 0.5
             if affine else None)
        b = (jax.random.normal(jax.random.PRNGKey(3), (k,)) if affine
             else None)
        c = jax.random.normal(jax.random.PRNGKey(4), (n,))
        return x, w, a, b, c

    @pytest.mark.parametrize("affine,relu", [(False, False), (True, True)])
    def test_forward(self, interpret, affine, relu):
        from apex_tpu.ops.conv_fused import _c3_ref_impl, conv3x3_bn_act

        x, w, a, b, c = self._args(affine=affine)
        y, s = conv3x3_bn_act(x, w, a, b, relu=relu, stats_shift=c)
        if affine:
            yr, sr = _c3_ref_impl(x, a, b, w, c, affine=True, relu=relu)
        else:
            yr, sr = _c3_ref_impl(x, None, None, w, c, affine=False,
                                  relu=False)
        np.testing.assert_allclose(y, yr, atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(s, sr, atol=1e-2, rtol=1e-4)

    def test_gradients_with_stats_cotangent(self, interpret):
        from apex_tpu.ops.conv_fused import _c3_ref_impl, conv3x3_bn_act

        x, w, a, b, c = self._args(nimg=2, H=6, W=6, k=8, n=16)
        r1 = jax.random.normal(jax.random.PRNGKey(5), (2, 6, 6, 16))
        r2 = jax.random.normal(jax.random.PRNGKey(6), (2, 16))

        def loss(fn):
            def f(x, a, b, w):
                y, s = fn(x, a, b, w)
                return jnp.sum(y * r1) + jnp.sum(s * r2)
            return f

        gf = jax.grad(loss(lambda x, a, b, w: conv3x3_bn_act(
            x, w, a, b, relu=True, stats_shift=c)),
            argnums=(0, 1, 2, 3))(x, a, b, w)
        gr = jax.grad(loss(lambda x, a, b, w: _c3_ref_impl(
            x, a, b, w, c, affine=True, relu=True)),
            argnums=(0, 1, 2, 3))(x, a, b, w)
        for f_, r_ in zip(gf, gr):
            np.testing.assert_allclose(f_, r_, atol=2e-3, rtol=2e-3)

    def test_multi_image_grid(self, interpret):
        """nimg > images-per-block exercises the revisited dW/da/db
        accumulators across grid steps."""
        import apex_tpu.ops.conv_fused as cf

        orig = cf._c3_pick_bn
        cf._c3_pick_bn = lambda *a, **kw: 2
        try:
            from apex_tpu.ops.conv_fused import (_c3_ref_impl,
                                                 conv3x3_bn_act)

            x, w, a, b, c = self._args(nimg=6, H=4, W=4, k=8, n=8)

            def f(fn):
                def g(x, a, b, w):
                    y, s = fn(x, a, b, w)
                    return jnp.sum(y ** 2) + jnp.sum(s ** 2)
                return g

            fused = f(lambda x, a, b, w: conv3x3_bn_act(
                x, w, a, b, relu=True, stats_shift=c))
            ref = f(lambda x, a, b, w: _c3_ref_impl(
                x, a, b, w, c, affine=True, relu=True))
            np.testing.assert_allclose(fused(x, a, b, w), ref(x, a, b, w),
                                       rtol=1e-5)
            gf = jax.grad(fused, argnums=(0, 1, 2, 3))(x, a, b, w)
            gr = jax.grad(ref, argnums=(0, 1, 2, 3))(x, a, b, w)
            for f_, r_ in zip(gf, gr):
                np.testing.assert_allclose(f_, r_, atol=2e-3, rtol=2e-3)
        finally:
            cf._c3_pick_bn = orig

    def test_forward_backward_bf16(self, interpret):
        """bf16 is the production amp-O2 dtype and the 3x3 kernel has more
        dtype-sensitive cast points (zb, dy_c, out_dtype, fp32 dzp)."""
        from apex_tpu.ops.conv_fused import _c3_ref_impl, conv3x3_bn_act

        x, w, a, b, c = self._args(nimg=2, H=6, W=6, k=8, n=16)
        x, w = x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)

        def f(fn):
            def g(x, a, b, w):
                y, s = fn(x, a, b, w)
                return (jnp.sum(y.astype(jnp.float32) ** 2)
                        + jnp.sum(s * 1e-3))
            return g

        fused = f(lambda x, a, b, w: conv3x3_bn_act(
            x, w, a, b, relu=True, stats_shift=c))
        ref = f(lambda x, a, b, w: _c3_ref_impl(
            x, a, b, w, c, affine=True, relu=True))
        np.testing.assert_allclose(float(fused(x, a, b, w)),
                                   float(ref(x, a, b, w)), rtol=2e-2)
        gf = jax.grad(fused, argnums=(0, 1, 2, 3))(x, a, b, w)
        gr = jax.grad(ref, argnums=(0, 1, 2, 3))(x, a, b, w)
        for f_, r_ in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(f_, np.float32),
                                       np.asarray(r_, np.float32),
                                       atol=0.15, rtol=0.1)
