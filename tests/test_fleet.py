"""Fleet serving tests: router, draining restarts, sharded decode.

Three contracts on top of the single-supervisor stack:

- **Routing**: least-loaded dispatch (``queue_depth × EWMA(service_s)``)
  is deterministic, sticky for in-flight requests, and fleet-wide
  admission removes an open-breaker replica from the dispatch set
  instead of fast-failing the caller — ``FleetUnavailableError`` only
  when NO replica can take work.
- **Draining restarts**: a replica rebuild quiesces, migrates in-flight
  work TOKEN-EXACT to a peer (the supervisor's re-prefill continuations
  fleet-wide), health-probes, and rejoins — capacity never below N−1,
  every request terminal exactly once, the monitor fleet section
  reconciling key-for-key with the counters.
- **Sharded decode**: :class:`~apex_tpu.serving.fleet.ShardedEngine` on
  a tp=2 CPU mesh is token-exact against the unsharded engine (greedy
  AND sampled) with zero decode retraces — the multichip parity bar
  applied to serving.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.loadtest import Scenario, run_scenario
from apex_tpu.loadtest.__main__ import EXIT_OK, main as loadtest_main
from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.models.generation import generate
from apex_tpu.observability import (
    InMemorySink,
    JsonlSink,
    MetricsRegistry,
    build_report,
    render_report,
)
from apex_tpu.observability.report import FLEET_INCIDENT_COUNTERS
from apex_tpu.serving import (
    BREAKER_OPEN,
    EngineConfig,
    EngineSupervisor,
    EngineUnavailableError,
    FINISH_REASONS,
    InferenceEngine,
    Request,
    SamplingParams,
    SchedulerConfig,
    SupervisorConfig,
)
from apex_tpu.serving.fleet import (
    REPLICA_ACTIVE,
    REPLICA_DRAINING,
    REPLICA_PROBING,
    FleetConfig,
    FleetUnavailableError,
    ReplicaFleet,
    Router,
    ShardedEngine,
)
from apex_tpu.serving.fleet.router import _Replica
from apex_tpu.testing_faults import ServingFaultInjector

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FLEET_SCENARIO = os.path.join(REPO, "benchmarks", "scenarios",
                              "fleet_smoke.json")


@pytest.fixture(scope="module")
def small():
    # 1 layer on purpose (same rationale as the resilience suite): fleet
    # tests build MANY engines — every replica and every rebuild is a
    # fresh prefill+decode compile — and routing/drain semantics do not
    # depend on depth
    model = GPTModel(TransformerConfig(
        num_layers=1, hidden_size=32, num_attention_heads=4, vocab_size=64,
        max_position_embeddings=64, hidden_dropout=0.0,
        attention_dropout=0.0))
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _prompts(lens, seed=7):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 64, size=n).tolist() for n in lens]


def _expected_greedy(model, params, request, max_len):
    out = generate(model, params, jnp.asarray([request.prompt], jnp.int32),
                   request.max_new_tokens, max_len=max_len,
                   eos_token=request.eos_token)
    toks = np.asarray(out[0, request.prompt_len:]).tolist()
    if request.eos_token is not None and request.eos_token in toks:
        toks = toks[:toks.index(request.eos_token) + 1]
    return toks


def _fleet(model, params, n=2, *, max_slots=2, max_len=32, faults=None,
           fleet_cfg=None, supervisor=None, metrics=None, max_queue=16):
    return ReplicaFleet(
        model, params,
        EngineConfig(max_slots=max_slots, max_len=max_len,
                     scheduler=SchedulerConfig(max_queue=max_queue)),
        supervisor=supervisor, metrics=metrics, faults=faults,
        fleet=fleet_cfg or FleetConfig(n_replicas=n))


# ---------------------------------------------------------------------------
# router policy (no engines: stub supervisors)


class _StubSup:
    def __init__(self, queued, active, service):
        self.queued_count = queued
        self.active_count = active
        self.service_estimate_s = service


def _stub_replica(rid, queued, active, service):
    r = _Replica.__new__(_Replica)
    r.replica_id = rid
    r.supervisor = _StubSup(queued, active, service)
    r.state = REPLICA_ACTIVE
    r.dispatches = 0
    r.probe_id = None
    r.probe_attempts = 0
    return r


class TestRouter:
    def test_least_loaded_wins(self):
        a = _stub_replica(0, queued=4, active=2, service=0.5)   # cost 3.0
        b = _stub_replica(1, queued=1, active=1, service=0.5)   # cost 1.0
        assert Router().pick([a, b]).replica_id == 1

    def test_ewma_weighs_depth(self):
        # deeper-but-faster beats shallower-but-slower
        fast = _stub_replica(0, queued=4, active=0, service=0.1)  # 0.4
        slow = _stub_replica(1, queued=1, active=0, service=1.0)  # 1.0
        assert Router().pick([fast, slow]).replica_id == 0

    def test_unknown_service_attracts_traffic(self):
        # a fresh (just rebuilt) replica has no EWMA yet: cost 0 — it
        # deliberately wins over any measured replica
        fresh = _stub_replica(1, queued=3, active=0, service=None)
        busy = _stub_replica(0, queued=1, active=0, service=0.01)
        assert Router().pick([busy, fresh]).replica_id == 1

    def test_ties_break_by_depth_then_id(self):
        a = _stub_replica(0, queued=2, active=0, service=None)
        b = _stub_replica(1, queued=1, active=0, service=None)
        assert Router().pick([a, b]).replica_id == 1
        c = _stub_replica(2, queued=1, active=0, service=None)
        assert Router().pick([b, c]).replica_id == 1  # id breaks the tie

    def test_empty_candidates_rejected(self):
        with pytest.raises(ValueError, match="no candidates"):
            Router().pick([])


class TestFleetConfig:
    def test_validation(self):
        with pytest.raises(ValueError, match="n_replicas"):
            FleetConfig(n_replicas=0)
        with pytest.raises(ValueError, match="max_rebuild_probes"):
            FleetConfig(max_rebuild_probes=0)

    def test_unknown_fault_replica_rejected(self, small):
        model, params = small
        with pytest.raises(ValueError, match="unknown replica ids"):
            ReplicaFleet(model, params, EngineConfig(max_slots=2,
                                                     max_len=16),
                         fleet=FleetConfig(n_replicas=2),
                         faults={5: ServingFaultInjector()})


# ---------------------------------------------------------------------------
# dispatch, stickiness, fleet-wide admission


class TestFleetDispatch:
    def test_spreads_load_and_labels_results(self, small):
        """Arrivals spread across replicas; every result and record
        carries the replica that served it; dispatch counters split
        exactly."""
        model, params = small
        reg = MetricsRegistry([InMemorySink()])
        fleet = _fleet(model, params, metrics=reg)
        reqs = [Request(prompt=p, max_new_tokens=4)
                for p in _prompts([4, 5, 3, 6], seed=11)]
        with fleet:
            results = fleet.serve(reqs)
        assert [r.finish_reason for r in results] == ["length"] * 4
        homes = {r.replica_id for r in results}
        assert homes == {0, 1}          # both replicas served work
        counters = reg.counters()
        assert counters["fleet_dispatches"] == 4
        assert (counters["replica0_dispatches"]
                + counters["replica1_dispatches"]) == 4
        assert counters["requests_submitted"] == 4

    def test_sticky_cancel_follows_the_request(self, small):
        model, params = small
        fleet = _fleet(model, params)
        reqs = [Request(prompt=p, max_new_tokens=16)
                for p in _prompts([4, 4], seed=13)]
        with fleet:
            for r in reqs:
                fleet.submit(r)
            fleet.tick()
            assert fleet.cancel(reqs[1].request_id)
            while fleet.inflight_count:
                fleet.tick()
            res = fleet.completed[reqs[1].request_id]
            assert res.finish_reason == "cancelled"
            assert fleet.completed[reqs[0].request_id].finish_reason \
                == "length"
        assert not fleet.cancel(reqs[0].request_id)  # already terminal

    def test_open_breaker_leaves_dispatch_set(self, small):
        """A failing replica's breaker removes it from routing; traffic
        flows to the healthy peer instead of fast-failing."""
        model, params = small
        # replica 0's decode always raises: supervisor restarts burn out
        # and its breaker opens; replica 1 is clean
        inj = ServingFaultInjector(decode_raise_calls=range(0, 64))
        fleet = _fleet(
            model, params, faults={0: inj},
            supervisor=SupervisorConfig(breaker_threshold=1,
                                        breaker_cooldown_s=60.0,
                                        max_restarts_per_request=1))
        with fleet:
            victim = Request(prompt=_prompts([4], seed=17)[0],
                             max_new_tokens=4)
            fleet.submit(victim)        # routed to replica 0 (empty)
            for _ in range(8):
                fleet.tick()
                if fleet.replicas[0].supervisor.breaker_state \
                        == BREAKER_OPEN:
                    break
            assert fleet.replicas[0].supervisor.breaker_state \
                == BREAKER_OPEN
            assert [r.replica_id for r in fleet.dispatch_set()] == [1]
            after = Request(prompt=_prompts([4], seed=19)[0],
                            max_new_tokens=3)
            fleet.submit(after)
            while fleet.inflight_count:
                fleet.tick()
            res = fleet.completed[after.request_id]
            assert res.finish_reason == "length"
            assert res.replica_id == 1

    def test_fleet_unavailable_when_all_replicas_open(self, small):
        """Only when EVERY replica is out does the front door reject —
        terminally recorded, reason='fleet'."""
        model, params = small
        reg = MetricsRegistry([InMemorySink()])
        inj = {i: ServingFaultInjector(decode_raise_calls=range(0, 64))
               for i in range(2)}
        fleet = _fleet(
            model, params, faults=inj, metrics=reg,
            # max_engine_restarts=1: the second rebuild retires every
            # survivor, so the drain loop below stays cheap (each
            # rebuild is a fresh compile)
            supervisor=SupervisorConfig(breaker_threshold=1,
                                        breaker_cooldown_s=60.0,
                                        max_restarts_per_request=1,
                                        max_engine_restarts=1))
        with fleet:
            doomed = [Request(prompt=p, max_new_tokens=4)
                      for p in _prompts([4, 4], seed=23)]
            for r in doomed:
                fleet.submit(r)
            for _ in range(10):
                fleet.tick()
                if not fleet.dispatch_set():
                    break
            assert not fleet.dispatch_set()
            shed = Request(prompt=_prompts([3], seed=29)[0],
                           max_new_tokens=2)
            with pytest.raises(FleetUnavailableError):
                fleet.submit(shed)
            assert fleet.completed[shed.request_id].finish_reason \
                == "rejected"
            guard = 0
            while fleet.inflight_count and guard < 50:
                fleet.tick()    # retry budgets exhaust -> error retire
                guard += 1
            assert not fleet.inflight_count
        counters = reg.counters()
        assert counters["requests_shed_fleet"] == 1
        # conservation: 2 doomed + 1 shed, each exactly one terminal
        assert counters["requests_submitted"] == 3
        terminal = sum(counters[f"requests_{r}"] for r in FINISH_REASONS)
        assert terminal == 3


# ---------------------------------------------------------------------------
# draining restarts


class TestDrainingRestart:
    @pytest.mark.slow  # migration parity vs generate(): slow-tier class
    def test_migrated_request_is_token_exact(self, small):
        """Drain mid-generation: in-flight work re-prefills on the peer
        and the stitched stream equals a fault-free greedy run; the
        rebuilt replica rejoins and serves again; the EWMA is carried."""
        model, params = small
        reg = MetricsRegistry([InMemorySink()])
        fleet = _fleet(model, params, metrics=reg)
        warm = [Request(prompt=p, max_new_tokens=3)
                for p in _prompts([4, 4], seed=31)]
        with fleet:
            fleet.serve(warm)           # seeds both replicas' EWMAs
            ewma_before = fleet.replicas[0].supervisor.service_estimate_s
            assert ewma_before is not None
            victim = Request(prompt=_prompts([5], seed=37)[0],
                             max_new_tokens=10)
            fleet.submit(victim)
            for _ in range(3):          # partial decode on its replica
                fleet.tick()
            assert victim.request_id not in fleet.completed
            victim_home = fleet._tracked[victim.request_id].replica_id
            fleet.drain_restart(victim_home)
            min_dispatchable = []
            while fleet.inflight_count:
                fleet.tick()
                min_dispatchable.append(len(fleet.dispatch_set()))
            # capacity never below N-1 while draining/rebuilding/probing
            assert min(min_dispatchable) >= fleet.n_replicas - 1
            res = fleet.completed[victim.request_id]
            assert res.finish_reason == "length"
            assert res.replica_id == 1 - victim_home  # finished on peer
            assert res.tokens == _expected_greedy(model, params, victim,
                                                  32)
            # the rebuilt replica rejoined with the carried estimate
            rebuilt = fleet.replicas[victim_home]
            assert rebuilt.state == REPLICA_ACTIVE
            assert rebuilt.supervisor.service_estimate_s is not None
            again = Request(prompt=_prompts([4], seed=41)[0],
                            max_new_tokens=2)
            fleet.serve([again])
            assert fleet.completed[again.request_id].finish_reason \
                == "length"
        counters = reg.counters()
        assert counters["replica_drains"] == 1
        assert counters["replica_rebuilds"] == 1
        assert counters["requests_migrated"] == 1
        for r in fleet.replicas:        # no slot leaks anywhere
            r.supervisor.engine.slots.check()

    @pytest.mark.slow  # drain-in-place parity vs generate(): slow tier
    def test_drain_without_migration_finishes_in_place(self, small):
        model, params = small
        reg = MetricsRegistry([InMemorySink()])
        fleet = _fleet(model, params, metrics=reg,
                       fleet_cfg=FleetConfig(n_replicas=2,
                                             migrate_on_drain=False))
        with fleet:
            req = Request(prompt=_prompts([4], seed=43)[0],
                          max_new_tokens=6)
            fleet.submit(req)
            fleet.tick()
            home = fleet._tracked[req.request_id].replica_id
            fleet.drain_restart(home)
            assert fleet.replicas[home].state == REPLICA_DRAINING
            while fleet.inflight_count:
                fleet.tick()
            res = fleet.completed[req.request_id]
            # finished on its ORIGINAL replica, then the rebuild happened
            assert res.replica_id == home
            assert res.tokens == _expected_greedy(model, params, req, 32)
            assert fleet.replicas[home].state == REPLICA_ACTIVE
        counters = reg.counters()
        assert counters["requests_migrated"] == 0
        assert counters["replica_rebuilds"] == 1

    def test_one_drain_at_a_time(self, small):
        model, params = small
        # no migration: the drain lingers while the victim replica
        # finishes its own work, holding the draining state open
        fleet = _fleet(model, params,
                       fleet_cfg=FleetConfig(n_replicas=2,
                                             migrate_on_drain=False,
                                             probe_on_rebuild=False))
        with fleet:
            req = Request(prompt=_prompts([4], seed=47)[0],
                          max_new_tokens=8)
            fleet.submit(req)
            fleet.tick()
            home = fleet._tracked[req.request_id].replica_id
            peer = 1 - home
            fleet.drain_restart(home)
            with pytest.raises(RuntimeError, match="one.*at a time"):
                fleet.drain_restart(peer)
            with pytest.raises(RuntimeError, match="not active"):
                fleet.drain_restart(home)
            with pytest.raises(ValueError, match="no replica"):
                fleet.drain_restart(7)
            while fleet.inflight_count:
                fleet.tick()

    def test_probe_gates_rejoin(self, small):
        """After a rebuild the replica serves a real one-token probe
        before taking traffic — the probe is a counted, recorded request
        (conservation holds)."""
        model, params = small
        reg = MetricsRegistry([InMemorySink()])
        fleet = _fleet(model, params, metrics=reg)
        with fleet:
            fleet.drain_restart(0)      # idle drain: immediate rebuild
            assert fleet.replicas[0].state == REPLICA_PROBING
            assert fleet.inflight_count == 1     # the probe itself
            while fleet.inflight_count:
                fleet.tick()
            assert fleet.replicas[0].state == REPLICA_ACTIVE
        counters = reg.counters()
        assert counters["requests_submitted"] == 1   # just the probe
        assert counters["requests_length"] == 1


class TestServiceEstimateCarry:
    def test_constructor_seed(self, small):
        model, params = small
        sup = EngineSupervisor(model, params,
                               EngineConfig(max_slots=1, max_len=16),
                               service_s=0.125)
        assert sup.service_estimate_s == 0.125
        sup.close()

    def test_survives_engine_rebuild(self, small):
        """The EWMA is supervisor state: an engine restart must NOT
        reset it (the first post-restart submits would be admitted with
        no service estimate)."""
        model, params = small
        sup = EngineSupervisor(model, params,
                               EngineConfig(max_slots=1, max_len=16))
        with sup:
            sup.serve([Request(prompt=_prompts([4], seed=53)[0],
                               max_new_tokens=3)])
            before = sup.service_estimate_s
            assert before is not None
            sup._restart("test: forced rebuild")
            assert sup.service_estimate_s == before


# ---------------------------------------------------------------------------
# the committed fleet smoke scenario (acceptance)


class TestFleetSmokeScenario:
    @pytest.mark.slow
    def test_fleet_smoke_conserves_and_reconciles(self, tmp_path):
        """Acceptance: N=2 replicas, one scheduled draining restart
        mid-run — every submitted request reaches a terminal state
        exactly once, ZERO error finishes, and the monitor fleet
        section reconciles key-for-key with the telemetry counters."""
        scn = Scenario.load(FLEET_SCENARIO)
        model, params = None, None
        from apex_tpu.loadtest.runner import build_model
        model, params = build_model(scn.model)
        log = str(tmp_path / "fleet_smoke.jsonl")
        run = run_scenario(scn, model=model, params=params, log_path=log)
        assert not run.aborted
        assert run.submitted == scn.total_requests
        assert run.ok, run.slo.as_dict()

        report = build_report(log)
        counters = report["counters"]
        req = report["requests"]
        # conservation: one counted submit == one terminal record, and
        # nothing finished as an error
        assert counters["requests_submitted"] == req["count"]
        assert req["by_finish_reason"].get("error", 0) == 0
        assert counters["requests_error"] == 0
        terminal = sum(counters[f"requests_{r}"] for r in FINISH_REASONS)
        assert terminal == req["count"]
        # every SCHEDULED request is terminal exactly once in the
        # runner's results (records may add fleet-internal probes)
        sched_ids = [s.request.request_id for s in run.schedule]
        assert len(sched_ids) == len(set(sched_ids))
        for rid in sched_ids:
            assert rid in run.results, rid
            assert run.results[rid].finish_reason in FINISH_REASONS
            assert run.results[rid].finish_reason != "error"

        # the drain actually happened and the fleet section reconciles
        # key-for-key: each incident event count equals its counter, and
        # the per-replica dispatch split sums to the total
        fleet = report["fleet"]
        assert fleet is not None
        assert counters["replica_drains"] == 1
        assert counters["replica_rebuilds"] >= 1
        for event, counter in FLEET_INCIDENT_COUNTERS.items():
            assert fleet["counts"].get(event, 0) == counters[counter], \
                event
        split = [v for k, v in fleet["dispatches"].items()
                 if k != "fleet_dispatches"]
        assert sum(split) == counters["fleet_dispatches"]
        # every terminal record is attributed to a replica (nothing was
        # shed at the fleet level in the smoke)
        assert sum(fleet["requests_by_replica"].values()) == req["count"]
        text = render_report(report)
        assert "fleet:" in text and "requests by replica" in text

        # and the gate goes green against a fresh baseline (CLI
        # plumbing over a real fleet run log)
        base = str(tmp_path / "base.json")
        assert loadtest_main([FLEET_SCENARIO, "--from-log", log,
                              "--baseline", base,
                              "--update-baseline"]) == EXIT_OK
        assert loadtest_main([FLEET_SCENARIO, "--from-log", log,
                              "--check", "--baseline", base]) == EXIT_OK

    def test_fleet_block_round_trips(self):
        scn = Scenario.load(FLEET_SCENARIO)
        assert scn.fleet is not None and scn.fleet.n_replicas == 2
        assert scn.fleet.drain_restarts == ((2.0, 0),)
        again = Scenario.from_dict(scn.to_dict())
        assert again.to_dict() == scn.to_dict()

    def test_fleet_block_validation(self):
        d = json.load(open(FLEET_SCENARIO))
        d["fleet"]["drain_restarts"] = [{"at_s": 1.0, "replica": 9}]
        with pytest.raises(ValueError, match="out of range"):
            Scenario.from_dict(d)
        d["fleet"] = {"n_replicas": 2, "bogus": 1}
        with pytest.raises(ValueError, match="unknown fleet keys"):
            Scenario.from_dict(d)


# ---------------------------------------------------------------------------
# sharded decode (tp=2 over the virtual CPU mesh)


@pytest.fixture
def tp2_mesh():
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2)
    yield mesh
    parallel_state.destroy_model_parallel()


class TestShardedEngine:
    def test_indivisible_heads_fail_fast(self, tp2_mesh):
        model = GPTModel(TransformerConfig(
            num_layers=1, hidden_size=32, num_attention_heads=4,
            num_query_groups=1, vocab_size=64,
            max_position_embeddings=64, hidden_dropout=0.0,
            attention_dropout=0.0))
        params = model.init(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="divisible"):
            ShardedEngine(model, params,
                          EngineConfig(max_slots=2, max_len=16))

    def test_indivisible_vocab_fails_fast(self, tp2_mesh):
        model = GPTModel(TransformerConfig(
            num_layers=1, hidden_size=32, num_attention_heads=4,
            vocab_size=97, max_position_embeddings=64,
            hidden_dropout=0.0, attention_dropout=0.0))
        params = model.init(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="vocab_size.*divisible"):
            ShardedEngine(model, params,
                          EngineConfig(max_slots=2, max_len=16))

    @pytest.mark.slow  # TP model parity: the slow-tier class (ROADMAP)
    def test_tp2_token_exact_vs_unsharded(self, small, tp2_mesh):
        """Acceptance: ShardedEngine decode on a tp=2 CPU mesh is
        token-exact vs the unsharded engine — greedy AND sampled — with
        zero decode retraces and bucket-bounded prefill compiles."""
        model, params = small
        rng = np.random.RandomState(61)
        specs = [(4, 6, SamplingParams()),
                 (7, 5, SamplingParams(temperature=0.8, top_k=8, seed=3)),
                 (3, 8, SamplingParams()),
                 (5, 4, SamplingParams(temperature=1.1, seed=9))]
        prompts = [rng.randint(0, 64, size=n).tolist()
                   for n, _, _ in specs]

        def requests():
            return [Request(prompt=p, max_new_tokens=m, sampling=s)
                    for p, (_, m, s) in zip(prompts, specs)]

        ref_engine = InferenceEngine(
            model, params, EngineConfig(max_slots=4, max_len=32))
        with ref_engine:
            ref = ref_engine.serve(requests())

        sharded = ShardedEngine(
            model, params, EngineConfig(max_slots=4, max_len=32))
        with sharded:
            out = sharded.serve(requests())
            assert sharded.decode_retraces == 0
            assert sharded.prefill_compiles <= len(sharded.buckets)
            sharded.slots.check()
        for a, b in zip(ref, out):
            assert a.finish_reason == b.finish_reason
            assert a.tokens == b.tokens, (a.request_id, a.tokens, b.tokens)

    @pytest.mark.slow
    def test_sharded_engine_under_supervision(self, small, tp2_mesh):
        """The composition the fleet is for: a ShardedEngine replica
        under an EngineSupervisor recovers from an injected crash
        token-exact — the sharded program rebuilds like any engine."""
        model, params = small
        inj = ServingFaultInjector(decode_raise_calls={2})
        sup = EngineSupervisor(
            model, params, EngineConfig(max_slots=2, max_len=32),
            faults=inj,
            engine_factory=lambda m, p, c, **kw: ShardedEngine(m, p, c,
                                                               **kw))
        req = Request(prompt=_prompts([4], seed=67)[0], max_new_tokens=8)
        with sup:
            results = sup.serve([req])
        assert sup.restarts == 1
        assert results[0].tokens == _expected_greedy(model, params, req,
                                                     32)


# ---------------------------------------------------------------------------
# chaos: randomized arrivals x per-replica faults x draining restarts


@pytest.mark.slow
class TestFleetChaosSweep:
    def test_chaos_terminal_exactly_once_no_leaks(self, small):
        """Slow-tier acceptance: randomized arrivals, per-replica fault
        injection, cancellations, and draining restarts — every request
        reaches exactly one terminal state, no replica leaks slots, and
        structural capacity never drops below N-1 (at most one replica
        draining/probing at any point)."""
        model, params = small
        for seed in (0, 1, 2):
            rng = np.random.RandomState(100 + seed)
            faults = {
                0: ServingFaultInjector(
                    decode_raise_calls={int(rng.randint(2, 12))},
                    poison_decode={int(rng.randint(4, 16)):
                                   (int(rng.randint(0, 2)),
                                    "nonfinite")}),
                1: ServingFaultInjector(
                    decode_raise_calls={int(rng.randint(2, 12))}),
            }
            reg = MetricsRegistry([InMemorySink()])
            fleet = _fleet(
                model, params, faults=faults, metrics=reg,
                supervisor=SupervisorConfig(max_restarts_per_request=3,
                                            breaker_threshold=3,
                                            breaker_cooldown_s=0.05))
            submitted = []
            cancelled = set()
            drained = [False]
            with fleet:
                for step in range(40):
                    if rng.rand() < 0.6:
                        req = Request(
                            prompt=rng.randint(
                                0, 64,
                                size=int(rng.randint(2, 9))).tolist(),
                            max_new_tokens=int(rng.randint(1, 8)),
                            sampling=(
                                SamplingParams() if rng.rand() < 0.5
                                else SamplingParams(
                                    temperature=0.9,
                                    seed=int(rng.randint(0, 2**31)))))
                        try:
                            fleet.submit(req)
                            submitted.append(req)
                        except Exception:
                            submitted.append(req)  # recorded terminally
                    if submitted and rng.rand() < 0.1:
                        victim = submitted[int(rng.randint(
                            0, len(submitted)))]
                        if fleet.cancel(victim.request_id):
                            cancelled.add(victim.request_id)
                    if step == 15 and not drained[0]:
                        target = [r.replica_id for r in fleet.replicas
                                  if r.state == REPLICA_ACTIVE]
                        if target:
                            try:
                                fleet.drain_restart(target[0])
                                drained[0] = True
                            except RuntimeError:
                                pass
                    fleet.tick()
                    busy = sum(1 for r in fleet.replicas
                               if r.state in (REPLICA_DRAINING,
                                              REPLICA_PROBING))
                    assert busy <= 1, "capacity fell below N-1"
                guard = 0
                while fleet.inflight_count and guard < 400:
                    fleet.tick()
                    guard += 1
                assert not fleet.inflight_count, "requests stuck"
                for req in submitted:
                    assert req.request_id in fleet.completed, \
                        req.request_id
                    assert fleet.completed[req.request_id].finish_reason \
                        in FINISH_REASONS
                for r in fleet.replicas:
                    r.supervisor.engine.slots.check()
            # conservation: counted submits == terminal records, split
            # by reason (probe requests included on both sides)
            counters = reg.counters()
            terminal = sum(counters[f"requests_{r}"]
                           for r in FINISH_REASONS)
            assert counters["requests_submitted"] == terminal
