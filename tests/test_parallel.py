"""Data-parallel collectives + SyncBN on the 8-device CPU mesh (analog of
``tests/distributed/`` in the reference)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.parallel import (
    DistributedDataParallel,
    Reducer,
    SyncBatchNorm,
    all_reduce_gradients,
)
from apex_tpu.transformer import parallel_state


def test_all_reduce_gradients_mean(data_mesh):
    mesh = data_mesh
    n = mesh.shape["data"]
    grads = jnp.arange(n * 4, dtype=jnp.float32).reshape(n, 4)

    @jax.shard_map(mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    def reduce(g):
        return all_reduce_gradients({"g": g}, "data")["g"]

    out = reduce(grads)
    expect = np.broadcast_to(np.asarray(grads).reshape(n, 1, 4).mean(axis=0), (n, 1, 4)).reshape(n, 4)
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_ddp_options(data_mesh):
    mesh = data_mesh
    n = mesh.shape["data"]
    ddp = DistributedDataParallel(
        allreduce_always_fp32=True, gradient_predivide_factor=2.0)
    grads = jnp.ones((n, 8), jnp.bfloat16)

    @jax.shard_map(mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    def reduce(g):
        out = ddp.reduce_gradients({"g": g})["g"]
        return out

    out = reduce(grads)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), 1.0)  # mean of ones


def test_reducer(data_mesh):
    mesh = data_mesh
    n = mesh.shape["data"]
    vals = jnp.arange(n, dtype=jnp.float32).reshape(n, 1)

    @jax.shard_map(mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    def rd(v):
        return Reducer().reduce({"v": v})["v"]

    out = rd(vals)
    np.testing.assert_allclose(out, np.full((n, 1), (n - 1) / 2.0), rtol=1e-6)


def test_syncbn_matches_global_bn(data_mesh):
    """Per-shard SyncBN stats == full-batch BN stats (the key invariant the
    reference tests in tests/distributed/synced_batchnorm)."""
    mesh = data_mesh
    n = mesh.shape["data"]
    batch, feat = 4 * n, 6
    x = jax.random.normal(jax.random.PRNGKey(0), (batch, feat)) * 3 + 1

    bn = SyncBatchNorm(num_features=feat, axis_name="data", momentum=1.0)
    variables = bn.init(jax.random.PRNGKey(1), x[:4])

    @jax.shard_map(mesh=mesh, in_specs=(P(), P("data")), out_specs=(P("data"), P()))
    def run(vars_, xs):
        y, updated = bn.apply(vars_, xs, mutable=["batch_stats"])
        return y, updated["batch_stats"]

    y, stats = run(variables, x)
    # reference: plain full-batch normalization
    mean = x.mean(axis=0)
    var = x.var(axis=0)
    expect = (x - mean) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(np.asarray(y), np.asarray(expect), atol=1e-4)
    np.testing.assert_allclose(np.asarray(stats["mean"]), np.asarray(mean), atol=1e-5)
    unbiased = x.var(axis=0, ddof=1)
    np.testing.assert_allclose(np.asarray(stats["var"]), np.asarray(unbiased), atol=1e-4)


def test_syncbn_channel_first_and_relu():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 4, 4))  # NCHW
    bn = SyncBatchNorm(num_features=3, channel_last=False, fuse_relu=True)
    variables = bn.init(jax.random.PRNGKey(1), x)
    y = bn.apply(variables, x, mutable=["batch_stats"])[0]
    assert y.shape == x.shape
    assert float(jnp.min(y)) >= 0.0  # relu fused


def test_syncbn_eval_mode_uses_running_stats():
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 4))
    bn = SyncBatchNorm(num_features=4, momentum=1.0)
    variables = bn.init(jax.random.PRNGKey(1), x)
    _, updated = bn.apply(variables, x, mutable=["batch_stats"])
    variables = {**variables, "batch_stats": updated["batch_stats"]}
    y = bn.apply(variables, x, use_running_stats=True)
    mean = np.asarray(x).mean(axis=0)
    var = np.asarray(x).var(axis=0, ddof=1)
    np.testing.assert_allclose(
        np.asarray(y), (np.asarray(x) - mean) / np.sqrt(var + 1e-5), atol=1e-4)


def test_syncbn_process_groups_sub_axis():
    """Reference ``tests/distributed/synced_batchnorm/test_groups.py``:
    BN synchronized within *groups* of ranks, not globally. Here groups =
    a sub-axis of a 2D data mesh: stats psum over ``group`` only, so each
    group of shards normalizes with its own statistics."""
    from jax.sharding import Mesh

    from apex_tpu.parallel import SyncBatchNorm

    if len(jax.devices()) < 8:
        pytest.skip("needs an 8-device mesh (2x4 group layout)")
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("group", "member"))
    bn = SyncBatchNorm(num_features=3, axis_name="member",
                       momentum=1.0, channel_last=True)

    x = jax.random.normal(jax.random.PRNGKey(0), (16, 4, 4, 3))
    # make the two groups statistically different
    x = x.at[8:].add(5.0)
    variables = bn.init(jax.random.PRNGKey(1), x[:2])

    def body(v, xs):
        out, updates = bn.apply(v, xs, mutable=["batch_stats"])
        return out, updates["batch_stats"]

    y, stats = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(("group", "member"))),
        out_specs=(P(("group", "member")), P("group")),
        check_vma=False))(variables, x)

    # per-group running means differ (group 1 saw the +5 shift);
    # out_spec P("group") concatenates the two [C] vectors along dim 0
    m0 = np.asarray(stats["mean"][:3])
    m1 = np.asarray(stats["mean"][3:])
    assert abs(float(np.mean(m1 - m0)) - 5.0) < 0.5
    # ...and each group's output is normalized with its own stats: both
    # halves come out ~zero-mean despite the shift
    y = np.asarray(y, np.float32)
    assert abs(float(y[:8].mean())) < 0.1
    assert abs(float(y[8:].mean())) < 0.1
    # global BN (sync over both axes) would instead leave opposite-signed
    # group means ~ +-2.5/std; assert we did NOT do that
    assert abs(float(y[:8].mean() - y[8:].mean())) < 0.2


class TestSpecAwareGradSync:
    """sync_data_parallel_grads with param_spec: prefix pytrees (the same
    prefix semantics shard_map in_specs accept) and data-sharded leaves."""

    def test_prefix_spec_accepted(self):
        from apex_tpu.training import sync_data_parallel_grads

        if len(jax.devices()) < 8:
            pytest.skip("assertions assume an 8-rank data axis")

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel()   # data = 8
        grads = {"block": {"w": jnp.ones((8, 4)), "b": jnp.ones((4,))},
                 "head": jnp.ones((4, 2))}
        # prefix spec: one entry covers the whole nested "block" subtree
        spec = {"block": P(), "head": P()}

        def per_rank(g):
            g = jax.tree.map(
                lambda x: x * (1.0 + jax.lax.axis_index("data")), g)
            return sync_data_parallel_grads(g, ("data",), spec)

        out = jax.jit(jax.shard_map(
            per_rank, mesh=mesh, in_specs=(jax.tree.map(lambda _: P(), grads),),
            out_specs=jax.tree.map(lambda _: P(), grads),
            check_vma=False))(grads)
        # pmean of (1..8) = 4.5 for every replicated leaf
        jax.tree.map(
            lambda x: np.testing.assert_allclose(np.asarray(x), 4.5),
            out)
        parallel_state.destroy_model_parallel()

    def test_data_sharded_leaf_divided_not_averaged(self):
        from apex_tpu.training import sync_data_parallel_grads

        if len(jax.devices()) < 8:
            pytest.skip("assertions assume an 8-rank data axis")

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel()
        grads = {"expert": jnp.ones((8, 4)), "shared": jnp.ones((8, 4))}
        spec = {"expert": P("data", None), "shared": P()}

        def per_rank(g):
            g = jax.tree.map(
                lambda x: x * (1.0 + jax.lax.axis_index("data")), g)
            return sync_data_parallel_grads(g, ("data",), spec)

        out = jax.jit(jax.shard_map(
            per_rank, mesh=mesh,
            in_specs=({"expert": P("data", None), "shared": P()},),
            out_specs={"expert": P("data", None), "shared": P()},
            check_vma=False))(grads)
        # sharded leaf: rank r's rows scaled by (1+r)/8, no cross-rank mixing
        expert = np.asarray(out["expert"])
        for r in range(8):
            np.testing.assert_allclose(expert[r], (1.0 + r) / 8.0)
        np.testing.assert_allclose(np.asarray(out["shared"]), 4.5)
        parallel_state.destroy_model_parallel()
