"""Data-parallel collectives + SyncBN on the 8-device CPU mesh (analog of
``tests/distributed/`` in the reference)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.parallel import (
    DistributedDataParallel,
    Reducer,
    SyncBatchNorm,
    all_reduce_gradients,
)
from apex_tpu.transformer import parallel_state
from apex_tpu.utils.sharding import axis_size, shard_map


def test_all_reduce_gradients_mean(data_mesh):
    mesh = data_mesh
    n = mesh.shape["data"]
    grads = jnp.arange(n * 4, dtype=jnp.float32).reshape(n, 4)

    @shard_map(mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    def reduce(g):
        return all_reduce_gradients({"g": g}, "data")["g"]

    out = reduce(grads)
    expect = np.broadcast_to(np.asarray(grads).reshape(n, 1, 4).mean(axis=0), (n, 1, 4)).reshape(n, 4)
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_ddp_options(data_mesh):
    mesh = data_mesh
    n = mesh.shape["data"]
    ddp = DistributedDataParallel(
        allreduce_always_fp32=True, gradient_predivide_factor=2.0)
    grads = jnp.ones((n, 8), jnp.bfloat16)

    @shard_map(mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    def reduce(g):
        out = ddp.reduce_gradients({"g": g})["g"]
        return out

    out = reduce(grads)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), 1.0)  # mean of ones


def test_reducer(data_mesh):
    mesh = data_mesh
    n = mesh.shape["data"]
    vals = jnp.arange(n, dtype=jnp.float32).reshape(n, 1)

    @shard_map(mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    def rd(v):
        return Reducer().reduce({"v": v})["v"]

    out = rd(vals)
    np.testing.assert_allclose(out, np.full((n, 1), (n - 1) / 2.0), rtol=1e-6)


def test_syncbn_matches_global_bn(data_mesh):
    """Per-shard SyncBN stats == full-batch BN stats (the key invariant the
    reference tests in tests/distributed/synced_batchnorm)."""
    mesh = data_mesh
    n = mesh.shape["data"]
    batch, feat = 4 * n, 6
    x = jax.random.normal(jax.random.PRNGKey(0), (batch, feat)) * 3 + 1

    bn = SyncBatchNorm(num_features=feat, axis_name="data", momentum=1.0)
    variables = bn.init(jax.random.PRNGKey(1), x[:4])

    @shard_map(mesh=mesh, in_specs=(P(), P("data")), out_specs=(P("data"), P()))
    def run(vars_, xs):
        y, updated = bn.apply(vars_, xs, mutable=["batch_stats"])
        return y, updated["batch_stats"]

    y, stats = run(variables, x)
    # reference: plain full-batch normalization
    mean = x.mean(axis=0)
    var = x.var(axis=0)
    expect = (x - mean) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(np.asarray(y), np.asarray(expect), atol=1e-4)
    np.testing.assert_allclose(np.asarray(stats["mean"]), np.asarray(mean), atol=1e-5)
    unbiased = x.var(axis=0, ddof=1)
    np.testing.assert_allclose(np.asarray(stats["var"]), np.asarray(unbiased), atol=1e-4)


def test_syncbn_channel_first_and_relu():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 4, 4))  # NCHW
    bn = SyncBatchNorm(num_features=3, channel_last=False, fuse_relu=True)
    variables = bn.init(jax.random.PRNGKey(1), x)
    y = bn.apply(variables, x, mutable=["batch_stats"])[0]
    assert y.shape == x.shape
    assert float(jnp.min(y)) >= 0.0  # relu fused


def test_syncbn_eval_mode_uses_running_stats():
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 4))
    bn = SyncBatchNorm(num_features=4, momentum=1.0)
    variables = bn.init(jax.random.PRNGKey(1), x)
    _, updated = bn.apply(variables, x, mutable=["batch_stats"])
    variables = {**variables, "batch_stats": updated["batch_stats"]}
    y = bn.apply(variables, x, use_running_stats=True)
    mean = np.asarray(x).mean(axis=0)
    var = np.asarray(x).var(axis=0, ddof=1)
    np.testing.assert_allclose(
        np.asarray(y), (np.asarray(x) - mean) / np.sqrt(var + 1e-5), atol=1e-4)


def test_syncbn_process_groups_sub_axis():
    """Reference ``tests/distributed/synced_batchnorm/test_groups.py``:
    BN synchronized within *groups* of ranks, not globally. Here groups =
    a sub-axis of a 2D data mesh: stats psum over ``group`` only, so each
    group of shards normalizes with its own statistics."""
    from jax.sharding import Mesh

    from apex_tpu.parallel import SyncBatchNorm

    if len(jax.devices()) < 8:
        pytest.skip("needs an 8-device mesh (2x4 group layout)")
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("group", "member"))
    bn = SyncBatchNorm(num_features=3, axis_name="member",
                       momentum=1.0, channel_last=True)

    x = jax.random.normal(jax.random.PRNGKey(0), (16, 4, 4, 3))
    # make the two groups statistically different
    x = x.at[8:].add(5.0)
    variables = bn.init(jax.random.PRNGKey(1), x[:2])

    def body(v, xs):
        out, updates = bn.apply(v, xs, mutable=["batch_stats"])
        return out, updates["batch_stats"]

    y, stats = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(("group", "member"))),
        out_specs=(P(("group", "member")), P("group")),
        check_vma=False))(variables, x)

    # per-group running means differ (group 1 saw the +5 shift);
    # out_spec P("group") concatenates the two [C] vectors along dim 0
    m0 = np.asarray(stats["mean"][:3])
    m1 = np.asarray(stats["mean"][3:])
    assert abs(float(np.mean(m1 - m0)) - 5.0) < 0.5
    # ...and each group's output is normalized with its own stats: both
    # halves come out ~zero-mean despite the shift
    y = np.asarray(y, np.float32)
    assert abs(float(y[:8].mean())) < 0.1
    assert abs(float(y[8:].mean())) < 0.1
    # global BN (sync over both axes) would instead leave opposite-signed
    # group means ~ +-2.5/std; assert we did NOT do that
    assert abs(float(y[:8].mean() - y[8:].mean())) < 0.2


class TestSpecAwareGradSync:
    """sync_data_parallel_grads with param_spec: prefix pytrees (the same
    prefix semantics shard_map in_specs accept) and data-sharded leaves."""

    def test_prefix_spec_accepted(self):
        from apex_tpu.training import sync_data_parallel_grads

        if len(jax.devices()) < 8:
            pytest.skip("assertions assume an 8-rank data axis")

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel()   # data = 8
        grads = {"block": {"w": jnp.ones((8, 4)), "b": jnp.ones((4,))},
                 "head": jnp.ones((4, 2))}
        # prefix spec: one entry covers the whole nested "block" subtree
        spec = {"block": P(), "head": P()}

        def per_rank(g):
            g = jax.tree.map(
                lambda x: x * (1.0 + jax.lax.axis_index("data")), g)
            return sync_data_parallel_grads(g, ("data",), spec)

        out = jax.jit(shard_map(
            per_rank, mesh=mesh, in_specs=(jax.tree.map(lambda _: P(), grads),),
            out_specs=jax.tree.map(lambda _: P(), grads),
            check_vma=False))(grads)
        # pmean of (1..8) = 4.5 for every replicated leaf
        jax.tree.map(
            lambda x: np.testing.assert_allclose(np.asarray(x), 4.5),
            out)
        parallel_state.destroy_model_parallel()

    def test_data_sharded_leaf_divided_not_averaged(self):
        from apex_tpu.training import sync_data_parallel_grads

        if len(jax.devices()) < 8:
            pytest.skip("assertions assume an 8-rank data axis")

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel()
        grads = {"expert": jnp.ones((8, 4)), "shared": jnp.ones((8, 4))}
        spec = {"expert": P("data", None), "shared": P()}

        def per_rank(g):
            g = jax.tree.map(
                lambda x: x * (1.0 + jax.lax.axis_index("data")), g)
            return sync_data_parallel_grads(g, ("data",), spec)

        out = jax.jit(shard_map(
            per_rank, mesh=mesh,
            in_specs=({"expert": P("data", None), "shared": P()},),
            out_specs={"expert": P("data", None), "shared": P()},
            check_vma=False))(grads)
        # sharded leaf: rank r's rows scaled by (1+r)/8, no cross-rank mixing
        expert = np.asarray(out["expert"])
        for r in range(8):
            np.testing.assert_allclose(expert[r], (1.0 + r) / 8.0)
        np.testing.assert_allclose(np.asarray(out["shared"]), 4.5)
        parallel_state.destroy_model_parallel()


def test_syncbn_unequal_per_rank_batches(data_mesh):
    """Count-weighted merge with unequal REAL batch sizes per rank
    (reference ``tests/distributed/synced_batchnorm/
    two_gpu_test_different_batch_size.py``): under SPMD every rank's shapes
    match, so short ranks pad and pass ``sample_mask``; statistics must
    equal full-batch BN over only the real rows."""
    mesh = data_mesh
    n = mesh.shape["data"]
    per_rank, feat = 4, 6
    # rank r has (4 - r % 3) real samples: e.g. 4,3,2,4,3,2,... over 8 ranks
    counts = np.array([per_rank - (r % 3) for r in range(n)])
    x = jax.random.normal(jax.random.PRNGKey(0),
                          (n * per_rank, feat)) * 3 + 1
    mask = np.zeros((n * per_rank,), bool)
    for r in range(n):
        mask[r * per_rank: r * per_rank + counts[r]] = True
    mask_j = jnp.asarray(mask)

    bn = SyncBatchNorm(num_features=feat, axis_name="data", momentum=1.0)
    variables = bn.init(jax.random.PRNGKey(1), x[:4])

    @shard_map(mesh=mesh, in_specs=(P(), P("data"), P("data")),
                   out_specs=(P("data"), P()))
    def run(vars_, xs, m):
        y, updated = bn.apply(vars_, xs, sample_mask=m,
                              mutable=["batch_stats"])
        return y, updated["batch_stats"]

    y, stats = run(variables, x, mask_j)
    real = np.asarray(x)[mask]
    mean = real.mean(axis=0)
    var = real.var(axis=0)
    # real rows normalized by the count-weighted global stats
    expect = (real - mean) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(np.asarray(y)[mask], expect, atol=1e-4)
    np.testing.assert_allclose(np.asarray(stats["mean"]), mean, atol=1e-5)
    np.testing.assert_allclose(np.asarray(stats["var"]),
                               real.var(axis=0, ddof=1), atol=1e-4)


def test_syncbn_unequal_batches_grads(data_mesh):
    """Gradients through the count-weighted masked SyncBN match the
    reference computation on only-the-real rows (the grad-parity half of
    the reference's different-batch-size test)."""
    mesh = data_mesh
    n = mesh.shape["data"]
    per_rank, feat = 2, 4
    counts = np.array([per_rank if r % 2 == 0 else 1 for r in range(n)])
    x = jax.random.normal(jax.random.PRNGKey(3),
                          (n * per_rank, feat)) * 2 - 1
    mask = np.zeros((n * per_rank,), bool)
    for r in range(n):
        mask[r * per_rank: r * per_rank + counts[r]] = True
    mask_j = jnp.asarray(mask)

    bn = SyncBatchNorm(num_features=feat, axis_name="data", momentum=1.0)
    variables = bn.init(jax.random.PRNGKey(1), x[:2])
    tgt = jax.random.normal(jax.random.PRNGKey(4), x.shape)

    @shard_map(mesh=mesh, in_specs=(P("data"), P("data"), P("data")),
                   out_specs=P("data"), check_vma=False)
    def grad_x(xs, m, t):
        def loss(xs):
            y = bn.apply(variables, xs, sample_mask=m,
                         mutable=["batch_stats"])[0]
            # loss over real rows only (masked rows are padding); the /n
            # compensates psum's transpose summing every rank's unit
            # cotangent (each rank differentiates the same replicated loss)
            w = m.astype(jnp.float32)[:, None]
            return jax.lax.psum(
                jnp.sum(w * (y - t) ** 2), "data") / axis_size("data")
        return jax.grad(loss)(xs)

    g = np.asarray(grad_x(x, mask_j, tgt))

    # reference: same loss with only real rows through unmasked global BN
    real_idx = np.where(mask)[0]
    xr = jnp.asarray(np.asarray(x)[real_idx])
    tr = jnp.asarray(np.asarray(tgt)[real_idx])

    def ref_loss(xr):
        m_ = jnp.mean(xr, axis=0)
        v_ = jnp.mean((xr - m_) ** 2, axis=0)
        y = (xr - m_) / jnp.sqrt(v_ + 1e-5)
        return jnp.sum((y - tr) ** 2)

    g_ref = np.asarray(jax.grad(ref_loss)(xr))
    np.testing.assert_allclose(g[real_idx], g_ref, atol=1e-4)
    # padded rows contribute nothing and receive no gradient
    np.testing.assert_allclose(g[~mask], 0.0, atol=1e-6)


def test_syncbn_all_masked_batch_is_noop_on_running_stats():
    """A fully-padded global batch must leave batch_stats untouched —
    unguarded, the momentum blend decays them toward the count-guard's
    zero mean/var (ADVICE r4)."""
    from apex_tpu.parallel import SyncBatchNorm

    bn = SyncBatchNorm(num_features=3, axis_name=None, momentum=0.5)
    x = jnp.ones((4, 3)) * 2.0
    variables = bn.init(jax.random.PRNGKey(0), x)
    # one real step moves the stats off their init values
    _, v1 = bn.apply(variables, x, sample_mask=jnp.ones((4,), bool),
                     mutable=["batch_stats"])
    stats1 = jax.tree.map(np.asarray, v1["batch_stats"])
    assert stats1["mean"][0] != 0.0
    # an all-masked step is a no-op
    _, v2 = bn.apply({"params": variables["params"],
                      "batch_stats": v1["batch_stats"]}, x,
                     sample_mask=jnp.zeros((4,), bool),
                     mutable=["batch_stats"])
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        stats1, jax.tree.map(np.asarray, v2["batch_stats"]))


def test_bn_apply_sample_mask():
    """Functional bn_apply counterpart (the vision-model path): masked NHWC
    rows drop out of the count-weighted stats."""
    from apex_tpu.utils.batch_norm import bn_apply, bn_init

    x = np.asarray(jax.random.normal(jax.random.PRNGKey(0), (4, 3, 3, 5)),
                   np.float32) * 2 + 3
    mask = np.array([True, True, True, False])
    p, s = bn_init(5)
    y, new_s = bn_apply(jax.tree.map(jnp.asarray, p),
                        jax.tree.map(jnp.asarray, s), jnp.asarray(x),
                        train=True, momentum=1.0, eps=1e-5, axis_name=None,
                        sample_mask=jnp.asarray(mask))
    real = x[mask].reshape(-1, 5)
    mean = real.mean(axis=0)
    var = real.var(axis=0)
    np.testing.assert_allclose(np.asarray(new_s["mean"]), mean, atol=1e-5)
    expect = (x[mask] - mean) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(np.asarray(y)[mask], expect, atol=1e-4)


def test_syncbn_mask_robust_to_garbage_padding():
    """Padded rows may hold ANYTHING (uninitialized buffers): NaN/Inf in a
    masked-out row must not leak into statistics or outputs (where-masking,
    not multiply — 0*NaN is NaN), and an all-padded batch must degrade to
    finite stats rather than 0/0."""
    from apex_tpu.utils.batch_norm import bn_apply, bn_init

    x = np.ones((4, 2, 2, 3), np.float32)
    x[2:] = np.nan
    x[3, 0, 0, 0] = np.inf
    mask = np.array([True, True, False, False])
    p, s = bn_init(3)
    p = jax.tree.map(jnp.asarray, p)
    s = jax.tree.map(jnp.asarray, s)
    y, new_s = bn_apply(p, s, jnp.asarray(x), train=True, momentum=1.0,
                        eps=1e-5, axis_name=None,
                        sample_mask=jnp.asarray(mask))
    assert np.isfinite(np.asarray(new_s["mean"])).all()
    assert np.isfinite(np.asarray(new_s["var"])).all()
    assert np.isfinite(np.asarray(y)[mask]).all()

    # flax module path
    bn = SyncBatchNorm(num_features=3, momentum=1.0)
    variables = bn.init(jax.random.PRNGKey(0), jnp.asarray(x))
    y2, upd = bn.apply(variables, jnp.asarray(x),
                       sample_mask=jnp.asarray(mask),
                       mutable=["batch_stats"])
    assert np.isfinite(np.asarray(upd["batch_stats"]["mean"])).all()
    assert np.isfinite(np.asarray(y2)[mask]).all()

    # all-padded: finite (degraded) stats, not NaN
    none = jnp.zeros((4,), bool)
    y3, new_s3 = bn_apply(p, s, jnp.asarray(x), train=True, momentum=1.0,
                          eps=1e-5, axis_name=None, sample_mask=none)
    assert np.isfinite(np.asarray(new_s3["mean"])).all()
    assert np.isfinite(np.asarray(new_s3["var"])).all()


def test_convert_syncbn_model(data_mesh):
    """The functional convert_syncbn_model analog (reference
    apex/parallel/__init__.py:21-77): flax BatchNorm modules in the
    dataclass tree become SyncBatchNorm with the SAME param/collection
    layout (params transfer), training-mode outputs match flax BN on a
    single device, and the converted model's statistics synchronize
    across the data axis."""
    import flax.linen as fnn
    from apex_tpu.parallel import SyncBatchNorm, convert_syncbn_model

    model = fnn.Sequential([
        fnn.Dense(8),
        fnn.BatchNorm(use_running_average=False, momentum=0.9),
        fnn.Dense(4),
        fnn.BatchNorm(use_running_average=False, momentum=0.9),
    ])
    conv = convert_syncbn_model(model, axis_name="data")
    assert isinstance(conv.layers[1], SyncBatchNorm)
    assert conv.layers[1].momentum == pytest.approx(0.1)
    assert isinstance(conv.layers[3], SyncBatchNorm)

    x = jnp.asarray(np.random.default_rng(0).standard_normal((8, 16)),
                    jnp.float32)
    vars_flax = model.init(jax.random.PRNGKey(0), x)
    # identical param/collection tree -> flax-initialized variables drive
    # the converted model directly
    vars_conv = jax.tree.map(lambda a: a, vars_flax)
    y_flax, st_flax = model.apply(vars_flax, x, mutable=["batch_stats"])
    y_conv, st_conv = conv.apply(vars_conv, x, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(y_flax), np.asarray(y_conv),
                               rtol=2e-5, atol=2e-5)
    # running stats track the SOURCE module's (biased-variance, flax)
    # semantics so eval-mode behavior is preserved across conversion
    for a, b in zip(jax.tree.leaves(st_flax), jax.tree.leaves(st_conv)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)

    # cross-rank sync: per-rank batches with different statistics must
    # normalize with the GLOBAL moments (parity vs running the unsharded
    # batch through one device)
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    xg = jnp.asarray(np.random.default_rng(1).standard_normal((8, 16)),
                     jnp.float32) * 3.0 + 1.0

    def fwd(xs):
        y, _ = conv.apply(vars_conv, xs, mutable=["batch_stats"])
        return y

    y_sharded = shard_map(fwd, mesh=data_mesh, in_specs=P("data"),
                          out_specs=P("data"))(xg)
    # global reference: the ORIGINAL flax model over the unsharded batch
    # (training-mode BN over the full batch == synced per-shard BN)
    y_global, _ = model.apply(vars_flax, xg, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(y_sharded), np.asarray(y_global),
                               rtol=2e-5, atol=2e-5)


def test_convert_syncbn_model_guards():
    import flax.linen as fnn
    from apex_tpu.parallel import convert_syncbn_model

    with pytest.raises(NotImplementedError, match="axis"):
        convert_syncbn_model(fnn.Sequential(
            [fnn.BatchNorm(use_running_average=False, axis=1)]))
    with pytest.raises(NotImplementedError, match="eval-mode"):
        convert_syncbn_model(fnn.Sequential(
            [fnn.BatchNorm(use_running_average=True)]))
    # a compute/output dtype override has no SyncBatchNorm equivalent
    with pytest.raises(NotImplementedError, match="dtype"):
        convert_syncbn_model(fnn.Sequential(
            [fnn.BatchNorm(use_running_average=False,
                           dtype=jnp.bfloat16)]))
    with pytest.raises(NotImplementedError, match="use_fast_variance"):
        convert_syncbn_model(fnn.Sequential(
            [fnn.BatchNorm(use_running_average=False,
                           use_fast_variance=False)]))


def test_convert_syncbn_model_transfers_param_dtype():
    """A BN with non-default param_dtype must convert to a SyncBatchNorm
    initializing scale/bias in that dtype, not silently fp32."""
    import flax.linen as fnn
    from apex_tpu.parallel import convert_syncbn_model

    model = fnn.Sequential([
        fnn.BatchNorm(use_running_average=False,
                      param_dtype=jnp.bfloat16),
    ])
    conv = convert_syncbn_model(model)
    assert conv.layers[0].param_dtype == jnp.bfloat16
    x = jnp.ones((4, 8), jnp.float32)
    variables = conv.init(jax.random.PRNGKey(0), x)
    bn_params = variables["params"]["layers_0"]
    assert bn_params["scale"].dtype == jnp.bfloat16
    assert bn_params["bias"].dtype == jnp.bfloat16
    # running stats stay fp32 (flax BatchNorm keeps them fp32 too)
    assert variables["batch_stats"]["layers_0"]["mean"].dtype == jnp.float32
