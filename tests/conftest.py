"""Test harness configuration.

Mirrors the reference's strategy of exercising distributed logic on a single
node (``apex/transformer/testing/distributed_test_base.py:22-60`` spawns one
process per GPU): here a single process gets 8 virtual CPU devices via
``--xla_force_host_platform_device_count`` (SURVEY.md §4 implication), and
Pallas kernels run in interpreter mode where exercised.

Set ``APEX_TPU_TEST_TPU=1`` to run the suite on a real TPU backend instead.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (import after env setup)

if os.environ.get("APEX_TPU_TEST_TPU", "0") != "1":
    # the env var JAX_PLATFORMS can be overridden by TPU plugins in this
    # image; the config knob wins
    jax.config.update("jax_platforms", "cpu")
else:
    # numerics tests were written against true-fp32 math; TPU's default
    # matmul precision multiplies fp32 operands in bf16 passes (~4e-3
    # relative error), which is a precision POLICY, not a kernel bug —
    # force full fp32 so CPU-calibrated tolerances hold on hardware
    jax.config.update("jax_default_matmul_precision", "highest")

import gc  # noqa: E402

import pytest  # noqa: E402

# The suite holds thousands of compiled XLA programs by the time the later
# files run, and jax's allocation churn makes CPython run full (gen-2)
# collections constantly — each one scanning the whole ever-growing heap.
# Measured effect: the same serving test takes 2-3x longer at the 80% mark
# of a full run than in isolation. Periodically promoting survivors to the
# GC's permanent generation keeps collections scanning only recent objects;
# long-lived executables/caches were never collectable garbage anyway.
_GC_FREEZE_EVERY = 25
_tests_run = 0


def pytest_collection_finish(session):
    gc.collect()
    gc.freeze()


def pytest_runtest_teardown(item, nextitem):
    global _tests_run
    _tests_run += 1
    if _tests_run % _GC_FREEZE_EVERY == 0:
        gc.collect()
        gc.freeze()


@pytest.fixture
def mesh8():
    """A (2 data, 2 pipeline, 1 context, 2 tensor) mesh over 8 devices."""
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2, pipeline_model_parallel_size=2)
    yield mesh
    parallel_state.destroy_model_parallel()


@pytest.fixture
def data_mesh():
    """Pure data-parallel mesh over all 8 devices."""
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel()
    yield mesh
    parallel_state.destroy_model_parallel()


def _skip_if_undersized_mesh(excinfo):
    """On backends with fewer than 8 devices (the real single-chip TPU
    under APEX_TPU_TEST_TPU=1), a mesh request the hardware cannot satisfy
    is a SKIP, not a failure — the same tests run for real on the 8-device
    virtual CPU mesh. Anchored on the dedicated exception TYPE (ADVICE r2:
    message-substring anchors would also mask genuine mesh-construction
    regressions, e.g. num_slices divisibility errors)."""
    from apex_tpu.transformer.parallel_state import UndersizedMeshError

    if (isinstance(excinfo, UndersizedMeshError)
            and len(jax.devices()) < 8):
        pytest.skip(f"multi-device test on a {len(jax.devices())}-device "
                    f"backend: {excinfo}")


@pytest.hookimpl(wrapper=True)
def pytest_runtest_call(item):
    try:
        return (yield)
    except RuntimeError as e:
        _skip_if_undersized_mesh(e)
        raise


@pytest.hookimpl(wrapper=True)
def pytest_runtest_setup(item):
    # mesh fixtures (mesh8/data_mesh) raise during setup
    try:
        return (yield)
    except RuntimeError as e:
        _skip_if_undersized_mesh(e)
        raise
