"""RoPE wired through the model stack (``position_embedding_type="rope"``).

The reference ships fused RoPE kernels (``csrc/megatron/fused_rotary_
positional_embedding``) but its standalone GPT uses learned positions; here
rotary is a first-class config option. Anchors:

- no position-embedding table is allocated;
- relative-position property: shifting an entire causal sequence window
  changes nothing about next-token logits when positions are rotary and the
  content is shift-invariant (checked via decode offsets);
- cached decode logits match the full forward (rope offset = cache_index,
  rotate-then-cache);
- training decreases loss; TP=2 reproduces single-rank numerics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.models.generation import decode_step, init_kv_caches


def _cfg(**kw):
    d = dict(num_layers=2, hidden_size=64, num_attention_heads=4,
             position_embedding_type="rope", vocab_size=64,
             max_position_embeddings=32, hidden_dropout=0.0,
             attention_dropout=0.0)
    d.update(kw)
    return TransformerConfig(**d)


def test_no_position_table():
    model = GPTModel(_cfg())
    params = model.init(jax.random.PRNGKey(0))
    assert "position_embeddings" not in params["embedding"]
    assert "position_embeddings" not in model.spec()["embedding"]


def test_rope_freqs_layout():
    from apex_tpu.models.transformer import rope_freqs

    f = rope_freqs(0, 8, 16, 10000.0)
    assert f.shape == (8, 1, 1, 16)
    np.testing.assert_allclose(np.asarray(f[0, 0, 0]), 0.0)   # pos 0 -> no rot
    # Megatron concat(f, f) convention
    np.testing.assert_allclose(np.asarray(f[3, 0, 0, :8]),
                               np.asarray(f[3, 0, 0, 8:]))


def test_rope_changes_the_function():
    """rope vs none with identical params must differ on varied tokens —
    i.e. the rotation is actually applied."""
    rope = GPTModel(_cfg())
    none = GPTModel(_cfg(position_embedding_type="none"))
    params = rope.init(jax.random.PRNGKey(0))   # same tree shape for both
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, 64)
    out_rope = rope.apply(params, toks)
    out_none = none.apply(params, toks)
    assert not np.allclose(np.asarray(out_rope, np.float32),
                           np.asarray(out_none, np.float32), atol=1e-4)


def test_relative_position_property():
    """A uniform token sequence yields position-independent outputs under
    rope (identical values at every slot make attention output independent
    of the rotated scores) — the relative-position contract; learned
    positions break it."""
    model = GPTModel(_cfg())
    params = model.init(jax.random.PRNGKey(0))
    toks = jnp.full((1, 8), 5, jnp.int32)
    logits = model.apply(params, toks)
    np.testing.assert_allclose(np.asarray(logits[0, 0], np.float32),
                               np.asarray(logits[7, 0], np.float32),
                               atol=1e-4)


@pytest.mark.slow
def test_cached_decode_matches_full_forward():
    model = GPTModel(_cfg())
    params = model.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 64)
    full = model.apply(params, tokens)
    caches = init_kv_caches(model, 2, 16)
    for i in range(10):
        logits, caches = decode_step(model, params, caches, tokens[:, i], i)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full[i]).astype(np.float32),
            rtol=2e-4, atol=2e-4)


@pytest.mark.slow  # decode parity sweep: slow tier (ROADMAP)


def test_rope_with_gqa_decode():
    model = GPTModel(_cfg(num_attention_heads=8, num_query_groups=2))
    params = model.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, 64)
    full = model.apply(params, tokens)
    caches = init_kv_caches(model, 2, 8)
    for i in range(6):
        logits, caches = decode_step(model, params, caches, tokens[:, i], i)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full[i]).astype(np.float32),
            rtol=2e-4, atol=2e-4)


def test_partial_rotary():
    model = GPTModel(_cfg(rotary_percent=0.5))
    params = model.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, 64)
    logits = model.apply(params, toks)
    assert np.isfinite(np.asarray(logits, np.float32)).all()


def test_training_decreases_loss():
    model = GPTModel(_cfg())
    params = model.init(jax.random.PRNGKey(0))
    from apex_tpu.optimizers import FusedAdam

    opt = FusedAdam(lr=2e-3)
    st = opt.init(params)
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    labs = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0, 64)

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(lambda p: model.apply(p, toks, labs))(p)
        p, s = opt.step(g, p, s)
        return p, s, l

    losses = []
    for _ in range(5):
        params, st, l = step(params, st)
        losses.append(float(l))
    assert losses[-1] < losses[0]


def _tp_parity_train(tp, cfg_kwargs, sp=False, steps=3):
    """Train the same seeded GPT under (tp, sp); return the loss trace."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=tp)
    model = GPTModel(_cfg(sequence_parallel=sp, **cfg_kwargs))
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-3)
    ost = opt.init(params)
    step = make_train_step(
        lambda p, b, r: model.apply(p, b["tokens"], b["labels"], rng=r),
        opt, mesh, model.spec(),
        {"tokens": P("data"), "labels": P("data")},
        params_template=params)
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    labs = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0, 64)
    losses = []
    for _ in range(steps):
        params, ost, loss = step(params, ost,
                                 {"tokens": toks, "labels": labs},
                                 jax.random.PRNGKey(3))
        losses.append(float(loss))
    parallel_state.destroy_model_parallel()
    return losses


def _losses_after_training(model, steps=4, lr=2e-3):
    from apex_tpu.optimizers import FusedAdam

    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=lr)
    st = opt.init(params)
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
    labs = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, 64)

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(lambda p: model.apply(p, toks, labs))(p)
        return opt.step(g, p, s) + (l,)

    losses = []
    for _ in range(steps):
        params, st, l = step(params, st)
        losses.append(float(l))
    return losses, params


@pytest.mark.slow
def test_tp2_matches_unsharded():
    np.testing.assert_allclose(_tp_parity_train(1, {}),
                               _tp_parity_train(2, {}),
                               atol=2e-5, rtol=2e-5)


def test_invalid_position_type_rejected():
    with pytest.raises(ValueError, match="position_embedding_type"):
        _cfg(position_embedding_type="rotary")


def test_invalid_rotary_percent_rejected():
    with pytest.raises(ValueError, match="rotary_percent"):
        _cfg(rotary_percent=1.5)
    with pytest.raises(ValueError, match="rotary_percent"):
        _cfg(rotary_percent=0.0)
    with pytest.raises(ValueError):
        _cfg(rotary_percent=0.01).rotary_dim   # rounds below 2 channels


def test_pipelined_param_tree_matches_gpt():
    """PipelinedGPT under rope must not allocate the dead position table
    (same embedding tree as GPTModel for the same config)."""
    from apex_tpu.models import PipelinedGPT

    cfg = _cfg(num_layers=2)
    pp = PipelinedGPT(cfg, pipeline_size=1, num_microbatches=1)
    params = pp.init(jax.random.PRNGKey(0))
    assert "position_embeddings" not in params["embedding"]
    assert "position_embeddings" not in pp.spec()["embedding"]


class TestActivations:
    """MLP activation config incl. gated variants (swiglu/geglu — exceeds
    the gelu-only reference ParallelMLP). Gated runs one fused 2*ffn
    column projection with gate/up unit-interleaved."""

    @pytest.mark.parametrize("act", ["gelu", "relu", "swiglu", "geglu"])
    def test_trains(self, act):
        model = GPTModel(_cfg(activation=act,
                              position_embedding_type="learned"))
        losses, params = _losses_after_training(model)
        if act in ("swiglu", "geglu"):
            w = params["transformer"]["layers"]["mlp"]["dense_h_to_4h"][
                "weight"]
            assert w.shape[-2] == 2 * 4 * 64   # fused [2*ffn, h], per layer
        assert losses[-1] < losses[0]

    @pytest.mark.slow
    def test_swiglu_tp2_matches_unsharded(self):
        np.testing.assert_allclose(
            _tp_parity_train(1, {"activation": "swiglu"}),
            _tp_parity_train(2, {"activation": "swiglu"}),
            atol=2e-5, rtol=2e-5)

    def test_invalid_activation_rejected(self):
        with pytest.raises(ValueError, match="activation"):
            _cfg(activation="swish")


class TestNormalization:
    """normalization="rmsnorm" (LLaMA-class, bias-free RMS statistics via
    the fused RMSNorm kernel) vs the reference's LayerNorm default."""

    def test_rmsnorm_params_have_no_bias(self):
        model = GPTModel(_cfg(normalization="rmsnorm"))
        params = model.init(jax.random.PRNGKey(0))
        ln = params["transformer"]["layers"]["input_layernorm"]
        assert "bias" not in ln and "weight" in ln
        fln = params["transformer"]["final_layernorm"]
        assert "bias" not in fln

    def test_rmsnorm_trains_llama_trio(self):
        model = GPTModel(_cfg(normalization="rmsnorm", activation="swiglu"))
        losses, _ = _losses_after_training(model)
        assert losses[-1] < losses[0]

    def test_rmsnorm_matches_manual(self):
        from apex_tpu.models.transformer import _ln, _ln_params

        x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 32))
        p = _ln_params(32, jnp.float32, "rmsnorm")
        y = _ln(p, x, 1e-5, norm="rmsnorm")
        ref = x / np.sqrt(np.mean(np.asarray(x) ** 2, -1, keepdims=True)
                          + 1e-5)
        np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-5, atol=2e-5)

    @pytest.mark.slow
    def test_rmsnorm_tp2_sp_matches_unsharded(self):
        ref = _tp_parity_train(1, {"normalization": "rmsnorm"})
        np.testing.assert_allclose(
            ref, _tp_parity_train(2, {"normalization": "rmsnorm"}),
            atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(
            ref, _tp_parity_train(2, {"normalization": "rmsnorm"}, sp=True),
            atol=2e-5, rtol=2e-5)

    def test_invalid_normalization_rejected(self):
        with pytest.raises(ValueError, match="normalization"):
            _cfg(normalization="batchnorm")


def test_gelu_init_stream_is_plain_two_way_split():
    """Default-gelu params come from the historical 2-way key split
    (seed-stable init for old checkpoints)."""
    from apex_tpu.models.transformer import ParallelMLP

    mlp = ParallelMLP(_cfg(position_embedding_type="learned"))
    p = mlp.init(jax.random.PRNGKey(7))
    k1, _ = jax.random.split(jax.random.PRNGKey(7))
    ref = mlp.dense_h_to_4h.init(k1)
    np.testing.assert_array_equal(np.asarray(p["dense_h_to_4h"]["weight"]),
                                  np.asarray(ref["weight"]))


@pytest.mark.slow  # composition parity sweep: slow tier (ROADMAP)


def test_moe_with_gated_activation():
    """activation threads through MoEConfig: swiglu experts get the
    unit-interleaved 2*ffn w_in and the model trains."""
    model = GPTModel(_cfg(activation="swiglu", num_moe_experts=4,
                          position_embedding_type="learned",
                          moe_expert_axis=None))
    losses, params = _losses_after_training(model)
    w_in = params["transformer"]["layers"]["mlp"]["w_in"]
    assert w_in.shape[-1] == 2 * 4 * 64      # [L, E, h, 2*ffn]
    assert losses[-1] < losses[0]


def test_gated_projection_is_bias_free():
    """LLaMA convention: the fused gate/up projection carries no bias
    (dense and MoE paths share it via utils/activations.py)."""
    from apex_tpu.transformer.moe import MoEConfig, SwitchMLP

    model = GPTModel(_cfg(activation="swiglu",
                          position_embedding_type="learned"))
    params = model.init(jax.random.PRNGKey(0))
    assert "bias" not in params["transformer"]["layers"]["mlp"][
        "dense_h_to_4h"]
    moe = SwitchMLP(MoEConfig(hidden_size=32, ffn_hidden_size=64,
                              num_experts=2, activation="swiglu",
                              expert_axis=None))
    mp = moe.init(jax.random.PRNGKey(0))
    assert "b_in" not in mp and "b_in" not in moe.spec()


class TestSlidingWindowModel:
    @pytest.mark.slow
    def test_decode_matches_full_forward(self):
        """Cached decode must reproduce the full windowed forward (window
        folded into the cache mask at real cache offsets)."""
        model = GPTModel(_cfg(sliding_window=4,
                              position_embedding_type="learned"))
        params = model.init(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 64)
        full = model.apply(params, tokens)
        caches = init_kv_caches(model, 2, 16)
        for i in range(10):
            logits, caches = decode_step(model, params, caches,
                                         tokens[:, i], i)
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(full[i]).astype(np.float32),
                rtol=2e-4, atol=2e-4)

    def test_window_changes_function(self):
        full = GPTModel(_cfg(position_embedding_type="learned"))
        win = GPTModel(_cfg(sliding_window=2,
                            position_embedding_type="learned"))
        params = full.init(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 64)
        assert not np.allclose(
            np.asarray(full.apply(params, toks), np.float32),
            np.asarray(win.apply(params, toks), np.float32), atol=1e-4)

    def test_validation(self):
        with pytest.raises(ValueError, match="sliding_window"):
            _cfg(sliding_window=0)
        # sliding_window under context parallelism is supported: the ring
        # masks with global positions (exact across chunk boundaries)
        _cfg(sliding_window=4, context_parallel_method="ring")


def test_sliding_window_with_dropout_trains_windowed():
    """Regression for the dropped-mask bug: with attention dropout active
    (unfused softmax path) the window must still bind — rows beyond the
    window get zero probability, so changing far-past tokens cannot change
    the loss."""
    model = GPTModel(_cfg(sliding_window=2, attention_dropout=0.3,
                          position_embedding_type="learned"))
    params = model.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0, 64)
    # same rng -> same dropout; mutate a token far outside every window of
    # the last position's loss contribution... simplest check: full-vs-window
    # divergence on the dropout path
    full = GPTModel(_cfg(attention_dropout=0.3,
                         position_embedding_type="learned"))
    r = jax.random.PRNGKey(7)
    lw = model.apply(params, toks, toks, rng=r, deterministic=False)
    lf = full.apply(params, toks, toks, rng=r, deterministic=False)
    assert float(lw) != float(lf)
