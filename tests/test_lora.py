"""Multi-LoRA tests: adapter bank lifecycle, fused fine-tuning, and
multi-tenant serving correctness.

Correctness anchor: an engine serving adapter traffic through the
stacked device bank must be TOKEN-EXACT against a reference engine
serving ``merge_adapter(params, factors)`` (``W' = W + (A @ B).T``) —
the per-slot factored delta is an execution strategy, never an
approximation. The structural satellites ride along: typed submit
validation with its own shed counter, a per-adapter admission ledger
the monitor reconciles key-for-key, adapter-salted prefix chains (no
cross-tenant page aliasing), and conservation under randomized
multi-tenant churn with a mid-run unload.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.lora import (
    LORA_TARGETS,
    AdapterStore,
    UnknownAdapterError,
    init_adapter,
    lora_finetune,
    merge_adapter,
    random_adapter,
    target_dims,
)
from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.observability import (
    JsonlSink,
    MetricsRegistry,
    build_report,
    render_report,
)
from apex_tpu.serving import (
    EngineConfig,
    InferenceEngine,
    Request,
    SamplingParams,
    ShardedEngine,
)


@pytest.fixture(scope="module")
def small():
    model = GPTModel(TransformerConfig(
        num_layers=2, hidden_size=32, num_attention_heads=4, vocab_size=64,
        max_position_embeddings=64, hidden_dropout=0.0,
        attention_dropout=0.0))
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _prompts(lens, seed=7):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 64, size=n).tolist() for n in lens]


def _store(config, ids=("a",), rank=4, max_adapters=4, scale=0.05):
    """An AdapterStore with nonzero (random_adapter) factors per id —
    the adapters are also returned so tests can merge them."""
    store = AdapterStore(config, rank, max_adapters=max_adapters)
    factors = {}
    for i, aid in enumerate(ids):
        factors[aid] = random_adapter(config, rank,
                                      jax.random.PRNGKey(i + 1),
                                      scale=scale)
        store.load(aid, factors[aid])
    return store, factors


# ---------------------------------------------------------------------------
# adapter format + store lifecycle (host-side, no engine)


class TestAdapterStore:
    def test_bank_shape_and_reserved_null_row(self, small):
        model, _ = small
        store = AdapterStore(model.config, rank=4, max_adapters=3)
        assert store.null_index == 3
        dims = target_dims(model.config)
        assert set(store.bank) == set(dims) == set(LORA_TARGETS)
        L = model.config.num_layers
        for t, (din, dout) in dims.items():
            assert store.bank[t]["A"].shape == (L, 4, din, 4)
            assert store.bank[t]["B"].shape == (L, 4, 4, dout)
        # null row stays all-zeros through load/unload traffic
        ix = store.load("a", random_adapter(model.config, 4,
                                            jax.random.PRNGKey(1)))
        assert ix != store.null_index
        for t in store.bank:
            assert not np.asarray(
                store.bank[t]["A"][:, store.null_index]).any()
            assert not np.asarray(
                store.bank[t]["B"][:, store.null_index]).any()

    def test_load_unload_index_lifecycle(self, small):
        model, _ = small
        store, _ = _store(model.config, ids=("a", "b"), max_adapters=3)
        assert store.ids() == ["a", "b"]
        assert "a" in store and "ghost" not in store
        assert len(store) == 2
        ia, ib = store.index_of("a"), store.index_of("b")
        assert ia != ib
        assert store.index_of(None) == store.null_index
        # overwrite keeps the index; the row content changes in place
        before = np.asarray(store.bank[LORA_TARGETS[0]]["A"][:, ia]).copy()
        assert store.load("a", random_adapter(
            model.config, 4, jax.random.PRNGKey(9))) == ia
        after = np.asarray(store.bank[LORA_TARGETS[0]]["A"][:, ia])
        assert not np.array_equal(before, after)
        # unload zeroes the row, frees the index, and forgets the id
        store.unload("a")
        assert "a" not in store and store.ids() == ["b"]
        for t in store.bank:
            assert not np.asarray(store.bank[t]["A"][:, ia]).any()
            assert not np.asarray(store.bank[t]["B"][:, ia]).any()
        with pytest.raises(UnknownAdapterError):
            store.index_of("a")
        with pytest.raises(UnknownAdapterError):
            store.unload("a")
        # freed index is reused (lowest-first, like the slot pool)
        assert store.load("c", random_adapter(
            model.config, 4, jax.random.PRNGKey(3))) == min(
                ia, store.null_index)

    def test_full_bank_and_bad_factors_rejected(self, small):
        model, _ = small
        store, _ = _store(model.config, ids=("a", "b"), max_adapters=2)
        with pytest.raises(ValueError, match="full"):
            store.load("c", random_adapter(model.config, 4,
                                           jax.random.PRNGKey(5)))
        # rank mismatch / missing target fail the shape check
        with pytest.raises(ValueError, match="shape"):
            store.load("a", random_adapter(model.config, 2,
                                           jax.random.PRNGKey(5)))
        wrong = random_adapter(model.config, 4, jax.random.PRNGKey(5))
        wrong.pop("dense_h_to_4h")
        with pytest.raises(ValueError, match="targets"):
            store.load("a", wrong)
        with pytest.raises(ValueError, match="adapter_id"):
            store.load("", random_adapter(model.config, 4,
                                          jax.random.PRNGKey(5)))

    def test_constructor_validation(self, small):
        model, _ = small
        with pytest.raises(ValueError, match="rank"):
            AdapterStore(model.config, rank=0)
        with pytest.raises(ValueError, match="max_adapters"):
            AdapterStore(model.config, rank=4, max_adapters=0)

    def test_unknown_adapter_error_is_key_error(self):
        # submit paths catch it as the typed error; callers that treat
        # the store as a mapping still catch their KeyError
        assert issubclass(UnknownAdapterError, KeyError)


# ---------------------------------------------------------------------------
# merge math: the ground truth the parity tests compare against


class TestMergeMath:
    def test_merge_matches_manual_fold(self, small):
        model, params = small
        f = random_adapter(model.config, 4, jax.random.PRNGKey(2))
        merged = merge_adapter(params, f)
        layers = params["transformer"]["layers"]
        mlayers = merged["transformer"]["layers"]
        paths = {"query_key_value": ("self_attention", "query_key_value"),
                 "dense_h_to_4h": ("mlp", "dense_h_to_4h")}
        for t, (sub, name) in paths.items():
            w = np.asarray(layers[sub][name]["weight"], np.float32)
            got = np.asarray(mlayers[sub][name]["weight"], np.float32)
            for layer in range(model.config.num_layers):
                delta = (np.asarray(f[t]["A"][layer]) @
                         np.asarray(f[t]["B"][layer])).T
                np.testing.assert_allclose(got[layer], w[layer] + delta,
                                           rtol=1e-5, atol=1e-5)
        # untouched leaves are the SAME arrays; the input pytree is not
        # mutated (merge returns a new tree)
        assert merged["embedding"] is params["embedding"]
        assert merged["transformer"]["layers"]["self_attention"] \
            ["dense"] is layers["self_attention"]["dense"]

    def test_zero_init_adapter_merges_to_identity(self, small):
        model, params = small
        f = init_adapter(model.config, 4, jax.random.PRNGKey(2))
        merged = merge_adapter(params, f)
        same = jax.tree.map(np.array_equal, params, merged)
        assert all(jax.tree.leaves(same))


# ---------------------------------------------------------------------------
# fused fine-tuning: batched jobs, frozen base, flat-bucket updates


class TestFinetune:
    def test_batched_jobs_loss_decreases_base_frozen(self, small):
        model, params = small
        rng = np.random.RandomState(11)
        tokens = jnp.asarray(rng.randint(0, 64, size=(2, 2, 8)), jnp.int32)
        labels = jnp.asarray(rng.randint(0, 64, size=(2, 2, 8)), jnp.int32)
        base_snapshot = jax.tree.map(lambda x: np.asarray(x).copy(), params)
        factors, losses = lora_finetune(model, params, tokens, labels,
                                        rank=2, steps=8, lr=1e-2,
                                        rng=jax.random.PRNGKey(0))
        assert losses.shape == (8, 2)
        # B init is zero, so step-0 loss IS the base-model loss; every
        # job must then improve on it — only the factors trained
        for j in range(2):
            assert float(losses[-1, j]) < float(losses[0, j])
        same = jax.tree.map(lambda a, b: np.array_equal(np.asarray(a), b),
                            params, base_snapshot)
        assert all(jax.tree.leaves(same)), "base params were touched"
        # the stacked output slices into per-job adapters that the store
        # accepts — the finetune -> serve handoff
        store = AdapterStore(model.config, 2, max_adapters=2)
        for j in range(2):
            store.load(f"job{j}", jax.tree.map(lambda x: x[j], factors))
        assert store.ids() == ["job0", "job1"]

    @pytest.mark.slow  # training-convergence claim: slow tier (ROADMAP)

    def test_trained_adapter_beats_base_when_merged(self, small):
        model, params = small
        rng = np.random.RandomState(13)
        tokens = jnp.asarray(rng.randint(0, 64, size=(1, 2, 8)), jnp.int32)
        labels = tokens  # learn to echo: an easy, monotone objective
        factors, losses = lora_finetune(model, params, tokens, labels,
                                        rank=2, steps=10, lr=2e-2,
                                        rng=jax.random.PRNGKey(1))
        merged = merge_adapter(params, jax.tree.map(lambda x: x[0],
                                                    factors))
        base_loss = float(model.apply(params, tokens[0], labels[0]))
        tuned_loss = float(model.apply(merged, tokens[0], labels[0]))
        assert tuned_loss < base_loss

    def test_label_shape_mismatch_rejected(self, small):
        model, params = small
        tokens = jnp.zeros((1, 2, 8), jnp.int32)
        with pytest.raises(ValueError, match="labels"):
            lora_finetune(model, params, tokens,
                          jnp.zeros((1, 2, 7), jnp.int32))


# ---------------------------------------------------------------------------
# submit validation + the per-adapter ledger (no compile: every request
# here is shed or cancelled before prefill)


class TestSubmitValidation:
    def test_unknown_adapter_typed_error_counter_and_record(self, small):
        model, params = small
        store, _ = _store(model.config, ids=("a",))
        eng = InferenceEngine(model, params,
                              EngineConfig(max_slots=2, max_len=16),
                              adapters=store)
        req = Request(prompt=[1, 2], max_new_tokens=2,
                      sampling=SamplingParams(adapter_id="ghost"))
        with pytest.raises(UnknownAdapterError, match="ghost"):
            eng.submit(req)
        assert eng.metrics.counters()["requests_shed_adapter"] == 1
        # terminal rejected record, conservation-safe: the result exists
        # even though submit raised
        res = eng.completed[req.request_id]
        assert res.finish_reason == "rejected"
        assert res.adapter_id == "ghost"
        assert eng.queued_count == 0 and eng.active_count == 0
        eng.close()

    def test_engine_without_store_rejects_adapter_requests(self, small):
        model, params = small
        eng = InferenceEngine(model, params,
                              EngineConfig(max_slots=2, max_len=16))
        with pytest.raises(UnknownAdapterError, match="AdapterStore"):
            eng.submit(Request(prompt=[1], max_new_tokens=1,
                               sampling=SamplingParams(adapter_id="a")))
        assert eng.metrics.counters()["requests_shed_adapter"] == 1
        eng.close()

    def test_ledger_reconciles_key_for_key(self, small, tmp_path):
        """The satellite acceptance: per-adapter counters, the
        adapter_request event stream, and the adapter_id-stamped result
        rows all reconcile key-for-key through the monitor report."""
        model, params = small
        store, _ = _store(model.config, ids=("a", "b", "c"))
        log = tmp_path / "lora.jsonl"
        reg = MetricsRegistry([JsonlSink(str(log))])
        eng = InferenceEngine(model, params,
                              EngineConfig(max_slots=2, max_len=16),
                              metrics=reg, adapters=store)
        mix = ["a", "a", "a", "b", "b", None, "c"]
        reqs = [Request(prompt=[1, 2], max_new_tokens=2,
                        sampling=SamplingParams(adapter_id=aid))
                for aid in mix]
        for r in reqs:
            eng.submit(r)
        with pytest.raises(UnknownAdapterError):
            eng.submit(Request(prompt=[1], max_new_tokens=1,
                               sampling=SamplingParams(adapter_id="ghost")))
        for r in reqs:          # cancelled while queued: no compile
            assert eng.cancel(r.request_id)
        eng.close()
        report = build_report(str(log))
        sec = report["adapters"]
        assert sec is not None
        assert sec["admitted_by_adapter"] == {"a": 3, "b": 2, "c": 1}
        assert sec["admitted_by_index"] == {
            str(store.index_of("a")): 3, str(store.index_of("b")): 2,
            str(store.index_of("c")): 1}
        # counter view matches the event view key-for-key
        assert sec["counters"] == {
            f"adapter{store.index_of('a')}_requests": 3,
            f"adapter{store.index_of('b')}_requests": 2,
            f"adapter{store.index_of('c')}_requests": 1}
        assert sec["shed_unknown"] == 1
        # every terminal row carries its adapter_id (incl. the shed one)
        assert sec["finished_by_adapter"] == {"a": 3, "b": 2, "c": 1,
                                              "ghost": 1}
        text = render_report(report)
        assert "adapters (multi-LoRA):" in text

    def test_base_only_log_has_no_adapter_section(self, small, tmp_path):
        model, params = small
        log = tmp_path / "base.jsonl"
        reg = MetricsRegistry([JsonlSink(str(log))])
        eng = InferenceEngine(model, params,
                              EngineConfig(max_slots=2, max_len=16),
                              metrics=reg)
        req = Request(prompt=[1, 2], max_new_tokens=2)
        eng.submit(req)
        eng.cancel(req.request_id)
        eng.close()
        assert build_report(str(log))["adapters"] is None


# ---------------------------------------------------------------------------
# randomized multi-tenant churn (tier-1: one engine, one compile set)


class TestMultiTenantChurn:
    @pytest.mark.slow  # compile-bound churn integration (ROADMAP tiers)
    def test_churn_terminal_once_no_leaks_co_tenant_exact(self, small):
        """Seeded random multi-tenant arrivals x cancellations x a
        mid-run unload on one paged engine: every request reaches
        exactly one terminal state, pages/slots drain back to full,
        decode never retraces, and co-tenant duplicates (same prompt,
        same adapter, greedy) stay token-exact with each other."""
        model, params = small
        store, _ = _store(model.config, ids=("a", "b"))
        eng = InferenceEngine(model, params, EngineConfig(
            max_slots=3, max_len=16, page_size=4, retrace_budget=0),
            adapters=store)
        rng = np.random.RandomState(53)
        twin_prompt = rng.randint(0, 64, size=5).tolist()
        twins = [Request(prompt=list(twin_prompt), max_new_tokens=5,
                         sampling=SamplingParams(adapter_id="a"))
                 for _ in range(2)]
        randoms = [
            Request(prompt=rng.randint(0, 64,
                                       size=rng.randint(1, 9)).tolist(),
                    max_new_tokens=int(rng.randint(1, 6)),
                    sampling=SamplingParams(
                        adapter_id=[None, "a", "b"][rng.randint(3)]))
            for _ in range(10)]
        reqs = randoms[:4] + twins[:1] + randoms[4:] + twins[1:]
        shed = 0
        with eng:
            done = {}
            pending = list(reqs)
            ticks = 0
            unloaded = False
            while pending or eng.active_count or eng.queued_count:
                while pending and eng.queued_count < 4:
                    try:
                        eng.submit(pending.pop(0))
                    except UnknownAdapterError:
                        shed += 1   # recorded terminally by the engine
                for res in eng.tick():
                    done[res.request_id] = res
                ticks += 1
                if ticks == 6 and not unloaded:
                    # mid-run unload: in-flight "b" requests degrade to
                    # the zero row; queued/new "b" submits shed
                    store.unload("b")
                    unloaded = True
                if ticks % 5 == 0 and eng.active_count:
                    req, _, _ = eng.inflight()[
                        int(rng.randint(eng.active_count))]
                    eng.cancel(req.request_id)
                assert eng.pages.free_count + eng.pages.in_use_count == \
                    eng.pages.n_pages
            assert eng.decode_retraces == 0
            eng.pages.check()
            eng.slots.check()
            done.update(eng.completed)
        # conservation: every request terminal exactly once
        assert len(done) == len(reqs)
        assert sorted(done) == sorted(r.request_id for r in reqs)
        reasons = {r.finish_reason for r in done.values()}
        assert reasons <= {"length", "eos", "cancelled", "rejected"}
        rejected = [r for r in done.values()
                    if r.finish_reason == "rejected"]
        assert len(rejected) == shed
        assert all(r.adapter_id == "b" for r in rejected)
        assert eng.metrics.counters()["requests_shed_adapter"] == shed
        # co-tenant exactness: both twins finished under adapter "a"
        # (never unloaded) and emitted identical streams
        t0, t1 = (done[t.request_id] for t in twins)
        if t0.finish_reason != "cancelled" and \
                t1.finish_reason != "cancelled":
            assert t0.tokens == t1.tokens


# ---------------------------------------------------------------------------
# slow tier: merged-weights token-exactness (compile-bound parity)


@pytest.mark.slow
class TestAdapterParity:
    def test_paged_token_exact_greedy_and_sampled(self, small):
        """Acceptance: per-slot bank gathers are token-exact vs a
        reference engine serving merge_adapter'd params — greedy AND
        sampled, multiple tenants and base interleaved in one batch,
        with zero decode retraces."""
        model, params = small
        store, factors = _store(model.config, ids=("ta", "tb"))
        prompts = _prompts([5, 9, 3])
        ec = EngineConfig(max_slots=4, max_len=64, retrace_budget=0)
        eng = InferenceEngine(model, params, ec, adapters=store)

        def mk(p, aid, **kw):
            return Request(prompt=list(p), max_new_tokens=6,
                           sampling=SamplingParams(adapter_id=aid, **kw))

        reqs = [mk(prompts[0], "ta"), mk(prompts[0], "tb"),
                mk(prompts[1], None),
                mk(prompts[2], "ta", temperature=0.8, top_k=8, seed=11)]
        with eng:
            res = eng.serve(reqs)
            assert eng.decode_retraces == 0
        got = {q.request_id: r.tokens for q, r in zip(reqs, res)}
        merged = {"ta": merge_adapter(params, factors["ta"]),
                  "tb": merge_adapter(params, factors["tb"]),
                  None: params}
        for aid in ("ta", "tb", None):
            ref = InferenceEngine(model, merged[aid], ec)
            sel = [q for q in reqs if q.sampling.adapter_id == aid]
            with ref:
                rres = ref.serve([
                    Request(prompt=list(q.prompt),
                            max_new_tokens=q.max_new_tokens,
                            sampling=SamplingParams(
                                temperature=q.sampling.temperature,
                                top_k=q.sampling.top_k,
                                seed=q.sampling.seed))
                    for q in sel])
            for q, rr in zip(sel, rres):
                assert got[q.request_id] == rr.tokens, aid

    def test_variant_engines_token_exact(self, small):
        """The adapter path composes with every serving variant: flat
        KV, speculation, and int8+speculation all match their own
        merged-weights reference under the same config."""
        model, params = small
        store, factors = _store(model.config, ids=("a",))
        merged = merge_adapter(params, factors["a"])
        prompts = _prompts([5, 9, 3], seed=3)
        for name, ec in [
            ("flat", EngineConfig(max_slots=4, max_len=64,
                                  kv_layout="flat", retrace_budget=0)),
            ("spec", EngineConfig(max_slots=4, max_len=64, speculation=3,
                                  retrace_budget=0)),
            ("int8+spec", EngineConfig(max_slots=4, max_len=64,
                                       speculation=3, kv_dtype="int8",
                                       retrace_budget=0)),
        ]:
            eng = InferenceEngine(model, params, ec, adapters=store)
            with eng:
                res = eng.serve([
                    Request(prompt=list(p), max_new_tokens=6,
                            sampling=SamplingParams(adapter_id="a"))
                    for p in prompts])
            ref = InferenceEngine(model, merged, ec)
            with ref:
                rres = ref.serve([Request(prompt=list(p),
                                          max_new_tokens=6)
                                  for p in prompts])
            for r, rr in zip(res, rres):
                assert r.tokens == rr.tokens, name

    def test_hot_unload_degrades_inflight_rejects_new(self, small):
        model, params = small
        store, factors = _store(model.config, ids=("a",))
        ec = EngineConfig(max_slots=2, max_len=32, retrace_budget=0)
        prompt = _prompts([6], seed=9)[0]
        with InferenceEngine(model, params, ec, adapters=store) as eng:
            # admit under "a", then unload BEFORE prefill: the queued
            # request degrades to the null row — base-model output
            req = Request(prompt=list(prompt), max_new_tokens=6,
                          sampling=SamplingParams(adapter_id="a"))
            eng.submit(req)
            store.unload("a")
            while req.request_id not in eng.completed:
                eng.tick()
            degraded = eng.completed[req.request_id]
            with pytest.raises(UnknownAdapterError):
                eng.submit(Request(prompt=list(prompt), max_new_tokens=6,
                                   sampling=SamplingParams(
                                       adapter_id="a")))
        with InferenceEngine(model, params, ec) as base:
            ref = base.serve([Request(prompt=list(prompt),
                                      max_new_tokens=6)])
        assert degraded.tokens == ref[0].tokens

    def test_prefix_cache_no_cross_tenant_aliasing(self, small):
        """The aliasing regression at engine level: with the prefix
        cache ON, one prompt served under two adapters and base must
        give each tenant ITS merged-reference stream — adapter-salted
        chains keep adapter-specific K/V pages from crossing tenants —
        while same-tenant repeats still hit the cache."""
        model, params = small
        store, factors = _store(model.config, ids=("a", "b"))
        prompt = _prompts([8], seed=17)[0]
        ec = EngineConfig(max_slots=4, max_len=32, page_size=4,
                          prefix_cache=True, retrace_budget=0)

        def mk(aid):
            return Request(prompt=list(prompt), max_new_tokens=6,
                           sampling=SamplingParams(adapter_id=aid))

        eng = InferenceEngine(model, params, ec, adapters=store)
        with eng:
            first = eng.serve([mk("a"), mk("b"), mk(None)])
            again = eng.serve([mk("a")])   # same tenant: cache hit
            assert eng.metrics.counters()["prefix_hits"] >= 1
        expected = {}
        for aid, p in (("a", merge_adapter(params, factors["a"])),
                       ("b", merge_adapter(params, factors["b"])),
                       (None, params)):
            with InferenceEngine(model, p, ec) as ref:
                expected[aid] = ref.serve(
                    [Request(prompt=list(prompt),
                             max_new_tokens=6)])[0].tokens
        assert first[0].tokens == expected["a"]
        assert first[1].tokens == expected["b"]
        assert first[2].tokens == expected[None]
        assert again[0].tokens == expected["a"]


# ---------------------------------------------------------------------------
# slow tier: tp=2 sharded adapters (B bank shards with the heads)


@pytest.fixture()
def tp2_mesh():
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2)
    yield mesh
    parallel_state.destroy_model_parallel()


class TestShardedAdapters:
    @pytest.mark.slow
    def test_tp2_token_exact_vs_unsharded(self, small, tp2_mesh):
        """ShardedEngine with adapters on a tp=2 CPU mesh: the B bank
        shards its out dim with the weights (A replicated), and decode
        stays token-exact vs the unsharded adapter engine — greedy and
        sampled — with zero decode retraces."""
        model, params = small
        store, _ = _store(model.config, ids=("a",))
        prompts = _prompts([4, 7, 3], seed=61)

        def reqs():
            return [Request(prompt=list(prompts[0]), max_new_tokens=6,
                            sampling=SamplingParams(adapter_id="a")),
                    Request(prompt=list(prompts[1]), max_new_tokens=5,
                            sampling=SamplingParams(
                                adapter_id="a", temperature=0.8,
                                top_k=8, seed=3)),
                    Request(prompt=list(prompts[2]), max_new_tokens=6)]

        ec = EngineConfig(max_slots=4, max_len=32, retrace_budget=0)
        from apex_tpu.transformer import parallel_state

        parallel_state.destroy_model_parallel()
        ref = InferenceEngine(model, params, ec, adapters=store)
        with ref:
            base = ref.serve(reqs())
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=2)
        sh = ShardedEngine(model, params, ec, adapters=store)
        with sh:
            out = sh.serve(reqs())
            assert sh.decode_retraces == 0
        for a, b in zip(base, out):
            assert a.tokens == b.tokens
