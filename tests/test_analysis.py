"""apex_tpu.analysis suite: one positive + one negative fixture per APX
rule, suppression/baseline/config behavior, CLI exit codes, and the
retrace watchdog (fires on a forced recompile storm, stays silent on
stable shapes — standalone and wired through ``resilience.run_training``).
"""

import json
import logging
import os
import textwrap

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.analysis import (
    Baseline,
    RetraceBudgetExceeded,
    RetraceWatchdog,
    analyze_source,
    load_config,
)
from apex_tpu.analysis.engine import main as cli_main
from apex_tpu.analysis.rules import all_rules


def codes(src, only=None):
    """Run the pack (or one rule) over a snippet, return finding codes."""
    rules = all_rules()
    if only is not None:
        rules = [r for r in rules if r.code == only]
    return [f.code for f in analyze_source(textwrap.dedent(src),
                                           "snippet.py", rules)]


# ---------------------------------------------------------------------------
# rule fixtures: positive (must fire) + negative (must stay silent)
# ---------------------------------------------------------------------------

class TestAPX001PrngReuse:
    def test_positive_sequential_reuse(self):
        src = """
            import jax
            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
        """
        assert codes(src, "APX001") == ["APX001"]

    def test_positive_loop_reuse(self):
        src = """
            import jax
            def sample(key, n):
                out = []
                for _ in range(n):
                    out.append(jax.random.normal(key, (3,)))
                return out
        """
        assert codes(src, "APX001") == ["APX001"]

    def test_positive_comprehension_reuse(self):
        src = """
            import jax
            def sample(key):
                return [jax.random.normal(key, (3,)) for _ in range(4)]
        """
        assert codes(src, "APX001") == ["APX001"]

    def test_negative_split_between(self):
        src = """
            import jax
            def sample(key):
                a = jax.random.normal(key, (3,))
                key, sub = jax.random.split(key)
                b = jax.random.uniform(key, (3,))
                c = {k: jax.random.normal(k, (2,))
                     for k in jax.random.split(sub, 3)}
                return a + b, c
        """
        assert codes(src, "APX001") == []

    def test_negative_fold_in_loop(self):
        src = """
            import jax
            def sample(key, n):
                out = []
                for i in range(n):
                    k = jax.random.fold_in(key, i)
                    out.append(jax.random.normal(k, (3,)))
                return out
        """
        assert codes(src, "APX001") == []

    def test_import_alias_resolved(self):
        src = """
            from jax import random as jr
            def sample(key):
                return jr.normal(key, (3,)) + jr.uniform(key, (3,))
        """
        assert codes(src, "APX001") == ["APX001"]


class TestAPX002Concretization:
    def test_positive_float_and_if(self):
        src = """
            import jax
            @jax.jit
            def f(x):
                if x > 0:
                    return float(x)
                return x
        """
        got = codes(src, "APX002")
        assert got == ["APX002", "APX002"]

    def test_positive_call_form_jit(self):
        src = """
            import jax
            def f(x):
                return x.item()
            g = jax.jit(f)
        """
        assert codes(src, "APX002") == ["APX002"]

    def test_negative_static_and_shape_reads(self):
        src = """
            import jax
            from functools import partial
            @partial(jax.jit, static_argnames=("n",))
            def f(x, n):
                if n > 2:               # static: fine
                    pass
                if x is not None:       # structure check: fine
                    pass
                if x.ndim == 2:         # shape read: fine
                    pass
                m = int(x.shape[0])     # static shape: fine
                return x * m
        """
        assert codes(src, "APX002") == []


class TestAPX003HostSync:
    def test_positive_step_body(self):
        src = """
            import jax
            def train_step(state, batch):
                loss = state + batch
                jax.device_get(loss)
                return loss
        """
        assert codes(src, "APX003") == ["APX003"]

    def test_positive_block_until_ready(self):
        src = """
            import jax
            def _step(x):
                x.block_until_ready()
                return x
        """
        assert codes(src, "APX003") == ["APX003"]

    def test_negative_poll_helper_and_tests(self):
        src = """
            import jax
            def poll_metrics(pending):
                return jax.device_get(pending)   # off the hot loop: fine
            def test_step_values(x):
                return jax.device_get(x)         # test body: fine
        """
        assert codes(src, "APX003") == []


class TestAPX004Recompile:
    def test_positive_mutable_default_and_shape(self):
        src = """
            import jax
            @jax.jit
            def f(x, opts={}, shape=None):
                return x
        """
        got = codes(src, "APX004")
        assert got == ["APX004", "APX004"]

    def test_negative_static_shape(self):
        src = """
            import jax
            from functools import partial
            @partial(jax.jit, static_argnames=("shape",))
            def f(x, shape=None, opts=()):
                return x
        """
        assert codes(src, "APX004") == []


class TestAPX005Collectives:
    def test_positive_unbound_axis(self):
        src = """
            from jax import lax
            def f(x):
                return lax.psum(x, "tp")
        """
        assert codes(src, "APX005") == ["APX005"]

    def test_negative_bound_by_spec_or_mesh(self):
        src = """
            from jax import lax
            from jax.sharding import Mesh, PartitionSpec
            def make(devs):
                return Mesh(devs, ("data",))
            SPEC = PartitionSpec("tp")
            def f(x, axis):
                return lax.psum(x, "tp") + lax.pmean(x, "data") \\
                    + lax.psum(x, axis)   # variable axis: resolved elsewhere
        """
        assert codes(src, "APX005") == []


class TestAPX006Dtype:
    def test_positive_chained_roundtrip(self):
        src = """
            import jax.numpy as jnp
            def f(x):
                return x.astype(jnp.float32).astype(jnp.bfloat16)
        """
        assert codes(src, "APX006") == ["APX006"]

    def test_positive_fp32_in_bf16_function(self):
        src = """
            import jax.numpy as jnp
            def f(x):
                h = x.astype(jnp.bfloat16)
                acc = jnp.zeros((4,), dtype=jnp.float32)
                return h, acc
        """
        assert codes(src, "APX006") == ["APX006"]

    def test_negative_single_policy(self):
        src = """
            import jax.numpy as jnp
            def f(x):
                return x.astype(jnp.bfloat16)
            def g(x):
                return jnp.zeros((4,), dtype=jnp.float32)
        """
        assert codes(src, "APX006") == []


class TestAPX007PallasScan:
    def test_positive_interpret_in_scan_body(self):
        src = """
            from jax import lax
            from jax.experimental import pallas as pl
            def body(c, x):
                y = pl.pallas_call(lambda r: None, interpret=True)(x)
                return c, y
            def run(xs):
                return lax.scan(body, 0, xs)
        """
        assert codes(src, "APX007") == ["APX007"]

    def test_positive_one_call_hop(self):
        src = """
            from jax import lax
            from jax.experimental import pallas as pl
            def kernel(x, interpret):
                return pl.pallas_call(lambda r: None,
                                      interpret=interpret)(x)
            def body(c, x):
                return c, kernel(x, True)
            def run(xs):
                return lax.scan(body, 0, xs)
        """
        assert codes(src, "APX007") == ["APX007"]

    def test_negative_interpret_false_or_no_scan(self):
        src = """
            from jax import lax
            from jax.experimental import pallas as pl
            def body(c, x):
                y = pl.pallas_call(lambda r: None, interpret=False)(x)
                return c, y
            def run(xs):
                return lax.scan(body, 0, xs)
            def standalone(x):
                return pl.pallas_call(lambda r: None, interpret=True)(x)
        """
        assert codes(src, "APX007") == []


class TestAPX008MutableState:
    def test_positive_store_and_method(self):
        src = """
            import jax
            _CACHE = {}
            _LOG = []
            @jax.jit
            def f(x):
                _CACHE["last"] = x
                _LOG.append(1)
                return x
        """
        got = codes(src, "APX008")
        assert got == ["APX008", "APX008"]

    def test_negative_outside_jit_or_immutable(self):
        src = """
            import jax
            _CACHE = {}
            _LIMIT = 3
            def warm(x):
                _CACHE["x"] = x     # host-side registry: fine
                return x
            @jax.jit
            def f(x):
                return x * _LIMIT   # read-only: fine
        """
        assert codes(src, "APX008") == []


def codes_at(src, path, only):
    """Like :func:`codes` but with an explicit module path — the
    path-scoped rules (APX011/APX012) key off where the file lives."""
    rules = [r for r in all_rules() if r.code == only]
    return [f.code for f in analyze_source(textwrap.dedent(src), path,
                                           rules)]


class TestAPX009RecordContract:
    def test_positive_emit_without_counter(self):
        src = """
            def emit(metrics):
                metrics.emit_record({"kind": "widget", "n": 1})
        """
        assert codes(src, "APX009") == ["APX009"]

    def test_positive_dict_via_variable(self):
        src = """
            def emit(metrics):
                rec = {"kind": "widget"}
                rec.update(n=1)
                metrics.emit_record(rec)
        """
        assert codes(src, "APX009") == ["APX009"]

    def test_negative_counter_in_module(self):
        src = """
            def emit(metrics):
                metrics.inc("widgets")
                metrics.emit_record({"kind": "widget", "n": 1})
        """
        assert codes(src, "APX009") == []

    def test_negative_typed_result_record_skipped(self):
        # result.record() is the typed RequestResult path — reconciled
        # by construction, not a dict-literal contract site
        src = """
            def emit(metrics, result):
                metrics.emit_record(result.record(wall=0.0))
        """
        assert codes(src, "APX009") == []

    def _tree(self, tmp_path, report_src):
        from apex_tpu.analysis.engine import AnalysisConfig, analyze_paths
        from apex_tpu.analysis.rules.apx009_record_contract import (
            APX009RecordContract,
        )
        pkg = tmp_path / "pkg"
        obs = tmp_path / "observability"
        pkg.mkdir()
        obs.mkdir()
        (pkg / "emitter.py").write_text(textwrap.dedent("""
            def emit(metrics):
                metrics.inc("widgets")
                metrics.emit_record({"kind": "widget"})
        """))
        (obs / "report.py").write_text(report_src)
        cfg = AnalysisConfig(root=str(tmp_path))
        return analyze_paths([str(pkg), str(obs)], cfg,
                             [APX009RecordContract()])

    def test_cross_file_kind_unknown_to_report(self, tmp_path):
        found = self._tree(tmp_path, 'KINDS = ("request", "scenario")\n')
        assert [f.code for f in found] == ["APX009"]
        assert "unknown to observability/report.py" in found[0].message

    def test_cross_file_kind_reconciled(self, tmp_path):
        found = self._tree(tmp_path, 'KINDS = ("request", "widget")\n')
        assert found == []


class TestAPX010ScenarioSchema:
    def _tree(self, tmp_path, scenario_src, runner_src):
        from apex_tpu.analysis.engine import AnalysisConfig, analyze_paths
        from apex_tpu.analysis.rules.apx010_scenario_schema import (
            APX010ScenarioSchema,
        )
        lt = tmp_path / "loadtest"
        lt.mkdir()
        (lt / "scenario.py").write_text(textwrap.dedent(scenario_src))
        (lt / "runner.py").write_text(textwrap.dedent(runner_src))
        cfg = AnalysisConfig(root=str(tmp_path))
        return analyze_paths([str(lt)], cfg, [APX010ScenarioSchema()])

    _DRIFTED = """
        class Scenario:
            name: str
            seed: int = 0
            extra: int = 0

            @property
            def total_requests(self):
                return 0

            @classmethod
            def from_dict(cls, data):
                known = {"name", "seed", "ghost"}
                return cls()
    """

    _ALIGNED = """
        class Scenario:
            name: str
            seed: int = 0

            @property
            def total_requests(self):
                return 0

            @classmethod
            def from_dict(cls, data):
                known = {"name", "seed"}
                return cls()
    """

    def test_positive_schema_drift_both_directions(self, tmp_path):
        found = self._tree(tmp_path, self._DRIFTED,
                           "def run(scenario):\n    return scenario.name\n")
        msgs = [f.message for f in found]
        assert len(found) == 2
        assert any("'ghost'" in m for m in msgs)
        assert any("'extra'" in m for m in msgs)

    def test_positive_runner_reads_missing_attr(self, tmp_path):
        found = self._tree(
            tmp_path, self._ALIGNED,
            "def run(scenario):\n"
            "    n = scenario.total_requests\n"
            "    return scenario.bogus\n")
        assert [f.code for f in found] == ["APX010"]
        assert "scenario.bogus" in found[0].message

    def test_negative_aligned_surfaces(self, tmp_path):
        found = self._tree(
            tmp_path, self._ALIGNED,
            "def run(scenario):\n"
            "    return scenario.name, scenario.seed, "
            "scenario.total_requests\n")
        assert found == []

    def test_real_tree_is_clean(self):
        # the live scenario/runner pair must satisfy its own contract
        import apex_tpu

        from apex_tpu.analysis.engine import analyze_paths
        from apex_tpu.analysis.rules.apx010_scenario_schema import (
            APX010ScenarioSchema,
        )
        lt = os.path.join(os.path.dirname(apex_tpu.__file__), "loadtest")
        assert analyze_paths([lt], rules=[APX010ScenarioSchema()]) == []


class TestAPX011WallClock:
    def test_positive_direct_reads_in_serving(self):
        src = """
            import time
            def poll():
                t0 = time.monotonic()
                time.sleep(0.1)
                return time.time() - t0
        """
        got = codes_at(src, "apex_tpu/serving/foo.py", "APX011")
        assert got == ["APX011"] * 3

    def test_positive_alias_resolved_in_loadtest(self):
        src = """
            import time as _t
            def stamp():
                return _t.perf_counter()
        """
        assert codes_at(src, "apex_tpu/loadtest/foo.py",
                        "APX011") == ["APX011"]

    def test_negative_clock_module_is_exempt(self):
        src = """
            import time
            def now():
                return time.monotonic()
        """
        assert codes_at(src, "apex_tpu/serving/clock.py", "APX011") == []

    def test_negative_outside_scoped_trees(self):
        src = """
            import time
            def now():
                return time.monotonic()
        """
        assert codes_at(src, "apex_tpu/checkpoint/retry.py",
                        "APX011") == []

    def test_negative_clock_seam_usage(self):
        src = """
            from apex_tpu.serving import clock
            def poll():
                clock.sleep(0.1)
                return clock.now()
        """
        assert codes_at(src, "apex_tpu/serving/foo.py", "APX011") == []


class TestAPX012CounterBypass:
    def test_positive_bare_paired_counter(self):
        src = """
            def retire(self, rid):
                self.metrics.inc("replica_scale_downs")
        """
        got = codes_at(src, "apex_tpu/serving/fleet/foo.py", "APX012")
        assert got == ["APX012"]

    def test_negative_event_co_sited(self):
        src = """
            def retire(self, rid):
                self.metrics.inc("replica_scale_downs")
                self.metrics.event("replica_scale_down", replica_id=rid)
        """
        assert codes_at(src, "apex_tpu/serving/fleet/foo.py",
                        "APX012") == []

    def test_negative_unpaired_counter_is_fine(self):
        # dispatch counters are deliberately high-frequency/unpaired
        src = """
            def dispatch(self):
                self.metrics.inc("fleet_dispatches")
        """
        assert codes_at(src, "apex_tpu/serving/fleet/foo.py",
                        "APX012") == []

    def test_negative_outside_serving(self):
        src = """
            def retire(self):
                self.metrics.inc("replica_scale_downs")
        """
        assert codes_at(src, "apex_tpu/loadtest/foo.py", "APX012") == []

    def test_rule_set_matches_mc_invariants(self):
        # the lint rule and the runtime invariant must police the same
        # counter<->event pairs
        from apex_tpu.analysis.rules.apx012_counter_bypass import (
            _PAIRED_COUNTERS,
        )
        inv = pytest.importorskip("apex_tpu.analysis.mc.invariants")
        assert _PAIRED_COUNTERS == frozenset(inv.COUNTER_EVENTS)


class TestAPX013TriggerTable:
    """Every ``*_INCIDENT_COUNTERS`` key in the monitor must be a
    flight-recorder trigger — an incident the monitor reconciles but
    the recorder sleeps through leaves no postmortem."""

    def test_positive_ghost_incident_event(self):
        src = """
            GHOST_INCIDENT_COUNTERS = {
                "foo_melted": "foo_meltdowns",
            }
        """
        got = codes_at(src, "apex_tpu/observability/report.py",
                       "APX013")
        assert got == ["APX013"]

    def test_negative_real_trigger_events_pass(self):
        src = """
            SERVING_INCIDENT_COUNTERS = {
                "engine_restart": "engine_restarts",
                "tick_failure": "tick_failures",
            }
        """
        assert codes_at(src, "apex_tpu/observability/report.py",
                        "APX013") == []

    def test_negative_non_incident_maps_ignored(self):
        # only *_INCIDENT_COUNTERS assignments are the contract; other
        # dicts (shed reasons, render tables) may name non-triggers
        src = """
            SERVING_SHED_COUNTERS = {
                "queue_full": "requests_shed_queue_full",
            }
        """
        assert codes_at(src, "apex_tpu/observability/report.py",
                        "APX013") == []

    def test_negative_scoped_to_monitor_module(self):
        src = """
            MY_INCIDENT_COUNTERS = {"foo_melted": "x"}
        """
        assert codes_at(src, "apex_tpu/serving/foo.py", "APX013") == []

    def test_real_tree_is_clean(self):
        """The committed monitor module passes its own lint — the
        recorder builds TRIGGER_EVENTS from these maps by
        construction."""
        import apex_tpu.observability.report as report_mod
        with open(report_mod.__file__, encoding="utf-8") as f:
            src = f.read()
        rules = [r for r in all_rules() if r.code == "APX013"]
        found = analyze_source(
            src, "apex_tpu/observability/report.py", rules)
        assert [f.code for f in found] == []


# ---------------------------------------------------------------------------
# suppression, baseline, config, CLI
# ---------------------------------------------------------------------------

REUSE_SRC = """
import jax
def sample(key):
    a = jax.random.normal(key, (3,))
    b = jax.random.uniform(key, (3,))%s
    return a + b
"""


class TestSuppression:
    def test_noqa_specific_code(self):
        assert codes(REUSE_SRC % "  # noqa: APX001") == []

    def test_noqa_bare(self):
        assert codes(REUSE_SRC % "  # noqa") == []

    def test_noqa_other_code_does_not_suppress(self):
        assert codes(REUSE_SRC % "  # noqa: APX005") == ["APX001"]

    def test_noqa_multiple_codes(self):
        assert codes(REUSE_SRC % "  # noqa: APX005, APX001") == []


class TestBaseline:
    def _findings(self):
        from apex_tpu.analysis.engine import analyze_source
        return analyze_source(REUSE_SRC % "", "pkg/mod.py")

    def test_partition_matches_and_news(self):
        found = self._findings()
        bl = Baseline([{"path": "pkg/mod.py", "code": "APX001",
                        "snippet": found[0].snippet,
                        "justification": "known"}])
        new, matched, stale = bl.partition(found)
        assert new == [] and len(matched) == 1 and stale == []

    def test_unmatched_finding_is_new(self):
        found = self._findings()
        bl = Baseline([{"path": "other.py", "code": "APX001",
                        "snippet": found[0].snippet,
                        "justification": "known"}])
        new, matched, stale = bl.partition(found)
        assert len(new) == 1 and matched == [] and len(stale) == 1

    def test_snippet_keying_survives_line_drift(self):
        found = self._findings()
        bl = Baseline([{"path": "pkg/mod.py", "code": "APX001",
                        "line": 9999,  # wrong line: snippet still matches
                        "snippet": found[0].snippet,
                        "justification": "known"}])
        new, _, _ = bl.partition(found)
        assert new == []

    def test_roundtrip_save_load(self, tmp_path):
        found = self._findings()
        bl = Baseline.from_findings(found)
        p = tmp_path / "bl.json"
        bl.save(str(p))
        loaded = Baseline.load(str(p))
        # fresh from --write-baseline: placeholder justification, so the
        # entry does NOT yet suppress (the gate stays red until edited)
        new, _, _ = loaded.partition(found)
        assert len(new) == 1
        assert loaded.unjustified_entries() == loaded.entries
        for e in loaded.entries:    # ...the human step
            e["justification"] = "deliberate in this fixture"
        loaded.save(str(p))
        loaded = Baseline.load(str(p))
        new, matched, stale = loaded.partition(found)
        assert new == [] and len(matched) == 1 and stale == []
        assert all("justification" in e for e in loaded.entries)


class TestConfigAndCLI:
    def _project(self, tmp_path, extra=""):
        (tmp_path / "pyproject.toml").write_text(textwrap.dedent(f"""
            [project]
            name = "demo"

            [tool.apex_tpu.analysis]
            paths = ["pkg"]
            baseline = "bl.json"
            exclude = ["skipme"]
            {extra}
        """))
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "mod.py").write_text(REUSE_SRC % "")
        (pkg / "skipme.py").write_text(REUSE_SRC % "")
        return tmp_path

    def test_load_config_walks_up(self, tmp_path):
        root = self._project(tmp_path)
        cfg = load_config(str(root / "pkg" / "mod.py"))
        assert cfg.paths == ["pkg"]
        assert cfg.baseline == "bl.json"
        assert cfg.exclude == ["skipme"]
        assert cfg.root == str(root)

    def test_cli_reports_and_exits_nonzero(self, tmp_path, capsys):
        root = self._project(tmp_path)
        rc = cli_main([str(root / "pkg")])
        out = capsys.readouterr().out
        assert rc == 1
        assert "APX001" in out and "skipme" not in out

    @staticmethod
    def _justify(root):
        """The human step after ``--write-baseline``: replace the
        placeholder justifications with a real reason."""
        p = root / "bl.json"
        data = json.loads(p.read_text())
        for e in data["entries"]:
            e["justification"] = "deliberate in this fixture"
        p.write_text(json.dumps(data))

    def test_cli_write_baseline_then_clean(self, tmp_path, capsys):
        root = self._project(tmp_path)
        rc = cli_main([str(root / "pkg"), "--write-baseline"])
        assert rc == 0
        assert json.loads((root / "bl.json").read_text())["entries"]
        # placeholder justifications do not suppress: still red, with
        # the unjustified entry called out on stderr
        rc = cli_main([str(root / "pkg")])
        captured = capsys.readouterr()
        assert rc == 1
        assert "justification" in captured.err
        self._justify(root)
        rc = cli_main([str(root / "pkg")])
        out = capsys.readouterr().out
        assert rc == 0
        assert "1 baselined" in out

    def test_cli_stale_entry_reported(self, tmp_path, capsys):
        root = self._project(tmp_path)
        cli_main([str(root / "pkg"), "--write-baseline"])
        self._justify(root)
        (root / "pkg" / "mod.py").write_text("x = 1\n")
        rc = cli_main([str(root / "pkg")])
        err = capsys.readouterr().err
        assert rc == 0
        assert "stale" in err

    def test_cli_select_disable(self, tmp_path, capsys):
        root = self._project(tmp_path)
        assert cli_main([str(root / "pkg"), "--disable", "APX001"]) == 0
        assert cli_main([str(root / "pkg"), "--select", "APX005"]) == 0
        assert cli_main([str(root / "pkg"), "--select", "APX001"]) == 1
        capsys.readouterr()

    def test_syntax_error_is_finding_not_crash(self, tmp_path, capsys):
        root = self._project(tmp_path)
        (root / "pkg" / "broken.py").write_text("def f(:\n")
        rc = cli_main([str(root / "pkg")])
        out = capsys.readouterr().out
        assert rc == 1 and "APX000" in out

    def test_cli_prune_baseline_drops_dead_entries(self, tmp_path, capsys):
        root = self._project(tmp_path)
        cli_main([str(root / "pkg"), "--write-baseline"])
        self._justify(root)
        # fix the offending code: the baseline entry is now dead weight
        (root / "pkg" / "mod.py").write_text("x = 1\n")
        rc = cli_main([str(root / "pkg"), "--prune-baseline"])
        captured = capsys.readouterr()
        assert rc == 0
        assert "pruned 1 stale baseline entry (0 kept)" in captured.out
        assert json.loads((root / "bl.json").read_text())["entries"] == []
        # and the stale warning is gone on the next normal run
        rc = cli_main([str(root / "pkg")])
        assert rc == 0 and "stale" not in capsys.readouterr().err

    def test_cli_prune_keeps_live_entries(self, tmp_path, capsys):
        root = self._project(tmp_path)
        cli_main([str(root / "pkg"), "--write-baseline"])
        self._justify(root)
        rc = cli_main([str(root / "pkg"), "--prune-baseline"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "pruned 0 stale" in out and "(1 kept)" in out
        assert len(json.loads(
            (root / "bl.json").read_text())["entries"]) == 1

    def test_cli_prune_without_baseline_file_is_usage_error(
            self, tmp_path, capsys):
        root = self._project(tmp_path)   # bl.json configured, not written
        rc = cli_main([str(root / "pkg"), "--prune-baseline"])
        captured = capsys.readouterr()
        assert rc == 2
        assert "no baseline file to prune" in captured.err


class TestTomlReader:
    """``_read_toml_table`` prefers stdlib tomllib (py3.11+) and falls
    back to the mini reader on 3.10 — whose documented gap is backslash
    escapes in basic strings (returned verbatim, not decoded)."""

    def _table(self, tmp_path, body):
        from apex_tpu.analysis.engine import _read_toml_table
        p = tmp_path / "pyproject.toml"
        p.write_text("[tool.apex_tpu.analysis]\n" + textwrap.dedent(body))
        return _read_toml_table(str(p), "tool.apex_tpu.analysis")

    def test_plain_values_agree_across_readers(self, tmp_path):
        table = self._table(tmp_path, """\
            paths = ["pkg", "tools"]
            baseline = "bl.json"
            exclude = []
        """)
        assert table == {"paths": ["pkg", "tools"],
                         "baseline": "bl.json", "exclude": []}

    def test_escaped_string_values(self, tmp_path):
        # TOML basic strings decode \\t to a TAB; the mini reader does
        # not decode escapes — this test pins the divergence down so
        # config values stay escape-free until the gap matters
        table = self._table(tmp_path, 'baseline = "bl\\tname.json"\n')
        try:
            import tomllib  # noqa: F401  (py3.11+: the real parser)
            assert table["baseline"] == "bl\tname.json"
        except ImportError:
            assert table["baseline"] == "bl\\tname.json"

    def test_missing_file_and_table_are_empty(self, tmp_path):
        from apex_tpu.analysis.engine import _read_toml_table
        assert _read_toml_table(str(tmp_path / "nope.toml"),
                                "tool.apex_tpu.analysis") == {}
        assert self._table(tmp_path, "") == {}


# ---------------------------------------------------------------------------
# log_event ordering stamps (satellite: seq + monotonic ts)
# ---------------------------------------------------------------------------

class TestLogEventStamps:
    def test_seq_and_ts_present_and_monotonic(self):
        from apex_tpu.utils.logging import get_logger, log_event
        log = get_logger("apex_tpu.test_stamps")
        log.setLevel(logging.CRITICAL)  # keep output quiet
        lines = [log_event(log, "retrace", fn="step", call=i)
                 for i in range(3)]
        seqs, tss = [], []
        for line in lines:
            fields = dict(kv.split("=", 1) for kv in line.split()
                          if "=" in kv)
            assert fields["event"] == "retrace"
            seqs.append(int(fields["seq"]))
            tss.append(float(fields["ts"]))
        assert seqs == sorted(seqs) and len(set(seqs)) == 3
        assert tss == sorted(tss)

    def test_wall_stamp_is_epoch_time(self):
        # wall= (time.time()) rides next to the monotonic ts= so events
        # from different processes/hosts can be correlated; ts stays the
        # rate-measurement stamp (immune to clock steps)
        import time as _time

        from apex_tpu.utils.logging import get_logger, log_event
        log = get_logger("apex_tpu.test_stamps")
        log.setLevel(logging.CRITICAL)
        before = _time.time()
        line = log_event(log, "retrace", fn="step", call=0)
        after = _time.time()
        fields = dict(kv.split("=", 1) for kv in line.split() if "=" in kv)
        assert before <= float(fields["wall"]) <= after
        assert "ts" in fields  # monotonic stamp kept alongside


# ---------------------------------------------------------------------------
# retrace watchdog
# ---------------------------------------------------------------------------

class TestRetraceWatchdog:
    def test_stable_shapes_stay_silent(self):
        f = jax.jit(lambda x: x * 2)
        wd = RetraceWatchdog(f, budget=0)
        for _ in range(5):
            wd(jnp.ones((4,)))
        assert wd.retraces == 0 and wd.compiles == 1 and wd.calls == 5

    def test_budget_fires_on_forced_recompiles(self):
        f = jax.jit(lambda x: x * 2)
        wd = RetraceWatchdog(f, budget=2)
        with pytest.raises(RetraceBudgetExceeded) as exc:
            for n in range(2, 10):
                wd(jnp.ones((n,)))  # every call a new shape = a retrace
        assert exc.value.retraces == 3 and exc.value.budget == 2

    def test_log_only_when_budget_none(self):
        f = jax.jit(lambda x: x + 1)
        wd = RetraceWatchdog(f, budget=None)
        for n in range(2, 8):
            wd(jnp.ones((n,)))
        assert wd.retraces == 5  # counted, never raised

    def test_prewarmed_cache_is_baselined(self):
        f = jax.jit(lambda x: x - 1)
        f(jnp.ones((3,)))  # compile before the watchdog watches
        wd = RetraceWatchdog(f, budget=0)
        wd(jnp.ones((3,)))
        assert wd.compiles == 0 and wd.retraces == 0

    def test_signature_fallback_for_plain_callables(self):
        calls = []

        def plain(x):
            calls.append(x.shape)
            return x

        wd = RetraceWatchdog(plain, budget=2)
        wd(jnp.ones((2,)))
        wd(jnp.ones((2,)))
        assert wd.compiles == 1  # same signature, one "trace"
        with pytest.raises(RetraceBudgetExceeded):
            for n in range(3, 10):
                wd(jnp.ones((n,)))

    def test_dtype_change_counts_as_retrace(self):
        f = jax.jit(lambda x: x * 1)
        wd = RetraceWatchdog(f, budget=None)
        wd(jnp.ones((4,), jnp.float32))
        wd(jnp.ones((4,), jnp.bfloat16))
        assert wd.retraces == 1


class TestRunTrainingRetraceIntegration:
    def _step(self):
        @jax.jit
        def step(state, batch, rng):
            new = {"params": state["params"] - 0.1 * batch.mean(),
                   "step": state["step"] + 1}
            return new, {"loss": batch.mean(), "skipped": jnp.asarray(False)}
        return step

    def test_ragged_batches_trip_budget(self):
        from apex_tpu.resilience import ResilienceConfig, run_training
        state = {"params": jnp.zeros(()), "step": jnp.asarray(0, jnp.int32)}
        cfg = ResilienceConfig(retrace_budget=2, handle_sigterm=False,
                               poll_interval_steps=100)
        with pytest.raises(RetraceBudgetExceeded):
            # a ragged data pipeline: every step a new batch shape
            run_training(self._step(), state,
                         lambda step: jnp.ones((step + 2,)),
                         num_steps=10, config=cfg)

    def test_stable_run_reports_zero_retraces(self):
        from apex_tpu.resilience import ResilienceConfig, run_training
        state = {"params": jnp.zeros(()), "step": jnp.asarray(0, jnp.int32)}
        cfg = ResilienceConfig(retrace_budget=2, handle_sigterm=False,
                               poll_interval_steps=4)
        res = run_training(self._step(), state,
                           lambda step: jnp.ones((8,)),
                           num_steps=6, config=cfg)
        assert res.status == "completed"
        assert res.telemetry["retraces"] == 0

    def test_watchdog_disabled_with_none(self):
        from apex_tpu.resilience import ResilienceConfig, run_training
        state = {"params": jnp.zeros(()), "step": jnp.asarray(0, jnp.int32)}
        cfg = ResilienceConfig(retrace_budget=None, handle_sigterm=False,
                               poll_interval_steps=4)
        res = run_training(self._step(), state,
                           lambda step: jnp.ones((step + 2,)),
                           num_steps=5, config=cfg)
        assert res.status == "completed"  # slow, but allowed when opted out
