"""apex_tpu.analysis suite: one positive + one negative fixture per APX
rule, suppression/baseline/config behavior, CLI exit codes, and the
retrace watchdog (fires on a forced recompile storm, stays silent on
stable shapes — standalone and wired through ``resilience.run_training``).
"""

import json
import logging
import os
import textwrap

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.analysis import (
    Baseline,
    RetraceBudgetExceeded,
    RetraceWatchdog,
    analyze_source,
    load_config,
)
from apex_tpu.analysis.engine import main as cli_main
from apex_tpu.analysis.rules import all_rules


def codes(src, only=None):
    """Run the pack (or one rule) over a snippet, return finding codes."""
    rules = all_rules()
    if only is not None:
        rules = [r for r in rules if r.code == only]
    return [f.code for f in analyze_source(textwrap.dedent(src),
                                           "snippet.py", rules)]


# ---------------------------------------------------------------------------
# rule fixtures: positive (must fire) + negative (must stay silent)
# ---------------------------------------------------------------------------

class TestAPX001PrngReuse:
    def test_positive_sequential_reuse(self):
        src = """
            import jax
            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
        """
        assert codes(src, "APX001") == ["APX001"]

    def test_positive_loop_reuse(self):
        src = """
            import jax
            def sample(key, n):
                out = []
                for _ in range(n):
                    out.append(jax.random.normal(key, (3,)))
                return out
        """
        assert codes(src, "APX001") == ["APX001"]

    def test_positive_comprehension_reuse(self):
        src = """
            import jax
            def sample(key):
                return [jax.random.normal(key, (3,)) for _ in range(4)]
        """
        assert codes(src, "APX001") == ["APX001"]

    def test_negative_split_between(self):
        src = """
            import jax
            def sample(key):
                a = jax.random.normal(key, (3,))
                key, sub = jax.random.split(key)
                b = jax.random.uniform(key, (3,))
                c = {k: jax.random.normal(k, (2,))
                     for k in jax.random.split(sub, 3)}
                return a + b, c
        """
        assert codes(src, "APX001") == []

    def test_negative_fold_in_loop(self):
        src = """
            import jax
            def sample(key, n):
                out = []
                for i in range(n):
                    k = jax.random.fold_in(key, i)
                    out.append(jax.random.normal(k, (3,)))
                return out
        """
        assert codes(src, "APX001") == []

    def test_import_alias_resolved(self):
        src = """
            from jax import random as jr
            def sample(key):
                return jr.normal(key, (3,)) + jr.uniform(key, (3,))
        """
        assert codes(src, "APX001") == ["APX001"]


class TestAPX002Concretization:
    def test_positive_float_and_if(self):
        src = """
            import jax
            @jax.jit
            def f(x):
                if x > 0:
                    return float(x)
                return x
        """
        got = codes(src, "APX002")
        assert got == ["APX002", "APX002"]

    def test_positive_call_form_jit(self):
        src = """
            import jax
            def f(x):
                return x.item()
            g = jax.jit(f)
        """
        assert codes(src, "APX002") == ["APX002"]

    def test_negative_static_and_shape_reads(self):
        src = """
            import jax
            from functools import partial
            @partial(jax.jit, static_argnames=("n",))
            def f(x, n):
                if n > 2:               # static: fine
                    pass
                if x is not None:       # structure check: fine
                    pass
                if x.ndim == 2:         # shape read: fine
                    pass
                m = int(x.shape[0])     # static shape: fine
                return x * m
        """
        assert codes(src, "APX002") == []


class TestAPX003HostSync:
    def test_positive_step_body(self):
        src = """
            import jax
            def train_step(state, batch):
                loss = state + batch
                jax.device_get(loss)
                return loss
        """
        assert codes(src, "APX003") == ["APX003"]

    def test_positive_block_until_ready(self):
        src = """
            import jax
            def _step(x):
                x.block_until_ready()
                return x
        """
        assert codes(src, "APX003") == ["APX003"]

    def test_negative_poll_helper_and_tests(self):
        src = """
            import jax
            def poll_metrics(pending):
                return jax.device_get(pending)   # off the hot loop: fine
            def test_step_values(x):
                return jax.device_get(x)         # test body: fine
        """
        assert codes(src, "APX003") == []


class TestAPX004Recompile:
    def test_positive_mutable_default_and_shape(self):
        src = """
            import jax
            @jax.jit
            def f(x, opts={}, shape=None):
                return x
        """
        got = codes(src, "APX004")
        assert got == ["APX004", "APX004"]

    def test_negative_static_shape(self):
        src = """
            import jax
            from functools import partial
            @partial(jax.jit, static_argnames=("shape",))
            def f(x, shape=None, opts=()):
                return x
        """
        assert codes(src, "APX004") == []


class TestAPX005Collectives:
    def test_positive_unbound_axis(self):
        src = """
            from jax import lax
            def f(x):
                return lax.psum(x, "tp")
        """
        assert codes(src, "APX005") == ["APX005"]

    def test_negative_bound_by_spec_or_mesh(self):
        src = """
            from jax import lax
            from jax.sharding import Mesh, PartitionSpec
            def make(devs):
                return Mesh(devs, ("data",))
            SPEC = PartitionSpec("tp")
            def f(x, axis):
                return lax.psum(x, "tp") + lax.pmean(x, "data") \\
                    + lax.psum(x, axis)   # variable axis: resolved elsewhere
        """
        assert codes(src, "APX005") == []


class TestAPX006Dtype:
    def test_positive_chained_roundtrip(self):
        src = """
            import jax.numpy as jnp
            def f(x):
                return x.astype(jnp.float32).astype(jnp.bfloat16)
        """
        assert codes(src, "APX006") == ["APX006"]

    def test_positive_fp32_in_bf16_function(self):
        src = """
            import jax.numpy as jnp
            def f(x):
                h = x.astype(jnp.bfloat16)
                acc = jnp.zeros((4,), dtype=jnp.float32)
                return h, acc
        """
        assert codes(src, "APX006") == ["APX006"]

    def test_negative_single_policy(self):
        src = """
            import jax.numpy as jnp
            def f(x):
                return x.astype(jnp.bfloat16)
            def g(x):
                return jnp.zeros((4,), dtype=jnp.float32)
        """
        assert codes(src, "APX006") == []


class TestAPX007PallasScan:
    def test_positive_interpret_in_scan_body(self):
        src = """
            from jax import lax
            from jax.experimental import pallas as pl
            def body(c, x):
                y = pl.pallas_call(lambda r: None, interpret=True)(x)
                return c, y
            def run(xs):
                return lax.scan(body, 0, xs)
        """
        assert codes(src, "APX007") == ["APX007"]

    def test_positive_one_call_hop(self):
        src = """
            from jax import lax
            from jax.experimental import pallas as pl
            def kernel(x, interpret):
                return pl.pallas_call(lambda r: None,
                                      interpret=interpret)(x)
            def body(c, x):
                return c, kernel(x, True)
            def run(xs):
                return lax.scan(body, 0, xs)
        """
        assert codes(src, "APX007") == ["APX007"]

    def test_negative_interpret_false_or_no_scan(self):
        src = """
            from jax import lax
            from jax.experimental import pallas as pl
            def body(c, x):
                y = pl.pallas_call(lambda r: None, interpret=False)(x)
                return c, y
            def run(xs):
                return lax.scan(body, 0, xs)
            def standalone(x):
                return pl.pallas_call(lambda r: None, interpret=True)(x)
        """
        assert codes(src, "APX007") == []


class TestAPX008MutableState:
    def test_positive_store_and_method(self):
        src = """
            import jax
            _CACHE = {}
            _LOG = []
            @jax.jit
            def f(x):
                _CACHE["last"] = x
                _LOG.append(1)
                return x
        """
        got = codes(src, "APX008")
        assert got == ["APX008", "APX008"]

    def test_negative_outside_jit_or_immutable(self):
        src = """
            import jax
            _CACHE = {}
            _LIMIT = 3
            def warm(x):
                _CACHE["x"] = x     # host-side registry: fine
                return x
            @jax.jit
            def f(x):
                return x * _LIMIT   # read-only: fine
        """
        assert codes(src, "APX008") == []


# ---------------------------------------------------------------------------
# suppression, baseline, config, CLI
# ---------------------------------------------------------------------------

REUSE_SRC = """
import jax
def sample(key):
    a = jax.random.normal(key, (3,))
    b = jax.random.uniform(key, (3,))%s
    return a + b
"""


class TestSuppression:
    def test_noqa_specific_code(self):
        assert codes(REUSE_SRC % "  # noqa: APX001") == []

    def test_noqa_bare(self):
        assert codes(REUSE_SRC % "  # noqa") == []

    def test_noqa_other_code_does_not_suppress(self):
        assert codes(REUSE_SRC % "  # noqa: APX005") == ["APX001"]

    def test_noqa_multiple_codes(self):
        assert codes(REUSE_SRC % "  # noqa: APX005, APX001") == []


class TestBaseline:
    def _findings(self):
        from apex_tpu.analysis.engine import analyze_source
        return analyze_source(REUSE_SRC % "", "pkg/mod.py")

    def test_partition_matches_and_news(self):
        found = self._findings()
        bl = Baseline([{"path": "pkg/mod.py", "code": "APX001",
                        "snippet": found[0].snippet,
                        "justification": "known"}])
        new, matched, stale = bl.partition(found)
        assert new == [] and len(matched) == 1 and stale == []

    def test_unmatched_finding_is_new(self):
        found = self._findings()
        bl = Baseline([{"path": "other.py", "code": "APX001",
                        "snippet": found[0].snippet,
                        "justification": "known"}])
        new, matched, stale = bl.partition(found)
        assert len(new) == 1 and matched == [] and len(stale) == 1

    def test_snippet_keying_survives_line_drift(self):
        found = self._findings()
        bl = Baseline([{"path": "pkg/mod.py", "code": "APX001",
                        "line": 9999,  # wrong line: snippet still matches
                        "snippet": found[0].snippet,
                        "justification": "known"}])
        new, _, _ = bl.partition(found)
        assert new == []

    def test_roundtrip_save_load(self, tmp_path):
        found = self._findings()
        bl = Baseline.from_findings(found)
        p = tmp_path / "bl.json"
        bl.save(str(p))
        loaded = Baseline.load(str(p))
        # fresh from --write-baseline: placeholder justification, so the
        # entry does NOT yet suppress (the gate stays red until edited)
        new, _, _ = loaded.partition(found)
        assert len(new) == 1
        assert loaded.unjustified_entries() == loaded.entries
        for e in loaded.entries:    # ...the human step
            e["justification"] = "deliberate in this fixture"
        loaded.save(str(p))
        loaded = Baseline.load(str(p))
        new, matched, stale = loaded.partition(found)
        assert new == [] and len(matched) == 1 and stale == []
        assert all("justification" in e for e in loaded.entries)


class TestConfigAndCLI:
    def _project(self, tmp_path, extra=""):
        (tmp_path / "pyproject.toml").write_text(textwrap.dedent(f"""
            [project]
            name = "demo"

            [tool.apex_tpu.analysis]
            paths = ["pkg"]
            baseline = "bl.json"
            exclude = ["skipme"]
            {extra}
        """))
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "mod.py").write_text(REUSE_SRC % "")
        (pkg / "skipme.py").write_text(REUSE_SRC % "")
        return tmp_path

    def test_load_config_walks_up(self, tmp_path):
        root = self._project(tmp_path)
        cfg = load_config(str(root / "pkg" / "mod.py"))
        assert cfg.paths == ["pkg"]
        assert cfg.baseline == "bl.json"
        assert cfg.exclude == ["skipme"]
        assert cfg.root == str(root)

    def test_cli_reports_and_exits_nonzero(self, tmp_path, capsys):
        root = self._project(tmp_path)
        rc = cli_main([str(root / "pkg")])
        out = capsys.readouterr().out
        assert rc == 1
        assert "APX001" in out and "skipme" not in out

    @staticmethod
    def _justify(root):
        """The human step after ``--write-baseline``: replace the
        placeholder justifications with a real reason."""
        p = root / "bl.json"
        data = json.loads(p.read_text())
        for e in data["entries"]:
            e["justification"] = "deliberate in this fixture"
        p.write_text(json.dumps(data))

    def test_cli_write_baseline_then_clean(self, tmp_path, capsys):
        root = self._project(tmp_path)
        rc = cli_main([str(root / "pkg"), "--write-baseline"])
        assert rc == 0
        assert json.loads((root / "bl.json").read_text())["entries"]
        # placeholder justifications do not suppress: still red, with
        # the unjustified entry called out on stderr
        rc = cli_main([str(root / "pkg")])
        captured = capsys.readouterr()
        assert rc == 1
        assert "justification" in captured.err
        self._justify(root)
        rc = cli_main([str(root / "pkg")])
        out = capsys.readouterr().out
        assert rc == 0
        assert "1 baselined" in out

    def test_cli_stale_entry_reported(self, tmp_path, capsys):
        root = self._project(tmp_path)
        cli_main([str(root / "pkg"), "--write-baseline"])
        self._justify(root)
        (root / "pkg" / "mod.py").write_text("x = 1\n")
        rc = cli_main([str(root / "pkg")])
        err = capsys.readouterr().err
        assert rc == 0
        assert "stale" in err

    def test_cli_select_disable(self, tmp_path, capsys):
        root = self._project(tmp_path)
        assert cli_main([str(root / "pkg"), "--disable", "APX001"]) == 0
        assert cli_main([str(root / "pkg"), "--select", "APX005"]) == 0
        assert cli_main([str(root / "pkg"), "--select", "APX001"]) == 1
        capsys.readouterr()

    def test_syntax_error_is_finding_not_crash(self, tmp_path, capsys):
        root = self._project(tmp_path)
        (root / "pkg" / "broken.py").write_text("def f(:\n")
        rc = cli_main([str(root / "pkg")])
        out = capsys.readouterr().out
        assert rc == 1 and "APX000" in out


# ---------------------------------------------------------------------------
# log_event ordering stamps (satellite: seq + monotonic ts)
# ---------------------------------------------------------------------------

class TestLogEventStamps:
    def test_seq_and_ts_present_and_monotonic(self):
        from apex_tpu.utils.logging import get_logger, log_event
        log = get_logger("apex_tpu.test_stamps")
        log.setLevel(logging.CRITICAL)  # keep output quiet
        lines = [log_event(log, "retrace", fn="step", call=i)
                 for i in range(3)]
        seqs, tss = [], []
        for line in lines:
            fields = dict(kv.split("=", 1) for kv in line.split()
                          if "=" in kv)
            assert fields["event"] == "retrace"
            seqs.append(int(fields["seq"]))
            tss.append(float(fields["ts"]))
        assert seqs == sorted(seqs) and len(set(seqs)) == 3
        assert tss == sorted(tss)

    def test_wall_stamp_is_epoch_time(self):
        # wall= (time.time()) rides next to the monotonic ts= so events
        # from different processes/hosts can be correlated; ts stays the
        # rate-measurement stamp (immune to clock steps)
        import time as _time

        from apex_tpu.utils.logging import get_logger, log_event
        log = get_logger("apex_tpu.test_stamps")
        log.setLevel(logging.CRITICAL)
        before = _time.time()
        line = log_event(log, "retrace", fn="step", call=0)
        after = _time.time()
        fields = dict(kv.split("=", 1) for kv in line.split() if "=" in kv)
        assert before <= float(fields["wall"]) <= after
        assert "ts" in fields  # monotonic stamp kept alongside


# ---------------------------------------------------------------------------
# retrace watchdog
# ---------------------------------------------------------------------------

class TestRetraceWatchdog:
    def test_stable_shapes_stay_silent(self):
        f = jax.jit(lambda x: x * 2)
        wd = RetraceWatchdog(f, budget=0)
        for _ in range(5):
            wd(jnp.ones((4,)))
        assert wd.retraces == 0 and wd.compiles == 1 and wd.calls == 5

    def test_budget_fires_on_forced_recompiles(self):
        f = jax.jit(lambda x: x * 2)
        wd = RetraceWatchdog(f, budget=2)
        with pytest.raises(RetraceBudgetExceeded) as exc:
            for n in range(2, 10):
                wd(jnp.ones((n,)))  # every call a new shape = a retrace
        assert exc.value.retraces == 3 and exc.value.budget == 2

    def test_log_only_when_budget_none(self):
        f = jax.jit(lambda x: x + 1)
        wd = RetraceWatchdog(f, budget=None)
        for n in range(2, 8):
            wd(jnp.ones((n,)))
        assert wd.retraces == 5  # counted, never raised

    def test_prewarmed_cache_is_baselined(self):
        f = jax.jit(lambda x: x - 1)
        f(jnp.ones((3,)))  # compile before the watchdog watches
        wd = RetraceWatchdog(f, budget=0)
        wd(jnp.ones((3,)))
        assert wd.compiles == 0 and wd.retraces == 0

    def test_signature_fallback_for_plain_callables(self):
        calls = []

        def plain(x):
            calls.append(x.shape)
            return x

        wd = RetraceWatchdog(plain, budget=2)
        wd(jnp.ones((2,)))
        wd(jnp.ones((2,)))
        assert wd.compiles == 1  # same signature, one "trace"
        with pytest.raises(RetraceBudgetExceeded):
            for n in range(3, 10):
                wd(jnp.ones((n,)))

    def test_dtype_change_counts_as_retrace(self):
        f = jax.jit(lambda x: x * 1)
        wd = RetraceWatchdog(f, budget=None)
        wd(jnp.ones((4,), jnp.float32))
        wd(jnp.ones((4,), jnp.bfloat16))
        assert wd.retraces == 1


class TestRunTrainingRetraceIntegration:
    def _step(self):
        @jax.jit
        def step(state, batch, rng):
            new = {"params": state["params"] - 0.1 * batch.mean(),
                   "step": state["step"] + 1}
            return new, {"loss": batch.mean(), "skipped": jnp.asarray(False)}
        return step

    def test_ragged_batches_trip_budget(self):
        from apex_tpu.resilience import ResilienceConfig, run_training
        state = {"params": jnp.zeros(()), "step": jnp.asarray(0, jnp.int32)}
        cfg = ResilienceConfig(retrace_budget=2, handle_sigterm=False,
                               poll_interval_steps=100)
        with pytest.raises(RetraceBudgetExceeded):
            # a ragged data pipeline: every step a new batch shape
            run_training(self._step(), state,
                         lambda step: jnp.ones((step + 2,)),
                         num_steps=10, config=cfg)

    def test_stable_run_reports_zero_retraces(self):
        from apex_tpu.resilience import ResilienceConfig, run_training
        state = {"params": jnp.zeros(()), "step": jnp.asarray(0, jnp.int32)}
        cfg = ResilienceConfig(retrace_budget=2, handle_sigterm=False,
                               poll_interval_steps=4)
        res = run_training(self._step(), state,
                           lambda step: jnp.ones((8,)),
                           num_steps=6, config=cfg)
        assert res.status == "completed"
        assert res.telemetry["retraces"] == 0

    def test_watchdog_disabled_with_none(self):
        from apex_tpu.resilience import ResilienceConfig, run_training
        state = {"params": jnp.zeros(()), "step": jnp.asarray(0, jnp.int32)}
        cfg = ResilienceConfig(retrace_budget=None, handle_sigterm=False,
                               poll_interval_steps=4)
        res = run_training(self._step(), state,
                           lambda step: jnp.ones((step + 2,)),
                           num_steps=5, config=cfg)
        assert res.status == "completed"  # slow, but allowed when opted out
