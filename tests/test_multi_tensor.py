"""multi_tensor_apply machinery tests (analog of the amp multi-tensor kernel
tests, ``tests/L0/run_amp/test_multi_tensor_scale.py``)."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.multi_tensor_apply import (
    flatten_by_dtype,
    unflatten_by_dtype,
    multi_tensor_applier,
)


def test_flatten_roundtrip_mixed_dtypes():
    tree = {
        "a": jnp.ones((3, 5), jnp.float32),
        "b": jnp.full((7,), 2.0, jnp.bfloat16),
        "c": {"d": jnp.arange(6, dtype=jnp.float32).reshape(2, 3)},
    }
    buffers, metas, aux = flatten_by_dtype(tree)
    assert set(buffers) == {"float32", "bfloat16"}
    for k, buf in buffers.items():
        assert buf.shape[0] % 1024 == 0
    back = unflatten_by_dtype(buffers, metas, aux)
    for a, b in zip(jax.tree_util.tree_leaves(tree), jax.tree_util.tree_leaves(back)):
        np.testing.assert_allclose(np.asarray(a, np.float32), np.asarray(b, np.float32))
        assert a.dtype == b.dtype and a.shape == b.shape


def test_multi_tensor_scale():
    tensors = [jnp.ones((4, 4)), jnp.full((10,), 2.0), jnp.ones((3, 3, 3))]

    def scale_op(flat, scale):
        return flat * scale

    (out,) = multi_tensor_applier(scale_op, [tensors], 0.5)
    np.testing.assert_allclose(out[0], 0.5)
    np.testing.assert_allclose(out[1], 1.0)
    assert out[2].shape == (3, 3, 3)


def test_multi_tensor_axpby():
    xs = [jnp.ones((5,)), jnp.full((3, 2), 2.0)]
    ys = [jnp.full((5,), 10.0), jnp.full((3, 2), 20.0)]

    def axpby(fx, fy, a, b):
        return a * fx + b * fy

    (out,) = multi_tensor_applier(axpby, [xs, ys], 2.0, 0.5)
    np.testing.assert_allclose(out[0], 2.0 + 5.0)
    np.testing.assert_allclose(out[1], 4.0 + 10.0)
