"""Tensor-parallel layer/mapping tests.

Mirrors the reference suite ``tests/L0/run_transformer/`` (``test_layers.py``,
``test_mapping.py``, ``test_cross_entropy.py``, ``test_random.py``,
``test_data.py``): sharded results computed under ``shard_map`` on the 8-way
virtual CPU mesh must match a single-rank reference computed from the same
global parameters.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    vocab_parallel_cross_entropy,
    broadcast_data,
    divide,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    copy_to_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
    get_rng_tracker,
    model_parallel_rng_key,
)
from apex_tpu.utils.sharding import shard_map

TENSOR = parallel_state.TENSOR_AXIS


@pytest.fixture
def tp8_mesh():
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(tensor_model_parallel_size=8)
    yield mesh
    parallel_state.destroy_model_parallel()


def shmap(fn, mesh, in_specs, out_specs):
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         check_vma=False)


# ---------------------------------------------------------------------------
# mappings (reference tests/L0/run_transformer/test_mapping.py)
# ---------------------------------------------------------------------------

class TestMappings:
    def test_copy_identity_fwd_psum_bwd(self, tp8_mesh):
        # Per-rank autodiff (the canonical torch-style usage, grad computed
        # *inside* shard_map): each rank scales the copied activation by
        # (rank+1); the copy region's backward all-reduce must therefore give
        # every rank grad sum(1..8) = 36.
        x = jnp.ones((4,))

        def per_rank(v):
            scale = jax.lax.axis_index(TENSOR).astype(jnp.float32) + 1.0
            return jax.grad(
                lambda u: (copy_to_tensor_model_parallel_region(u) * scale).sum()
            )(v)

        g = shmap(per_rank, tp8_mesh, P(), P())(x)
        np.testing.assert_allclose(g, 36.0 * np.ones(4))

    def test_scatter_gather_roundtrip(self, tp8_mesh):
        x = jnp.arange(32.0).reshape(4, 8)

        def f(v):
            # v arrives replicated [4, 8]; scatter keeps the local last-dim
            # chunk [4, 1]; gather restores [4, 8]
            s = scatter_to_tensor_model_parallel_region(v)
            assert s.shape == (4, 1)
            return gather_from_tensor_model_parallel_region(s)

        out = shmap(f, tp8_mesh, P(), P())(x)
        np.testing.assert_allclose(out, x)

    def test_reduce(self, tp8_mesh):
        x = jnp.ones((8, 4))

        def f(v):
            return reduce_from_tensor_model_parallel_region(v)

        out = shmap(f, tp8_mesh, P(TENSOR, None), P(TENSOR, None))(x)
        np.testing.assert_allclose(out, 8 * np.ones((8, 4)))

    def test_sequence_parallel_roundtrip(self, tp8_mesh):
        x = jnp.arange(16.0).reshape(16, 1)

        def f(v):
            s = scatter_to_sequence_parallel_region(v)
            assert s.shape == (2, 1)
            return gather_from_sequence_parallel_region(s, False)

        out = shmap(f, tp8_mesh, P(), P())(x)
        np.testing.assert_allclose(out, x)

    def test_reduce_scatter_then_gather_is_psum(self, tp8_mesh):
        x = jnp.ones((16, 2))

        def f(v):
            rs = reduce_scatter_to_sequence_parallel_region(v)
            assert rs.shape == (2, 2)
            return gather_from_sequence_parallel_region(rs, False)

        out = shmap(f, tp8_mesh, P(), P())(x)
        np.testing.assert_allclose(out, 8 * np.ones((16, 2)))

    def test_unsharded_identity(self):
        # outside shard_map every region is the identity (world size 1)
        x = jnp.arange(6.0).reshape(2, 3)
        for fn in (copy_to_tensor_model_parallel_region,
                   reduce_from_tensor_model_parallel_region,
                   scatter_to_tensor_model_parallel_region,
                   gather_from_tensor_model_parallel_region,
                   scatter_to_sequence_parallel_region,
                   reduce_scatter_to_sequence_parallel_region):
            np.testing.assert_allclose(fn(x), x)


# ---------------------------------------------------------------------------
# layers (reference tests/L0/run_transformer/test_layers.py)
# ---------------------------------------------------------------------------

class TestColumnParallelLinear:
    def test_matches_unsharded(self, tp8_mesh):
        layer = ColumnParallelLinear(16, 32, gather_output=True)
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))

        ref = layer.apply(params, x)  # unsharded path
        out = shmap(layer.apply, tp8_mesh,
                    (layer.spec(), P()), P())(params, x)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_grads_match_unsharded(self, tp8_mesh):
        # Canonical usage: per-rank autodiff *inside* shard_map (torch-style),
        # param grads exit through the same sharded specs as the params.
        layer = ColumnParallelLinear(16, 32, gather_output=True)
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))

        def per_rank(p, v):
            return jax.grad(lambda pp: (layer.apply(pp, v) ** 2).sum())(p)

        g_ref = jax.grad(lambda p: (layer.apply(p, x) ** 2).sum())(params)
        g_sh = shmap(per_rank, tp8_mesh,
                     (layer.spec(), P()), layer.spec())(params, x)
        for k in g_ref:
            np.testing.assert_allclose(g_sh[k], g_ref[k], rtol=1e-4, atol=1e-5)

    def test_no_gather_output_shape(self, tp8_mesh):
        layer = ColumnParallelLinear(16, 32, gather_output=False)
        params = layer.init(jax.random.PRNGKey(0))
        x = jnp.ones((4, 16))
        out = shmap(layer.apply, tp8_mesh,
                    (layer.spec(), P()), P(None, TENSOR))(params, x)
        ref = layer.apply(params, x)
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_skip_bias_add(self):
        layer = ColumnParallelLinear(8, 8, skip_bias_add=True)
        params = layer.init(jax.random.PRNGKey(0))
        out, bias = layer.apply(params, jnp.ones((2, 8)))
        assert out.shape == (2, 8) and bias.shape == (8,)

    def test_sp_incompatible_with_gather(self):
        with pytest.raises(ValueError):
            ColumnParallelLinear(8, 8, gather_output=True,
                                 sequence_parallel_enabled=True)


class TestRowParallelLinear:
    def test_matches_unsharded(self, tp8_mesh):
        layer = RowParallelLinear(32, 16, input_is_parallel=False)
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 32))

        ref = layer.apply(params, x)
        out = shmap(layer.apply, tp8_mesh,
                    (layer.spec(), P()), P())(params, x)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_grads_match_unsharded(self, tp8_mesh):
        layer = RowParallelLinear(32, 16, input_is_parallel=False)
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 32))

        def per_rank(p, v):
            return jax.grad(lambda pp: (layer.apply(pp, v) ** 2).sum())(p)

        g_ref = jax.grad(lambda p: (layer.apply(p, x) ** 2).sum())(params)
        g_sh = shmap(per_rank, tp8_mesh,
                     (layer.spec(), P()), layer.spec())(params, x)
        for k in g_ref:
            np.testing.assert_allclose(g_sh[k], g_ref[k], rtol=1e-4, atol=1e-5)


class TestColumnRowSequenceParallel:
    """Megatron SP: sequence-sharded activations through Column→Row pair
    (reference layers.py:310-325,797 + test_layers.py SP cases)."""

    def test_column_row_pair_sp(self, tp8_mesh):
        col = ColumnParallelLinear(16, 64, gather_output=False,
                                   sequence_parallel_enabled=True)
        row = RowParallelLinear(64, 16, input_is_parallel=True,
                                sequence_parallel_enabled=True)
        cp = col.init(jax.random.PRNGKey(0))
        rp = row.init(jax.random.PRNGKey(1))
        # [s, b, h] with s sharded over tensor axis
        x = jax.random.normal(jax.random.PRNGKey(2), (16, 2, 16))

        def fwd(cparams, rparams, v):
            h = col.apply(cparams, v)
            return row.apply(rparams, h)

        out = shmap(fwd, tp8_mesh,
                    (col.spec(), row.spec(), P(TENSOR)), P(TENSOR))(cp, rp, x)

        # reference: same math without sharding
        col_ref = ColumnParallelLinear(16, 64, gather_output=False)
        row_ref = RowParallelLinear(64, 16, input_is_parallel=True)
        ref = row_ref.apply(rp, col_ref.apply(cp, x))
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_sp_grads_match(self, tp8_mesh):
        col = ColumnParallelLinear(8, 32, gather_output=False,
                                   sequence_parallel_enabled=True)
        row = RowParallelLinear(32, 8, input_is_parallel=True,
                                sequence_parallel_enabled=True)
        cp = col.init(jax.random.PRNGKey(0))
        rp = row.init(jax.random.PRNGKey(1))
        x = jax.random.normal(jax.random.PRNGKey(2), (8, 2, 8))

        # Canonical usage (see mappings.py docstring): per-rank autodiff
        # *inside* shard_map — the global loss is the sum of per-rank local
        # losses over the sequence shards, and the region backwards
        # (all-gather / psum) assemble full grads on every rank.
        def per_rank(cparams, rparams, v):
            def local_loss(c, r):
                return (row.apply(r, col.apply(c, v)) ** 2).sum()
            return jax.grad(local_loss, argnums=(0, 1))(cparams, rparams)

        g_sh = shmap(per_rank, tp8_mesh,
                     (col.spec(), row.spec(), P(TENSOR)),
                     (col.spec(), row.spec()))(cp, rp, x)

        col_ref = ColumnParallelLinear(8, 32, gather_output=False)
        row_ref = RowParallelLinear(32, 8, input_is_parallel=True)

        def loss_ref(cparams, rparams):
            return (row_ref.apply(rparams, col_ref.apply(cparams, x)) ** 2).sum()

        g_ref = jax.grad(loss_ref, argnums=(0, 1))(cp, rp)
        for a, b in zip(jax.tree.leaves(g_sh), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


class TestVocabParallelEmbedding:
    def test_matches_take(self, tp8_mesh):
        emb = VocabParallelEmbedding(64, 16)
        params = emb.init(jax.random.PRNGKey(0))
        ids = jax.random.randint(jax.random.PRNGKey(1), (4, 12), 0, 64)

        ref = jnp.take(params["weight"], ids, axis=0)
        out = shmap(emb.apply, tp8_mesh,
                    (emb.spec(), P()), P())(params, ids)
        np.testing.assert_allclose(out, ref, rtol=1e-6)

    def test_grad_matches(self, tp8_mesh):
        emb = VocabParallelEmbedding(64, 16)
        params = emb.init(jax.random.PRNGKey(0))
        ids = jax.random.randint(jax.random.PRNGKey(1), (4, 12), 0, 64)

        def per_rank(p, t):
            return jax.grad(lambda pp: emb.apply(pp, t).sum())(p)

        g_ref = jax.grad(lambda p: jnp.take(p["weight"], ids, axis=0).sum())(params)
        g_sh = shmap(per_rank, tp8_mesh,
                     (emb.spec(), P()), emb.spec())(params, ids)
        np.testing.assert_allclose(g_sh["weight"], g_ref["weight"], rtol=1e-6)


# ---------------------------------------------------------------------------
# cross entropy (reference tests/L0/run_transformer/test_cross_entropy.py)
# ---------------------------------------------------------------------------

class TestVocabParallelCrossEntropy:
    def _ref_ce(self, logits, target, smoothing=0.0):
        V = logits.shape[-1]
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, target[..., None], axis=-1)[..., 0]
        if smoothing > 0:
            s = smoothing * V / (V - 1)
            return (1 - s) * nll - s * logp.mean(axis=-1)
        return nll

    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_matches_full_softmax(self, tp8_mesh, smoothing):
        logits = jax.random.normal(jax.random.PRNGKey(0), (4, 6, 64))
        target = jax.random.randint(jax.random.PRNGKey(1), (4, 6), 0, 64)

        ref = self._ref_ce(logits, target, smoothing)
        out = shmap(
            lambda l, t: vocab_parallel_cross_entropy(l, t, smoothing),
            tp8_mesh, (P(None, None, TENSOR), P()), P())(logits, target)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_grads_match(self, tp8_mesh, smoothing):
        logits = jax.random.normal(jax.random.PRNGKey(0), (4, 6, 64))
        target = jax.random.randint(jax.random.PRNGKey(1), (4, 6), 0, 64)

        def per_rank(l, t):
            return jax.grad(lambda ll: vocab_parallel_cross_entropy(
                ll, t, smoothing).sum())(l)

        g_ref = jax.grad(
            lambda l: self._ref_ce(l, target, smoothing).sum())(logits)
        g_sh = shmap(per_rank, tp8_mesh,
                     (P(None, None, TENSOR), P()),
                     P(None, None, TENSOR))(logits, target)
        np.testing.assert_allclose(g_sh, g_ref, rtol=1e-4, atol=1e-6)

    def test_unsharded_path(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (4, 32))
        target = jax.random.randint(jax.random.PRNGKey(1), (4,), 0, 32)
        out = vocab_parallel_cross_entropy(logits, target)
        np.testing.assert_allclose(out, self._ref_ce(logits, target), rtol=1e-5)


# ---------------------------------------------------------------------------
# random / data / utils
# ---------------------------------------------------------------------------

class TestRandom:
    def test_model_parallel_keys_distinct_per_rank(self, tp8_mesh):
        key = jax.random.PRNGKey(0)

        def draw(k):
            k = model_parallel_rng_key(k)
            return jax.random.normal(k, (1, 4))

        out = shmap(draw, tp8_mesh, P(), P(TENSOR))(key)
        # 8 ranks → 8 distinct rows
        assert len({tuple(np.asarray(r)) for r in out}) == 8

    def test_default_region_identical_across_ranks(self, tp8_mesh):
        key = jax.random.PRNGKey(0)

        def draw(k):
            return jax.random.normal(k, (1, 4))

        out = shmap(draw, tp8_mesh, P(), P(TENSOR))(key)
        assert len({tuple(np.asarray(r)) for r in out}) == 1

    def test_tracker_fork_advances(self):
        tracker = get_rng_tracker()
        tracker.reset()
        with tracker.fork() as k1:
            pass
        with tracker.fork() as k2:
            pass
        assert not np.array_equal(np.asarray(k1), np.asarray(k2))


class TestDataUtils:
    def test_divide(self):
        assert divide(8, 2) == 4
        with pytest.raises(ValueError):
            divide(7, 2)

    def test_broadcast_data(self, tp8_mesh):
        data = {"text": jnp.ones((4, 8), jnp.int32),
                "types": jnp.zeros((4, 8), jnp.int32)}
        out = broadcast_data(["text", "types"], data, jnp.int32)
        np.testing.assert_array_equal(out["text"], data["text"])
        with pytest.raises(ValueError):
            broadcast_data(["text"], {"text": jnp.ones((2,), jnp.float32)}, jnp.int32)


class TestZLoss:
    """z-loss logit regularization on the vocab-parallel CE (PaLM-style,
    exceeds the reference): loss += z * log(Z)^2, grads via the custom vjp
    must match autodiff through an explicit reference."""

    def _ref(self, logits, target, z):
        lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
        nll = lse - jnp.take_along_axis(
            logits.astype(jnp.float32), target[..., None], -1)[..., 0]
        return nll + z * lse * lse

    def test_forward_matches_reference(self):
        from apex_tpu.transformer.tensor_parallel import (
            vocab_parallel_cross_entropy,
        )

        logits = jax.random.normal(jax.random.PRNGKey(0), (4, 3, 32))
        target = jax.random.randint(jax.random.PRNGKey(1), (4, 3), 0, 32)
        out = vocab_parallel_cross_entropy(logits, target, z_loss=1e-2)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(self._ref(logits, target, 1e-2)),
                                   rtol=1e-5, atol=1e-5)

    def test_grads_match_autodiff_reference(self):
        from apex_tpu.transformer.tensor_parallel import (
            vocab_parallel_cross_entropy,
        )

        logits = jax.random.normal(jax.random.PRNGKey(2), (2, 5, 16))
        target = jax.random.randint(jax.random.PRNGKey(3), (2, 5), 0, 16)
        g = jax.grad(lambda l: jnp.sum(vocab_parallel_cross_entropy(
            l, target, z_loss=1e-2)))(logits)
        gr = jax.grad(lambda l: jnp.sum(self._ref(l, target, 1e-2)))(logits)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                                   rtol=1e-5, atol=1e-5)

    def test_zero_coef_is_plain_ce(self):
        from apex_tpu.transformer.tensor_parallel import (
            vocab_parallel_cross_entropy,
        )

        logits = jax.random.normal(jax.random.PRNGKey(4), (3, 4, 8))
        target = jax.random.randint(jax.random.PRNGKey(5), (3, 4), 0, 8)
        a = vocab_parallel_cross_entropy(logits, target)
        b = vocab_parallel_cross_entropy(logits, target, z_loss=0.0)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    def test_with_label_smoothing_grads_consistent(self):
        """Regression: z-loss must be added AFTER the smoothing rescale so
        the custom vjp matches autodiff of the returned value."""
        from apex_tpu.transformer.tensor_parallel import (
            vocab_parallel_cross_entropy,
        )

        logits = jax.random.normal(jax.random.PRNGKey(6), (2, 4, 16))
        target = jax.random.randint(jax.random.PRNGKey(7), (2, 4), 0, 16)

        def ref(l):
            l32 = l.astype(jnp.float32)
            lse = jax.nn.logsumexp(l32, axis=-1)
            nll = lse - jnp.take_along_axis(l32, target[..., None],
                                            -1)[..., 0]
            sp = 0.1 * 16 / 15
            mean_lp = jnp.mean(l32 - lse[..., None], axis=-1)
            sm = (1.0 - sp) * nll - sp * mean_lp
            return jnp.sum(sm + 1e-3 * lse * lse)

        val = jnp.sum(vocab_parallel_cross_entropy(
            logits, target, 0.1, z_loss=1e-3))
        np.testing.assert_allclose(float(val), float(ref(logits)),
                                   rtol=1e-5)
        g = jax.grad(lambda l: jnp.sum(vocab_parallel_cross_entropy(
            l, target, 0.1, z_loss=1e-3)))(logits)
        gr = jax.grad(ref)(logits)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                                   rtol=1e-4, atol=1e-5)

    def test_sharded_matches_unsharded(self, mesh8):
        """z-loss under a bound tensor axis: logZ must use the psum'd
        denominator + pmax'd max — sharded loss/grads == unsharded."""
        from apex_tpu.transformer.tensor_parallel import (
            vocab_parallel_cross_entropy,
        )

        logits = jax.random.normal(jax.random.PRNGKey(8), (3, 4, 32))
        target = jax.random.randint(jax.random.PRNGKey(9), (3, 4), 0, 32)
        ref_loss = vocab_parallel_cross_entropy(logits, target, z_loss=1e-2)
        ref_grad = jax.grad(lambda l: jnp.sum(
            vocab_parallel_cross_entropy(l, target, z_loss=1e-2)))(logits)

        def body(l, t):
            loss = vocab_parallel_cross_entropy(l, t, z_loss=1e-2)
            grad = jax.grad(lambda ll: jnp.sum(
                vocab_parallel_cross_entropy(ll, t, z_loss=1e-2)))(l)
            return loss, grad

        loss, grad = jax.jit(shard_map(
            body, mesh=mesh8,
            in_specs=(P(None, None, "tensor"), P()),
            out_specs=(P(), P(None, None, "tensor")),
            check_vma=False))(logits, target)
        np.testing.assert_allclose(np.asarray(loss), np.asarray(ref_loss),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad),
                                   rtol=1e-5, atol=1e-5)
