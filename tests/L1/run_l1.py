"""L1 integration runner: ResNet-50 amp opt-level convergence at depth.

The reference's L1 tier trains full ResNet-50 sweeps of opt-level x
loss-scale x keep-batchnorm against an O0 baseline and diffs the loss /
grad-norm traces (``tests/L1/common/run_test.sh:29-48``, ``main_amp.py``,
``compare.py``). This runner is that harness for TPU: real ResNet-50
(depth 50, 224px), >=500 iterations per configuration on synthetic data
(fixed random images, random labels — memorization gives a real descending
objective with deterministic data), traces recorded to
``tests/L1/traces/<config>.json`` and compared with
:func:`compare_traces`.

Run on hardware:
    PYTHONPATH=/root/repo:/root/.axon_site python tests/L1/run_l1.py \
        [--iters 500] [--batch 64] [--configs all]

The pytest wrapper (`test_l1_traces.py`) validates whatever traces are
recorded in-tree, so the hardware evidence is versioned.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import jax
import jax.numpy as jnp
import numpy as np

TRACE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "traces")

# the run_test.sh-style matrix: name -> (opt_level, loss_scale, keep_bn)
CONFIGS = {
    "o0_fp32": ("O0", None, None),
    "o2_bf16_dynamic": ("O2", "dynamic", None),
    "o2_bf16_static128": ("O2", 128.0, None),
    "o2_bf16_keepbn_false": ("O2", "dynamic", False),
    "o2_bf16_static1": ("O2", 1.0, True),
}


def _cast_bn_params(params, dtype):
    from jax.tree_util import tree_map_with_path

    def f(path, x):
        keys = "/".join(str(getattr(p, "key", p)) for p in path)
        return x.astype(dtype) if "bn" in keys else x

    return tree_map_with_path(f, params)


def train_one(name, opt_level, loss_scale, keep_bn, *, iters, batch,
              image=224, classes=100, n_images=512, log_every=25):
    from apex_tpu import amp
    from apex_tpu.models import ResNet, ResNetConfig
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.utils.tree import global_norm

    amp_state = amp.initialize(opt_level, loss_scale=loss_scale,
                               keep_batchnorm_fp32=keep_bn,
                               half_dtype=jnp.bfloat16)
    props = amp_state.properties
    compute = jnp.float32 if opt_level == "O0" else jnp.bfloat16
    model = ResNet(ResNetConfig(depth=50, num_classes=classes,
                                compute_dtype=compute))
    params, state = model.init(jax.random.PRNGKey(0))
    opt = FusedSGD(lr=0.02, momentum=0.9, weight_decay=1e-4,
                   master_weights=bool(props.master_weights))
    opt_state = opt.init(params)
    scaler = amp_state.scaler
    sstate = amp_state.scaler_states[0]

    # deterministic synthetic dataset: fixed images + labels, memorizable
    xs = jax.random.normal(jax.random.PRNGKey(1),
                           (n_images, image, image, 3))
    ys = jax.random.randint(jax.random.PRNGKey(2), (n_images,), 0, classes)
    n_batches = n_images // batch
    half_bn = props.keep_batchnorm_fp32 is False and opt_level != "O0"

    @jax.jit
    def step(params, state, opt_state, sstate, x, y):
        def loss_fn(p):
            if half_bn:
                p = _cast_bn_params(p, jnp.bfloat16)
            logits, new_s = model.apply(p, state, x, train=True)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(logp[jnp.arange(x.shape[0]), y]), new_s

        def scaled(p):
            loss, new_s = loss_fn(p)
            return scaler.scale(loss, sstate), (loss, new_s)

        (_, (loss, new_s)), grads = jax.value_and_grad(
            scaled, has_aux=True)(params)
        grads, found_inf = scaler.unscale(grads, sstate)
        gnorm = global_norm(grads)
        params, opt_state = opt.step(grads, params, opt_state,
                                     found_inf=found_inf)
        new_sstate = scaler.update(sstate, found_inf)
        return (params, new_s, opt_state, new_sstate, loss, gnorm,
                new_sstate.loss_scale)

    losses, gnorms, scales = [], [], []
    t0 = time.time()
    for i in range(iters):
        b = i % n_batches
        x = xs[b * batch:(b + 1) * batch]
        y = ys[b * batch:(b + 1) * batch]
        params, state, opt_state, sstate, loss, gnorm, scale = step(
            params, state, opt_state, sstate, x, y)
        losses.append(float(loss))
        gnorms.append(float(gnorm))
        scales.append(float(scale))
        if i % log_every == 0 or i == iters - 1:
            print(f"[{name}] iter {i:4d} loss {losses[-1]:.4f} "
                  f"gnorm {gnorms[-1]:.3f} scale {scales[-1]:.0f}",
                  flush=True)
    trace = {
        "config": {"name": name, "opt_level": opt_level,
                   "loss_scale": loss_scale, "keep_batchnorm_fp32": keep_bn,
                   "iters": iters, "batch": batch, "image": image,
                   "depth": 50, "device": str(jax.devices()[0])},
        "wall_seconds": round(time.time() - t0, 1),
        "loss": losses, "grad_norm": gnorms, "loss_scale": scales,
    }
    os.makedirs(TRACE_DIR, exist_ok=True)
    with open(os.path.join(TRACE_DIR, f"{name}.json"), "w") as f:
        json.dump(trace, f)
    return trace


def compare_traces(trace, baseline, *, early=50, early_rtol=0.2,
                   loss_floor=1e-3):
    """The compare.py contract: finite traces, early-trajectory agreement
    with O0, end-state convergence, sane scaler behavior. Returns a list
    of failure strings (empty = pass).

    ``loss_floor``: relative deviation is only judged while the baseline
    loss is above this — once both runs have collapsed to ~0 (small
    memorization tasks do this within a few iterations), the ratio of two
    near-zero numbers measures noise, not tracking.
    """
    fails = []
    L = np.asarray(trace["loss"])
    G = np.asarray(trace["grad_norm"])
    B = np.asarray(baseline["loss"])
    if not np.isfinite(L).all():
        fails.append("non-finite loss")
    if not np.isfinite(G).all():
        fails.append("non-finite grad norm")
    # early trajectory must track the fp32 baseline (precision-level drift
    # only); later iterations diverge chaotically for ANY precision change
    n = min(early, len(L), len(B))
    meaningful = np.abs(B[:n]) > loss_floor
    dev = np.where(meaningful,
                   np.abs(L[:n] - B[:n]) / np.maximum(np.abs(B[:n]),
                                                      loss_floor), 0.0)
    if dev.max() > early_rtol:
        fails.append(f"early loss deviates from O0 by {dev.max():.3f} "
                     f"(> {early_rtol})")
    # both must actually converge (memorization objective)
    if not (L[-25:].mean() < 0.5 * L[:25].mean()):
        fails.append(f"did not converge: start {L[:25].mean():.3f} "
                     f"end {L[-25:].mean():.3f}")
    S = np.asarray(trace["loss_scale"])
    if (S <= 0).any() or not np.isfinite(S).all():
        fails.append("loss scale left the sane range")
    return fails


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=500)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--configs", type=str, default="all")
    args = ap.parse_args()
    names = (list(CONFIGS) if args.configs == "all"
             else args.configs.split(","))
    traces = {}
    for name in names:
        ol, ls, kb = CONFIGS[name]
        traces[name] = train_one(name, ol, ls, kb, iters=args.iters,
                                 batch=args.batch)
    base = traces.get("o0_fp32")
    if base is None:
        base_path = os.path.join(TRACE_DIR, "o0_fp32.json")
        with open(base_path) as f:
            base = json.load(f)
    ok = True
    for name, tr in traces.items():
        if name == "o0_fp32":
            continue
        fails = compare_traces(tr, base)
        status = "OK" if not fails else f"FAIL: {fails}"
        ok = ok and not fails
        print(f"[compare] {name}: {status}", flush=True)
    print("L1 SWEEP", "PASSED" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
