"""Distributed L1 tier: dp=8 convergence traces vs a single-device O0 run.

The reference runs its L1 convergence cross-product under 2-process DDP as
well as single-GPU (``tests/L1/cross_product_distributed/run.sh`` wraps the
same ``main_amp.py`` in ``torch.distributed.launch``), plus targeted
multi-rank regressions (``tests/distributed/amp_master_params``,
``DDP/ddp_race_condition_test.py``). This runner is that tier for TPU:
the SAME ResNet training flow as ``run_l1.py``, but sharded dp=8 over the
8-device virtual CPU mesh — SyncBN statistics over the data axis, psum'd
gradients, bf16-O2 + dynamic scaler — traced for >=500 iterations and
diffed against a single-device O0 run of the identical (small) model.

The invariant being proven is the distributed-equivalence one: dp=8 with
SyncBN + grad-pmean IS the single-device run, up to precision-level drift
(bf16 vs fp32), so the O0 single-device trace is the comparison baseline
exactly as in the reference's distributed cross-product.

Sized for CPU feasibility (32px, depth 50, width 32, batch 16): the point is the
distributed composition, not chip throughput — the single-chip 224px
traces in ``run_l1.py`` cover depth-at-scale.

Run:
    python tests/L1/run_l1_distributed.py [--iters 500] [--batch 32]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
from apex_tpu.utils.sharding import shard_map
import numpy as np
from jax.sharding import PartitionSpec as P

TRACE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "traces")

# name -> (opt_level, loss_scale, data-parallel size)
DIST_CONFIGS = {
    "dist_o0_fp32_single": ("O0", None, 1),
    "dist_o2_dp8_syncbn": ("O2", "dynamic", 8),
}


def train_one(name, opt_level, loss_scale, dp, *, iters, batch,
              image=32, width=32, classes=10, n_images=128, log_every=50):
    from apex_tpu import amp
    from apex_tpu.models import ResNet, ResNetConfig
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.transformer import parallel_state
    from apex_tpu.utils.tree import global_norm

    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        devices=jax.devices()[:dp])  # pure data-parallel mesh

    amp_state = amp.initialize(opt_level, loss_scale=loss_scale,
                               half_dtype=jnp.bfloat16)
    props = amp_state.properties
    compute = jnp.float32 if opt_level == "O0" else jnp.bfloat16
    model = ResNet(ResNetConfig(
        depth=50, num_classes=classes, width=width, compute_dtype=compute,
        axis_name="data" if dp > 1 else None))
    params, state = model.init(jax.random.PRNGKey(0))
    opt = FusedSGD(lr=0.02, momentum=0.9, weight_decay=1e-4,
                   master_weights=bool(props.master_weights))
    opt_state = opt.init(params)
    scaler = amp_state.scaler
    sstate = amp_state.scaler_states[0]

    xs = jax.random.normal(jax.random.PRNGKey(1),
                           (n_images, image, image, 3))
    ys = jax.random.randint(jax.random.PRNGKey(2), (n_images,), 0, classes)
    n_batches = n_images // batch

    def step_body(params, state, opt_state, sstate, x, y):
        def loss_fn(p):
            logits, new_s = model.apply(p, state, x, train=True)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(logp[jnp.arange(x.shape[0]), y]), new_s

        def scaled(p):
            loss, new_s = loss_fn(p)
            return scaler.scale(loss, sstate), (loss, new_s)

        (_, (loss, new_s)), grads = jax.value_and_grad(
            scaled, has_aux=True)(params)
        if dp > 1:
            # DDP: gradient mean over the data axis (scaled grads — the
            # pmean of per-rank local-mean grads IS the global-batch grad)
            grads = jax.lax.pmean(grads, "data")
            loss = jax.lax.pmean(loss, "data")
        grads, found_inf = scaler.unscale(grads, sstate)
        gnorm = global_norm(grads)
        params, opt_state = opt.step(grads, params, opt_state,
                                     found_inf=found_inf)
        new_sstate = scaler.update(sstate, found_inf)
        return (params, new_s, opt_state, new_sstate, loss, gnorm,
                new_sstate.loss_scale)

    if dp > 1:
        rep = P()
        step = jax.jit(shard_map(
            step_body, mesh=mesh,
            in_specs=(rep, rep, rep, rep, P("data"), P("data")),
            out_specs=(rep, rep, rep, rep, rep, rep, rep),
            check_vma=False))
    else:
        step = jax.jit(step_body)

    losses, gnorms, scales = [], [], []
    t0 = time.time()
    for i in range(iters):
        b = i % n_batches
        x = xs[b * batch:(b + 1) * batch]
        y = ys[b * batch:(b + 1) * batch]
        params, state, opt_state, sstate, loss, gnorm, scale = step(
            params, state, opt_state, sstate, x, y)
        losses.append(float(loss))
        gnorms.append(float(gnorm))
        scales.append(float(scale))
        if i % log_every == 0 or i == iters - 1:
            print(f"[{name}] iter {i:4d} loss {losses[-1]:.4f} "
                  f"gnorm {gnorms[-1]:.3f} scale {scales[-1]:.0f} "
                  f"({(time.time() - t0) / (i + 1):.2f}s/it)", flush=True)
    trace = {
        "config": {"name": name, "opt_level": opt_level,
                   "loss_scale": loss_scale, "data_parallel_size": dp,
                   "syncbn": dp > 1, "iters": iters, "batch": batch,
                   "image": image, "width": width, "depth": 50,
                   "devices": [str(d) for d in jax.devices()[:dp]]},
        "wall_seconds": round(time.time() - t0, 1),
        "loss": losses, "grad_norm": gnorms, "loss_scale": scales,
    }
    os.makedirs(TRACE_DIR, exist_ok=True)
    with open(os.path.join(TRACE_DIR, f"{name}.json"), "w") as f:
        json.dump(trace, f)
    parallel_state.destroy_model_parallel()
    return trace


def main():
    from run_l1 import compare_traces

    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=500)
    ap.add_argument("--batch", type=int, default=16)
    args = ap.parse_args()
    traces = {}
    for name, (ol, ls, dp) in DIST_CONFIGS.items():
        traces[name] = train_one(name, ol, ls, dp, iters=args.iters,
                                 batch=args.batch)
    # this task memorizes within ~15 iterations, so the meaningful
    # tracking window (baseline loss still O(1)) is the first ~10 iters;
    # after that ANY precision change diverges chaotically while both
    # runs converge to ~0. Exact bf16-level dp8==single equivalence is
    # pinned separately (tests/test_parallel.py masked-SyncBN tests).
    fails = compare_traces(traces["dist_o2_dp8_syncbn"],
                           traces["dist_o0_fp32_single"],
                           early=10, early_rtol=0.1, loss_floor=0.05)
    status = "OK" if not fails else f"FAIL: {fails}"
    print(f"[compare] dist_o2_dp8_syncbn vs dist_o0_fp32_single: {status}")
    print("DISTRIBUTED L1", "PASSED" if not fails else "FAILED")
    return 0 if not fails else 1


if __name__ == "__main__":
    raise SystemExit(main())
