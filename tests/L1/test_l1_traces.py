"""Validate the recorded L1 hardware traces (``tests/L1/traces/*.json``).

The traces are produced by ``run_l1.py`` on real TPU hardware (>=500
iterations of ResNet-50 per amp configuration) and committed in-tree —
this test re-applies the ``compare.py`` contract to the stored evidence,
so trace regressions (or accidentally truncated runs) fail the suite.
"""

import glob
import json
import os
import sys

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

from run_l1 import CONFIGS, compare_traces  # noqa: E402

TRACES = {os.path.splitext(os.path.basename(p))[0]: p
          for p in glob.glob(os.path.join(_HERE, "traces", "*.json"))}


def _load(name):
    with open(TRACES[name]) as f:
        return json.load(f)


@pytest.mark.skipif("o0_fp32" not in TRACES,
                    reason="no recorded L1 traces (run run_l1.py on "
                           "hardware)")
class TestRecordedTraces:
    def test_all_configs_recorded_at_depth(self):
        missing = set(CONFIGS) - set(TRACES)
        assert not missing, f"configs without traces: {missing}"
        for name in CONFIGS:
            tr = _load(name)
            assert tr["config"]["iters"] >= 500, (
                f"{name} recorded at {tr['config']['iters']} iters (<500)")
            assert tr["config"]["depth"] == 50
            assert len(tr["loss"]) == tr["config"]["iters"]

    @pytest.mark.parametrize("name",
                             [n for n in CONFIGS if n != "o0_fp32"])
    def test_trace_tracks_baseline(self, name):
        if name not in TRACES:
            pytest.skip(f"{name} not recorded")
        fails = compare_traces(_load(name), _load("o0_fp32"))
        assert not fails, fails

    def test_baseline_converged(self):
        import numpy as np

        L = np.asarray(_load("o0_fp32")["loss"])
        assert np.isfinite(L).all()
        assert L[-25:].mean() < 0.5 * L[:25].mean()
