"""Validate the recorded L1 hardware traces (``tests/L1/traces/*.json``).

The traces are produced by ``run_l1.py`` on real TPU hardware (>=500
iterations of ResNet-50 per amp configuration) and committed in-tree —
this test re-applies the ``compare.py`` contract to the stored evidence,
so trace regressions (or accidentally truncated runs) fail the suite.
"""

import glob
import json
import os
import sys

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

from run_l1 import CONFIGS, compare_traces  # noqa: E402

TRACES = {os.path.splitext(os.path.basename(p))[0]: p
          for p in glob.glob(os.path.join(_HERE, "traces", "*.json"))}


def _load(name):
    with open(TRACES[name]) as f:
        return json.load(f)


@pytest.mark.skipif("o0_fp32" not in TRACES,
                    reason="no recorded L1 traces (run run_l1.py on "
                           "hardware)")
class TestRecordedTraces:
    def test_all_configs_recorded_at_depth(self):
        missing = set(CONFIGS) - set(TRACES)
        assert not missing, f"configs without traces: {missing}"
        for name in CONFIGS:
            tr = _load(name)
            assert tr["config"]["iters"] >= 500, (
                f"{name} recorded at {tr['config']['iters']} iters (<500)")
            assert tr["config"]["depth"] == 50
            assert len(tr["loss"]) == tr["config"]["iters"]

    @pytest.mark.parametrize("name",
                             [n for n in CONFIGS if n != "o0_fp32"])
    def test_trace_tracks_baseline(self, name):
        if name not in TRACES:
            pytest.skip(f"{name} not recorded")
        fails = compare_traces(_load(name), _load("o0_fp32"))
        assert not fails, fails

    def test_baseline_converged(self):
        import numpy as np

        L = np.asarray(_load("o0_fp32")["loss"])
        assert np.isfinite(L).all()
        assert L[-25:].mean() < 0.5 * L[:25].mean()


@pytest.mark.skipif("dist_o0_fp32_single" not in TRACES
                    or "dist_o2_dp8_syncbn" not in TRACES,
                    reason="no recorded distributed L1 traces (run "
                           "run_l1_distributed.py)")
class TestDistributedTraces:
    """The distributed tier (reference
    ``tests/L1/cross_product_distributed/run.sh``): the dp=8 SyncBN bf16-O2
    trace must track and converge against its single-device O0 baseline."""

    def test_recorded_at_depth(self):
        for name in ("dist_o0_fp32_single", "dist_o2_dp8_syncbn"):
            tr = _load(name)
            assert tr["config"]["iters"] >= 500, (
                f"{name} recorded at {tr['config']['iters']} iters (<500)")
            assert len(tr["loss"]) == tr["config"]["iters"]
        dist = _load("dist_o2_dp8_syncbn")["config"]
        assert dist["data_parallel_size"] == 8
        assert dist["syncbn"] is True

    def test_dp8_tracks_single_device_baseline(self):
        # early window/floor: the small memorization task collapses by
        # ~iter 15, after which relative deviation measures chaos, not
        # tracking (see run_l1_distributed.main)
        fails = compare_traces(_load("dist_o2_dp8_syncbn"),
                               _load("dist_o0_fp32_single"),
                               early=10, early_rtol=0.1, loss_floor=0.05)
        assert not fails, fails

    def test_equivalence_is_tight_early(self):
        """dp=8 + SyncBN + grad-pmean vs single device is the SAME
        computation up to precision drift: the first iterations (before
        the memorization collapse amplifies bf16-vs-fp32 noise) must
        track far tighter than the generic envelope."""
        import numpy as np

        a = np.asarray(_load("dist_o2_dp8_syncbn")["loss"][:8])
        b = np.asarray(_load("dist_o0_fp32_single")["loss"][:8])
        assert (np.abs(a - b) / np.maximum(np.abs(b), 0.05)).max() < 0.05
