"""GPT/BERT standalone model tests.

Mirrors the reference's model-level suite
(``tests/L0/run_transformer/test_gpt_minimal.py``, ``test_bert_minimal.py``:
convergence smoke on the standalone Megatron LM) plus the TP-vs-single-rank
numerics strategy of ``test_layers.py`` — sharded runs must match the
unsharded reference computed from the same seeds.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

os.environ.setdefault("APEX_TPU_FORCE_PALLAS", "interpret")

from apex_tpu.models import BertModel, GPTModel, TransformerConfig  # noqa: E402
from apex_tpu.optimizers import FusedAdam  # noqa: E402
from apex_tpu.training import make_train_step  # noqa: E402
from apex_tpu.transformer import parallel_state  # noqa: E402
from apex_tpu.utils.sharding import shard_map  # noqa: E402


def small_config(**kw):
    defaults = dict(num_layers=2, hidden_size=64, num_attention_heads=4,
                    vocab_size=128, max_position_embeddings=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    defaults.update(kw)
    return TransformerConfig(**defaults)


def _batch(bs=8, seq=16, vocab=128):
    toks = jax.random.randint(jax.random.PRNGKey(1), (bs, seq), 0, vocab)
    labels = jax.random.randint(jax.random.PRNGKey(2), (bs, seq), 0, vocab)
    return {"tokens": toks, "labels": labels}


def _train(tp, sp, steps=3, recompute=False, scan_unroll=1):
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=tp)
    cfg = small_config(sequence_parallel=sp, recompute=recompute,
                       scan_unroll=scan_unroll)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-3)
    opt_state = opt.init(params)

    def loss_fn(p, batch, rng):
        return model.apply(p, batch["tokens"], batch["labels"], rng=rng)

    step = make_train_step(loss_fn, opt, mesh, model.spec(),
                           {"tokens": P("data"), "labels": P("data")},
                           params_template=params)
    batch = _batch()
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, batch,
                                       jax.random.PRNGKey(3))
        losses.append(float(loss))
    parallel_state.destroy_model_parallel()
    return losses, params


class TestGPT:
    def test_forward_loss_near_uniform_at_init(self):
        model = GPTModel(small_config())
        params = model.init(jax.random.PRNGKey(0))
        b = _batch()
        loss = model.apply(params, b["tokens"], b["labels"])
        assert abs(float(loss) - np.log(128)) < 0.2

    def test_logits_shape_vocab_parallel_layout(self):
        model = GPTModel(small_config())
        params = model.init(jax.random.PRNGKey(0))
        b = _batch()
        logits = model.apply(params, b["tokens"])
        assert logits.shape == (16, 8, 128)  # [s, b, vocab/tp] with tp=1

    @pytest.mark.slow
    def test_training_decreases_loss(self):
        losses, _ = _train(tp=1, sp=False, steps=5)
        assert losses[-1] < losses[0]

    @pytest.mark.slow
    @pytest.mark.parametrize("tp,sp", [(2, False), (2, True), (4, True)])
    def test_tensor_parallel_matches_single_rank(self, tp, sp):
        # same seeds -> sharded training must reproduce the unsharded run
        # (reference test_layers.py strategy)
        ref_losses, ref_params = _train(tp=1, sp=False)
        tp_losses, tp_params = _train(tp=tp, sp=sp)
        np.testing.assert_allclose(ref_losses, tp_losses, atol=2e-5, rtol=2e-5)
        for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(tp_params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5, rtol=5e-5)

    @pytest.mark.slow
    def test_recompute_matches_plain(self):
        ref_losses, _ = _train(tp=1, sp=False)
        rc_losses, _ = _train(tp=1, sp=False, recompute=True)
        np.testing.assert_allclose(ref_losses, rc_losses, atol=1e-6)

    @pytest.mark.slow  # full-vocab parity forward x2: compile-bound (ROADMAP tiers)
    def test_chunked_lm_head_loss_matches_plain(self):
        """loss_seq_chunks (the long-context vocab-head memory guard) is a
        pure schedule change — loss and grads must match unchunked."""
        model_p = GPTModel(small_config())
        model_c = GPTModel(small_config(loss_seq_chunks=4))
        params = model_p.init(jax.random.PRNGKey(0))
        b = _batch()

        def loss(model):
            return lambda p: model.apply(p, b["tokens"], b["labels"])

        lp, gp = jax.value_and_grad(loss(model_p))(params)
        lc_, gc = jax.value_and_grad(loss(model_c))(params)
        np.testing.assert_allclose(float(lp), float(lc_), rtol=1e-6)
        for a_, b_ in zip(jax.tree.leaves(gp), jax.tree.leaves(gc)):
            np.testing.assert_allclose(np.asarray(a_), np.asarray(b_),
                                       atol=1e-6, rtol=1e-5)

    @pytest.mark.slow
    def test_selective_recompute_and_unroll_match_plain(self):
        """'selective' remat policy (save dots, recompute elementwise) and
        an unrolled layer scan are pure schedule changes — numerics must
        match the plain path."""
        ref_losses, _ = _train(tp=1, sp=False)
        sel_losses, _ = _train(tp=1, sp=False, recompute="selective")
        np.testing.assert_allclose(ref_losses, sel_losses, atol=1e-6)
        un_losses, _ = _train(tp=1, sp=False, scan_unroll=4)
        np.testing.assert_allclose(ref_losses, un_losses, atol=1e-6)

    def test_dropout_needs_rng_and_decorrelates_ranks(self):
        cfg = small_config(hidden_dropout=0.5)
        model = GPTModel(cfg)
        params = model.init(jax.random.PRNGKey(0))
        b = _batch()
        l1 = model.apply(params, b["tokens"], b["labels"],
                         rng=jax.random.PRNGKey(1), deterministic=False)
        l2 = model.apply(params, b["tokens"], b["labels"],
                         rng=jax.random.PRNGKey(2), deterministic=False)
        assert float(l1) != float(l2)

    @pytest.mark.slow  # packed-path dropout statistics: compile-bound (ROADMAP tiers)
    def test_attention_dropout_on_packed_path(self):
        # attention dropout rides the packed kernels (in-kernel hash
        # mask); must be seed-reproducible, seed-sensitive, trainable,
        # and no-op when deterministic
        cfg = small_config(attention_dropout=0.3)
        model = GPTModel(cfg)
        params = model.init(jax.random.PRNGKey(0))
        b = _batch()

        def loss(p, key):
            return model.apply(p, b["tokens"], b["labels"], rng=key,
                               deterministic=False)

        l1 = loss(params, jax.random.PRNGKey(1))
        l1b = loss(params, jax.random.PRNGKey(1))
        l2 = loss(params, jax.random.PRNGKey(2))
        ld = model.apply(params, b["tokens"], b["labels"])
        np.testing.assert_allclose(float(l1), float(l1b))   # reproducible
        assert float(l1) != float(l2)                       # seed-sensitive
        assert float(l1) != float(ld)                       # dropout active
        g = jax.grad(loss)(params, jax.random.PRNGKey(1))
        assert all(bool(jnp.all(jnp.isfinite(x)))
                   for x in jax.tree.leaves(g))

    def test_attention_dropout_seed_layer_distinct(self):
        # the stack derives per-layer seeds as base + layer*GOLDEN (one
        # base draw, odd-constant offset) so masks are STRUCTURALLY
        # distinct across layers — two independent 32-bit draws could
        # collide and share a mask. The attention module must honor the
        # explicit dropout_seed: same seed → identical mask, the next
        # layer's offset seed → a different mask over identical inputs.
        from apex_tpu.models.transformer import ParallelAttention

        # head_dim 64 / 2 groups: packed_geometry aligns (gpc=2, in_w=384)
        # so the in-kernel hash-dropout path actually engages — the seed
        # override is dead weight on the XLA fallback, and y0 == y0b below
        # (equal under DIFFERENT rng) certifies the packed path was taken
        cfg = small_config(attention_dropout=0.3, hidden_size=128,
                           num_attention_heads=2)
        attn = ParallelAttention(cfg)
        params = attn.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(3),
                              (16, 8, cfg.hidden_size), jnp.float32)
        base = jnp.asarray([12345], jnp.int32)
        golden = jnp.int32(-1640531527)
        y0 = attn.apply(params, x, rng=jax.random.PRNGKey(1),
                        deterministic=False, dropout_seed=base)
        y0b = attn.apply(params, x, rng=jax.random.PRNGKey(2),
                         deterministic=False, dropout_seed=base)
        y1 = attn.apply(params, x, rng=jax.random.PRNGKey(1),
                        deterministic=False, dropout_seed=base + golden)
        # the override fully determines the mask (rng is irrelevant)...
        np.testing.assert_array_equal(np.asarray(y0), np.asarray(y0b))
        # ...and the layer-offset seed draws a different mask
        assert bool(jnp.any(y0 != y1))


class TestBert:
    def _bert(self, **kw):
        cfg = small_config(**kw)
        return BertModel(cfg, add_binary_head=True)

    def test_forward_heads(self):
        model = self._bert()
        params = model.init(jax.random.PRNGKey(0))
        b = _batch()
        pad = jnp.ones((8, 16), bool).at[:, 12:].set(False)
        lm_loss, binary_logits = model.apply(
            params, b["tokens"], padding_mask=pad, lm_labels=b["labels"])
        assert binary_logits.shape == (8, 2)
        assert abs(float(lm_loss) - np.log(128)) < 0.3

    def test_padding_mask_excludes_padded_positions(self):
        model = self._bert()
        params = model.init(jax.random.PRNGKey(0))
        b = _batch()
        pad = jnp.ones((8, 16), bool).at[:, 8:].set(False)
        # perturbing padded token ids must not change the masked loss
        toks2 = b["tokens"].at[:, 8:].set(0)
        l1, _ = model.apply(params, b["tokens"], padding_mask=pad,
                            lm_labels=b["labels"])
        l2, _ = model.apply(params, toks2, padding_mask=pad,
                            lm_labels=b["labels"])
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)

    @pytest.mark.slow
    @pytest.mark.parametrize("sp", [False, True])
    def test_tensor_parallel_matches_single_rank(self, sp):
        def run(tp, sp):
            parallel_state.destroy_model_parallel()
            mesh = parallel_state.initialize_model_parallel(
                tensor_model_parallel_size=tp)
            model = self._bert(sequence_parallel=sp)
            params = model.init(jax.random.PRNGKey(0))
            b = _batch()
            pad = jnp.ones((8, 16), bool).at[:, 12:].set(False)

            def loss_fn(p, batch, rng):
                lm, bin_logits = model.apply(
                    p, batch["tokens"], padding_mask=pad,
                    lm_labels=batch["labels"])
                return lm + 0.0 * jnp.sum(bin_logits)

            grad_fn = jax.value_and_grad(loss_fn)
            per_rank = lambda p, batch: grad_fn(p, batch, None)
            out = shard_map(
                per_rank, mesh=mesh,
                in_specs=(model.spec(), {"tokens": P(), "labels": P()}),
                out_specs=(P(), model.spec()), check_vma=False,
            )(params, b)
            parallel_state.destroy_model_parallel()
            return out

        ref_loss, ref_grads = run(1, False)
        tp_loss, tp_grads = run(2, sp)
        np.testing.assert_allclose(float(ref_loss), float(tp_loss),
                                   atol=2e-5, rtol=2e-5)
        for a, b_ in zip(jax.tree.leaves(ref_grads), jax.tree.leaves(tp_grads)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=5e-5, rtol=5e-5)
