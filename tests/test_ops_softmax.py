"""Fused softmax parity (tier-L0 analog of the megatron softmax tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import (
    scaled_softmax,
    scaled_masked_softmax,
    scaled_upper_triang_masked_softmax,
    generic_scaled_masked_softmax,
)
from apex_tpu.ops import _support


def ref_masked(x, mask, scale):
    logits = x.astype(jnp.float32) * scale
    if mask is not None:
        logits = jnp.where(jnp.broadcast_to(mask, x.shape), -10000.0, logits)
    return jax.nn.softmax(logits, axis=-1)


def test_scaled_softmax():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8, 24))
    y = scaled_softmax(x, 0.5)
    np.testing.assert_allclose(y, ref_masked(x, None, 0.5), atol=1e-6)
    g = jax.grad(lambda x: jnp.sum(scaled_softmax(x, 0.5) * jnp.cos(x)))(x)
    gr = jax.grad(lambda x: jnp.sum(ref_masked(x, None, 0.5) * jnp.cos(x)))(x)
    np.testing.assert_allclose(g, gr, atol=1e-5)


def test_scaled_masked_softmax():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8, 24))
    mask = jax.random.bernoulli(jax.random.PRNGKey(1), 0.3, (2, 1, 8, 24))
    y = scaled_masked_softmax(x, mask, 2.0)
    np.testing.assert_allclose(y, ref_masked(x, mask, 2.0), atol=1e-6)
    g = jax.grad(lambda x: jnp.sum(scaled_masked_softmax(x, mask, 2.0) ** 2))(x)
    gr = jax.grad(lambda x: jnp.sum(ref_masked(x, mask, 2.0) ** 2))(x)
    np.testing.assert_allclose(g, gr, atol=1e-5)
    yg = generic_scaled_masked_softmax(x, mask, 2.0)
    np.testing.assert_allclose(yg, y, atol=1e-7)


def test_causal_softmax():
    sq = 16
    x = jax.random.normal(jax.random.PRNGKey(0), (6, sq, sq))
    y = scaled_upper_triang_masked_softmax(x, 1.0)
    causal = jnp.triu(jnp.ones((sq, sq), bool), k=1)
    yr = ref_masked(x, causal[None], 1.0)
    np.testing.assert_allclose(y, yr, atol=1e-6)
    # strictly-upper entries ~0 probability mass
    assert float(jnp.max(jnp.where(causal[None], y, 0.0))) < 1e-4
    g = jax.grad(lambda x: jnp.sum(scaled_upper_triang_masked_softmax(x, 1.0) ** 2))(x)
    gr = jax.grad(lambda x: jnp.sum(ref_masked(x, causal[None], 1.0) ** 2))(x)
    np.testing.assert_allclose(g, gr, atol=1e-5)


def test_bf16():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 32), jnp.bfloat16)
    y = scaled_softmax(x, 1.0)
    assert y.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(y, np.float32),
        np.asarray(ref_masked(x, None, 1.0), np.float32), atol=0.01)


def test_pallas_interpret_kernels(monkeypatch):
    monkeypatch.setenv("APEX_TPU_FORCE_PALLAS", "interpret")
    _support.pallas_mode.cache_clear()
    try:
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 2, 8, 40))
        mask = jax.random.bernoulli(jax.random.PRNGKey(1), 0.3, (2, 1, 8, 40))
        y = scaled_masked_softmax(x, mask, 1.5)
        np.testing.assert_allclose(y, ref_masked(x, mask, 1.5), atol=1e-6)
        g = jax.grad(lambda x: jnp.sum(scaled_masked_softmax(x, mask, 1.5) ** 2))(x)
        gr = jax.grad(lambda x: jnp.sum(ref_masked(x, mask, 1.5) ** 2))(x)
        np.testing.assert_allclose(g, gr, atol=1e-5)

        xc = jax.random.normal(jax.random.PRNGKey(2), (4, 8, 8))
        yc = scaled_upper_triang_masked_softmax(xc, 1.0)
        causal = jnp.triu(jnp.ones((8, 8), bool), k=1)
        np.testing.assert_allclose(yc, ref_masked(xc, causal[None], 1.0), atol=1e-6)
    finally:
        _support.pallas_mode.cache_clear()
