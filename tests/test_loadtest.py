"""Load-test harness + SLO regression gate tests.

Three contracts, layered:

- **Determinism**: same seed + same scenario => byte-identical arrival
  schedule and per-request sampling draws (what makes a committed SLO
  baseline meaningful at all).
- **Reconciliation**: the tier-1 smoke scenario drives the full
  generator -> supervisor -> JSONL -> SLO-verdict pipeline and its
  monitor SLO section must reconcile exactly with the registry counters
  and request records — every offered arrival reaches exactly one
  terminal record.
- **The gate fails red**: the regression gate is only worth committing
  if it FAILS on a violation — synthetic bad-latency logs and synthetic
  tightened baselines must exit nonzero (1 and 2 respectively), not
  just the green path exit 0.

The full overload and crash-recovery scenarios are slow-tier; a scaled-
down crash scenario keeps the finite-recovery-time acceptance in
tier-1.
"""

import glob
import json
import os
import subprocess
import sys

import jax
import pytest

from apex_tpu.loadtest import (
    FaultSchedule,
    Scenario,
    TrafficGenerator,
    compare_to_baseline,
    load_baseline,
    run_scenario,
    update_baseline,
)
from apex_tpu.loadtest.__main__ import (
    EXIT_NO_BASELINE,
    EXIT_OK,
    EXIT_REGRESSION,
    EXIT_SLO_VIOLATION,
    main as loadtest_main,
)
from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.observability import build_report, render_report
from apex_tpu.observability.slo import (
    SLOSpec,
    evaluate_slos,
    measure_slo_metrics,
)
from apex_tpu.serving import FINISH_REASONS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCENARIO_DIR = os.path.join(REPO, "benchmarks", "scenarios")


@pytest.fixture(scope="module")
def small():
    """The tier-1 serving model — SAME dims as the committed scenarios'
    model spec, so tests can run them without a second model build."""
    model = GPTModel(TransformerConfig(
        num_layers=2, hidden_size=32, num_attention_heads=4, vocab_size=64,
        max_position_embeddings=64, hidden_dropout=0.0,
        attention_dropout=0.0))
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _scenario_dict(**over):
    base = {
        "name": "t", "seed": 3,
        "model": {"num_layers": 2, "hidden_size": 32,
                  "num_attention_heads": 4, "vocab_size": 64,
                  "max_position_embeddings": 64},
        "engine": {"max_slots": 4, "max_len": 32, "max_queue": 16},
        "phases": [{"name": "p", "n_requests": 8, "rate_rps": 200.0,
                    "prompt_lens": {"4": 2, "8": 1},
                    "max_new_tokens": {"3": 1, "5": 1}}],
    }
    base.update(over)
    return base


# ---------------------------------------------------------------------------
# scenario schema


class TestScenarioSchema:
    def test_committed_scenarios_load_and_round_trip(self):
        paths = sorted(glob.glob(os.path.join(SCENARIO_DIR, "*.json")))
        assert len(paths) >= 3, f"missing committed scenarios: {paths}"
        for path in paths:
            scn = Scenario.load(path)
            # to_dict -> from_dict is a fixed point (the schema is
            # self-describing, no silent field loss)
            again = Scenario.from_dict(scn.to_dict())
            assert again.to_dict() == scn.to_dict(), path
            assert scn.total_requests >= 1

    def test_unknown_scenario_key_rejected(self):
        with pytest.raises(ValueError, match="unknown scenario keys"):
            Scenario.from_dict(_scenario_dict(bogus=1))

    def test_unknown_phase_key_rejected(self):
        d = _scenario_dict()
        d["phases"][0]["surprise"] = True
        with pytest.raises(ValueError, match="unknown keys"):
            Scenario.from_dict(d)

    def test_unknown_supervisor_knob_rejected(self):
        with pytest.raises(ValueError, match="supervisor knobs"):
            Scenario.from_dict(_scenario_dict(supervisor={"not_a_knob": 1}))

    def test_unknown_slo_metric_rejected(self):
        with pytest.raises(ValueError, match="unknown SLO metric"):
            Scenario.from_dict(_scenario_dict(slo={"p99_vibes": 1.0}))

    def test_phase_budget_must_fit_engine(self):
        d = _scenario_dict()
        d["phases"][0]["max_new_tokens"] = {"40": 1}   # 8 + 40 > 32
        with pytest.raises(ValueError, match="exceeds engine max_len"):
            Scenario.from_dict(d)

    def test_bad_mix_weight_rejected(self):
        d = _scenario_dict()
        d["phases"][0]["prompt_lens"] = {"4": 0}
        with pytest.raises(ValueError, match="weight"):
            Scenario.from_dict(d)

    def test_kv_layout_knobs_round_trip(self):
        d = _scenario_dict(engine={
            "max_slots": 4, "max_len": 32, "max_queue": 16,
            "kv_layout": "paged", "page_size": 8, "n_pages": 12})
        scn = Scenario.from_dict(d)
        assert scn.engine.kv_layout == "paged"
        assert scn.engine.page_size == 8
        assert scn.engine.n_pages == 12
        again = Scenario.from_dict(scn.to_dict())
        assert again.to_dict() == scn.to_dict()
        # flat opt-out survives too, and n_pages=None stays absent
        flat = Scenario.from_dict(_scenario_dict(engine={
            "max_slots": 4, "max_len": 32, "kv_layout": "flat"}))
        assert flat.engine.kv_layout == "flat"
        assert "n_pages" not in flat.to_dict()["engine"]

    def test_bad_kv_layout_rejected(self):
        with pytest.raises(ValueError, match="kv_layout"):
            Scenario.from_dict(_scenario_dict(engine={
                "max_slots": 4, "max_len": 32, "kv_layout": "ragged"}))

    def test_kv_dtype_and_speculation_round_trip(self):
        scn = Scenario.from_dict(_scenario_dict(engine={
            "max_slots": 4, "max_len": 32, "max_queue": 16,
            "kv_dtype": "int8", "speculation": 3}))
        assert scn.engine.kv_dtype == "int8"
        assert scn.engine.speculation == 3
        again = Scenario.from_dict(scn.to_dict())
        assert again.to_dict() == scn.to_dict()
        # defaults stay absent: a pre-existing scenario file's dict form
        # is unchanged by the new knobs
        plain = Scenario.from_dict(_scenario_dict())
        assert "kv_dtype" not in plain.to_dict()["engine"]
        assert "speculation" not in plain.to_dict()["engine"]

    def test_bad_kv_dtype_and_speculation_rejected(self):
        with pytest.raises(ValueError, match="kv_dtype"):
            Scenario.from_dict(_scenario_dict(engine={
                "max_slots": 4, "max_len": 32, "kv_dtype": "fp4"}))
        with pytest.raises(ValueError, match="needs kv_layout='paged'"):
            Scenario.from_dict(_scenario_dict(engine={
                "max_slots": 4, "max_len": 32, "kv_layout": "flat",
                "kv_dtype": "int8"}))
        with pytest.raises(ValueError, match="speculation"):
            Scenario.from_dict(_scenario_dict(engine={
                "max_slots": 4, "max_len": 32, "speculation": 1}))
        with pytest.raises(ValueError, match="needs kv_layout='paged'"):
            Scenario.from_dict(_scenario_dict(engine={
                "max_slots": 4, "max_len": 32, "kv_layout": "flat",
                "speculation": 2}))

    def test_prompt_period_round_trip_and_validation(self):
        d = _scenario_dict()
        d["phases"][0]["prompt_period"] = 4
        scn = Scenario.from_dict(d)
        assert scn.phases[0].prompt_period == 4
        assert Scenario.from_dict(scn.to_dict()).to_dict() == scn.to_dict()
        assert "prompt_period" not in \
            Scenario.from_dict(_scenario_dict()).to_dict()["phases"][0]
        d["phases"][0]["prompt_period"] = -1
        with pytest.raises(ValueError, match="prompt_period"):
            Scenario.from_dict(d)

    def test_prompt_period_tiles_prompts(self):
        d = _scenario_dict()
        d["phases"][0]["prompt_period"] = 2
        for s in TrafficGenerator(Scenario.from_dict(d)).schedule():
            p = s.request.prompt
            assert p == (p[:2] * len(p))[:len(p)]

    def test_lora_knobs_and_adapter_mix_round_trip(self):
        d = _scenario_dict(engine={
            "max_slots": 4, "max_len": 32, "max_queue": 16,
            "lora_rank": 4, "lora_adapters": 2})
        d["phases"][0]["adapter_mix"] = {"0": 3, "1": 1, "base": 2}
        scn = Scenario.from_dict(d)
        assert scn.engine.lora_rank == 4
        assert scn.engine.lora_adapters == 2
        assert scn.phases[0].adapter_mix == {"0": 3.0, "1": 1.0,
                                             "base": 2.0}
        again = Scenario.from_dict(scn.to_dict())
        assert again.to_dict() == scn.to_dict()
        # defaults stay absent: a pre-LoRA scenario's dict form is
        # unchanged by the new knobs
        plain = Scenario.from_dict(_scenario_dict())
        assert "lora_rank" not in plain.to_dict()["engine"]
        assert "lora_adapters" not in plain.to_dict()["engine"]
        assert "adapter_mix" not in plain.to_dict()["phases"][0]

    def test_bad_lora_knobs_and_adapter_mix_rejected(self):
        # rank and bank size come together or not at all
        with pytest.raises(ValueError, match="lora_rank"):
            Scenario.from_dict(_scenario_dict(engine={
                "max_slots": 4, "max_len": 32, "lora_rank": 4}))
        with pytest.raises(ValueError, match="lora_rank"):
            Scenario.from_dict(_scenario_dict(engine={
                "max_slots": 4, "max_len": 32, "lora_adapters": 2}))
        # an adapter_mix needs a store, and its ids must fit the bank
        d = _scenario_dict()
        d["phases"][0]["adapter_mix"] = {"0": 1}
        with pytest.raises(ValueError, match="lora_adapters"):
            Scenario.from_dict(d)
        d = _scenario_dict(engine={
            "max_slots": 4, "max_len": 32, "lora_rank": 4,
            "lora_adapters": 2})
        d["phases"][0]["adapter_mix"] = {"2": 1}
        with pytest.raises(ValueError, match="adapter_mix"):
            Scenario.from_dict(d)
        d["phases"][0]["adapter_mix"] = {"tenant-a": 1}
        with pytest.raises(ValueError, match="adapter_mix"):
            Scenario.from_dict(d)
        d["phases"][0]["adapter_mix"] = {"0": 0}
        with pytest.raises(ValueError, match="weight"):
            Scenario.from_dict(d)

    def test_fault_schedule_round_trip(self):
        fs = FaultSchedule.from_dict({
            "decode_raise_calls": [3], "decode_hang": {"5": 1.5},
            "poison_decode": {"7": [1, "nonfinite"]}})
        assert fs.poison_decode == {7: (1, "nonfinite")}
        assert FaultSchedule.from_dict(fs.to_dict()) == fs
        kw = fs.injector_kwargs()
        assert kw["decode_hang"] == {5: 1.5}


class TestAutoscaleDeploySchema:
    """The PR 16 scenario blocks: strict parse-time validation, so a
    typo'd autoscale/deploy scenario fails at load, not mid-run."""

    def test_autoscale_round_trip(self):
        d = _scenario_dict(
            fleet={"n_replicas": 2},
            autoscale={"min_replicas": 1, "max_replicas": 3,
                       "poll_interval_s": 0.1, "cooldown_s": 1.0,
                       "scale_up_queue_per_replica": 3.0})
        scn = Scenario.from_dict(d)
        assert scn.autoscale.max_replicas == 3
        assert Scenario.from_dict(scn.to_dict()).to_dict() == scn.to_dict()
        # the runner builds AutoscaleConfig from exactly these kwargs
        kw = scn.autoscale.config_kwargs()
        assert len(kw) == 11 and kw["scale_up_queue_per_replica"] == 3.0

    def test_autoscale_unknown_key_rejected(self):
        with pytest.raises(ValueError, match="unknown autoscale keys"):
            Scenario.from_dict(_scenario_dict(
                fleet={"n_replicas": 1},
                autoscale={"max_replicas": 2, "vibes": 1}))

    def test_autoscale_needs_fleet_block(self):
        with pytest.raises(ValueError, match="needs a 'fleet' block"):
            Scenario.from_dict(_scenario_dict(
                autoscale={"max_replicas": 2}))

    def test_autoscale_band_must_cover_n_replicas(self):
        with pytest.raises(ValueError, match="autoscale band"):
            Scenario.from_dict(_scenario_dict(
                fleet={"n_replicas": 4},
                autoscale={"min_replicas": 1, "max_replicas": 2}))

    def test_autoscale_bad_band_rejected_at_parse(self):
        with pytest.raises(ValueError, match="max_replicas"):
            Scenario.from_dict(_scenario_dict(
                fleet={"n_replicas": 2},
                autoscale={"min_replicas": 3, "max_replicas": 2}))

    def test_deploy_round_trip(self):
        d = _scenario_dict(
            fleet={"n_replicas": 2},
            deploy={"at_s": 2.0, "kind": "checkpoint", "poison": True,
                    "canary": {"window_s": 0.5, "min_requests": 3}})
        scn = Scenario.from_dict(d)
        assert scn.deploy.poison is True
        assert Scenario.from_dict(scn.to_dict()).to_dict() == scn.to_dict()

    def test_deploy_unknown_keys_rejected(self):
        with pytest.raises(ValueError, match="unknown deploy keys"):
            Scenario.from_dict(_scenario_dict(
                fleet={"n_replicas": 2}, deploy={"at_s": 1.0, "when": 2}))
        with pytest.raises(ValueError,
                           match="unknown deploy canary keys"):
            Scenario.from_dict(_scenario_dict(
                fleet={"n_replicas": 2},
                deploy={"at_s": 1.0, "canary": {"vibe_check": 1}}))

    def test_deploy_needs_fleet_block(self):
        with pytest.raises(ValueError, match="needs a 'fleet' block"):
            Scenario.from_dict(_scenario_dict(deploy={"at_s": 1.0}))

    def test_deploy_bad_kind_rejected(self):
        with pytest.raises(ValueError, match="deploy kind"):
            Scenario.from_dict(_scenario_dict(
                fleet={"n_replicas": 2},
                deploy={"at_s": 1.0, "kind": "yolo"}))

    def test_adapter_deploy_needs_lora_and_fresh_id(self):
        with pytest.raises(ValueError, match="adapter store"):
            Scenario.from_dict(_scenario_dict(
                fleet={"n_replicas": 2},
                deploy={"at_s": 1.0, "kind": "adapter"}))
        # digit ids below lora_adapters are the runner's preloaded
        # tenants — the canary must be a NEW tenant
        d = _scenario_dict(fleet={"n_replicas": 2},
                           deploy={"at_s": 1.0, "kind": "adapter",
                                   "adapter_id": "0"})
        d["engine"].update({"lora_adapters": 2, "lora_rank": 2})
        with pytest.raises(ValueError, match="collides"):
            Scenario.from_dict(d)
        d["deploy"]["adapter_id"] = "canary"
        scn = Scenario.from_dict(d)
        assert scn.deploy.adapter_id == "canary"


class TestSentinelRecorderSchema:
    """The PR 18 scenario blocks: the drift sentinel and the flight
    recorder, validated at parse time like every other block."""

    def test_sentinel_round_trip(self):
        d = _scenario_dict(
            fleet={"n_replicas": 2},
            sentinel={"poll_interval_s": 0.1, "warmup_polls": 4,
                      "z_threshold": 5.0, "min_abs_dev": 2.0,
                      "signals": ["queue_depth", "ttft_p99_s"]})
        scn = Scenario.from_dict(d)
        assert scn.sentinel.z_threshold == 5.0
        assert scn.sentinel.signals == ("queue_depth", "ttft_p99_s")
        assert Scenario.from_dict(scn.to_dict()).to_dict() == scn.to_dict()
        # the runner builds SentinelConfig from exactly these kwargs
        from apex_tpu.observability.sentinel import SentinelConfig
        cfg = SentinelConfig(**scn.sentinel.config_kwargs())
        assert cfg.min_abs_dev == 2.0

    def test_recorder_round_trip(self):
        d = _scenario_dict(recorder={"events_capacity": 32,
                                     "max_bundles": 2})
        scn = Scenario.from_dict(d)
        assert scn.recorder.max_bundles == 2
        assert Scenario.from_dict(scn.to_dict()).to_dict() == scn.to_dict()
        from apex_tpu.observability import FlightRecorder
        rec = FlightRecorder(**scn.recorder.recorder_kwargs())
        assert rec.events.maxlen == 32 and rec.max_bundles == 2

    def test_sentinel_unknown_key_rejected(self):
        with pytest.raises(ValueError, match="unknown sentinel keys"):
            Scenario.from_dict(_scenario_dict(
                fleet={"n_replicas": 1}, sentinel={"vibes": 1}))

    def test_recorder_unknown_key_rejected(self):
        with pytest.raises(ValueError, match="unknown recorder keys"):
            Scenario.from_dict(_scenario_dict(recorder={"vibes": 1}))

    def test_sentinel_needs_fleet_block(self):
        with pytest.raises(ValueError, match="needs a 'fleet' block"):
            Scenario.from_dict(_scenario_dict(
                sentinel={"z_threshold": 4.0}))

    def test_sentinel_validation_mirrors_runtime_config(self):
        with pytest.raises(ValueError, match="ewma_alpha"):
            Scenario.from_dict(_scenario_dict(
                fleet={"n_replicas": 1}, sentinel={"ewma_alpha": 0.0}))
        with pytest.raises(ValueError, match="signals"):
            Scenario.from_dict(_scenario_dict(
                fleet={"n_replicas": 1}, sentinel={"signals": []}))
        with pytest.raises(ValueError, match="events_capacity"):
            Scenario.from_dict(_scenario_dict(
                recorder={"events_capacity": 0}))


# ---------------------------------------------------------------------------
# generator determinism (satellite: asserted across two runs)


class TestGeneratorDeterminism:
    def test_same_seed_same_schedule(self):
        d = _scenario_dict(phases=[
            {"name": "a", "n_requests": 10, "rate_rps": 100.0,
             "prompt_lens": {"4": 1, "8": 1}, "max_new_tokens": {"3": 1},
             "deadline_fraction": 0.5, "deadline_min_s": 1.0,
             "deadline_max_s": 2.0, "greedy_fraction": 0.4,
             "temperatures": [0.7, 1.1], "top_ks": [0, 8]},
            {"name": "b", "n_requests": 6, "rate_rps": 500.0,
             "prompt_lens": {"6": 1}, "max_new_tokens": {"2": 1, "4": 3}}])
        s1 = TrafficGenerator(Scenario.from_dict(d)).schedule()
        s2 = TrafficGenerator(Scenario.from_dict(d)).schedule()
        sig1 = [s.signature() for s in s1]
        sig2 = [s.signature() for s in s2]
        # identical arrival times AND per-request sampling draws —
        # prompts, budgets, deadlines, temperature/top-k/seed
        assert sig1 == sig2
        # ... while request_ids are fresh (process-global by design)
        assert [a.request.request_id for a in s1] != \
            [a.request.request_id for a in s2]

    def test_different_seed_differs(self):
        s1 = TrafficGenerator(
            Scenario.from_dict(_scenario_dict(seed=1))).schedule()
        s2 = TrafficGenerator(
            Scenario.from_dict(_scenario_dict(seed=2))).schedule()
        assert [a.signature() for a in s1] != [a.signature() for a in s2]

    def test_schedule_is_time_ordered_and_phased(self):
        d = _scenario_dict(phases=[
            {"name": "a", "n_requests": 5, "rate_rps": 100.0,
             "prompt_lens": {"4": 1}, "max_new_tokens": {"3": 1}},
            {"name": "b", "n_requests": 7, "rate_rps": 100.0,
             "prompt_lens": {"8": 1}, "max_new_tokens": {"2": 1}}])
        sched = TrafficGenerator(Scenario.from_dict(d)).schedule()
        assert len(sched) == 12
        times = [s.at_s for s in sched]
        assert times == sorted(times)
        assert all(t > 0 for t in times)
        assert [s.phase for s in sched] == ["a"] * 5 + ["b"] * 7
        assert all(len(s.request.prompt) == 4 for s in sched[:5])
        assert all(len(s.request.prompt) == 8 for s in sched[5:])

    def test_adapter_mix_deterministic_and_isolated(self):
        """The adapter draw rides LAST in the per-request draw chain:
        same seed -> same adapter assignment (part of signature()), and
        an empty mix leaves the pre-LoRA schedule byte-identical."""
        lora_engine = {"max_slots": 4, "max_len": 32, "max_queue": 16,
                       "lora_rank": 4, "lora_adapters": 2}
        with_mix = _scenario_dict(engine=dict(lora_engine))
        with_mix["phases"][0]["n_requests"] = 30
        with_mix["phases"][0]["adapter_mix"] = {"0": 2, "1": 1, "base": 1}
        s1 = TrafficGenerator(Scenario.from_dict(with_mix)).schedule()
        s2 = TrafficGenerator(Scenario.from_dict(with_mix)).schedule()
        assert [s.signature() for s in s1] == [s.signature() for s in s2]
        aids = {s.request.sampling.adapter_id for s in s1}
        assert aids == {"0", "1", None}   # 30 draws at 2/1/1 hit all
        # enabling the store WITHOUT a mix changes nothing: the adapter
        # draw only exists when the phase declares one
        plain = TrafficGenerator(
            Scenario.from_dict(_scenario_dict())).schedule()
        stored = TrafficGenerator(Scenario.from_dict(
            _scenario_dict(engine=dict(lora_engine)))).schedule()
        assert [s.signature() for s in plain] == \
            [s.signature() for s in stored]
        assert all(s.request.sampling.adapter_id is None for s in stored)

    def test_mixes_are_honored(self):
        d = _scenario_dict(phases=[
            {"name": "p", "n_requests": 40, "rate_rps": 100.0,
             "prompt_lens": {"4": 1, "8": 1},
             "max_new_tokens": {"3": 1, "5": 1},
             "deadline_fraction": 1.0, "deadline_min_s": 2.0,
             "deadline_max_s": 3.0, "greedy_fraction": 0.0,
             "temperatures": [0.9], "top_ks": [8]}])
        reqs = TrafficGenerator(Scenario.from_dict(d)).requests()
        assert {len(r.prompt) for r in reqs} == {4, 8}
        assert {r.max_new_tokens for r in reqs} == {3, 5}
        assert all(2.0 <= r.deadline_s <= 3.0 for r in reqs)
        assert all(r.sampling.temperature == 0.9 for r in reqs)
        assert all(r.sampling.top_k == 8 for r in reqs)
        assert all(0 <= t < 64 for r in reqs for t in r.prompt)


# ---------------------------------------------------------------------------
# SLO scoring (synthetic records — no engine, no jit)


def _req(reason="length", ttft=None, tpot=None, total=None, wall=0.0):
    r = {"kind": "request", "request_id": 0, "finish_reason": reason,
         "prompt_len": 4, "new_tokens": 3, "wall": wall}
    if ttft is not None:
        r["ttft_s"] = ttft
    if tpot is not None:
        r["tpot_s"] = tpot
    if total is not None:
        r["total_s"] = total
    return r


class TestSLOScoring:
    def test_hand_computed_metrics(self):
        records = [
            _req(ttft=0.1, tpot=0.01, total=0.5),
            _req(ttft=0.2, tpot=0.02, total=1.0),
            _req(ttft=0.4, tpot=0.04, total=2.0),
            _req(reason="error"),
            _req(reason="rejected"),
        ]
        m = measure_slo_metrics(records)
        assert m["ttft_p50_s"] == 0.2         # nearest-rank over 3 values
        assert m["ttft_p99_s"] == 0.4
        assert m["latency_p99_s"] == 2.0
        assert m["goodput"] == pytest.approx(3 / 5)
        assert m["error_budget"] == pytest.approx(1 / 5)
        assert m["recovery_s"] is None        # no disruption events

    def test_recovery_finite_then_infinite(self):
        ev = {"kind": "event", "event": "engine_restart", "wall": 10.0}
        done = _req(total=0.5, wall=12.5)
        m = measure_slo_metrics([ev, done])
        assert m["recovery_s"] == pytest.approx(2.5)
        # breaker_open counts as a disruption too
        m = measure_slo_metrics([
            {"kind": "event", "event": "breaker_open", "wall": 11.0}, done])
        assert m["recovery_s"] == pytest.approx(1.5)
        # no completion after the disruption: never recovered
        m = measure_slo_metrics([ev, _req(total=0.5, wall=9.0)])
        assert m["recovery_s"] == float("inf")

    def test_directions_and_verdict(self):
        records = [_req(ttft=0.2, total=1.0), _req(reason="error")]
        rep = evaluate_slos(records, SLOSpec.from_dict(
            {"ttft_p99_s": 0.5, "goodput": 0.9, "error_budget": 0.0}))
        by = {o.name: o for o in rep.objectives}
        assert by["ttft_p99_s"].ok            # 0.2 <= 0.5
        assert not by["goodput"].ok           # 0.5 < 0.9
        assert not by["error_budget"].ok      # 0.5 > 0.0
        assert not rep.ok and len(rep.failures) == 2

    def test_declared_objective_without_data_fails(self):
        # a pre-TTFT log cannot pass a TTFT objective — no data is a
        # failure, not a silent green
        rep = evaluate_slos([_req()], SLOSpec.from_dict(
            {"ttft_p99_s": 1.0}))
        assert not rep.ok
        assert rep.objectives[0].measured is None

    def test_unknown_metric_rejected(self):
        with pytest.raises(ValueError, match="unknown SLO metric"):
            SLOSpec.from_dict({"vibes": 1.0})


# ---------------------------------------------------------------------------
# the regression gate


class TestGate:
    def test_direction_aware_comparison(self):
        baseline = {"ttft_p99_s": 1.0, "goodput": 0.9}
        # within tolerance both ways
        assert not compare_to_baseline(
            {"ttft_p99_s": 1.2, "goodput": 0.8}, baseline, tolerance=0.25)
        # latency regression: grew past 1.25x
        regs = compare_to_baseline(
            {"ttft_p99_s": 1.3, "goodput": 0.9}, baseline, tolerance=0.25)
        assert [r.metric for r in regs] == ["ttft_p99_s"]
        # goodput regression: shrank past 0.75x
        regs = compare_to_baseline(
            {"ttft_p99_s": 1.0, "goodput": 0.6}, baseline, tolerance=0.25)
        assert [r.metric for r in regs] == ["goodput"]
        # improvements never fail
        assert not compare_to_baseline(
            {"ttft_p99_s": 0.1, "goodput": 1.0}, baseline, tolerance=0.25)

    def test_unmeasurable_baselined_metric_is_regression(self):
        regs = compare_to_baseline({"recovery_s": None},
                                   {"recovery_s": 2.0}, tolerance=0.5)
        assert regs and regs[0].measured is None
        assert "measured nothing" in regs[0].describe()

    def test_update_baseline_drops_unmeasured(self, tmp_path):
        path = str(tmp_path / "base.json")
        entry = update_baseline(path, "s", {
            "ttft_p99_s": 1.0, "recovery_s": None,
            "latency_p99_s": float("inf")})
        assert entry == {"ttft_p99_s": 1.0}
        assert load_baseline(path) == {"s": {"ttft_p99_s": 1.0}}
        # merge keeps other scenarios
        update_baseline(path, "t", {"goodput": 1.0})
        assert set(load_baseline(path)) == {"s", "t"}

    def test_malformed_baseline_rejected(self, tmp_path):
        path = tmp_path / "bad.json"
        path.write_text('{"s": 3}')
        with pytest.raises(ValueError, match="metric dicts"):
            load_baseline(str(path))


# ---------------------------------------------------------------------------
# gate CLI on synthetic fixtures (red paths FIRST-CLASS: the gate must
# fail on a violation, not only pass on the green path)


def _write_gate_fixture(tmp_path, *, ttft=0.01, slo_ttft=1.0):
    """A scenario file + a synthetic run log measuring ttft_p99_s=ttft."""
    scn = tmp_path / "scn.json"
    scn.write_text(json.dumps(_scenario_dict(
        name="gatecase", slo={"ttft_p99_s": slo_ttft, "goodput": 0.9},
        tolerance=0.25)))
    log = tmp_path / "run.jsonl"
    rows = [{"kind": "scenario", "name": "gatecase", "seed": 3,
             "slo": {"ttft_p99_s": slo_ttft, "goodput": 0.9},
             "wall": 1.0}]
    rows += [_req(ttft=ttft, tpot=0.001, total=ttft + 0.05,
                  wall=2.0 + i) for i in range(4)]
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    return str(scn), str(log)


class TestGateCLI:
    def test_green_path_exit_zero(self, tmp_path):
        scn, log = _write_gate_fixture(tmp_path)
        base = str(tmp_path / "base.json")
        assert loadtest_main([scn, "--from-log", log, "--baseline", base,
                              "--update-baseline"]) == EXIT_OK
        assert loadtest_main([scn, "--from-log", log, "--check",
                              "--baseline", base]) == EXIT_OK

    def test_gate_fails_on_slo_violation(self, tmp_path):
        # measured ttft 5.0 >> objective 1.0 -> exit 1
        scn, log = _write_gate_fixture(tmp_path, ttft=5.0, slo_ttft=1.0)
        rc = loadtest_main([scn, "--from-log", log, "--check",
                            "--baseline", str(tmp_path / "none.json")])
        assert rc == EXIT_SLO_VIOLATION

    def test_gate_fails_on_synthetic_regression(self, tmp_path):
        # SLOs pass (0.5 <= 1.0) but the committed baseline says 0.01:
        # a 50x latency growth must trip the tolerance gate -> exit 2
        scn, log = _write_gate_fixture(tmp_path, ttft=0.5, slo_ttft=1.0)
        base = tmp_path / "base.json"
        base.write_text(json.dumps(
            {"gatecase": {"ttft_p99_s": 0.01, "goodput": 1.0}}))
        rc = loadtest_main([scn, "--from-log", log, "--check",
                            "--baseline", str(base)])
        assert rc == EXIT_REGRESSION

    def test_missing_baseline_entry_exit_three(self, tmp_path):
        scn, log = _write_gate_fixture(tmp_path)
        base = tmp_path / "base.json"
        base.write_text(json.dumps({"other_scenario": {"goodput": 1.0}}))
        rc = loadtest_main([scn, "--from-log", log, "--check",
                            "--baseline", str(base)])
        assert rc == EXIT_NO_BASELINE

    @pytest.mark.slow
    def test_real_cli_red_path(self, tmp_path):
        """The actual ``python -m apex_tpu.loadtest --check`` process
        exits nonzero on the synthetic regression fixture (subprocess —
        slow tier; the in-process tests above cover the same exit codes
        through the same main())."""
        scn, log = _write_gate_fixture(tmp_path, ttft=0.5)
        base = tmp_path / "base.json"
        base.write_text(json.dumps({"gatecase": {"ttft_p99_s": 0.01}}))
        proc = subprocess.run(
            [sys.executable, "-m", "apex_tpu.loadtest", scn,
             "--from-log", log, "--check", "--baseline", str(base)],
            capture_output=True, text=True, timeout=180,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == EXIT_REGRESSION, proc.stderr
        assert "regressions" in proc.stdout


# ---------------------------------------------------------------------------
# the tier-1 smoke scenario: full pipeline + exact reconciliation


def _assert_reconciles(report):
    """Counter/record conservation: one offered arrival == one counted
    submit == one terminal kind="request" record, split by reason."""
    counters = report["counters"]
    req = report["requests"]
    by_reason = req["by_finish_reason"]
    assert set(by_reason) <= set(FINISH_REASONS)
    assert req["count"] == sum(by_reason.values())
    assert counters["requests_submitted"] == req["count"]
    for reason in FINISH_REASONS:
        assert counters[f"requests_{reason}"] == \
            by_reason.get(reason, 0), reason


class TestSmokeScenario:
    def test_smoke_pipeline_reconciles_and_scores(self, small, tmp_path,
                                                  capsys):
        """Acceptance: the committed smoke scenario runs the generator ->
        supervisor -> JSONL -> SLO-verdict pipeline in tier-1; the
        monitor's SLO section (human and --json) reconciles exactly with
        the registry counters and request records."""
        model, params = small
        scn = Scenario.load(os.path.join(SCENARIO_DIR, "smoke.json"))
        log = str(tmp_path / "smoke.jsonl")
        run = run_scenario(scn, model=model, params=params, log_path=log)
        assert not run.aborted
        assert run.submitted == scn.total_requests
        assert run.slo is not None and run.ok, run.slo.as_dict()
        assert run.metrics_by_name["ttft_p99_s"] is not None
        assert run.metrics_by_name["tpot_p99_s"] is not None

        report = build_report(log)
        _assert_reconciles(report)
        # every terminal result the runner returned is one log record
        assert report["requests"]["count"] == len(run.results)
        # the embedded scenario record scored the log by itself
        assert report["scenario"]["name"] == "smoke"
        assert report["slo"] is not None and report["slo"]["ok"]
        slo_names = [o["name"] for o in report["slo"]["objectives"]]
        assert slo_names == list(scn.slo)
        text = render_report(report)
        assert "slo verdict: PASS" in text
        assert "ttft" in text and "tpot" in text

        # the monitor CLI agrees byte-for-byte on the verdict (in-process
        # main() — the ``python -m apex_tpu.monitor`` subprocess shim is
        # covered by the serving/observability tier-1 tests)
        from apex_tpu.observability.report import main as monitor_main

        assert monitor_main([log, "--json"]) == 0
        cli = json.loads(capsys.readouterr().out)
        assert cli["slo"] == json.loads(json.dumps(report["slo"]))
        assert cli["counters"] == report["counters"]

        # and the loadtest gate goes green against a just-written
        # baseline (CLI plumbing on a real run log)
        scn_path = os.path.join(SCENARIO_DIR, "smoke.json")
        base = str(tmp_path / "base.json")
        assert loadtest_main([scn_path, "--from-log", log,
                              "--baseline", base,
                              "--update-baseline"]) == EXIT_OK
        assert loadtest_main([scn_path, "--from-log", log, "--check",
                              "--baseline", base]) == EXIT_OK

    @pytest.mark.slow  # full scenario rerun: slow tier (ROADMAP)

    def test_crash_recovery_reports_finite_recovery(self, small, tmp_path):
        """Acceptance: a ServingFaultInjector-scheduled engine crash
        yields a finite measured recovery-time SLO (scaled-down tier-1
        variant of the slow-tier crash_recovery scenario)."""
        model, params = small
        scn = Scenario.from_dict(_scenario_dict(
            name="mini-crash", seed=5,
            supervisor={"max_restarts_per_request": 4},
            # one prompt bucket: each engine incarnation compiles a
            # single prefill shape — keeps the restart cheap in tier-1
            phases=[{"name": "steady", "n_requests": 8,
                     "rate_rps": 100.0, "prompt_lens": {"4": 1},
                     "max_new_tokens": {"5": 1}}],
            faults={"decode_raise_calls": [5]},
            slo={"goodput": 0.99, "error_budget": 0.0,
                 "recovery_s": 120.0}))
        log = str(tmp_path / "crash.jsonl")
        run = run_scenario(scn, model=model, params=params, log_path=log)
        assert run.engine_restarts >= 1
        assert run.counters["requests_recovered"] >= 1
        recovery = run.metrics_by_name["recovery_s"]
        assert recovery is not None and 0 < recovery < float("inf")
        assert run.ok, run.slo.as_dict()
        report = build_report(log)
        _assert_reconciles(report)
        assert report["slo"]["ok"]
        by = {o["name"]: o for o in report["slo"]["objectives"]}
        assert by["recovery_s"]["measured"] == pytest.approx(recovery)


class TestRecorderInRunner:
    def test_clean_run_arms_recorder_dumps_nothing(self, small,
                                                   tmp_path):
        """A recorder-armed clean run ends with ZERO bundles and the
        bundles counter declared at zero — arming the recorder is free
        on a healthy run (ring boundedness itself is asserted in
        test_observability's TestFlightRecorder)."""
        model, params = small
        scn = Scenario.from_dict(_scenario_dict(
            name="mini-clean", recorder={"events_capacity": 8,
                                         "records_capacity": 8,
                                         "gauges_capacity": 4}))
        log = str(tmp_path / "clean.jsonl")
        run = run_scenario(scn, model=model, params=params,
                           log_path=log)
        assert not run.aborted
        assert run.bundles == [] and run.bundle_paths == []
        assert run.counters["bundles_dumped"] == 0
        # no bundle file appeared next to the log
        import glob as _glob
        assert _glob.glob(str(tmp_path / "*-bundle-*.json")) == []
        # the report's bundle section says armed-but-quiet
        report = build_report(log)
        assert report["bundles"] is not None
        assert report["bundles"]["count"] == 0
        assert "nothing fired" in render_report(report)


# ---------------------------------------------------------------------------
# full scenarios: slow tier


@pytest.mark.slow
class TestFullScenarios:
    def test_overload_sheds_and_holds_goodput(self, small, tmp_path):
        model, params = small
        scn = Scenario.load(os.path.join(SCENARIO_DIR, "overload.json"))
        log = str(tmp_path / "overload.jsonl")
        run = run_scenario(scn, model=model, params=params, log_path=log)
        assert not run.aborted
        report = build_report(log)
        _assert_reconciles(report)
        counters = run.counters
        # the burst actually overloaded: rejected work exists, errors do
        # not — overload becomes fast rejections, not failures
        assert counters["requests_rejected"] > 0
        assert counters["requests_error"] == 0
        assert run.metrics_by_name["goodput"] < 1.0
        assert run.ok, run.slo.as_dict()

    def test_crash_recovery_scenario(self, small, tmp_path):
        model, params = small
        scn = Scenario.load(
            os.path.join(SCENARIO_DIR, "crash_recovery.json"))
        log = str(tmp_path / "crash.jsonl")
        run = run_scenario(scn, model=model, params=params, log_path=log)
        assert not run.aborted
        # decode crash + hung tick: two disruptions, both recovered
        assert run.engine_restarts >= 2
        recovery = run.metrics_by_name["recovery_s"]
        assert recovery is not None and recovery < float("inf")
        assert run.ok, run.slo.as_dict()
        report = build_report(log)
        _assert_reconciles(report)
        inc = report["serving_incidents"]
        assert inc["counts"]["engine_restart"] == \
            report["counters"]["engine_restarts"]

    def test_bimodal_burst_scenario(self, small, tmp_path):
        model, params = small
        scn = Scenario.load(
            os.path.join(SCENARIO_DIR, "bimodal_burst.json"))
        log = str(tmp_path / "bimodal.jsonl")
        run = run_scenario(scn, model=model, params=params, log_path=log)
        assert not run.aborted
        assert run.counters["requests_error"] == 0
        assert run.ok, run.slo.as_dict()
        # the burst's long prompts actually chunked (48 and 56 tokens at
        # budget 16 => 3-4 page-aligned chunks), short traffic did not
        done = list(run.results.values())
        chunks = [r.prefill_chunks or 1 for r in done
                  if r.finish_reason in ("eos", "length")]
        assert max(chunks) >= 3
        assert min(chunks) == 1
        # chunk audit reconciles: the counter equals the per-request sum
        report = build_report(log)
        _assert_reconciles(report)
        assert report["counters"]["prefill_chunks"] == \
            sum(r.prefill_chunks or 0 for r in done)

    def test_latency_drift_fires_sentinel_and_dumps_one_bundle(
            self, small, tmp_path, capsys):
        """Acceptance (PR 18): the committed latency_drift scenario —
        decode hangs degrade the fleet mid-surge with no hard failure —
        makes the sentinel fire ``kind="anomaly"`` with counters
        reconciling key-for-key, dumps EXACTLY ONE bundle next to the
        run log, and ``monitor bundle`` renders it (human and --json)
        with the trigger inside the frozen ring window."""
        model, params = small
        scn = Scenario.load(
            os.path.join(SCENARIO_DIR, "latency_drift.json"))
        log = str(tmp_path / "drift.jsonl")
        run = run_scenario(scn, model=model, params=params,
                           log_path=log)
        assert not run.aborted
        # the drift never hard-failed anything...
        assert run.engine_restarts == 0
        assert run.counters["requests_error"] == 0
        assert run.ok, run.slo.as_dict()
        # ...yet the sentinel caught it, reconciling key-for-key
        counters = run.counters
        assert counters["anomalies_total"] >= 1
        assert counters["anomalies_queue_depth"] == \
            counters["anomalies_total"]
        report = build_report(log)
        _assert_reconciles(report)
        anomalies = report["anomalies"]
        assert anomalies is not None
        assert anomalies["count"] == counters["anomalies_total"]
        assert anomalies["counters"]["anomalies_total"] == \
            counters["anomalies_total"]
        assert anomalies["by_signal"] == {
            "queue_depth": counters["anomalies_total"]}
        # exactly one bundle, dumped next to the run log
        assert counters["bundles_dumped"] == 1
        assert len(run.bundles) == 1
        expected = str(tmp_path / "drift-bundle-1.json")
        assert run.bundle_paths == [expected]
        assert report["bundles"]["count"] == 1
        assert report["bundles"]["dumps"][0]["trigger"] == "anomaly"
        text = render_report(report)
        assert "drift anomalies" in text
        assert "postmortem bundles (1 dumped" in text
        # the gauge trajectory fed the report
        assert len(report["gauge_trajectory"]) >= 3
        assert "signal trajectory" in text

        # the bundle is self-contained and renders in both modes with
        # the trigger inside the ring window it froze
        bundle = json.loads(open(expected).read())
        assert bundle["trigger"]["event"] == "anomaly"
        assert bundle["trigger"]["signal"] == "queue_depth"
        assert any(e.get("event") == "anomaly"
                   for e in bundle["events"])
        assert len(bundle["replicas"]) == 2
        from apex_tpu.observability.report import main as monitor_main

        assert monitor_main(["bundle", expected]) == 0
        human = capsys.readouterr().out
        assert "trigger: anomaly" in human and ">>" in human
        assert monitor_main(["bundle", expected, "--json"]) == 0
        assert json.loads(capsys.readouterr().out)["kind"] == \
            "flight_bundle"
