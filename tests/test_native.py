"""C++ host-runtime tests: flatten/unflatten, bucket planning, staging pool,
token queue, prefetch loader.

Mirrors the role of the reference's ``apex_C`` flatten plumbing
(``csrc/flatten_unflatten.cpp``) and DDP bucket bookkeeping
(``apex/parallel/distributed.py:366-390``); the loader test checks ordering
and completeness the way a DataLoader smoke test would.
"""

import threading
import time

import numpy as np
import pytest

from apex_tpu import native
from apex_tpu.data import PrefetchLoader


class TestBuild:
    def test_native_available(self):
        # g++ is baked into the image; the C++ path must actually build —
        # if this fails the rest silently tests only the numpy fallback
        assert native.available()


class TestFlatten:
    def test_roundtrip_mixed_dtypes(self):
        arrays = [
            np.arange(7, dtype=np.float32),
            np.ones((3, 5), dtype=np.float64),
            (np.arange(12).reshape(3, 4) % 5).astype(np.int32),
            np.random.default_rng(0).standard_normal((2, 2, 2)).astype(
                np.float16),
        ]
        flat = native.flatten(arrays)
        assert flat.dtype == np.uint8
        assert flat.nbytes == sum(a.nbytes for a in arrays)
        back = native.unflatten(flat, arrays)
        for a, b in zip(arrays, back):
            np.testing.assert_array_equal(a, b)

    def test_large_parallel_path(self):
        # > 8 MiB total triggers the multithreaded memcpy branch
        arrays = [np.random.default_rng(i).standard_normal(
            1 << 20).astype(np.float32) for i in range(4)]
        flat = native.flatten(arrays)
        back = native.unflatten(flat, arrays)
        for a, b in zip(arrays, back):
            np.testing.assert_array_equal(a, b)

    def test_empty_list(self):
        assert native.flatten([]).nbytes == 0
        assert native.unflatten(np.empty(0, np.uint8), []) == []

    def test_size_mismatch_raises(self):
        with pytest.raises(ValueError):
            native.unflatten(np.zeros(3, np.uint8),
                             [np.zeros(1, np.float32)])


class TestBucketPlan:
    def test_arrival_order_capped(self):
        # 4-byte cap -> greedy fill in arrival order
        ids = native.bucket_plan([2, 2, 2, 2], cap_bytes=4)
        np.testing.assert_array_equal(ids, [0, 0, 1, 1])

    def test_oversized_tensor_gets_own_bucket(self):
        ids = native.bucket_plan([10, 1, 1], cap_bytes=4)
        assert ids[0] == 0
        assert ids[1] == 1 and ids[2] == 1

    def test_monotone_ids(self):
        rng = np.random.default_rng(3)
        sizes = rng.integers(1, 100, size=50).tolist()
        ids = native.bucket_plan(sizes, cap_bytes=128)
        assert (np.diff(ids) >= 0).all()
        # every bucket except possibly each closing tensor respects the cap
        for b in np.unique(ids):
            members = [s for s, i in zip(sizes, ids) if i == b]
            assert sum(members[:-1]) < 128 or len(members) == 1


class TestTokenQueue:
    def test_fifo(self):
        q = native.TokenQueue(4)
        for i in range(4):
            assert q.put(i)
        assert len(q) == 4
        assert [q.get() for _ in range(4)] == [0, 1, 2, 3]

    def test_blocking_handoff(self):
        q = native.TokenQueue(1)
        seen = []

        def consumer():
            while True:
                t = q.get()
                if t is None:
                    return
                seen.append(t)

        th = threading.Thread(target=consumer)
        th.start()
        for i in range(20):
            q.put(i)
        q.close()
        th.join(timeout=10)
        assert seen == list(range(20))

    def test_get_timeout(self):
        q = native.TokenQueue(1)
        with pytest.raises(TimeoutError):
            q.get(timeout_ms=50)

    def test_close_unblocks_get(self):
        q = native.TokenQueue(1)
        out = {}

        def getter():
            out["v"] = q.get()

        th = threading.Thread(target=getter)
        th.start()
        time.sleep(0.05)
        q.close()
        th.join(timeout=5)
        assert out["v"] is None


class TestPrefetchLoader:
    def test_yields_all_batches_in_order_single_worker(self):
        batches = [{"x": np.full((2,), i)} for i in range(10)]
        out = list(PrefetchLoader(batches, prefetch=3))
        assert len(out) == 10
        for i, b in enumerate(out):
            np.testing.assert_array_equal(b["x"], np.full((2,), i))

    def test_multi_worker_complete(self):
        n = 24
        loader = PrefetchLoader((np.full(3, i) for i in range(n)),
                                prefetch=4, num_workers=3)
        got = sorted(int(b[0]) for b in loader)
        assert got == list(range(n))

    def test_device_put_hook_applied(self):
        calls = []

        def put(b):
            calls.append(1)
            return b * 2

        out = list(PrefetchLoader([np.ones(2)] * 4, prefetch=2,
                                  device_put=put))
        assert len(out) == 4 and len(calls) == 4
        for b in out:
            np.testing.assert_array_equal(b, 2 * np.ones(2))

    def test_reiterable(self):
        loader = PrefetchLoader(lambda: iter([np.zeros(1), np.ones(1)]),
                                prefetch=2)
        assert len(list(loader)) == 2
        assert len(list(loader)) == 2

    def test_overlaps_producer_and_consumer(self):
        # with prefetch, producer sleeps overlap consumer sleeps: compare
        # against a serial run measured in the same environment so machine
        # load can't flake the bound
        def gen():
            for i in range(6):
                time.sleep(0.05)
                yield np.full(1, i)

        t0 = time.perf_counter()
        serial = []
        for b in gen():
            time.sleep(0.05)
            serial.append(b)
        t_serial = time.perf_counter() - t0

        t0 = time.perf_counter()
        out = []
        for b in PrefetchLoader(gen, prefetch=4):
            time.sleep(0.05)      # consumer "compute"
            out.append(b)
        t_overlap = time.perf_counter() - t0
        assert len(out) == 6
        assert t_overlap < 0.85 * t_serial, \
            f"no overlap: {t_overlap:.3f}s vs serial {t_serial:.3f}s"


class TestStagingPool:
    def test_stats_and_trim(self):
        if not native.available():
            pytest.skip("native runtime unavailable")
        out0, pooled0 = native.staging_stats()
        native.staging_trim()
        out1, pooled1 = native.staging_stats()
        assert pooled1 == 0
        assert out1 == out0

    def test_staging_buffer_pool_reuse(self):
        if not native.available():
            pytest.skip("native runtime unavailable")
        import gc
        native.staging_trim()
        buf = native.staging_buffer(1 << 16)
        buf[:4] = [1, 2, 3, 4]
        del buf
        gc.collect()
        _, pooled = native.staging_stats()
        assert pooled >= 1 << 16      # buffer went back to the pool
        buf2 = native.staging_buffer(1 << 16)   # and is reused
        _, pooled2 = native.staging_stats()
        assert pooled2 == pooled - (1 << 16 if pooled >= (1 << 16) else 0)
        del buf2
        native.staging_trim()


class TestLoaderRobustness:
    def test_worker_exception_propagates(self):
        def gen():
            yield np.zeros(1)
            raise OSError("corrupt shard")

        with pytest.raises(OSError, match="corrupt shard"):
            list(PrefetchLoader(gen, prefetch=2))

    def test_abandoned_iterator_leaks_no_threads(self):
        before = threading.active_count()
        it = iter(PrefetchLoader([np.zeros(1)] * 100, prefetch=2))
        del it      # never advanced: generator never started -> no threads
        assert threading.active_count() == before

    def test_early_break_joins_workers(self):
        before = threading.active_count()
        for b in PrefetchLoader([np.zeros(1)] * 50, prefetch=2,
                                num_workers=2):
            break
        time.sleep(0.3)
        assert threading.active_count() <= before + 1

    def test_view_of_staging_buffer_survives_base_collection(self):
        if not native.available():
            pytest.skip("native runtime unavailable")
        import gc
        native.staging_trim()
        view = native.staging_buffer(4096)[:16]
        view[:] = np.arange(16, dtype=np.uint8)
        gc.collect()
        # buffer must NOT have returned to the pool while the view lives
        _, pooled = native.staging_stats()
        clobber = native.staging_buffer(4096)   # would reuse if freed
        clobber[:] = 0xFF
        np.testing.assert_array_equal(view, np.arange(16, dtype=np.uint8))
        del clobber, view
        native.staging_trim()
