"""Reshape-restore TRAINING parity (ISSUE 9 acceptance): a run saved
under dp=4×tp=2 on the 8-device CPU mesh, restored under dp=2×tp=4 and
under a single device, must continue training to the same final params
as an uninterrupted run — the checkpoint is the state, not the topology.

Slow tier: each topology is its own shard_map jit compile, which is
what dominates the wall clock (the actual training is a 4×8 matmul).
The cheap manager-level reshape-restore equality checks live in
``test_checkpoint_sharded.py``.
"""

import shutil

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.optimizers import FusedSGD
from apex_tpu.resilience import (
    ResilienceConfig,
    make_resilient_train_step,
    make_train_state,
    run_training,
)

pytestmark = pytest.mark.slow

D_GLOBAL = 8                       # feature width, sharded by "tensor"
W_TRUE = jnp.linspace(-0.5, 0.5, 4 * D_GLOBAL).reshape(4, D_GLOBAL)


def _loss(p, batch, rng):
    pred = batch["x"] @ p["w"]     # (B_loc, 4) @ (4, D_loc)
    se = jnp.sum((pred - batch["y"]) ** 2)
    try:
        # per-rank rows only see the local feature columns; the global
        # mean needs the squared error summed across the tensor axis.
        # Value-only (stop_gradient): d se/d w[:, local] has no
        # cross-tensor term, and under check_rep=False a differentiable
        # psum would transpose to another psum, scaling grads by tp
        se = se + lax.stop_gradient(lax.psum(se, "tensor") - se)
    except NameError:
        pass                       # single-device path: already global
    return se / (batch["x"].shape[0] * D_GLOBAL)


def _batch(step):
    x = jax.random.normal(jax.random.PRNGKey(step), (8, 4))
    return {"x": x, "y": x @ W_TRUE}


def _mesh(rows, cols):
    devs = np.array(jax.devices()[:rows * cols]).reshape(rows, cols)
    return Mesh(devs, ("data", "tensor"))


def _make(mesh):
    """(step_fn, fresh state) for one topology; params deterministic so
    every topology starts from the identical point."""
    opt = FusedSGD(lr=0.05)
    w0 = jnp.linspace(-1.0, 1.0, 4 * D_GLOBAL).reshape(4, D_GLOBAL)
    if mesh is None:
        params = {"w": w0}
        step_fn = make_resilient_train_step(_loss, opt)
    else:
        params = {"w": jax.device_put(
            w0, NamedSharding(mesh, P(None, "tensor")))}
        step_fn = make_resilient_train_step(
            _loss, opt, mesh=mesh,
            param_spec={"w": P(None, "tensor")},
            batch_spec={"x": P("data", None), "y": P("data", "tensor")},
            params_template=params)
    return step_fn, make_train_state(params, opt.init(params))


def _cfg(**kw):
    base = dict(poll_interval_steps=2, save_interval_steps=4,
                min_history=4, save_backoff_base=0.0,
                handle_sigterm=False)
    base.update(kw)
    return ResilienceConfig(**base)


def _final_w(result):
    return np.asarray(jax.device_get(result.state["params"]["w"]))


class TestReshapeTrainingParity:
    @pytest.fixture(scope="class")
    def reference(self, tmp_path_factory):
        """Uninterrupted 12 steps on dp=4×tp=2, plus a checkpointed run
        stopped at step 8 (committed steps 4 and 8) to resume from."""
        ckpt = str(tmp_path_factory.mktemp("parity") / "ckpt")
        step_fn, state = _make(_mesh(4, 2))
        ref = run_training(step_fn, state, _batch, 12, config=_cfg())
        step_fn, state = _make(_mesh(4, 2))
        part = run_training(step_fn, state, _batch, 8,
                            checkpoint_dir=ckpt,
                            config=_cfg(save_final=False))
        assert part.steps_completed == 8
        return {"ref": ref, "ckpt": ckpt}

    @pytest.mark.parametrize("target", ["dp2tp4", "single"])
    def test_resume_on_new_topology_matches_uninterrupted(
            self, reference, target, tmp_path):
        # each target resumes from its own COPY of the saved run — a
        # resume writes new checkpoints, which must not leak between
        # parametrizations
        ckpt = str(tmp_path / "ckpt")
        shutil.copytree(reference["ckpt"], ckpt)
        mesh = _mesh(2, 4) if target == "dp2tp4" else None
        step_fn, state = _make(mesh)
        res = run_training(step_fn, state, _batch, 12,
                           checkpoint_dir=ckpt, config=_cfg())
        assert res.status == "completed"
        assert res.telemetry["resumes"] == 1
        assert res.steps_completed == 12

        ref = reference["ref"]
        np.testing.assert_allclose(_final_w(res), _final_w(ref),
                                   rtol=1e-5, atol=1e-6)
        # the continued steps replay the reference loss curve, not just
        # its endpoint
        ref_losses = {h["step"]: h["loss"] for h in ref.history}
        for h in res.history:
            np.testing.assert_allclose(h["loss"], ref_losses[h["step"]],
                                       rtol=1e-5, atol=1e-7)
