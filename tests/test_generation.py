"""KV-cache generation tests.

Correctness anchor: incrementally-decoded logits must match the full
(non-cached) forward pass position by position — the property that makes a
KV cache a cache and not an approximation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.models.generation import decode_step, generate, init_kv_caches
from apex_tpu.utils.sharding import shard_map


def _model(**kw):
    d = dict(num_layers=2, hidden_size=32, num_attention_heads=4,
             vocab_size=64, max_position_embeddings=32,
             hidden_dropout=0.0, attention_dropout=0.0)
    d.update(kw)
    return GPTModel(TransformerConfig(**d))


class TestDecodeStep:
    @pytest.mark.slow
    def test_cached_logits_match_full_forward(self):
        model = _model()
        params = model.init(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 64)
        # full forward logits [s, b, V]
        full = model.apply(params, tokens)
        caches = init_kv_caches(model, 2, 16)
        for i in range(10):
            logits, caches = decode_step(model, params, caches,
                                         tokens[:, i], i)
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(full[i]).astype(np.float32),
                rtol=2e-4, atol=2e-4)

    @pytest.mark.slow
    def test_moe_cached_logits_match_full_forward(self):
        # TRAINING-DEFAULT capacity factor (1.25): the cache path routes
        # drop-free (round 5), and the matching baseline is the drop-free
        # serving forward — parity is unconditional in the factor, where
        # round 4 needed capacity_factor = num_experts to avoid drops
        model = _model(num_moe_experts=4, moe_top_k=2)
        assert model.config.moe_capacity_factor == 1.25  # the default
        params = model.init(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)
        full = model.apply(params, tokens, moe_drop_free=True)
        caches = init_kv_caches(model, 2, 12)
        for i in range(8):
            logits, caches = decode_step(model, params, caches,
                                         tokens[:, i], i)
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(full[i]).astype(np.float32),
                rtol=2e-4, atol=2e-4)
        # the prefill (cached, batched) agrees with the decode steps too
        from apex_tpu.models.generation import _cached_forward
        caches2 = init_kv_caches(model, 2, 12)
        pre, _ = _cached_forward(model, params, caches2, tokens, 0)
        np.testing.assert_allclose(np.asarray(pre),
                                   np.asarray(full).astype(np.float32),
                                   rtol=2e-4, atol=2e-4)

    @pytest.mark.slow
    def test_moe_generate_runs(self):
        model = _model(num_moe_experts=4, moe_capacity_factor=4.0)
        params = model.init(jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 3), 0, 64)
        out = generate(model, params, prompt, max_new_tokens=4)
        assert out.shape == (2, 7)

    def test_cache_smaller_than_positions_guard(self):
        model = _model()
        params = model.init(jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 4), 0, 64)
        with pytest.raises(ValueError):
            generate(model, params, prompt, max_new_tokens=8, max_len=6)


class TestGenerate:
    @pytest.mark.slow
    def test_greedy_matches_stepwise_argmax(self):
        model = _model()
        params = model.init(jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0, 64)
        out = generate(model, params, prompt, max_new_tokens=5)
        assert out.shape == (2, 9)
        np.testing.assert_array_equal(np.asarray(out[:, :4]),
                                      np.asarray(prompt))
        # reference: recompute greedily with full forwards
        cur = prompt
        for _ in range(5):
            logits = model.apply(params, cur)       # [s, b, V]
            nxt = jnp.argmax(logits[-1], axis=-1).astype(prompt.dtype)
            cur = jnp.concatenate([cur, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))

    def test_generate_jits(self):
        model = _model()
        params = model.init(jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 3), 0, 64)
        f = jax.jit(lambda p, t: generate(model, p, t, max_new_tokens=4))
        out = f(params, prompt)
        assert out.shape == (1, 7)

    @pytest.mark.slow
    def test_sampling_reproducible_and_varied(self):
        model = _model()
        params = model.init(jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 3), 0, 64)
        r = jax.random.PRNGKey(7)
        o1 = generate(model, params, prompt, max_new_tokens=6,
                      temperature=1.0, rng=r)
        o2 = generate(model, params, prompt, max_new_tokens=6,
                      temperature=1.0, rng=r)
        np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
        o3 = generate(model, params, prompt, max_new_tokens=6,
                      temperature=1.0, rng=jax.random.PRNGKey(8))
        assert not np.array_equal(np.asarray(o1), np.asarray(o3))

    @pytest.mark.slow
    def test_top_k_restricts_support(self):
        model = _model()
        params = model.init(jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 3), 0, 64)
        # top_k=1 sampling == greedy
        o_top1 = generate(model, params, prompt, max_new_tokens=5,
                          temperature=1.0, top_k=1,
                          rng=jax.random.PRNGKey(2))
        o_greedy = generate(model, params, prompt, max_new_tokens=5)
        np.testing.assert_array_equal(np.asarray(o_top1),
                                      np.asarray(o_greedy))

    def test_sampling_requires_rng(self):
        model = _model()
        params = model.init(jax.random.PRNGKey(0))
        prompt = jnp.zeros((1, 2), jnp.int32)
        with pytest.raises(ValueError):
            generate(model, params, prompt, max_new_tokens=2,
                     temperature=0.7)

    def test_eos_freezes_rows(self):
        model = _model()
        params = model.init(jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 3), 0, 64)
        greedy = generate(model, params, prompt, max_new_tokens=8)
        first = int(greedy[0, 3])      # force the first generated token
        out = generate(model, params, prompt, max_new_tokens=8,
                       eos_token=first)
        # once eos is emitted every later token is eos
        gen = np.asarray(out[0, 3:])
        hit = np.where(gen == first)[0]
        assert hit.size > 0
        assert (gen[hit[0]:] == first).all()


class TestGuards:
    def test_max_new_tokens_zero_rejected(self):
        """max_new_tokens=0 would make total == prompt_len, so the
        first-token write (out.at[:, prompt_len]) silently clamps onto
        the last prompt slot — must raise instead."""
        model = _model()
        params = model.init(jax.random.PRNGKey(0))
        prompt = jnp.zeros((1, 3), jnp.int32)
        with pytest.raises(ValueError, match="max_new_tokens"):
            generate(model, params, prompt, max_new_tokens=0)

    def test_top_k_below_one_rejected(self):
        model = _model()
        params = model.init(jax.random.PRNGKey(0))
        prompt = jnp.zeros((1, 3), jnp.int32)
        with pytest.raises(ValueError, match="top_k"):
            generate(model, params, prompt, max_new_tokens=2,
                     temperature=1.0, top_k=0, rng=jax.random.PRNGKey(0))

    def test_position_overflow_rejected(self):
        model = _model()   # max_position_embeddings=32
        params = model.init(jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 30), 0, 64)
        with pytest.raises(ValueError, match="max_position_embeddings"):
            generate(model, params, prompt, max_new_tokens=10)

    @pytest.mark.slow
    def test_tp_generation_matches_single_rank(self):
        """Greedy generation under TP == unsharded (full-vocab argmax after
        the vocab all-gather)."""
        from jax.sharding import PartitionSpec as P

        from apex_tpu.transformer import parallel_state

        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0, 64)
        model = _model()
        params = model.init(jax.random.PRNGKey(0))
        ref = generate(model, params, prompt, max_new_tokens=5)

        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=2)
        out = shard_map(
            lambda p, t: generate(model, p, t, max_new_tokens=5),
            mesh=mesh, in_specs=(model.spec(), P()), out_specs=P(),
            check_vma=False)(params, prompt)
        parallel_state.destroy_model_parallel()
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


class TestCacheForms:
    @pytest.mark.slow
    def test_stacked_and_list_caches_agree(self):
        """The scan-form (stacked [L,...]) and the fast decode form
        (per-layer list, PERF.md round 4) must produce identical logits
        through prefill AND stepwise decode."""
        from apex_tpu.models.generation import _cached_forward

        model = _model()
        params = model.init(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 64)
        stacked = init_kv_caches(model, 2, 16)
        listed = init_kv_caches(model, 2, 16, stacked=False)
        assert isinstance(listed, list) and len(listed) == 2
        # prefill over 6 tokens, then 4 incremental steps, on both forms
        l_s, stacked = _cached_forward(model, params, stacked,
                                       tokens[:, :6], 0)
        l_l, listed = _cached_forward(model, params, listed,
                                      tokens[:, :6], 0)
        np.testing.assert_allclose(np.asarray(l_s), np.asarray(l_l),
                                   rtol=1e-5, atol=1e-5)
        for i in range(6, 10):
            l_s, stacked = decode_step(model, params, stacked,
                                       tokens[:, i], i)
            l_l, listed = decode_step(model, params, listed,
                                      tokens[:, i], i)
            np.testing.assert_allclose(np.asarray(l_s), np.asarray(l_l),
                                       rtol=1e-5, atol=1e-5)
        # cache contents agree leaf-for-leaf
        for l, (k_l, v_l) in enumerate(listed):
            np.testing.assert_allclose(np.asarray(stacked[0][l]),
                                       np.asarray(k_l), atol=1e-6)
            np.testing.assert_allclose(np.asarray(stacked[1][l]),
                                       np.asarray(v_l), atol=1e-6)

    @pytest.mark.slow
    def test_flat_caches_agree(self):
        """The FLAT [b, S, h*d] decode form (PERF.md round 5) must match
        the 4D list form through prefill and stepwise decode."""
        from apex_tpu.models.generation import _cached_forward

        model = _model()
        params = model.init(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 64)
        listed = init_kv_caches(model, 2, 16, stacked=False)
        flat = init_kv_caches(model, 2, 16, stacked=False, flat=True)
        assert flat[0][0].ndim == 3
        l_l, listed = _cached_forward(model, params, listed,
                                      tokens[:, :6], 0)
        l_f, flat = _cached_forward(model, params, flat, tokens[:, :6], 0)
        np.testing.assert_allclose(np.asarray(l_l), np.asarray(l_f),
                                   rtol=1e-5, atol=1e-5)
        for i in range(6, 10):
            l_l, listed = decode_step(model, params, listed,
                                      tokens[:, i], i)
            l_f, flat = decode_step(model, params, flat, tokens[:, i], i)
            np.testing.assert_allclose(np.asarray(l_l), np.asarray(l_f),
                                       rtol=1e-5, atol=1e-5)

    def test_flat_cache_kv_lengths_masks_padding(self):
        """kv_lengths must mask pad slots on the FLAT path exactly as on
        the 4D path (the flat branch initially dropped it — r5 review)."""
        model = _model()
        params = model.init(jax.random.PRNGKey(0))
        c = model.config
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)
        kvl = jnp.array([5, 8], jnp.int32)
        emb = model.embedding.apply(
            params["embedding"]["word_embeddings"], tokens)
        hidden = emb.transpose(1, 0, 2)[:1]      # decode one position
        outs = {}
        flat_cache0 = None
        layer0 = jax.tree.map(lambda x: x[0],
                              params["transformer"]["layers"])
        from apex_tpu.models.generation import _cached_forward
        for name, flat in (("4d", False), ("flat", True)):
            caches = init_kv_caches(model, 2, 8, stacked=False, flat=flat)
            # prefill the cache with 8 tokens' K/V, then attend one query
            # with kv_lengths = [5, 8]: row 0 must ignore slots 5..7
            _, caches = _cached_forward(model, params, caches, tokens, 0)
            if flat:
                flat_cache0 = caches[0]
            out, _ = model.transformer.layer.attention.apply(
                layer0["self_attention"], hidden, kv_cache=caches[0],
                cache_index=7, kv_lengths=kvl)
            outs[name] = np.asarray(out)
        np.testing.assert_allclose(outs["4d"], outs["flat"],
                                   rtol=1e-5, atol=1e-5)
        # and kv_lengths actually changes the result (masking is live)
        out_nolen, _ = model.transformer.layer.attention.apply(
            layer0["self_attention"], hidden, kv_cache=flat_cache0,
            cache_index=7, kv_lengths=None)
        assert not np.allclose(outs["flat"], np.asarray(out_nolen),
                               atol=1e-6)
