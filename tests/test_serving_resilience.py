"""Serving-resilience tests: supervisor recovery, quarantine, admission.

The robustness contract on top of test_serving.py's correctness anchor:
under injected faults (poisoned slot, decode/prefill exceptions with
engine restart, hung tick) every submitted request reaches a terminal
state — no request silently lost, no slot leaks — and unaffected
co-tenants stay TOKEN-EXACT against a fault-free greedy run. Overload
is bounded: the circuit breaker fails submits fast while open, deadline
shedding rejects doomed work at the edge, and every incident reconciles
key-for-key between the monitor report and the registry counters.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.models.generation import generate
from apex_tpu.observability import (
    InMemorySink,
    JsonlSink,
    MetricsRegistry,
    build_report,
    render_report,
)
from apex_tpu.observability.report import (
    SERVING_INCIDENT_COUNTERS,
    SERVING_SHED_COUNTERS,
)
from apex_tpu.serving import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    DeadlineExpiredError,
    EngineConfig,
    EngineSupervisor,
    EngineUnavailableError,
    FINISH_REASONS,
    InferenceEngine,
    Request,
    SamplingParams,
    SlotError,
    SlotPool,
    SupervisorConfig,
)
from apex_tpu.testing_faults import InjectedEngineFault, ServingFaultInjector


@pytest.fixture(scope="module")
def small():
    # 1 layer on purpose: these tests build MANY engines (every
    # supervisor restart recompiles prefill+decode), and recovery
    # semantics do not depend on depth — compile cost does
    model = GPTModel(TransformerConfig(
        num_layers=1, hidden_size=32, num_attention_heads=4, vocab_size=64,
        max_position_embeddings=64, hidden_dropout=0.0,
        attention_dropout=0.0))
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _prompts(lens, seed=7):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 64, size=n).tolist() for n in lens]


def _expected_greedy(model, params, request, max_len):
    out = generate(model, params, jnp.asarray([request.prompt], jnp.int32),
                   request.max_new_tokens, max_len=max_len,
                   eos_token=request.eos_token)
    toks = np.asarray(out[0, request.prompt_len:]).tolist()
    if request.eos_token is not None and request.eos_token in toks:
        toks = toks[:toks.index(request.eos_token) + 1]
    return toks


class TestSlotPoolReset:
    def test_reset_rebuilds_free_list(self):
        pool = SlotPool(3)
        for _ in range(3):
            pool.allocate()
        assert pool.free_count == 0
        pool.reset()
        assert pool.free_count == 3 and pool.active_count == 0
        pool.check()
        # deterministic lowest-first order is restored too
        assert [pool.allocate() for _ in range(3)] == [0, 1, 2]

    def test_reset_idempotent_on_clean_pool(self):
        pool = SlotPool(2)
        pool.reset()
        pool.reset()
        pool.check()
        assert pool.free_count == 2

    def test_double_release_still_raises_after_reset(self):
        pool = SlotPool(2)
        s = pool.allocate()
        pool.reset()
        with pytest.raises(SlotError):
            pool.release(s)


class TestContextManagers:
    def test_engine_context_manager_releases_slots(self, small):
        model, params = small
        with InferenceEngine(model, params,
                             EngineConfig(max_slots=2, max_len=16)) as eng:
            eng.submit(Request(prompt=_prompts([3])[0], max_new_tokens=8))
            eng.tick()               # prefill holds a slot
            assert eng.active_count == 1
        eng.slots.check()
        assert eng.slots.free_count == 2
        eng.close()                  # second close is a no-op
        with pytest.raises(RuntimeError, match="closed"):
            eng.submit(Request(prompt=[1], max_new_tokens=1))
        with pytest.raises(RuntimeError, match="closed"):
            eng.tick()

    def test_engine_closes_on_exception_path(self, small):
        model, params = small
        with pytest.raises(ValueError):
            with InferenceEngine(model, params,
                                 EngineConfig(max_slots=1,
                                              max_len=16)) as eng:
                raise ValueError("boom")
        assert eng.slots.free_count == 1

    def test_supervisor_context_manager(self, small):
        model, params = small
        with EngineSupervisor(model, params,
                              EngineConfig(max_slots=1, max_len=16)) as sup:
            (res,) = sup.serve([Request(prompt=_prompts([3])[0],
                                        max_new_tokens=2)])
            assert res.finish_reason == "length"
        sup.close()                  # idempotent
        with pytest.raises(RuntimeError, match="closed"):
            sup.submit(Request(prompt=[1], max_new_tokens=1))


class TestDeadlineFastFail:
    def test_expired_at_submit_rejected_not_queued(self, small):
        model, params = small
        sink = InMemorySink()
        eng = InferenceEngine(model, params,
                              EngineConfig(max_slots=1, max_len=16),
                              metrics=MetricsRegistry([sink]))
        stale = Request(prompt=_prompts([3])[0], max_new_tokens=2,
                        deadline_s=0.05,
                        arrival_ts=time.monotonic() - 1.0)
        with pytest.raises(DeadlineExpiredError):
            eng.submit(stale)
        assert eng.queued_count == 0          # never queued
        res = eng.completed[stale.request_id]
        assert res.finish_reason == "rejected" and res.tokens == []
        assert eng.metrics.counters()["requests_rejected"] == 1
        events = [r for r in sink.of_kind("event")
                  if r.get("event") == "request_rejected"]
        assert events and events[0]["reason"] == "deadline_expired"

    def test_fresh_deadline_still_queues(self, small):
        model, params = small
        eng = InferenceEngine(model, params,
                              EngineConfig(max_slots=1, max_len=16))
        eng.submit(Request(prompt=_prompts([3])[0], max_new_tokens=2,
                           deadline_s=60.0,
                           arrival_ts=time.monotonic()))
        assert eng.queued_count == 1


class TestQuarantine:
    @pytest.mark.parametrize("kind", [
        "nonfinite",
        # same quarantine machinery from a different poison; slow tier
        pytest.param("oov", marks=pytest.mark.slow),
    ])
    def test_poisoned_slot_quarantined_cotenant_exact(self, small, kind):
        """Poison slot 0's decode output: its request retires with
        ``error`` (partial tokens intact), the co-tenant in slot 1 stays
        token-exact vs the fault-free run, and the slot is reusable."""
        model, params = small
        reqs = [Request(prompt=p, max_new_tokens=6)
                for p in _prompts([3, 5], seed=23)]
        sink = InMemorySink()
        inj = ServingFaultInjector(poison_decode={1: (0, kind)})
        eng = InferenceEngine(model, params,
                              EngineConfig(max_slots=2, max_len=16),
                              metrics=MetricsRegistry([sink]), faults=inj)
        victim, cotenant = eng.serve(reqs)
        expected0 = _expected_greedy(model, params, reqs[0], 16)
        assert victim.finish_reason == "error"
        # prefill token + decode call 0's token survived; the poisoned
        # token was never appended
        assert victim.tokens == expected0[:victim.new_tokens]
        assert 0 < victim.new_tokens < 6
        assert cotenant.finish_reason == "length"
        assert cotenant.tokens == _expected_greedy(model, params,
                                                   reqs[1], 16)
        eng.slots.check()
        assert eng.slots.free_count == 2
        assert eng.decode_retraces == 0       # quarantine never retraces
        counters = eng.metrics.counters()
        assert counters["slots_quarantined"] == 1
        assert counters["requests_error"] == 1
        causes = [r.get("cause") for r in sink.of_kind("event")
                  if r.get("event") == "slot_quarantined"]
        assert causes == [
            "nonfinite_logits" if kind == "nonfinite"
            else "out_of_vocab_token"]

    @pytest.mark.slow
    def test_quarantined_slot_reused_cleanly(self, small):
        """A later request decoding in the scrubbed slot is token-exact —
        the poison does not outlive its victim."""
        model, params = small
        (p0, p1) = _prompts([3, 4], seed=29)
        inj = ServingFaultInjector(poison_decode={0: (0, "nonfinite")})
        eng = InferenceEngine(model, params,
                              EngineConfig(max_slots=1, max_len=16),
                              faults=inj)
        first = Request(prompt=p0, max_new_tokens=6)
        second = Request(prompt=p1, max_new_tokens=6)
        res = eng.serve([first, second])
        assert res[0].finish_reason == "error"
        assert res[1].finish_reason == "length"
        assert res[1].tokens == _expected_greedy(model, params, second, 16)
        eng.slots.check()


class TestSupervisorRecovery:
    @pytest.mark.slow
    def test_decode_exception_restart_token_exact(self, small):
        """The tentpole acceptance path: a decode exception mid-flight
        kills the engine; the supervisor rebuilds it and re-prefills both
        in-flight requests from prompt + generated tokens — final outputs
        are token-exact vs the fault-free greedy run."""
        model, params = small
        reqs = [Request(prompt=p, max_new_tokens=n)
                for p, n in zip(_prompts([3, 5], seed=31), (6, 8))]
        inj = ServingFaultInjector(decode_raise_calls={2})
        sup = EngineSupervisor(model, params,
                               EngineConfig(max_slots=2, max_len=16),
                               faults=inj)
        results = sup.serve(reqs)
        for req, res in zip(reqs, results):
            assert res.finish_reason == "length"
            assert res.tokens == _expected_greedy(model, params, req, 16)
            assert res.prompt_len == req.prompt_len   # original, stitched
        counters = sup.metrics.counters()
        assert counters["engine_restarts"] == 1
        assert counters["tick_failures"] == 1
        assert counters["requests_recovered"] == 2
        assert counters["requests_submitted"] == 2    # resubmits not double-counted
        sup.engine.slots.check()
        assert sup.engine.slots.free_count == 2

    @pytest.mark.slow
    def test_sampled_stream_survives_restart(self, small):
        """Sampling keys on the absolute position, so a restart resumes
        even a sampled request token-exact."""
        model, params = small
        (prompt,) = _prompts([4], seed=37)
        kw = dict(prompt=prompt, max_new_tokens=6,
                  sampling=SamplingParams(temperature=1.0, top_k=5,
                                          seed=123))
        clean_sup = EngineSupervisor(model, params,
                                     EngineConfig(max_slots=2, max_len=16))
        (clean,) = clean_sup.serve([Request(**kw)])
        inj = ServingFaultInjector(decode_raise_calls={1})
        sup = EngineSupervisor(model, params,
                               EngineConfig(max_slots=2, max_len=16),
                               faults=inj)
        (faulted,) = sup.serve([Request(**kw)])
        assert sup.restarts == 1
        assert faulted.tokens == clean.tokens

    @pytest.mark.slow
    def test_prefill_exception_recovers_without_slot_leak(self, small):
        model, params = small
        reqs = [Request(prompt=p, max_new_tokens=4)
                for p in _prompts([3, 5], seed=41)]
        inj = ServingFaultInjector(prefill_raise_calls={1})
        sup = EngineSupervisor(model, params,
                               EngineConfig(max_slots=2, max_len=16),
                               faults=inj)
        results = sup.serve(reqs)
        for req, res in zip(reqs, results):
            assert res.finish_reason == "length"
            assert res.tokens == _expected_greedy(model, params, req, 16)
        sup.engine.slots.check()
        assert sup.metrics.counters()["engine_restarts"] == 1

    def test_hung_tick_triggers_restart(self, small):
        model, params = small
        (prompt,) = _prompts([3], seed=43)
        inj = ServingFaultInjector(decode_hang={1: 0.08})
        sup = EngineSupervisor(
            model, params, EngineConfig(max_slots=2, max_len=16),
            supervisor=SupervisorConfig(hung_tick_s=0.03), faults=inj)
        (res,) = sup.serve([Request(prompt=prompt, max_new_tokens=6)])
        req = Request(prompt=prompt, max_new_tokens=6)
        assert res.finish_reason == "length"
        assert res.tokens == _expected_greedy(model, params, req, 16)
        assert sup.restarts == 1                 # exactly the hung tick;
        #                                          compile warmups exempt
        assert sup.metrics.counters()["tick_failures"] == 1

    def test_retry_budget_exhausted_retires_with_error(self, small):
        """A persistently-failing engine never silently loses a request:
        past the per-request restart budget it retires with ``error``,
        carrying the tokens recovered so far."""
        model, params = small
        inj = ServingFaultInjector(decode_raise_calls=set(range(100)))
        sup = EngineSupervisor(
            model, params, EngineConfig(max_slots=2, max_len=16),
            supervisor=SupervisorConfig(max_restarts_per_request=1,
                                        breaker_threshold=100),
            faults=inj)
        (res,) = sup.serve([Request(prompt=_prompts([3], seed=47)[0],
                                    max_new_tokens=6)])
        assert res.finish_reason == "error"
        assert res.new_tokens >= 1               # prefill tokens kept
        assert sup.restarts == 2                 # budget + the last straw
        assert sup.inflight_count == 0
        sup.engine.slots.check()


class TestCircuitBreaker:
    def test_open_half_open_close_cycle(self, small):
        model, params = small
        inj = ServingFaultInjector(decode_raise_calls={0, 1})
        sup = EngineSupervisor(
            model, params, EngineConfig(max_slots=2, max_len=16),
            supervisor=SupervisorConfig(breaker_threshold=2,
                                        breaker_cooldown_s=0.05,
                                        max_restarts_per_request=5),
            faults=inj)
        victim = Request(prompt=_prompts([3], seed=53)[0], max_new_tokens=6)
        sup.submit(victim)
        sup.tick()
        assert sup.breaker_state == BREAKER_CLOSED   # 1 failure < threshold
        sup.tick()
        assert sup.breaker_state == BREAKER_OPEN     # 2nd consecutive
        # fast-fail while open: terminal immediately, engine untouched
        shed = Request(prompt=_prompts([4], seed=54)[0], max_new_tokens=3)
        with pytest.raises(EngineUnavailableError):
            sup.submit(shed)
        assert sup.completed[shed.request_id].finish_reason == "rejected"
        time.sleep(0.06)                             # cooldown elapses
        sup.tick()                                   # half-open probe: clean
        assert sup.breaker_state == BREAKER_CLOSED
        while sup.inflight_count:
            sup.tick()
        # the victim survived the whole episode, token-exact
        res = sup.completed[victim.request_id]
        assert res.tokens == _expected_greedy(model, params, victim, 16)
        counters = sup.metrics.counters()
        assert counters["breaker_opens"] == 1
        assert counters["breaker_half_opens"] == 1
        assert counters["breaker_closes"] == 1
        assert counters["requests_shed_breaker"] == 1

    @pytest.mark.slow
    def test_failed_probe_reopens(self, small):
        model, params = small
        inj = ServingFaultInjector(decode_raise_calls={0, 1, 2})
        sup = EngineSupervisor(
            model, params, EngineConfig(max_slots=2, max_len=16),
            supervisor=SupervisorConfig(breaker_threshold=2,
                                        breaker_cooldown_s=0.02,
                                        max_restarts_per_request=10),
            faults=inj)
        sup.submit(Request(prompt=_prompts([3], seed=59)[0],
                           max_new_tokens=4))
        sup.tick()
        sup.tick()
        assert sup.breaker_state == BREAKER_OPEN
        time.sleep(0.03)
        sup.tick()                                   # probe fails (call 2)
        assert sup.breaker_state == BREAKER_OPEN
        assert sup.metrics.counters()["breaker_opens"] == 2
        while sup.inflight_count:
            sup.tick()                               # drains clean


class TestDeadlineShedding:
    def test_projected_wait_sheds_at_submit(self, small):
        model, params = small
        sup = EngineSupervisor(model, params,
                               EngineConfig(max_slots=1, max_len=16))
        sup._service_s = 50.0        # observed: ~50s per request
        sup.submit(Request(prompt=_prompts([3], seed=61)[0],
                           max_new_tokens=8))
        sup.submit(Request(prompt=_prompts([4], seed=62)[0],
                           max_new_tokens=8))       # 1 deep in queue
        doomed = Request(prompt=_prompts([3], seed=63)[0],
                         max_new_tokens=2, deadline_s=1.0)
        with pytest.raises(EngineUnavailableError, match="deadline"):
            sup.submit(doomed)
        res = sup.completed[doomed.request_id]
        assert res.finish_reason == "rejected" and res.tokens == []
        assert sup.metrics.counters()["requests_shed_deadline"] == 1
        # no-deadline traffic is never shed by the estimate
        sup.submit(Request(prompt=_prompts([3], seed=64)[0],
                           max_new_tokens=2))

    def test_no_shedding_before_first_observation(self, small):
        model, params = small
        sup = EngineSupervisor(model, params,
                               EngineConfig(max_slots=1, max_len=16))
        assert sup._service_s is None
        sup.submit(Request(prompt=_prompts([3], seed=65)[0],
                           max_new_tokens=2, deadline_s=30.0))
        assert sup.inflight_count == 1


class TestMonitorReconciliation:
    @pytest.mark.slow  # report-level reconciliation integration: slow tier (ROADMAP)
    def test_incidents_reconcile_with_counters(self, small, tmp_path):
        """Acceptance: drive restarts, quarantine, breaker transitions,
        and sheds in one run — the monitor report's serving-incidents
        counts must reconcile key-for-key with the registry counters,
        and every submitted request must reach exactly one terminal
        record."""
        model, params = small
        log = tmp_path / "resilient_serving.jsonl"
        reg = MetricsRegistry([JsonlSink(str(log))])
        inj = ServingFaultInjector(decode_raise_calls={1, 2},
                                   poison_decode={4: (0, "nonfinite")})
        sup = EngineSupervisor(
            model, params, EngineConfig(max_slots=2, max_len=16),
            supervisor=SupervisorConfig(breaker_threshold=2,
                                        breaker_cooldown_s=0.01,
                                        max_restarts_per_request=5),
            metrics=reg, faults=inj)
        reqs = [Request(prompt=p, max_new_tokens=n)
                for p, n in zip(_prompts([3, 5, 4], seed=67), (6, 8, 4))]
        sup.serve(reqs)
        # one extra shed while we force the breaker open state into the
        # log: reopen it artificially is not possible — instead verify
        # whatever transitions actually happened reconcile
        sup.close()
        report = build_report(str(log))
        counters = report["counters"]
        inc = report["serving_incidents"]
        assert inc is not None
        # key-for-key: every incident type's event count equals its
        # counter, including zero-count types (declared up front)
        for event, counter in SERVING_INCIDENT_COUNTERS.items():
            assert inc["counts"].get(event, 0) == counters[counter], event
        # .get on the counter side: the mapping also names fleet-tier
        # counters (requests_shed_fleet) that a supervisor-only run
        # never declares — absent must reconcile with zero sheds
        for reason, counter in SERVING_SHED_COUNTERS.items():
            assert inc["shed_by_reason"].get(reason, 0) == \
                counters.get(counter, 0), reason
        assert counters["engine_restarts"] >= 1
        assert counters["slots_quarantined"] == 1
        # request-level conservation: one submit == one terminal record
        req_sec = report["requests"]
        by_reason = req_sec["by_finish_reason"]
        assert set(by_reason) <= set(FINISH_REASONS)
        assert req_sec["count"] == sum(by_reason.values())
        assert counters["requests_submitted"] == req_sec["count"]
        for reason in FINISH_REASONS:
            assert counters[f"requests_{reason}"] == \
                by_reason.get(reason, 0), reason
        text = render_report(report)
        assert "serving incidents" in text
        assert "engine_restart" in text

    @pytest.mark.slow
    def test_every_result_terminal_under_faults(self, small):
        model, params = small
        inj = ServingFaultInjector(decode_raise_calls={3},
                                   poison_decode={1: (1, "oov")})
        sup = EngineSupervisor(model, params,
                               EngineConfig(max_slots=2, max_len=16),
                               faults=inj)
        reqs = [Request(prompt=p, max_new_tokens=4)
                for p in _prompts([3, 5, 2, 4], seed=71)]
        results = sup.serve(reqs)
        assert len(results) == len(reqs)
        assert all(r.finish_reason in FINISH_REASONS for r in results)
        assert sup.inflight_count == 0
        sup.engine.slots.check()


@pytest.mark.slow
class TestServingChaosSweep:
    def test_randomized_faults_arrivals_cancellations(self, small):
        """Chaos acceptance: randomized fault schedules (poison, raises,
        hangs) x randomized arrivals x cancellations. Every submitted
        request reaches a terminal state, no slot leaks, supervisor
        always drains."""
        model, params = small
        rng = np.random.RandomState(1)
        max_len = 24
        for round_i in range(3):
            poison = {int(rng.randint(1, 12)):
                      (int(rng.randint(0, 3)),
                       "nonfinite" if rng.rand() < 0.5 else "oov")}
            raises = {int(rng.randint(1, 10))}
            hangs = {int(rng.randint(2, 10)): 0.06}
            inj = ServingFaultInjector(
                poison_decode=poison, decode_raise_calls=raises,
                decode_hang=hangs)
            sup = EngineSupervisor(
                model, params,
                EngineConfig(max_slots=3, max_len=max_len),
                supervisor=SupervisorConfig(hung_tick_s=0.03,
                                            breaker_threshold=4,
                                            breaker_cooldown_s=0.02,
                                            max_restarts_per_request=3),
                faults=inj)
            reqs = []
            for _ in range(10):
                pl = int(rng.randint(1, 10))
                mn = int(rng.randint(1, 1 + min(8, max_len - pl)))
                reqs.append(Request(
                    prompt=rng.randint(0, 64, size=pl).tolist(),
                    max_new_tokens=mn,
                    eos_token=(int(rng.randint(0, 64))
                               if rng.rand() < 0.25 else None),
                    deadline_s=(30.0 if rng.rand() < 0.3 else None)))
            cancel_at = {reqs[3].request_id: 2, reqs[7].request_id: 4}

            def chaos(supervisor, tick):
                for rid, t in cancel_at.items():
                    if tick == t:
                        supervisor.cancel(rid)

            results = sup.serve(reqs, on_tick=chaos)
            assert len(results) == len(reqs), round_i
            for res in results:
                assert res.finish_reason in FINISH_REASONS, res
            assert sup.inflight_count == 0
            sup.engine.slots.check()
            assert sup.engine.slots.free_count == 3
            counters = sup.metrics.counters()
            assert counters["requests_submitted"] == sum(
                counters[f"requests_{r}"] for r in FINISH_REASONS)
            sup.close()
