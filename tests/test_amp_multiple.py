"""Multiple models / optimizers / losses under one amp state.

Mirrors the reference's ``tests/L0/run_amp/test_multiple_models_optimizers_
losses.py`` (762 LoC): ``amp.initialize(num_losses=N)`` creates independent
loss scalers; an overflow in one loss's backward must back off only that
scaler and skip only the optimizers stepped under it, while the other
model/optimizer pair keeps training and its scaler keeps growing. Also the
DCGAN-shaped scenario (two models, two optimizers, three losses) the
reference exercises in ``examples/dcgan/main_amp.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.optimizers import FusedAdam, FusedSGD


def _linear_loss(p, x, target):
    pred = x @ p["w"] + p["b"]
    return jnp.mean((pred - target) ** 2)


def test_num_losses_independent_states():
    st = amp.initialize("O2", num_losses=3)
    assert len(st.scaler_states) == 3
    # states are independent values, not aliases
    s0 = st.scaler.update(st.scaler_states[0], jnp.asarray(True))
    assert float(s0.loss_scale) < float(st.scaler_states[1].loss_scale)


def test_state_dict_roundtrip_multi_loss():
    st = amp.initialize("O1", num_losses=3)
    # push scaler 1 through an overflow so the three diverge
    states = list(st.scaler_states)
    states[1] = st.scaler.update(states[1], jnp.asarray(True))
    st.scaler_states[:] = states
    d = amp.state_dict(st)
    assert set(d) == {"loss_scaler0", "loss_scaler1", "loss_scaler2"}
    st2 = amp.initialize("O1", num_losses=3)
    st2 = amp.load_state_dict(st2, d)
    for a, b in zip(st.scaler_states, st2.scaler_states):
        assert float(a.loss_scale) == float(b.loss_scale)


def test_overflow_isolated_per_loss():
    """Loss 0 overflows; optimizer 0 skips + scaler 0 backs off; loss 1's
    model steps normally and scaler 1 is untouched."""
    sc = amp.LossScaler("dynamic", init_scale=2.0 ** 8)
    st0, st1 = sc.init(), sc.init()

    p0 = {"w": jnp.ones((4, 2)), "b": jnp.zeros((2,))}
    p1 = {"w": jnp.ones((4, 2)), "b": jnp.zeros((2,))}
    opt0, opt1 = FusedSGD(lr=0.1), FusedAdam(lr=0.1)
    os0, os1 = opt0.init(p0), opt1.init(p1)
    x = jnp.ones((3, 4))
    y = jnp.zeros((3, 2))

    @jax.jit
    def step(p0, os0, st0, p1, os1, st1, poison):
        g0 = jax.grad(lambda p: sc.scale(_linear_loss(p, x, y), st0))(p0)
        # inject an overflow into model 0's grads only
        g0 = jax.tree.map(lambda g: g + poison, g0)
        g0, inf0 = sc.unscale(g0, st0)
        p0, os0 = opt0.step(g0, p0, os0, found_inf=inf0)  # on-device skip
        st0 = sc.update(st0, inf0)

        g1 = jax.grad(lambda p: sc.scale(_linear_loss(p, x, y), st1))(p1)
        g1, inf1 = sc.unscale(g1, st1)
        p1, os1 = opt1.step(g1, p1, os1, found_inf=inf1)
        st1 = sc.update(st1, inf1)
        return p0, os0, st0, p1, os1, st1

    p0b, os0b, st0b, p1b, os1b, st1b = step(
        p0, os0, st0, p1, os1, st1, jnp.asarray(jnp.inf))
    # model 0: skipped, scaler backed off
    np.testing.assert_allclose(p0b["w"], p0["w"])
    assert float(st0b.loss_scale) == 2.0 ** 7
    # model 1: stepped, scaler intact
    assert not np.allclose(p1b["w"], p1["w"])
    assert float(st1b.loss_scale) == 2.0 ** 8


def test_shared_model_two_losses_sequential_backward():
    """Reference scenario: the same model backed through two losses with
    per-loss scalers (amp.scale_loss(loss, opt, loss_id=i)); gradients
    accumulate across the two backwards before one optimizer step."""
    sc = amp.LossScaler(2.0 ** 4)   # static
    st0, st1 = sc.init(), sc.init()
    p = {"w": jnp.ones((4, 2)), "b": jnp.zeros((2,))}
    opt = FusedSGD(lr=0.05)
    os_ = opt.init(p)
    x = jnp.ones((3, 4))
    y0, y1 = jnp.zeros((3, 2)), jnp.ones((3, 2))

    @jax.jit
    def step(p, os_):
        g0 = jax.grad(lambda q: sc.scale(_linear_loss(q, x, y0), st0))(p)
        g0, i0 = sc.unscale(g0, st0)
        g1 = jax.grad(lambda q: sc.scale(_linear_loss(q, x, y1), st1))(p)
        g1, i1 = sc.unscale(g1, st1)
        g = jax.tree.map(jnp.add, g0, g1)
        inf = jnp.logical_or(i0, i1)
        return opt.step(g, p, os_, found_inf=inf)

    # reference: grads of (loss0 + loss1) == accumulated per-loss grads
    g_ref = jax.grad(lambda q: _linear_loss(q, x, y0)
                     + _linear_loss(q, x, y1))(p)
    p_ref, _ = opt.step(g_ref, p, opt.init(p))
    p_new, _ = step(p, os_)
    np.testing.assert_allclose(p_new["w"], p_ref["w"], rtol=1e-5)


def test_dcgan_shaped_three_scalers():
    """Two models (G, D), two optimizers, three losses (errD_real,
    errD_fake, errG) each with its own scaler — the examples/dcgan_amp.py
    topology — trains without NaN and decreases both losses."""
    key = jax.random.PRNGKey(0)
    amp_state = amp.initialize("O1", num_losses=3, loss_scale="dynamic")
    sc = amp_state.scaler
    s = list(amp_state.scaler_states)

    kG, kD, kz = jax.random.split(key, 3)
    G = {"w": jax.random.normal(kG, (8, 16)) * 0.1}
    D = {"w": jax.random.normal(kD, (16, 1)) * 0.1}
    optG, optD = FusedAdam(lr=2e-3), FusedAdam(lr=2e-3)
    osG, osD = optG.init(G), optD.init(D)
    real = jax.random.normal(kz, (32, 16))

    def d_out(D, h):
        return jax.nn.sigmoid(h @ D["w"])

    def bce(p, label):
        eps = 1e-6
        return -jnp.mean(label * jnp.log(p + eps)
                         + (1 - label) * jnp.log(1 - p + eps))

    @jax.jit
    def step(G, D, osG, osD, s0, s1, s2, z):
        # D on real (loss 0) + D on fake (loss 1), accumulated
        fake = z @ G["w"]
        gr = jax.grad(lambda d: sc.scale(bce(d_out(d, real), 1.0), s0))(D)
        gr, i0 = sc.unscale(gr, s0)
        gf = jax.grad(lambda d: sc.scale(bce(d_out(d, fake), 0.0), s1))(D)
        gf, i1 = sc.unscale(gf, s1)
        gD = jax.tree.map(jnp.add, gr, gf)
        D, osD = optD.step(gD, D, osD, found_inf=jnp.logical_or(i0, i1))
        s0, s1 = sc.update(s0, i0), sc.update(s1, i1)
        # G (loss 2)
        gG = jax.grad(
            lambda g: sc.scale(bce(d_out(D, z @ g["w"]), 1.0), s2))(G)
        gG, i2 = sc.unscale(gG, s2)
        G, osG = optG.step(gG, G, osG, found_inf=i2)
        s2 = sc.update(s2, i2)
        errD = bce(d_out(D, real), 1.0) + bce(d_out(D, fake), 0.0)
        return G, D, osG, osD, s0, s1, s2, errD

    errs = []
    for i in range(20):
        z = jax.random.normal(jax.random.fold_in(kz, i), (32, 8))
        G, D, osG, osD, s[0], s[1], s[2], errD = step(
            G, D, osG, osD, s[0], s[1], s[2], z)
        errs.append(float(errD))
    assert np.isfinite(errs).all()
    assert errs[-1] < errs[0]
