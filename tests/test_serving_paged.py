"""Paged KV cache + fused decode-step kernel tests.

The ISSUE-10 contract: the paged engine is a memory-layout optimization,
never an approximation. Tier-1 pins (a) PagePool free-list invariants
(conservation asserted like slot leaks), (b) fused-kernel-vs-reference
attention parity in interpret mode, (c) paged-vs-flat engine TOKEN
EXACTNESS — greedy and sampled — with zero decode retraces, (d) the
``pages_exhausted`` admission shed + kv-page gauges reconciling in the
monitor report, and (e) quarantine scrubbing and releasing pages. The
compile-bound cases (supervisor restart on paged, tp=2 sharded paged
crossed against unsharded flat) sit in the slow tier per the ROADMAP
tier policy.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.models.generation import generate
from apex_tpu.observability import (
    InMemorySink,
    JsonlSink,
    MetricsRegistry,
    build_report,
    render_report,
)
from apex_tpu.observability.report import SERVING_SHED_COUNTERS
from apex_tpu.ops import _support, fused_paged_decode_attention, \
    paged_pages_for
from apex_tpu.ops.decode_attention import _pallas, _reference
from apex_tpu.serving import (
    EngineConfig,
    EngineSupervisor,
    InferenceEngine,
    PageError,
    PagePool,
    Request,
    SamplingParams,
)
from apex_tpu.testing_faults import ServingFaultInjector


@pytest.fixture(autouse=True)
def _pallas_off(monkeypatch):
    """Pin the jnp reference path: other test modules export
    ``APEX_TPU_FORCE_PALLAS=interpret`` process-wide at import, and the
    bitwise paged-vs-flat claims below hold for the reference dispatch
    (the interpret-mode kernel is compared to tolerance, explicitly)."""
    monkeypatch.setenv("APEX_TPU_FORCE_PALLAS", "off")
    _support.pallas_mode.cache_clear()
    yield
    _support.pallas_mode.cache_clear()


@pytest.fixture(scope="module")
def small():
    model = GPTModel(TransformerConfig(
        num_layers=2, hidden_size=32, num_attention_heads=4, vocab_size=64,
        max_position_embeddings=64, hidden_dropout=0.0,
        attention_dropout=0.0))
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _prompts(lens, seed=7):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 64, size=n).tolist() for n in lens]


def _expected_greedy(model, params, request, max_len):
    out = generate(model, params, jnp.asarray([request.prompt], jnp.int32),
                   request.max_new_tokens, max_len=max_len,
                   eos_token=request.eos_token)
    toks = np.asarray(out[0, request.prompt_len:]).tolist()
    if request.eos_token is not None and request.eos_token in toks:
        toks = toks[:toks.index(request.eos_token) + 1]
    return toks


# ---------------------------------------------------------------------------
# PagePool free-list invariants


class TestPagePool:
    def test_map_release_conservation(self):
        pool = PagePool(n_pages=8, page_size=4, pages_per_slot=4)
        a = pool.map_slot(0, 7)                 # 2 pages
        b = pool.map_slot(1, 9)                 # 3 pages
        assert len(a) == 2 and len(b) == 3
        assert set(a).isdisjoint(b)
        assert pool.free_count == 3
        assert pool.in_use_count == 5
        assert pool.release_slot(0) == a
        assert pool.free_count == 5
        pool.check()

    def test_pages_for(self):
        pool = PagePool(n_pages=4, page_size=4, pages_per_slot=4)
        assert pool.pages_for(1) == 1
        assert pool.pages_for(4) == 1
        assert pool.pages_for(5) == 2
        assert paged_pages_for(5, 4) == 2

    def test_exhaustion_returns_none_not_partial(self):
        pool = PagePool(n_pages=3, page_size=4, pages_per_slot=4)
        assert pool.map_slot(0, 8) is not None  # 2 pages
        # 2 more pages needed, 1 free: no partial grab, pool untouched
        assert pool.map_slot(1, 8) is None
        assert pool.free_count == 1
        pool.check()

    def test_double_map_raises(self):
        pool = PagePool(n_pages=4, page_size=4, pages_per_slot=4)
        pool.map_slot(0, 4)
        with pytest.raises(PageError, match="already"):
            pool.map_slot(0, 4)

    def test_need_beyond_pages_per_slot_raises(self):
        pool = PagePool(n_pages=8, page_size=4, pages_per_slot=2)
        with pytest.raises(PageError, match="pages_per_slot"):
            pool.map_slot(0, 12)                # 3 pages > pps=2

    def test_extend_on_demand(self):
        pool = PagePool(n_pages=4, page_size=4, pages_per_slot=4)
        first = list(pool.map_slot(0, 3))
        assert len(first) == 1
        assert pool.extend_slot(0, 4) == []     # still fits page 0
        grown = pool.extend_slot(0, 5)          # crosses into page 1
        assert len(grown) == 1 and grown[0] not in first
        assert pool.slot_pages(0) == first + grown
        pool.check()

    def test_extend_exhausted_returns_none(self):
        pool = PagePool(n_pages=2, page_size=4, pages_per_slot=4)
        pool.map_slot(0, 4)
        pool.map_slot(1, 4)
        assert pool.extend_slot(0, 5) is None   # no page left
        assert pool.slot_pages(0) == [0]        # ownership unchanged
        pool.check()

    def test_reset_restores_free_list(self):
        pool = PagePool(n_pages=6, page_size=4, pages_per_slot=3)
        pool.map_slot(0, 12)
        pool.map_slot(1, 4)
        pool.reset()
        assert pool.free_count == 6
        assert pool.in_use_count == 0
        pool.check()

    def test_randomized_conservation(self):
        """Random map/extend/release traffic: pages are conserved at
        every step — the page analog of the slot-leak assertion."""
        rng = np.random.RandomState(41)
        pool = PagePool(n_pages=16, page_size=4, pages_per_slot=4)
        tokens = {}
        for _ in range(300):
            op = rng.randint(3)
            slot = int(rng.randint(6))
            if op == 0 and slot not in tokens:
                if pool.map_slot(slot, int(rng.randint(1, 13))) is not None:
                    tokens[slot] = True
            elif op == 1 and slot in tokens:
                pool.extend_slot(slot, int(rng.randint(1, 17)))
            elif op == 2 and slot in tokens:
                pool.release_slot(slot)
                del tokens[slot]
            assert pool.free_count + pool.in_use_count == 16
            pool.check()
        for slot in list(tokens):
            pool.release_slot(slot)
        assert pool.free_count == 16


# ---------------------------------------------------------------------------
# fused kernel vs reference (interpret mode — the tier-1 hardware proxy)


def _rand_paged_case(seed, b=3, kvh=2, group=2, dh=8, page_size=8, pps=4,
                     dtype=jnp.float32):
    keys = jax.random.split(jax.random.PRNGKey(seed), 5)
    n_pages = b * pps + 2
    hl = kvh * group
    f = kvh * dh
    q = jax.random.normal(keys[0], (b, hl, dh), dtype)
    k_new = jax.random.normal(keys[1], (b, f), dtype)
    v_new = jax.random.normal(keys[2], (b, f), dtype)
    k_pages = jax.random.normal(keys[3], (n_pages, page_size, f), dtype)
    v_pages = jax.random.normal(keys[4], (n_pages, page_size, f), dtype)
    # positions straddle page boundaries; each slot maps exactly the
    # pages its position needs, the rest carry the unmapped sentinel
    positions = jnp.asarray([0, page_size - 1, 2 * page_size + 3])[:b]
    pt = np.full((b, pps), n_pages, np.int32)
    perm = np.random.RandomState(seed).permutation(b * pps)
    next_page = 0
    for r in range(b):
        for j in range(paged_pages_for(int(positions[r]) + 1, page_size)):
            pt[r, j] = perm[next_page]
            next_page += 1
    return q, k_new, v_new, k_pages, v_pages, jnp.asarray(pt), positions


def _single(fn, case, group, sliding_window=None):
    """Call the windowed internals (:func:`_pallas` / :func:`_reference`)
    on an old-style single-token case: w == 1, no quantization."""
    q, k_new, v_new, kp, vp, pt, pos = case
    ctx, kk, vk, _, _ = fn(q[:, None], k_new[:, None], v_new[:, None],
                           kp, vp, None, None, pt, pos,
                           group=group, sliding_window=sliding_window)
    return ctx[:, 0], kk, vk


class TestFusedKernelParity:
    def test_interpret_matches_reference(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_FORCE_PALLAS", "interpret")
        _support.pallas_mode.cache_clear()
        try:
            case = _rand_paged_case(0)
            ctx_k, kk, vk = _single(_pallas, case, group=2)
            ctx_r, kr, vr = _single(_reference, case, group=2)
            np.testing.assert_allclose(ctx_k, ctx_r, atol=2e-5, rtol=2e-5)
            # the append is the same scatter on both paths: exact
            np.testing.assert_array_equal(kk, kr)
            np.testing.assert_array_equal(vk, vr)
        finally:
            _support.pallas_mode.cache_clear()

    def test_interpret_sliding_window(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_FORCE_PALLAS", "interpret")
        _support.pallas_mode.cache_clear()
        try:
            case = _rand_paged_case(1)
            ctx_k, _, _ = _single(_pallas, case, group=2, sliding_window=5)
            ctx_r, _, _ = _single(_reference, case, group=2,
                                  sliding_window=5)
            np.testing.assert_allclose(ctx_k, ctx_r, atol=2e-5, rtol=2e-5)
        finally:
            _support.pallas_mode.cache_clear()

    def test_interpret_mha_group_one(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_FORCE_PALLAS", "interpret")
        _support.pallas_mode.cache_clear()
        try:
            case = _rand_paged_case(2, kvh=4, group=1)
            ctx_k, _, _ = _single(_pallas, case, group=1)
            ctx_r, _, _ = _single(_reference, case, group=1)
            np.testing.assert_allclose(ctx_k, ctx_r, atol=2e-5, rtol=2e-5)
        finally:
            _support.pallas_mode.cache_clear()

    def test_cpu_dispatch_is_reference(self):
        """With pallas off (the CPU default) the public entry point IS
        the reference — what makes paged-vs-flat engine parity bitwise."""
        case = _rand_paged_case(3)
        ctx, kk, vk = fused_paged_decode_attention(
            *case, queries_per_group=2)
        ctx_r, kr, vr = _single(_reference, case, group=2)
        np.testing.assert_array_equal(ctx, ctx_r)
        np.testing.assert_array_equal(kk, kr)

    def test_appended_row_lands_at_position(self):
        case = _rand_paged_case(4)
        q, k_new, v_new, k_pages, _, pt, positions = case
        page_size = k_pages.shape[1]
        _, kk, _ = fused_paged_decode_attention(*case, queries_per_group=2)
        for r in range(q.shape[0]):
            page = int(pt[r, int(positions[r]) // page_size])
            np.testing.assert_array_equal(
                kk[page, int(positions[r]) % page_size], k_new[r])

    def test_shape_validation(self):
        case = _rand_paged_case(5)
        with pytest.raises(ValueError, match="queries_per_group"):
            fused_paged_decode_attention(*case, queries_per_group=3)
        q = case[0]
        with pytest.raises(ValueError, match="pool minor dim"):
            fused_paged_decode_attention(
                q, case[1], case[2], case[3][:, :, :-1], case[4][:, :, :-1],
                case[5], case[6], queries_per_group=2)


# ---------------------------------------------------------------------------
# paged engine: token exactness, shedding, gauges, quarantine


class TestPagedEngine:
    def _requests(self, seed=7):
        specs = [(4, 6, SamplingParams()),
                 (6, 5, SamplingParams(temperature=0.8, top_k=8, seed=3)),
                 (3, 8, SamplingParams()),
                 (5, 4, SamplingParams(temperature=1.1, seed=9)),
                 (2, 6, SamplingParams(temperature=0.7, top_k=16, seed=5))]
        prompts = _prompts([n for n, _, _ in specs], seed=seed)
        return [Request(prompt=p, max_new_tokens=m, sampling=s)
                for p, (_, m, s) in zip(prompts, specs)]

    def test_paged_vs_flat_token_exact(self, small):
        """The acceptance bar: identical mixed greedy/sampled traffic
        through ``kv_layout="flat"`` and ``kv_layout="paged"`` engines is
        TOKEN-EXACT, with zero decode retraces on both, and the paged
        run returns every page. max_len divisible by page_size keeps the
        logical reduction lengths identical, so parity is bitwise."""
        model, params = small
        flat_eng = InferenceEngine(model, params, EngineConfig(
            max_slots=3, max_len=16, kv_layout="flat"))
        with flat_eng:
            ref = flat_eng.serve(self._requests())
            assert flat_eng.decode_retraces == 0
        paged_eng = InferenceEngine(model, params, EngineConfig(
            max_slots=3, max_len=16, kv_layout="paged", page_size=4))
        with paged_eng:
            out = paged_eng.serve(self._requests())
            assert paged_eng.decode_retraces == 0
            # drained: every page is free or held only by the prefix
            # intern index (entries survive their writer for reuse)
            assert paged_eng.pages.free_count + \
                paged_eng.pages.reclaimable_count == paged_eng.pages.n_pages
            paged_eng.pages.check()
            paged_eng.slots.check()
        for a, b in zip(ref, out):
            assert a.finish_reason == b.finish_reason
            assert a.tokens == b.tokens, (a.request_id, a.tokens, b.tokens)
        # greedy rows also match the per-request generate() anchor
        for r, req in zip(out, self._requests()):
            if req.sampling.temperature == 0.0:
                assert r.tokens == _expected_greedy(model, params, req, 16)

    def test_close_resets_page_pool(self, small):
        model, params = small
        eng = InferenceEngine(model, params, EngineConfig(
            max_slots=2, max_len=16, page_size=4))
        eng.serve([Request(prompt=_prompts([4])[0], max_new_tokens=3)])
        eng.close()
        assert eng.pages.free_count == eng.pages.n_pages
        assert eng._reserved_pages == 0
        assert (eng._page_table_h == eng.pages.n_pages).all()

    def test_pages_exhausted_shed_and_monitor(self, small, tmp_path):
        """A request whose worst-case reservation exceeds the WHOLE pool
        sheds as ``pages_exhausted`` (own counter + event reason, the
        supervisor-shed convention); a fitting request completes; the kv
        page gauges/histogram render and reconcile in the monitor."""
        model, params = small
        log = tmp_path / "paged.jsonl"
        sink = InMemorySink()
        reg = MetricsRegistry([sink, JsonlSink(str(log))])
        eng = InferenceEngine(model, params, EngineConfig(
            max_slots=2, max_len=16, page_size=4, n_pages=2), metrics=reg)
        fits = Request(prompt=_prompts([3])[0], max_new_tokens=4)   # 2 pages
        doomed = Request(prompt=_prompts([8], seed=9)[0],
                         max_new_tokens=6)                          # 4 pages
        with eng:
            results = {r.request_id: r for r in eng.serve([fits, doomed])}
        assert results[doomed.request_id].finish_reason == "rejected"
        assert results[fits.request_id].finish_reason == "length"
        assert results[fits.request_id].tokens == _expected_greedy(
            model, params, fits, 16)
        counters = reg.counters()
        assert counters["requests_shed_pages"] == 1
        sheds = [r for r in sink.of_kind("event")
                 if r.get("event") == "request_shed"]
        assert [s["reason"] for s in sheds] == ["pages_exhausted"]
        assert sheds[0]["pages_needed"] == 4
        assert SERVING_SHED_COUNTERS["pages_exhausted"] == \
            "requests_shed_pages"
        report = build_report(str(log))
        gauges = report["gauges"]
        assert gauges["kv_pages_in_use"] == 0       # final tick: drained
        assert gauges["kv_pages_free"] == 2
        occ = report["histograms"]["kv_page_occupancy"]
        assert occ["count"] >= 1 and occ["max"] <= 1.0
        text = render_report(report)
        assert "kv pages:" in text
        # the real CLI parses the same log (pure stdlib)
        proc = subprocess.run(
            [sys.executable, "-m", "apex_tpu.monitor", str(log), "--json"],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stderr
        cli = json.loads(proc.stdout)
        assert cli["counters"]["requests_shed_pages"] == 1
        assert cli["gauges"]["kv_pages_free"] == 2

    def test_quarantine_scrubs_and_releases_pages(self, small):
        """Poisoned decode output on a paged engine: the victim's pages
        return to the free list AND the scrub zeroes the pool rows it
        owned, so the poison cannot leak into a later tenant's pages."""
        model, params = small
        inj = ServingFaultInjector(poison_decode={0: (0, "nonfinite")})
        # prefix_cache=False: the all-rows-zero sweep below relies on the
        # one-owner pool (no intern index keeping prefill K/V resident);
        # quarantine WITH shared pages is covered in test_prefix_cache.py
        eng = InferenceEngine(model, params, EngineConfig(
            max_slots=1, max_len=16, page_size=4, prefix_cache=False),
            faults=inj)
        victim = Request(prompt=_prompts([5], seed=29)[0], max_new_tokens=6)
        with eng:
            res = eng.serve([victim])
            assert res[0].finish_reason == "error"
            assert eng.pages.free_count == eng.pages.n_pages
            eng.pages.check()
            assert eng.metrics.counters()["slots_quarantined"] == 1
            # only the victim ever wrote: every pool row must be zero
            for k_pages, v_pages in eng._caches:
                assert not np.asarray(k_pages).any()
                assert not np.asarray(v_pages).any()
            # the scrubbed pool serves a fresh request token-exact
            clean = Request(prompt=_prompts([4], seed=31)[0],
                            max_new_tokens=5)
            res2 = eng.serve([clean])
        assert res2[0].tokens == _expected_greedy(model, params, clean, 16)
        assert eng.decode_retraces == 0

    def test_randomized_arrivals_cancellations_no_page_leaks(self, small):
        """Seeded random arrivals + mid-flight cancellations on one paged
        engine: every request terminal, zero retraces, and the page pool
        drains back to full — conservation under churn."""
        model, params = small
        rng = np.random.RandomState(53)
        eng = InferenceEngine(model, params, EngineConfig(
            max_slots=3, max_len=16, page_size=4))
        reqs = [Request(prompt=rng.randint(0, 64,
                                           size=rng.randint(1, 9)).tolist(),
                        max_new_tokens=int(rng.randint(1, 8)))
                for _ in range(12)]
        with eng:
            done = {}
            pending = list(reqs)
            ticks = 0
            while pending or eng.active_count or eng.queued_count:
                while pending and eng.queued_count < 4:
                    eng.submit(pending.pop(0))
                for res in eng.tick():
                    done[res.request_id] = res
                ticks += 1
                if ticks % 5 == 0 and eng.active_count:
                    # cancel a random in-flight request
                    req, _, _ = eng.inflight()[
                        int(rng.randint(eng.active_count))]
                    eng.cancel(req.request_id)
                assert eng.pages.free_count + eng.pages.in_use_count == \
                    eng.pages.n_pages
            assert eng.decode_retraces == 0
            eng.pages.check()
            eng.slots.check()
            assert eng.pages.free_count + eng.pages.reclaimable_count == \
                eng.pages.n_pages
        assert len(done) == len(reqs)
        assert all(r.finish_reason in ("length", "eos", "cancelled")
                   for r in done.values())


# ---------------------------------------------------------------------------
# slow tier: supervisor restart + tp=2 sharded (compile-bound, ROADMAP)


class TestPagedResilience:
    @pytest.mark.slow
    def test_supervisor_restart_token_exact_on_paged(self, small):
        """A decode exception mid-flight on the PAGED engine: the
        supervisor rebuild (fresh PagePool + page tables + jit) and
        prompt+tokens re-prefill stays token-exact — recovery semantics
        are layout-independent by construction."""
        model, params = small
        reqs = [Request(prompt=p, max_new_tokens=n)
                for p, n in zip(_prompts([3, 5], seed=31), (6, 8))]
        inj = ServingFaultInjector(decode_raise_calls={2})
        sup = EngineSupervisor(
            model, params,
            EngineConfig(max_slots=2, max_len=16, page_size=4),
            faults=inj)
        with sup:
            results = {r.request_id: r for r in sup.serve(reqs)}
        assert sup.restarts == 1
        for req in reqs:
            assert results[req.request_id].tokens == _expected_greedy(
                model, params, req, 16)
        eng = sup.engine
        assert eng.pages.free_count + eng.pages.reclaimable_count == \
            eng.pages.n_pages
        eng.pages.check()

    @pytest.mark.slow
    def test_tp2_sharded_paged_vs_unsharded_flat(self, small):
        """The strongest cross: ShardedEngine (tp=2, paged pool sharded
        on the heads-minor dim, page table replicated) against the
        UNSHARDED FLAT engine — token-exact, greedy and sampled, zero
        decode retraces. Crossing both the layout and the mesh axis in
        one assertion means neither can be hiding in the other."""
        from apex_tpu.serving import ShardedEngine
        from apex_tpu.transformer import parallel_state

        model, params = small
        rng = np.random.RandomState(61)
        specs = [(4, 6, SamplingParams()),
                 (7, 5, SamplingParams(temperature=0.8, top_k=8, seed=3)),
                 (3, 8, SamplingParams()),
                 (5, 4, SamplingParams(temperature=1.1, seed=9))]
        prompts = [rng.randint(0, 64, size=n).tolist() for n, _, _ in specs]

        def requests():
            return [Request(prompt=p, max_new_tokens=m, sampling=s)
                    for p, (_, m, s) in zip(prompts, specs)]

        flat_eng = InferenceEngine(model, params, EngineConfig(
            max_slots=4, max_len=32, kv_layout="flat"))
        with flat_eng:
            ref = flat_eng.serve(requests())

        parallel_state.destroy_model_parallel()
        try:
            parallel_state.initialize_model_parallel(
                tensor_model_parallel_size=2)
            sharded = ShardedEngine(model, params, EngineConfig(
                max_slots=4, max_len=32, kv_layout="paged", page_size=8))
            with sharded:
                out = sharded.serve(requests())
                assert sharded.decode_retraces == 0
                assert sharded.pages.free_count + \
                    sharded.pages.reclaimable_count == sharded.pages.n_pages
                sharded.pages.check()
        finally:
            parallel_state.destroy_model_parallel()
        for a, b in zip(ref, out):
            assert a.finish_reason == b.finish_reason
            assert a.tokens == b.tokens, (a.request_id, a.tokens, b.tokens)
