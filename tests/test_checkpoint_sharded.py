"""Sharded-checkpoint suite (ISSUE 9): manifest/commit protocol, elastic
mesh-reshape restore, async saves with retry/drain/abandon semantics,
shard-level fault injection with checksum-verified fallback, partial-dir
cleanup, the ``python -m apex_tpu.checkpoint verify`` fsck, preemption
during an in-flight async write, and monitor reconciliation of the
``ckpt_*`` counters.

Everything here runs on the 8 virtual CPU devices the conftest forces;
the compile-bound reshape-parity TRAINING runs live in
``test_checkpoint_reshape_parity.py`` (slow tier).
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.checkpoint import (
    CheckpointCorruptionError,
    RetryingCheckpointManager,
    ShardedCheckpointManager,
    verify_directory,
)
from apex_tpu.checkpoint.manifest import (
    COMMIT_NAME,
    atomic_write_bytes,
    read_commit,
    validate_step_dir,
)
from apex_tpu.checkpoint.verify import main as verify_main
from apex_tpu.observability import JsonlSink, MetricsRegistry, build_report
from apex_tpu.observability.report import CHECKPOINT_INCIDENT_COUNTERS
from apex_tpu.resilience import (
    ResilienceConfig,
    make_resilient_train_step,
    make_train_state,
    run_training,
)
from apex_tpu.testing_faults import (
    FaultInjector,
    corrupt_shard,
    tear_manifest,
)


def _mesh(rows, cols):
    devs = np.array(jax.devices()[:rows * cols]).reshape(rows, cols)
    return Mesh(devs, ("data", "tensor"))


def _sharded_state(mesh, scale=1.0):
    """A small train-state-shaped pytree with the dryrun sharding mix:
    2-D sharded, 1-D sharded, dp-replicated, and an unsharded scalar."""
    w = jax.device_put(scale * jnp.arange(64.0).reshape(8, 8),
                       NamedSharding(mesh, P("data", "tensor")))
    b = jax.device_put(scale * jnp.arange(8.0),
                       NamedSharding(mesh, P("tensor")))
    full = jax.device_put(scale * jnp.arange(16.0).reshape(4, 4),
                          NamedSharding(mesh, P()))
    return {"params": {"w": w, "b": b}, "full": full,
            "step": jnp.asarray(3, jnp.int32)}


def _template(mesh):
    if mesh is None:
        return {"params": {"w": jnp.zeros((8, 8)), "b": jnp.zeros(8)},
                "full": jnp.zeros((4, 4)),
                "step": jnp.asarray(0, jnp.int32)}
    zeros = _sharded_state(mesh, scale=0.0)
    zeros["step"] = jnp.asarray(0, jnp.int32)
    return zeros


def _assert_state_equal(restored, reference):
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        jax.device_get(restored), jax.device_get(reference))


# ---------------------------------------------------------------------------
# manifest / commit protocol
# ---------------------------------------------------------------------------

class TestCommitProtocol:
    def test_committed_step_validates_clean(self, tmp_path):
        mgr = ShardedCheckpointManager(str(tmp_path))
        mgr.save(5, _sharded_state(_mesh(4, 2)))
        step_dir = str(tmp_path / "5")
        marker = read_commit(step_dir)
        assert marker is not None and marker["step"] == 5
        assert validate_step_dir(step_dir, deep=True) == []
        # shards are addressed by (param-path, global-shard-index)
        names = sorted(os.listdir(step_dir))
        assert "manifest.json" in names and COMMIT_NAME in names
        assert any(n.startswith("leaf0000_s") for n in names)

    def test_replicas_deduplicated_on_save(self, tmp_path):
        # the dp-replicated leaf ("full", spec P()) exists on all 8
        # devices but must be written exactly once
        mgr = ShardedCheckpointManager(str(tmp_path))
        mgr.save(0, _sharded_state(_mesh(4, 2)))
        manifest = json.loads((tmp_path / "0" / "manifest.json").read_text())
        leaves = manifest["leaves"]
        full_key = next(k for k in leaves if "full" in k)
        w_key = next(k for k in leaves if "'w'" in k)
        assert len(leaves[full_key]["shards"]) == 1
        assert len(leaves[w_key]["shards"]) == 8  # 4x2 distinct tiles

    def test_no_commit_marker_means_invisible(self, tmp_path):
        mgr = ShardedCheckpointManager(str(tmp_path))
        mgr.save(1, _sharded_state(_mesh(4, 2)))
        # simulate a writer killed between the data/manifest writes and
        # the commit rename: a full step directory minus COMMIT
        mgr.save(2, _sharded_state(_mesh(4, 2)))
        os.remove(str(tmp_path / "2" / COMMIT_NAME))
        assert mgr.all_steps() == [1]
        assert mgr.latest_step() == 1
        assert mgr.uncommitted_steps() == [2]
        assert mgr.restore(_template(_mesh(4, 2)))[0] == 1

    def test_cleanup_partial_removes_debris_not_excluded(self, tmp_path):
        mgr = ShardedCheckpointManager(str(tmp_path))
        mgr.save(1, _sharded_state(_mesh(4, 2)))
        for junk in (2, 3):
            os.makedirs(str(tmp_path / str(junk)))
        assert mgr.cleanup_partial(exclude=[3]) == [2]
        assert not (tmp_path / "2").exists()
        assert (tmp_path / "3").exists()     # mid-write step protected
        assert mgr.all_steps() == [1]

    def test_atomic_write_leaves_no_temp_droppings(self, tmp_path):
        atomic_write_bytes(str(tmp_path / "x"), b"payload")
        assert sorted(os.listdir(tmp_path)) == ["x"]
        assert (tmp_path / "x").read_bytes() == b"payload"

    def test_max_to_keep_prunes_oldest(self, tmp_path):
        mgr = ShardedCheckpointManager(str(tmp_path), max_to_keep=2)
        state = _sharded_state(_mesh(4, 2))
        for step in (1, 2, 3, 4):
            mgr.save(step, state)
        assert mgr.all_steps() == [3, 4]


# ---------------------------------------------------------------------------
# elastic (mesh-reshape) restore
# ---------------------------------------------------------------------------

class TestElasticRestore:
    @pytest.mark.parametrize("target", ["dp2tp4", "dp8tp1", "single"])
    def test_reshape_restore_matches(self, tmp_path, target):
        """Save under dp=4×tp=2; restore under a different layout. The
        acceptance matrix: values must be identical bit-for-bit."""
        save_mesh = _mesh(4, 2)
        state = _sharded_state(save_mesh)
        mgr = ShardedCheckpointManager(str(tmp_path))
        mgr.save(7, state)

        tmpl_mesh = {"dp2tp4": _mesh(2, 4), "dp8tp1": _mesh(8, 1),
                     "single": None}[target]
        step, restored = mgr.restore(_template(tmpl_mesh))
        assert step == 7
        _assert_state_equal(restored, state)
        if tmpl_mesh is not None:
            # the restore landed in the TARGET layout, not the saved one
            restored_w = restored["params"]["w"]
            assert restored_w.sharding.mesh.shape == dict(
                tmpl_mesh.shape)

    def test_single_device_save_restores_onto_mesh(self, tmp_path):
        # the reverse direction: unsharded save, sharded restore
        plain = {"params": {"w": jnp.arange(64.0).reshape(8, 8),
                            "b": jnp.arange(8.0)},
                 "full": jnp.arange(16.0).reshape(4, 4),
                 "step": jnp.asarray(3, jnp.int32)}
        mgr = ShardedCheckpointManager(str(tmp_path))
        mgr.save(0, plain)
        _, restored = mgr.restore(_template(_mesh(2, 4)))
        _assert_state_equal(restored, plain)

    def test_structure_mismatch_raises(self, tmp_path):
        mgr = ShardedCheckpointManager(str(tmp_path))
        mgr.save(0, _sharded_state(_mesh(4, 2)))
        with pytest.raises(ValueError, match="no leaf"):
            mgr.restore_step(0, {"something": jnp.zeros((8, 8))})

    def test_global_shape_mismatch_raises(self, tmp_path):
        mgr = ShardedCheckpointManager(str(tmp_path))
        mgr.save(0, _sharded_state(_mesh(4, 2)))
        bad = _template(_mesh(4, 2))
        bad["full"] = jnp.zeros((2, 2))
        with pytest.raises(ValueError, match="global shape"):
            mgr.restore_step(0, bad)


# ---------------------------------------------------------------------------
# shard-level fault injection -> checksum detection -> fallback
# ---------------------------------------------------------------------------

class TestIntegrityFaults:
    def _two_steps(self, tmp_path):
        mesh = _mesh(4, 2)
        mgr = ShardedCheckpointManager(str(tmp_path))
        mgr.save(1, _sharded_state(mesh, scale=1.0))
        mgr.save(2, _sharded_state(mesh, scale=2.0))
        return mgr, mesh

    @pytest.mark.parametrize("kind", ["bitflip", "truncate", "missing"])
    def test_single_damaged_shard_detected_and_fallback(self, tmp_path,
                                                        kind):
        mgr, mesh = self._two_steps(tmp_path)
        # leaf 2 is params['w'] in keystr order ('full', params 'b', 'w',
        # 'step'): the 4×2-sharded leaf, so shard 3 is one of 8 tiles
        corrupt_shard(str(tmp_path), 2, leaf=2, shard=3, kind=kind)
        # direct restore of the damaged step: the checksum catches it
        with pytest.raises(CheckpointCorruptionError):
            mgr.restore_step(2, _template(mesh))
        # through the retry layer: fall back to the older committed step
        rmgr = RetryingCheckpointManager(mgr, backoff_base=0.0)
        step, restored = rmgr.restore_latest(_template(mesh))
        assert step == 1
        _assert_state_equal(restored, _sharded_state(mesh, scale=1.0))
        assert rmgr.telemetry["verify_failures"] == 1
        assert rmgr.telemetry["restore_fallbacks"] == 1
        assert rmgr.telemetry["deleted_corrupt"] == 1
        assert mgr.all_steps() == [1]

    def test_torn_manifest_detected(self, tmp_path):
        mgr, mesh = self._two_steps(tmp_path)
        tear_manifest(str(tmp_path), 2)
        with pytest.raises(CheckpointCorruptionError, match="manifest"):
            mgr.restore_step(2, _template(mesh))
        rmgr = RetryingCheckpointManager(mgr, backoff_base=0.0)
        assert rmgr.restore_latest(_template(mesh))[0] == 1

    def test_verify_step_raises_with_problem_list(self, tmp_path):
        mgr, _ = self._two_steps(tmp_path)
        mgr.verify_step(2)  # healthy: no raise
        corrupt_shard(str(tmp_path), 2, kind="bitflip")
        with pytest.raises(CheckpointCorruptionError, match="sha256"):
            mgr.verify_step(2)


# ---------------------------------------------------------------------------
# async saves: retry on the writer, drain vs abandon, partial cleanup
# ---------------------------------------------------------------------------

class _ExplodingManager(ShardedCheckpointManager):
    """Fails the first N write attempts AFTER creating partial debris —
    the disk-full-mid-write shape the cleanup satellite targets."""

    def __init__(self, directory, explosions, **kw):
        super().__init__(directory, **kw)
        self.explosions = explosions

    def write_snapshot(self, step, snap, *, force=False):
        if self.explosions > 0:
            self.explosions -= 1
            os.makedirs(self._step_dir(step), exist_ok=True)
            with open(os.path.join(self._step_dir(step),
                                   "leaf0000_s00.npy"), "wb") as f:
                f.write(b"partial")
            raise IOError("injected: disk full mid-write")
        return super().write_snapshot(step, snap, force=force)


class TestAsyncSaves:
    def test_async_save_returns_before_commit_and_drains(self, tmp_path):
        state = _sharded_state(_mesh(4, 2))
        inj = FaultInjector(save_delays={1: 0.3})
        rmgr = RetryingCheckpointManager(
            ShardedCheckpointManager(str(tmp_path)), backoff_base=0.0,
            before_save=inj.before_checkpoint_save)
        t0 = time.monotonic()
        assert rmgr.save(1, state) is True
        accepted_in = time.monotonic() - t0
        # only the host snapshot blocked the caller, not the delayed write
        assert accepted_in < 0.25
        assert rmgr.pending_saves == [1]
        rmgr.drain()
        assert rmgr.manager.all_steps() == [1]
        assert verify_directory(str(tmp_path))[0].status == "ok"
        rmgr.close()

    def test_writer_errors_surface_in_retry_loop(self, tmp_path):
        state = _sharded_state(_mesh(4, 2))
        inj = FaultInjector(save_failures={1: 2})
        rmgr = RetryingCheckpointManager(
            ShardedCheckpointManager(str(tmp_path)), max_retries=3,
            backoff_base=0.0, before_save=inj.before_checkpoint_save)
        assert rmgr.save(1, state) is True
        rmgr.drain()
        assert rmgr.manager.all_steps() == [1]   # retried to success
        assert rmgr.telemetry["save_retries"] == 2
        assert rmgr.telemetry["save_failures"] == 0
        rmgr.close()

    def test_terminal_writer_failure_counted_step_absent(self, tmp_path):
        state = _sharded_state(_mesh(4, 2))
        inj = FaultInjector(save_failures={1: 99})
        rmgr = RetryingCheckpointManager(
            ShardedCheckpointManager(str(tmp_path)), max_retries=2,
            backoff_base=0.0, before_save=inj.before_checkpoint_save)
        rmgr.save(1, state)
        rmgr.drain()
        assert rmgr.manager.all_steps() == []
        assert rmgr.telemetry["save_failures"] == 1
        rmgr.close()

    def test_forced_save_drains_inflight_write(self, tmp_path):
        state = _sharded_state(_mesh(4, 2))
        inj = FaultInjector(save_delays={1: 0.3})
        rmgr = RetryingCheckpointManager(
            ShardedCheckpointManager(str(tmp_path)), backoff_base=0.0,
            drain_on_force=True, before_save=inj.before_checkpoint_save)
        rmgr.save(1, state)
        assert rmgr.save(2, state, force=True) is True
        # the emergency save waited for the pending write: both committed
        assert rmgr.manager.all_steps() == [1, 2]
        assert rmgr.telemetry["saves_abandoned"] == 0
        rmgr.close()

    def test_forced_save_abandons_queued_write(self, tmp_path):
        state = _sharded_state(_mesh(4, 2))
        inj = FaultInjector(save_delays={1: 0.5})
        rmgr = RetryingCheckpointManager(
            ShardedCheckpointManager(str(tmp_path)), backoff_base=0.0,
            drain_on_force=False, before_save=inj.before_checkpoint_save)
        rmgr.save(1, state)   # running (held by the delay)
        rmgr.save(2, state)   # queued behind it on the single writer
        assert rmgr.save(3, state, force=True) is True
        # the running write still commits (atomicity holds), the queued
        # one is dropped, the emergency save lands — never a torn step
        assert rmgr.manager.all_steps() == [1, 3]
        assert rmgr.telemetry["saves_abandoned"] == 1
        assert all(r.status == "ok" for r in verify_directory(
            str(tmp_path)))
        rmgr.close()

    def test_failed_attempts_sweep_their_partial_debris(self, tmp_path):
        state = _sharded_state(_mesh(4, 2))
        mgr = _ExplodingManager(str(tmp_path), explosions=2)
        rmgr = RetryingCheckpointManager(mgr, max_retries=3,
                                         backoff_base=0.0,
                                         async_writes=False)
        assert rmgr.save(1, state, force=True) is True
        assert rmgr.telemetry["partials_cleaned"] == 2
        assert mgr.uncommitted_steps() == []
        assert mgr.all_steps() == [1]

    def test_restore_sweeps_and_never_adopts_partials(self, tmp_path):
        mesh = _mesh(4, 2)
        mgr = ShardedCheckpointManager(str(tmp_path))
        mgr.save(1, _sharded_state(mesh))
        os.makedirs(str(tmp_path / "9"))   # interrupted-save debris
        rmgr = RetryingCheckpointManager(mgr, backoff_base=0.0)
        step, _ = rmgr.restore_latest(_template(mesh))
        assert step == 1
        assert rmgr.telemetry["partials_cleaned"] == 1
        assert not (tmp_path / "9").exists()
        rmgr.close()

    def test_donated_buffers_cannot_corrupt_inflight_snapshot(self,
                                                              tmp_path):
        # the snapshot must deep-copy: overwrite the source arrays while
        # the (delayed) write is in flight, then restore and compare
        mesh = _mesh(4, 2)
        state = _sharded_state(mesh, scale=1.0)
        expect = jax.device_get(state)
        inj = FaultInjector(save_delays={1: 0.3})
        rmgr = RetryingCheckpointManager(
            ShardedCheckpointManager(str(tmp_path)), backoff_base=0.0,
            before_save=inj.before_checkpoint_save)
        rmgr.save(1, state)
        # donate every param buffer while the write is still sleeping:
        # if the snapshot aliased device memory the checksum would be a
        # valid hash of garbage
        clobber = jax.jit(lambda x: x * -7.0, donate_argnums=0)
        state["params"] = jax.tree.map(clobber, state["params"])
        jax.block_until_ready(state["params"])
        rmgr.drain()
        _, restored = rmgr.restore_latest(_template(mesh))
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
            jax.device_get(restored), expect)
        rmgr.close()


# ---------------------------------------------------------------------------
# preemption during an in-flight async save (satellite 3)
# ---------------------------------------------------------------------------

TARGET = jnp.full((4, 4), 0.3)


def _loss_fn(p, batch, rng):
    pred = batch["x"] @ p["w"] + p["b"]
    return jnp.mean((pred - batch["y"]) ** 2)


def _batch_fn(step):
    x = jax.random.normal(jax.random.PRNGKey(step), (8, 4))
    return {"x": x, "y": x @ TARGET}


def _fresh():
    from apex_tpu.optimizers import FusedSGD
    opt = FusedSGD(lr=0.05)
    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    return make_train_state(params, opt.init(params))


def _step_fn():
    from apex_tpu.optimizers import FusedSGD
    return make_resilient_train_step(_loss_fn, FusedSGD(lr=0.05))


def _cfg(**kw):
    base = dict(poll_interval_steps=2, save_interval_steps=4,
                max_consecutive_skips=3, min_history=4,
                save_backoff_base=0.0, handle_sigterm=False)
    base.update(kw)
    return ResilienceConfig(**base)


class TestPreemptionDuringAsyncSave:
    @pytest.mark.parametrize("drain", [True, False])
    def test_committed_set_is_never_torn(self, tmp_path, drain):
        """Preempt while the step-8 save is still on the writer. The
        committed-step set afterward must be exactly the pre-save set or
        include the new step — and every committed step must pass a deep
        fsck; an uncommitted hybrid may exist only as invisible debris."""
        run_dir = str(tmp_path / "run")
        inj = FaultInjector(save_delays={8: 0.4}, preempt_at_call=8)
        res = run_training(_step_fn(), _fresh(), _batch_fn, 40,
                           checkpoint_dir=run_dir,
                           config=_cfg(preemption_drain=drain,
                                       save_final=False),
                           fault_injector=inj)
        assert res.status == "preempted"
        assert res.telemetry["emergency_saves"] == 1
        reports = verify_directory(run_dir)
        committed = [r.step for r in reports if r.status != "uncommitted"]
        assert all(r.status == "ok" for r in reports
                   if r.step in committed), reports
        # pre-save set {4} plus the new step(s): 8 from the drained (or
        # still-running) write and/or the forced emergency save
        assert 4 in committed and 8 in committed
        assert res.telemetry["ckpt_save_failures"] == 0

        # and the run is resumable from what was committed
        resumed = run_training(_step_fn(), _fresh(), _batch_fn, 12,
                               checkpoint_dir=run_dir, config=_cfg())
        assert resumed.status == "completed"
        assert resumed.telemetry["resumes"] == 1
        assert resumed.steps_completed == 12

    def test_writer_killed_before_commit_leaves_invisible_debris(
            self, tmp_path):
        # the on-disk shape a hard kill mid-write leaves: shards +
        # manifest, no COMMIT. restore_latest must not see it, and the
        # next resume sweeps it.
        mesh = _mesh(4, 2)
        mgr = ShardedCheckpointManager(str(tmp_path))
        mgr.save(1, _sharded_state(mesh))
        mgr.save(2, _sharded_state(mesh, scale=2.0))
        os.remove(str(tmp_path / "2" / COMMIT_NAME))
        rmgr = RetryingCheckpointManager(mgr, backoff_base=0.0)
        step, _ = rmgr.restore_latest(_template(mesh))
        assert step == 1
        assert rmgr.telemetry["restore_fallbacks"] == 0  # never adopted
        assert mgr.uncommitted_steps() == []             # swept
        rmgr.close()


# ---------------------------------------------------------------------------
# fsck CLI (satellite 1)
# ---------------------------------------------------------------------------

class TestVerifyCLI:
    def _populate(self, root):
        mesh = _mesh(4, 2)
        mgr = ShardedCheckpointManager(str(root), max_to_keep=10)
        mgr.save(1, _sharded_state(mesh))
        mgr.save(2, _sharded_state(mesh, scale=2.0))
        mgr.save(3, _sharded_state(mesh, scale=3.0))
        corrupt_shard(str(root), 2, kind="bitflip")
        os.remove(str(root / "3" / COMMIT_NAME))  # uncommitted debris

    def test_verify_main_exit_codes_and_listing(self, tmp_path, capsys):
        self._populate(tmp_path)
        assert verify_main(["verify", str(tmp_path)]) == 1  # damage
        out = capsys.readouterr().out
        assert "adoptable steps: [1]" in out
        assert "DAMAGED steps:   [2]" in out
        assert "uncommitted" in out and "sha256 mismatch" in out

    def test_verify_clean_dir_exits_zero(self, tmp_path):
        mgr = ShardedCheckpointManager(str(tmp_path))
        mgr.save(1, _sharded_state(_mesh(4, 2)))
        assert verify_main(["verify", str(tmp_path)]) == 0

    def test_gc_removes_uncommitted_only(self, tmp_path, capsys):
        self._populate(tmp_path)
        verify_main(["verify", str(tmp_path), "--gc"])
        capsys.readouterr()
        assert not (tmp_path / "3").exists()
        assert (tmp_path / "2").exists()  # damaged-but-committed is kept

    def test_shallow_misses_bitflip_catches_truncation(self, tmp_path):
        mgr = ShardedCheckpointManager(str(tmp_path))
        mgr.save(1, _sharded_state(_mesh(4, 2)))
        corrupt_shard(str(tmp_path), 1, kind="bitflip")
        assert verify_main(["verify", str(tmp_path), "--shallow"]) == 0
        assert verify_main(["verify", str(tmp_path)]) == 1
        corrupt_shard(str(tmp_path), 1, leaf=2, shard=1, kind="truncate")
        assert verify_main(["verify", str(tmp_path), "--shallow"]) == 1

    def test_cli_subprocess_contract(self, tmp_path):
        """The real entry point: ``python -m apex_tpu.checkpoint verify``
        exits non-zero on damage, zero once the damage is gone."""
        self._populate(tmp_path)
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        proc = subprocess.run(
            [sys.executable, "-m", "apex_tpu.checkpoint", "verify",
             str(tmp_path)],
            capture_output=True, text=True, timeout=300, env=env)
        assert proc.returncode == 1, proc.stderr
        assert "DAMAGED" in proc.stdout
        import shutil
        shutil.rmtree(str(tmp_path / "2"))
        proc = subprocess.run(
            [sys.executable, "-m", "apex_tpu.checkpoint", "verify",
             str(tmp_path)],
            capture_output=True, text=True, timeout=300, env=env)
        assert proc.returncode == 0, proc.stderr


# ---------------------------------------------------------------------------
# monitor reconciliation of checkpoint telemetry
# ---------------------------------------------------------------------------

class TestCheckpointTelemetryReconciliation:
    def test_counters_and_events_reconcile(self, tmp_path):
        run_dir = str(tmp_path / "run")

        # run 1: a transient save failure exercises the retry counters
        jsonl1 = str(tmp_path / "run1.jsonl")
        reg1 = MetricsRegistry([JsonlSink(jsonl1)])
        inj = FaultInjector(save_failures={4: 1})
        res1 = run_training(_step_fn(), _fresh(), _batch_fn, 8,
                            checkpoint_dir=run_dir,
                            config=_cfg(metrics=reg1, save_final=False),
                            fault_injector=inj)
        reg1.close()
        assert res1.status == "completed"
        assert res1.telemetry["ckpt_save_retries"] == 1
        report1 = build_report(jsonl1)
        assert report1["counters"] == res1.telemetry

        # damage the newest step, then resume: checksum-verified fallback
        corrupt_shard(run_dir, 8, kind="bitflip")
        jsonl2 = str(tmp_path / "run2.jsonl")
        reg2 = MetricsRegistry([JsonlSink(jsonl2)])
        res2 = run_training(_step_fn(), _fresh(), _batch_fn, 12,
                            checkpoint_dir=run_dir,
                            config=_cfg(metrics=reg2))
        reg2.close()
        assert res2.status == "completed"
        assert res2.telemetry["resumes"] == 1
        assert res2.telemetry["ckpt_verify_failures"] == 1
        assert res2.telemetry["ckpt_restore_fallbacks"] == 1
        assert res2.telemetry["ckpt_deleted_corrupt"] == 1

        report2 = build_report(jsonl2)
        # the headline contract: the monitor's final counter snapshot IS
        # the result telemetry, ckpt_* keys included
        assert report2["counters"] == res2.telemetry
        # and the checkpoints section reconciles event-for-counter
        ckpt = report2["checkpoints"]
        assert ckpt is not None
        for event, counter in CHECKPOINT_INCIDENT_COUNTERS.items():
            assert ckpt["counts"].get(event, 0) == \
                report2["counters"].get(counter, 0), (event, counter)
        # write/snapshot histograms observed
        assert ckpt["timings"]["ckpt_write_s"]["count"] >= 1
        assert ckpt["timings"]["ckpt_snapshot_blocked_s"]["count"] >= 1

    def test_render_includes_checkpoint_section(self, tmp_path):
        from apex_tpu.observability import render_report

        jsonl = str(tmp_path / "run.jsonl")
        reg = MetricsRegistry([JsonlSink(jsonl)])
        run_training(_step_fn(), _fresh(), _batch_fn, 8,
                     checkpoint_dir=str(tmp_path / "run"),
                     config=_cfg(metrics=reg))
        reg.close()
        text = render_report(build_report(jsonl))
        assert "checkpoints:" in text
        assert "save attempts:" in text
