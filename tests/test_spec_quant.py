"""int8 KV quantization + self-speculative decoding (PR 12).

Four contracts, layered bottom-up:

- **Windowed op**: a ``w``-row verify window through
  :func:`fused_paged_decode_attention` is BITWISE ``w`` sequential
  single-row calls on the float path (the claim the engine's
  speculative acceptance rests on), and the interpret-mode kernel
  matches the reference on quantized pools to numerical tolerance.
- **int8 engine**: greedy traffic through the ``kv_dtype="int8"``
  engine is token-exact against the bf16 default, and the per-page
  scale sidecar honors the page lifecycle (fresh pages enter at scale
  0, quarantine scrubs zero content AND scales —
  ``PagePool.check(k_scales, v_scales)`` asserts it).
- **Speculative engine**: greedy AND seeded sampled streams are
  token-for-token what the non-speculative engine emits — speculation
  may only change HOW MANY forwards produced them (``decode_steps``
  strictly drops on repeated text while ``tokens_generated``
  reconciles) — and the sampled stream's frequencies match the target
  distribution (seeded chi-square, deterministic by construction).
- **Observability**: draft counters, the ``spec_accept_rate``
  histogram, and the ``kv_bytes_per_step`` gauge flow through the
  JSONL log and render in ``python -m apex_tpu.monitor`` key-for-key
  with the registry.

Slow tier: the tp=2 quantized ShardedEngine cross and speculation
under a supervisor restart (compile-bound; ROADMAP).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.models.generation import _cached_forward, init_kv_caches
from apex_tpu.observability import (
    InMemorySink,
    JsonlSink,
    MetricsRegistry,
    build_report,
    render_report,
)
from apex_tpu.ops import _support, fused_paged_decode_attention
from apex_tpu.ops.decode_attention import (
    _pallas,
    _reference,
    paged_pages_for,
    paged_quant_fill,
    paged_quant_scatter,
)
from apex_tpu.serving import (
    EngineConfig,
    EngineSupervisor,
    InferenceEngine,
    Request,
    SamplingParams,
)
from apex_tpu.serving.speculation import propose_draft
from apex_tpu.testing_faults import ServingFaultInjector


@pytest.fixture(autouse=True)
def _pallas_off(monkeypatch):
    """Pin the jnp reference path (same rationale as
    tests/test_serving_paged.py): the bitwise claims below hold for the
    reference dispatch; the interpret-mode kernel is compared to
    tolerance, explicitly."""
    monkeypatch.setenv("APEX_TPU_FORCE_PALLAS", "off")
    _support.pallas_mode.cache_clear()
    yield
    _support.pallas_mode.cache_clear()


@pytest.fixture(scope="module")
def small():
    model = GPTModel(TransformerConfig(
        num_layers=2, hidden_size=32, num_attention_heads=4, vocab_size=64,
        max_position_embeddings=64, hidden_dropout=0.0,
        attention_dropout=0.0))
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _repeated_prompt(period, length):
    return (list(period) * (length // len(period) + 1))[:length]


def _mixed_requests(seed=7):
    """Repeated-text prompts (the speculation-friendly shape), mixed
    greedy/sampled — the cross-engine parity traffic."""
    rng = np.random.RandomState(seed)
    specs = [(12, 8, SamplingParams()),
             (16, 6, SamplingParams(temperature=0.8, top_k=8, seed=3)),
             (8, 10, SamplingParams()),
             (12, 5, SamplingParams(temperature=1.1, seed=9)),
             (16, 7, SamplingParams(temperature=0.7, top_k=16, seed=5))]
    out = []
    for n, m, s in specs:
        period = rng.randint(0, 64, size=4).tolist()
        out.append(Request(prompt=_repeated_prompt(period, n),
                           max_new_tokens=m, sampling=s))
    return out


# ---------------------------------------------------------------------------
# n-gram drafter


class TestProposeDraft:
    def test_repeated_text_continues_the_period(self):
        ctx = [3, 7, 9, 3, 7, 9, 3, 7]
        assert propose_draft(ctx, 3) == [9, 3, 7]

    def test_prefers_longest_matching_suffix(self):
        # suffix [5, 1] last recurred before a 2; the shorter [1] also
        # occurs before a 9 — the longer order must win
        ctx = [5, 1, 2, 9, 1, 9, 5, 1]
        assert propose_draft(ctx, 1) == [2]

    def test_no_match_repeats_last_token(self):
        assert propose_draft([1, 2, 3, 4], 2) == [4, 4]

    def test_zero_and_empty(self):
        assert propose_draft([1, 2, 3], 0) == []
        assert propose_draft([], 2) == [0, 0]


# ---------------------------------------------------------------------------
# the windowed / quantized op


def _window_case(seed, b=3, kvh=2, group=2, dh=8, page_size=8, pps=4, w=3):
    """A w-row window case: each slot's page table covers its window."""
    keys = jax.random.split(jax.random.PRNGKey(seed), 5)
    n_pages = b * pps + 2
    hl = kvh * group
    f = kvh * dh
    q = jax.random.normal(keys[0], (b, w, hl, dh), jnp.float32)
    k_new = jax.random.normal(keys[1], (b, w, f), jnp.float32)
    v_new = jax.random.normal(keys[2], (b, w, f), jnp.float32)
    k_pages = jax.random.normal(keys[3], (n_pages, page_size, f))
    v_pages = jax.random.normal(keys[4], (n_pages, page_size, f))
    positions = jnp.asarray([0, page_size - 1, 2 * page_size + 3])[:b]
    pt = np.full((b, pps), n_pages, np.int32)
    perm = np.random.RandomState(seed).permutation(b * pps)
    nxt = 0
    for r in range(b):
        for j in range(paged_pages_for(int(positions[r]) + w, page_size)):
            pt[r, j] = perm[nxt]
            nxt += 1
    return q, k_new, v_new, k_pages, v_pages, jnp.asarray(pt), positions


def _quantize_pools(k_pages, v_pages):
    """Round-trip float pools into (int8 pool, scale sidecar) pairs."""
    n_pages, ps, f = k_pages.shape
    kvh = 2
    zk = jnp.zeros((n_pages, ps, f), jnp.int8)
    zs = jnp.zeros((n_pages, kvh), jnp.float32)
    dest = jnp.arange(n_pages, dtype=jnp.int32)
    k_q, k_s = paged_quant_fill(zk, zs, k_pages, dest)
    v_q, v_s = paged_quant_fill(zk, zs, v_pages, dest)
    return k_q, k_s, v_q, v_s


class TestWindowedOp:
    def test_window_matches_sequential_rows_bitwise(self):
        """The acceptance rule's foundation: context row ``t`` of one
        w=3 windowed call is BITWISE the single-row call at
        ``positions + t`` (float pools; reference dispatch)."""
        q, k_new, v_new, kp, vp, pt, pos = _window_case(0)
        w = q.shape[1]
        ctx_w, kw, vw = fused_paged_decode_attention(
            q, k_new, v_new, kp, vp, pt, pos, queries_per_group=2)
        kp_s, vp_s = kp, vp
        for t in range(w):
            ctx_t, kp_s, vp_s = fused_paged_decode_attention(
                q[:, t], k_new[:, t], v_new[:, t], kp_s, vp_s, pt,
                pos + t, queries_per_group=2)
            np.testing.assert_array_equal(np.asarray(ctx_w[:, t]),
                                          np.asarray(ctx_t))
        np.testing.assert_array_equal(np.asarray(kw), np.asarray(kp_s))
        np.testing.assert_array_equal(np.asarray(vw), np.asarray(vp_s))

    def test_quantized_reference_close_to_float(self):
        """int8 pools with per-page scales reproduce the float context
        to quantization tolerance (the dequantize-inside-the-op
        contract)."""
        q, k_new, v_new, kp, vp, pt, pos = _window_case(1)
        ctx_f, _, _ = fused_paged_decode_attention(
            q, k_new, v_new, kp, vp, pt, pos, queries_per_group=2)
        k_q, k_s, v_q, v_s = _quantize_pools(kp, vp)
        ctx_q, _, _, _, _ = fused_paged_decode_attention(
            q, k_new, v_new, k_q, v_q, pt, pos, queries_per_group=2,
            k_scales=k_s, v_scales=v_s)
        np.testing.assert_allclose(np.asarray(ctx_q), np.asarray(ctx_f),
                                   atol=0.08, rtol=0.1)

    def test_interpret_kernel_quantized_matches_reference(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_FORCE_PALLAS", "interpret")
        _support.pallas_mode.cache_clear()
        try:
            q, k_new, v_new, kp, vp, pt, pos = _window_case(2)
            k_q, k_s, v_q, v_s = _quantize_pools(kp, vp)
            out_k = _pallas(q, k_new, v_new, k_q, v_q, k_s, v_s, pt, pos,
                            group=2, sliding_window=None)
            out_r = _reference(q, k_new, v_new, k_q, v_q, k_s, v_s, pt,
                               pos, group=2, sliding_window=None)
            np.testing.assert_allclose(np.asarray(out_k[0]),
                                       np.asarray(out_r[0]),
                                       atol=2e-5, rtol=2e-5)
            for a, b in zip(out_k[1:], out_r[1:]):   # pools + scales
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        finally:
            _support.pallas_mode.cache_clear()

    def test_scale_grows_monotonically_and_rescales_residents(self):
        """Rescale-on-append: a page's scale only ever grows; resident
        rows are rescaled by old/new so their dequantized values
        survive; a zero-scale (fresh) page quantizes at exactly the
        incoming rows' absmax / 127."""
        ps, f, kvh = 4, 8, 2
        pages = jnp.zeros((2, ps, f), jnp.int8)
        scales = jnp.zeros((2, kvh), jnp.float32)
        row0 = jnp.full((1, f), 0.5, jnp.float32)
        pages, scales = paged_quant_scatter(
            pages, scales, row0, jnp.asarray([0]), jnp.asarray([0]))
        np.testing.assert_allclose(np.asarray(scales[0]), 0.5 / 127.0)
        deq0 = np.asarray(pages[0, 0], np.float32) * \
            np.repeat(np.asarray(scales[0]), f // kvh)
        np.testing.assert_allclose(deq0, 0.5, rtol=1e-2)
        # a larger row lands on the same page: scale grows, row 0's
        # dequantized value is preserved through the resident rescale
        row1 = jnp.full((1, f), 2.0, jnp.float32)
        pages, scales = paged_quant_scatter(
            pages, scales, row1, jnp.asarray([0]), jnp.asarray([1]))
        np.testing.assert_allclose(np.asarray(scales[0]), 2.0 / 127.0)
        deq0 = np.asarray(pages[0, 0], np.float32) * \
            np.repeat(np.asarray(scales[0]), f // kvh)
        np.testing.assert_allclose(deq0, 0.5, rtol=0.05)
        # untouched page: still zero scale, zero content
        assert not np.asarray(scales[1]).any()
        assert not np.asarray(pages[1]).any()

    def test_sentinel_window_rows_drop(self):
        """Window rows landing past the page table's span (or on
        unmapped sentinel entries) drop instead of clobbering the
        slot's own last mapped page."""
        q, k_new, v_new, kp, vp, _, _ = _window_case(3, b=1)
        before = np.asarray(kp)
        # a fully-unmapped 1-page table at a position past its span:
        # all three window rows must drop, the pool is untouched
        _, kk, _ = fused_paged_decode_attention(
            q, k_new, v_new, kp, vp,
            jnp.full((1, 1), kp.shape[0], jnp.int32),
            jnp.asarray([2 * kp.shape[1] + 3]), queries_per_group=2)
        np.testing.assert_array_equal(np.asarray(kk), before)


# ---------------------------------------------------------------------------
# int8 engine


class TestInt8Engine:
    def test_int8_greedy_token_exact_vs_bf16(self, small):
        """The acceptance bar: greedy traffic through the int8 pool is
        TOKEN-EXACT against the bf16 default (argmax margins of the
        logits dominate the quantization error), zero retraces."""
        model, params = small

        def greedy():
            return [r for r in _mixed_requests()
                    if r.sampling.temperature == 0.0]

        ref_eng = InferenceEngine(model, params, EngineConfig(
            max_slots=3, max_len=32, page_size=4))
        with ref_eng:
            ref = ref_eng.serve(greedy())
        q_eng = InferenceEngine(model, params, EngineConfig(
            max_slots=3, max_len=32, page_size=4, kv_dtype="int8"))
        with q_eng:
            out = q_eng.serve(greedy())
            assert q_eng.decode_retraces == 0
            q_eng.pages.check()
        for a, b in zip(ref, out):
            assert a.finish_reason == b.finish_reason
            assert a.tokens == b.tokens, (a.tokens, b.tokens)

    @pytest.mark.slow  # quarantine x int8 feature-cross: slow tier (ROADMAP)

    def test_quarantine_scrubs_scales_and_check_asserts_it(self, small):
        """Poisoned decode on the int8 engine: the scrub zeroes the
        victim's pages AND their scale sidecar rows;
        ``PagePool.check(k_scales, v_scales)`` — the invariant extended
        for quantized pools — passes after, and a synthetic dirty scale
        on a scrubbed free page makes it throw."""
        from apex_tpu.serving.slots import PageError

        model, params = small
        inj = ServingFaultInjector(poison_decode={0: (0, "nonfinite")})
        eng = InferenceEngine(model, params, EngineConfig(
            max_slots=1, max_len=16, page_size=4, prefix_cache=False,
            kv_dtype="int8"), faults=inj)
        victim = Request(prompt=_repeated_prompt([9, 2, 5, 1], 6),
                         max_new_tokens=6)
        with eng:
            res = eng.serve([victim])
            assert res[0].finish_reason == "error"
            assert eng.pages.free_count == eng.pages.n_pages
            for (kq, ks), (vq, vs) in eng._caches:
                assert not np.asarray(kq).any()
                assert not np.asarray(vq).any()
                eng.pages.check(np.asarray(ks), np.asarray(vs))
            # check() genuinely bites: a dirty scale on a scrubbed page
            dirty = np.asarray(eng._caches[0][0][1]).copy()
            dirty[next(iter(eng.pages._scrubbed)), 0] = 0.25
            with pytest.raises(PageError, match="scale"):
                eng.pages.check(dirty, dirty)
            # the scrubbed pool serves a fresh request, token-exact

            def clean():
                return Request(prompt=_repeated_prompt([3, 8], 4),
                               max_new_tokens=5)

            ref_eng = InferenceEngine(model, params, EngineConfig(
                max_slots=1, max_len=16, page_size=4,
                prefix_cache=False))
            with ref_eng:
                expect = ref_eng.serve([clean()])[0].tokens
            assert eng.serve([clean()])[0].tokens == expect

    @pytest.mark.slow  # int8 x prefix-cache cross: slow-tier composition
    def test_prefix_sharing_carries_scales(self, small):
        """Two prompts sharing an interned prefix on the int8 engine:
        the second request's suffix-only prefill reads the shared pages
        through their scales — token streams match the bf16 engine's."""
        model, params = small
        shared = _repeated_prompt([4, 11, 7, 2], 8)

        def reqs():
            return [Request(prompt=shared + [5, 9], max_new_tokens=6),
                    Request(prompt=shared + [1], max_new_tokens=6)]

        ref_eng = InferenceEngine(model, params, EngineConfig(
            max_slots=2, max_len=32, page_size=4))
        with ref_eng:
            ref = ref_eng.serve(reqs())
        q_eng = InferenceEngine(model, params, EngineConfig(
            max_slots=2, max_len=32, page_size=4, kv_dtype="int8"))
        with q_eng:
            out = q_eng.serve(reqs())
            assert q_eng.metrics.counters()["prefix_hits"] >= 1
        for a, b in zip(ref, out):
            assert a.tokens == b.tokens


# ---------------------------------------------------------------------------
# speculative engine


class TestSpeculativeEngine:
    def _serve(self, small, cfg_kwargs, reqs, metrics=None):
        model, params = small
        eng = InferenceEngine(model, params, EngineConfig(
            max_slots=3, max_len=32, page_size=4, **cfg_kwargs),
            metrics=metrics)
        with eng:
            out = eng.serve(reqs)
            assert eng.decode_retraces == 0
            counters = eng.metrics.counters()
        return out, counters

    def test_greedy_and_sampled_token_exact_with_acceptance(self, small):
        """THE speculation contract: identical mixed traffic through
        ``speculation=3`` and the plain engine is token-exact (greedy
        and seeded-sampled rows alike), with a strictly smaller
        ``decode_steps`` and a nonzero acceptance on repeated text —
        same tokens, fewer forwards."""
        ref, ref_c = self._serve(small, {}, _mixed_requests())
        out, spec_c = self._serve(small, {"speculation": 3},
                                  _mixed_requests())
        for a, b in zip(ref, out):
            assert a.finish_reason == b.finish_reason
            assert a.tokens == b.tokens, (a.tokens, b.tokens)
        # reconciliation, key-for-key: same tokens out of fewer steps
        assert spec_c["tokens_generated"] == ref_c["tokens_generated"]
        assert spec_c["decode_steps"] < ref_c["decode_steps"]
        assert spec_c["draft_tokens_accepted"] > 0
        assert spec_c["draft_tokens_accepted"] <= \
            spec_c["draft_tokens_proposed"]
        # the plain engine declares the draft counters too (zero-valued)
        assert ref_c["draft_tokens_proposed"] == 0

    @pytest.mark.slow  # speculation x int8 cross: slow-tier composition
    def test_spec_with_int8_token_exact(self, small):
        """Both tentpole knobs at once: int8 pool + speculation, still
        token-exact against the plain bf16 engine."""
        ref, _ = self._serve(small, {}, _mixed_requests(seed=11))
        out, c = self._serve(small, {"speculation": 3,
                                     "kv_dtype": "int8"},
                             _mixed_requests(seed=11))
        for a, b in zip(ref, out):
            assert a.tokens == b.tokens, (a.tokens, b.tokens)
        assert c["draft_tokens_accepted"] > 0

    @pytest.mark.slow  # statistical-distribution sweep: slow tier (ROADMAP)

    def test_sampled_frequencies_match_target_distribution(self, small):
        """Distribution preservation, measured: many seeds sample the
        SECOND generated token (the first one emitted from a verify
        window) of the same repeated-text prompt; its empirical
        frequencies must match the conditional target distribution
        (temperature-scaled, top-k-truncated softmax given the prompt
        plus each request's own first token) under a chi-square at
        alpha = 0.001. Every draw is seeded, so the verdict is
        deterministic — this fails only if the sampling law itself
        drifts."""
        model, params = small
        prompt = _repeated_prompt([5, 9, 3, 7], 16)
        temp, top_k, n_req = 1.0, 8, 200
        reqs = [Request(prompt=prompt, max_new_tokens=3,
                        sampling=SamplingParams(temperature=temp,
                                                top_k=top_k, seed=i))
                for i in range(n_req)]
        eng = InferenceEngine(model, params, EngineConfig(
            max_slots=4, max_len=32, page_size=4, speculation=3))
        with eng:
            results = eng.serve(reqs)
            assert eng.metrics.counters()["draft_tokens_proposed"] > 0

        def target_probs(ids):
            caches = init_kv_caches(model, 1, 32, stacked=False)
            logits, _ = _cached_forward(
                model, params, caches,
                jnp.asarray([ids], jnp.int32), 0, last_only=True)
            row = np.asarray(logits[0, 0], np.float64) / temp
            kth = np.sort(row)[-top_k]
            row[row < kth] = -np.inf
            e = np.exp(row - row.max())
            return e / e.sum()

        # conditional mixture: expected counts sum each first-token
        # group's target distribution for the second token
        firsts = {}
        for r in results:
            firsts.setdefault(r.tokens[0], []).append(r.tokens[1])
        expected = np.zeros(64)
        observed = np.zeros(64)
        for t0, seconds in firsts.items():
            p = target_probs(prompt + [t0])
            assert all(p[t1] > 0 for t1 in seconds), \
                "a sampled token fell outside the top-k support"
            expected += len(seconds) * p
            for t1 in seconds:
                observed[t1] += 1
        # bin tails with expected < 5 into one category (chi-square
        # validity), then test at alpha = 0.001 via Wilson–Hilferty
        big = expected >= 5.0
        obs = np.append(observed[big], observed[~big].sum())
        exp = np.append(expected[big], expected[~big].sum())
        chi2 = float(((obs - exp) ** 2 / np.maximum(exp, 1e-9)).sum())
        df = len(obs) - 1
        crit = df * (1.0 - 2.0 / (9 * df)
                     + 3.09 * np.sqrt(2.0 / (9 * df))) ** 3
        assert chi2 < crit, (chi2, crit, df)

    def test_monitor_renders_spec_and_kv_bytes(self, small, tmp_path):
        """The observability satellite end-to-end: draft counters, the
        spec_accept_rate histogram, and the kv_bytes_per_step gauge
        land in the JSONL log, reconcile key-for-key with the registry,
        and render in the monitor report."""
        model, params = small
        log = tmp_path / "spec.jsonl"
        sink = InMemorySink()
        reg = MetricsRegistry([sink, JsonlSink(str(log))])
        eng = InferenceEngine(model, params, EngineConfig(
            max_slots=3, max_len=32, page_size=4, speculation=3,
            kv_dtype="int8"), metrics=reg)
        with eng:
            eng.serve(_mixed_requests())
            page_read = eng._page_read_bytes
        counters = reg.counters()
        report = build_report(str(log))
        for key in ("draft_tokens_proposed", "draft_tokens_accepted"):
            assert report["counters"][key] == counters[key]
        assert counters["draft_tokens_accepted"] > 0
        hist = report["histograms"]["spec_accept_rate"]
        assert hist["count"] >= 1 and 0.0 <= hist["mean"] <= 1.0
        gauge = report["gauges"]["kv_bytes_per_step"]
        assert gauge > 0 and gauge % page_read == 0
        text = render_report(report)
        assert "speculation: proposed=" in text
        assert "kv bytes/step" in text
        rate = counters["draft_tokens_accepted"] \
            / counters["draft_tokens_proposed"]
        assert f"accept_rate={rate:.1%}" in text


# ---------------------------------------------------------------------------
# slow tier: compile-bound crosses (tp=2 quantized, spec under restart)


class TestSpecQuantSlow:
    @pytest.mark.slow
    def test_tp2_quantized_and_spec_token_exact(self, small):
        """ShardedEngine (tp=2) with the int8 pool and speculation on:
        token-exact against the unsharded bf16 plain engine — the scale
        sidecar shards per-head, the windowed decode body shard_maps
        with the same specs as the plain one."""
        from apex_tpu.serving import ShardedEngine
        from apex_tpu.transformer import parallel_state

        model, params = small
        ref_eng = InferenceEngine(model, params, EngineConfig(
            max_slots=3, max_len=32, page_size=4))
        with ref_eng:
            ref = ref_eng.serve(_mixed_requests(seed=13))
        parallel_state.destroy_model_parallel()
        try:
            parallel_state.initialize_model_parallel(
                tensor_model_parallel_size=2)
            sharded = ShardedEngine(model, params, EngineConfig(
                max_slots=3, max_len=32, page_size=4, kv_dtype="int8",
                speculation=3))
            with sharded:
                out = sharded.serve(_mixed_requests(seed=13))
                assert sharded.decode_retraces == 0
                assert sharded.metrics.counters()[
                    "draft_tokens_accepted"] > 0
        finally:
            parallel_state.destroy_model_parallel()
        for a, b in zip(ref, out):
            assert a.finish_reason == b.finish_reason
            assert a.tokens == b.tokens, (a.tokens, b.tokens)

    @pytest.mark.slow
    def test_spec_supervisor_restart_token_exact(self, small):
        """A decode exception mid-flight with speculation on: the
        supervisor rebuild + re-prefill replays token-exact — restart
        recovery is windowed-decode-agnostic."""
        model, params = small
        ref_eng = InferenceEngine(model, params, EngineConfig(
            max_slots=3, max_len=32, page_size=4))
        with ref_eng:
            expect = [r.tokens
                      for r in ref_eng.serve(_mixed_requests(seed=17)[:3])]
        inj = ServingFaultInjector(decode_raise_calls={2})
        sup = EngineSupervisor(
            model, params,
            EngineConfig(max_slots=3, max_len=32, page_size=4,
                         speculation=3),
            faults=inj)
        with sup:
            results = sup.serve(_mixed_requests(seed=17)[:3])
        assert sup.restarts == 1
        assert [r.tokens for r in results] == expect
