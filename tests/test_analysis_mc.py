"""Fleet model-checker suite (apex_tpu.analysis.mc).

Three gates, per docs/analysis.md#model-checker:

- **green on main**: bounded exploration of the real fleet control
  plane (>= 2 replicas, faults on, depth >= 6) upholds every invariant;
- **mutation gate**: an injected exactly-once protocol bug (a duplicate
  terminal record emitted during drain migration) is caught, minimized,
  and the reproduction replays deterministically from (seed, indices);
- **determinism**: the same schedule always produces the same applied
  trace, counters, and verdict — the property every replay relies on.
"""

import json

import pytest

from apex_tpu.analysis.mc import (
    MCConfig,
    exhaustive,
    explore,
    generate_schedule,
    replay,
    run_schedule,
)
from apex_tpu.analysis.mc.cli import main as mc_main
from apex_tpu.analysis.mc.harness import MUTATIONS


class TestSchedules:
    def test_generation_deterministic(self):
        assert generate_schedule(7, 12) == generate_schedule(7, 12)
        assert len(generate_schedule(7, 12)) == 12

    def test_faults_flag_prunes_vocabulary(self):
        kinds = {ev.kind for s in range(40)
                 for ev in generate_schedule(s, 12, faults=False)}
        assert "fault" not in kinds and "deploy_poisoned" not in kinds

    def test_run_schedule_deterministic(self):
        cfg = MCConfig(depth=10)
        sched = generate_schedule(3, 10)
        r1 = run_schedule(cfg, sched)
        r2 = run_schedule(cfg, sched)
        assert r1.applied == r2.applied
        assert r1.counters == r2.counters
        assert ([vars(v) for v in r1.violations]
                == [vars(v) for v in r2.violations])


class TestExploration:
    def test_bounded_exploration_clean_on_main(self):
        # the acceptance gate: depth >= 6, >= 2 replicas, faults on —
        # zero invariant violations on the unmutated fleet
        er = explore(MCConfig(replicas=2, depth=8, schedules=20,
                              faults=True))
        assert er.ok, er.render()
        assert er.explored == 20

    def test_exploration_serves_real_traffic(self):
        # the checker must actually drive requests through the fleet,
        # not vacuously pass on empty schedules
        sched = [ev for s in range(5)
                 for ev in generate_schedule(s, 12)]
        res = run_schedule(MCConfig(depth=12), sched)
        assert res.ok, [v.render() for v in res.violations]
        assert res.requests > 0
        assert res.counters.get("requests_submitted", 0) >= res.requests

    @pytest.mark.slow
    def test_exhaustive_small_depth_is_proof(self):
        er = exhaustive(MCConfig(replicas=2, depth=4), depth=4)
        assert er.ok, er.render()
        assert er.explored == 4 ** 4      # every schedule, enumerated


class TestMutationGate:
    def test_double_terminal_is_caught_minimized_and_replayable(self):
        assert "double_terminal_drain" in MUTATIONS
        cfg = MCConfig(depth=12, schedules=30,
                       mutation="double_terminal_drain")
        er = explore(cfg)
        assert not er.ok, "mutation gate failed: injected bug not found"
        assert any(v.invariant == "exactly_once"
                   for v in er.failure.violations)
        # minimized: ddmin kept a strict subset of the schedule
        assert len(er.indices) < cfg.depth
        # deterministic replay: (seed, indices) reproduces the violation
        rep = replay(cfg, er.seed, er.indices)
        assert any(v.invariant == "exactly_once" for v in rep.violations)
        # and the same minimized schedule is clean without the mutation
        clean = replay(MCConfig(depth=12, schedules=30),
                       er.seed, er.indices)
        assert clean.ok, [v.render() for v in clean.violations]


class TestCLI:
    def test_explore_clean_exit_zero(self, capsys):
        assert mc_main(["--schedules", "5", "--depth", "6"]) == 0
        assert "no invariant violations" in capsys.readouterr().out

    def test_mutation_exit_one_with_replay_line(self, capsys):
        rc = mc_main(["--schedules", "30", "--depth", "12",
                      "--mutate", "double_terminal_drain"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "VIOLATION" in out and "--replay" in out

    def test_replay_json_roundtrip(self, capsys):
        rc = mc_main(["--replay", "3", "--depth", "8", "--json"])
        assert rc == 0
        data = json.loads(capsys.readouterr().out)
        assert data["seed"] == 3 and data["violations"] == []
        assert data["applied"]

    def test_dispatch_from_analysis_main(self):
        from apex_tpu.analysis.__main__ import _dispatch
        assert _dispatch(["mc", "--schedules", "2", "--depth", "4"]) == 0
