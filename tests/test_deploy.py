"""Continuous-deployment tests: canary scoring, rollback, accounting.

The contract under test (docs/serving.md#continuous-deployment):

- **Pre-flight fsck** — a checkpoint that fails deep verification
  (``corrupt_shard``) is REJECTED before the first drain: no replica
  ever touches it, the fleet stays untouched, ``deploys_rejected``
  reconciles, and the next deploy attempt is not blocked.
- **Value poisoning slips past fsck** — ``corrupt_checkpoint_weights``
  re-checksums after poisoning, so manifest + COMMIT + per-shard
  digests all stay green while every float leaf goes non-finite. Deep
  fsck passes; the one-token health probe passes too (argmax of an
  all-NaN row is a valid token id) — only live canary traffic catches
  it. That gap is exactly what the canary window exists for.
- **Happy path** — deploying the fleet's own saved weights rolls every
  replica through one-drain-at-a-time canary windows and promotes
  each; the fleet stays greedy-token-exact afterwards.
- **Rollback accounting** — a poisoned deploy is detected by the
  canary's live error rate and rolled back: every client request
  exactly one terminal record, migrated requests keep their ORIGINAL
  trace_id, span conservation holds over the deploy-window log, and
  the deploy_* events / counters / records reconcile key-for-key.
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.checkpoint import (
    CheckpointCorruptionError,
    ShardedCheckpointManager,
)
from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.models.generation import generate
from apex_tpu.observability import (
    InMemorySink,
    MetricsRegistry,
    check_span_conservation,
)
from apex_tpu.serving import (
    EngineConfig,
    Request,
    SamplingParams,
    SchedulerConfig,
)
from apex_tpu.serving.fleet import (
    DEPLOY_CANARY,
    DEPLOY_COMPLETE,
    DEPLOY_DRAINING,
    DEPLOY_REJECTED,
    DEPLOY_ROLLED_BACK,
    DEPLOY_ROLLING,
    CanaryConfig,
    Deployment,
    FleetConfig,
    ReplicaFleet,
)
from apex_tpu.testing_faults import (
    corrupt_checkpoint_weights,
    corrupt_shard,
)

#: deployment states during which tests keep feeding live traffic —
#: the canary window needs scored terminals to close
_FEEDING = (DEPLOY_ROLLING, DEPLOY_DRAINING, DEPLOY_CANARY)


@pytest.fixture(scope="module")
def small():
    # 1 layer for the same reason as the fleet suite: every replica
    # rebuild is a fresh compile, and deploy semantics don't need depth
    model = GPTModel(TransformerConfig(
        num_layers=1, hidden_size=32, num_attention_heads=4, vocab_size=64,
        max_position_embeddings=64, hidden_dropout=0.0,
        attention_dropout=0.0))
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _save_step(root, params, step=1):
    directory = str(root)
    ShardedCheckpointManager(directory, max_to_keep=1).save(step, params)
    return directory


def _drain(fleet, cap=20000):
    ticks = 0
    while fleet.inflight_count:
        fleet.tick()
        ticks += 1
        assert ticks < cap, "fleet failed to settle"


def _nonfinite_float_leaves(tree):
    return [leaf for leaf in jax.tree_util.tree_leaves(tree)
            if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)
            and not bool(jnp.isfinite(leaf).all())]


# ---------------------------------------------------------------------------
# the fault primitive


class TestCorruptCheckpointWeights:
    def test_poisons_values_but_fsck_stays_green(self, small, tmp_path):
        _, params = small
        directory = _save_step(tmp_path / "ckpt", params)
        n = corrupt_checkpoint_weights(directory, 1)
        assert n > 0
        mgr = ShardedCheckpointManager(directory, max_to_keep=1)
        # the whole point: manifest, COMMIT, sizes AND shard checksums
        # all verify — the corruption is invisible to fsck
        mgr.verify_step(1, deep=True)
        restored = mgr.restore_step(1, params)
        assert _nonfinite_float_leaves(restored)

    def test_custom_poison_value(self, small, tmp_path):
        _, params = small
        directory = _save_step(tmp_path / "ckpt", params)
        corrupt_checkpoint_weights(directory, 1, value=float("inf"))
        restored = ShardedCheckpointManager(
            directory, max_to_keep=1).restore_step(1, params)
        assert any(bool(jnp.isposinf(leaf).any())
                   for leaf in jax.tree_util.tree_leaves(restored))

    def test_distinct_from_corrupt_shard(self, small, tmp_path):
        """``corrupt_shard`` damages bytes and IS caught by deep fsck;
        ``corrupt_checkpoint_weights`` damages values and is not — the
        two faults sit on opposite sides of the verification gap."""
        _, params = small
        directory = _save_step(tmp_path / "ckpt", params)
        corrupt_shard(directory, 1, kind="bitflip")
        with pytest.raises(CheckpointCorruptionError):
            ShardedCheckpointManager(
                directory, max_to_keep=1).verify_step(1, deep=True)


# ---------------------------------------------------------------------------
# deployment construction + pre-flight


def _fleet(small, *, metrics=None, adapters=None, n=2,
           probe_on_rebuild=True):
    model, params = small
    return ReplicaFleet(
        model, params,
        EngineConfig(max_slots=2, max_len=32,
                     scheduler=SchedulerConfig(max_queue=32)),
        fleet=FleetConfig(n_replicas=n,
                          probe_on_rebuild=probe_on_rebuild),
        metrics=metrics, adapters=adapters)


class TestDeployPreflight:
    def test_exactly_one_target_required(self):
        with pytest.raises(ValueError, match="exactly one"):
            Deployment()
        with pytest.raises(ValueError, match="exactly one"):
            Deployment("/tmp/ckpt", adapter=("a", {}))

    def test_byte_corrupt_checkpoint_rejected_before_any_drain(
            self, small, tmp_path):
        _, params = small
        directory = _save_step(tmp_path / "ckpt", params)
        corrupt_shard(directory, 1, kind="bitflip")
        mem = InMemorySink()
        registry = MetricsRegistry([mem])
        fleet = _fleet(small, metrics=registry)
        try:
            with pytest.raises(CheckpointCorruptionError):
                fleet.deploy(directory, step=1)
            # terminal REJECTED deployment; fleet topology untouched
            assert fleet.deployment is not None
            assert fleet.deployment.state == DEPLOY_REJECTED
            assert fleet.deployment.done
            assert fleet.topology_busy is None
            counters = fleet.metrics.counters()
            assert counters["deploys_rejected"] == 1
            assert counters["deploys_started"] == 0
            assert counters["replica_drains"] == 0
            events = [r for r in mem.records if r.get("kind") == "event"]
            assert any(e.get("event") == "deploy_rejected"
                       for e in events)
            rows = [r for r in mem.records if r.get("kind") == "deploy"]
            assert [r["action"] for r in rows] == ["rejected"]
            # a rejected deployment does not block the next attempt
            good = _save_step(tmp_path / "good", params)
            dep = fleet.deploy(good, step=1)
            assert not dep.done
        finally:
            fleet.close()

    def test_empty_directory_rejected(self, small, tmp_path):
        directory = str(tmp_path / "empty")
        os.makedirs(directory)
        fleet = _fleet(small)
        try:
            with pytest.raises(CheckpointCorruptionError,
                               match="no committed step"):
                fleet.deploy(directory)
            assert fleet.deployment.state == DEPLOY_REJECTED
            assert fleet.metrics.counters()["deploys_rejected"] == 1
        finally:
            fleet.close()

    def test_one_deployment_at_a_time(self, small, tmp_path):
        _, params = small
        directory = _save_step(tmp_path / "ckpt", params)
        fleet = _fleet(small)
        try:
            fleet.deploy(directory, step=1)
            with pytest.raises(RuntimeError, match="already"):
                fleet.deploy(directory, step=1)
        finally:
            fleet.close()

    def test_adapter_deploy_needs_a_store(self, small):
        fleet = _fleet(small)
        try:
            with pytest.raises(ValueError, match="AdapterStore"):
                fleet.deploy(adapter=("t", {}))
        finally:
            fleet.close()

    def test_canary_config_validation(self):
        with pytest.raises(ValueError, match="window_s"):
            CanaryConfig(window_s=0.0)
        with pytest.raises(ValueError, match="max_window_s"):
            CanaryConfig(window_s=1.0, max_window_s=0.5)
        with pytest.raises(ValueError, match="max_error_rate"):
            CanaryConfig(max_error_rate=1.5)


# ---------------------------------------------------------------------------
# end-to-end rollouts (compile-heavy: slow lane; the committed
# canary_rollback scenario gates the poisoned path under --check)


def _feed(fleet, dep, submitted, *, max_inflight=3, tokens=3,
          adapter_id=None, cap=60000):
    """Tick the deployment to a terminal state, feeding live traffic
    while the rollout can still use it. Returns submitted client ids."""
    rng = np.random.RandomState(1234)
    ticks = 0
    while not dep.done:
        fleet.tick()
        ticks += 1
        assert ticks < cap, f"deployment stuck in state {dep.state}"
        if (dep.state in _FEEDING
                and fleet.inflight_count < max_inflight):
            rid = fleet.submit(Request(
                prompt=rng.randint(1, 64, size=4).tolist(),
                max_new_tokens=tokens,
                sampling=SamplingParams(adapter_id=adapter_id)))
            submitted.append(rid)
    _drain(fleet)
    return submitted


def _conservation_stream(registry, mem):
    return mem.records + [{"kind": "counters", "wall": time.time(),
                           "values": dict(registry.counters())}]


@pytest.mark.slow
class TestDeployEndToEnd:
    CANARY = CanaryConfig(window_s=0.05, min_requests=2, max_window_s=15.0)

    def test_happy_deploy_promotes_every_replica(self, small, tmp_path):
        model, params = small
        directory = _save_step(tmp_path / "ckpt", params)
        mem = InMemorySink()
        registry = MetricsRegistry([mem])
        fleet = _fleet(small, metrics=registry)
        try:
            ids = []
            for _ in range(4):
                ids.append(fleet.submit(Request(
                    prompt=[1, 2, 3, 4], max_new_tokens=2)))
            dep = fleet.deploy(directory, step=1, canary=self.CANARY)
            _feed(fleet, dep, ids)
            assert dep.state == DEPLOY_COMPLETE
            # both replicas canaried and promoted, in rollout order
            assert dep.promoted == [0, 1]
            assert [s["pass"] for s in dep.scores] == [True, True]
            counters = fleet.metrics.counters()
            assert counters["deploys_started"] == 1
            assert counters["deploys_completed"] == 1
            assert counters["canary_promotions"] == 2
            assert counters["deploys_rolled_back"] == 0
            # exactly-once terminal accounting for every client request
            assert set(ids) <= set(fleet.completed)
            records = [r for r in mem.records
                       if r.get("kind") == "request"
                       and r["request_id"] in set(ids)]
            assert len(records) == len(ids)
            assert check_span_conservation(
                _conservation_stream(registry, mem)) == []
            # the new weights ARE the old weights: greedy stays exact
            pid = fleet.submit(Request(prompt=[5, 6, 7, 8],
                                       max_new_tokens=4))
            _drain(fleet)
            want = generate(model, params,
                            jnp.asarray([[5, 6, 7, 8]], jnp.int32),
                            4, max_len=32)
            assert fleet.completed[pid].tokens == \
                np.asarray(want[0, 4:]).tolist()
        finally:
            fleet.close()

    def test_poisoned_deploy_rolls_back_with_exact_accounting(
            self, small, tmp_path):
        model, params = small
        directory = _save_step(tmp_path / "ckpt", params)
        corrupt_checkpoint_weights(directory, 1)
        mem = InMemorySink()
        registry = MetricsRegistry([mem])
        fleet = _fleet(small, metrics=registry)
        try:
            ids = []
            for _ in range(4):
                ids.append(fleet.submit(Request(
                    prompt=[1, 2, 3, 4], max_new_tokens=3)))
            # fsck passes (checksums re-computed over poisoned bytes):
            # the deploy STARTS — live canary traffic is the detector
            dep = fleet.deploy(directory, step=1, canary=self.CANARY)
            assert dep.state == DEPLOY_ROLLING
            _feed(fleet, dep, ids)
            assert dep.state == DEPLOY_ROLLED_BACK
            assert dep.rollback_reason == "error_rate"
            assert dep.promoted == []
            assert dep.scores and dep.scores[-1]["pass"] is False
            assert dep.scores[-1]["errors"] > 0
            counters = fleet.metrics.counters()
            assert counters["deploys_started"] == 1
            assert counters["deploys_rolled_back"] == 1
            assert counters["deploys_completed"] == 0
            assert counters["canary_promotions"] == 0
            # every client submission exactly one terminal record —
            # nothing dropped or duplicated across canary + rollback
            idset = set(ids)
            assert idset <= set(fleet.completed)
            records = [r for r in mem.records
                       if r.get("kind") == "request"
                       and r["request_id"] in idset]
            assert len(records) == len(ids)
            assert check_span_conservation(
                _conservation_stream(registry, mem)) == []
            # migrated-off-canary requests keep their ORIGINAL trace_id:
            # every span of a client request carries the trace_id its
            # terminal record carries
            span_tids = {}
            for s in mem.records:
                if s.get("kind") == "span" and s.get("request_id") in idset:
                    span_tids.setdefault(s["request_id"],
                                         set()).add(s["trace_id"])
            for r in records:
                assert span_tids[r["request_id"]] == {r["trace_id"]}
            # the incumbent weights serve the post-rollback fleet
            # greedy-token-exact — the poison left no residue
            pid = fleet.submit(Request(prompt=[5, 6, 7, 8],
                                       max_new_tokens=4))
            _drain(fleet)
            want = generate(model, params,
                            jnp.asarray([[5, 6, 7, 8]], jnp.int32),
                            4, max_len=32)
            assert fleet.completed[pid].tokens == \
                np.asarray(want[0, 4:]).tolist()
        finally:
            fleet.close()

    def test_adapter_canary_promote_then_poisoned_rollback(self, small):
        from apex_tpu.lora import AdapterStore, random_adapter

        model, params = small
        registry = MetricsRegistry()
        store = AdapterStore(model.config, 4, max_adapters=4)
        fleet = _fleet(small, metrics=registry, adapters=store)
        try:
            good = random_adapter(model.config, 4, jax.random.PRNGKey(3))
            dep = fleet.deploy(adapter=("tenant-x", good),
                               canary=self.CANARY)
            assert "tenant-x" in store    # hot-loaded for the canary
            _feed(fleet, dep, [], tokens=2, adapter_id="tenant-x")
            assert dep.state == DEPLOY_COMPLETE
            assert "tenant-x" in store    # promoted: stays loaded
            # poisoned adapter: NaN factors error every decode
            bad = jax.tree_util.tree_map(
                lambda a: a * float("nan"),
                random_adapter(model.config, 4, jax.random.PRNGKey(4)))
            dep2 = fleet.deploy(adapter=("tenant-bad", bad),
                                canary=self.CANARY)
            _feed(fleet, dep2, [], tokens=2, adapter_id="tenant-bad")
            assert dep2.state == DEPLOY_ROLLED_BACK
            assert dep2.rollback_reason == "error_rate"
            assert "tenant-bad" not in store  # rolled back: unloaded
            assert "tenant-x" in store        # incumbent tenant intact
            counters = fleet.metrics.counters()
            assert counters["deploys_started"] == 2
            assert counters["deploys_completed"] == 1
            assert counters["canary_promotions"] == 1
            assert counters["deploys_rolled_back"] == 1
        finally:
            fleet.close()
