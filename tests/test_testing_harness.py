"""Tests for the transformer testing toolkit, memory arenas, and launcher
helper (reference: ``apex/transformer/testing/*``,
``tensor_parallel/memory.py``, ``apex/parallel/multiproc.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel.memory import (
    MemoryBuffer,
    RingMemBuffer,
    allocate_mem_buff,
)
from apex_tpu.transformer.testing import (
    DistributedTestBase,
    IdentityLayer,
    initialize_distributed,
    parse_args,
    set_random_seed,
)
from apex_tpu.transformer.testing import global_vars


class TestArguments:
    def test_defaults_and_derived(self):
        args = parse_args(args=[])
        assert args.ffn_hidden_size == 4 * args.hidden_size
        assert args.data_parallel_size == args.world_size
        assert args.global_batch_size == (args.micro_batch_size
                                          * args.data_parallel_size)

    def test_parallel_divisibility_enforced(self):
        with pytest.raises(ValueError):
            parse_args(args=["--tensor-model-parallel-size", "3",
                             "--world-size", "8"])

    def test_fp16_bf16_exclusive(self):
        with pytest.raises(ValueError):
            parse_args(args=["--fp16", "--bf16"])

    def test_defaults_override(self):
        args = parse_args(args=[], defaults={"hidden-size": 64})
        # explicit CLI value survives, unset one takes the default
        assert args.hidden_size == 128  # argparse default wins (set)
        args2 = parse_args(args=[], defaults={"save": "/tmp/x"})
        assert args2.save == "/tmp/x"

    def test_config_from_args(self):
        from apex_tpu.transformer.testing.arguments import (
            core_transformer_config_from_args,
        )

        args = parse_args(args=["--num-layers", "3", "--bf16"])
        cfg = core_transformer_config_from_args(args)
        assert cfg.num_layers == 3
        assert cfg.compute_dtype == jnp.bfloat16


class TestGlobalVars:
    def test_singleton_lifecycle(self):
        global_vars.destroy_global_vars()
        with pytest.raises(RuntimeError):
            global_vars.get_args()
        args = global_vars.set_global_variables(parse_args(args=[]))
        assert global_vars.get_args() is args
        with pytest.raises(RuntimeError):
            global_vars.set_global_variables(args)
        global_vars.destroy_global_vars()


class TestCommons:
    def test_identity_layer_grad(self):
        layer = IdentityLayer((4, 4), scale=0.5)
        params = layer.init()
        g = jax.grad(lambda p: jnp.sum(layer.apply(p) ** 2))(params)
        np.testing.assert_allclose(np.asarray(g["weight"]),
                                   2 * np.asarray(params["weight"]),
                                   rtol=1e-6)

    def test_set_random_seed(self):
        k1 = set_random_seed(7)
        k2 = set_random_seed(7)
        np.testing.assert_array_equal(np.asarray(k1), np.asarray(k2))

    def test_initialize_distributed(self):
        mesh = initialize_distributed(tensor_model_parallel_size=2)
        assert parallel_state.get_tensor_model_parallel_world_size() == 2
        parallel_state.destroy_model_parallel()


class TestDistributedTestBase:
    def test_mesh_lifecycle(self):
        class _T(DistributedTestBase):
            def runTest(self):
                pass

        t = _T()
        t.setUp()
        assert t.world_size == len(jax.devices())
        mesh = t.initialize_model_parallel(tensor_model_parallel_size=2)
        assert parallel_state.model_parallel_is_initialized()
        t.tearDown()
        assert not parallel_state.model_parallel_is_initialized()

    def test_world_size_cap(self):
        class _T(DistributedTestBase):
            MAX_WORLD_SIZE = 2

            def runTest(self):
                pass

        assert _T().world_size == 2


class TestMemoryBuffer:
    def test_get_and_reset(self):
        buf = MemoryBuffer("test", 64, jnp.float32)
        a = buf.get((4, 4))
        b = buf.get((8,))
        assert a.shape == (4, 4) and b.shape == (8,)
        assert buf.numel_in_use() == 24
        buf.reset()
        assert not buf.is_in_use()

    def test_overflow_raises(self):
        buf = MemoryBuffer("small", 8, jnp.float32)
        buf.get((8,))
        with pytest.raises(MemoryError):
            buf.get((1,))

    def test_dtype_mismatch_raises(self):
        buf = allocate_mem_buff("t", 8, jnp.bfloat16)
        with pytest.raises(ValueError):
            buf.get((2,), jnp.float32)

    def test_ring_rotates_and_resets(self):
        ring = RingMemBuffer("ring", 2, 16, jnp.float32)
        b0 = ring.get_next_buffer()
        b0.get((16,))
        b1 = ring.get_next_buffer()
        assert b1 is not b0
        b0_again = ring.get_next_buffer()
        assert b0_again is b0
        assert not b0_again.is_in_use()   # reset on reacquisition


class TestMultiproc:
    def test_init_distributed_single_process(self):
        from apex_tpu.parallel.multiproc import init_distributed

        # single-process: jax.distributed init either succeeds trivially or
        # is already initialized; either way process_count is 1 here
        try:
            n = init_distributed()
        except Exception:
            pytest.skip("jax.distributed unavailable in this environment")
        assert n == 1


class TestModelParallelGradScaler:
    """transformer.amp.GradScaler: one rank's overflow must skip everywhere
    (reference apex/transformer/amp/grad_scaler.py:21-125)."""

    def test_overflow_on_one_tp_rank_seen_by_all(self):
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        from apex_tpu.transformer.amp import GradScaler

        mesh = initialize_distributed(tensor_model_parallel_size=8)
        scaler = GradScaler("dynamic")
        state = scaler.init()

        # grads sharded over tensor ranks; rank 3's shard holds an inf
        g = np.ones((8, 4), np.float32)
        g[3, 1] = np.inf

        def per_rank(g_local, state):
            scaled = jax.tree.map(lambda x: x * state.loss_scale, g_local)
            _, found_inf = scaler.unscale(scaled, state)
            return found_inf.reshape(1)

        found = shard_map(per_rank, mesh=mesh,
                          in_specs=(P("tensor"), P()),
                          out_specs=P("tensor"))(g, state)
        # every rank agrees: all True
        assert np.asarray(found).all()
        parallel_state.destroy_model_parallel()

    def test_no_overflow_plain(self):
        from apex_tpu.transformer.amp import GradScaler

        parallel_state.destroy_model_parallel()
        scaler = GradScaler("dynamic")
        state = scaler.init()
        grads = {"w": jnp.ones((3,)) * state.loss_scale}
        un, found = scaler.unscale(grads, state)
        assert not bool(found)
        np.testing.assert_allclose(np.asarray(un["w"]), np.ones(3), rtol=1e-6)


class TestProfiling:
    def test_nvtx_range_and_annotate(self):
        from apex_tpu.utils import annotate_fn, nvtx_range

        with nvtx_range("block"):
            y = jnp.sum(jnp.ones(4))
        assert float(y) == 4.0

        @annotate_fn("scoped")
        def f(x):
            return x * 2

        np.testing.assert_allclose(np.asarray(f(jnp.ones(2))), 2 * np.ones(2))

    def test_named_scope_in_jit(self):
        from apex_tpu.utils import nvtx_range

        @jax.jit
        def f(x):
            with nvtx_range("inner"):
                return x + 1

        assert float(f(jnp.zeros(()))) == 1.0

    def test_device_memory_stats_shape(self):
        from apex_tpu.utils import device_memory_stats

        stats = device_memory_stats()
        assert isinstance(stats, dict)

    def test_trace_writes_profile(self, tmp_path):
        from apex_tpu.utils import trace

        with trace(str(tmp_path)):
            jax.block_until_ready(jnp.dot(jnp.ones((8, 8)), jnp.ones((8, 8))))
        import os
        found = any("trace" in f or f.endswith(".pb") or "plugins" in r
                    for r, _, fs in os.walk(tmp_path) for f in fs + [r])
        assert found


class TestExtendedArgSurface:
    """Round-2 arg-surface growth: every model knob added to the framework
    (GQA, rope, rmsnorm, swiglu, sliding window, MoE, CP method, fp8,
    optimizer selection) parses and reaches TransformerConfig."""

    def test_modern_llm_config(self):
        from apex_tpu.transformer.testing.arguments import (
            core_transformer_config_from_args,
        )

        args = parse_args(args=[
            "--num-layers", "4", "--hidden-size", "256",
            "--num-attention-heads", "8", "--num-query-groups", "2",
            "--position-embedding-type", "rope", "--rotary-percent", "0.5",
            "--normalization", "rmsnorm", "--swiglu",
            "--sliding-window", "64", "--bf16"])
        cfg = core_transformer_config_from_args(args)
        assert cfg.num_query_groups == 2
        assert cfg.position_embedding_type == "rope"
        assert cfg.rotary_percent == 0.5
        assert cfg.normalization == "rmsnorm"
        assert cfg.activation == "swiglu"
        assert cfg.sliding_window == 64

    def test_moe_and_cp_args(self):
        from apex_tpu.transformer.testing.arguments import (
            core_transformer_config_from_args,
        )

        args = parse_args(args=[
            "--num-experts", "4", "--moe-router-topk", "2",
            "--moe-expert-axis", "data", "--world-size", "4",
            "--context-parallel-size", "1"])
        cfg = core_transformer_config_from_args(args)
        assert cfg.num_moe_experts == 4
        assert cfg.moe_top_k == 2
        # cp size 1 -> no CP method regardless of flag default
        assert cfg.context_parallel_method is None

    def test_cp_method_defaults_to_ring(self):
        args = parse_args(args=["--context-parallel-size", "2",
                                "--world-size", "2"])
        assert args.context_parallel_method == "ring"

    def test_gqa_divisibility_enforced(self):
        import pytest

        with pytest.raises(ValueError, match="num_query_groups"):
            parse_args(args=["--num-attention-heads", "8",
                             "--num-query-groups", "3"])

    def test_optimizer_and_fp8_groups(self):
        args = parse_args(args=["--optimizer", "lamb", "--fp8",
                                "--fp8-amax-history-len", "8",
                                "--use-distributed-optimizer"])
        assert args.optimizer == "lamb"
        assert args.fp8 and args.fp8_amax_history_len == 8
        assert args.use_distributed_optimizer

    def test_global_vars_build_microbatch_calculator(self):
        from apex_tpu.transformer.testing import global_vars
        from apex_tpu.transformer.pipeline_parallel import utils as pp_utils

        global_vars.destroy_global_vars()
        global_vars.set_global_variables(parse_args(args=[
            "--micro-batch-size", "2", "--global-batch-size", "8",
            "--world-size", "1"]))
        assert global_vars.get_num_microbatches() == 4
        assert global_vars.get_current_global_batch_size() == 8
        assert global_vars.get_timers() is not None
        assert global_vars.get_adlr_autoresume() is None
        assert global_vars.get_tensorboard_writer() is None
        global_vars.destroy_global_vars()
