"""Tests for the transformer testing toolkit, memory arenas, and launcher
helper (reference: ``apex/transformer/testing/*``,
``tensor_parallel/memory.py``, ``apex/parallel/multiproc.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel.memory import (
    MemoryBuffer,
    RingMemBuffer,
    allocate_mem_buff,
)
from apex_tpu.transformer.testing import (
    DistributedTestBase,
    IdentityLayer,
    initialize_distributed,
    parse_args,
    set_random_seed,
)
from apex_tpu.transformer.testing import global_vars


class TestArguments:
    def test_defaults_and_derived(self):
        args = parse_args(args=[])
        assert args.ffn_hidden_size == 4 * args.hidden_size
        assert args.data_parallel_size == args.world_size
        assert args.global_batch_size == (args.micro_batch_size
                                          * args.data_parallel_size)

    def test_parallel_divisibility_enforced(self):
        with pytest.raises(ValueError):
            parse_args(args=["--tensor-model-parallel-size", "3",
                             "--world-size", "8"])

    def test_fp16_bf16_exclusive(self):
        with pytest.raises(ValueError):
            parse_args(args=["--fp16", "--bf16"])

    def test_defaults_override(self):
        args = parse_args(args=[], defaults={"hidden-size": 64})
        # explicit CLI value survives, unset one takes the default
        assert args.hidden_size == 128  # argparse default wins (set)
        args2 = parse_args(args=[], defaults={"save": "/tmp/x"})
        assert args2.save == "/tmp/x"

    def test_config_from_args(self):
        from apex_tpu.transformer.testing.arguments import (
            core_transformer_config_from_args,
        )

        args = parse_args(args=["--num-layers", "3", "--bf16"])
        cfg = core_transformer_config_from_args(args)
        assert cfg.num_layers == 3
        assert cfg.compute_dtype == jnp.bfloat16


class TestGlobalVars:
    def test_singleton_lifecycle(self):
        global_vars.destroy_global_vars()
        with pytest.raises(RuntimeError):
            global_vars.get_args()
        args = global_vars.set_global_variables(parse_args(args=[]))
        assert global_vars.get_args() is args
        with pytest.raises(RuntimeError):
            global_vars.set_global_variables(args)
        global_vars.destroy_global_vars()


class TestCommons:
    def test_identity_layer_grad(self):
        layer = IdentityLayer((4, 4), scale=0.5)
        params = layer.init()
        g = jax.grad(lambda p: jnp.sum(layer.apply(p) ** 2))(params)
        np.testing.assert_allclose(np.asarray(g["weight"]),
                                   2 * np.asarray(params["weight"]),
                                   rtol=1e-6)

    def test_set_random_seed(self):
        k1 = set_random_seed(7)
        k2 = set_random_seed(7)
        np.testing.assert_array_equal(np.asarray(k1), np.asarray(k2))

    def test_initialize_distributed(self):
        mesh = initialize_distributed(tensor_model_parallel_size=2)
        assert parallel_state.get_tensor_model_parallel_world_size() == 2
        parallel_state.destroy_model_parallel()


class TestDistributedTestBase:
    def test_mesh_lifecycle(self):
        class _T(DistributedTestBase):
            def runTest(self):
                pass

        t = _T()
        t.setUp()
        assert t.world_size == len(jax.devices())
        mesh = t.initialize_model_parallel(tensor_model_parallel_size=2)
        assert parallel_state.model_parallel_is_initialized()
        t.tearDown()
        assert not parallel_state.model_parallel_is_initialized()

    def test_world_size_cap(self):
        class _T(DistributedTestBase):
            MAX_WORLD_SIZE = 2

            def runTest(self):
                pass

        assert _T().world_size == 2


class TestMemoryBuffer:
    def test_get_and_reset(self):
        buf = MemoryBuffer("test", 64, jnp.float32)
        a = buf.get((4, 4))
        b = buf.get((8,))
        assert a.shape == (4, 4) and b.shape == (8,)
        assert buf.numel_in_use() == 24
        buf.reset()
        assert not buf.is_in_use()

    def test_overflow_raises(self):
        buf = MemoryBuffer("small", 8, jnp.float32)
        buf.get((8,))
        with pytest.raises(MemoryError):
            buf.get((1,))

    def test_dtype_mismatch_raises(self):
        buf = allocate_mem_buff("t", 8, jnp.bfloat16)
        with pytest.raises(ValueError):
            buf.get((2,), jnp.float32)

    def test_ring_rotates_and_resets(self):
        ring = RingMemBuffer("ring", 2, 16, jnp.float32)
        b0 = ring.get_next_buffer()
        b0.get((16,))
        b1 = ring.get_next_buffer()
        assert b1 is not b0
        b0_again = ring.get_next_buffer()
        assert b0_again is b0
        assert not b0_again.is_in_use()   # reset on reacquisition


class TestMultiproc:
    def test_init_distributed_single_process(self):
        from apex_tpu.parallel.multiproc import init_distributed

        # single-process: jax.distributed init either succeeds trivially or
        # is already initialized; either way process_count is 1 here
        try:
            n = init_distributed()
        except Exception:
            pytest.skip("jax.distributed unavailable in this environment")
        assert n == 1


class TestModelParallelGradScaler:
    """transformer.amp.GradScaler: one rank's overflow must skip everywhere
    (reference apex/transformer/amp/grad_scaler.py:21-125)."""

    def test_overflow_on_one_tp_rank_seen_by_all(self):
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        from apex_tpu.transformer.amp import GradScaler

        mesh = initialize_distributed(tensor_model_parallel_size=8)
        scaler = GradScaler("dynamic")
        state = scaler.init()

        # grads sharded over tensor ranks; rank 3's shard holds an inf
        g = np.ones((8, 4), np.float32)
        g[3, 1] = np.inf

        def per_rank(g_local, state):
            scaled = jax.tree.map(lambda x: x * state.loss_scale, g_local)
            _, found_inf = scaler.unscale(scaled, state)
            return found_inf.reshape(1)

        found = shard_map(per_rank, mesh=mesh,
                          in_specs=(P("tensor"), P()),
                          out_specs=P("tensor"))(g, state)
        # every rank agrees: all True
        assert np.asarray(found).all()
        parallel_state.destroy_model_parallel()

    def test_no_overflow_plain(self):
        from apex_tpu.transformer.amp import GradScaler

        parallel_state.destroy_model_parallel()
        scaler = GradScaler("dynamic")
        state = scaler.init()
        grads = {"w": jnp.ones((3,)) * state.loss_scale}
        un, found = scaler.unscale(grads, state)
        assert not bool(found)
        np.testing.assert_allclose(np.asarray(un["w"]), np.ones(3), rtol=1e-6)


class TestProfiling:
    def test_nvtx_range_and_annotate(self):
        from apex_tpu.utils import annotate_fn, nvtx_range

        with nvtx_range("block"):
            y = jnp.sum(jnp.ones(4))
        assert float(y) == 4.0

        @annotate_fn("scoped")
        def f(x):
            return x * 2

        np.testing.assert_allclose(np.asarray(f(jnp.ones(2))), 2 * np.ones(2))

    def test_named_scope_in_jit(self):
        from apex_tpu.utils import nvtx_range

        @jax.jit
        def f(x):
            with nvtx_range("inner"):
                return x + 1

        assert float(f(jnp.zeros(()))) == 1.0

    def test_device_memory_stats_shape(self):
        from apex_tpu.utils import device_memory_stats

        stats = device_memory_stats()
        assert isinstance(stats, dict)

    @pytest.mark.slow
    def test_trace_writes_profile(self, tmp_path):
        from apex_tpu.utils import trace

        with trace(str(tmp_path)):
            jax.block_until_ready(jnp.dot(jnp.ones((8, 8)), jnp.ones((8, 8))))
        import os
        found = any("trace" in f or f.endswith(".pb") or "plugins" in r
                    for r, _, fs in os.walk(tmp_path) for f in fs + [r])
        assert found


class TestExtendedArgSurface:
    """Round-2 arg-surface growth: every model knob added to the framework
    (GQA, rope, rmsnorm, swiglu, sliding window, MoE, CP method, fp8,
    optimizer selection) parses and reaches TransformerConfig."""

    def test_modern_llm_config(self):
        from apex_tpu.transformer.testing.arguments import (
            core_transformer_config_from_args,
        )

        args = parse_args(args=[
            "--num-layers", "4", "--hidden-size", "256",
            "--num-attention-heads", "8", "--num-query-groups", "2",
            "--position-embedding-type", "rope", "--rotary-percent", "0.5",
            "--normalization", "rmsnorm", "--swiglu",
            "--sliding-window", "64", "--bf16"])
        cfg = core_transformer_config_from_args(args)
        assert cfg.num_query_groups == 2
        assert cfg.position_embedding_type == "rope"
        assert cfg.rotary_percent == 0.5
        assert cfg.normalization == "rmsnorm"
        assert cfg.activation == "swiglu"
        assert cfg.sliding_window == 64

    def test_moe_and_cp_args(self):
        from apex_tpu.transformer.testing.arguments import (
            core_transformer_config_from_args,
        )

        args = parse_args(args=[
            "--num-experts", "4", "--moe-router-topk", "2",
            "--moe-expert-axis", "data", "--world-size", "4",
            "--context-parallel-size", "1"])
        cfg = core_transformer_config_from_args(args)
        assert cfg.num_moe_experts == 4
        assert cfg.moe_top_k == 2
        # cp size 1 -> no CP method regardless of flag default
        assert cfg.context_parallel_method is None

    def test_cp_method_defaults_to_ring(self):
        args = parse_args(args=["--context-parallel-size", "2",
                                "--world-size", "2"])
        assert args.context_parallel_method == "ring"

    def test_gqa_divisibility_enforced(self):
        import pytest

        with pytest.raises(ValueError, match="num_query_groups"):
            parse_args(args=["--num-attention-heads", "8",
                             "--num-query-groups", "3"])

    def test_optimizer_and_fp8_groups(self):
        args = parse_args(args=["--optimizer", "lamb", "--fp8",
                                "--fp8-amax-history-len", "8",
                                "--use-distributed-optimizer"])
        assert args.optimizer == "lamb"
        assert args.fp8 and args.fp8_amax_history_len == 8
        assert args.use_distributed_optimizer

    def test_global_vars_build_microbatch_calculator(self):
        from apex_tpu.transformer.testing import global_vars
        from apex_tpu.transformer.pipeline_parallel import utils as pp_utils

        global_vars.destroy_global_vars()
        global_vars.set_global_variables(parse_args(args=[
            "--micro-batch-size", "2", "--global-batch-size", "8",
            "--world-size", "1"]))
        assert global_vars.get_num_microbatches() == 4
        assert global_vars.get_current_global_batch_size() == 8
        assert global_vars.get_timers() is not None
        assert global_vars.get_adlr_autoresume() is None
        assert global_vars.get_tensorboard_writer() is None
        global_vars.destroy_global_vars()


# The reference's complete flag surface (``apex/transformer/testing/
# arguments.py``), frozen here as the parity checklist: every flag must be
# accepted by parse_args and carry an explicit disposition.
REFERENCE_FLAGS = [
    "--accumulate-allreduce-grads-in-fp32", "--adam-beta1", "--adam-beta2",
    "--adam-eps", "--adlr-autoresume", "--adlr-autoresume-interval",
    "--apply-residual-connection-post-layernorm", "--attention-dropout",
    "--attention-softmax-in-fp32", "--batch-size", "--bert-load",
    "--bert-no-binary-head", "--bf16", "--biencoder-projection-dim",
    "--biencoder-shared-query-context-model", "--block-data-path",
    "--checkpoint-activations", "--classes-fraction", "--clip-grad",
    "--cpu-offload", "--data-impl", "--data-path",
    "--data-per-class-fraction", "--dataloader-type",
    "--decoder-seq-length", "--dino-bottleneck-size",
    "--dino-freeze-last-layer", "--dino-head-hidden-size",
    "--dino-local-crops-number", "--dino-local-img-size",
    "--dino-norm-last-layer", "--dino-teacher-temp",
    "--dino-warmup-teacher-temp", "--dino-warmup-teacher-temp-epochs",
    "--distribute-saved-activations", "--distributed-backend",
    "--embedding-path", "--empty-unused-memory-level",
    "--encoder-seq-length", "--end-weight-decay", "--eod-mask-loss",
    "--eval-interval", "--eval-iters", "--evidence-data-path",
    "--exit-duration-in-mins", "--exit-interval", "--ffn-hidden-size",
    "--finetune", "--fp16", "--fp16-lm-cross-entropy",
    "--fp32-residual-connection", "--global-batch-size", "--head-lr-mult",
    "--hidden-dropout", "--hidden-size", "--hysteresis", "--ict-head-size",
    "--ict-load", "--img-h", "--img-w", "--indexer-batch-size",
    "--indexer-log-interval", "--inference-batch-times-seqlen-threshold",
    "--init-method-std", "--init-method-xavier-uniform",
    "--initial-loss-scale", "--iter-per-epoch", "--kv-channels",
    "--layernorm-epsilon", "--lazy-mpu-init", "--load",
    "--log-batch-size-to-tensorboard", "--log-interval",
    "--log-memory-to-tensorboard", "--log-num-zeros-in-grad",
    "--log-params-norm", "--log-timers-to-tensorboard",
    "--log-validation-ppl-to-tensorboard",
    "--log-world-size-to-tensorboard", "--loss-scale",
    "--loss-scale-window", "--lr", "--lr-decay-iters", "--lr-decay-samples",
    "--lr-decay-style", "--lr-warmup-fraction", "--lr-warmup-iters",
    "--lr-warmup-samples", "--make-vocab-size-divisible-by",
    "--mask-factor", "--mask-prob", "--mask-type",
    "--max-position-embeddings", "--merge-file", "--micro-batch-size",
    "--min-loss-scale", "--min-lr", "--mmap-warmup",
    "--model-parallel-size", "--no-async-tensor-model-parallel-allreduce",
    "--no-bias-dropout-fusion", "--no-bias-gelu-fusion",
    "--no-contiguous-buffers-in-local-ddp", "--no-data-sharding",
    "--no-gradient-accumulation-fusion", "--no-load-optim", "--no-load-rng",
    "--no-log-learnig-rate-to-tensorboard",
    "--no-log-loss-scale-to-tensorboard", "--no-masked-softmax-fusion",
    "--no-persist-layer-norm", "--no-query-key-layer-scaling",
    "--no-save-optim", "--no-save-rng",
    "--no-scatter-gather-tensors-in-pipeline", "--num-attention-heads",
    "--num-channels", "--num-classes", "--num-experts", "--num-layers",
    "--num-layers-per-virtual-pipeline-stage", "--num-workers",
    "--onnx-safe", "--openai-gelu", "--optimizer",
    "--override-lr-scheduler", "--patch-dim",
    "--pipeline-model-parallel-size",
    "--pipeline-model-parallel-split-rank", "--query-in-block-prob",
    "--rampup-batch-size", "--recompute-activations",
    "--recompute-granularity", "--recompute-method",
    "--recompute-num-layers", "--reset-attention-mask",
    "--reset-position-ids", "--retriever-report-topk-accuracies",
    "--retriever-score-scaling", "--retriever-seq-length", "--sample-rate",
    "--save", "--save-interval", "--seed", "--seq-length",
    "--sequence-parallel", "--sgd-momentum", "--short-seq-prob", "--split",
    "--standalone-embedding-stage", "--start-weight-decay",
    "--swin-backbone-type", "--tensor-model-parallel-size",
    "--tensorboard-dir", "--tensorboard-log-interval",
    "--tensorboard-queue-size", "--titles-data-path", "--tokenizer-type",
    "--train-iters", "--train-samples", "--use-checkpoint-lr-scheduler",
    "--use-cpu-initialization", "--use-one-sent-docs",
    "--vision-backbone-type", "--vision-pretraining",
    "--vision-pretraining-type", "--vocab-extra-ids", "--vocab-file",
    "--warmup", "--weight-decay", "--weight-decay-incr-style",
]


class TestFullReferenceArgsContract:
    def test_disposition_registry_is_exhaustive_and_exact(self):
        from apex_tpu.transformer.testing.arguments import (
            REFERENCE_DISPOSITIONS,
        )

        assert set(REFERENCE_DISPOSITIONS) == set(REFERENCE_FLAGS)
        for flag, (status, note) in REFERENCE_DISPOSITIONS.items():
            assert status in ("wired", "inert"), flag
            assert note, flag

    def test_every_reference_flag_parses(self):
        import warnings as _w

        needs_value = {
            "--batch-size": "4", "--bert-load": "/tmp/x",
            "--data-path": "/tmp/d", "--lr": "1e-4",
            "--hidden-size": "64", "--num-layers": "2",
            "--num-attention-heads": "4", "--dataloader-type": "single",
            "--lr-decay-style": "cosine", "--optimizer": "sgd",
            "--recompute-granularity": "full",
            "--recompute-method": "uniform",
            "--weight-decay-incr-style": "linear",
            "--rampup-batch-size": None,     # nargs=3, handled below
        }
        # parse in one invocation per flag so store_true/value flags both
        # work; every flag must be ACCEPTED (no argparse error)
        for flag in REFERENCE_FLAGS:
            argv = [flag]
            from apex_tpu.transformer.testing.arguments import parse_args
            import argparse as _ap
            # value-taking flags need a value: introspect via a dry parse
            with _w.catch_warnings():
                _w.simplefilter("ignore")
                try:
                    parse_args(args=argv)
                    continue
                except ValueError:
                    continue    # parsed; post-validation fired = wired
                except SystemExit:
                    pass
                # needs a value (or conflicts); retry with a plausible one
                if flag == "--rampup-batch-size":
                    argv2 = [flag, "4", "4", "64",
                             "--global-batch-size", "16"]
                elif flag == "--start-weight-decay":
                    argv2 = [flag, "0.0", "--end-weight-decay", "0.1"]
                elif flag == "--end-weight-decay":
                    argv2 = [flag, "0.1", "--start-weight-decay", "0.0"]
                else:
                    argv2 = [flag, needs_value.get(flag, "1")]
                try:
                    parse_args(args=argv2)
                except ValueError:
                    pass        # parsed; post-validation fired = wired
                except SystemExit as e:      # pragma: no cover
                    raise AssertionError(
                        f"reference flag {flag} rejected") from e

    def test_inert_flags_warn_and_record(self):
        import warnings as _w

        from apex_tpu.transformer.testing.arguments import parse_args

        with _w.catch_warnings(record=True) as rec:
            _w.simplefilter("always")
            args = parse_args(args=["--tensorboard-dir", "/tmp/tb"])
        assert args.inert_flags_set == ["--tensorboard-dir"]
        assert any("--tensorboard-dir" in str(m.message) for m in rec)

    def test_deprecated_aliases(self):
        from apex_tpu.transformer.testing.arguments import parse_args

        a = parse_args(args=["--model-parallel-size", "2",
                             "--world-size", "4"])
        assert a.tensor_model_parallel_size == 2
        a = parse_args(args=["--batch-size", "8"])
        assert a.micro_batch_size == 8
        a = parse_args(args=["--warmup", "5"])
        assert a.lr_warmup_fraction == 0.05
        a = parse_args(args=["--checkpoint-activations"])
        assert a.recompute is True
        a = parse_args(args=["--recompute-activations"])
        assert a.recompute == "selective"

    def test_derivations(self):
        from apex_tpu.transformer.testing.arguments import parse_args

        a = parse_args(args=["--num-layers", "8",
                             "--pipeline-model-parallel-size", "2",
                             "--num-layers-per-virtual-pipeline-stage", "2",
                             "--world-size", "2"])
        assert a.virtual_pipeline_model_parallel_size == 2
        a = parse_args(args=["--vocab-size", "50257",
                             "--tensor-model-parallel-size", "2",
                             "--world-size", "2"])
        assert a.padded_vocab_size == 50432       # ceil to 256
        import pytest as _pt
        with _pt.raises(ValueError):
            parse_args(args=["--kv-channels", "999"])
        with _pt.raises(ValueError):
            parse_args(args=["--seq-length", "256",
                             "--max-position-embeddings", "128"])
        a = parse_args(args=["--start-weight-decay", "0.0",
                             "--end-weight-decay", "0.1"])
        assert a.start_weight_decay == 0.0 and a.end_weight_decay == 0.1
