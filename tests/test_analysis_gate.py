"""Tier-1 hazard gate: the lint engine runs over the whole configured
tree and fails on any finding that is neither ``# noqa``-suppressed nor
recorded (with a justification) in the committed baseline — so JAX
hazards are caught by the same ``pytest -m 'not slow'`` invocation that
runs everything else, with no new CI infrastructure.

Also enforces the slow-tier marker discipline that PR 1's budget
regression motivated: test modules importing the compile-heavy
interpret-mode pallas models must carry ``slow`` markers (or sit on the
reviewed cheap-usage allowlist below), so the tier-1 wall clock cannot
quietly re-absorb the multi-layer parity suites.
"""

import ast
import os
import re
import subprocess
import sys
from pathlib import Path

import pytest

from apex_tpu.analysis import (
    Baseline,
    Finding,
    analyze_paths,
    load_config,
)
from apex_tpu.analysis.engine import PLACEHOLDER_JUSTIFICATION

REPO_ROOT = Path(__file__).resolve().parents[1]
PYPROJECT = REPO_ROOT / "pyproject.toml"

#: model-importing test modules reviewed as tier-1-cheap (small configs /
#: single layers; measured ~2 min combined on CPU, inside the 870 s
#: budget). A NEW module importing apex_tpu.models with no slow markers
#: must either be added here after review or mark its heavy tests.
CHEAP_MODEL_TEST_MODULES = {
    "test_context_parallel.py",
    "test_data_pipeline.py",
    "test_gqa.py",
    "test_imports.py",
    "test_moe.py",
    "test_trace_fleet.py",
}


def _config():
    cfg = load_config(pyproject=str(PYPROJECT))
    assert cfg.baseline, "pyproject [tool.apex_tpu.analysis] lost baseline"
    return cfg


class TestHazardGate:
    # full-tree scans + a subprocess entrypoint run: the three heavy
    # gate tests are slow-tier per the ROADMAP tier policy (they still
    # gate nightly; the targeted unit tests below stay tier-1)
    @pytest.mark.slow
    def test_tree_has_no_unbaselined_findings(self):
        cfg = _config()
        findings = analyze_paths(
            [str(REPO_ROOT / p) for p in cfg.paths], cfg)
        bl = Baseline.load(str(REPO_ROOT / cfg.baseline))
        new, _, _ = bl.partition(findings)
        assert not new, (
            "new JAX-hazard findings (fix them, add `# noqa: APX###` "
            "with cause, or baseline with a justification — see "
            "docs/analysis.md):\n" + "\n".join(f.render() for f in new))

    @pytest.mark.slow
    def test_baseline_is_fresh_and_justified(self):
        cfg = _config()
        findings = analyze_paths(
            [str(REPO_ROOT / p) for p in cfg.paths], cfg)
        bl = Baseline.load(str(REPO_ROOT / cfg.baseline))
        _, _, stale = bl.partition(findings)
        assert not stale, (
            "stale baseline entries (the hazard was fixed — drop its "
            "ledger line):\n" + "\n".join(str(e) for e in stale))
        unjustified = [e for e in bl.entries
                       if not str(e.get("justification", "")).strip()
                       or "TODO" in str(e.get("justification", ""))]
        assert not unjustified, (
            "baseline entries need a real one-line justification:\n"
            + "\n".join(str(e) for e in unjustified))

    def test_placeholder_justification_does_not_suppress(self):
        """A baseline entry still carrying the ``--write-baseline``
        placeholder (or a blank justification) must NOT suppress its
        finding — the gate stays red until a human writes the reason."""
        finding = Finding(code="APX001", message="m",
                          path="pkg/mod.py", line=3, col=0,
                          snippet="jax.random.normal(key)")
        entry = {"path": "pkg/mod.py", "code": "APX001", "line": 3,
                 "snippet": "jax.random.normal(key)"}
        for bad in (PLACEHOLDER_JUSTIFICATION,
                    f"{PLACEHOLDER_JUSTIFICATION} later", "", "   ", None):
            bl = Baseline([{**entry, "justification": bad}])
            new, matched, stale = bl.partition([finding])
            assert new == [finding] and not matched and not stale, (
                f"justification {bad!r} suppressed the finding")
            assert bl.unjustified_entries() == bl.entries
        # the same entry with a real justification does suppress it
        bl = Baseline([{**entry,
                        "justification": "deliberate: test fixture"}])
        new, matched, stale = bl.partition([finding])
        assert not new and matched == [finding] and not stale
        assert bl.unjustified_entries() == []

    def test_write_baseline_output_is_rejected_until_edited(self):
        """``Baseline.from_findings`` (what ``--write-baseline`` saves)
        stamps the placeholder, so a freshly written baseline cannot
        silently green the gate."""
        finding = Finding(code="APX002", message="m", path="a.py",
                          line=1, col=0, snippet="x")
        bl = Baseline.from_findings([finding])
        assert bl.unjustified_entries() == bl.entries
        new, _, _ = bl.partition([finding])
        assert new == [finding]

    @pytest.mark.slow
    def test_module_entrypoint_runs_clean(self):
        """``python -m apex_tpu.analysis`` exits 0 on the committed tree
        (the acceptance criterion, exercised through the real CLI)."""
        proc = subprocess.run(
            [sys.executable, "-m", "apex_tpu.analysis"],
            cwd=str(REPO_ROOT), capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=300)
        assert proc.returncode == 0, (
            f"linter found new hazards:\n{proc.stdout}\n{proc.stderr}")

    def test_console_script_registered(self):
        text = PYPROJECT.read_text()
        assert "apex-tpu-analysis" in text and \
            "apex_tpu.analysis.engine:main" in text


class TestSlowTierDiscipline:
    @staticmethod
    def _imports_models(tree: ast.AST) -> bool:
        for n in ast.walk(tree):
            if isinstance(n, ast.ImportFrom) and n.module and \
                    n.module.startswith("apex_tpu.models"):
                return True
            if isinstance(n, ast.Import) and any(
                    a.name.startswith("apex_tpu.models")
                    for a in n.names):
                return True
        return False

    @staticmethod
    def _has_any_slow_marker(tree: ast.AST) -> bool:
        return any(
            isinstance(n, (ast.Attribute, ast.Name))
            and getattr(n, "attr", getattr(n, "id", "")) == "slow"
            for n in ast.walk(tree))

    def test_model_importing_modules_carry_slow_markers(self):
        violations = []
        for path in sorted((REPO_ROOT / "tests").glob("*.py")):
            tree = ast.parse(path.read_text())
            if not self._imports_models(tree):
                continue
            if path.name in CHEAP_MODEL_TEST_MODULES:
                continue
            if not self._has_any_slow_marker(tree):
                violations.append(path.name)
        assert not violations, (
            f"test modules importing apex_tpu.models (interpret-mode "
            f"pallas multi-layer fixtures) without any @pytest.mark.slow: "
            f"{violations} — mark the compile-bound tests slow, or review "
            f"and add to CHEAP_MODEL_TEST_MODULES")

    def test_parity_and_convergence_tests_are_slow(self):
        """The specific shape of the PR 1 regression: multi-layer
        model-parity / convergence sweeps in the quick tier."""
        pat = re.compile(r"parity|convergence")
        violations = []
        for path in sorted((REPO_ROOT / "tests").glob("*.py")):
            if path.name == "test_analysis_gate.py":
                continue
            tree = ast.parse(path.read_text())
            if not self._imports_models(tree):
                continue
            module_slow = any(
                isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "pytestmark"
                    for t in n.targets)
                and "slow" in ast.dump(n.value)
                for n in tree.body)

            def deco_slow(deco_list):
                return any("slow" in ast.dump(d) for d in deco_list)

            def check(body, inherited):
                for n in body:
                    if isinstance(n, ast.ClassDef):
                        check(n.body,
                              inherited or deco_slow(n.decorator_list))
                    elif isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)) and \
                            n.name.startswith("test") and \
                            pat.search(n.name):
                        if not (module_slow or inherited
                                or deco_slow(n.decorator_list)):
                            violations.append(
                                f"{path.name}::{n.name}")
            check(tree.body, False)
        assert not violations, (
            f"parity/convergence tests over apex_tpu.models outside the "
            f"slow tier: {violations}")
