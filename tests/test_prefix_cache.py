"""Fleet-wide prefix cache tests: interning, COW seam, affinity routing.

The ISSUE-11 contract: shared-prefix reuse is a *memory and compute*
optimization, never an approximation. Tier-1 pins (a) the hash chain's
page-aligned cumulative semantics, (b) PagePool intern/refcount
conservation under randomized map/intern/release/evict churn, (c)
hit-vs-cold engine TOKEN EXACTNESS — greedy and sampled, partial-page
and fully page-aligned boundaries — with zero decode retraces, (d)
quarantine of a sharing slot leaving co-tenants and the interned pages
intact, (e) LRU eviction under page pressure followed by re-intern, and
(f) the router's prefix-affinity discount being bounded (a hot replica
still sheds to cold peers). The compile-bound crosses (tp=2 sharded
prefix parity, supervisor restart over shared pages) sit in the slow
tier per the ROADMAP tier policy.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.models.generation import generate
from apex_tpu.ops import _support
from apex_tpu.serving import (
    EngineConfig,
    EngineSupervisor,
    InferenceEngine,
    PageError,
    PagePool,
    Request,
    SamplingParams,
)
from apex_tpu.serving.fleet import FleetConfig, Router
from apex_tpu.serving.fleet.router import _Replica
from apex_tpu.serving.prefix import (
    adapter_salt,
    common_chain_len,
    prefix_hash_chain,
    prefix_salt,
)
from apex_tpu.testing_faults import ServingFaultInjector


@pytest.fixture(autouse=True)
def _pallas_off(monkeypatch):
    """Pin the jnp reference dispatch (same rationale as the paged
    suite): the bitwise hit-vs-cold claims below hold for the reference
    path; the interpret-mode kernel has its own tolerance tests."""
    monkeypatch.setenv("APEX_TPU_FORCE_PALLAS", "off")
    _support.pallas_mode.cache_clear()
    yield
    _support.pallas_mode.cache_clear()


@pytest.fixture(scope="module")
def small():
    model = GPTModel(TransformerConfig(
        num_layers=2, hidden_size=32, num_attention_heads=4, vocab_size=64,
        max_position_embeddings=64, hidden_dropout=0.0,
        attention_dropout=0.0))
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _prompts(lens, seed=7):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 64, size=n).tolist() for n in lens]


def _expected_greedy(model, params, request, max_len):
    out = generate(model, params, jnp.asarray([request.prompt], jnp.int32),
                   request.max_new_tokens, max_len=max_len,
                   eos_token=request.eos_token)
    toks = np.asarray(out[0, request.prompt_len:]).tolist()
    if request.eos_token is not None and request.eos_token in toks:
        toks = toks[:toks.index(request.eos_token) + 1]
    return toks


# ---------------------------------------------------------------------------
# hash chain semantics (pure host-side)


class TestPrefixHash:
    def test_full_pages_only(self):
        toks = list(range(11))
        assert len(prefix_hash_chain(toks, 4)) == 2       # 11 // 4
        assert prefix_hash_chain(toks[:3], 4) == ()       # no full page
        # the trailing partial page never contributes: 8..10 ignored
        assert prefix_hash_chain(toks, 4) == prefix_hash_chain(toks[:8], 4)

    def test_cumulative_divergence(self):
        a = list(range(16))
        b = list(a)
        b[5] = 63                                         # inside page 1
        ca, cb = prefix_hash_chain(a, 4), prefix_hash_chain(b, 4)
        assert ca[0] == cb[0]
        assert ca[1] != cb[1] and ca[2] != cb[2] and ca[3] != cb[3]
        assert common_chain_len(ca, cb) == 1

    def test_salt_separates_models(self):
        toks = list(range(8))
        assert prefix_hash_chain(toks, 4, "a") != \
            prefix_hash_chain(toks, 4, "b")

    def test_salt_is_sampling_invariant(self):
        """The salt fingerprints architecture dims only — greedy and
        sampled requests over one model MUST share pages."""
        cfg = TransformerConfig(
            num_layers=2, hidden_size=32, num_attention_heads=4,
            vocab_size=64, max_position_embeddings=64)
        s = prefix_salt(cfg)
        assert str(cfg.num_layers) in s.split(":")[0]
        assert prefix_salt(cfg) == s                      # deterministic

    def test_adapter_salt_regression_naive_salt_aliases_tenants(self):
        """REGRESSION (multi-LoRA): adapter deltas make K/V
        adapter-specific, so the model-only salt is NOT enough — two
        tenants with identical prompts would alias each other's interned
        pages and silently read another adapter's K/V. First demonstrate
        the trap (naive chains collide), then that ``adapter_salt``
        separates tenants while base traffic (``adapter_id=None``) keeps
        the plain salt and still shares."""
        toks = list(range(12))
        base = "model-fingerprint"
        # the bug the fold exists to prevent: same prompt, same naive
        # salt, different adapters -> IDENTICAL chains (full aliasing)
        naive_a = prefix_hash_chain(toks, 4, base)
        naive_b = prefix_hash_chain(toks, 4, base)
        assert naive_a == naive_b
        chain_a = prefix_hash_chain(toks, 4, adapter_salt(base, "tenant-a"))
        chain_b = prefix_hash_chain(toks, 4, adapter_salt(base, "tenant-b"))
        assert chain_a != chain_b                 # tenants never share
        assert chain_a != naive_a                 # nor with base traffic
        assert common_chain_len(chain_a, chain_b) == 0
        # None is base traffic: plain salt unchanged, base still shares
        assert adapter_salt(base, None) == base
        assert prefix_hash_chain(toks, 4, adapter_salt(base)) == naive_a


# ---------------------------------------------------------------------------
# PagePool: intern index, refcounts, eviction


class TestInternPool:
    def test_intern_outlives_writer_and_is_shared(self):
        pool = PagePool(n_pages=8, page_size=4, pages_per_slot=4,
                        lru_capacity=8)
        chain = prefix_hash_chain(list(range(8)), 4)
        owned = pool.map_slot(0, 8)                       # 2 pages
        assert pool.intern_prefix(chain, owned)
        assert pool.release_slot(0) == []                 # entry holds refs
        assert pool.free_count == 6
        assert pool.reclaimable_count == 2
        pages, matched = pool.match_prefix(chain)
        assert matched == 2 and pages == owned
        # a second tenant pins the shared pages + one private
        mapped = pool.map_slot(1, 9, shared=pages)
        assert mapped[:2] == pages and len(mapped) == 3
        pool.check()
        freed = pool.release_slot(1)
        assert len(freed) == 1 and freed[0] not in pages  # private only
        pool.check()

    def test_intern_off_at_zero_capacity(self):
        pool = PagePool(n_pages=4, page_size=4, pages_per_slot=4,
                        lru_capacity=0)
        owned = pool.map_slot(0, 8)
        assert not pool.intern_prefix((1, 2), owned)
        assert pool.interned_count == 0
        assert pool.release_slot(0) == owned              # nothing held

    def test_lru_capacity_evicts_oldest(self):
        pool = PagePool(n_pages=8, page_size=4, pages_per_slot=4,
                        lru_capacity=2)
        chains = [prefix_hash_chain([i] * 4, 4) for i in range(3)]
        for slot, chain in enumerate(chains):
            pages = pool.map_slot(slot, 4)
            assert pool.intern_prefix(chain, pages)
            pool.release_slot(slot)
        assert pool.interned_count == 2
        assert pool.evictions == 1
        assert pool.match_prefix(chains[0])[1] == 0       # oldest gone
        assert pool.match_prefix(chains[2])[1] == 1
        assert pool.free_count + pool.reclaimable_count == 8
        pool.check()

    def test_longer_chain_subsumes_shorter(self):
        pool = PagePool(n_pages=8, page_size=4, pages_per_slot=4,
                        lru_capacity=8)
        toks = list(range(12))
        short, full = prefix_hash_chain(toks[:8], 4), \
            prefix_hash_chain(toks, 4)
        pages = pool.map_slot(0, 12)
        assert pool.intern_prefix(short, pages[:2])
        assert pool.intern_prefix(full, pages)            # upgrades
        assert pool.interned_count == 1
        assert pool.evictions == 0                        # upgrade, not evict
        assert pool.match_prefix(full)[1] == 3
        pool.release_slot(0)
        pool.check()

    def test_pressure_evicts_reclaimable_not_slot_held(self):
        pool = PagePool(n_pages=4, page_size=4, pages_per_slot=4,
                        lru_capacity=8)
        chain = prefix_hash_chain(list(range(8)), 4)
        pool.intern_prefix(chain, pool.map_slot(0, 8))
        pool.release_slot(0)                              # 2 reclaimable
        assert pool.map_slot(1, 12) is not None           # needs 3: evicts
        assert pool.evictions == 1
        assert pool.match_prefix(chain)[1] == 0
        pool.check()
        # now every referenced page is slot-held: nothing evictable
        assert pool.map_slot(2, 8) is None
        pool.check()

    def test_randomized_intern_churn_conserves(self):
        """Random arrivals x cancellations x interning x pressure
        evictions: refcounts recomputed from memberships match at every
        step, and pages partition into free/referenced exactly."""
        rng = np.random.RandomState(47)
        pool = PagePool(n_pages=16, page_size=4, pages_per_slot=4,
                        lru_capacity=4)
        live = {}                                         # slot -> chain
        for _ in range(400):
            op = rng.randint(4)
            slot = int(rng.randint(6))
            if op == 0 and slot not in live:
                toks = rng.randint(0, 8, size=rng.randint(4, 14)).tolist()
                chain = prefix_hash_chain(toks, 4)
                shared, matched = pool.match_prefix(chain)
                mapped = pool.map_slot(slot, len(toks),
                                       shared=shared or None)
                if mapped is not None:
                    live[slot] = (chain, mapped)
            elif op == 1 and slot in live:
                chain, mapped = live[slot]
                if chain:
                    pool.intern_prefix(chain, mapped[:len(chain)])
            elif op == 2 and slot in live:
                pool.release_slot(slot)
                del live[slot]
            elif op == 3 and slot in live:
                pool.extend_slot(slot, int(rng.randint(1, 17)))
            assert pool.free_count + pool.in_use_count == 16
            pool.check()
        for slot in list(live):
            pool.release_slot(slot)
        assert pool.free_count + pool.reclaimable_count == 16
        pool.reset()
        assert pool.free_count == 16 and pool.interned_count == 0
        pool.check()


# ---------------------------------------------------------------------------
# engine: hit-vs-cold token exactness, COW seam, quarantine, eviction


def _shared_prefix_requests(seed=19):
    """Mixed traffic over one 8-token prefix (2 full pages at page_size
    4): a miss that interns, a fully page-aligned hit (the skip_first
    COW seam), and partial-page-suffix hits, greedy AND sampled."""
    rng = np.random.RandomState(seed)
    prefix = rng.randint(0, 64, size=8).tolist()

    def req(extra, max_new, sampling):
        return Request(
            prompt=prefix + rng.randint(0, 64, size=extra).tolist()
            if extra else list(prefix),
            max_new_tokens=max_new, sampling=sampling)

    return prefix, [
        req(3, 5, SamplingParams()),                       # miss, interns
        req(0, 6, SamplingParams()),                       # aligned hit
        req(5, 4, SamplingParams(temperature=0.8, top_k=8, seed=3)),
        req(1, 5, SamplingParams(temperature=1.1, seed=9)),
    ]


class TestPrefixEngine:
    def test_hit_vs_cold_token_exact(self, small):
        """The acceptance bar: identical shared-prefix traffic through
        ``prefix_cache=True`` and ``prefix_cache=False`` engines is
        TOKEN-EXACT — greedy and sampled, aligned and partial-page
        boundaries — with zero decode retraces, hits + misses == paged
        prefills, and every page free or interned after drain."""
        model, params = small
        _, reqs = _shared_prefix_requests()
        cold = InferenceEngine(model, params, EngineConfig(
            max_slots=2, max_len=32, page_size=4, prefix_cache=False))
        with cold:
            _, cold_reqs = _shared_prefix_requests()
            ref = cold.serve(cold_reqs)
            assert cold.decode_retraces == 0
            assert cold.metrics.counters()["prefix_hits"] == 0
            assert cold.pages.interned_count == 0
        hot = InferenceEngine(model, params, EngineConfig(
            max_slots=2, max_len=32, page_size=4))
        with hot:
            out = hot.serve(reqs)
            assert hot.decode_retraces == 0
            c = hot.metrics.counters()
            assert c["prefix_misses"] == 1
            assert c["prefix_hits"] == 3
            assert c["prefix_hits"] + c["prefix_misses"] == c["prefills"]
            assert c["prefix_pages_shared"] == 6          # 2 pages x 3 hits
            assert hot.pages.interned_count >= 1
            assert hot.pages.free_count + hot.pages.reclaimable_count == \
                hot.pages.n_pages
            hot.pages.check()
            hot.slots.check()
        for a, b in zip(ref, out):
            assert a.finish_reason == b.finish_reason
            assert a.tokens == b.tokens, (a.request_id, a.tokens, b.tokens)
        for r, req in zip(out, reqs):
            if req.sampling.temperature == 0.0:
                assert r.tokens == _expected_greedy(model, params, req, 32)

    @pytest.mark.slow  # COW edge-seam sweep: slow tier (ROADMAP)

    def test_partial_page_boundary_cow(self, small):
        """Two prompts sharing full pages but diverging INSIDE the
        trailing partial page: the second maps the shared run and
        prefills its divergent suffix into private pages only — serving
        the first prompt again (now a hit itself) stays token-exact,
        proving the divergent tenant never wrote the shared pages."""
        model, params = small
        rng = np.random.RandomState(23)
        base = rng.randint(0, 64, size=10).tolist()       # 2 pages + 2
        fork = list(base)
        fork[9] = (fork[9] + 1) % 64                      # partial page only
        eng = InferenceEngine(model, params, EngineConfig(
            max_slots=2, max_len=32, page_size=4))
        with eng:
            for prompt in (base, fork, base):
                req = Request(prompt=list(prompt), max_new_tokens=5)
                res = eng.serve([req])
                assert res[0].tokens == _expected_greedy(
                    model, params, req, 32), prompt
            c = eng.metrics.counters()
            assert c["prefix_misses"] == 1 and c["prefix_hits"] == 2
            assert eng.decode_retraces == 0
            eng.pages.check()

    @pytest.mark.slow  # quarantine x prefix feature-cross: slow tier (ROADMAP)

    def test_quarantine_sharing_slot_leaves_co_tenants_exact(self, small):
        """Poisoned decode on one of two slots sharing interned prefix
        pages: the victim quarantines (only its PRIVATE freed pages are
        scrubbed), the co-tenant finishes token-exact, and a later
        request still HITS the interned prefix and decodes exactly —
        shared pages survive a sharing tenant's quarantine untouched."""
        model, params = small
        rng = np.random.RandomState(29)
        prefix = rng.randint(0, 64, size=8).tolist()
        survivor = Request(prompt=prefix + [3, 4], max_new_tokens=6)
        victim = Request(prompt=prefix + [9], max_new_tokens=6)
        # slot 1 = the second prefill (the victim); poison a decode call
        # late enough that both tenants are mid-decode
        inj = ServingFaultInjector(poison_decode={3: (1, "nonfinite")})
        eng = InferenceEngine(model, params, EngineConfig(
            max_slots=2, max_len=32, page_size=4), faults=inj)
        with eng:
            results = {r.request_id: r
                       for r in eng.serve([survivor, victim])}
            assert results[victim.request_id].finish_reason == "error"
            assert results[survivor.request_id].tokens == _expected_greedy(
                model, params, survivor, 32)
            assert eng.metrics.counters()["slots_quarantined"] == 1
            eng.pages.check()
            assert eng.pages.free_count + eng.pages.reclaimable_count == \
                eng.pages.n_pages
            late = Request(prompt=prefix + [7, 8, 9], max_new_tokens=5)
            res = eng.serve([late])
            assert res[0].tokens == _expected_greedy(model, params,
                                                     late, 32)
            c = eng.metrics.counters()
            assert c["prefix_hits"] >= 2                  # victim + late
            assert eng.decode_retraces == 0

    @pytest.mark.slow  # eviction stress sweep: slow tier (ROADMAP)

    def test_lru_eviction_under_pressure_then_reintern(self, small):
        """A pool sized so distinct prefixes cannot all stay interned:
        admission keeps working (eviction instead of shedding), the
        ``prefix_evictions`` counter advances, conservation holds, and
        the evicted prefix re-interns on its next miss, token-exact."""
        model, params = small
        rng = np.random.RandomState(37)
        prefixes = [rng.randint(0, 64, size=8).tolist() for _ in range(4)]
        eng = InferenceEngine(model, params, EngineConfig(
            max_slots=2, max_len=16, page_size=4, n_pages=8))
        with eng:
            for p in prefixes:                            # distinct misses
                req = Request(prompt=list(p), max_new_tokens=4)
                res = eng.serve([req])
                assert res[0].tokens == _expected_greedy(
                    model, params, req, 16)
            c = eng.metrics.counters()
            assert c["prefix_evictions"] >= 1             # pressure evicted
            assert c["prefix_misses"] == 4
            eng.pages.check()
            assert eng.pages.free_count + eng.pages.reclaimable_count == \
                eng.pages.n_pages
            # the first prefix was evicted: a repeat misses, re-interns,
            # and an immediate second repeat hits
            again = Request(prompt=list(prefixes[0]), max_new_tokens=4)
            res = eng.serve([again])
            assert res[0].tokens == _expected_greedy(
                model, params, again, 16)
            hit = Request(prompt=list(prefixes[0]), max_new_tokens=4)
            res = eng.serve([hit])
            assert res[0].tokens == _expected_greedy(model, params, hit, 16)
            c = eng.metrics.counters()
            assert c["prefix_hits"] >= 1
            assert eng.decode_retraces == 0

    def test_close_clears_intern_index(self, small):
        model, params = small
        eng = InferenceEngine(model, params, EngineConfig(
            max_slots=2, max_len=16, page_size=4))
        eng.serve([Request(prompt=_prompts([8])[0], max_new_tokens=3)])
        assert eng.pages.interned_count == 1
        eng.close()
        assert eng.pages.free_count == eng.pages.n_pages
        assert eng.pages.interned_count == 0


# ---------------------------------------------------------------------------
# router: bounded prefix-affinity discount


class _StubSup:
    def __init__(self, queued, active, service):
        self.queued_count = queued
        self.active_count = active
        self.service_estimate_s = service


def _stub_replica(rid, queued, active, service):
    return _Replica(rid, _StubSup(queued, active, service))


class TestRouterAffinity:
    def test_resident_match_wins_equal_load(self):
        rt = Router(affinity_weight=0.3)
        chain = (11, 22, 33)
        rt.note_dispatch(1, chain)
        a = _stub_replica(0, queued=2, active=0, service=0.5)
        b = _stub_replica(1, queued=2, active=0, service=0.5)
        assert rt.pick([a, b], chain=chain).replica_id == 1
        # no chain / no match: id still breaks the tie deterministically
        assert rt.pick([a, b]).replica_id == 0
        assert rt.pick([a, b], chain=(99,)).replica_id == 0

    def test_partial_match_scores_fractionally(self):
        rt = Router(affinity_weight=0.5)
        rt.note_dispatch(0, (1, 2))
        assert rt.affinity(0, (1, 2, 3, 4)) == 0.5
        assert rt.affinity(0, (7, 8)) == 0.0
        assert rt.affinity(1, (1, 2)) == 0.0              # not resident

    def test_bonus_is_bounded_load_still_sheds(self):
        """The discount can never beat a big enough load gap: with
        weight w a full match scales cost by (1 - w) > 0, so a hot
        resident replica still loses to an idle cold peer."""
        rt = Router(affinity_weight=0.3)
        chain = (1, 2, 3)
        rt.note_dispatch(0, chain)
        hot = _stub_replica(0, queued=6, active=0, service=0.5)   # 3.0->2.1
        cold = _stub_replica(1, queued=1, active=0, service=0.5)  # 0.5
        assert rt.pick([hot, cold], chain=chain).replica_id == 1

    def test_invalidate_forgets_residency(self):
        rt = Router(affinity_weight=0.3)
        rt.note_dispatch(0, (1, 2))
        rt.invalidate(0)
        assert rt.affinity(0, (1, 2)) == 0.0

    def test_residency_is_bounded_lru(self):
        rt = Router(affinity_weight=0.3, residency_capacity=2)
        for i in range(5):
            rt.note_dispatch(0, (i,))
        assert rt.affinity(0, (0,)) == 0.0                # evicted
        assert rt.affinity(0, (4,)) == 1.0

    def test_weight_validation(self):
        with pytest.raises(ValueError, match="affinity_weight"):
            Router(affinity_weight=1.0)
        with pytest.raises(ValueError, match="prefix_affinity_weight"):
            FleetConfig(prefix_affinity_weight=-0.1)
        with pytest.raises(ValueError, match="prefix_affinity_weight"):
            FleetConfig(prefix_affinity_weight=1.5)


# ---------------------------------------------------------------------------
# slow tier: compile-bound crosses (ROADMAP tier policy)


class TestPrefixResilience:
    @pytest.mark.slow
    def test_supervisor_restart_over_shared_pages_token_exact(self, small):
        """A decode crash while two requests share interned prefix
        pages: the supervisor rebuild (fresh pool, EMPTY intern index)
        re-prefills through the same prefix-cache admit path and every
        request stays token-exact — recovery and reuse compose."""
        model, params = small
        rng = np.random.RandomState(43)
        prefix = rng.randint(0, 64, size=8).tolist()
        reqs = [Request(prompt=prefix + [1, 2], max_new_tokens=6),
                Request(prompt=prefix + [5], max_new_tokens=8)]
        inj = ServingFaultInjector(decode_raise_calls={3})
        sup = EngineSupervisor(
            model, params,
            EngineConfig(max_slots=2, max_len=32, page_size=4),
            faults=inj)
        with sup:
            results = {r.request_id: r for r in sup.serve(reqs)}
        assert sup.restarts == 1
        for req in reqs:
            assert results[req.request_id].tokens == _expected_greedy(
                model, params, req, 32)
        eng = sup.engine
        assert eng.pages.free_count + eng.pages.reclaimable_count == \
            eng.pages.n_pages
        eng.pages.check()

    @pytest.mark.slow
    def test_tp2_sharded_prefix_hits_vs_unsharded_flat(self, small):
        """ShardedEngine (tp=2, prefix cache ON, suffix prefill
        shard_mapped) against the unsharded FLAT engine on shared-prefix
        traffic: token-exact with real prefix hits on the sharded side —
        the mesh cannot hide in the reuse path nor vice versa."""
        from apex_tpu.serving import ShardedEngine
        from apex_tpu.transformer import parallel_state

        model, params = small
        _, reqs = _shared_prefix_requests(seed=59)
        flat_eng = InferenceEngine(model, params, EngineConfig(
            max_slots=2, max_len=32, kv_layout="flat"))
        with flat_eng:
            _, flat_reqs = _shared_prefix_requests(seed=59)
            ref = flat_eng.serve(flat_reqs)

        parallel_state.destroy_model_parallel()
        try:
            parallel_state.initialize_model_parallel(
                tensor_model_parallel_size=2)
            sharded = ShardedEngine(model, params, EngineConfig(
                max_slots=2, max_len=32, kv_layout="paged", page_size=4))
            with sharded:
                out = sharded.serve(reqs)
                assert sharded.decode_retraces == 0
                c = sharded.metrics.counters()
                assert c["prefix_hits"] == 3
                assert c["prefix_hits"] + c["prefix_misses"] == \
                    c["prefills"]
                assert sharded.pages.free_count + \
                    sharded.pages.reclaimable_count == sharded.pages.n_pages
                sharded.pages.check()
        finally:
            parallel_state.destroy_model_parallel()
        for a, b in zip(ref, out):
            assert a.finish_reason == b.finish_reason
            assert a.tokens == b.tokens, (a.request_id, a.tokens, b.tokens)
