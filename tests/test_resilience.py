"""Resilience-layer suite: watchdog, rollback, retrying checkpoints,
preemption — every recovery path driven deterministically on CPU via the
fault-injection harness (``apex_tpu/testing_faults.py``).

The acceptance bar (ISSUE 1): (a) injected NaN gradients trip the watchdog,
training rolls back to the last good checkpoint with a reduced loss scale
and converges to the SAME final loss as an uninterrupted run on the same
seed; (b) a save killed mid-write falls back to the next-older step on
restore; (c) SIGTERM produces a resumable emergency checkpoint.
"""

import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.amp.scaler import LossScaler
from apex_tpu.checkpoint import (
    CheckpointManager,
    RetryingCheckpointManager,
    ShardedCheckpointManager,
)
from apex_tpu.optimizers import FusedSGD
from apex_tpu.resilience import (
    ResilienceConfig,
    TrainingDiverged,
    Watchdog,
    make_resilient_train_step,
    make_train_state,
    run_training,
)
from apex_tpu.testing_faults import FaultInjector, corrupt_checkpoint

# small + fast: every run_training test finishes in a few seconds on CPU
TARGET = jnp.full((4, 4), 0.3)


def _loss_fn(p, batch, rng):
    pred = batch["x"] @ p["w"] + p["b"]
    return jnp.mean((pred - batch["y"]) ** 2)


def _batch_fn(step):
    x = jax.random.normal(jax.random.PRNGKey(step), (8, 4))
    return {"x": x, "y": x @ TARGET}


def _scaler():
    return LossScaler("dynamic", init_scale=2.0 ** 8, scale_window=100)


def _fresh(scaler=None, opt=None):
    opt = opt or FusedSGD(lr=0.05)
    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    sstate = scaler.init() if scaler is not None else None
    return make_train_state(params, opt.init(params), sstate)


def _step_fn(scaler=None, opt=None):
    return make_resilient_train_step(_loss_fn, opt or FusedSGD(lr=0.05),
                                     scaler)


def _cfg(**kw):
    base = dict(poll_interval_steps=2, save_interval_steps=4,
                max_consecutive_skips=3, min_history=4,
                save_backoff_base=0.0, handle_sigterm=False)
    base.update(kw)
    return ResilienceConfig(**base)


class TestWatchdog:
    def test_consecutive_skips_trip(self):
        wd = Watchdog(ResilienceConfig(max_consecutive_skips=3))
        assert wd.observe(1, 1.0, 1.0, skipped=True) is None
        assert wd.observe(2, float("nan"), 1.0) is None
        v = wd.observe(3, 1.0, 1.0, skipped=True)
        assert v is not None and v.reason == "consecutive_skips"
        assert v.first_bad_step == 1

    def test_healthy_step_resets_skip_run(self):
        wd = Watchdog(ResilienceConfig(max_consecutive_skips=3))
        for step in range(20):
            # alternating skip/healthy never reaches 3 consecutive
            assert wd.observe(step, 1.0, 1.0,
                              skipped=(step % 2 == 0)) is None

    def test_loss_spike(self):
        cfg = ResilienceConfig(min_history=4, loss_spike_factor=10.0,
                               anomaly_patience=2)
        wd = Watchdog(cfg)
        for step in range(6):
            assert wd.observe(step, 1.0 + 0.01 * step, 1.0) is None
        assert wd.observe(6, 500.0, 1.0) is None          # patience 1/2
        v = wd.observe(7, 500.0, 1.0)
        assert v is not None and v.reason == "loss_spike"
        assert v.first_bad_step == 6

    def test_grad_norm_spike(self):
        cfg = ResilienceConfig(min_history=4, grad_spike_factor=50.0,
                               anomaly_patience=1)
        wd = Watchdog(cfg)
        for step in range(6):
            assert wd.observe(step, 1.0, 2.0) is None
        v = wd.observe(6, 1.0, 1e4)
        assert v is not None and v.reason == "grad_spike"

    def test_single_anomaly_forgiven(self):
        cfg = ResilienceConfig(min_history=4, loss_spike_factor=10.0,
                               anomaly_patience=2)
        wd = Watchdog(cfg)
        for step in range(6):
            assert wd.observe(step, 1.0, 1.0) is None
        assert wd.observe(6, 500.0, 1.0) is None
        # healthy step resets patience — and the spike never entered the
        # rolling history, so the baseline is still ~1.0
        assert wd.observe(7, 1.0, 1.0) is None
        assert wd.observe(8, 500.0, 1.0) is None


class TestRetryingCheckpointManager:
    def test_transient_save_failure_retried(self, tmp_path):
        inj = FaultInjector(save_failures={3: 2})
        mgr = RetryingCheckpointManager(
            CheckpointManager(str(tmp_path / "run"), save_interval_steps=1),
            max_retries=3, backoff_base=0.0,
            before_save=inj.before_checkpoint_save)
        assert mgr.save(3, {"w": jnp.ones((4,))}) is True
        assert mgr.telemetry["save_retries"] == 2
        assert mgr.telemetry["save_failures"] == 0
        assert mgr.manager.all_steps() == [3]
        mgr.close()

    def test_exhausted_retries_counted_not_fatal(self, tmp_path):
        inj = FaultInjector(save_failures={3: 99})
        mgr = RetryingCheckpointManager(
            CheckpointManager(str(tmp_path / "run"), save_interval_steps=1),
            max_retries=2, backoff_base=0.0,
            before_save=inj.before_checkpoint_save)
        assert mgr.save(3, {"w": jnp.ones((4,))}) is False
        assert mgr.telemetry["save_failures"] == 1
        assert mgr.manager.all_steps() == []
        mgr.close()

    def test_corrupt_restore_falls_back_and_deletes(self, tmp_path):
        state = {"w": jnp.zeros((4,))}
        base = CheckpointManager(str(tmp_path / "run"),
                                 save_interval_steps=1)
        for step in (1, 2, 3):
            base.save(step, {"w": jnp.full((4,), float(step))})
        base.wait_until_finished()
        assert corrupt_checkpoint(str(tmp_path / "run"), 3) > 0
        mgr = RetryingCheckpointManager(base, backoff_base=0.0)
        step, restored = mgr.restore_latest(state)
        assert step == 2
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.full((4,), 2.0))
        assert mgr.telemetry["restore_fallbacks"] == 1
        assert mgr.telemetry["deleted_corrupt"] == 1
        assert base.all_steps() == [1, 2]  # corrupt step gone
        mgr.close()

    def test_restore_before_bounds_step(self, tmp_path):
        base = CheckpointManager(str(tmp_path / "run"),
                                 save_interval_steps=1)
        for step in (1, 2, 3):
            base.save(step, {"w": jnp.full((4,), float(step))})
        base.wait_until_finished()
        mgr = RetryingCheckpointManager(base, backoff_base=0.0)
        step, restored = mgr.restore_before(3, {"w": jnp.zeros((4,))})
        assert step == 2
        assert mgr.restore_before(1, {"w": jnp.zeros((4,))}) is None
        mgr.close()


class TestNaNRollbackRecovery:
    """Acceptance (a): NaN injection → watchdog → rollback → convergence
    parity with the uninterrupted run."""

    def test_recovers_to_uninterrupted_trajectory(self, tmp_path):
        scaler = _scaler()
        step_fn = _step_fn(scaler)
        cfg = _cfg()
        clean = run_training(step_fn, _fresh(scaler), _batch_fn, 20,
                             checkpoint_dir=str(tmp_path / "clean"),
                             config=cfg)
        assert clean.status == "completed" and clean.rollbacks == 0

        inj = FaultInjector(nan_grad_calls=range(6, 10))
        faulted = run_training(step_fn, _fresh(scaler), _batch_fn, 20,
                               checkpoint_dir=str(tmp_path / "faulted"),
                               config=cfg, fault_injector=inj)
        assert faulted.status == "completed"
        assert faulted.rollbacks == 1
        assert faulted.telemetry["skips"] >= 3   # the injected window
        # rolled back and replayed: more step calls than the step budget
        assert faulted.telemetry["steps"] > 20

        # SAME final loss as the uninterrupted run on the same seed: the
        # rollback restored params/opt/scaler from before the poison and
        # the replayed steps saw identical (clean) batches and rng
        assert clean.history[-1]["step"] == faulted.history[-1]["step"] == 20
        np.testing.assert_allclose(faulted.history[-1]["loss"],
                                   clean.history[-1]["loss"], rtol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7),
            jax.device_get(faulted.state["params"]),
            jax.device_get(clean.state["params"]))

        # the retry ran at a decayed loss scale (clean run kept 2**8)
        assert (float(jax.device_get(faulted.state["scaler"].loss_scale))
                < float(jax.device_get(clean.state["scaler"].loss_scale)))

    def test_rollback_reseeds_data_iterator(self, tmp_path):
        scaler = _scaler()
        step_fn = _step_fn(scaler)
        seen = []

        def batch_fn(step, retry_epoch):
            seen.append((step, retry_epoch))
            return _batch_fn(step)

        inj = FaultInjector(nan_grad_calls=range(6, 10))
        res = run_training(step_fn, _fresh(scaler), batch_fn, 16,
                           checkpoint_dir=str(tmp_path / "run"),
                           config=_cfg(), fault_injector=inj)
        assert res.rollbacks == 1
        # the replay after rollback ran under an incremented retry epoch —
        # the hook a real pipeline uses to skip the poisoned window
        assert {e for _, e in seen} == {0, 1}
        replayed = [s for s, e in seen if e == 1]
        assert min(replayed) < 8  # re-reads steps from the restore point

    def test_persistent_divergence_exhausts_budget(self, tmp_path):
        scaler = _scaler()
        step_fn = _step_fn(scaler)
        # clean until call 6 (a good checkpoint lands at step 4), then
        # NaN forever: every retry re-diverges until the budget runs out
        inj = FaultInjector(nan_grad_calls=range(6, 10_000))
        with pytest.raises(TrainingDiverged, match="budget"):
            run_training(step_fn, _fresh(scaler), _batch_fn, 40,
                         checkpoint_dir=str(tmp_path / "run"),
                         config=_cfg(max_rollbacks=2), fault_injector=inj)

    def test_divergence_with_no_good_checkpoint(self, tmp_path):
        scaler = _scaler()
        step_fn = _step_fn(scaler)
        inj = FaultInjector(nan_grad_calls=range(0, 10_000))
        with pytest.raises(TrainingDiverged, match="no healthy checkpoint"):
            run_training(step_fn, _fresh(scaler), _batch_fn, 40,
                         checkpoint_dir=str(tmp_path / "run"),
                         config=_cfg(), fault_injector=inj)

    def test_verdict_without_manager_raises(self):
        scaler = _scaler()
        step_fn = _step_fn(scaler)
        inj = FaultInjector(nan_grad_calls=range(0, 100))
        with pytest.raises(TrainingDiverged, match="no checkpoint manager"):
            run_training(step_fn, _fresh(scaler), _batch_fn, 20,
                         config=_cfg(), fault_injector=inj)

    def test_no_scaler_still_skips_and_recovers(self, tmp_path):
        # without amp, the step's fused finiteness check still reports
        # skipped=True and the optimizer's found_inf select holds params
        step_fn = _step_fn(scaler=None)
        inj = FaultInjector(nan_grad_calls=range(6, 10))
        res = run_training(step_fn, _fresh(), _batch_fn, 16,
                           checkpoint_dir=str(tmp_path / "run"),
                           config=_cfg(), fault_injector=inj)
        assert res.status == "completed" and res.rollbacks == 1
        skipped = [h for h in res.history if h["skipped"]]
        assert len(skipped) >= 3
        assert np.isfinite(res.history[-1]["loss"])


class TestCheckpointFaultRecovery:
    """Acceptance (b): a save killed mid-write → restore falls back to the
    next-older step."""

    def test_resume_falls_back_past_corrupt_newest(self, tmp_path):
        scaler = _scaler()
        step_fn = _step_fn(scaler)
        run_dir = str(tmp_path / "run")
        cfg = _cfg(save_final=False)
        first = run_training(step_fn, _fresh(scaler), _batch_fn, 12,
                             checkpoint_dir=run_dir, config=cfg)
        assert first.status == "completed"
        # garble the newest step on disk (a writer killed after the data
        # write raced orbax's commit, or plain bit rot)
        assert corrupt_checkpoint(run_dir, 12) > 0

        resumed = run_training(step_fn, _fresh(scaler), _batch_fn, 16,
                               checkpoint_dir=run_dir, config=cfg)
        assert resumed.status == "completed"
        assert resumed.telemetry["resumes"] == 1
        # resumed from step 8, not 12: history starts at 9
        assert resumed.history[0]["step"] == 9
        assert resumed.steps_completed == 16

    def test_transient_save_failures_do_not_stop_training(self, tmp_path):
        scaler = _scaler()
        step_fn = _step_fn(scaler)
        inj = FaultInjector(save_failures={4: 2, 8: 99})
        res = run_training(step_fn, _fresh(scaler), _batch_fn, 12,
                           checkpoint_dir=str(tmp_path / "run"),
                           config=_cfg(save_retries=2),
                           fault_injector=inj)
        # step-4 save succeeded on retry; step-8 save failed terminally;
        # training completed regardless
        assert res.status == "completed" and res.steps_completed == 12
        # run_training's default manager writes the sharded format — list
        # the committed steps with the same
        steps = ShardedCheckpointManager(str(tmp_path / "run")).all_steps()
        assert 4 in steps and 8 not in steps and 12 in steps


class TestPreemption:
    """Acceptance (c): SIGTERM → emergency checkpoint → clean exit →
    resumable."""

    def test_sigterm_emergency_save_and_resume(self, tmp_path):
        scaler = _scaler()
        step_fn = _step_fn(scaler)
        run_dir = str(tmp_path / "run")
        cfg = _cfg(handle_sigterm=True)
        prev_handler = signal.getsignal(signal.SIGTERM)
        calls = {"n": 0}

        def batch_fn(step):
            calls["n"] += 1
            if calls["n"] == 6:
                os.kill(os.getpid(), signal.SIGTERM)
            return _batch_fn(step)

        res = run_training(step_fn, _fresh(scaler), batch_fn, 40,
                           checkpoint_dir=run_dir, config=cfg)
        assert res.status == "preempted"
        assert res.telemetry["emergency_saves"] == 1
        assert 0 < res.steps_completed < 40
        # the previous handler was restored on exit
        assert signal.getsignal(signal.SIGTERM) == prev_handler

        resumed = run_training(step_fn, _fresh(scaler), _batch_fn, 40,
                               checkpoint_dir=run_dir, config=cfg)
        assert resumed.status == "completed"
        assert resumed.telemetry["resumes"] == 1
        assert resumed.steps_completed == 40

        # trajectory parity: preempt+resume equals one uninterrupted run
        clean = run_training(step_fn, _fresh(scaler), _batch_fn, 40,
                             checkpoint_dir=str(tmp_path / "clean"),
                             config=cfg)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7),
            jax.device_get(resumed.state["params"]),
            jax.device_get(clean.state["params"]))

    def test_injected_preemption_is_equivalent(self, tmp_path):
        scaler = _scaler()
        step_fn = _step_fn(scaler)
        inj = FaultInjector(preempt_at_call=5)
        res = run_training(step_fn, _fresh(scaler), _batch_fn, 40,
                           checkpoint_dir=str(tmp_path / "run"),
                           config=_cfg(), fault_injector=inj)
        assert res.status == "preempted"
        assert res.steps_completed == 5
        assert res.telemetry["emergency_saves"] == 1


class TestScalerCheckpointRoundtrip:
    """Satellite: LossScalerState through CheckpointManager — hysteresis /
    growth trackers resume exactly, plus the load_state_dict defaulting
    paths (amp/scaler.py:147-155)."""

    def _advance(self, scaler, state, pattern):
        for inf in pattern:
            state = scaler.update(state, jnp.asarray(bool(inf)))
        return state

    def test_roundtrip_resumes_trackers_exactly(self, tmp_path):
        scaler = LossScaler("dynamic", init_scale=2.0 ** 10,
                            scale_window=8, hysteresis=3)
        # 2 overflows (one hysteresis credit left), then 5 finite steps
        state = self._advance(scaler, scaler.init(),
                              [1, 1, 0, 0, 0, 0, 0])
        mgr = CheckpointManager(str(tmp_path / "run"),
                                save_interval_steps=1)
        mgr.save(7, {"scaler": state})
        mgr.wait_until_finished()
        step, restored = mgr.restore({"scaler": state})
        mgr.close()
        got = restored["scaler"]
        assert int(got.growth_tracker) == int(state.growth_tracker) == 5
        assert int(got.hysteresis_tracker) == int(
            state.hysteresis_tracker) == 1
        assert int(got.unskipped) == int(state.unskipped) == 5
        assert float(got.loss_scale) == float(state.loss_scale) == 2.0 ** 10

        # continuation parity: stepping the restored state matches
        # stepping the original — growth fires at the same step (3 more
        # finite steps reach the window of 8) and hysteresis refills
        cont_a = self._advance(scaler, state, [0, 0, 0])
        cont_b = self._advance(scaler, got, [0, 0, 0])
        assert float(cont_a.loss_scale) == float(cont_b.loss_scale) \
            == 2.0 ** 11
        assert int(cont_b.hysteresis_tracker) == 3
        assert int(cont_b.growth_tracker) == int(cont_a.growth_tracker) == 0

    def test_load_state_dict_defaults(self):
        scaler = LossScaler("dynamic", hysteresis=4)
        # minimal dict (an old checkpoint): trackers default — growth 0,
        # hysteresis refilled to the constructor's value, unskipped 0
        state = scaler.load_state_dict({"loss_scale": 512.0})
        assert float(state.loss_scale) == 512.0
        assert int(state.growth_tracker) == 0
        assert int(state.hysteresis_tracker) == 4
        assert int(state.unskipped) == 0
        # full dict round-trips exactly
        full = self._advance(scaler, scaler.init(), [1, 0, 0])
        again = scaler.load_state_dict(scaler.state_dict(full))
        assert scaler.state_dict(again) == scaler.state_dict(full)


class TestResilientStepMesh:
    """The shard_map path of make_resilient_train_step: same contract on a
    data-parallel mesh, grads pmean'd, metrics replicated."""

    def test_data_parallel_step_descends(self, data_mesh):
        from jax.sharding import PartitionSpec as P

        scaler = _scaler()
        opt = FusedSGD(lr=0.05)
        params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
        spec = {"w": P(), "b": P()}
        step_fn = make_resilient_train_step(
            _loss_fn, opt, scaler, mesh=data_mesh, param_spec=spec,
            batch_spec={"x": P("data"), "y": P("data")},
            params_template=params)
        state = make_train_state(params, opt.init(params), scaler.init())
        losses = []
        for i in range(6):
            state, metrics = step_fn(state, _batch_fn(i), None)
            losses.append(float(metrics["loss"]))
            assert not bool(metrics["skipped"])
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]
        assert int(jax.device_get(state["step"])) == 6

    def test_mesh_step_reports_nan_skip(self, data_mesh):
        from jax.sharding import PartitionSpec as P

        from apex_tpu.testing_faults import poison_batch

        scaler = _scaler()
        opt = FusedSGD(lr=0.05)
        params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
        spec = {"w": P(), "b": P()}
        step_fn = make_resilient_train_step(
            _loss_fn, opt, scaler, mesh=data_mesh, param_spec=spec,
            batch_spec={"x": P("data"), "y": P("data")},
            params_template=params)
        state = make_train_state(params, opt.init(params), scaler.init())
        new_state, metrics = step_fn(state, poison_batch(_batch_fn(0)),
                                     None)
        assert bool(jax.device_get(metrics["skipped"]))
        # params held (the optimizer's found_inf select)
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(new_state["params"]["w"])),
            np.ones((4, 4)))
