"""Context-parallel (long-context) suite.

The reference has no CP (SURVEY.md §5: attention kernels cap at 16k and the
only sequence mechanism is Megatron SP), so the ground truth here is the
single-device flash/reference attention: ring and Ulysses attention over a
sharded sequence must reproduce it — forward and gradients — and the GPT
model must train identically with the sequence split over the ``context``
mesh axis.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

os.environ.setdefault("APEX_TPU_FORCE_PALLAS", "interpret")

from apex_tpu.models import GPTModel, TransformerConfig  # noqa: E402
from apex_tpu.ops import flash_attention, ring_attention, ulysses_attention  # noqa: E402
from apex_tpu.transformer import parallel_state  # noqa: E402
from apex_tpu.utils.sharding import shard_map  # noqa: E402


def _qkv(b=2, h=4, s=32, d=16, key=0):
    ks = jax.random.split(jax.random.PRNGKey(key), 3)
    shape = (b, h, s, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


def _weights(shape, s_offset, s_total):
    """Position-dependent weights consistent between global and sharded
    layouts: w[b,h,s,d] = flat index in the GLOBAL [b,h,s_total,d] array."""
    b, h, sc, d = shape
    bi = jnp.arange(b).reshape(b, 1, 1, 1)
    hi = jnp.arange(h).reshape(1, h, 1, 1)
    si = jnp.arange(sc).reshape(1, 1, sc, 1) + s_offset
    di = jnp.arange(d).reshape(1, 1, 1, d)
    return (((bi * h + hi) * s_total + si) * d + di).astype(jnp.float32)


def _run_cp(fn, q, k, v, cp, causal):
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(context_parallel_size=cp)
    s_total = q.shape[2]

    def attn_loss(q, k, v):
        o = fn(q, k, v, causal=causal)
        sc = o.shape[2]
        w = _weights(o.shape, jax.lax.axis_index("context") * sc, s_total)
        # pmean: per-rank autodiff seeds one cotangent per rank, so the mean
        # yields exactly the global-sum gradients; value is ref/cp
        return jax.lax.pmean(jnp.sum(o * w), "context")

    grads = jax.jit(shard_map(
        jax.value_and_grad(attn_loss, argnums=(0, 1, 2)), mesh=mesh,
        in_specs=(P(None, None, "context"),) * 3,
        out_specs=(P(), (P(None, None, "context"),) * 3),
        check_vma=False))
    loss, (dq, dk, dv) = grads(q, k, v)
    out = jax.jit(shard_map(
        lambda q, k, v: fn(q, k, v, causal=causal), mesh=mesh,
        in_specs=(P(None, None, "context"),) * 3,
        out_specs=P(None, None, "context"),
        check_vma=False))(q, k, v)
    parallel_state.destroy_model_parallel()
    return out, loss, (dq, dk, dv)


def _reference(q, k, v, causal):
    def attn_loss(q, k, v):
        o = flash_attention(q, k, v, causal=causal)
        w = _weights(o.shape, 0, o.shape[2])
        return jnp.sum(o * w)

    out = flash_attention(q, k, v, causal=causal)
    loss, grads = jax.value_and_grad(attn_loss, argnums=(0, 1, 2))(q, k, v)
    return out, loss, grads


class TestRingAttention:
    @pytest.mark.slow  # cp=4 parity vs reference: slow-tier family (ROADMAP)
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv()
        out, loss, grads = _run_cp(ring_attention, q, k, v, cp=4,
                                   causal=causal)
        ref_out, ref_loss, ref_grads = _reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(float(loss) * 4, float(ref_loss),
                                   rtol=1e-5)
        for g, rg in zip(grads, ref_grads):
            np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                       rtol=1e-3, atol=1e-3)

    def test_degrades_to_flash_unsharded(self):
        q, k, v = _qkv()
        out = ring_attention(q, k, v, causal=True)
        ref = flash_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv()
        out, loss, grads = _run_cp(ulysses_attention, q, k, v, cp=4,
                                   causal=causal)
        ref_out, ref_loss, ref_grads = _reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(float(loss) * 4, float(ref_loss),
                                   rtol=1e-5)
        for g, rg in zip(grads, ref_grads):
            np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                       rtol=1e-3, atol=1e-3)

    def test_head_divisibility_check(self):
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=4)
        q, k, v = _qkv(h=2)  # 2 heads, cp=4 -> invalid
        with pytest.raises(ValueError, match="divisible"):
            jax.jit(shard_map(
                lambda q, k, v: ulysses_attention(q, k, v), mesh=mesh,
                in_specs=(P(None, None, "context"),) * 3,
                out_specs=P(None, None, "context"),
                check_vma=False))(q, k, v)
        parallel_state.destroy_model_parallel()


class TestGPTContextParallel:
    @pytest.mark.parametrize("method", ["ring", "ulysses"])
    def test_loss_matches_unsharded(self, method):
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=2)
        cfg = dict(num_layers=2, hidden_size=64, num_attention_heads=4,
                   vocab_size=128, max_position_embeddings=32,
                   hidden_dropout=0.0, attention_dropout=0.0)
        ref_model = GPTModel(TransformerConfig(**cfg))
        cp_model = GPTModel(TransformerConfig(
            **cfg, context_parallel_method=method))
        params = ref_model.init(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 128)
        labels = jax.random.randint(jax.random.PRNGKey(2), (4, 32), 0, 128)

        ref_loss = ref_model.apply(params, tokens, labels)

        def per_rank(p, tokens, labels):
            # local loss is the mean over this rank's positions; global mean
            # = pmean over equal-size shards
            loss = cp_model.apply(p, tokens, labels)
            return jax.lax.pmean(loss, "context")

        loss = jax.jit(shard_map(
            per_rank, mesh=mesh,
            in_specs=(ref_model.spec(), P(None, "context"),
                      P(None, "context")),
            out_specs=P(),
            check_vma=False))(params, tokens, labels)
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=2e-5, atol=2e-5)
        parallel_state.destroy_model_parallel()


class TestRingVarlenWindowGQA:
    """Flash-blockwise ring features that close the reference 16k cap
    (scaled_masked_softmax.h:460) with exact cross-chunk semantics."""

    def _ref_and_ring(self, q, k, v, cp=4, **kw):
        ref = flash_attention(q, k, v, causal=True, **kw)
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=cp)
        out = jax.jit(shard_map(
            lambda q, k, v: ring_attention(q, k, v, causal=True, **kw),
            mesh=mesh, in_specs=(P(None, None, "context"),) * 3,
            out_specs=P(None, None, "context"),
            check_vma=False))(q, k, v)
        parallel_state.destroy_model_parallel()
        return np.asarray(ref), np.asarray(out)

    def test_kv_lengths_global_across_chunks(self):
        q, k, v = _qkv(b=3, s=32)
        kvl = jnp.asarray([9, 32, 17], jnp.int32)   # crosses chunk bounds
        ref, out = self._ref_and_ring(q, k, v, kv_lengths=kvl)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_sliding_window_across_chunks(self):
        q, k, v = _qkv(s=32)
        # window 11 spans chunk boundaries at cp=4 (chunks of 8)
        ref, out = self._ref_and_ring(q, k, v, sliding_window=11)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_gqa_ring(self):
        q, _, _ = _qkv(h=4)
        _, k, v = _qkv(h=2, key=3)
        ref, out = self._ref_and_ring(q, k, v)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_window_grads_match(self):
        q, k, v = _qkv(s=32)

        def run(fn, sharded):
            def loss(q, k, v):
                o = fn(q, k, v)
                sc = o.shape[2]
                off = (jax.lax.axis_index("context") * sc if sharded else 0)
                w = _weights(o.shape, off, 32)
                l = jnp.sum(o * w)
                return jax.lax.pmean(l, "context") if sharded else l
            return jax.value_and_grad(loss, argnums=(0, 1, 2))

        ref_loss, ref_grads = run(
            lambda q, k, v: flash_attention(q, k, v, causal=True,
                                            sliding_window=11), False)(q, k, v)
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=4)
        loss, grads = jax.jit(shard_map(
            run(lambda q, k, v: ring_attention(q, k, v, causal=True,
                                               sliding_window=11), True),
            mesh=mesh, in_specs=(P(None, None, "context"),) * 3,
            out_specs=(P(), (P(None, None, "context"),) * 3),
            check_vma=False))(q, k, v)
        parallel_state.destroy_model_parallel()
        np.testing.assert_allclose(float(loss) * 4, float(ref_loss),
                                   rtol=1e-5)
        for g, rg in zip(grads, ref_grads):
            np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                       rtol=1e-3, atol=1e-3)


class TestRingMemory:
    """Ring attention's point: per-rank memory scales with the LOCAL chunk,
    not the global sequence. Compare compiled temp memory against gather-
    everything attention (all_gather K/V then full attention) at a long
    sequence on the virtual mesh."""

    @pytest.mark.slow  # memory-benchmark comparison: slow tier (ROADMAP)

    def test_ring_temp_memory_beats_allgather(self):
        # measured on the XLA fallback path (interpret-mode emulation
        # buffers would dominate): the contrast here is the DESIGN —
        # per-hop local-chunk math vs a gathered full sequence; the Pallas
        # block-memory bound is benchmarked on real TPU
        from apex_tpu.ops import _support

        prior = os.environ.get("APEX_TPU_FORCE_PALLAS")
        os.environ["APEX_TPU_FORCE_PALLAS"] = "off"
        _support.pallas_mode.cache_clear()
        b, h, s, d = 1, 2, 4096, 32
        q = jnp.zeros((b, h, s, d), jnp.bfloat16)
        k, v = q, q
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=8)

        def ring(q, k, v):
            return ring_attention(q, k, v, causal=True)

        def allgather(q, k, v):
            kg = jax.lax.all_gather(k, "context", axis=2, tiled=True)
            vg = jax.lax.all_gather(v, "context", axis=2, tiled=True)
            return flash_attention(q, kg, vg, causal=False)

        def temp(fn):
            f = jax.jit(shard_map(
                fn, mesh=mesh, in_specs=(P(None, None, "context"),) * 3,
                out_specs=P(None, None, "context"), check_vma=False))
            ma = f.lower(q, k, v).compile().memory_analysis()
            if ma is None:
                pytest.skip("no memory_analysis on this backend")
            return ma.temp_size_in_bytes

        try:
            ring_b, gather_b = temp(ring), temp(allgather)
        finally:
            if prior is None:
                os.environ.pop("APEX_TPU_FORCE_PALLAS", None)
            else:
                os.environ["APEX_TPU_FORCE_PALLAS"] = prior
            _support.pallas_mode.cache_clear()
            parallel_state.destroy_model_parallel()
        assert ring_b < gather_b / 2, (
            f"ring temp {ring_b}B not substantially below all-gather "
            f"{gather_b}B at s={s}, cp=8")
