"""Context-parallel (long-context) suite.

The reference has no CP (SURVEY.md §5: attention kernels cap at 16k and the
only sequence mechanism is Megatron SP), so the ground truth here is the
single-device flash/reference attention: ring and Ulysses attention over a
sharded sequence must reproduce it — forward and gradients — and the GPT
model must train identically with the sequence split over the ``context``
mesh axis.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

os.environ.setdefault("APEX_TPU_FORCE_PALLAS", "interpret")

from apex_tpu.models import GPTModel, TransformerConfig  # noqa: E402
from apex_tpu.ops import flash_attention, ring_attention, ulysses_attention  # noqa: E402
from apex_tpu.transformer import parallel_state  # noqa: E402


def _qkv(b=2, h=4, s=32, d=16, key=0):
    ks = jax.random.split(jax.random.PRNGKey(key), 3)
    shape = (b, h, s, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


def _weights(shape, s_offset, s_total):
    """Position-dependent weights consistent between global and sharded
    layouts: w[b,h,s,d] = flat index in the GLOBAL [b,h,s_total,d] array."""
    b, h, sc, d = shape
    bi = jnp.arange(b).reshape(b, 1, 1, 1)
    hi = jnp.arange(h).reshape(1, h, 1, 1)
    si = jnp.arange(sc).reshape(1, 1, sc, 1) + s_offset
    di = jnp.arange(d).reshape(1, 1, 1, d)
    return (((bi * h + hi) * s_total + si) * d + di).astype(jnp.float32)


def _run_cp(fn, q, k, v, cp, causal):
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(context_parallel_size=cp)
    s_total = q.shape[2]

    def attn_loss(q, k, v):
        o = fn(q, k, v, causal=causal)
        sc = o.shape[2]
        w = _weights(o.shape, jax.lax.axis_index("context") * sc, s_total)
        # pmean: per-rank autodiff seeds one cotangent per rank, so the mean
        # yields exactly the global-sum gradients; value is ref/cp
        return jax.lax.pmean(jnp.sum(o * w), "context")

    grads = jax.jit(jax.shard_map(
        jax.value_and_grad(attn_loss, argnums=(0, 1, 2)), mesh=mesh,
        in_specs=(P(None, None, "context"),) * 3,
        out_specs=(P(), (P(None, None, "context"),) * 3),
        check_vma=False))
    loss, (dq, dk, dv) = grads(q, k, v)
    out = jax.jit(jax.shard_map(
        lambda q, k, v: fn(q, k, v, causal=causal), mesh=mesh,
        in_specs=(P(None, None, "context"),) * 3,
        out_specs=P(None, None, "context"),
        check_vma=False))(q, k, v)
    parallel_state.destroy_model_parallel()
    return out, loss, (dq, dk, dv)


def _reference(q, k, v, causal):
    def attn_loss(q, k, v):
        o = flash_attention(q, k, v, causal=causal)
        w = _weights(o.shape, 0, o.shape[2])
        return jnp.sum(o * w)

    out = flash_attention(q, k, v, causal=causal)
    loss, grads = jax.value_and_grad(attn_loss, argnums=(0, 1, 2))(q, k, v)
    return out, loss, grads


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv()
        out, loss, grads = _run_cp(ring_attention, q, k, v, cp=4,
                                   causal=causal)
        ref_out, ref_loss, ref_grads = _reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(float(loss) * 4, float(ref_loss),
                                   rtol=1e-5)
        for g, rg in zip(grads, ref_grads):
            np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                       rtol=1e-3, atol=1e-3)

    def test_degrades_to_flash_unsharded(self):
        q, k, v = _qkv()
        out = ring_attention(q, k, v, causal=True)
        ref = flash_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv()
        out, loss, grads = _run_cp(ulysses_attention, q, k, v, cp=4,
                                   causal=causal)
        ref_out, ref_loss, ref_grads = _reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(float(loss) * 4, float(ref_loss),
                                   rtol=1e-5)
        for g, rg in zip(grads, ref_grads):
            np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                       rtol=1e-3, atol=1e-3)

    def test_head_divisibility_check(self):
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=4)
        q, k, v = _qkv(h=2)  # 2 heads, cp=4 -> invalid
        with pytest.raises(ValueError, match="divisible"):
            jax.jit(jax.shard_map(
                lambda q, k, v: ulysses_attention(q, k, v), mesh=mesh,
                in_specs=(P(None, None, "context"),) * 3,
                out_specs=P(None, None, "context"),
                check_vma=False))(q, k, v)
        parallel_state.destroy_model_parallel()


class TestGPTContextParallel:
    @pytest.mark.parametrize("method", ["ring", "ulysses"])
    def test_loss_matches_unsharded(self, method):
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=2)
        cfg = dict(num_layers=2, hidden_size=64, num_attention_heads=4,
                   vocab_size=128, max_position_embeddings=32,
                   hidden_dropout=0.0, attention_dropout=0.0)
        ref_model = GPTModel(TransformerConfig(**cfg))
        cp_model = GPTModel(TransformerConfig(
            **cfg, context_parallel_method=method))
        params = ref_model.init(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 128)
        labels = jax.random.randint(jax.random.PRNGKey(2), (4, 32), 0, 128)

        ref_loss = ref_model.apply(params, tokens, labels)

        def per_rank(p, tokens, labels):
            # local loss is the mean over this rank's positions; global mean
            # = pmean over equal-size shards
            loss = cp_model.apply(p, tokens, labels)
            return jax.lax.pmean(loss, "context")

        loss = jax.jit(jax.shard_map(
            per_rank, mesh=mesh,
            in_specs=(ref_model.spec(), P(None, "context"),
                      P(None, "context")),
            out_specs=P(),
            check_vma=False))(params, tokens, labels)
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=2e-5, atol=2e-5)
        parallel_state.destroy_model_parallel()
