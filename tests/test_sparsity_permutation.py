"""Channel-permutation search for 2:4 sparsity
(``apex/contrib/sparsity/permutation_lib.py`` capability)."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.contrib.sparsity import (
    compute_sparse_mask_2to4,
    invert_permutation,
    mask_efficacy,
    permute_columns,
    search_for_good_permutation,
)


def test_efficacy_bounds():
    w = jax.random.normal(jax.random.PRNGKey(0), (16, 16))
    e = float(mask_efficacy(w))
    assert 0.5 < e <= 1.0   # 2-of-4 keeps at least half the magnitude


def test_search_improves_adversarial_layout():
    """Columns arranged so big weights collide inside groups; the search
    must spread them and strictly raise efficacy."""
    rng = np.random.RandomState(1)
    w = rng.randn(32, 16).astype(np.float32) * 0.01
    w[:, :4] += rng.randn(32, 4) * 10.0    # 4 dominant columns in one group
    w = jnp.asarray(w)
    before = float(mask_efficacy(w))
    perm = search_for_good_permutation(w)
    after = float(mask_efficacy(permute_columns(w, perm)))
    assert after > before + 0.05
    assert sorted(perm.tolist()) == list(range(16))   # is a permutation


def test_identity_when_already_optimal():
    # one dominant column per group: nothing to gain
    w = np.full((8, 8), 0.01, np.float32)
    w[:, [0, 4]] = 5.0
    perm = search_for_good_permutation(jnp.asarray(w))
    np.testing.assert_array_equal(perm, np.arange(8))


def test_inverse_permutation_roundtrip():
    perm = search_for_good_permutation(
        jax.random.normal(jax.random.PRNGKey(2), (8, 12)))
    inv = invert_permutation(perm)
    np.testing.assert_array_equal(perm[inv], np.arange(12))
    np.testing.assert_array_equal(inv[perm], np.arange(12))


def test_composed_network_unchanged():
    """Permuting layer2's input channels + the same perm on layer1's output
    rows leaves the composed function identical (cross-layer propagation
    contract)."""
    k1, k2, kx = jax.random.split(jax.random.PRNGKey(3), 3)
    w1 = jax.random.normal(k1, (12, 6))    # [out=12, in=6]
    w2 = jax.random.normal(k2, (5, 12))    # [out=5, in=12]
    x = jax.random.normal(kx, (6,))
    perm = search_for_good_permutation(w2)
    w2p = permute_columns(w2, perm)
    w1p = w1[jnp.asarray(perm), :]         # permute producer's output rows
    y_ref = w2 @ (w1 @ x)
    y_new = w2p @ (w1p @ x)
    np.testing.assert_allclose(np.asarray(y_new), np.asarray(y_ref),
                               rtol=2e-5, atol=2e-5)


def test_mask_after_permutation_keeps_more():
    rng = np.random.RandomState(4)
    w = rng.randn(64, 32).astype(np.float32)
    w[:, ::4] *= 8.0
    w[:, 1::4] *= 8.0
    w = jnp.asarray(w)   # two dominant columns per group: 2:4 already ideal
    perm = search_for_good_permutation(w)
    masked = permute_columns(w, perm) * compute_sparse_mask_2to4(
        permute_columns(w, perm))
    kept = float(jnp.sum(jnp.abs(masked)) / jnp.sum(jnp.abs(w)))
    assert kept >= float(mask_efficacy(w)) - 1e-6
