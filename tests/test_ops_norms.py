"""Fused LayerNorm/RMSNorm parity (tier-L0 analog of
``tests/L0/run_fused_layer_norm``): values and grads vs pure-jnp references,
plus kernel validation in Pallas interpreter mode."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import (
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
)
from apex_tpu.ops import _support


def ref_layer_norm(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    if w is not None:
        y = y * w
    if b is not None:
        y = y + b
    return y


def ref_rms_norm(x, w, eps):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(ms + eps)
    if w is not None:
        y = y * w
    return y


@pytest.fixture(params=[(4, 8, 96), (2, 384)])
def shapes(request):
    return request.param


def test_layer_norm_affine_fwd_bwd(shapes):
    key = jax.random.PRNGKey(0)
    h = shapes[-1]
    x = jax.random.normal(key, shapes, jnp.float32) * 2 + 1
    w = jax.random.normal(jax.random.PRNGKey(1), (h,), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(2), (h,), jnp.float32)

    y = fused_layer_norm_affine(x, w, b, h, 1e-5)
    np.testing.assert_allclose(y, ref_layer_norm(x, w, b, 1e-5), atol=1e-5)

    def loss_fused(x, w, b):
        return jnp.sum(jnp.sin(fused_layer_norm_affine(x, w, b, h, 1e-5)))

    def loss_ref(x, w, b):
        return jnp.sum(jnp.sin(ref_layer_norm(x, w, b, 1e-5)))

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2))(x, w, b)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
    for gf, gr in zip(g_fused, g_ref):
        np.testing.assert_allclose(gf, gr, atol=2e-4, rtol=1e-3)


def test_layer_norm_no_affine(shapes):
    h = shapes[-1]
    x = jax.random.normal(jax.random.PRNGKey(0), shapes, jnp.float32)
    y = fused_layer_norm(x, h)
    np.testing.assert_allclose(y, ref_layer_norm(x, None, None, 1e-5), atol=1e-5)
    gf = jax.grad(lambda x: jnp.sum(fused_layer_norm(x, h) ** 2))(x)
    gr = jax.grad(lambda x: jnp.sum(ref_layer_norm(x, None, None, 1e-5) ** 2))(x)
    np.testing.assert_allclose(gf, gr, atol=2e-4, rtol=1e-3)


def test_rms_norm(shapes):
    h = shapes[-1]
    x = jax.random.normal(jax.random.PRNGKey(0), shapes, jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (h,), jnp.float32) + 1.0
    y = fused_rms_norm_affine(x, w, h, 1e-6)
    np.testing.assert_allclose(y, ref_rms_norm(x, w, 1e-6), atol=3e-5)
    g_fused = jax.grad(
        lambda x, w: jnp.sum(jnp.cos(fused_rms_norm_affine(x, w, h, 1e-6))),
        argnums=(0, 1))(x, w)
    g_ref = jax.grad(
        lambda x, w: jnp.sum(jnp.cos(ref_rms_norm(x, w, 1e-6))),
        argnums=(0, 1))(x, w)
    for gf, gr in zip(g_fused, g_ref):
        np.testing.assert_allclose(gf, gr, atol=2e-4, rtol=1e-3)
    yn = fused_rms_norm(x, h)
    np.testing.assert_allclose(yn, ref_rms_norm(x, None, 1e-6), atol=3e-5)


def test_memory_efficient_matches():
    h = 64
    x = jax.random.normal(jax.random.PRNGKey(0), (8, h), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (h,), jnp.float32) + 1.0
    b = jax.random.normal(jax.random.PRNGKey(2), (h,), jnp.float32)

    def g(me):
        return jax.grad(
            lambda x, w, b: jnp.sum(
                fused_layer_norm_affine(x, w, b, h, 1e-5, memory_efficient=me) ** 2),
            argnums=(0, 1, 2))(x, w, b)

    for a, bb in zip(g(False), g(True)):
        np.testing.assert_allclose(a, bb, atol=1e-3, rtol=1e-3)


def test_bf16_io():
    h = 128
    x = jax.random.normal(jax.random.PRNGKey(0), (16, h), jnp.bfloat16)
    w = jnp.ones((h,), jnp.bfloat16)
    b = jnp.zeros((h,), jnp.bfloat16)
    y = fused_layer_norm_affine(x, w, b, h)
    assert y.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(y, np.float32),
        np.asarray(ref_layer_norm(x, w, b, 1e-5), np.float32), atol=0.05)


def test_pallas_interpret_kernel(monkeypatch):
    """Validate the actual Pallas kernel logic via interpreter mode."""
    monkeypatch.setenv("APEX_TPU_FORCE_PALLAS", "interpret")
    _support.pallas_mode.cache_clear()
    try:
        h = 96  # exercises padding to 128
        x = jax.random.normal(jax.random.PRNGKey(0), (16, h), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (h,), jnp.float32) + 1.0
        b = jax.random.normal(jax.random.PRNGKey(2), (h,), jnp.float32)
        y = fused_layer_norm_affine(x, w, b, h, 1e-5)
        np.testing.assert_allclose(y, ref_layer_norm(x, w, b, 1e-5), atol=1e-5)
        g_fused = jax.grad(
            lambda x, w, b: jnp.sum(fused_layer_norm_affine(x, w, b, h, 1e-5) ** 2),
            argnums=(0, 1, 2))(x, w, b)
        g_ref = jax.grad(
            lambda x, w, b: jnp.sum(ref_layer_norm(x, w, b, 1e-5) ** 2),
            argnums=(0, 1, 2))(x, w, b)
        for gf, gr in zip(g_fused, g_ref):
            np.testing.assert_allclose(gf, gr, atol=2e-4, rtol=1e-3)
        # rms norm kernel path too
        yr = fused_rms_norm_affine(x, w, h, 1e-6)
        np.testing.assert_allclose(yr, ref_rms_norm(x, w, 1e-6), atol=1e-5)
    finally:
        _support.pallas_mode.cache_clear()


def test_pallas_interpret_multiblock_grid(monkeypatch):
    """m > block_rows forces grid > 1, exercising the dw/db revisited-block
    accumulator and the tail-row masking (a past TPU bug lived here)."""
    monkeypatch.setenv("APEX_TPU_FORCE_PALLAS", "interpret")
    _support.pallas_mode.cache_clear()
    try:
        h = 96
        m = 600  # bm=256 -> grid=(3,), last block partially filled
        x = jax.random.normal(jax.random.PRNGKey(0), (m, h), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (h,), jnp.float32) + 1.0
        b = jax.random.normal(jax.random.PRNGKey(2), (h,), jnp.float32)
        g_fused = jax.grad(
            lambda x, w, b: jnp.sum(fused_layer_norm_affine(x, w, b, h, 1e-5) ** 2),
            argnums=(0, 1, 2))(x, w, b)
        g_ref = jax.grad(
            lambda x, w, b: jnp.sum(ref_layer_norm(x, w, b, 1e-5) ** 2),
            argnums=(0, 1, 2))(x, w, b)
        for gf, gr in zip(g_fused, g_ref):
            np.testing.assert_allclose(gf, gr, atol=1e-3, rtol=1e-3)
    finally:
        _support.pallas_mode.cache_clear()
