"""fp16_utils tier — mirrors the reference's ``tests/L0/run_fp16util``
(``test_fp16util.py``: prep_param_lists / master↔model copies) plus
``FP16_Optimizer`` step/overflow flow from ``run_deprecated``.
"""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.fp16_utils import (
    DynamicLossScaler,
    FP16_Optimizer,
    LossScaler,
    convert_network,
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
)
from apex_tpu.optimizers import FusedAdam, FusedSGD


def _params():
    return {
        "dense": {"kernel": jnp.ones((4, 3)), "bias": jnp.zeros((3,))},
        "batchnorm": {"scale": jnp.ones((3,)), "bias": jnp.zeros((3,))},
        "step": jnp.asarray(0, jnp.int32),          # non-float leaf
    }


class TestConvertNetwork:
    def test_network_to_half_casts_all_floats(self):
        half = network_to_half(_params(), jnp.bfloat16)
        assert half["dense"]["kernel"].dtype == jnp.bfloat16
        assert half["batchnorm"]["scale"].dtype == jnp.bfloat16

    def test_convert_network_keeps_norms_fp32(self):
        """BN_convert_float capability (fp16util.py:60-71): norm-named
        leaves stay fp32, everything else halves, ints untouched."""
        half = convert_network(_params(), jnp.bfloat16)
        assert half["dense"]["kernel"].dtype == jnp.bfloat16
        assert half["batchnorm"]["scale"].dtype == jnp.float32
        assert half["batchnorm"]["bias"].dtype == jnp.float32
        assert half["step"].dtype == jnp.int32

    def test_convert_network_custom_predicate(self):
        half = convert_network(_params(), jnp.bfloat16, keep_fp32=None)
        assert half["batchnorm"]["scale"].dtype == jnp.bfloat16


class TestMasterModelCopies:
    def test_prep_param_lists(self):
        model = network_to_half(_params(), jnp.bfloat16)
        model_out, master = prep_param_lists(model)
        assert model_out is model
        assert master["dense"]["kernel"].dtype == jnp.float32

    def test_grads_to_master_and_back(self):
        model = network_to_half({"w": jnp.ones((4,))}, jnp.bfloat16)
        grads = jax.tree.map(lambda p: jnp.full_like(p, 0.5), model)
        master_grads = model_grads_to_master_grads(grads)
        assert master_grads["w"].dtype == jnp.float32
        # master update then copy back preserves model dtype
        _, master = prep_param_lists(model)
        master = jax.tree.map(lambda m, g: m - 0.1 * g, master, master_grads)
        model2 = master_params_to_model_params(master, model)
        assert model2["w"].dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(model2["w"], np.float32), 0.95, rtol=1e-2)


class TestFP16Optimizer:
    def test_step_matches_inner_on_fp32(self):
        """With scale 1.0 and fp32 params the wrapper must reproduce the
        inner optimizer exactly (fp16_optimizer.py step path)."""
        p = {"w": jnp.linspace(0.1, 1.0, 8)}
        g = {"w": jnp.full((8,), 0.25)}
        inner = FusedSGD(lr=0.1, momentum=0.9)
        wrapped = FP16_Optimizer(FusedSGD(lr=0.1, momentum=0.9))
        st = wrapped.init(p)
        p_ref, _ = inner.step(g, p, inner.init(p))
        p_new, _ = wrapped.step(g, st, p)
        np.testing.assert_allclose(p_new["w"], p_ref["w"], rtol=1e-6)

    def test_half_params_master_flow(self):
        p = network_to_half({"w": jnp.ones((8,))}, jnp.bfloat16)
        opt = FP16_Optimizer(FusedAdam(lr=0.01), static_loss_scale=128.0)
        st = opt.init(p)
        loss_scale = opt.scale_loss(jnp.asarray(1.0), st)
        assert float(loss_scale) == 128.0
        grads = {"w": (jnp.ones((8,)) * 128.0).astype(jnp.bfloat16)}  # scaled
        p2, st2 = opt.step(grads, st, p)
        assert p2["w"].dtype == jnp.bfloat16
        # master moved by ~lr in the right direction (unscaled grad == 1)
        assert float(st2.master_params["w"][0]) < 1.0

    def test_dynamic_overflow_skips_and_backs_off(self):
        p = {"w": jnp.ones((4,), jnp.bfloat16)}
        opt = FP16_Optimizer(FusedSGD(lr=0.5), dynamic_loss_scale=True,
                             dynamic_loss_args={"init_scale": 2.0 ** 10})
        st = opt.init(p)
        bad = {"w": jnp.full((4,), jnp.inf, jnp.bfloat16)}
        p2, st2 = opt.step(bad, st, p)
        np.testing.assert_allclose(np.asarray(p2["w"], np.float32), 1.0)
        assert float(st2.scaler_state.loss_scale) == 2.0 ** 9

    def test_jittable(self):
        p = {"w": jnp.ones((4,), jnp.bfloat16)}
        opt = FP16_Optimizer(FusedSGD(lr=0.1), dynamic_loss_scale=True)
        st = opt.init(p)

        @jax.jit
        def step(g, st, p):
            return opt.step(g, st, p)

        # step() expects *scaled* grads (unscaled grad == 0.5 here)
        scale = float(st.scaler_state.loss_scale)
        g = {"w": jnp.full((4,), 0.5 * scale, jnp.bfloat16)}
        p2, st2 = step(g, st, p)
        assert not np.allclose(np.asarray(p2["w"], np.float32), 1.0)


class TestLegacyScalers:
    def test_static_alias(self):
        sc = LossScaler(64.0)
        st = sc.init()
        assert float(st.loss_scale) == 64.0
        st2 = sc.update(st, jnp.asarray(True))
        assert float(st2.loss_scale) == 64.0     # static: never changes

    def test_dynamic_alias_window(self):
        sc = DynamicLossScaler(init_scale=2.0 ** 8, scale_window=2)
        st = sc.init()
        for _ in range(2):
            st = sc.update(st, jnp.asarray(False))
        assert float(st.loss_scale) == 2.0 ** 9   # grew after window
        st = sc.update(st, jnp.asarray(True))
        assert float(st.loss_scale) == 2.0 ** 8   # backed off
