"""GQA/MQA through the model stack (``TransformerConfig.num_query_groups``).

Exceeds the reference (which is MHA-only). Anchors:
- fused-QKV param shape uses the grouped layout;
- training step runs with finite loss/grads;
- cached decode logits match the full forward (the KV cache holds
  ``num_query_groups`` heads, so this exercises the grouped cache);
- TP=2 sharded forward matches the unsharded one (whole K/V groups per
  rank via the grouped QKV layout).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.models.generation import decode_step, init_kv_caches
from apex_tpu.utils.sharding import shard_map


def _cfg(**kw):
    d = dict(num_layers=2, hidden_size=64, num_attention_heads=8,
             num_query_groups=2, vocab_size=64, max_position_embeddings=32,
             hidden_dropout=0.0, attention_dropout=0.0)
    d.update(kw)
    return TransformerConfig(**d)


def test_qkv_param_shape_grouped():
    model = GPTModel(_cfg())
    params = model.init(jax.random.PRNGKey(0))
    qkv = params["transformer"]["layers"]["self_attention"][
        "query_key_value"]["weight"]
    # [layers, kv_heads * (q_per_group + 2) * head_dim, hidden] (out, in)
    dh = 64 // 8
    assert qkv.shape == (2, 2 * (4 + 2) * dh, 64)


def test_train_step_finite():
    model = GPTModel(_cfg())
    params = model.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
    labels = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, 64)
    loss, grads = jax.jit(jax.value_and_grad(
        lambda p: model.apply(p, tokens, labels)))(params)
    assert np.isfinite(float(loss))
    assert all(np.isfinite(np.asarray(g, np.float32)).all()
               for g in jax.tree.leaves(grads))


def test_mqa_single_group():
    model = GPTModel(_cfg(num_query_groups=1))
    params = model.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, 64)
    logits = model.apply(params, tokens)
    assert logits.shape == (8, 1, 64)


def test_invalid_groups_rejected():
    with pytest.raises(Exception):
        GPTModel(_cfg(num_query_groups=3)).init(jax.random.PRNGKey(0))


@pytest.mark.slow  # generation cache parity: the slow-tier class
def test_cached_decode_matches_full_forward():
    model = GPTModel(_cfg())
    params = model.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 64)
    full = model.apply(params, tokens)
    caches = init_kv_caches(model, 2, 16)
    assert caches[0].shape[2] == 2        # kv heads, not query heads
    for i in range(10):
        logits, caches = decode_step(model, params, caches, tokens[:, i], i)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full[i]).astype(np.float32),
            rtol=2e-4, atol=2e-4)


def _train(tp, steps=3):
    from jax.sharding import PartitionSpec as P

    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=tp)
    model = GPTModel(_cfg(num_query_groups=4))   # 4 groups / tp=2 -> 2/rank
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-3)
    opt_state = opt.init(params)

    def loss_fn(p, batch, rng):
        return model.apply(p, batch["tokens"], batch["labels"], rng=rng)

    step = make_train_step(loss_fn, opt, mesh, model.spec(),
                           {"tokens": P("data"), "labels": P("data")},
                           params_template=params)
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    labels = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0, 64)
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state,
                                       {"tokens": toks, "labels": labels},
                                       jax.random.PRNGKey(3))
        losses.append(float(loss))
    parallel_state.destroy_model_parallel()
    return losses, params


@pytest.mark.slow  # TP model parity: the slow-tier class (ROADMAP tiers)
def test_tp2_matches_unsharded():
    """Sharded GQA training reproduces the single-rank run: the grouped QKV
    layout keeps whole K/V groups per TP rank."""
    ref_losses, ref_params = _train(tp=1)
    tp_losses, tp_params = _train(tp=2)
    np.testing.assert_allclose(ref_losses, tp_losses, atol=2e-5, rtol=2e-5)
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(tp_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_tp_exceeding_groups_fails_fast():
    """MQA (1 group) with tp=2 must raise a clear config error, not emit a
    zero-head cache or an opaque reshape failure."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2)
    try:
        model = GPTModel(_cfg(num_query_groups=1))
        params = model.init(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)
        with pytest.raises(ValueError, match="divisible"):
            jax.jit(shard_map(
                lambda p, t: model.apply(p, t), mesh=mesh,
                in_specs=(model.spec(), jax.sharding.PartitionSpec()),
                out_specs=jax.sharding.PartitionSpec(),
                check_vma=False))(params, tokens)
        with pytest.raises(ValueError, match="divisible"):
            jax.jit(shard_map(
                lambda: init_kv_caches(model, 2, 16), mesh=mesh,
                in_specs=(), out_specs=jax.sharding.PartitionSpec(),
                check_vma=False))()
    finally:
        parallel_state.destroy_model_parallel()
