"""FusedScaleMaskSoftmax dispatcher + fused RoPE wrapper tests.

Mirrors ``/root/reference/tests/L0/run_transformer/test_fused_softmax.py``
(fused vs torch-path parity for causal and padding mask types) and
``test_fused_rope.py``.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

os.environ.setdefault("APEX_TPU_FORCE_PALLAS", "interpret")

from apex_tpu.transformer import AttnMaskType  # noqa: E402
from apex_tpu.transformer.functional import (  # noqa: E402
    FusedScaleMaskSoftmax,
    fused_apply_rotary_pos_emb,
    fused_apply_rotary_pos_emb_cached,
)


def _rand(shape, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(shape), dtype)


@pytest.mark.parametrize("mask_type", [AttnMaskType.padding, AttnMaskType.causal])
@pytest.mark.parametrize("scale", [None, 2.0])
def test_fused_vs_unfused(mask_type, scale):
    x = _rand((2, 4, 32, 32), seed=1)
    mask = None
    if mask_type == AttnMaskType.padding:
        rng = np.random.default_rng(2)
        mask = jnp.asarray(rng.random((2, 1, 32, 32)) < 0.3)
    fused = FusedScaleMaskSoftmax(
        attn_mask_type=mask_type, scaled_masked_softmax_fusion=True,
        scale=scale)
    unfused = FusedScaleMaskSoftmax(
        attn_mask_type=mask_type, scaled_masked_softmax_fusion=False,
        scale=scale)
    np.testing.assert_allclose(np.asarray(fused(x, mask)),
                               np.asarray(unfused(x, mask)),
                               atol=1e-5, rtol=1e-5)


def test_scale_requires_fp32_softmax():
    with pytest.raises(RuntimeError):
        FusedScaleMaskSoftmax(softmax_in_fp32=False, scale=2.0)


def test_both_dtype_flags_rejected():
    with pytest.raises(RuntimeError):
        FusedScaleMaskSoftmax(input_in_fp16=True, input_in_bf16=True)


def test_rope_grad_is_inverse_rotation():
    s, b, h, d = 16, 2, 3, 32
    t = _rand((s, b, h, d), seed=3)
    inv_freq = 1.0 / (10000 ** (np.arange(0, d, 2) / d))
    pos = np.arange(s)
    f = np.einsum("s,d->sd", pos, inv_freq)
    freqs = jnp.asarray(np.concatenate([f, f], axis=-1)[:, None, None, :],
                        jnp.float32)

    out = fused_apply_rotary_pos_emb(t, freqs)
    # rotations are orthonormal: ||rope(t)|| == ||t|| per (s, position) pair
    np.testing.assert_allclose(
        np.asarray(jnp.linalg.norm(out, axis=-1)),
        np.asarray(jnp.linalg.norm(t, axis=-1)), atol=1e-5, rtol=1e-5)

    cached = fused_apply_rotary_pos_emb_cached(
        t, jnp.cos(freqs), jnp.sin(freqs))
    np.testing.assert_allclose(np.asarray(out), np.asarray(cached),
                               atol=1e-6, rtol=1e-6)

    # grad of sum(rope(t)) == rope^{-1}(ones): orthogonality check via vjp
    g = jax.grad(lambda t: jnp.sum(fused_apply_rotary_pos_emb(t, freqs)))(t)
    _, vjp = jax.vjp(lambda t: fused_apply_rotary_pos_emb(t, freqs), t)
    (g2,) = vjp(jnp.ones_like(t))
    np.testing.assert_allclose(np.asarray(g), np.asarray(g2), atol=1e-6)


def test_causal_with_explicit_mask_honors_both():
    """Regression: the fused causal kernel takes no mask — an explicit mask
    under causal mask-type must route to the unfused path and apply BOTH
    constraints (sliding-window/varlen/cache masks were silently dropped
    when sq == sk)."""
    import numpy as np

    from apex_tpu.transformer.enums import AttnMaskType
    from apex_tpu.transformer.functional import FusedScaleMaskSoftmax

    sm = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.causal,
                               scaled_masked_softmax_fusion=True,
                               softmax_in_fp32=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 1, 8, 8))
    # mask out everything except the diagonal (True = masked)
    mask = ~jnp.eye(8, dtype=bool)[None, None]
    probs = sm(x, mask)
    # only the self position survives both causal and the mask
    np.testing.assert_allclose(np.asarray(probs[0, 0]), np.eye(8),
                               atol=1e-5)
    # without a mask the fused causal branch still runs (row sums 1, upper
    # triangle zero)
    p2 = sm(x, None)
    np.testing.assert_allclose(np.asarray(jnp.sum(p2, -1)[0, 0]), 1.0,
                               atol=1e-5)
    assert float(p2[0, 0, 0, 1]) == 0.0
