"""Flash attention kernel parity tests.

Mirrors the reference's attention test strategy
(``apex/contrib/test/fmha/test_fmha.py``: fused kernel vs a pure-python
reference over padded varlen batches; ``apex/contrib/test/multihead_attn``:
fused vs unfused module outputs/grads).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

os.environ.setdefault("APEX_TPU_FORCE_PALLAS", "interpret")

from apex_tpu.ops.attention import _mha_reference, flash_attention  # noqa: E402


def _rand(shape, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(shape), dtype)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sq,sk", [(64, 64), (100, 300), (257, 257)])
def test_forward_matches_reference(causal, sq, sk):
    q = _rand((2, 3, sq, 64), seed=1)
    k = _rand((2, 3, sk, 64), seed=2)
    v = _rand((2, 3, sk, 64), seed=3)
    out = flash_attention(q, k, v, causal=causal)
    ref = _mha_reference(q, k, v, None, 1.0 / np.sqrt(64), causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_varlen_kv_lengths(causal):
    # second batch element's valid length (37) is below the k-block size, so
    # whole k-blocks are fully masked — the fmha padded-batch case
    # (apex/contrib/fmha/fmha.py:41-56)
    q = _rand((2, 2, 96, 64), seed=1)
    k = _rand((2, 2, 300, 64), seed=2)
    v = _rand((2, 2, 300, 64), seed=3)
    lens = jnp.asarray([300, 37], jnp.int32)
    out = flash_attention(q, k, v, causal=causal, kv_lengths=lens)
    ref = _mha_reference(q, k, v, lens, 1.0 / np.sqrt(64), causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("lens", [None, (300, 37)])
def test_backward_matches_reference(causal, lens):
    q = _rand((2, 2, 96, 64), seed=4)
    k = _rand((2, 2, 300, 64), seed=5)
    v = _rand((2, 2, 300, 64), seed=6)
    kvl = None if lens is None else jnp.asarray(lens, jnp.int32)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, kv_lengths=kvl)
        return jnp.sum(o.astype(jnp.float32) * jnp.cos(o.astype(jnp.float32)))

    def loss_ref(q, k, v):
        o = _mha_reference(q, k, v, kvl, 1.0 / np.sqrt(64), causal)
        return jnp.sum(o.astype(jnp.float32) * jnp.cos(o.astype(jnp.float32)))

    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_cross_attention_offset():
    # sq != sk causal: the last q row attends to everything, row 0 attends to
    # the first sk - sq + 1 keys (the standard offset convention)
    q = _rand((1, 1, 4, 64), seed=7)
    k = _rand((1, 1, 10, 64), seed=8)
    v = _rand((1, 1, 10, 64), seed=9)
    out = flash_attention(q, k, v, causal=True)
    ref = _mha_reference(q, k, v, None, 1.0 / np.sqrt(64), True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_jit_and_scale():
    q = _rand((1, 2, 128, 32), seed=1)
    k = _rand((1, 2, 128, 32), seed=2)
    v = _rand((1, 2, 128, 32), seed=3)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, softmax_scale=0.5))
    out = f(q, k, v)
    ref = _mha_reference(q, k, v, None, 0.5, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


class TestGQA:
    """Grouped-query / multi-query attention (kv_heads divides heads): the
    kernel reads shared K/V blocks per group — parity vs the broadcast
    reference, forward and backward."""

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("kv_heads", [1, 2])   # MQA and GQA
    def test_forward(self, causal, kv_heads):
        q = _rand((2, 4, 96, 64), seed=11)
        k = _rand((2, kv_heads, 160, 64), seed=12)
        v = _rand((2, kv_heads, 160, 64), seed=13)
        out = flash_attention(q, k, v, causal=causal)
        ref = _mha_reference(q, k, v, None, 1.0 / np.sqrt(64), causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_backward(self, causal):
        q = _rand((2, 4, 64, 64), seed=14)
        k = _rand((2, 2, 128, 64), seed=15)
        v = _rand((2, 2, 128, 64), seed=16)

        def loss(fn):
            def inner(q, k, v):
                o = fn(q, k, v)
                return jnp.sum(o * jnp.sin(o))
            return inner

        g = jax.grad(loss(lambda q, k, v: flash_attention(
            q, k, v, causal=causal)), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss(lambda q, k, v: _mha_reference(
            q, k, v, None, 1.0 / np.sqrt(64), causal)),
            argnums=(0, 1, 2))(q, k, v)
        assert g[1].shape == k.shape     # dk has kv_heads, not heads
        for a, b in zip(g, gr):
            # atol 2e-4: on real TPU a handful of elements differ at ~1e-4
            # from fp32 accumulation ORDER (block-wise vs full-row sums),
            # even at highest matmul precision
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=5e-5)

    def test_varlen_gqa(self):
        q = _rand((2, 4, 64, 64), seed=17)
        k = _rand((2, 1, 200, 64), seed=18)
        v = _rand((2, 1, 200, 64), seed=19)
        lens = jnp.asarray([200, 23], jnp.int32)
        out = flash_attention(q, k, v, kv_lengths=lens)
        ref = _mha_reference(q, k, v, lens, 1.0 / np.sqrt(64), False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_indivisible_heads_rejected(self):
        q = _rand((1, 3, 32, 64))
        k = _rand((1, 2, 32, 64))
        with pytest.raises(ValueError, match="divide"):
            flash_attention(q, k, k)


class TestSlidingWindow:
    """Mistral-class local attention: keep the last ``window`` keys per
    query; far-past K blocks are skipped in the kernel."""

    @pytest.mark.parametrize("window", [1, 16, 100, 1000])
    def test_forward(self, window):
        q = _rand((2, 2, 300, 64), seed=21)
        k = _rand((2, 2, 300, 64), seed=22)
        v = _rand((2, 2, 300, 64), seed=23)
        out = flash_attention(q, k, v, causal=True, sliding_window=window)
        ref = _mha_reference(q, k, v, None, 1.0 / np.sqrt(64), True, window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_backward(self):
        q = _rand((1, 2, 160, 64), seed=24)
        k = _rand((1, 2, 160, 64), seed=25)
        v = _rand((1, 2, 160, 64), seed=26)

        def loss(fn):
            return lambda q, k, v: jnp.sum(jnp.sin(fn(q, k, v)))

        g = jax.grad(loss(lambda q, k, v: flash_attention(
            q, k, v, causal=True, sliding_window=48)),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss(lambda q, k, v: _mha_reference(
            q, k, v, None, 1.0 / np.sqrt(64), True, 48)),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            # atol 2e-4: TPU fp32 accumulation-order noise (see TestGQA)
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=5e-5)

    def test_gqa_window(self):
        q = _rand((1, 4, 128, 64), seed=27)
        k = _rand((1, 2, 128, 64), seed=28)
        v = _rand((1, 2, 128, 64), seed=29)
        out = flash_attention(q, k, v, causal=True, sliding_window=32)
        ref = _mha_reference(q, k, v, None, 1.0 / np.sqrt(64), True, 32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_window_one_attends_self_only(self):
        q = _rand((1, 1, 32, 64), seed=30)
        k = _rand((1, 1, 32, 64), seed=31)
        v = _rand((1, 1, 32, 64), seed=32)
        out = flash_attention(q, k, v, causal=True, sliding_window=1)
        # softmax over a single key == that key's value
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(v, np.float32),
                                   atol=2e-5, rtol=2e-5)

    def test_requires_causal(self):
        q = _rand((1, 1, 32, 64))
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, q, q, sliding_window=8)


class TestBandedWindowGrid:
    """The sliding-window banded grid (static-offset fast path): the k/q
    grid axes only walk blocks near the window diagonal. These sizes force
    multiple blocks and nonzero band bases (seq >> block), pinning the
    band-base arithmetic, the nk_grid/nq_grid sizing, and the edge clamps
    that single-block tests never reach."""

    @pytest.mark.parametrize("window", [1, 130, 200, 1000])
    def test_fwd_parity_multiblock(self, window):
        q = _rand((1, 2, 1024, 32), seed=1)
        k = _rand((1, 2, 1024, 32), seed=2)
        v = _rand((1, 2, 1024, 32), seed=3)
        out = flash_attention(q, k, v, causal=True, sliding_window=window,
                              block_q=128, block_k=256)
        ref = _mha_reference(q, k, v, None, 1.0 / np.sqrt(32), True, window)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32),
                                   rtol=2e-2, atol=2e-2)

    def test_bwd_parity_multiblock(self):
        q = _rand((1, 2, 768, 32), seed=4)
        k = _rand((1, 2, 768, 32), seed=5)
        v = _rand((1, 2, 768, 32), seed=6)

        def loss(fn):
            return lambda q, k, v: jnp.sum(
                fn(q, k, v).astype(jnp.float32) ** 2)

        g_new = jax.grad(loss(lambda q, k, v: flash_attention(
            q, k, v, causal=True, sliding_window=200,
            block_q=128, block_k=128)), argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss(lambda q, k, v: _mha_reference(
            q, k, v, None, 1.0 / np.sqrt(32), True, 200)),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_new, g_ref):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       rtol=5e-2, atol=5e-2)

    def test_cross_attention_offset_band(self):
        # sk > sq: queries sit at the end; the band base includes the
        # static sk-sq offset
        q = _rand((1, 2, 256, 32), seed=7)
        k = _rand((1, 2, 1024, 32), seed=8)
        v = _rand((1, 2, 1024, 32), seed=9)
        out = flash_attention(q, k, v, causal=True, sliding_window=300,
                              block_q=128, block_k=128)
        ref = _mha_reference(q, k, v, None, 1.0 / np.sqrt(32), True, 300)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32),
                                   rtol=2e-2, atol=2e-2)


# ---------------------------------------------------------------------------
# packed-QKV (layout-native) path
# ---------------------------------------------------------------------------

from apex_tpu.ops.attention import (  # noqa: E402
    flash_attention_packed,
    packed_attention_supported,
    packed_geometry,
)


def _pack_qkv(q, k, v, qpg, d):
    """[b,h,s,d] triple -> the ParallelAttention packed [s, b, W] layout
    (per group: q_0..q_{qpg-1} | k | v along the column dim)."""
    b, h, s, _ = q.shape
    g = h // qpg
    q5 = q.transpose(2, 0, 1, 3).reshape(s, b, g, qpg, d)
    k5 = k.transpose(2, 0, 1, 3)[:, :, :, None]
    v5 = v.transpose(2, 0, 1, 3)[:, :, :, None]
    return jnp.concatenate([q5, k5, v5], axis=3).reshape(s, b, -1)


class TestPackedQKV:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("g,qpg", [(4, 1), (2, 2), (1, 4)])
    def test_fwd_bwd_matches_unpacked(self, causal, g, qpg):
        s, b, d = 128, 2, 64
        h = g * qpg
        q = _rand((b, h, s, d), seed=11)
        k = _rand((b, g, s, d), seed=12)
        v = _rand((b, g, s, d), seed=13)
        qkv = _pack_qkv(q, k, v, qpg, d)

        def packed_loss(qkv):
            o = flash_attention_packed(qkv, queries_per_group=qpg,
                                       head_dim=d, causal=causal)
            return jnp.sum(o.astype(jnp.float32) ** 2), o

        def ref_loss(qkv):
            # unpack exactly as the packed kernel sees it
            qkv5 = qkv.reshape(s, b, g, qpg + 2, d)
            qq = qkv5[:, :, :, :qpg].reshape(s, b, h, d).transpose(1, 2, 0, 3)
            kk = qkv5[:, :, :, qpg].transpose(1, 2, 0, 3)
            vv = qkv5[:, :, :, qpg + 1].transpose(1, 2, 0, 3)
            o4 = _mha_reference(qq, kk, vv, None, 1.0 / np.sqrt(d), causal)
            o = o4.transpose(2, 0, 1, 3).reshape(s, b, h * d)
            return jnp.sum(o.astype(jnp.float32) ** 2), o

        (_, op), gp = jax.value_and_grad(packed_loss, has_aux=True)(qkv)
        (_, orf), gr = jax.value_and_grad(ref_loss, has_aux=True)(qkv)
        np.testing.assert_allclose(np.asarray(op), np.asarray(orf),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gr),
                                   rtol=2e-3, atol=2e-3)

    @pytest.mark.slow  # varlen/window parity sweep: slow tier (ROADMAP)

    def test_varlen_and_window(self):
        s, b, g, qpg, d = 256, 3, 2, 1, 64
        qkv = _rand((s, b, g * (qpg + 2) * d), seed=21)
        kvl = jnp.asarray([256, 100, 3], jnp.int32)
        for kwargs in ({"kv_lengths": kvl},
                       {"causal": True, "sliding_window": 50}):
            def packed_loss(qkv):
                o = flash_attention_packed(qkv, queries_per_group=qpg,
                                           head_dim=d, **kwargs)
                return jnp.sum(o.astype(jnp.float32) ** 2)

            def ref_loss(qkv):
                qkv5 = qkv.reshape(s, b, g, qpg + 2, d)
                qq = qkv5[:, :, :, 0].transpose(1, 2, 0, 3)
                kk = qkv5[:, :, :, 1].transpose(1, 2, 0, 3)
                vv = qkv5[:, :, :, 2].transpose(1, 2, 0, 3)
                o4 = _mha_reference(qq, kk, vv, kwargs.get("kv_lengths"),
                                    1.0 / np.sqrt(d),
                                    kwargs.get("causal", False),
                                    kwargs.get("sliding_window"))
                return jnp.sum(o4.astype(jnp.float32) ** 2)

            gp = jax.grad(packed_loss)(qkv)
            gr = jax.grad(ref_loss)(qkv)
            np.testing.assert_allclose(np.asarray(gp), np.asarray(gr),
                                       rtol=2e-3, atol=2e-3)

    @pytest.mark.parametrize("s,causal", [(100, True), (197, False)])
    def test_ragged_s_pads_internally(self, s, causal):
        # ViT-class lengths (197 = 196 patches + CLS): rows pad to the
        # sublane multiple, padded keys masked via kv_lengths, padded
        # query rows sliced off
        b, g, qpg, d = 2, 4, 1, 64
        qkv = _rand((s, b, g * (qpg + 2) * d), seed=61)

        def packed_loss(qkv):
            o = flash_attention_packed(qkv, queries_per_group=qpg,
                                       head_dim=d, causal=causal)
            assert o.shape == (s, b, g * qpg * d)
            return jnp.sum(o.astype(jnp.float32) ** 2), o

        def ref_loss(qkv):
            qkv5 = qkv.reshape(s, b, g, qpg + 2, d)
            qq = qkv5[:, :, :, 0].transpose(1, 2, 0, 3)
            kk = qkv5[:, :, :, 1].transpose(1, 2, 0, 3)
            vv = qkv5[:, :, :, 2].transpose(1, 2, 0, 3)
            o4 = _mha_reference(qq, kk, vv, None, 1.0 / np.sqrt(d), causal)
            o = o4.transpose(2, 0, 1, 3).reshape(s, b, g * d)
            return jnp.sum(o.astype(jnp.float32) ** 2), o

        (_, op), gp = jax.value_and_grad(packed_loss, has_aux=True)(qkv)
        (_, orf), gr = jax.value_and_grad(ref_loss, has_aux=True)(qkv)
        np.testing.assert_allclose(np.asarray(op), np.asarray(orf),
                                   rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gr),
                                   rtol=2e-3, atol=2e-3)

    def test_geometry_gate(self):
        # d=64, qpg odd -> two groups per cell; odd group count unsupported
        assert packed_geometry(16, 1, 64) == (2, 384, 128)
        assert packed_geometry(3, 1, 64) is None
        assert packed_geometry(4, 2, 64) == (1, 256, 128)
        assert packed_geometry(2, 1, 128) == (1, 384, 128)
        # s gating: anything up to 1024 (ragged s pads to the sublane
        # multiple internally); beyond that the (s, s) block leaves VMEM
        assert packed_attention_supported(1024, 16, 1, 64)
        assert packed_attention_supported(1000, 16, 1, 64)
        assert packed_attention_supported(197, 16, 1, 64)
        assert not packed_attention_supported(2048, 16, 1, 64)


class TestFusedMultiblockBackward:
    """The fused one-pass dq/dk/dv kernel (non-banded nq >= 2 shapes) —
    small explicit blocks force real multi-block grids so the aliased
    fp32 dq accumulation, dead-block passthrough and scratch flushes run
    for every grid transition the dispatch condition allows.

    The fused kernel's dq accumulation is a compiled Mosaic window-DMA
    mechanism that the Pallas interpreter cannot model (it reads inputs
    functionally, ignoring input_output_aliases), so under the default
    interpret-mode suite these shapes take the two-kernel path and this
    class pins THAT parity; under ``APEX_TPU_TEST_TPU=1`` on hardware the
    same tests compile and pin the fused kernel itself."""

    def _grads(self, q, k, v, kvl=None, causal=True, bq=128, bk=128):
        def loss(fn):
            return lambda q, k, v: jnp.sum(
                fn(q, k, v).astype(jnp.float32) ** 2)
        g_new = jax.grad(loss(lambda q, k, v: flash_attention(
            q, k, v, causal=causal, kv_lengths=kvl,
            block_q=bq, block_k=bk)), argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss(lambda q, k, v: _mha_reference(
            q, k, v, kvl, 1.0 / np.sqrt(q.shape[-1]), causal)),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_new, g_ref):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       rtol=2e-3, atol=2e-3)

    @pytest.mark.parametrize("causal", [False, True])
    def test_2x2_grid(self, causal):
        # nq = nk = 2: dq blocks revisited across the outer j dim — the
        # aliased read-modify-write accumulation path
        q = _rand((2, 3, 256, 64), seed=31)
        k = _rand((2, 3, 256, 64), seed=32)
        v = _rand((2, 3, 256, 64), seed=33)
        self._grads(q, k, v, causal=causal)

    def test_causal_dead_blocks_4x4(self):
        # nq = nk = 4: 6 of 16 blocks are causally dead — their steps
        # must pass dq through unchanged (a dropped write loses a j
        # contribution; a stale write corrupts a neighbor block)
        q = _rand((1, 2, 512, 64), seed=34)
        k = _rand((1, 2, 512, 64), seed=35)
        v = _rand((1, 2, 512, 64), seed=36)
        self._grads(q, k, v, causal=True)

    def test_gqa_group_sweep(self):
        # grouped heads extend the inner t sweep; dk/dv scratch must
        # accumulate across the whole (g, i) walk before flushing
        q = _rand((2, 4, 256, 64), seed=37)
        k = _rand((2, 2, 256, 64), seed=38)
        v = _rand((2, 2, 256, 64), seed=39)
        self._grads(q, k, v, causal=True)

    def test_varlen(self):
        q = _rand((2, 2, 256, 64), seed=40)
        k = _rand((2, 2, 256, 64), seed=41)
        v = _rand((2, 2, 256, 64), seed=42)
        self._grads(q, k, v, causal=False,
                    kvl=jnp.asarray([200, 37], jnp.int32))

    @pytest.mark.slow  # cross-shape parity sweep: slow tier (ROADMAP)

    def test_cross_shapes(self):
        # sq != sk, including the nk == 1 single-j fused case and the
        # nq == 1 shape that must take the two-kernel fallback
        for sq, sk in [(256, 512), (384, 128), (128, 512)]:
            q = _rand((1, 2, sq, 64), seed=43 + sq)
            k = _rand((1, 2, sk, 64), seed=44 + sk)
            v = _rand((1, 2, sk, 64), seed=45 + sk)
            self._grads(q, k, v, causal=True)


class TestPackedRope:
    """In-kernel RoPE on the packed path vs rotate-then-flash on the 4D
    path — forward and the un-rotated dqkv cotangent, full and partial
    rotary dims."""

    @pytest.mark.parametrize("rot", [64, 32])
    def test_rope_parity(self, rot):
        from apex_tpu.ops.rope import fused_rope
        s, b, g, qpg, d = 128, 2, 4, 1, 64
        qkv = _rand((s, b, g * (qpg + 2) * d), seed=51)
        inv = 1.0 / 10000.0 ** (np.arange(0, rot, 2, dtype=np.float32)
                                / rot)
        f = np.arange(s, dtype=np.float32)[:, None] * inv[None, :]
        freqs = jnp.asarray(np.concatenate([f, f], axis=-1))   # [s, rot]

        def packed_loss(qkv):
            o = flash_attention_packed(qkv, queries_per_group=qpg,
                                       head_dim=d, causal=True,
                                       rope_freqs=freqs)
            return jnp.sum(o.astype(jnp.float32) ** 2), o

        def ref_loss(qkv):
            qkv5 = qkv.reshape(s, b, g, qpg + 2, d)
            qq = qkv5[:, :, :, 0]                        # [s, b, g, d]
            kk = qkv5[:, :, :, 1]
            vv = qkv5[:, :, :, 2].transpose(1, 2, 0, 3)
            f4 = freqs.reshape(s, 1, 1, rot)
            qq = fused_rope(qq, f4).transpose(1, 2, 0, 3)
            kk = fused_rope(kk, f4).transpose(1, 2, 0, 3)
            o4 = _mha_reference(qq, kk, vv, None, 1.0 / np.sqrt(d), True)
            o = o4.transpose(2, 0, 1, 3).reshape(s, b, g * d)
            return jnp.sum(o.astype(jnp.float32) ** 2), o

        (_, op), gp = jax.value_and_grad(packed_loss, has_aux=True)(qkv)
        (_, orf), gr = jax.value_and_grad(ref_loss, has_aux=True)(qkv)
        np.testing.assert_allclose(np.asarray(op), np.asarray(orf),
                                   rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gr),
                                   rtol=5e-3, atol=5e-3)


class TestPackedDropout:
    """In-kernel attention dropout on the packed path (the reference fmha
    capability). The mask is a position-deterministic hash shared by the
    kernels, interpret mode and the XLA fallback, so every test here —
    including the exact-mask parity check — runs on all backends."""

    def test_rate_zero_is_exact_noop(self):
        s, b, g, qpg, d = 128, 2, 4, 1, 64
        qkv = _rand((s, b, g * (qpg + 2) * d), seed=71)
        o0 = flash_attention_packed(qkv, queries_per_group=qpg, head_dim=d,
                                    causal=True)
        o1 = flash_attention_packed(qkv, queries_per_group=qpg, head_dim=d,
                                    causal=True, dropout_rate=0.0,
                                    dropout_seed=jnp.asarray([3], jnp.int32))
        np.testing.assert_array_equal(np.asarray(o0), np.asarray(o1))

    @pytest.mark.slow
    def test_fallback_dropout_statistics(self):
        # CPU/interpret route: jax.random dropout on materialized probs —
        # unbiased in expectation and deterministic per seed
        s, b, g, qpg, d = 128, 2, 2, 1, 64
        qkv = _rand((s, b, g * (qpg + 2) * d), seed=72)
        kw = dict(queries_per_group=qpg, head_dim=d, causal=False)
        o_ref = flash_attention_packed(qkv, **kw).astype(jnp.float32)
        outs = [flash_attention_packed(
            qkv, dropout_rate=0.3,
            dropout_seed=jnp.asarray([i], jnp.int32), **kw)
            .astype(jnp.float32) for i in range(24)]
        same = flash_attention_packed(
            qkv, dropout_rate=0.3, dropout_seed=jnp.asarray([0], jnp.int32),
            **kw)
        np.testing.assert_array_equal(np.asarray(outs[0]), np.asarray(same))
        mean = jnp.stack(outs).mean(0)
        err = float(jnp.mean(jnp.abs(mean - o_ref))
                    / (jnp.mean(jnp.abs(o_ref)) + 1e-9))
        assert err < 0.25, f"dropout mean deviates {err:.3f} from no-drop"

    def test_kernel_dropout_exact_vs_hash_mask(self):
        """The dropout mask is a position-deterministic hash, so the
        expected mask is computable OUTSIDE the kernel: replay attention
        with that exact mask in plain XLA and demand fwd AND grads match
        the packed path — proving the forward mask, the backward's
        regenerated mask, and the dropout VJP algebra all agree."""
        from apex_tpu.ops.attention import _hash_keep, packed_geometry

        s, b, g, qpg, d = 128, 2, 4, 1, 64
        rate = 0.3
        seed = jnp.asarray([12345], jnp.int32)
        qkv = _rand((s, b, g * (qpg + 2) * d), seed=73, dtype=jnp.float32)
        h_tot = g * qpg
        from apex_tpu.ops.attention import _drop_combo
        combo = _drop_combo(
            jnp.arange(b, dtype=jnp.uint32)[:, None, None, None],
            jnp.arange(h_tot, dtype=jnp.uint32)[None, :, None, None])
        keep = _hash_keep(seed.reshape(()), combo, (b, h_tot, s, s), rate)

        def packed_loss(qkv):
            o = flash_attention_packed(
                qkv, queries_per_group=qpg, head_dim=d, causal=True,
                dropout_rate=rate, dropout_seed=seed)
            return jnp.sum(o.astype(jnp.float32) ** 2), o

        def ref_loss(qkv):
            qkv5 = qkv.reshape(s, b, g, qpg + 2, d)
            qq = qkv5[:, :, :, 0].transpose(1, 2, 0, 3)
            kk = qkv5[:, :, :, 1].transpose(1, 2, 0, 3)
            vv = qkv5[:, :, :, 2].transpose(1, 2, 0, 3)
            sm = jnp.einsum("bhqd,bhkd->bhqk", qq, kk) / np.sqrt(d)
            row = jnp.arange(s)[:, None]
            col = jnp.arange(s)[None, :]
            sm = jnp.where(col <= row, sm, -1e30)
            p = jax.nn.softmax(sm, axis=-1)
            p = jnp.where(keep, p / (1.0 - rate), 0.0)
            o4 = jnp.einsum("bhqk,bhkd->bhqd", p, vv)
            o = o4.transpose(2, 0, 1, 3).reshape(s, b, g * d)
            return jnp.sum(o.astype(jnp.float32) ** 2), o

        (_, op), gp = jax.value_and_grad(packed_loss, has_aux=True)(qkv)
        (_, orf), gr = jax.value_and_grad(ref_loss, has_aux=True)(qkv)
        np.testing.assert_allclose(np.asarray(op), np.asarray(orf),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gr),
                                   rtol=2e-3, atol=2e-3)

    def test_hash_mask_statistics(self):
        from apex_tpu.ops.attention import _hash_keep
        keep = _hash_keep(jnp.uint32(7), jnp.uint32(3), (512, 512), 0.3)
        frac = float(jnp.mean(keep.astype(jnp.float32)))
        assert abs(frac - 0.7) < 0.01, frac
        # rows/cols must not be degenerate (per-row keep rate spread)
        rowfrac = jnp.mean(keep.astype(jnp.float32), axis=1)
        assert float(jnp.std(rowfrac)) < 0.05
