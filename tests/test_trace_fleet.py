"""Tracing + fleet-telemetry acceptance suite (ISSUE 14).

Unit layer (jax-free modules under test): span emission and the
conservation checker's red paths, :class:`ReplicaRegistry` dual-write
semantics, :func:`merge_histograms`, and :class:`FleetMetrics.signals`
driven exactly against a hand-built fake fleet.

Acceptance layer (tier-1, shared 2-layer model — same dims as the
committed scenarios, so one build serves both runs):

- the committed ``multi_tenant`` scenario: every terminal request's
  span timeline is complete and gap-free, per-request span durations
  sum to the measured latency, the per-tenant SLO table reconciles
  key-for-key with the adapter ledger, the monitor (human and
  ``--json``) renders both, the loadtest ``--check`` gate stays green
  on the real log and goes ``EXIT_ERROR`` on an injected violation —
  with tracing adding zero decode retraces.
- the committed ``fleet_smoke`` scenario: ``FleetMetrics.signals()``
  reconciles exactly with the merged replica counters even across a
  mid-run draining restart + migration, and the signals record lands
  in the log for the monitor's fleet-signals section.
"""

import json
import os
import time

import jax
import pytest

from apex_tpu.loadtest import Scenario, run_scenario
from apex_tpu.loadtest.__main__ import (
    EXIT_ERROR,
    EXIT_OK,
    main as loadtest_main,
)
from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.observability import (
    FleetMetrics,
    InMemorySink,
    MARK_SPANS,
    MetricsRegistry,
    PHASE_SPANS,
    ReplicaRegistry,
    build_report,
    build_timelines,
    check_span_conservation,
    emit_request_spans,
    emit_span,
    format_timeline,
    merge_histograms,
    new_trace_id,
    render_report,
)
from apex_tpu.observability.report import (
    main as monitor_main,
    read_records,
)
from apex_tpu.observability.trace import SPAN_COUNTER_PREFIX

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCENARIO_DIR = os.path.join(REPO, "benchmarks", "scenarios")
MT_SCENARIO = os.path.join(SCENARIO_DIR, "multi_tenant.json")
FLEET_SCENARIO = os.path.join(SCENARIO_DIR, "fleet_smoke.json")


# ---------------------------------------------------------------------------
# unit: span emission + the conservation checker


class TestSpanEmission:
    def test_emit_span_stamps_row_and_counter(self):
        mem = InMemorySink()
        reg = MetricsRegistry([mem])
        tid = new_trace_id()
        rec = emit_span(reg, "decode", trace_id=tid, request_id=7,
                        start_s=1.0, end_s=1.5, wall=100.0,
                        replica_id=1, detail="x", proposed=4)
        assert rec["kind"] == "span" and rec["span"] == "decode"
        assert rec["duration_s"] == pytest.approx(0.5)
        assert rec["replica_id"] == 1 and rec["proposed"] == 4
        assert mem.of_kind("span") == [rec]
        assert reg.counters()[SPAN_COUNTER_PREFIX + "decode"] == 1

    def test_trace_ids_are_unique_hex(self):
        ids = {new_trace_id() for _ in range(64)}
        assert len(ids) == 64
        assert all(len(t) == 16 and int(t, 16) >= 0 for t in ids)

    def test_emit_request_spans_full_trio_conserves(self):
        """The prefill-reaching path emits queued -> prefill -> decode,
        contiguous by construction, and the stream passes the checker
        once the terminal record + counters land next to it."""
        mem = InMemorySink()
        reg = MetricsRegistry([mem])
        tid = new_trace_id()
        spans = emit_request_spans(reg, trace_id=tid, request_id=0,
                                   submit_ts=10.0, now=10.7, wall=1.0,
                                   prefill_start=10.1, prefill_end=10.4)
        assert [s["span"] for s in spans] == ["queued", "prefill",
                                              "decode"]
        records = mem.records + [
            {"kind": "request", "request_id": 0, "trace_id": tid,
             "finish_reason": "eos", "total_s": 0.7, "wall": 1.0},
            {"kind": "counters", "wall": 1.0,
             "values": dict(reg.counters())},
        ]
        assert check_span_conservation(records) == []

    def test_emit_request_spans_shed_paths(self):
        reg = MetricsRegistry([InMemorySink()])
        shed = emit_request_spans(reg, trace_id=new_trace_id(),
                                  request_id=1, submit_ts=0.0, now=0.2,
                                  wall=1.0, detail="queue_full")
        assert [s["span"] for s in shed] == ["shed"]
        assert shed[0]["detail"] == "queue_full"
        waited = emit_request_spans(reg, trace_id=new_trace_id(),
                                    request_id=2, submit_ts=0.0,
                                    now=0.2, wall=1.0)
        assert [s["span"] for s in waited] == ["queued"]

    def test_format_timeline_renders_marks_and_sum(self):
        tid = new_trace_id()
        spans = [
            {"kind": "span", "span": "queued", "trace_id": tid,
             "request_id": 3, "start_s": 0.0, "end_s": 0.1,
             "duration_s": 0.1, "wall": 1.0},
            {"kind": "span", "span": "migration", "trace_id": tid,
             "request_id": 3, "start_s": 0.05, "end_s": 0.05,
             "duration_s": 0.0, "wall": 1.0, "from_replica": 0},
            {"kind": "span", "span": "decode", "trace_id": tid,
             "request_id": 3, "start_s": 0.1, "end_s": 0.4,
             "duration_s": 0.3, "wall": 1.0},
        ]
        text = format_timeline(3, spans, {"finish_reason": "eos",
                                          "total_s": 0.4})
        assert f"trace_id={tid}" in text and "finish=eos" in text
        assert "(mark)" in text and "from_replica=0" in text
        assert "span sum: 0.4000s over 2 phase span(s)" in text
        assert format_timeline(9, []) == "request 9: no spans recorded"


class TestCheckSpanConservation:
    @staticmethod
    def _stream(*, gap=0.0, pad=0.0, drop_spans=False, wrong_tid=False,
                counter_skew=0):
        tid = "aa" * 8
        spans = [] if drop_spans else [
            {"kind": "span", "span": "queued", "trace_id": tid,
             "request_id": 0, "start_s": 0.0, "end_s": 0.1,
             "duration_s": 0.1, "wall": 1.0},
            {"kind": "span", "span": "decode",
             "trace_id": "bb" * 8 if wrong_tid else tid,
             "request_id": 0, "start_s": 0.1 + gap,
             "end_s": 0.5 + gap + pad, "duration_s": 0.4 + pad,
             "wall": 1.0},
        ]
        return spans + [
            {"kind": "request", "request_id": 0, "trace_id": tid,
             "finish_reason": "eos", "total_s": 0.5, "wall": 1.0},
            {"kind": "counters", "wall": 1.0, "values": {
                "spans_queued": (0 if drop_spans else 1) + counter_skew,
                "spans_decode": 0 if drop_spans else 1}},
        ]

    def test_conserved_stream_passes(self):
        assert check_span_conservation(self._stream()) == []

    def test_traceless_log_is_vacuous(self):
        records = [{"kind": "request", "request_id": 0,
                    "finish_reason": "eos", "total_s": 0.5, "wall": 1.0}]
        assert check_span_conservation(records) == []

    def test_missing_spans_flagged(self):
        v = check_span_conservation(self._stream(drop_spans=True))
        assert any("no phase spans" in line for line in v)

    def test_gap_between_phases_flagged(self):
        v = check_span_conservation(self._stream(gap=0.05))
        assert any("gap between" in line for line in v)

    def test_span_sum_mismatch_flagged(self):
        v = check_span_conservation(self._stream(pad=0.2))
        assert any("phase span sum" in line for line in v)

    def test_foreign_trace_id_flagged(self):
        v = check_span_conservation(self._stream(wrong_tid=True))
        assert any("trace_id" in line for line in v)

    def test_counter_row_mismatch_flagged(self):
        v = check_span_conservation(self._stream(counter_skew=2))
        assert any("span counter spans_queued=3" in line for line in v)


# ---------------------------------------------------------------------------
# unit: the fleet telemetry plane


class TestReplicaRegistry:
    def test_producer_calls_dual_write(self):
        parent = MetricsRegistry([InMemorySink()])
        r0 = ReplicaRegistry(parent, 0)
        r1 = ReplicaRegistry(parent, 1)
        assert r0.inc("requests_eos", 2) == 2   # returns the GLOBAL count
        assert r1.inc("requests_eos") == 3
        assert r0.counters()["requests_eos"] == 2
        assert r1.counters()["requests_eos"] == 1
        assert parent.counters()["requests_eos"] == 3
        r0.set_gauge("kv_pages_free", 5.0)
        assert r0.gauges()["kv_pages_free"] == 5.0
        assert parent.gauges()["kv_pages_free"] == 5.0
        r1.observe("request_ttft_s", 0.25)
        assert r1.histogram("request_ttft_s").count == 1
        assert parent.histogram("request_ttft_s").count == 1
        assert r0.histogram("request_ttft_s") is None
        r0.declare_counters("requests_error")
        assert r0.counters()["requests_error"] == 0
        assert parent.counters()["requests_error"] == 0

    def test_stream_is_parent_only(self):
        """Events/records go through the parent's single seq-ordered
        stream — the fleet log stays byte-identical to the pre-split
        era, with no per-replica sinks to interleave."""
        mem = InMemorySink()
        parent = MetricsRegistry([mem])
        rep = ReplicaRegistry(parent, 1)
        ev = rep.event("replica_probe", replica_id=1)
        rep.emit_record({"kind": "span", "span": "queued"})
        assert mem.of_kind("event") == [ev]
        assert len(mem.of_kind("span")) == 1
        assert rep._sinks == () or list(rep._sinks) == []
        extra = InMemorySink()
        rep.add_sink(extra)             # lands on the parent
        rep.event("second")
        assert len(extra.of_kind("event")) == 1

    def test_flush_and_close_delegate(self):
        mem = InMemorySink()
        parent = MetricsRegistry([mem])
        rep = ReplicaRegistry(parent, 0)
        rep.inc("steps")
        rep.flush()
        snaps = mem.of_kind("counters")
        assert snaps and snaps[-1]["values"]["steps"] == 1
        rep.close()
        assert mem.closed


class TestMergeHistograms:
    def test_exact_aggregates_and_window_union(self):
        a = MetricsRegistry()
        b = MetricsRegistry()
        for v in (0.1, 0.2, 0.3):
            a.observe("ttft", v)
        for v in (1.0, 2.0):
            b.observe("ttft", v)
        merged = merge_histograms(
            [a.histogram("ttft"), b.histogram("ttft")], "ttft")
        assert merged.count == 5
        assert merged.sum == pytest.approx(3.6)
        assert merged.min == pytest.approx(0.1)
        assert merged.max == pytest.approx(2.0)
        # the merged percentile window saw BOTH replicas' observations
        assert merged.percentile(99) == pytest.approx(2.0)
        assert merged.percentile(0) == pytest.approx(0.1)


class _FakeSupervisor:
    def __init__(self, queued, active):
        self.queued_count = queued
        self.active_count = active


class _FakeReplica:
    def __init__(self, queued, active):
        self.supervisor = _FakeSupervisor(queued, active)


class _FakeConfig:
    def __init__(self, max_slots):
        self.max_slots = max_slots


class _FakeFleet:
    """The duck-typed surface FleetMetrics polls, with deterministic
    numbers so every signal is asserted exactly."""

    def __init__(self):
        self.metrics = MetricsRegistry([InMemorySink()])
        self.replica_metrics = {
            0: ReplicaRegistry(self.metrics, 0),
            1: ReplicaRegistry(self.metrics, 1),
        }
        self.replicas = [_FakeReplica(2, 1), _FakeReplica(1, 2)]
        self.config = _FakeConfig(max_slots=2)
        self._backlog = [object()]
        self.inflight_count = 3

    def dispatch_set(self):
        return [0, 1]


class TestFleetMetricsSignals:
    @pytest.fixture()
    def fleet(self):
        f = _FakeFleet()
        r0, r1 = f.replica_metrics[0], f.replica_metrics[1]
        r0.inc("requests_eos", 3)
        r0.inc("requests_error", 1)
        r0.inc("adapter0_requests", 3)
        r1.inc("requests_length", 2)
        r1.inc("requests_timeout", 1)
        r1.inc("adapter1_requests", 1)
        f.metrics.inc("requests_submitted", 7)   # fleet-level key
        r0.set_gauge("kv_pages_in_use", 6.0)
        r0.set_gauge("kv_pages_free", 2.0)
        r1.set_gauge("kv_pages_in_use", 2.0)
        r1.set_gauge("kv_pages_free", 6.0)
        for v in (0.1, 0.2):
            r0.observe("request_ttft_s", v)
        r1.observe("request_ttft_s", 0.9)
        return f

    def test_signals_exact(self, fleet):
        fm = FleetMetrics(fleet)
        s = fm.signals()
        assert s["replicas_total"] == 2
        assert s["replicas_dispatchable"] == 2
        assert s["inflight"] == 3
        # queued 2+1 across supervisors + 1 fleet backlog entry
        assert s["queue_depth"] == 4
        assert s["requests_submitted"] == 7
        assert s["requests_ok"] == 5            # 3 eos + 2 length
        assert s["requests_terminal"] == 7
        assert s["goodput"] == pytest.approx(5 / 7)
        assert s["slot_occupancy"] == pytest.approx(3 / 4)
        assert s["kv_page_occupancy"] == pytest.approx(8 / 16)
        # merged-window p99: sees replica 1's slow observation
        assert s["ttft_p99_s"] == pytest.approx(0.9)
        assert s["tpot_p99_s"] is None          # no data -> no number
        assert s["adapter_share"] == {
            "adapter0": pytest.approx(3 / 4),
            "adapter1": pytest.approx(1 / 4)}

    def test_goodput_window_is_since_last_poll(self, fleet):
        fm = FleetMetrics(fleet)
        first = fm.signals()
        assert first["window_terminal"] == 7
        assert first["goodput_window"] == pytest.approx(5 / 7)
        # nothing terminal between polls: an IDLE window reports 0.0
        # (never None/NaN) so autoscaler math rate-normalizes cleanly;
        # window_terminal == 0 is the "no traffic" discriminator
        idle = fm.signals()
        assert idle["window_terminal"] == 0
        assert idle["goodput_window"] == 0.0
        # one new failure: the window sees ONLY it, lifetime barely moves
        fleet.replica_metrics[0].inc("requests_error")
        third = fm.signals()
        assert third["window_terminal"] == 1
        assert third["goodput_window"] == 0.0
        assert third["goodput"] == pytest.approx(5 / 8)

    def test_window_s_stamped_across_idle_gap(self, fleet):
        """Every poll stamps the wall width of ITS window — including an
        idle gap with zero completions — so decisions rate-normalize."""
        fm = FleetMetrics(fleet)
        first = fm.signals()
        assert first["window_s"] > 0.0
        time.sleep(0.05)
        idle = fm.signals()
        assert idle["window_terminal"] == 0
        assert idle["goodput_window"] == 0.0
        assert idle["window_s"] == pytest.approx(0.05, abs=0.04)
        # the window RESETS each poll: a quick follow-up is narrow again
        third = fm.signals()
        assert third["window_s"] < idle["window_s"]

    def test_merged_counters_reconcile_with_parent(self, fleet):
        fm = FleetMetrics(fleet)
        merged = fm.merged_counters()
        parent = fleet.metrics.counters()
        # every replica-incremented counter sums to the parent's value
        for name, value in merged.items():
            assert parent[name] == value, name
        # fleet-level keys are the difference, never in the merge
        assert "requests_submitted" not in merged
        snap = fm.snapshot()
        assert snap["counters"] == parent
        assert snap["replica_counters"]["0"]["requests_eos"] == 3
        assert snap["gauges"]['kv_pages_in_use{replica="1"}'] == 2.0

    def test_write_prometheus_labeled_export(self, fleet, tmp_path):
        path = str(tmp_path / "fleet.prom")
        FleetMetrics(fleet).write_prometheus(path)
        text = open(path, encoding="utf-8").read()
        assert "apex_tpu_requests_eos_total 3" in text
        assert 'apex_tpu_kv_pages_in_use{replica="0"} 6.0' in text
        assert 'apex_tpu_kv_pages_in_use{replica="1"} 2.0' in text
        assert text.count("# TYPE apex_tpu_kv_pages_in_use gauge") == 1
        assert "apex_tpu_request_ttft_s_count 3" in text


# ---------------------------------------------------------------------------
# acceptance: the committed scenarios, slow tier (each reruns a full
# scenario; the span/signals unit tests above stay tier-1)


@pytest.fixture(scope="module")
def small():
    """Same dims as the committed scenarios' model spec (the
    test_loadtest convention) — one build serves both runs."""
    model = GPTModel(TransformerConfig(
        num_layers=2, hidden_size=32, num_attention_heads=4, vocab_size=64,
        max_position_embeddings=64, hidden_dropout=0.0,
        attention_dropout=0.0))
    params = model.init(jax.random.PRNGKey(0))
    return model, params


@pytest.fixture(scope="module")
def mt_run(small, tmp_path_factory):
    model, params = small
    scn = Scenario.load(MT_SCENARIO)
    log = str(tmp_path_factory.mktemp("trace") / "multi_tenant.jsonl")
    run = run_scenario(scn, model=model, params=params, log_path=log)
    assert not run.aborted and run.submitted == scn.total_requests
    return {"run": run, "log": log, "records": read_records(log)}


@pytest.fixture(scope="module")
def fleet_run(small, tmp_path_factory):
    model, params = small
    scn = Scenario.load(FLEET_SCENARIO)
    log = str(tmp_path_factory.mktemp("trace") / "fleet_smoke.jsonl")
    run = run_scenario(scn, model=model, params=params, log_path=log)
    assert not run.aborted and run.submitted == scn.total_requests
    return {"run": run, "log": log, "records": read_records(log)}


@pytest.mark.slow
class TestMultiTenantTraceAcceptance:
    def test_every_terminal_request_has_complete_timeline(self, mt_run):
        """Acceptance: span conservation over the real run — every
        terminal request's timeline exists, is gap-free, and its phase
        durations sum to the measured latency (the checker asserts all
        three, plus key-for-key counter reconciliation)."""
        records = mt_run["records"]
        assert check_span_conservation(records) == []
        requests = [r for r in records if r.get("kind") == "request"]
        assert requests and all(r.get("trace_id") for r in requests)
        timelines = build_timelines(records)
        for r in requests:
            spans = timelines[r["request_id"]]
            phases = [s for s in spans if s["span"] in PHASE_SPANS]
            assert phases, r
            # spot-check the invariant the checker enforces
            span_sum = sum(s["duration_s"] for s in phases)
            assert span_sum == pytest.approx(r["total_s"], rel=0.02,
                                             abs=0.002)

    def test_span_counters_match_rows(self, mt_run):
        records = mt_run["records"]
        counters = mt_run["run"].counters
        rows = [r for r in records if r.get("kind") == "span"]
        by_name = {}
        for s in rows:
            by_name[s["span"]] = by_name.get(s["span"], 0) + 1
        for name, n in by_name.items():
            assert counters[SPAN_COUNTER_PREFIX + name] == n, name
        # one timeline per terminal request, each starting queued
        assert by_name["queued"] == len(mt_run["run"].results)

    def test_per_tenant_table_reconciles_with_adapter_ledger(
            self, mt_run):
        """Acceptance: the per-tenant SLO attribution reconciles
        key-for-key with the adapter admission ledger — every
        ``adapterN_requests`` counter has a tenant row whose request
        count matches, and base traffic is attributed too."""
        run = mt_run["run"]
        counters = run.counters
        by_adapter = run.slo_by_adapter
        ledger = {name[len("adapter"):-len("_requests")]: n
                  for name, n in counters.items()
                  if name.startswith("adapter")
                  and name.endswith("_requests") and n}
        assert ledger, "multi_tenant ran without adapter traffic"
        for adapter_id, n in ledger.items():
            assert by_adapter[adapter_id]["requests"] == n
        base = [r for r in mt_run["records"]
                if r.get("kind") == "request"
                and not isinstance(r.get("adapter_id"), str)]
        if base:
            assert by_adapter["base"]["requests"] == len(base)
        assert set(by_adapter) == set(ledger) | ({"base"} if base
                                                 else set())
        total = sum(m["requests"] for m in by_adapter.values())
        assert total == len(run.results)

    def test_tracing_adds_no_retraces(self, mt_run):
        """The engine runs with ``retrace_budget=0`` (any decode retrace
        aborts the run), so a completed, conserved run IS the zero-new-
        jit-programs proof; the counter stays flat regardless."""
        run = mt_run["run"]
        assert not run.aborted
        assert run.counters.get("retraces", 0) == 0
        assert run.counters.get("requests_error", 0) == 0

    def test_monitor_renders_tracing_and_tenant_sections(
            self, mt_run, capsys):
        report = build_report(mt_run["log"])
        spans = report["spans"]
        assert spans is not None and spans["violations"] == []
        assert spans["traced_requests"] == len(mt_run["run"].results)
        assert set(report["slo_by_adapter"]) == \
            set(mt_run["run"].slo_by_adapter)
        text = render_report(report)
        assert "request tracing" in text
        assert "span conservation: OK" in text
        assert "per-tenant slo" in text
        # --json carries both sections, reconciled with the in-process run
        assert monitor_main([mt_run["log"], "--json"]) == 0
        cli = json.loads(capsys.readouterr().out)
        assert cli["spans"]["by_name"] == spans["by_name"]
        for tenant, metrics in cli["slo_by_adapter"].items():
            assert metrics["requests"] == \
                mt_run["run"].slo_by_adapter[tenant]["requests"]

    def test_monitor_trace_prints_one_timeline(self, mt_run, capsys):
        rid = min(mt_run["run"].results)
        assert monitor_main([mt_run["log"], "--trace", str(rid)]) == 0
        out = capsys.readouterr().out
        assert f"request {rid}" in out and "trace_id=" in out
        assert "span sum:" in out
        assert monitor_main([mt_run["log"], "--trace", "99999"]) == 2

    def test_loadtest_check_gate_green_and_red(self, mt_run, tmp_path,
                                               capsys):
        """``--check`` passes on the real log; a log with a torn
        invariant (an extra phase span forged into one timeline) exits
        ``EXIT_ERROR`` — span violations outrank the SLO verdict."""
        base = str(tmp_path / "base.json")
        assert loadtest_main([MT_SCENARIO, "--from-log", mt_run["log"],
                              "--baseline", base,
                              "--update-baseline"]) == EXIT_OK
        assert loadtest_main([MT_SCENARIO, "--from-log", mt_run["log"],
                              "--check", "--baseline", base]) == EXIT_OK
        assert "span conservation: OK" in capsys.readouterr().out

        records = mt_run["records"]
        victim = next(r for r in records if r.get("kind") == "request")
        forged = str(tmp_path / "forged.jsonl")
        with open(mt_run["log"], encoding="utf-8") as src, \
                open(forged, "w", encoding="utf-8") as dst:
            dst.write(src.read())
            dst.write(json.dumps({
                "kind": "span", "span": "decode",
                "trace_id": victim["trace_id"],
                "request_id": victim["request_id"],
                "start_s": 0.0, "end_s": 99.0, "duration_s": 99.0,
                "wall": 0.0}) + "\n")
        assert loadtest_main([MT_SCENARIO, "--from-log", forged,
                              "--check", "--baseline", base]) \
            == EXIT_ERROR
        assert "span conservation" in capsys.readouterr().out


@pytest.mark.slow
class TestFleetSignalsAcceptance:
    def test_signals_reconcile_with_merged_counters(self, fleet_run):
        """Acceptance: the final ``signals()`` poll is derived from —
        and reconciles exactly with — the merged replica counters, even
        after a draining restart migrated in-flight work."""
        run = fleet_run["run"]
        s = run.signals
        assert s is not None
        counters = run.counters
        ok = sum(counters.get(f"requests_{r}", 0)
                 for r in ("eos", "length"))
        terminal = sum(counters.get(f"requests_{r}", 0)
                       for r in ("eos", "length", "cancelled",
                                 "timeout", "rejected", "error"))
        assert s["requests_submitted"] == counters["requests_submitted"]
        assert s["requests_ok"] == ok
        assert s["requests_terminal"] == terminal
        assert s["goodput"] == pytest.approx(ok / terminal)
        assert s["replicas_total"] == 2
        # end of run: nothing queued or in flight
        assert s["queue_depth"] == 0 and s["inflight"] == 0
        assert s["ttft_p99_s"] is not None
        # the same dict was stamped into the log for the monitor
        stamped = [r for r in fleet_run["records"]
                   if r.get("kind") == "signals"]
        assert stamped and stamped[-1]["values"] == \
            json.loads(json.dumps(s))

    def test_spans_conserve_across_migration(self, fleet_run):
        """A migrated request still gets exactly one timeline (emitted
        by its final engine incarnation) that reconciles with the
        LOGGED record — conservation holds across drain/migrate/
        rebuild, with migration rendered as a mark, not a phase."""
        records = fleet_run["records"]
        assert check_span_conservation(records) == []
        marks = [r for r in records if r.get("kind") == "span"
                 and r.get("span") in MARK_SPANS]
        for m in marks:
            assert m["span"] == "migration"
        requests = [r for r in records if r.get("kind") == "request"]
        assert all(r.get("trace_id") for r in requests)

    def test_monitor_renders_fleet_signals(self, fleet_run, capsys):
        report = build_report(fleet_run["log"])
        assert report["signals"] == json.loads(
            json.dumps(fleet_run["run"].signals))
        text = render_report(report)
        assert "fleet signals" in text
        assert "request tracing" in text
        assert monitor_main([fleet_run["log"], "--json"]) == 0
        cli = json.loads(capsys.readouterr().out)
        assert cli["signals"]["replicas_total"] == 2
