"""Mixture-of-Experts / expert-parallelism tests.

The reference has no MoE (SURVEY.md §2.2 EP: absent) — this is
exceeds-reference capability, so correctness is established internally:
expert-parallel dispatch over the mesh must match the dense (unsharded)
dispatch bit-for-bit given the same params, and the routing machinery must
satisfy its contracts (capacity drops, weight normalization, aux-loss
sensitivity to imbalance).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.moe import MoEConfig, SwitchMLP
from apex_tpu.utils.sharding import shard_map


def _cfg(**kw):
    d = dict(hidden_size=16, ffn_hidden_size=32, num_experts=8,
             capacity_factor=2.0, expert_axis=None)
    d.update(kw)
    return MoEConfig(**d)


def _x(s=6, b=4, h=16, seed=1):
    return jax.random.normal(jax.random.PRNGKey(seed), (s, b, h))


class TestDense:
    def test_shapes_and_finite(self):
        moe = SwitchMLP(_cfg())
        params = moe.init(jax.random.PRNGKey(0))
        y, aux = jax.jit(lambda p, x: moe.apply(p, x))(params, _x())
        assert y.shape == (6, 4, 16)
        assert np.isfinite(np.asarray(y)).all()
        assert float(aux) > 0

    def test_top1_output_is_single_expert_ffn(self):
        """With huge capacity, each token's output equals its top-1 expert's
        FFN applied to it (weight 1.0 after top-1 renorm)."""
        cfg = _cfg(capacity_factor=8.0, top_k=1)
        moe = SwitchMLP(cfg)
        params = moe.init(jax.random.PRNGKey(0))
        x = _x()
        y, _ = moe.apply(params, x)
        x2d = x.reshape(-1, 16)
        logits = x2d @ params["router"]
        top = jnp.argmax(logits, axis=-1)
        for t in range(x2d.shape[0]):
            e = int(top[t])
            hmid = jax.nn.gelu(x2d[t] @ params["w_in"][e] + params["b_in"][e])
            ref = hmid @ params["w_out"][e] + params["b_out"][e]
            w = jax.nn.softmax(logits[t])[e]  # top-1 prob used as scale
            np.testing.assert_allclose(
                np.asarray(y.reshape(-1, 16)[t]), np.asarray(ref * w),
                rtol=1e-4, atol=1e-5)

    def test_top2_weights_normalized(self):
        cfg = _cfg(top_k=2, capacity_factor=8.0)
        moe = SwitchMLP(cfg)
        params = moe.init(jax.random.PRNGKey(0))
        y, _ = moe.apply(params, _x())
        assert np.isfinite(np.asarray(y)).all()

    def test_capacity_drops_tokens(self):
        """capacity_factor tiny -> most tokens dropped -> output mostly 0."""
        cfg = _cfg(capacity_factor=0.01)
        moe = SwitchMLP(cfg)
        params = moe.init(jax.random.PRNGKey(0))
        y, _ = moe.apply(params, _x(s=16, b=8))
        zero_rows = np.mean(
            np.all(np.asarray(y.reshape(-1, 16)) == 0.0, axis=1))
        assert zero_rows > 0.5

    def test_aux_loss_prefers_balance(self):
        """A router forced to one expert must have higher aux loss than the
        learned (roughly uniform at init) router."""
        cfg = _cfg()
        moe = SwitchMLP(cfg)
        params = moe.init(jax.random.PRNGKey(0))
        x = _x(s=16, b=8)
        _, aux_uniform = moe.apply(params, x)
        biased = dict(params)
        bias = jnp.zeros((16, 8)).at[:, 0].set(50.0)
        biased["router"] = params["router"] + bias
        _, aux_collapsed = moe.apply(biased, x)
        assert float(aux_collapsed) > float(aux_uniform) * 2

    def test_grads_flow_to_experts_and_router(self):
        moe = SwitchMLP(_cfg())
        params = moe.init(jax.random.PRNGKey(0))
        x = _x()

        def loss(p):
            y, aux = moe.apply(p, x)
            return jnp.mean(y ** 2) + 0.01 * aux

        g = jax.grad(loss)(params)
        assert float(jnp.sum(jnp.abs(g["router"]))) > 0
        assert float(jnp.sum(jnp.abs(g["w_in"]))) > 0


class TestDenseDropFree:
    """The >512-token drop-free path (round 5): dense per-expert scan with
    O(T*ffn) memory instead of the [T, E, cap] one-hots (quadratic at
    cap = tokens — the review's 32k/64-expert prefill example is ~275 GB)."""

    def test_matches_dropless_capacity_path(self):
        # capacity_factor = E/top_k => cap = tokens on the factor path too,
        # so both paths are drop-free and must agree
        cfg = _cfg(num_experts=4, top_k=2, capacity_factor=2.0)
        moe = SwitchMLP(cfg)
        params = moe.init(jax.random.PRNGKey(0))
        x = _x(s=48, b=16)                      # 768 tokens > 512 gate
        y_dense, aux_d = jax.jit(
            lambda p, x: moe.apply(p, x, drop_free=True))(params, x)
        y_cap, aux_c = jax.jit(lambda p, x: moe.apply(p, x))(params, x)
        np.testing.assert_allclose(np.asarray(y_dense), np.asarray(y_cap),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(float(aux_d), float(aux_c), rtol=1e-5)

    def test_ep_dense_drop_free_matches_unsharded(self):
        """Tokens SHARDED over the expert axis (EP rides DP — each rank
        holds different tokens): the dense path must gather tokens before
        its expert scan and slice its shard back after the psum; a
        shard-local psum would silently sum different ranks' tokens (r5
        review). Per-rank tokens exceed the 512 dense gate."""
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel()   # data = 8
        dense = SwitchMLP(_cfg(top_k=2, expert_axis=None))
        ep = SwitchMLP(_cfg(top_k=2, expert_axis="data"))
        params = dense.init(jax.random.PRNGKey(0))
        x = _x(s=80, b=64)                # 5120 tokens = 640/rank > 512
        y_ref, _ = dense.apply(params, x, drop_free=True)
        y, _ = jax.jit(shard_map(
            lambda p, x: ep.apply(p, x, drop_free=True), mesh=mesh,
            in_specs=(ep.spec(), P(None, "data")),
            out_specs=(P(None, "data"), P()), check_vma=False))(params, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   rtol=2e-5, atol=2e-6)
        parallel_state.destroy_model_parallel()

    def test_gated_activation_dense_path(self):
        cfg = _cfg(num_experts=4, top_k=1, activation="swiglu")
        moe = SwitchMLP(cfg)
        params = moe.init(jax.random.PRNGKey(0))
        assert "b_in" not in params             # gated experts bias-free
        x = _x(s=48, b=16)
        y, aux = jax.jit(
            lambda p, x: moe.apply(p, x, drop_free=True))(params, x)
        assert np.isfinite(np.asarray(y)).all()


class TestExpertParallel:
    def test_ep_matches_dense(self):
        """EP over the data axis == dense dispatch, same params/inputs."""
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel()   # data = 8
        dense = SwitchMLP(_cfg(expert_axis=None))
        ep = SwitchMLP(_cfg(expert_axis="data"))
        params = dense.init(jax.random.PRNGKey(0))
        x = _x(s=6, b=4)

        y_ref, aux_ref = dense.apply(params, x)

        def per_rank(p, x):
            y, aux = ep.apply(p, x)
            return y, aux.reshape(1)

        y, aux = jax.jit(shard_map(
            per_rank, mesh=mesh,
            in_specs=(ep.spec(), P()),
            out_specs=(P(), P("data")), check_vma=False))(params, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(float(aux[0]), float(aux_ref), rtol=1e-5)
        parallel_state.destroy_model_parallel()

    def test_ep_top2_matches_dense(self):
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel()
        dense = SwitchMLP(_cfg(expert_axis=None, top_k=2))
        ep = SwitchMLP(_cfg(expert_axis="data", top_k=2))
        params = dense.init(jax.random.PRNGKey(3))
        x = _x(s=4, b=4, seed=7)
        y_ref, _ = dense.apply(params, x)
        y, _ = jax.jit(shard_map(
            lambda p, x: ep.apply(p, x),
            mesh=mesh, in_specs=(ep.spec(), P()),
            out_specs=(P(), P()), check_vma=False))(params, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   rtol=2e-5, atol=2e-6)
        parallel_state.destroy_model_parallel()

    def test_ep_requires_divisible_experts(self):
        parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel()
        ep = SwitchMLP(_cfg(expert_axis="data", num_experts=6))
        params = SwitchMLP(_cfg(expert_axis=None, num_experts=6)).init(
            jax.random.PRNGKey(0))
        with pytest.raises(Exception):
            jax.jit(shard_map(
                lambda p, x: ep.apply(p, x), mesh=mesh,
                in_specs=(ep.spec(), P()), out_specs=(P(), P()),
                check_vma=False))(params, _x())
        parallel_state.destroy_model_parallel()


class TestExpertParallelTraining:
    """Whole-model EP-over-DP training: expert params sharded over the data
    axis must train identically to the dense unsharded model — pins the
    spec-aware gradient sync (expert grads divided by the data-axis size
    instead of pmean'd, which would mix different experts)."""

    @pytest.mark.slow  # whole-model EP-vs-dense parity: slow-tier class
    def test_ep_training_matches_dense(self):
        from apex_tpu.models import GPTModel, TransformerConfig
        from apex_tpu.optimizers import FusedAdam
        from apex_tpu.training import make_train_step

        cfg = dict(num_layers=2, hidden_size=32, num_attention_heads=4,
                   vocab_size=64, max_position_embeddings=32,
                   hidden_dropout=0.0, attention_dropout=0.0,
                   num_moe_experts=8,       # divisible by the dp=8 axis
                   moe_capacity_factor=8.0,   # = num_experts -> no drops
                   # the aux loss is a nonlinear function of per-shard token
                   # statistics, so its pmean differs from the global-batch
                   # value; zero it for exact loss parity
                   moe_aux_loss_weight=0.0)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
        labels = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0, 64)

        # dense reference, unsharded
        parallel_state.destroy_model_parallel()
        ref_model = GPTModel(TransformerConfig(**cfg))
        params = ref_model.init(jax.random.PRNGKey(0))
        opt = FusedAdam(lr=1e-2)
        p_ref, s_ref = params, opt.init(params)
        ref_losses = []

        @jax.jit
        def ref_step(p, s):
            loss, g = jax.value_and_grad(
                lambda p: ref_model.apply(p, tokens, labels))(p)
            p, s = opt.step(g, p, s)
            return p, s, loss

        for _ in range(3):
            p_ref, s_ref, loss = ref_step(p_ref, s_ref)
            ref_losses.append(float(loss))

        # EP over the data axis on the 8-device mesh
        mesh = parallel_state.initialize_model_parallel()   # dp = 8
        ep_model = GPTModel(TransformerConfig(**cfg, moe_expert_axis="data"))
        opt2 = FusedAdam(lr=1e-2)
        p_ep, s_ep = params, opt2.init(params)
        step = make_train_step(
            lambda p, b, rng: ep_model.apply(p, b["tokens"], b["labels"]),
            opt2, mesh, ep_model.spec(),
            {"tokens": P("data"), "labels": P("data")},
            opt_state_spec=opt2.state_spec(params, ep_model.spec()))
        ep_losses = []
        for _ in range(3):
            p_ep, s_ep, loss = step(p_ep, s_ep,
                                    {"tokens": tokens, "labels": labels},
                                    None)
            ep_losses.append(float(loss))
        np.testing.assert_allclose(ep_losses, ref_losses, rtol=2e-5)
        parallel_state.destroy_model_parallel()

    def test_zero_rejects_data_sharded_params(self):
        from apex_tpu.optimizers import DistributedFusedAdam

        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel()
        params = {"w": jnp.zeros((8, 4))}
        opt = DistributedFusedAdam(lr=1e-3, num_shards=8)
        with pytest.raises(NotImplementedError, match="ZeRO axis"):
            opt.init(params, {"w": P("data", None)})
        parallel_state.destroy_model_parallel()


class TestMoETransformer:
    """MoE wired into the transformer stack (TransformerConfig.num_moe_experts)."""

    def _model(self, **kw):
        from apex_tpu.models import GPTModel, TransformerConfig

        cfg = TransformerConfig(
            num_layers=2, hidden_size=32, num_attention_heads=4,
            vocab_size=64, max_position_embeddings=32,
            hidden_dropout=0.0, attention_dropout=0.0,
            num_moe_experts=4, moe_capacity_factor=2.0, **kw)
        return GPTModel(cfg)

    def test_moe_gpt_trains(self):
        from apex_tpu.optimizers import FusedAdam

        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        assert "w_in" in params["transformer"]["layers"]["mlp"]
        opt = FusedAdam(lr=1e-2)
        opt_state = opt.init(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
        labels = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, 64)

        @jax.jit
        def step(params, opt_state):
            loss, grads = jax.value_and_grad(
                lambda p: model.apply(p, tokens, labels))(params)
            params, opt_state = opt.step(grads, params, opt_state)
            return params, opt_state, loss

        losses = []
        for _ in range(6):
            params, opt_state, loss = step(params, opt_state)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_moe_router_gets_gradient(self):
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)
        labels = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, 64)
        g = jax.grad(lambda p: model.apply(p, tokens, labels))(params)
        router_g = g["transformer"]["layers"]["mlp"]["router"]
        assert float(jnp.sum(jnp.abs(router_g))) > 0

    def test_moe_logits_mode(self):
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)
        logits = model.apply(params, tokens)
        assert logits.shape == (8, 2, 64)

    def test_moe_in_bert_adds_aux_to_lm_loss(self):
        """MoE composes with BERT (round 3): the pre-scaled aux joins the
        masked-LM loss, and router grads flow."""
        from apex_tpu.models import BertModel, TransformerConfig

        cfg = TransformerConfig(
            num_layers=2, hidden_size=32, num_attention_heads=4,
            vocab_size=64, max_position_embeddings=16,
            hidden_dropout=0.0, attention_dropout=0.0,
            num_moe_experts=4, moe_capacity_factor=4.0)
        model = BertModel(cfg, add_binary_head=False)
        params = model.init(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)

        def loss(p):
            lm_loss, _ = model.apply(p, tokens, lm_labels=tokens)
            return lm_loss

        l, g = jax.value_and_grad(loss)(params)
        assert np.isfinite(float(l))
        router_g = g["transformer"]["layers"]["mlp"]["router"]
        assert float(jnp.sum(jnp.abs(router_g))) > 0

    def test_moe_in_vit_returns_logits_and_aux(self):
        from apex_tpu.models import TransformerConfig, ViTConfig, ViTModel

        cfg = TransformerConfig(
            num_layers=2, hidden_size=32, num_attention_heads=4,
            hidden_dropout=0.0, attention_dropout=0.0,
            num_moe_experts=4, moe_capacity_factor=4.0)
        model = ViTModel(ViTConfig(image_size=32, patch_size=16,
                                   num_classes=4, transformer=cfg))
        params = model.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        logits, aux = model.apply(params, x)
        assert logits.shape == (2, 4)
        assert np.isfinite(float(aux))
