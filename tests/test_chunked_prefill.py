"""Chunked prefill: token-budgeted mixed ticks (docs/serving.md#chunked-prefill).

Correctness anchor: with ``prefill_token_budget`` set, a prompt
prefills as a sequence of fixed-shape chunk programs interleaved with
co-tenant decode steps — and the engine's output must stay TOKEN-EXACT
against the monolithic (unchunked) engine, greedy AND sampled, across
every KV configuration chunking composes with (flat, paged, int8,
speculation, prefix cache, LoRA). The scheduling property rides along:
a long prompt can no longer monopolize a tick, so co-tenant decode
advances every tick while the long prompt is mid-prefill.
"""

import numpy as np
import pytest

import jax

from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.models.generation import generate
from apex_tpu.observability import (
    InMemorySink,
    MetricsRegistry,
    build_report,
    render_report,
)
from apex_tpu.observability.trace import check_span_conservation
from apex_tpu.serving import (
    EngineConfig,
    EngineSupervisor,
    FCFSScheduler,
    InferenceEngine,
    Request,
    SamplingParams,
    SchedulerConfig,
)
from apex_tpu.testing_faults import ServingFaultInjector


@pytest.fixture(scope="module")
def small():
    model = GPTModel(TransformerConfig(
        num_layers=2, hidden_size=32, num_attention_heads=4, vocab_size=64,
        max_position_embeddings=64, hidden_dropout=0.0,
        attention_dropout=0.0))
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _prompts(lens, seed=7):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 64, size=n).tolist() for n in lens]


def _serve(model, params, cfg, requests, *, metrics=None, on_tick=None):
    eng = InferenceEngine(model, params, cfg, metrics=metrics)
    try:
        results = eng.serve(requests, on_tick=on_tick)
    finally:
        eng.close()
    return eng, {r.request_id: r for r in results}


def _mixed_requests(prompts, *, sampled=False):
    reqs = []
    for i, p in enumerate(prompts):
        sp = SamplingParams(temperature=0.9, top_k=8, seed=100 + i) \
            if sampled and i % 2 else SamplingParams()
        reqs.append(Request(prompt=p, max_new_tokens=6, sampling=sp,
                            request_id=i))
    return reqs


class TestConfigValidation:
    def test_budget_below_one_rejected(self):
        with pytest.raises(ValueError, match="prefill_token_budget"):
            EngineConfig(max_slots=2, max_len=16, prefill_token_budget=0)

    def test_paged_budget_below_page_size_rejected(self):
        with pytest.raises(ValueError, match="page-aligned"):
            EngineConfig(max_slots=2, max_len=32, kv_layout="paged",
                         page_size=8, prefill_token_budget=4)

    def test_flat_budget_one_allowed(self):
        cfg = EngineConfig(max_slots=2, max_len=16, kv_layout="flat",
                           prefill_token_budget=1)
        assert cfg.prefill_token_budget == 1


class TestTokenExactness:
    """Chunked == monolithic, token for token, on both layouts."""

    @pytest.mark.parametrize("layout", [
        # flat is the bisection opt-out layout; its exactness variant is
        # slow-tier (ROADMAP), the default paged layout stays tier-1
        pytest.param("flat", marks=pytest.mark.slow),
        "paged",
    ])
    def test_greedy_and_sampled_exact(self, small, layout):
        model, params = small
        prompts = _prompts((23, 5, 11, 17), seed=41)
        extra = dict(page_size=4, n_pages=96) if layout == "paged" else {}
        mono_cfg = EngineConfig(max_slots=4, max_len=64, kv_layout=layout,
                                **extra)
        chunk_cfg = EngineConfig(max_slots=4, max_len=64, kv_layout=layout,
                                 prefill_token_budget=8, **extra)
        _, mono = _serve(model, params, mono_cfg,
                         _mixed_requests(prompts, sampled=True))
        eng, chunked = _serve(model, params, chunk_cfg,
                              _mixed_requests(prompts, sampled=True))
        for rid, m in mono.items():
            c = chunked[rid]
            assert c.tokens == m.tokens, (layout, rid)
            assert c.finish_reason == m.finish_reason
        # the 23-token prompt could not fit one 8-token tick budget
        assert chunked[0].prefill_chunks and chunked[0].prefill_chunks > 1
        # monolithic results never carry the field
        assert all(m.prefill_chunks is None for m in mono.values())
        assert eng.decode_retraces == 0
        assert eng.chunk_compiles <= len(eng.buckets)

    @pytest.mark.slow  # parity vs generate(): slow-tier family (ROADMAP)
    def test_flat_matches_generate_reference(self, small):
        """Chunked greedy output equals the per-request ``generate()``
        reference — not just the monolithic engine (guards against a
        bug both engines share)."""
        model, params = small
        prompts = _prompts((19, 6), seed=43)
        cfg = EngineConfig(max_slots=2, max_len=64, kv_layout="flat",
                           prefill_token_budget=4)
        _, out = _serve(model, params, cfg, _mixed_requests(prompts))
        import jax.numpy as jnp
        for rid, p in enumerate(prompts):
            ref = generate(model, params, jnp.asarray([p], jnp.int32),
                           6, max_len=64)
            assert out[rid].tokens == \
                np.asarray(ref[0, len(p):]).tolist(), rid


@pytest.mark.slow  # compile-bound feature-cross parity: slow tier;
# tier-1 keeps both layouts' chunked-vs-monolithic exactness above
class TestComposition:
    def test_int8_paged_exact(self, small):
        """Page-aligned chunk boundaries keep int8 quantization bitwise:
        every fresh page is filled whole by one scatter, so scales —
        and therefore tokens — match the monolithic engine."""
        model, params = small
        prompts = _prompts((21, 9), seed=47)
        base = dict(max_slots=2, max_len=64, kv_layout="paged",
                    page_size=4, n_pages=64, kv_dtype="int8")
        _, mono = _serve(model, params, EngineConfig(**base),
                         _mixed_requests(prompts, sampled=True))
        _, chunked = _serve(
            model, params,
            EngineConfig(prefill_token_budget=8, **base),
            _mixed_requests(prompts, sampled=True))
        for rid, m in mono.items():
            assert chunked[rid].tokens == m.tokens, rid

    def test_prefix_cache_exact_and_counted(self, small):
        """Chunked prefill interns and reuses shared prefixes exactly
        like the monolithic path; hit/miss counters reconcile with
        prefills even though the hit is stamped at completion."""
        model, params = small
        rng = np.random.RandomState(53)
        shared = rng.randint(0, 64, size=12).tolist()
        prompts = [shared + rng.randint(0, 64, size=6).tolist(),
                   shared + rng.randint(0, 64, size=9).tolist()]
        base = dict(max_slots=2, max_len=64, kv_layout="paged",
                    page_size=4, n_pages=64, prefix_cache=True,
                    scheduler=SchedulerConfig(max_prefills_per_tick=1))
        _, mono = _serve(model, params, EngineConfig(**base),
                         _mixed_requests(prompts))
        reg = MetricsRegistry()
        eng, chunked = _serve(
            model, params, EngineConfig(prefill_token_budget=8, **base),
            _mixed_requests(prompts), metrics=reg)
        for rid, m in mono.items():
            assert chunked[rid].tokens == m.tokens, rid
        counters = reg.counters()
        assert counters["prefix_hits"] >= 1
        assert counters["prefix_hits"] + counters["prefix_misses"] == \
            counters["prefills"]

    def test_speculation_exact(self, small):
        model, params = small
        prompts = _prompts((18, 7), seed=59)
        base = dict(max_slots=2, max_len=64, kv_layout="paged",
                    page_size=4, n_pages=64, speculation=3)
        _, mono = _serve(model, params, EngineConfig(**base),
                         _mixed_requests(prompts))
        _, chunked = _serve(
            model, params, EngineConfig(prefill_token_budget=8, **base),
            _mixed_requests(prompts))
        for rid, m in mono.items():
            assert chunked[rid].tokens == m.tokens, rid

    def test_lora_exact(self, small):
        """Chunked prefill resolves the adapter row once at admission
        and feeds it to every chunk — per-tenant output matches the
        monolithic engine."""
        from apex_tpu.lora import AdapterStore, random_adapter

        model, params = small
        adapters = AdapterStore(model.config, 2, max_adapters=2)
        adapters.load("t0", random_adapter(model.config, 2,
                                           jax.random.PRNGKey(5)))
        prompts = _prompts((17, 6), seed=61)

        def reqs():
            return [Request(prompt=p, max_new_tokens=5, request_id=i,
                            sampling=SamplingParams(
                                adapter_id="t0" if i == 0 else None))
                    for i, p in enumerate(prompts)]

        base = dict(max_slots=2, max_len=64, kv_layout="paged",
                    page_size=4, n_pages=64)

        def run(cfg):
            eng = InferenceEngine(model, params, cfg, adapters=adapters)
            try:
                return {r.request_id: r for r in eng.serve(reqs())}
            finally:
                eng.close()

        mono = run(EngineConfig(**base))
        chunked = run(EngineConfig(prefill_token_budget=8, **base))
        for rid, m in mono.items():
            assert chunked[rid].tokens == m.tokens, rid


class TestMixedTicks:
    def test_cotenant_decode_advances_during_long_prefill(self, small):
        """The tentpole scheduling property, deterministically: while a
        long prompt is mid-chunked-prefill, a co-tenant that is already
        decoding emits a token EVERY tick — the long prefill never
        stalls it. (The monolithic engine runs the whole long prefill
        inside one tick instead.)"""
        model, params = small
        short = Request(prompt=_prompts([3], seed=67)[0],
                        max_new_tokens=20, request_id=0)
        long_p = Request(prompt=_prompts([40], seed=68)[0],
                         max_new_tokens=4, request_id=1)
        cfg = EngineConfig(max_slots=2, max_len=64, kv_layout="paged",
                           page_size=4, n_pages=64,
                           prefill_token_budget=8)
        eng = InferenceEngine(model, params, cfg)
        try:
            eng.submit(short)
            eng.tick()                      # short prefills + decodes
            eng.submit(long_p)
            progress = []
            while long_p.request_id not in eng.completed:
                mid_prefill = bool(eng._prefilling)
                before = len(eng._active[0].tokens) \
                    if 0 in eng._active else None
                eng.tick()
                after = len(eng._active[0].tokens) \
                    if 0 in eng._active else None
                if mid_prefill and before is not None \
                        and after is not None:
                    progress.append(after - before)
            # 40 tokens / 8-token budget = 5 chunk ticks; the short
            # request gained one token on every one of them
            assert len(progress) >= 4
            assert all(p == 1 for p in progress), progress
            res = eng.completed[long_p.request_id]
            assert res.prefill_chunks == 5
        finally:
            eng.close()

    def test_budget_bounds_tokens_per_tick(self, small):
        model, params = small
        reg = MetricsRegistry()
        cfg = EngineConfig(max_slots=4, max_len=64, kv_layout="flat",
                           prefill_token_budget=8)
        _serve(model, params, cfg,
               _mixed_requests(_prompts((23, 11, 5, 9), seed=71)),
               metrics=reg)
        hist = reg.histogram("prefill_tokens_per_tick")
        assert hist is not None and hist.count > 0
        assert hist.max <= 8
        # counter/histogram reconciliation: every chunked token is
        # observed exactly once, so the histogram total is the chunked
        # prompt-token volume
        assert hist.sum == 23 + 11 + 5 + 9

    def test_ttft_stamped_at_emitting_tick(self, small):
        """Satellite: under multi-tick prefill, ttft_s is stamped when
        the FINAL chunk emits token #1 — it equals queue_s + prefill_s
        (which now spans several ticks), never just the first chunk."""
        model, params = small
        cfg = EngineConfig(max_slots=2, max_len=64, kv_layout="flat",
                           prefill_token_budget=4)
        req = Request(prompt=_prompts([20], seed=73)[0],
                      max_new_tokens=3, request_id=0)
        _, out = _serve(model, params, cfg, [req])
        res = out[0]
        assert res.prefill_chunks == 5
        assert res.ttft_s is not None
        assert res.ttft_s == pytest.approx(
            res.queue_s + res.prefill_s, abs=0.05)

    @pytest.mark.slow  # ordering sweep over full engine builds: slow tier (ROADMAP)

    def test_fcfs_admission_order_preserved(self, small):
        """Token-budget admission stays strictly FCFS: the admission
        log lists requests in submit order even when budget starvation
        delays later heads by several ticks."""
        model, params = small
        cfg = EngineConfig(max_slots=4, max_len=64, kv_layout="flat",
                           prefill_token_budget=4)
        reqs = _mixed_requests(_prompts((15, 3, 9, 4), seed=79))
        eng, _ = _serve(model, params, cfg, reqs)
        assert eng.admission_log == [r.request_id for r in reqs]


class TestTracing:
    def test_multi_segment_prefill_conserves(self, small):
        """A chunked request's prefill phase is one span per chunk —
        contiguous, chunk-indexed, and exactly conserving total_s; the
        loadtest gate's checker accepts the log."""
        model, params = small
        sink = InMemorySink()
        reg = MetricsRegistry([sink])
        cfg = EngineConfig(max_slots=2, max_len=64, kv_layout="flat",
                           prefill_token_budget=4)
        req = Request(prompt=_prompts([13], seed=83)[0],
                      max_new_tokens=3, request_id=0)
        _, out = _serve(model, params, cfg, [req], metrics=reg)
        assert check_span_conservation(sink.records) == []
        spans = [r for r in sink.records if r.get("kind") == "span"
                 and r.get("span") == "prefill"]
        assert len(spans) == out[0].prefill_chunks == 4
        assert [s["chunk"] for s in spans] == [0, 1, 2, 3]
        # segments tile [prefill_start, prefill_end] exactly
        for a, b in zip(spans, spans[1:]):
            assert a["end_s"] == b["start_s"]

    def test_monolithic_span_shape_unchanged(self, small):
        """Without a budget the timeline is bit-for-bit the pre-chunking
        one: a single un-indexed prefill span."""
        model, params = small
        sink = InMemorySink()
        reg = MetricsRegistry([sink])
        cfg = EngineConfig(max_slots=2, max_len=64, kv_layout="flat")
        req = Request(prompt=_prompts([13], seed=83)[0],
                      max_new_tokens=3, request_id=0)
        _serve(model, params, cfg, [req], metrics=reg)
        spans = [r for r in sink.records if r.get("kind") == "span"
                 and r.get("span") == "prefill"]
        assert len(spans) == 1 and "chunk" not in spans[0]

    def test_report_renders_chunk_audit(self, small, tmp_path):
        """Satellite: the monitor report renders the chunk counter, the
        per-request record sum, and the tokens-per-tick histogram, all
        reconciling key-for-key."""
        from apex_tpu.observability import JsonlSink

        model, params = small
        log = tmp_path / "chunked.jsonl"
        reg = MetricsRegistry([JsonlSink(str(log))])
        cfg = EngineConfig(max_slots=2, max_len=64, kv_layout="flat",
                           prefill_token_budget=4)
        _, out = _serve(model, params, cfg,
                        _mixed_requests(_prompts((13, 6), seed=89)),
                        metrics=reg)
        reg.close()
        report = build_report(str(log))
        total = sum(r.prefill_chunks or 0 for r in out.values())
        assert report["counters"]["prefill_chunks"] == total
        assert report["requests"]["prefill_chunks"] == total
        text = render_report(report)
        assert f"chunked prefill: chunks={total}" in text
        assert "tokens/tick" in text


class TestLifecycle:
    def test_deadline_expiry_mid_prefill(self, small):
        """A request whose deadline elapses between chunks retires as a
        timeout, releases its slot and pages, and leaves the engine
        serving the co-tenants."""
        import time

        model, params = small
        cfg = EngineConfig(max_slots=2, max_len=64, kv_layout="paged",
                           page_size=4, n_pages=64,
                           prefill_token_budget=4)
        eng = InferenceEngine(model, params, cfg)
        try:
            doomed = Request(prompt=_prompts([30], seed=97)[0],
                             max_new_tokens=4, deadline_s=0.05,
                             request_id=0)
            eng.submit(doomed)
            eng.tick()                    # first chunk runs
            assert eng._prefilling
            time.sleep(0.1)
            eng.tick()                    # deadline check fires
            res = eng.completed[doomed.request_id]
            assert res.finish_reason == "timeout"
            assert res.tokens == []
            assert res.prefill_chunks == 1
            assert not eng._prefilling
            eng.slots.check()
            assert eng.pages.in_use_count == 0
            # a fresh request still serves cleanly on the freed slot
            ok = Request(prompt=_prompts([5], seed=98)[0],
                         max_new_tokens=3, request_id=1)
            out = eng.serve([ok])
            assert out[0].finish_reason in ("eos", "length")
        finally:
            eng.close()

    def test_cancel_mid_prefill(self, small):
        model, params = small
        cfg = EngineConfig(max_slots=2, max_len=64, kv_layout="flat",
                           prefill_token_budget=4)
        eng = InferenceEngine(model, params, cfg)
        try:
            req = Request(prompt=_prompts([30], seed=101)[0],
                          max_new_tokens=4, request_id=0)
            eng.submit(req)
            eng.tick()
            assert eng._prefilling
            eng.cancel(req.request_id)
            eng.tick()
            res = eng.completed[req.request_id]
            assert res.finish_reason == "cancelled"
            assert not eng._prefilling
            eng.slots.check()
        finally:
            eng.close()

    @pytest.mark.slow  # restart x chunking feature-cross: slow tier (ROADMAP)

    def test_supervisor_restart_mid_prefill_token_exact(self, small):
        """A crash between chunks re-prefills the request from its
        prompt through the same admit path (the per-slot prefill state
        is host data, not jit-trace state) — the recovered output is
        token-exact."""
        model, params = small
        req = Request(prompt=_prompts([20], seed=103)[0],
                      max_new_tokens=5, request_id=0)
        cfg = EngineConfig(max_slots=2, max_len=64, kv_layout="flat",
                           prefill_token_budget=4)
        # prefill call 2 = the long prompt's THIRD chunk: the crash
        # lands mid-chunked-prefill, with two chunks already resident
        inj = ServingFaultInjector(prefill_raise_calls={2})
        sup = EngineSupervisor(model, params, cfg, faults=inj)
        try:
            results = sup.serve([Request(prompt=req.prompt,
                                         max_new_tokens=5,
                                         request_id=0)])
        finally:
            sup.close()
        assert sup.restarts == 1
        assert ("prefill_raise", 2) in inj.log
        mono = InferenceEngine(model, params,
                               EngineConfig(max_slots=2, max_len=64,
                                            kv_layout="flat"))
        try:
            ref = mono.serve([Request(prompt=req.prompt, max_new_tokens=5,
                                      request_id=1)])
        finally:
            mono.close()
        assert results[0].tokens == ref[0].tokens
        assert results[0].finish_reason == ref[0].finish_reason


@pytest.fixture
def tp2_mesh():
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2)
    yield mesh
    parallel_state.destroy_model_parallel()


@pytest.mark.slow  # TP model parity: the slow-tier class (ROADMAP)
class TestShardedChunked:
    @pytest.mark.parametrize("layout", ["flat", "paged"])
    def test_tp2_chunked_token_exact(self, small, tp2_mesh, layout):
        """Chunked prefill on a tp=2 mesh is token-exact vs the
        unsharded MONOLITHIC engine — the chunk programs shard like
        their parent bodies (paged chunks ride the suffix program's
        existing wiring; flat chunks get their own shard_map)."""
        from apex_tpu.serving.fleet import ShardedEngine

        model, params = small
        prompts = _prompts((19, 6, 11), seed=113)
        extra = dict(page_size=4, n_pages=64) if layout == "paged" else {}
        _, mono = _serve(
            model, params,
            EngineConfig(max_slots=4, max_len=64, kv_layout=layout,
                         **extra),
            _mixed_requests(prompts, sampled=True))
        sharded = ShardedEngine(
            model, params,
            EngineConfig(max_slots=4, max_len=64, kv_layout=layout,
                         prefill_token_budget=8, **extra))
        with sharded:
            out = {r.request_id: r
                   for r in sharded.serve(
                       _mixed_requests(prompts, sampled=True))}
            assert sharded.decode_retraces == 0
            assert sharded.chunk_compiles <= len(sharded.buckets)
        for rid, m in mono.items():
            assert out[rid].tokens == m.tokens, (layout, rid)
            assert out[rid].finish_reason == m.finish_reason
        assert out[0].prefill_chunks > 1


class TestTokenAwareLoad:
    def test_scheduler_queued_tokens(self):
        sched = FCFSScheduler(SchedulerConfig(max_queue=8))
        assert sched.queued_tokens == 0
        for n in (5, 11, 3):
            sched.submit(Request(prompt=list(range(1, n + 1)),
                                 max_new_tokens=2), now=0.0)
        assert sched.queued_tokens == 19
        sched.pop_admissible(1, False)
        assert sched.queued_tokens == 11 + 3

    def test_supervisor_excess_zero_until_measured(self, small):
        model, params = small
        sup = EngineSupervisor(model, params,
                               EngineConfig(max_slots=2, max_len=16))
        try:
            assert sup.queued_token_excess_s == 0.0
            assert sup.queued_prompt_tokens == 0
        finally:
            sup.close()

    def test_supervisor_excess_bounded_and_additive(self, small):
        """The token surcharge prices only the tokens BEYOND depth x
        avg-prompt, at the measured per-token prefill rate — zero for a
        typical backlog, positive for a long-prompt one, never
        negative."""
        model, params = small
        sup = EngineSupervisor(model, params,
                               EngineConfig(max_slots=2, max_len=64,
                                            scheduler=SchedulerConfig(
                                                max_queue=16)))
        try:
            sup._prefill_s_per_token = 0.01
            sup._avg_prompt_tokens = 4.0
            # 2 queued requests x 4 avg tokens = 8 expected; a 40-token
            # backlog carries 32 excess tokens -> 0.32s surcharge
            for p in _prompts((20, 20), seed=107):
                sup.engine.scheduler.submit(
                    Request(prompt=p, max_new_tokens=1), now=0.0)
            assert sup.queued_prompt_tokens == 40
            assert sup.queued_token_excess_s == pytest.approx(0.32)
        finally:
            sup.close()

    def test_supervisor_short_backlog_no_discount(self, small):
        model, params = small
        sup = EngineSupervisor(model, params,
                               EngineConfig(max_slots=2, max_len=64))
        try:
            sup._prefill_s_per_token = 0.01
            sup._avg_prompt_tokens = 16.0
            sup.engine.scheduler.submit(
                Request(prompt=[1, 2], max_new_tokens=1), now=0.0)
            # 2 tokens vs 16 expected: excess clamps at zero — short
            # prompts never discount below the depth-based estimate
            assert sup.queued_token_excess_s == 0.0
        finally:
            sup.close()

    @pytest.mark.slow  # compile-bound load-measurement sweep: slow tier (ROADMAP)

    def test_harvest_measures_token_rate(self, small):
        model, params = small
        sup = EngineSupervisor(model, params,
                               EngineConfig(max_slots=2, max_len=32))
        try:
            sup.serve([Request(prompt=p, max_new_tokens=3)
                       for p in _prompts((6, 12), seed=109)])
            assert sup._prefill_s_per_token is not None
            assert sup._prefill_s_per_token > 0
            assert sup._avg_prompt_tokens is not None
            assert 6 <= sup._avg_prompt_tokens <= 12
        finally:
            sup.close()

    def test_router_cost_prices_queued_tokens(self):
        """Two replicas at equal depth and service estimate: the one
        whose queue holds the long-prompt backlog costs more — and a
        fresh replica (no estimates) still costs exactly zero."""
        from apex_tpu.serving.fleet.router import Router

        class _Sup:
            def __init__(self, excess):
                self.queued_count = 2
                self.active_count = 0
                self.service_estimate_s = 0.5
                self.queued_token_excess_s = excess

        class _Rep:
            def __init__(self, rid, excess):
                self.replica_id = rid
                self.supervisor = _Sup(excess)

        short = _Rep(0, 0.0)
        long_ = _Rep(1, 0.4)
        assert Router.cost(short) < Router.cost(long_)
        assert Router().pick([long_, short]) is short

        class _Fresh:
            replica_id = 2

            class supervisor:
                queued_count = 0
                active_count = 0
                service_estimate_s = None
                queued_token_excess_s = 0.0

        assert Router.cost(_Fresh())[0] == 0.0
